"""Graph500 BFS benchmark on the real TPU chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "MTEPS", "vs_baseline": N}

Protocol (adapted from the reference's TopDownBFS driver,
TopDownBFS.cpp:421-479): R-MAT scale-S graph (edgefactor 16, symmetrized,
deloop'd, dedup'd), BFS from NROOTS random reachable roots, AGGREGATE MTEPS
over the batch (sum of kernel-2 traversed edges / total batch wall time).
NOTE: the Graph500 spec and the archived baseline use harmonic-mean
per-root TEPS; per-root timing needs trustworthy per-launch sync, which
this device does not provide (see below), so the aggregate — which
amortizes launch overhead across roots — is reported instead and
vs_baseline should be read with that caveat.

AXON D2H NOTE: this chip's runtime permanently degrades launch performance
(~1000x) after ANY device->host readback, so the pipeline is strictly
phased: (1) host-numpy graph construction + ELL bucketing, (2) one upload,
(3) timed BFS launches synchronized only via block_until_ready, (4) all
readbacks (TEPS accounting, validation) after timing.

vs_baseline compares single-chip MTEPS against the smallest archived
reference run: 1,636 MTEPS on 1,024 Hopper (Cray XE6) cores
(BASELINE.md: HopperResults/script1024.reducedgraph_mini:149).
"""

from __future__ import annotations

import json
import os
import time

SCALE = int(os.environ.get("BENCH_SCALE", "19"))
EDGEFACTOR = int(os.environ.get("BENCH_EDGEFACTOR", "16"))
NROOTS = int(os.environ.get("BENCH_NROOTS", "8"))
BASELINE_MTEPS = 1636.0  # Hopper 1024 cores, R-MAT "mini"


def main():
    import jax
    import numpy as np

    from combblas_tpu.models.bfs import bfs
    from combblas_tpu.parallel.ellmat import EllParMat
    from combblas_tpu.parallel.grid import Grid
    from combblas_tpu.utils.rmat import rmat_symmetric_coo_host

    grid = Grid.make(1, 1)
    n = 1 << SCALE

    # --- Phase 1: host-only construction ---------------------------------
    rows, cols = rmat_symmetric_coo_host(42, SCALE, EDGEFACTOR)
    key = rows * np.int64(n) + cols
    uniq = np.unique(key)
    rows_u = (uniq // n).astype(np.int64)
    cols_u = (uniq % n).astype(np.int64)
    deg = np.bincount(rows_u, minlength=n)
    nnz = len(rows_u)

    rng = np.random.default_rng(7)
    roots = rng.choice(np.flatnonzero(deg > 0), size=NROOTS, replace=False)

    # --- Phase 2: upload (H2D only) ---------------------------------------
    E = EllParMat.from_host_coo(
        grid, rows_u, cols_u, np.ones(nnz, np.float32), n, n
    )

    # --- Phase 3: timed launches ------------------------------------------
    # block_until_ready does not reliably synchronize through the axon
    # tunnel (launches appear to complete in microseconds), so the timed
    # section is the WHOLE batch of BFS launches closed by one scalar D2H —
    # the only true synchronization point. The D2H's poison (see module
    # docstring) then only affects the post-timing accounting phase, and
    # its ~5 ms latency inflates dt, biasing the reported TEPS DOWN.
    p, _, _ = bfs(E, int(roots[0]))  # compile warmup
    jax.block_until_ready(p.blocks)
    time.sleep(3.0)  # drain any in-flight warmup work

    t0 = time.perf_counter()
    results = []
    for r in roots:
        parents, _, _ = bfs(E, int(r))
        results.append(parents)
    _sync = int(jax.device_get(results[-1].blocks[0, 0]))  # true barrier
    dt_total = time.perf_counter() - t0

    # --- Phase 4: readbacks / accounting ----------------------------------
    total_te = 0
    for parents in results:
        disc = parents.to_global() >= 0
        total_te += int(deg[disc].sum()) // 2
    mteps = total_te / dt_total / 1e6
    print(
        json.dumps(
            {
                "metric": f"graph500_bfs_rmat_scale{SCALE}_1chip_MTEPS",
                "value": round(mteps, 2),
                "unit": "MTEPS",
                "vs_baseline": round(mteps / BASELINE_MTEPS, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
