"""Graph500 BFS benchmark on the real TPU chip.

Prints INCREMENTAL JSON lines. The FULL official record is re-printed,
enriched, as the protocol progresses:
  {"metric": ..., "value": N, "unit": "MTEPS", "vs_baseline": N, ...}
and the very LAST stdout line is a COMPACT headline summary
  {"summary": 1, "metric": ..., "value": N, "median": N, "warning": ...,
   "rc": 0}
also mirrored to BENCH_SUMMARY.json (ISSUE 3 satellite: the r05 capture
lost its headline to tail truncation of the giant record; a ~150-byte
final line + sidecar file cannot lose it again).

ROUND-5 PROTOCOL (VERDICT r4 items 1+8 — the r4 driver capture timed out
with an empty tail because the single JSON line printed only after a
30-45 min protocol):
  * INCREMENTAL OUTPUT: a COMPLETE official line is printed (flushed)
    immediately after the repeat phase; the line is then re-printed,
    enriched, after EVERY sequential-root child. A driver timeout at any
    point still finds a complete, parseable last line.
  * BUDGET BOUNDING: the protocol sizes itself to BENCH_BUDGET_S
    (default 1200 s): the 3 validated repeats always run; sequential
    roots run newest-estimate-first only while they fit the remaining
    budget, and the artifact records how many fit ("seq_roots_timed").
  * COMPILE-CACHE PERSISTENCE: every child sets
    jax_compilation_cache_dir=.jax_cache (verified to work through the
    axon remote compiler: 2.7 s -> 0.5 s cold-process recompile), so
    across-children and across-run warmups collapse to load time.
  * OFFICIAL-RUN RULE (predeclared, VERDICT r4 Weak #5): the canonical
    artifact for a round is the DRIVER's end-of-round capture
    (BENCH_r{N}.json), i.e. the last complete JSON line of that run.
    Builder-side runs are archived under benchmarks/results/ as
    supplementary evidence only; where several builder runs exist, the
    FIRST complete run of bench day is the one quoted in PERF_NOTES.
  * REPEAT REPLACEMENT (VERDICT r4 Weak #6): if any repeat lands >2x
    below the operating point (or fails), exactly ONE replacement repeat
    is appended; the original stays in "runs" and the median is taken
    over all successful repeats.
  * HEADLINE (VERDICT r4 Weak #3): "value"/"vs_baseline" carry the
    SPEC's sequential per-root statistic (harmonic-mean MTEPS over
    individually-timed roots — the only number apples-to-apples with
    BASELINE.md) once at least 4 sequential roots have been timed; the
    amortized batch median is reported alongside as
    "batch_median_mteps"/"batch_vs_baseline". Before that point (line 1,
    or a timeout before 4 roots) the batch median is the value and
    "statistic" says so.

Protocol (adapted from the reference's TopDownBFS driver,
TopDownBFS.cpp:421-479): R-MAT scale-S graph (edgefactor 16, symmetrized,
deloop'd, dedup'd), BFS from NROOTS random reachable roots, AGGREGATE MTEPS
over the batch (sum of kernel-2 traversed edges / total wall time), plus an
amortized per-root harmonic-mean decomposition (see below).

VARIANCE CONTROL (round 3 — the round-2 driver capture measured 46.98
MTEPS where the builder's sweep measured 297.0 with identical config, a
6.3x run-to-run swing): the benchmark now runs BENCH_REPEATS (default 3)
INDEPENDENT SUBPROCESS repeats — process isolation is mandatory because on
this chip any device->host readback permanently degrades later launches in
that process (see below), so in-process repeats after the first timed
readback measure a poisoned runtime.  The parent builds the graph once,
ships it to children via an .npz, collects each child's JSON, and reports
the MEDIAN with all per-repeat values recorded.  Each child also:
  * uses a LONG warm drain (BENCH_DRAIN_S, default 45 s) — the round-2
    default of 5 s did not cover the warmup launch's ~20-30 s EXECUTION
    (block_until_ready through the tunnel returns early), so a cold or
    slow run could overlap leftover warmup execution into the timed
    window — the leading suspect for the 6.3x;
  * records warmup_s (compile + first execution) so a cold compile cache
    is visible in the artifact;
  * warns (field "warning") when its MTEPS lands >2x below the recorded
    operating point (297 MTEPS at scale 20 / W=256).

DESIGN (round 2, from the measured probe decomposition in
benchmarks/results/instrument_r2_raw*.txt):
  * per-launch dispatch through the axon tunnel costs ~105 ms regardless
    of resident argument bytes → the WHOLE batch is ONE launch;
  * the ELL SpMV kernel is gather-bound (~130M idx/s small-table) and a
    gather's cost is per-INDEX: all NROOTS roots advance together as one
    [n, W] frontier matrix (bfs_batch; SURVEY §2.3 strategy 7);
  * kernel-2 TEPS accounting runs on device (batch_traversed_edges); the
    only D2H is one [W] vector + the sync scalar, AFTER timing;
  * int8 LEVEL indicators + one-pass parent reconstruction
    (bfs_batch_compact) halve HBM state.

PER-ROOT STATISTICS (round 4: BOTH are reported):
  * amortized (equal-share) decomposition of the batch: every level's
    gather serves all W roots at once, so each root's attributed time is
    dt/W: TEPS_r = te_r * W / dt, harmonic-mean over live roots.  A real
    property of the batched design, but not the spec's statistic.
  * SEQUENTIAL per-root (the spec's, TopDownBFS.cpp:437-479):
    BENCH_SEQ_ROOTS (default 16) additional children each run ONE root,
    timed individually.  One process per root because per-root timing
    needs a D2H sync and the first readback poisons a process (below).
    "seq_harmonic_mean_mteps" is the only number apples-to-apples with
    BASELINE.md (which stores exactly this statistic).
    ROUND 5: the sequential child runs models/bfs.py:bfs_single — the
    FRONTIER-PROPORTIONAL tiered kernel (budgeted sparse column walks +
    dense sweep chosen per level on device, parents carried in the
    gathers) instead of the W=1 batched kernel whose every level paid a
    frontier-independent O(nnz) gather (VERDICT r4 Missing #1; the
    reference's top-down property, BFSFriends.h:59-182). Tier spec:
    BENCH_SEQ_TIERS="td:F0,..,F5|bu:F0,..,F5|..." — per-degree-class
    vertex budgets on models/bfs.py:BFS_CLASS_LADDER; an untimed warmup
    child populates the compile cache before the timed roots.

VALIDATION (round 4): each repeat child runs the device-side Graph500
tree checks (models/bfs.py:validate_bfs_device) AFTER its timed readback
(validation launches run poisoned — slow but harmless to timing); the
official JSON carries the median run's counts plus a "validated" flag
covering every successful repeat.  BENCH_VALIDATE=0 disables.

KERNEL 1: graph construction is timed (construction_s in the JSON: host
R-MAT + dedup + ELL bucketing + upload).  The fully-distributed device
composition of kernel 1 (generate → all_to_all route → dedup →
relabel → isolated-compression, models/graph500.py:kernel1_device) is
exercised by __graft_entry__.dryrun_multichip and tests/test_graph500.py;
it is not used here because its sizing readbacks would poison the timed
BFS launches in the same process (readback note below).

AXON D2H NOTE: this chip's runtime permanently degrades launch performance
(~1000x) after ANY device->host readback, so each child is strictly
phased: (1) host graph load + ELL bucketing, (2) one upload, (3) ONE
timed launch closed by the te readback (the only reliable sync).

vs_baseline compares single-chip MTEPS against the smallest archived
reference run: 1,636 MTEPS on 1,024 Hopper (Cray XE6) cores
(BASELINE.md: HopperResults/script1024.reducedgraph_mini:149).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time

SCALE = int(os.environ.get("BENCH_SCALE", "20"))
EDGEFACTOR = int(os.environ.get("BENCH_EDGEFACTOR", "16"))
NROOTS = int(os.environ.get("BENCH_NROOTS", "256"))
DIROPT = os.environ.get("BENCH_DIROPT", "0") == "1"
REPEATS = int(os.environ.get("BENCH_REPEATS", "3"))
DRAIN_S = float(os.environ.get("BENCH_DRAIN_S", "45"))
# Round 4: validation is part of the OFFICIAL protocol (VERDICT r3 item 3)
# — each repeat child runs the device-side Graph500 checks after its timed
# readback, so the reported median is a validated number.
VALIDATE = os.environ.get("BENCH_VALIDATE", "1") == "1"
# Round 4: the spec's SEQUENTIAL per-root statistic (VERDICT r3 item 4,
# TopDownBFS.cpp:437-479): BENCH_SEQ_ROOTS extra children each time ONE
# root in its own process (per-root timing needs a D2H sync, and one
# readback poisons a process — so sequential roots cost a process each).
# Reported as the harmonic-mean per-root MTEPS next to the amortized
# batched statistic; this is the only number comparable with BASELINE.md.
SEQ_ROOTS = int(os.environ.get("BENCH_SEQ_ROOTS", "16"))
# single-root warmup executions are short (the frontier-proportional
# kernel's whole traversal is ~1-2 s); the W=256 repeats keep the 45 s
SEQ_DRAIN_S = float(os.environ.get("BENCH_SEQ_DRAIN_S", "10"))
# wall-clock budget the whole protocol must fit (driver timeout guard);
# repeats always run, sequential roots fill the remainder
BUDGET_S = float(os.environ.get("BENCH_BUDGET_S", "1200"))
# frontier-proportional tier ladder for the sequential child:
# "frontier_cap:edge_cap,..." ascending; beyond the last tier a level
# runs the dense sweep (the bottom-up regime)
# class-budget tier ladder (see models/bfs.py parse_tier_spec): a
# small top-down tier for the pre-peak levels, two bottom-up tiers for
# the post-peak levels (measured scale-20 level anatomy: one dense step
# per traversal), dense for the peak
from combblas_tpu.models.bfs import DEFAULT_SEQ_TIERS  # noqa: E402

SEQ_TIERS = os.environ.get("BENCH_SEQ_TIERS", DEFAULT_SEQ_TIERS)
BASELINE_MTEPS = 1636.0  # Hopper 1024 cores, R-MAT "mini"
OPERATING_MTEPS = 297.0  # recorded sweep at scale 20 / W=256 (r2h)
def _enable_compile_cache():
    """Persistent compilation cache (see utils/compile_cache.py):
    children share compiled programs with each other and with prior
    runs, so the 16 sequential-root processes compile bfs_single exactly
    once. BENCH_NOCACHE=1 disables (diagnostic)."""
    from combblas_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache()


def _obs_setup(tag: str) -> str | None:
    """BENCH_OBS=1: enable the structured telemetry subsystem in this
    process (combblas_tpu.obs; docs/observability.md) with a per-process
    JSONL sidecar — spans for the load/warmup/timed phases, compile-cache
    hit/miss counters, kernel dispatch counts. The official stdout JSON
    protocol is unchanged; each child reports its sidecar path under
    "obs_jsonl" and the parent merges them (the multihost-style
    per-process-files-merged-host-side aggregation path).

    DEVICE_SYNC stays OFF here: obs must never add a readback to a timed
    child on this chip (bench.py module docstring)."""
    from combblas_tpu import obs

    return obs.enable_sidecar(tag)


def _obs_dump(out: dict) -> None:
    """Dump this process's telemetry sidecar (if enabled) and reference
    it in the child's JSON line."""
    from combblas_tpu import obs

    if obs.ENABLED:
        try:
            out["obs_jsonl"] = obs.dump_jsonl()
        except Exception as e:  # telemetry must never fail the bench
            out["obs_error"] = str(e)


def build_graph_npz(path: str) -> float:
    """Kernel 1, host path: R-MAT generate + symmetricize + dedup; returns
    construction seconds (graph build only; the search structures are
    added by augment_npz_with_structures and timed separately)."""
    import numpy as np

    from combblas_tpu.utils.rmat import rmat_symmetric_coo_host

    t0 = time.perf_counter()
    n = 1 << SCALE
    rows, cols = rmat_symmetric_coo_host(42, SCALE, EDGEFACTOR)
    key = rows * np.int64(n) + cols
    uniq = np.unique(key)
    rows_u = (uniq // n).astype(np.int64)
    cols_u = (uniq % n).astype(np.int64)
    deg = np.bincount(rows_u, minlength=n)
    dt = time.perf_counter() - t0
    rng = np.random.default_rng(7)
    roots = rng.choice(np.flatnonzero(deg > 0), size=NROOTS, replace=False)
    np.savez(
        path,
        rows=rows_u.astype(np.int32),  # scale <= 31 fits; halves the file
        cols=cols_u.astype(np.int32),
        deg=deg.astype(np.int32),
        roots=roots.astype(np.int32),
    )
    return dt


def augment_npz_with_structures(path: str) -> float:
    """Kernel-1 tail, host: build the ELL buckets + CSC companion ONCE in
    the parent (numpy only — the parent never attaches to the chip) and
    append them to the graph .npz, so every timing child just uploads.
    Returns build seconds (counted into construction_s: the reference's
    kernel 1 likewise includes assembling the search structure,
    SpParMat.cpp:3343 OptimizeForGraph500)."""
    import numpy as np

    from combblas_tpu.parallel.ellmat import (
        EllParMat,
        build_csc_companion_host,
    )
    from combblas_tpu.parallel.grid import HostGrid

    t0 = time.perf_counter()
    z = dict(np.load(path))
    grid = HostGrid(1, 1)
    n = 1 << SCALE
    buckets = EllParMat.host_build(
        grid, z["rows"], z["cols"],
        np.zeros(len(z["rows"]), np.int8), n, n,
    )
    indptr, rowidx = build_csc_companion_host(
        grid, z["rows"], z["cols"], n, n
    )
    z["csc_indptr"], z["csc_rowidx"] = indptr, rowidx
    z["nnz"] = np.int64(len(z["rows"]))
    z["ell_nbuckets"] = np.int32(len(buckets))
    for b, (bc, _bv, br) in enumerate(buckets):
        z[f"ell{b}_bc"] = bc
        z[f"ell{b}_br"] = br
    np.savez(path, **z)
    return time.perf_counter() - t0


def k1_device_child(path: str):
    """Kernel 1, DISTRIBUTED device path (VERDICT r3 item 7): run
    ``models/graph500.py:kernel1_device`` on the chip in THIS dedicated
    process (the post-build readback poisons it — which is why the timed
    BFS runs in separate child processes), serialize the graph for the
    BFS children, and report per-stage construction timings.  This makes
    the official construction_s the distributed pipeline's number
    (SpParMat.cpp:3140-3441 role) instead of the host numpy path."""
    _enable_compile_cache()
    _obs_setup("k1")
    import jax
    import numpy as np

    from combblas_tpu.models.graph500 import kernel1_device
    from combblas_tpu.parallel.grid import Grid

    def log(msg):
        print(f"[k1] {time.strftime('%H:%M:%S')} {msg}",
              file=sys.stderr, flush=True)

    grid = Grid.make(1, 1)
    n = 1 << SCALE
    # warmup pass: compiles every stage (the per-stage syncs are
    # block_until_ready, not readbacks, so the process stays unpoisoned);
    # the timed pass below then measures construction EXECUTION, matching
    # the host path's semantics (the reference doesn't time compilation)
    log("warmup start")
    _, _, _, wt = kernel1_device(
        grid, SCALE, EDGEFACTOR, jax.random.PRNGKey(41),
        compress_isolated=False,
    )
    log(f"warmup done {[ (k, round(v,1)) for k,v in wt.items() if k != 'dropped_dev' ]}")
    time.sleep(float(os.environ.get("BENCH_K1_DRAIN_S", "15")))
    t0 = time.perf_counter()
    A, degrees, _nkeep, timings = kernel1_device(
        grid, SCALE, EDGEFACTOR, jax.random.PRNGKey(42),
        compress_isolated=False,
    )
    construction_s = time.perf_counter() - t0
    log(f"timed pass done {construction_s:.1f}s")
    # post-timing verification (first readback of this process): the
    # deferred route-capacity drop count must be zero or the build is
    # invalid and the parent falls back to the host kernel 1
    dropped = int(np.asarray(jax.device_get(timings.pop("dropped_dev"))))
    if dropped != 0:
        raise SystemExit(f"kernel1_device dropped {dropped} tuples")
    log("drop check ok; D2H start")
    # D2H serialization (untimed: the reference hands kernel 1's output to
    # kernel 2 in-memory; our process boundary is the axon-poison firewall)
    t = A.local_tile(A.rows, A.cols, A.vals, A.nnz)
    rows = np.asarray(jax.device_get(t.rows))
    log("rows fetched")
    cols = np.asarray(jax.device_get(t.cols))
    log("cols fetched")
    live = rows < n
    rows_u, cols_u = rows[live], cols[live]
    deg = np.asarray(jax.device_get(degrees.blocks)).reshape(-1)[:n]
    log("deg fetched; writing npz")
    rng = np.random.default_rng(7)
    roots = rng.choice(np.flatnonzero(deg > 0), size=NROOTS, replace=False)
    np.savez(
        path,
        rows=rows_u.astype(np.int32),
        cols=cols_u.astype(np.int32),
        deg=deg.astype(np.int32),
        roots=roots.astype(np.int32),
    )
    out = {
        "construction_s": round(construction_s, 2),
        "stages": {k: round(v, 3) for k, v in timings.items()},
        "nnz": int(len(rows_u)),
    }
    _obs_dump(out)
    print(json.dumps(out))


def _load_structures(grid, data, n, want_csc=True):
    """Upload the parent-prebuilt ELL buckets (+ CSC companion when the
    caller walks columns — ``want_csc=False`` skips its ~4B/nnz upload
    in the plain batched repeats) from the .npz, falling back to
    in-child construction for an un-augmented graph file."""
    import numpy as np

    from combblas_tpu.parallel.ellmat import (
        EllParMat,
        build_csc_companion,
        upload_csc_companion,
    )

    if "ell_nbuckets" in data:
        nb = int(data["ell_nbuckets"])
        host_buckets = [
            (
                data[f"ell{b}_bc"],
                np.zeros(data[f"ell{b}_bc"].shape, np.int8),
                data[f"ell{b}_br"],
            )
            for b in range(nb)
        ]
        E = EllParMat.from_host_buckets(grid, host_buckets, n, n)
        csc = (
            upload_csc_companion(
                grid, data["csc_indptr"], data["csc_rowidx"]
            )
            if want_csc else None
        )
    else:
        rows_u, cols_u = data["rows"], data["cols"]
        E = EllParMat.from_host_coo(
            grid, rows_u, cols_u,
            np.zeros(len(rows_u), np.int8), n, n,
        )
        csc = (
            build_csc_companion(grid, rows_u, cols_u, n, n)
            if want_csc else None
        )
    return E, csc


def seq_child(graph_path: str, seq_idx: int):
    """Sequential-statistic child: ONE root, frontier-proportional
    tiered BFS (bfs_single), one launch, own process."""
    _enable_compile_cache()
    _obs_setup(f"seq{seq_idx}")
    import jax
    import numpy as np

    from combblas_tpu import obs
    from combblas_tpu.models.bfs import bfs_single, single_traversed_edges
    from combblas_tpu.parallel.grid import Grid
    from combblas_tpu.parallel.vec import DistVec

    grid = Grid.make(1, 1)
    n = 1 << SCALE

    t0 = time.perf_counter()
    with obs.span("bench.load"):
        data = np.load(graph_path)
        root = np.int32(data["roots"][seq_idx])
        E, csc = _load_structures(grid, data, n)
        deg_blocks = DistVec.from_global(
            grid, data["deg"], align="row"
        ).blocks
        # symmetric graph: per-column degrees == per-row degrees;
        # host-built (deriving them from the CSC indptr on device hits the
        # chip's pathological megascale-1-D path, probe_seq_r5 mode v6)
        coldeg_blocks = DistVec.from_global(
            grid, data["deg"], align="col"
        ).blocks
    from combblas_tpu.models.bfs import parse_tier_spec

    tiers = parse_tier_spec(SEQ_TIERS)
    construction_child_s = time.perf_counter() - t0

    # csr=csc REUSE CONTRACT (ADVICE r5): bfs_single's "bu" tiers walk the
    # CSR companion, and reusing the CSC there is correct ONLY because
    # (a) the Graph500 graph is SYMMETRIZED — in-edges equal out-edges, so
    # the column-major companion doubles as the row-major one — and
    # (b) the grid is 1x1, so build_csr_companion's per-tile layout
    # degenerates to the same single global array. An asymmetric graph or
    # a multi-chip grid must build the real companion
    # (ellmat.build_csr_companion / a csr twin in
    # augment_npz_with_structures) — fail loudly rather than traverse
    # wrong in-edges.
    assert grid.pr == 1 and grid.pc == 1, (
        "seq_child reuses csr=csc, valid only on a 1x1 grid with a "
        "symmetrized graph; build the real CSR companion for "
        f"{grid.pr}x{grid.pc}"
    )

    # warmup (compile via the persistent cache + one full execution)
    t0 = time.perf_counter()
    with obs.span("bench.warmup"):
        p, _, _ = bfs_single(E, root, csc, csr=csc, tiers=tiers,
                             coldeg=coldeg_blocks, rowdeg=deg_blocks)
        te_dev = single_traversed_edges(deg_blocks, p)
        jax.block_until_ready(te_dev)
    warmup_s = time.perf_counter() - t0
    time.sleep(SEQ_DRAIN_S)

    t0 = time.perf_counter()
    with obs.span("bench.timed", root_index=int(seq_idx)):
        p, l, niter = bfs_single(E, root, csc, csr=csc, tiers=tiers,
                                 coldeg=coldeg_blocks, rowdeg=deg_blocks)
        te_dev = single_traversed_edges(deg_blocks, p)
        te = int(np.asarray(jax.device_get(te_dev)))  # true barrier
    dt = time.perf_counter() - t0
    obs.span_event("bfs.result", traversed_edges=te, root_index=int(seq_idx))

    out = {
        "mteps": round(te / dt / 1e6, 4),
        "dt_s": round(dt, 4),
        "warmup_s": round(warmup_s, 2),
        "drain_s": SEQ_DRAIN_S,
        "total_traversed_edges": te,
        "levels": int(np.asarray(jax.device_get(niter))),
        "root_index": int(seq_idx),
        "construction_child_s": round(construction_child_s, 2),
    }
    if VALIDATE and os.environ.get("BENCH_SEQ_VALIDATE_THIS") == "1":
        # the headline statistic's kernel gets the same device-side tree
        # checks as the batch path (predeclared: the FIRST timed root
        # validates; the launch runs post-readback/poisoned — slow but
        # harmless to the timing)
        import jax.numpy as jnp

        from combblas_tpu.models.bfs import validate_bfs_device
        from combblas_tpu.parallel.vec import DistMultiVec

        mv = lambda v, dt_: DistMultiVec(
            blocks=v.blocks[:, :, None].astype(dt_), length=v.length,
            align=v.align, grid=v.grid,
        )
        v = np.asarray(jax.device_get(validate_bfs_device(
            E, mv(p, jnp.int32), mv(l, jnp.int32)
        )))
        out["validation"] = {
            "roots_bad": int(v[0].sum()),
            "level_step_bad": int(v[1].sum()),
            "tree_edge_bad": int(v[2].sum()),
            "edge_consistency_bad": int(v[3].sum()),
        }
    _obs_dump(out)
    print(json.dumps(out), flush=True)


def child(graph_path: str):
    _enable_compile_cache()
    _obs_setup("batch")
    import jax
    import numpy as np

    from combblas_tpu import obs

    from combblas_tpu.models.bfs import batch_traversed_edges, bfs_batch_compact
    from combblas_tpu.parallel.grid import Grid
    from combblas_tpu.parallel.vec import DistVec

    grid = Grid.make(1, 1)
    n = 1 << SCALE

    # --- Phase 1+2: host-only load, then upload (H2D only) ----------------
    t0 = time.perf_counter()
    with obs.span("bench.load"):
        data = np.load(graph_path)
        deg, roots = data["deg"], data["roots"]
        nnz = (
            int(data["nnz"]) if "nnz" in data else len(data["rows"])
        )
        E, csc_arrays = _load_structures(grid, data, n, want_csc=DIROPT)
        csc = None
        fcap = ecap = None
        if DIROPT:
            csc = csc_arrays
            fcap = grid.local_cols(n) // 8
            ecap = max(nnz // 16, 1 << 20)
        deg_blocks = DistVec.from_global(grid, deg, align="row").blocks
        roots_dev = jax.device_put(np.asarray(roots, np.int32))
    construction_child_s = time.perf_counter() - t0

    # --- Phase 3: ONE timed launch ----------------------------------------
    # Warmup compiles AND executes the whole batched program.
    # block_until_ready is not a reliable barrier through the tunnel, so
    # the drain sleep must cover the warmup EXECUTION (~20-30 s at the
    # operating point), not just dispatch — hence DRAIN_S=45 default.
    t0 = time.perf_counter()
    with obs.span("bench.warmup"):
        p, _, _ = bfs_batch_compact(
            E, roots_dev, csc=csc, frontier_capacity=fcap, edge_capacity=ecap
        )
        te_dev = batch_traversed_edges(deg_blocks, p)
        jax.block_until_ready(te_dev)
    warmup_s = time.perf_counter() - t0
    time.sleep(DRAIN_S)

    t0 = time.perf_counter()
    with obs.span("bench.timed", roots=int(len(roots))):
        parents, levels, _ = bfs_batch_compact(
            E, roots_dev, csc=csc, frontier_capacity=fcap, edge_capacity=ecap
        )
        te_dev = batch_traversed_edges(deg_blocks, parents)
        te = np.asarray(jax.device_get(te_dev))  # true barrier (poisons after)
    dt = time.perf_counter() - t0

    validation = None
    if VALIDATE:
        # Graph500 tree validation ON DEVICE (verify.c intent) — after the
        # timed section (the readback above already poisoned this process,
        # so the validation launch is slow but harmless to the timing).
        # Validates a LANE SUBSET: the validator's bucket-sweep
        # intermediates scale with slots x lanes (~46 GB at W=256 on
        # scale 20 — past HBM), so a handful of lanes is the memory-sane
        # spot check (BENCH_VALIDATE_LANES, default 4).
        from combblas_tpu.models.bfs import validate_bfs_device

        import jax.numpy as jnp

        nl = min(int(os.environ.get("BENCH_VALIDATE_LANES", "4")), len(te))

        def lanes(mv, dtype=None):
            b = mv.blocks[:, :, :nl]
            return type(mv)(
                blocks=b.astype(dtype) if dtype is not None else b,
                length=mv.length, align=mv.align, grid=mv.grid,
            )

        v = np.asarray(
            jax.device_get(
                validate_bfs_device(
                    E, lanes(parents), lanes(levels, jnp.int32)
                )
            )
        )
        validation = {
            "lanes_checked": nl,
            "roots_bad": int(v[0].sum()),
            "level_step_bad": int(v[1].sum()),
            "tree_edge_bad": int(v[2].sum()),
            "edge_consistency_bad": int(v[3].sum()),
        }

    # --- Phase 4: accounting ----------------------------------------------
    total_te = int(te.astype(np.int64).sum())
    W = len(te)
    mteps = total_te / dt / 1e6
    live = te[te > 0].astype(np.float64)
    hm = (
        (len(live) * W / (dt * np.sum(1.0 / live)) / 1e6)
        if len(live) else 0.0
    )
    out = {
        "mteps": round(mteps, 2),
        "harmonic_mean_amortized_mteps": round(float(hm), 2),
        "dt_s": round(dt, 3),
        "warmup_s": round(warmup_s, 2),
        "drain_s": DRAIN_S,
        "total_traversed_edges": total_te,
        "roots": int(W),
        "reachable_roots": int((te > 0).sum()),
        "construction_child_s": round(construction_child_s, 2),
    }
    if validation is not None:
        out["validation"] = validation
    if mteps < OPERATING_MTEPS / 2 and SCALE == 20 and NROOTS == 256:
        out["warning"] = (
            f"{mteps:.1f} MTEPS is >2x below the recorded operating point "
            f"({OPERATING_MTEPS}); suspect drain/compile-cache/chip state"
        )
    _obs_dump(out)
    print(json.dumps(out), flush=True)


def batch_median(runs) -> float:
    """Median batch MTEPS over the successful repeats (the same run
    ``emit`` picks as ``med_run``)."""
    ok = sorted(r.get("mteps", 0.0) for r in runs if r.get("mteps", 0) > 0)
    return ok[(len(ok) - 1) // 2] if ok else 0.0


def diagnose_variance(runs, rerun) -> dict:
    """The ``variance`` block (ISSUE 3 satellite): when the batch median
    lands >2x below the recorded operating point, ONE fresh child is
    re-run and the block names the leading suspect instead of leaving
    only a warning string.

      warmup contamination — the fresh child recovers the operating
          point, so the original children's timed windows overlapped
          leftover warmup execution (the round-2 6.3x swing mechanism);
      cache-cold — warmup_s shows the compile cache was cold, so the
          drain did not cover the first execution;
      degraded regime — the fresh child is ALSO slow: chip/host state,
          not a protocol artifact.
    """
    med = batch_median(runs)
    rerun_mteps = rerun.get("mteps", 0.0)
    warm = [
        r.get("warmup_s", 0.0) for r in runs if r.get("mteps", 0) > 0
    ]
    if rerun_mteps >= OPERATING_MTEPS / 2:
        suspect = "warmup_contamination"
        detail = (
            f"fresh child measured {rerun_mteps:.1f} MTEPS (>= half the "
            f"operating point): the original repeats' timed windows "
            "likely overlapped leftover warmup execution"
        )
    elif warm and max(warm) > 60:
        suspect = "cache_cold"
        detail = (
            f"max warmup_s={max(warm):.0f}s: cold compile cache pushed "
            "execution past the drain window"
        )
    else:
        suspect = "degraded_regime"
        detail = (
            f"fresh child also slow ({rerun_mteps:.1f} MTEPS): suspect "
            "chip/host state, not the protocol"
        )
    return {
        "median_mteps": round(med, 2),
        "operating_point_mteps": OPERATING_MTEPS,
        "rerun_mteps": round(rerun_mteps, 2),
        "suspect": suspect,
        "detail": detail,
    }


def emit_summary(official, rc: int = 0, path: str | None = None) -> None:
    """Print the COMPACT headline summary as the FINAL stdout line and
    mirror it to ``BENCH_SUMMARY.json`` (ISSUE 3 satellite): the r05
    driver capture lost its headline because tail truncation ate the end
    of the giant per-run record — a ~150-byte final line plus a sidecar
    file cannot lose it again.  The full record stays on the earlier
    lines (``emit``)."""
    official = official or {}
    s = {
        "summary": 1,
        "metric": official.get("metric"),
        "value": official.get("value", 0.0),
        "median": official.get(
            "batch_median_mteps", official.get("value", 0.0)
        ),
        "warning": official.get("warning"),
        "rc": rc,
    }
    # round-10 plan provenance (store hit vs probe vs heuristic + the
    # chosen knobs) rides along when the child reported it — still a
    # compact, truncation-proof line
    for k in ("plan_source", "plan"):
        if official.get(k) is not None:
            s[k] = official[k]
    path = path or os.environ.get("BENCH_SUMMARY_PATH", "BENCH_SUMMARY.json")
    try:
        with open(path, "w") as f:
            json.dump(s, f)
            f.write("\n")
    except OSError as e:
        s["summary_write_error"] = f"{path}: {e}"
    print(json.dumps(s), flush=True)


def emit(runs, seq_runs, construction_s, k1_info, t_start, variance=None):
    """Assemble and PRINT (flushed) the official JSON line from whatever
    has completed so far — called after the repeat phase and again after
    every sequential-root child, so a driver timeout at any point still
    finds a complete last line (VERDICT r4 Weak #1). Returns the dict it
    printed (the parent's ``emit_summary`` source)."""
    ok = sorted(
        (r for r in runs if r.get("mteps", 0) > 0), key=lambda r: r["mteps"]
    )
    # median REPEAT: value and the per-root statistic come from the same run
    med_run = ok[(len(ok) - 1) // 2] if ok else {}
    median = med_run.get("mteps", 0.0)
    # Graph500-spec sequential statistic: harmonic mean of per-root TEPS
    # over the individually-timed roots (each its own process)
    seq_ok = [
        r for r in seq_runs
        if r.get("mteps", 0) > 0 and r.get("total_traversed_edges", 0) > 0
    ]
    seq_hm = (
        len(seq_ok) / sum(1.0 / r["mteps"] for r in seq_ok) if seq_ok else 0.0
    )
    # HEADLINE RULE (docstring): the spec's sequential statistic is the
    # value once >= 4 roots are individually timed; the amortized batch
    # median otherwise (and always alongside as batch_median_mteps).
    spec_headline = len(seq_ok) >= 4
    value = seq_hm if spec_headline else median
    out = {
        "metric": f"graph500_bfs_rmat_scale{SCALE}_1chip_MTEPS",
        "value": round(value, 2),
        "unit": "MTEPS",
        "vs_baseline": round(value / BASELINE_MTEPS, 6),
        "statistic": (
            "seq_per_root_harmonic_mean" if spec_headline
            else "amortized_batch_median"
        ),
        "batch_median_mteps": round(median, 2),
        "batch_vs_baseline": round(median / BASELINE_MTEPS, 4),
        "repeats_mteps": [r.get("mteps", 0.0) for r in runs],
        "harmonic_mean_amortized_mteps": med_run.get(
            "harmonic_mean_amortized_mteps", 0.0
        ),
        "seq_harmonic_mean_mteps": round(seq_hm, 3),
        "seq_roots_timed": len(seq_ok),
        "seq_roots_planned": min(SEQ_ROOTS, NROOTS),
        "seq_per_root_mteps": [r.get("mteps", 0.0) for r in seq_runs],
        "seq_vs_baseline": round(seq_hm / BASELINE_MTEPS, 6),
        "construction_s": round(construction_s, 2),
        "construction": k1_info,
        "validation": med_run.get("validation"),
        "seq_validation": next(
            (r["validation"] for r in seq_ok if r.get("validation")), None
        ),
        "validated": bool(
            ok
            and all(
                r.get("validation") is not None
                and not any(
                    v for k, v in r["validation"].items() if k.endswith("_bad")
                )
                for r in ok
            )
            # when the headline IS the seq statistic, its kernel's tree
            # check must also be clean
            and (
                not spec_headline
                or any(
                    r.get("validation") is not None
                    and not any(
                        v for k, v in r["validation"].items()
                        if k.endswith("_bad")
                    )
                    for r in seq_ok
                )
            )
        ),
        "budget_s": BUDGET_S,
        "elapsed_s": round(time.perf_counter() - t_start, 1),
        "runs": runs,
        "seq_runs": seq_runs,
    }
    if ok:
        # median + spread of the (>= 3 by default) repeats — the
        # variance-diagnosis satellite's visibility requirement
        vals = [r["mteps"] for r in ok]
        out["repeats_spread"] = {
            "min": round(min(vals), 2),
            "max": round(max(vals), 2),
            "rel_spread": round(
                (max(vals) - min(vals)) / max(median, 1e-9), 3
            ),
        }
    if variance is not None:
        out["variance"] = variance
    if not ok:
        out["error"] = (
            "no repeat produced a valid measurement; see 'runs' for "
            "per-child diagnostics"
        )
    if median < OPERATING_MTEPS / 2 and SCALE == 20 and NROOTS == 256:
        out["warning"] = (
            f"batch median {median:.1f} MTEPS >2x below operating point "
            f"{OPERATING_MTEPS}; see per-run diagnostics in 'runs'"
        )
    print(json.dumps(out), flush=True)
    return out


def serve_bench_main():
    """BENCH_SERVE=1: the query-serving benchmark
    (benchmarks/serve_bench.py — batched lanes vs one-call-per-query on
    the 8-virtual-device CPU mesh). The child emits its
    serve-throughput telemetry as a JSONL sidecar through the existing
    obs.enable_sidecar plumbing (BENCH_OBS defaults ON for this path;
    the sidecar path rides the JSON line as "obs_jsonl").  The chaos /
    mutate / pool scenario knobs (BENCH_SERVE_CHAOS, BENCH_SERVE_MUTATE,
    BENCH_SERVE_POOL — the round-14 multi-tenant scenario emits its own
    headline summary line too) pass through via the environment."""
    _virtual_mesh_bench_main(
        "serve_bench.py", "serve_throughput",
        # every serve scenario reports its acceptance AND in "ok";
        # falling back to value covers a crashed child's stub dict
        rc_of=lambda out: out.get("ok", out.get("value", 0)),
        # the child's detail line must stay LAST under this runner:
        # the pool scenario's standalone summary line is suppressed
        extra_env={"BENCH_OBS": "1", "BENCH_EMIT_SUMMARY": "0"},
    )


def _virtual_mesh_bench_main(script_name: str, metric: str, rc_of,
                             extra_env: dict | None = None):
    """Shared child-runner for the virtual-8-device-mesh benches
    (serve_bench / spmm_bench): subprocess isolation so the forced CPU
    platform / device-count flags never touch THIS process's backend,
    the timeout fallback, and the JSON-tail guard (the official stream
    must stay one valid JSON line even when the child crashes or
    leaves stray stdout).  ``rc_of(out)`` maps the child's final dict
    to the summary rc."""
    env = dict(os.environ)
    for k, v in (extra_env or {}).items():
        env.setdefault(k, v)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    )
    script = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "benchmarks", script_name,
    )
    try:
        r = subprocess.run(
            [sys.executable, script], capture_output=True, text=True,
            env=env,
            timeout=float(os.environ.get("BENCH_CHILD_TIMEOUT", "1800")),
        )
    except subprocess.TimeoutExpired as e:
        out = {
            "metric": metric, "value": 0.0,
            "error": f"{script_name} child timed out after {e.timeout}s",
        }
        print(json.dumps(out), flush=True)
        emit_summary(out, rc=1)
        return
    lines = [l for l in r.stdout.strip().splitlines() if l.strip()]
    try:
        if r.returncode != 0 or not lines:
            raise json.JSONDecodeError("child failed", "", 0)
        out = json.loads(lines[-1])
    except json.JSONDecodeError:
        out = {
            "metric": metric, "value": 0.0,
            "error": (r.stderr or "no output")[-2000:],
        }
    print(json.dumps(out), flush=True)
    emit_summary(out, rc=0 if rc_of(out) else 1)


def spmm_bench_main():
    """BENCH_SPMM=1: the batched-SpMM benchmark
    (benchmarks/spmm_bench.py — fused k-hop sparse×dense vs
    loop-over-columns batch SpMV, scipy golden, and the serve
    "propagate" zero-retrace capture)."""
    _virtual_mesh_bench_main(
        "spmm_bench.py", "spmm_khop_speedup",
        rc_of=lambda out: out.get("ok"),
    )


def main():
    t_start = time.perf_counter()
    if os.environ.get("BENCH_SPMM") == "1":
        spmm_bench_main()
        return
    if os.environ.get("BENCH_SERVE") == "1":
        serve_bench_main()
        return
    if os.environ.get("BENCH_SEQ_ROOT_IDX") is not None:
        seq_child(
            os.environ["BENCH_GRAPH_NPZ"],
            int(os.environ["BENCH_SEQ_ROOT_IDX"]),
        )
        return
    if os.environ.get("BENCH_CHILD"):
        child(os.environ["BENCH_GRAPH_NPZ"])
        return
    if os.environ.get("BENCH_K1_CHILD"):
        k1_device_child(os.environ["BENCH_GRAPH_NPZ"])
        return

    import shutil

    def remaining():
        return BUDGET_S - (time.perf_counter() - t_start)

    tmp = tempfile.mkdtemp(prefix="bench_g500_")
    try:
        graph_path = os.path.join(tmp, "graph.npz")
        k1_info = None
        # BENCH_K1=device runs the distributed kernel1_device pipeline in a
        # dedicated process (k1_device_child). It works and is captured at
        # scale 14 (per-stage timings in the r4 smoke artifact), but the
        # axon REMOTE COMPILER takes >14 min to compile the route/dedup
        # program at scale >= 17 (PERF_NOTES_r4), so the official default
        # stays on the host kernel 1 to protect the driver's wall clock.
        if os.environ.get("BENCH_K1", "host") == "device":
            # distributed kernel 1 in its own process (see k1_device_child)
            env = dict(os.environ)
            env["BENCH_K1_CHILD"] = "1"
            env["BENCH_GRAPH_NPZ"] = graph_path
            try:
                r = subprocess.run(
                    [sys.executable, os.path.abspath(__file__)],
                    capture_output=True, text=True, env=env,
                    cwd=os.path.dirname(os.path.abspath(__file__)),
                    timeout=float(os.environ.get("BENCH_CHILD_TIMEOUT", "1800")),
                )
                k1_info = json.loads(
                    (r.stdout.strip().splitlines() or ["{}"])[-1]
                )
            except (subprocess.TimeoutExpired, json.JSONDecodeError):
                k1_info = None
        if k1_info and os.path.exists(graph_path):
            construction_s = k1_info["construction_s"]
        else:
            # fallback: host kernel 1 (and say so in the artifact) —
            # with the most recent DEVICE kernel-1 per-stage capture
            # attached so the distributed path is visible in the
            # official JSON even when the remote compiler can't build
            # it at this scale in budget (VERDICT r4 item 7)
            k1_info = {"fallback": "host numpy kernel 1"}
            ref = os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                "benchmarks", "results", "r5", "k1_device_stages.json",
            )
            if os.path.exists(ref):
                with open(ref) as f:
                    k1_info["device_reference"] = json.load(f)
            construction_s = build_graph_npz(graph_path)
        # search-structure assembly (ELL buckets + CSC companion), ONCE,
        # in the parent — part of kernel 1 (OptimizeForGraph500 role),
        # counted into construction_s; children only upload.
        structures_s = augment_npz_with_structures(graph_path)
        construction_s += structures_s
        k1_info["structures_s"] = round(structures_s, 2)

        def run_child(extra_env):
            env = dict(os.environ)
            env["BENCH_GRAPH_NPZ"] = graph_path
            env.update(extra_env)
            try:
                r = subprocess.run(
                    [sys.executable, os.path.abspath(__file__)],
                    capture_output=True, text=True, env=env,
                    cwd=os.path.dirname(os.path.abspath(__file__)),
                    timeout=float(os.environ.get("BENCH_CHILD_TIMEOUT", "1800")),
                )
                line = (r.stdout.strip().splitlines() or [""])[-1]
                stderr_tail = (r.stderr.strip().splitlines() or ["no output"])[-1]
            except subprocess.TimeoutExpired:
                line, stderr_tail = "", "child timeout (wedged launch?)"
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                return {"mteps": 0.0, "error": stderr_tail}

        runs = [
            run_child({"BENCH_CHILD": "1"}) for _ in range(max(REPEATS, 1))
        ]
        # REPEAT REPLACEMENT (predeclared; VERDICT r4 Weak #6): one extra
        # repeat if any landed >2x below the operating point or failed;
        # the original stays in "runs", the median absorbs both.
        # VARIANCE DIAGNOSIS (ISSUE 3 satellite): when the MEDIAN itself
        # is >2x below the operating point, the same fresh child doubles
        # as the diagnostic probe and the official record carries a
        # structured "variance" block naming the suspect.
        variance = None
        degraded = (
            batch_median(runs) < OPERATING_MTEPS / 2
            and SCALE == 20 and NROOTS == 256
        )
        if degraded or any(
            r.get("warning") or r.get("mteps", 0) <= 0 for r in runs
        ):
            rerun = run_child({"BENCH_CHILD": "1"})
            rerun["replacement"] = True
            if degraded:
                variance = diagnose_variance(runs, rerun)
            runs.append(rerun)

        seq_runs = []
        # line 1: complete official record before any sequential root
        official = emit(
            runs, seq_runs, construction_s, k1_info, t_start, variance
        )

        # UNTIMED WARMUP CHILD (predeclared protocol step): the first
        # process to compile the bfs_single program pays the remote
        # compile + persistent-cache write INSIDE its timed window
        # (measured 28.2 s vs 0.96 s warm for the same root); one
        # throwaway child populates the cache so every TIMED root runs
        # warm. Its stats are recorded as diagnostics, never in the
        # statistic.
        est = 240.0  # first-child guess: cold compile + upload + drain
        if SEQ_ROOTS > 0 and remaining() > est:
            t0 = time.perf_counter()
            warm = run_child({"BENCH_SEQ_ROOT_IDX": "0"})
            est = time.perf_counter() - t0
            k1_info["seq_warmup_child"] = {
                "mteps": warm.get("mteps"),
                "warmup_s": warm.get("warmup_s"),
                "wall_s": round(est, 1),
                "obs_jsonl": warm.get("obs_jsonl"),
            }
            est = max(est * 0.7, 45.0)  # timed children run warm
        for i in range(min(SEQ_ROOTS, NROOTS)):
            if remaining() < est * 1.3 + 15:
                break
            t0 = time.perf_counter()
            seq_runs.append(
                run_child({
                    "BENCH_SEQ_ROOT_IDX": str(i),
                    "BENCH_SEQ_VALIDATE_THIS": "1" if i == 0 else "0",
                })
            )
            est = time.perf_counter() - t0
            official = emit(
                runs, seq_runs, construction_s, k1_info, t_start, variance
            )
        if os.environ.get("BENCH_OBS") == "1":
            # merge the children's per-process telemetry sidecars into one
            # trace (the multihost aggregation path, host-side) and
            # re-emit the official line referencing it
            from combblas_tpu import obs

            # every obs-wired child: batch runs, seq roots, the k1 device
            # child (k1_info IS its JSON line), and the untimed warmup
            sources = runs + seq_runs + [
                k1_info, k1_info.get("seq_warmup_child") or {},
            ]
            sidecars = [
                r["obs_jsonl"] for r in sources
                if r.get("obs_jsonl") and os.path.exists(r["obs_jsonl"])
            ]
            if sidecars:
                merged_path = os.environ.get(
                    "BENCH_OBS_OUT", "obs_trace.jsonl"
                )
                try:
                    agg = obs.merge_jsonl_files(sidecars, merged_path)
                    k1_info["obs"] = {
                        "merged_jsonl": merged_path,
                        "children": len(sidecars),
                        "counters": agg["counters"],
                    }
                except Exception as e:
                    k1_info["obs"] = {"error": str(e)}
                official = emit(
                    runs, seq_runs, construction_s, k1_info, t_start,
                    variance,
                )
        if not seq_runs:
            # never leave the artifact without the final (identical) line
            official = emit(
                runs, seq_runs, construction_s, k1_info, t_start, variance
            )
        # FINAL LINE CONTRACT (ISSUE 3 satellite): the compact headline
        # summary is the last thing on stdout, plus BENCH_SUMMARY.json.
        emit_summary(official)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _is_child_mode() -> bool:
    return any(
        os.environ.get(k)
        for k in ("BENCH_CHILD", "BENCH_K1_CHILD", "BENCH_SEQ_ROOT_IDX")
    )


if __name__ == "__main__":
    if _is_child_mode():
        main()  # children speak the one-JSON-line protocol, no summary
    else:
        try:
            main()
        except BaseException as e:  # noqa: BLE001 — headline must survive
            # the final-line contract holds even on a crash: a summary
            # with rc=1 and the error as the warning, then re-raise so
            # the exit code and stderr traceback are unchanged
            if not isinstance(e, SystemExit) or (e.code or 0) != 0:
                emit_summary(
                    {"value": 0.0, "warning": f"{type(e).__name__}: {e}"},
                    rc=1,
                )
            raise
