"""Graph500 BFS benchmark on the real TPU chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "MTEPS", "vs_baseline": N}

Protocol (adapted from the reference's TopDownBFS driver,
TopDownBFS.cpp:421-479): R-MAT scale-S graph (edgefactor 16, symmetrized,
deloop'd, dedup'd), BFS from NROOTS random reachable roots, AGGREGATE MTEPS
over the batch (sum of kernel-2 traversed edges / total wall time).
NOTE: the Graph500 spec and the archived baseline use harmonic-mean
per-root TEPS; per-root timing needs per-launch sync, which this device
does not provide trustworthily, so the aggregate — which amortizes launch
overhead across roots — is reported instead, with that caveat.

DESIGN (round 2, from the measured probe decomposition in
benchmarks/results/instrument_r2_raw*.txt):
  * per-launch dispatch through the axon tunnel costs ~105 ms regardless
    of resident argument bytes → the WHOLE batch is ONE launch;
  * the ELL SpMV kernel is gather-bound at ~130M indices/s, and a gather's
    cost is per-INDEX: fetching W=64 payload lanes costs only ~2x one lane
    (gatherw probes) → all NROOTS=64 BFS trees advance together as one
    [n, 64] frontier matrix (bfs_batch; SURVEY §2.3 strategy 7), so the
    per-index cost is split 64 ways;
  * kernel-2 TEPS accounting runs on device (batch_traversed_edges); the
    only D2H is one [W] vector + the sync scalar, AFTER timing;
  * the search loop carries int8 LEVEL indicators (1 byte/root per
    gathered index instead of 4) and reconstructs parents in one final
    sweep (bfs_batch_compact) — the gather is payload-width sensitive
    above ~256B/index, so the byte-wide frontier cuts dense-level cost
    further and halves HBM state.
Operating point (measured sweep, benchmarks/results/bench_sweep_r2*.txt):
scale 20 x 256 roots = 217.8 MTEPS; W=384+ exceeds the 16G HBM at scale 20,
W=512 at scale 19 also OOMs; scale 21 x 256 OOMs. Round-1 single-root
per-launch design measured 3.32 MTEPS — this is 65x.

AXON D2H NOTE: this chip's runtime permanently degrades launch performance
(~1000x) after ANY device->host readback, so the pipeline is strictly
phased: (1) host-numpy graph construction + ELL bucketing, (2) one upload,
(3) ONE timed launch closed by the te readback (the only reliable sync).

vs_baseline compares single-chip MTEPS against the smallest archived
reference run: 1,636 MTEPS on 1,024 Hopper (Cray XE6) cores
(BASELINE.md: HopperResults/script1024.reducedgraph_mini:149).
"""

from __future__ import annotations

import json
import os
import time

SCALE = int(os.environ.get("BENCH_SCALE", "20"))
EDGEFACTOR = int(os.environ.get("BENCH_EDGEFACTOR", "16"))
NROOTS = int(os.environ.get("BENCH_NROOTS", "256"))
DIROPT = os.environ.get("BENCH_DIROPT", "0") == "1"  # union-frontier sparse
# levels (budgets below); measured configuration notes in PERF_NOTES_r2.md
BASELINE_MTEPS = 1636.0  # Hopper 1024 cores, R-MAT "mini"


def main():
    import jax
    import numpy as np

    from combblas_tpu.models.bfs import batch_traversed_edges, bfs_batch_compact
    from combblas_tpu.parallel.ellmat import EllParMat
    from combblas_tpu.parallel.grid import Grid
    from combblas_tpu.parallel.vec import DistVec
    from combblas_tpu.utils.rmat import rmat_symmetric_coo_host

    grid = Grid.make(1, 1)
    n = 1 << SCALE

    # --- Phase 1: host-only construction ---------------------------------
    rows, cols = rmat_symmetric_coo_host(42, SCALE, EDGEFACTOR)
    key = rows * np.int64(n) + cols
    uniq = np.unique(key)
    rows_u = (uniq // n).astype(np.int64)
    cols_u = (uniq % n).astype(np.int64)
    deg = np.bincount(rows_u, minlength=n)
    nnz = len(rows_u)

    rng = np.random.default_rng(7)
    roots = rng.choice(np.flatnonzero(deg > 0), size=NROOTS, replace=False)

    # --- Phase 2: upload (H2D only) ---------------------------------------
    E = EllParMat.from_host_coo(
        grid, rows_u, cols_u, np.ones(nnz, np.float32), n, n
    )
    csc = None
    fcap = ecap = None
    if DIROPT:
        from combblas_tpu.parallel.ellmat import build_csc_companion

        csc = build_csc_companion(grid, rows_u, cols_u, n, n)
        fcap = grid.local_cols(n) // 8
        ecap = max(nnz // 16, 1 << 20)
    deg_blocks = DistVec.from_global(
        grid, deg.astype(np.int32), align="row"
    ).blocks
    roots_dev = jax.device_put(np.asarray(roots, np.int32))

    # --- Phase 3: ONE timed launch ----------------------------------------
    # Warmup compiles the whole batched program; block_until_ready is not a
    # reliable barrier through the tunnel, so sleep covers the drain and the
    # timed section is closed by the te readback (its ~5 ms inflates dt,
    # biasing reported TEPS DOWN).
    p, _, _ = bfs_batch_compact(
        E, roots_dev, csc=csc, frontier_capacity=fcap, edge_capacity=ecap
    )
    te_dev = batch_traversed_edges(deg_blocks, p)
    jax.block_until_ready(te_dev)
    time.sleep(5.0)

    t0 = time.perf_counter()
    parents, _, _ = bfs_batch_compact(
        E, roots_dev, csc=csc, frontier_capacity=fcap, edge_capacity=ecap
    )
    te_dev = batch_traversed_edges(deg_blocks, parents)
    te = np.asarray(jax.device_get(te_dev))  # true barrier
    dt_total = time.perf_counter() - t0

    # --- Phase 4: accounting ----------------------------------------------
    total_te = int(te.sum())
    mteps = total_te / dt_total / 1e6
    print(
        json.dumps(
            {
                "metric": f"graph500_bfs_rmat_scale{SCALE}_1chip_MTEPS",
                "value": round(mteps, 2),
                "unit": "MTEPS",
                "vs_baseline": round(mteps / BASELINE_MTEPS, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
