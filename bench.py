"""Graph500 BFS benchmark on the real TPU chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "MTEPS", "vs_baseline": N}

Protocol (mirrors the reference's TopDownBFS driver, TopDownBFS.cpp:421-479):
R-MAT scale-S graph (edgefactor 16, symmetrized, deloop'd), BFS from NROOTS
random reachable roots, harmonic-mean MTEPS over roots, where traversed
edges = edges incident to discovered vertices / 2 (kernel-2 accounting).

vs_baseline compares single-chip MTEPS against the smallest archived
reference run: 1,636 MTEPS on 1,024 Hopper (Cray XE6) cores
(BASELINE.md: HopperResults/script1024.reducedgraph_mini:149). One v5e chip
vs 1,024 CPU cores — values < 1 are expected until multi-chip rounds.
"""

from __future__ import annotations

import json
import os
import sys
import time

SCALE = int(os.environ.get("BENCH_SCALE", "19"))
EDGEFACTOR = int(os.environ.get("BENCH_EDGEFACTOR", "16"))
NROOTS = int(os.environ.get("BENCH_NROOTS", "8"))
BASELINE_MTEPS = 1636.0  # Hopper 1024 cores, R-MAT "mini"


def main():
    import jax
    import numpy as np

    from combblas_tpu import PLUS_TIMES
    from combblas_tpu.models.bfs import bfs, traversed_edges
    from combblas_tpu.parallel.grid import Grid
    from combblas_tpu.parallel.spmat import SpParMat
    from combblas_tpu.utils.rmat import rmat_symmetric_coo

    grid = Grid.make(1, 1)
    n = 1 << SCALE
    rows, cols = rmat_symmetric_coo(jax.random.key(42), scale=SCALE, edgefactor=EDGEFACTOR)
    A = SpParMat.from_global_coo(
        grid, rows, cols, np.ones(len(rows), np.float32), n, n,
        dedup_sr=PLUS_TIMES,
    )
    # roots: vertices with nonzero degree, deterministic choice
    deg = np.zeros(n, np.int64)
    np.add.at(deg, rows, 1)
    candidates = np.flatnonzero(deg > 0)
    rng = np.random.default_rng(7)
    roots = rng.choice(candidates, size=NROOTS, replace=False)

    # warmup/compile on first root
    p, l, it = bfs(A, int(roots[0]))
    jax.block_until_ready(p.blocks)

    teps = []
    for r in roots:
        t0 = time.perf_counter()
        parents, levels, niter = bfs(A, int(r))
        jax.block_until_ready(parents.blocks)
        dt = time.perf_counter() - t0
        te = int(traversed_edges(A, parents))
        if te > 0:
            teps.append(te / dt)
    hmean = len(teps) / sum(1.0 / t for t in teps)
    mteps = hmean / 1e6
    print(
        json.dumps(
            {
                "metric": f"graph500_bfs_rmat_scale{SCALE}_1chip_harmonic_MTEPS",
                "value": round(mteps, 2),
                "unit": "MTEPS",
                "vs_baseline": round(mteps / BASELINE_MTEPS, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
