#!/usr/bin/env python
"""Tier-1 runtime budget guard (round 20).

The tier-1 suite runs under a hard ``timeout -k 10 870`` (ROADMAP.md).
The suite's measured wall has crept to within ~20 s of that ceiling —
a PR that quietly adds a 30-second "fast" test turns the whole gate
red by TIMEOUT, which reads as flakiness instead of what it is: a
budget overrun.  This guard makes the overrun loud and attributable
BEFORE the timeout does it silently:

    python -m pytest tests/ -q -m 'not slow' --durations=50 \
        2>&1 | tee /tmp/t1.log
    python scripts/check_tier1_budget.py /tmp/t1.log --budget 860

It parses the pytest summary wall clock (``... in 843.21s``) and the
``--durations`` table, projects the tier-1 wall (optionally
subtracting tests listed in ``--slow-ids`` — e.g. when the log came
from a full run that included slow-marked tests), and exits non-zero
when the projection exceeds the budget, naming the top offenders so
the fix (gate the test ``slow``, or shrink it) is obvious.

Exit codes: 0 within budget, 1 over budget, 2 unparseable log.
"""

from __future__ import annotations

import argparse
import re
import sys

#: pytest summary wall clock: "12 passed, 3 deselected in 843.21s" /
#: "2 failed, 10 passed in 91.02s (0:01:31)".
_WALL_RE = re.compile(
    r"\b(?:passed|failed|error(?:s)?|skipped|deselected|no tests ran)"
    r"\b.* in (\d+(?:\.\d+)?)s"
)

#: one ``--durations`` table row: "12.34s call     tests/x.py::test_y"
_DURATION_RE = re.compile(
    r"^\s*(\d+(?:\.\d+)?)s\s+(call|setup|teardown)\s+(\S+)\s*$"
)


def parse_log(text: str):
    """-> (wall_seconds | None, [(seconds, phase, test_id), ...])"""
    wall = None
    rows = []
    for line in text.splitlines():
        m = _DURATION_RE.match(line)
        if m:
            rows.append((float(m.group(1)), m.group(2), m.group(3)))
            continue
        m = _WALL_RE.search(line)
        if m:
            wall = float(m.group(1))  # last summary line wins
    return wall, rows


def project(wall: float, rows, slow_ids=()):
    """Projected tier-1 wall: the measured wall minus every recorded
    duration (all phases) of tests in ``slow_ids``.  Durations not in
    the table (pytest hides the sub-5 ms tail) stay inside ``wall`` —
    the projection only ever errs HIGH, which is the safe direction
    for a ceiling check."""
    slow = set(slow_ids)
    shaved = sum(s for s, _ph, tid in rows if tid in slow)
    return wall - shaved, shaved


def offenders(rows, slow_ids=(), top: int = 10):
    """Biggest per-test call-phase costs among the tests that COUNT
    toward the budget, worst first."""
    slow = set(slow_ids)
    per_test: dict = {}
    for s, ph, tid in rows:
        if tid in slow or ph != "call":
            continue
        per_test[tid] = per_test.get(tid, 0.0) + s
    return sorted(per_test.items(), key=lambda kv: -kv[1])[:top]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Fail when the tier-1 suite's projected wall "
        "clock exceeds the runtime budget."
    )
    ap.add_argument("log", help="pytest output (tee'd log file)")
    ap.add_argument("--budget", type=float, default=860.0,
                    help="wall-clock ceiling in seconds "
                    "(default 860 — 10 s under the 870 s timeout)")
    ap.add_argument("--slow-ids", metavar="FILE",
                    help="file of test ids (one per line) to subtract "
                    "from the projection (tests being slow-gated, or "
                    "a log that included slow-marked tests)")
    ap.add_argument("--top", type=int, default=10,
                    help="offenders to name when over budget")
    args = ap.parse_args(argv)

    with open(args.log, errors="replace") as f:
        text = f.read()
    wall, rows = parse_log(text)
    if wall is None:
        print("check_tier1_budget: no pytest summary wall clock in "
              f"{args.log} (did the run finish?)", file=sys.stderr)
        return 2

    slow_ids = []
    if args.slow_ids:
        with open(args.slow_ids) as f:
            slow_ids = [
                ln.strip() for ln in f
                if ln.strip() and not ln.startswith("#")
            ]
    projected, shaved = project(wall, rows, slow_ids)
    verdict = "OK" if projected <= args.budget else "OVER BUDGET"
    print(f"tier-1 wall {wall:.1f}s"
          + (f" - {shaved:.1f}s slow-gated" if shaved else "")
          + f" = {projected:.1f}s projected vs {args.budget:.0f}s "
          f"budget: {verdict}")
    if projected <= args.budget:
        return 0
    print(f"over by {projected - args.budget:.1f}s; "
          "top in-budget tests by call time:", file=sys.stderr)
    worst = offenders(rows, slow_ids, top=args.top)
    if not worst:
        print("  (no --durations table in the log; re-run pytest "
              "with --durations=50 to attribute the overrun)",
              file=sys.stderr)
    for tid, s in worst:
        print(f"  {s:8.2f}s  {tid}", file=sys.stderr)
    print("gate the biggest new tests with @pytest.mark.slow or "
          "shrink them.", file=sys.stderr)
    return 1


if __name__ == "__main__":
    raise SystemExit(main())
