"""Parity pack: operations zoo, BlockSpGEMM, estimators, MD ordering,
sparse-output SpMSpV, pallas semiring matmul."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from combblas_tpu import MIN_PLUS, PLUS_TIMES, SELECT2ND_MIN
from combblas_tpu import operations as ops
from combblas_tpu.models.ordering import minimum_degree_ordering
from combblas_tpu.ops.pallas_kernels import min_plus_matmul, semiring_matmul
from combblas_tpu.parallel.grid import Grid
from combblas_tpu.parallel.spgemm import (
    block_spgemm,
    estimate_flops,
    estimate_nnz_upper,
    spgemm,
)
from combblas_tpu.parallel.spmat import SpParMat
from combblas_tpu.parallel.spmv import dist_spmspv
from combblas_tpu.parallel.vec import DistVec
from conftest import random_dense


def test_operations_zoo():
    a = jnp.asarray([1.0, 0.0, -2.0])
    b = jnp.asarray([0.5, 3.0, -1.0])
    np.testing.assert_allclose(ops.maximum(a, b), [1.0, 3.0, -1.0])
    np.testing.assert_allclose(ops.sel2nd(a, b), b)
    np.testing.assert_allclose(ops.safemultinv(a), [1.0, 0.0, -0.5])
    np.testing.assert_allclose(ops.exponentiate(2.0)(a), [1.0, 0.0, 4.0])
    assert ops.exponentiate(2.0) is ops.exponentiate(2.0)  # stable identity
    f = ops.set_if_not_equal(-1.0)
    np.testing.assert_allclose(
        f(jnp.asarray([-1.0, 5.0]), jnp.asarray([7.0, 9.0])), [7.0, 5.0]
    )
    assert bool(ops.totality(a).all())


def test_row_split_roundtrip(rng):
    grid = Grid.make(2, 2)
    d = random_dense(rng, 16, 12, 0.4)
    A = SpParMat.from_dense(grid, d)
    parts = A.row_split(4)
    assert all(p.nrows == 4 for p in parts)
    # reassemble densely: local row split means piece s holds local rows
    # [s*lw, (s+1)*lw) of every tile
    back = np.zeros_like(d)
    lw = 2  # lr=8 over 4 splits
    for s, p in enumerate(parts):
        pd = p.to_dense()  # [4, 12] with local-split row layout
        for i in range(2):  # grid rows
            back[i * 8 + s * lw : i * 8 + (s + 1) * lw] = pd[i * lw : (i + 1) * lw]
    np.testing.assert_allclose(back, d)


def test_block_spgemm_blocks_match_plain(rng):
    grid = Grid.make(2, 2)
    da = random_dense(rng, 16, 16, 0.3)
    db = random_dense(rng, 16, 16, 0.3)
    A = SpParMat.from_dense(grid, da)
    B = SpParMat.from_dense(grid, db)
    full = spgemm(PLUS_TIMES, A, B).to_dense()
    # Reassemble from 2x2 output blocks (local split semantics on both dims)
    got = np.zeros_like(full)
    for (i, j), C in block_spgemm(PLUS_TIMES, A, B, row_blocks=2, col_blocks=2):
        cd = C.to_dense()  # [8, 8]
        for gi in range(2):
            for gj in range(2):
                got[
                    gi * 8 + i * 4 : gi * 8 + (i + 1) * 4,
                    gj * 8 + j * 4 : gj * 8 + (j + 1) * 4,
                ] = cd[gi * 4 : (gi + 1) * 4, gj * 4 : (gj + 1) * 4]
    np.testing.assert_allclose(got, full, rtol=1e-5, atol=1e-6)


def test_estimators(rng):
    grid = Grid.make(2, 2)
    da = random_dense(rng, 12, 12, 0.3)
    db = random_dense(rng, 12, 12, 0.3)
    A = SpParMat.from_dense(grid, da)
    B = SpParMat.from_dense(grid, db)
    flops = estimate_flops(A, B)
    expect = sum(
        int((db[k] != 0).sum()) for _, k in zip(*np.nonzero(da))
    )
    assert flops == expect
    nnz_true = int(((da @ db) != 0).sum())
    assert estimate_nnz_upper(A, B) >= nnz_true


def test_dist_spmspv_sparse_output(rng):
    grid = Grid.make(2, 2)
    d = random_dense(rng, 16, 16, 0.3)
    A = SpParMat.from_dense(grid, d)
    xfull = rng.random(16).astype(np.float32)
    act = np.zeros(16, bool)
    act[[2, 7, 11]] = True
    x = DistVec.from_global(grid, np.where(act, xfull, 0), align="col")
    xa = DistVec.from_global(grid, act, align="col", fill=False)
    y, ya, nnz = dist_spmspv(PLUS_TIMES, A, x, xa)
    expect = d @ np.where(act, xfull, 0)
    np.testing.assert_allclose(y.to_global(), expect, rtol=1e-5, atol=1e-6)
    reach = (d[:, act] != 0).any(axis=1)
    np.testing.assert_array_equal(ya.to_global(), reach)
    assert int(nnz) == int(reach.sum())


@pytest.mark.slow  # round 12 (tier-1 budget): MD is the sequential
# HOST prototype (STATUS: wontfix as a device kernel) — a 10 s
# permutation check of it need not run every tier-1
def test_minimum_degree_ordering_is_permutation(rng):
    grid = Grid.make(2, 2)
    d = random_dense(rng, 12, 12, 0.25)
    d = np.maximum(d, d.T)
    np.fill_diagonal(d, 0)
    A = SpParMat.from_dense(grid, d)
    p = minimum_degree_ordering(A).to_global()[:12]
    np.testing.assert_array_equal(np.sort(p), np.arange(12))


def test_md_prefers_low_degree_first():
    grid = Grid.make(2, 2)
    # star: center 0 has degree 5, leaves degree 1 — leaves eliminate first
    n = 8
    d = np.zeros((n, n), np.float32)
    d[0, 1:6] = d[1:6, 0] = 1
    A = SpParMat.from_dense(grid, d)
    p = minimum_degree_ordering(A).to_global()[:n]
    assert list(p).index(0) >= 4  # center goes after most leaves


@pytest.mark.parametrize("kind", ["plus_times", "min_plus", "max_min"])
def test_pallas_semiring_matmul(rng, kind):
    m = k = n = 256
    a = rng.random((m, k)).astype(np.float32)
    b = rng.random((k, n)).astype(np.float32)
    got = np.asarray(semiring_matmul(kind, jnp.asarray(a), jnp.asarray(b),
                                     interpret=True))
    if kind == "plus_times":
        expect = a @ b
        np.testing.assert_allclose(got, expect, rtol=1e-4, atol=1e-4)
    elif kind == "min_plus":
        expect = np.min(a[:, :, None] + b[None, :, :], axis=1)
        np.testing.assert_allclose(got, expect, rtol=1e-6)
    else:
        expect = np.max(np.minimum(a[:, :, None], b[None, :, :]), axis=1)
        np.testing.assert_allclose(got, expect, rtol=1e-6)


def test_pallas_min_plus_repeated_squaring(rng):
    """Dense APSP by repeated tropical squaring — the kernel's use case."""
    n = 128
    d = np.full((n, n), np.inf, np.float32)
    np.fill_diagonal(d, 0)
    rng2 = np.random.default_rng(1)
    for _ in range(300):
        i, j = rng2.integers(0, n, 2)
        if i != j:
            w = float(rng2.random() + 0.1)
            d[i, j] = min(d[i, j], w)
            d[j, i] = min(d[j, i], w)
    big = np.float32(1e6)
    dist = np.where(np.isinf(d), big, d)
    expect = dist.copy()
    for _ in range(8):
        expect = np.minimum(expect, np.min(expect[:, :, None] + expect[None, :, :], axis=1))
    got = jnp.asarray(dist)
    for _ in range(8):
        got = jnp.minimum(got, min_plus_matmul(got, got, interpret=True))
    np.testing.assert_allclose(np.asarray(got), expect, rtol=1e-4, atol=1e-3)


def test_kselect2_parity(rng):
    """Kselect2 = kselect thresholds + any-column-active flag
    (SpParMat.h:137)."""
    grid = Grid.make(2, 2)
    n = 24
    d = (rng.random((n, n)) < 0.3).astype(np.float32) * (
        1 + rng.random((n, n)).astype(np.float32)
    )
    A = SpParMat.from_dense(grid, d)
    th, active = A.kselect2(3)
    assert bool(active) == bool(((d != 0).sum(axis=0) >= 3).any())
    th2 = A.kselect(3)
    np.testing.assert_array_equal(
        np.asarray(th.blocks), np.asarray(th2.blocks)
    )
    _, none_active = A.kselect2(n + 1)
    assert not bool(none_active)


def test_kselect_small_int_dtypes(rng):
    """Sub-32-bit integer values widen to 32-bit keys (kselect supported
    int8/16 via astype fallthrough before the round-2 assert; regression
    coverage for the widening path)."""
    grid = Grid.make(2, 2)
    n = 32
    for dt in (np.int8, np.int16, np.uint8):
        d = ((rng.random((n, n)) < 0.4) * rng.integers(1, 100, (n, n))).astype(dt)
        if np.issubdtype(dt, np.signedinteger):
            d = (d * np.where(rng.random((n, n)) < 0.5, -1, 1)).astype(dt)
        A = SpParMat.from_dense(grid, d)
        th = np.asarray(A.kselect(3).realign("col").blocks).reshape(-1)[:n]
        assert th.dtype == dt
        lo = np.iinfo(dt).min if np.issubdtype(dt, np.signedinteger) else 0
        ref = np.full(n, lo, np.int64)
        for j in range(n):
            nz = np.sort(d[:, j][d[:, j] != 0].astype(np.int64))[::-1]
            if len(nz) >= 3:
                ref[j] = nz[2]
        np.testing.assert_array_equal(th.astype(np.int64), ref)


def test_block_split(rng):
    """BlockSplit (SpParMat.cpp:2974): 2D submatrix grid, reassembled."""
    grid = Grid.make(2, 2)
    n = 32
    d = (rng.random((n, n)) < 0.2).astype(np.float32)
    A = SpParMat.from_dense(grid, d)
    blocks = A.block_split(2, 2)
    assert len(blocks) == 2 and len(blocks[0]) == 2
    # row_split is local-strided; verify via nnz conservation + col stitch
    total = sum(
        int(np.asarray(b.getnnz())) for row in blocks for b in row
    )
    assert total == int((d != 0).sum())
    stitched = SpParMat.col_concatenate(blocks[0])
    assert stitched.ncols == n


def test_induced_subgraphs(rng):
    """InducedSubgraphs2Procs (SpParMat.cpp:4916): component groups ->
    induced subgraphs via SpRef."""
    from combblas_tpu.models.cc import connected_components

    grid = Grid.make(2, 2)
    n = 24
    d = np.zeros((n, n), np.float32)
    d[:6, :6] = 1.0  # clique A
    d[8:12, 8:12] = 1.0  # clique B
    d[16:18, 16:18] = 1.0  # tiny pair
    np.fill_diagonal(d, 0)
    A = SpParMat.from_dense(grid, d)
    labels, _ = connected_components(A)
    groups = A.induced_subgraphs(labels, ngroups=2)
    assert len(groups) == 2
    total_verts = sum(len(vi) for vi, _ in groups)
    assert total_verts == n
    total_nnz = sum(int(np.asarray(sub.getnnz())) for _, sub in groups)
    assert total_nnz == int((d != 0).sum())  # components never split
    for vi, sub in groups:
        np.testing.assert_allclose(
            sub.to_dense()[: len(vi), : len(vi)], d[np.ix_(vi, vi)]
        )


def test_cross_grid_concatenate(rng):
    """Concatenate (ParFriends.h:61-159): vectors from different grids."""
    from combblas_tpu.parallel.vec import concatenate

    g1 = Grid.make(2, 2)
    g2 = Grid.make(2, 4)
    x1 = rng.random(10).astype(np.float32)
    x2 = rng.random(17).astype(np.float32)
    v1 = DistVec.from_global(g1, x1, align="row")
    v2 = DistVec.from_global(g2, x2, align="row")
    out = concatenate([v1, v2], grid=g2)
    assert out.length == 27
    np.testing.assert_allclose(out.to_global(), np.concatenate([x1, x2]))


def test_multihost_single_process():
    """init_distributed is a no-op single-process and reports devices."""
    from combblas_tpu.parallel.multihost import init_distributed, make_global_grid

    nd = init_distributed()
    assert nd >= 1
    g = make_global_grid()
    assert g.size <= nd
