"""RCM ordering + bipartite matchings vs trusted slow paths."""

import numpy as np
import pytest

from combblas_tpu.models.matching import (
    awpm,
    is_maximal,
    is_valid_matching,
    matching_weight,
    maximal_matching,
    maximum_matching,
)
from combblas_tpu.models.ordering import bandwidth, rcm_ordering
from combblas_tpu.parallel.grid import Grid
from combblas_tpu.parallel.indexing import subsref
from combblas_tpu.parallel.spmat import SpParMat
from conftest import random_dense


def hopcroft_karp_size(adj) -> int:
    """Trusted slow path: maximum bipartite matching size (augmenting DFS)."""
    nr, nc = adj.shape
    mc = [-1] * nc

    def try_row(i, seen):
        for j in np.nonzero(adj[i])[0]:
            if seen[j]:
                continue
            seen[j] = True
            if mc[j] < 0 or try_row(mc[j], seen):
                mc[j] = i
                return True
        return False

    size = 0
    for i in range(nr):
        if try_row(i, [False] * nc):
            size += 1
    return size


def _band_matrix(n, halfband, rng):
    d = np.zeros((n, n), np.float32)
    for i in range(n):
        for j in range(max(0, i - halfband), min(n, i + halfband + 1)):
            if i != j and rng.random() < 0.8:
                d[i, j] = d[j, i] = 1
    return d


def test_rcm_is_permutation(rng):
    grid = Grid.make(2, 2)
    d = _band_matrix(16, 2, rng)
    A = SpParMat.from_dense(grid, d)
    p = rcm_ordering(A).to_global()
    np.testing.assert_array_equal(np.sort(p[:16]), np.arange(16))


def test_rcm_path_graph_bandwidth_one():
    """RCM of a shuffled path graph must recover bandwidth 1."""
    grid = Grid.make(2, 2)
    n = 16
    rng = np.random.default_rng(5)
    sigma = rng.permutation(n)
    d = np.zeros((n, n), np.float32)
    for i in range(n - 1):
        d[sigma[i], sigma[i + 1]] = d[sigma[i + 1], sigma[i]] = 1
    A = SpParMat.from_dense(grid, d)
    p = rcm_ordering(A).to_global()[:n]
    reordered = subsref(A, p, p).to_dense()
    assert bandwidth(reordered) == 1


def test_rcm_reduces_bandwidth(rng):
    grid = Grid.make(2, 2)
    n = 24
    band = _band_matrix(n, 3, rng)
    sigma = rng.permutation(n)
    shuffled = band[np.ix_(sigma, sigma)]
    A = SpParMat.from_dense(grid, shuffled)
    p = rcm_ordering(A).to_global()[:n]
    reordered = subsref(A, p, p).to_dense()
    assert bandwidth(reordered) <= bandwidth(shuffled)
    assert bandwidth(reordered) <= 2 * bandwidth(band) + 2


@pytest.mark.parametrize("ks", [False, True])
def test_maximal_matching(rng, ks):
    grid = Grid.make(2, 2)
    d = (rng.random((14, 10)) < 0.25).astype(np.float32)
    A = SpParMat.from_dense(grid, d)
    mr, mc = maximal_matching(A, karp_sipser=ks)
    mr, mc = mr.to_global(), mc.to_global()
    assert is_valid_matching(d, mr, mc)
    assert is_maximal(d, mr, mc)


def test_maximum_matching_size(rng):
    grid = Grid.make(2, 2)
    d = (rng.random((12, 12)) < 0.2).astype(np.float32)
    A = SpParMat.from_dense(grid, d)
    mr, mc = maximum_matching(A)
    mr, mc = mr.to_global(), mc.to_global()
    assert is_valid_matching(d, mr, mc)
    assert int((mr >= 0).sum()) == hopcroft_karp_size(d)


def test_maximum_matching_perfect_on_cycle():
    grid = Grid.make(2, 2)
    n = 8  # even cycle as bipartite rows->cols: perfect matching exists
    d = np.zeros((n, n), np.float32)
    for i in range(n):
        d[i, i] = 1
        d[i, (i + 1) % n] = 1
    A = SpParMat.from_dense(grid, d)
    mr, mc = maximum_matching(A)
    assert int((mr.to_global() >= 0).sum()) == n


def test_awpm_weight_reasonable(rng):
    grid = Grid.make(2, 2)
    d = (rng.random((10, 10)) * (rng.random((10, 10)) < 0.5)).astype(np.float32)
    # ensure a perfect matching exists (diagonal)
    np.fill_diagonal(d, np.maximum(d.diagonal(), 0.05))
    A = SpParMat.from_dense(grid, d)
    mr, mc = awpm(A)
    mr, mc = mr.to_global(), mc.to_global()
    assert is_valid_matching(d, mr, mc)
    assert int((mr >= 0).sum()) == hopcroft_karp_size(d != 0)
    # weight sanity: at least the greedy row-max lower bound / 2
    assert matching_weight(d, mr) > 0


def test_maximum_matching_device_matches_host(rng):
    """Device augmentation (VERDICT r3 item 6) must reach the same
    cardinality as the host-augmentation oracle."""
    from conftest import random_dense

    grid = Grid.make(2, 2)
    for seed in range(3):
        r2 = np.random.default_rng(seed)
        d = (random_dense(r2, 24, 20, 0.15) != 0).astype(np.float32)
        A = SpParMat.from_dense(grid, d)
        mr_d, mc_d = maximum_matching(A, device=True)
        mr_h, mc_h = maximum_matching(A, device=False)
        card_d = int((np.asarray(mr_d.to_global()) >= 0).sum())
        card_h = int((np.asarray(mr_h.to_global()) >= 0).sum())
        assert card_d == card_h
        assert is_valid_matching(
            d, mr_d.to_global(), mc_d.to_global()
        )
