"""Local ESC SpGEMM and distributed SUMMA vs dense numpy products.

Mirrors the reference's MultTest golden-product pattern
(ReleaseTests/MultTest.cpp:122-234) with generated inputs.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from combblas_tpu import MIN_PLUS, OR_AND, PLUS_TIMES, SpTuples
from combblas_tpu.ops.compressed import CSR
from combblas_tpu.ops.spgemm import expand, flops, local_spgemm
from combblas_tpu.parallel.grid import Grid
from combblas_tpu.parallel.spgemm import spgemm, summa_capacities, summa_spgemm
from combblas_tpu.parallel.spmat import SpParMat
from conftest import random_dense


def test_local_flops(rng):
    da = random_dense(rng, 9, 7, 0.4)
    db = random_dense(rng, 7, 11, 0.4)
    a = SpTuples.from_dense(da, capacity=64)
    b = CSR.from_tuples(SpTuples.from_dense(db, capacity=64))
    expect = sum(
        int((db[k] != 0).sum()) for i, k in zip(*np.nonzero(da))
    )
    assert int(flops(a, b)) == expect


def test_local_spgemm_plus_times(rng):
    da = random_dense(rng, 13, 9, 0.35)
    db = random_dense(rng, 9, 10, 0.35)
    a = SpTuples.from_dense(da, capacity=128)
    b = CSR.from_tuples(SpTuples.from_dense(db, capacity=128))
    from combblas_tpu.ops.spgemm import flops_padded

    fl = int(flops(a, b))
    flp = int(flops_padded(a, b))
    c = local_spgemm(PLUS_TIMES, a, b, flop_capacity=max(flp, 1), out_capacity=max(fl, 1))
    np.testing.assert_allclose(np.asarray(c.to_dense()), da @ db, rtol=1e-5, atol=1e-6)


def test_local_spgemm_min_plus(rng):
    da = random_dense(rng, 6, 6, 0.5)
    db = random_dense(rng, 6, 6, 0.5)
    a = SpTuples.from_dense(da, capacity=36)
    b = CSR.from_tuples(SpTuples.from_dense(db, capacity=36))
    from combblas_tpu.ops.spgemm import flops_padded

    c = local_spgemm(
        MIN_PLUS, a, b,
        flop_capacity=int(flops_padded(a, b)), out_capacity=64,
    )
    expect = np.full((6, 6), np.inf, np.float32)
    for i in range(6):
        for j in range(6):
            for k in range(6):
                if da[i, k] and db[k, j]:
                    expect[i, j] = min(expect[i, j], da[i, k] + db[k, j])
    got = np.asarray(c.to_dense(MIN_PLUS))
    mask = ~np.isinf(expect)
    np.testing.assert_allclose(got[mask], expect[mask], rtol=1e-6)


@pytest.mark.parametrize("p", [1, 2])
@pytest.mark.parametrize("ring", [False, True])
def test_summa_vs_dense(p, ring, rng):
    grid = Grid.make(p, p)
    da = random_dense(rng, 21, 17, 0.25)
    db = random_dense(rng, 17, 19, 0.25)
    A = SpParMat.from_dense(grid, da)
    B = SpParMat.from_dense(grid, db)
    flop_cap, out_cap = summa_capacities(A, B)
    C = summa_spgemm(
        PLUS_TIMES, A, B,
        flop_capacity=flop_cap, out_capacity=out_cap, ring=ring,
    )
    np.testing.assert_allclose(C.to_dense(), da @ db, rtol=1e-5, atol=1e-6)


def test_summa_boolean_reachability(rng):
    grid = Grid.make(2, 2)
    da = (random_dense(rng, 16, 16, 0.15) != 0)
    A = SpParMat.from_dense(grid, da.astype(np.float32))
    A2 = spgemm(OR_AND, A.apply(lambda v: v != 0), A.apply(lambda v: v != 0))
    expect = (da.astype(np.int32) @ da.astype(np.int32)) > 0
    np.testing.assert_array_equal(A2.to_dense().astype(bool), expect)


def test_summa_square_rmat(rng):
    from combblas_tpu.utils.rmat import rmat_symmetric_coo

    rows, cols = rmat_symmetric_coo(jax.random.key(11), scale=6, edgefactor=6)
    n = 64
    grid = Grid.make(2, 2)
    A = SpParMat.from_global_coo(
        grid, rows, cols, np.ones(len(rows), np.float32), n, n,
        dedup_sr=PLUS_TIMES,
    )
    d = A.to_dense()
    C = spgemm(PLUS_TIMES, A, A)
    np.testing.assert_allclose(C.to_dense(), d @ d, rtol=1e-4, atol=1e-5)
    # jitted with static capacities
    flop_cap, out_cap = summa_capacities(A, A)
    f = jax.jit(
        lambda A, B: summa_spgemm(
            PLUS_TIMES, A, B, flop_capacity=flop_cap, out_capacity=out_cap
        )
    )
    np.testing.assert_allclose(f(A, A).to_dense(), d @ d, rtol=1e-4, atol=1e-5)


def test_summa_rect_matrices_nonuniform(rng):
    # shapes that don't divide the grid evenly
    grid = Grid.make(2, 2)
    da = random_dense(rng, 23, 15, 0.3)
    db = random_dense(rng, 15, 27, 0.3)
    A = SpParMat.from_dense(grid, da)
    B = SpParMat.from_dense(grid, db)
    C = spgemm(PLUS_TIMES, A, B)
    np.testing.assert_allclose(C.to_dense(), da @ db, rtol=1e-5, atol=1e-6)


def test_summa_stage_flops_host_matches_device(rng):
    """The host symbolic twin must track the device pass exactly — axon
    benchmarks size capacities from it with no device cross-check."""
    from combblas_tpu.parallel.spgemm import (
        summa_capacities,
        summa_capacities_host,
        summa_stage_flops,
        summa_stage_flops_host,
    )

    grid = Grid.make(2, 2)
    n = 37  # non-divisible dims exercise the padded owner math
    d = (rng.random((n, n)) < 0.2).astype(np.float32)
    r, c = np.nonzero(d)
    A = SpParMat.from_global_coo(grid, r, c, d[r, c], n, n)
    dev = np.asarray(summa_stage_flops(A, A), np.float64)
    host = summa_stage_flops_host(grid, r, c, r, c, n, n, n)
    np.testing.assert_array_equal(dev, host)
    assert summa_capacities_host(grid, r, c, r, c, n, n, n) == summa_capacities(A, A)


def test_spgemm_scan_matches_summa(rng):
    """Output-bounded scanned SUMMA == the unphased product."""
    from combblas_tpu.parallel.spgemm import spgemm_scan

    grid = Grid.make(2, 2)
    n = 40
    d = (rng.random((n, n)) < 0.15).astype(np.float32)
    A = SpParMat.from_dense(grid, d)
    C1 = spgemm(PLUS_TIMES, A, A)
    C2 = spgemm_scan(PLUS_TIMES, A, A)
    np.testing.assert_allclose(C2.to_dense(), d @ d, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(C2.to_dense(), C1.to_dense(), rtol=1e-6)


def test_spgemm_scan_ring_matches(rng):
    from combblas_tpu.parallel.spgemm import spgemm_scan

    grid = Grid.make(2, 2)
    n = 32
    d = (rng.random((n, n)) < 0.2).astype(np.float32)
    A = SpParMat.from_dense(grid, d)
    C = spgemm_scan(PLUS_TIMES, A, A, ring=True)
    np.testing.assert_allclose(C.to_dense(), d @ d, rtol=1e-5, atol=1e-6)


def test_spgemm_scan_overflow_retry(rng):
    """A deliberately tiny initial out_capacity must be corrected by the
    exact distinct-key count (the estimateNNZ_Hash role) via retry."""
    from combblas_tpu.parallel.spgemm import spgemm_scan, summa_spgemm_scan, summa_capacities

    grid = Grid.make(2, 2)
    n = 32
    d = (rng.random((n, n)) < 0.3).astype(np.float32)
    A = SpParMat.from_dense(grid, d)
    # direct call underreports capacity -> overflow flagged, result truncated
    fcap, _ = summa_capacities(A, A)
    C, overflow = summa_spgemm_scan(
        PLUS_TIMES, A, A, flop_capacity=fcap, out_capacity=4
    )
    assert int(overflow) > 0
    # driver retries to exactness
    C2 = spgemm_scan(PLUS_TIMES, A, A, out_capacity=4)
    np.testing.assert_allclose(C2.to_dense(), d @ d, rtol=1e-5, atol=1e-6)


def test_spgemm_scan_memory_bounded(rng):
    """The scanned variant's compiled peak memory must undercut the
    all-stages-live variant when flops >> nnz_out (the MCL A-squared
    regime) — the round-1 'ESC peak memory scales with flops' weakness."""
    import jax

    from combblas_tpu.parallel.spgemm import summa_spgemm, summa_spgemm_scan

    grid = Grid.make(2, 2)
    n = 64
    # dense-ish columns -> high collision: flops ~ nnz^2/n >> nnz_out <= n^2
    d = (rng.random((n, n)) < 0.5).astype(np.float32)
    A = SpParMat.from_dense(grid, d)
    fcap, ocap = 1 << 17, 1 << 10  # flops-shaped vs output-shaped
    lowered_old = jax.jit(
        lambda a: summa_spgemm(
            PLUS_TIMES, a, a, flop_capacity=fcap, out_capacity=ocap
        )
    ).lower(A)
    lowered_new = jax.jit(
        lambda a: summa_spgemm_scan(
            PLUS_TIMES, a, a, flop_capacity=fcap, out_capacity=ocap
        )
    ).lower(A)
    mem_old = lowered_old.compile().memory_analysis()
    mem_new = lowered_new.compile().memory_analysis()
    assert mem_new.temp_size_in_bytes < mem_old.temp_size_in_bytes, (
        mem_new.temp_size_in_bytes, mem_old.temp_size_in_bytes,
    )


@pytest.mark.parametrize("srname", [
    "plus_times", "min_plus",
    # max_min rides the slow lane (tier-1 870 s budget, round 12): the
    # same dense-kernel path as min_plus, which stays as the tropical
    # tier-1 representative
    pytest.param("max_min", marks=pytest.mark.slow),
])
def test_spgemm_mxu_matches_dense(rng, srname):
    """Dense-block MXU SUMMA == reference product for every dense-kernel
    semiring (Pallas kernel in interpret mode on CPU)."""
    from combblas_tpu import MAX_MIN
    from combblas_tpu.parallel.spgemm import spgemm_auto

    sr = {"plus_times": PLUS_TIMES, "min_plus": MIN_PLUS,
          "max_min": MAX_MIN}[srname]
    grid = Grid.make(2, 2)
    n = 48
    d = (rng.random((n, n)) < 0.2).astype(np.float32) * (
        1 + rng.random((n, n)).astype(np.float32)
    )
    A = SpParMat.from_dense(grid, d)
    C = spgemm_auto(sr, A, A, interpret=True)
    got = C.to_dense()
    if srname == "plus_times":
        np.testing.assert_allclose(got, d @ d, rtol=1e-5, atol=1e-6)
    else:
        # the ESC kernel is the independently-tested reference for the
        # tropical semirings
        want = spgemm(sr, A, A).to_dense()
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_spgemm_mxu_overflow_retry(rng):
    from combblas_tpu.parallel.spgemm import spgemm_auto

    grid = Grid.make(2, 2)
    n = 32
    d = (rng.random((n, n)) < 0.3).astype(np.float32)
    A = SpParMat.from_dense(grid, d)
    C = spgemm_auto(PLUS_TIMES, A, A, out_capacity=4, interpret=True)
    np.testing.assert_allclose(C.to_dense(), d @ d, rtol=1e-5, atol=1e-6)


def test_densify_sparsify_roundtrip(rng):
    from combblas_tpu import SpTuples
    from combblas_tpu.ops.spgemm import densify, sparsify

    d = (rng.random((20, 36)) < 0.25).astype(np.float32)
    t = SpTuples.from_dense(d, capacity=512)
    dense = densify(t, 128, 128, 0.0)
    np.testing.assert_allclose(np.asarray(dense)[:20, :36], d)
    back, total = sparsify(dense, 0.0, 20, 36, 512)
    assert int(total) == int((d != 0).sum())
    got = np.zeros_like(d)
    r, c, v = np.asarray(back.rows), np.asarray(back.cols), np.asarray(back.vals)
    m = r < 20
    got[r[m], c[m]] = v[m]
    np.testing.assert_allclose(got, d)


@pytest.mark.parametrize("mode", ["bf16", "bf16x3"])
def test_spgemm_mxu_precision_modes(rng, mode):
    """bf16 is EXACT on 0/1 inputs (counts < 2^24); bf16x3 split-float is
    f32-grade on general values (round-4 _mxu_dot modes)."""
    from combblas_tpu.parallel.spgemm import spgemm_auto

    grid = Grid.make(2, 2)
    n = 48
    if mode == "bf16":
        d = (rng.random((n, n)) < 0.2).astype(np.float32)
    else:
        d = random_dense(rng, n, n, 0.2)
    A = SpParMat.from_dense(grid, d)
    C = spgemm_auto(PLUS_TIMES, A, A, mode=mode, interpret=True)
    got = np.asarray(C.to_dense())
    want = d @ d
    if mode == "bf16":
        np.testing.assert_array_equal(got, want)  # exact
    else:
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=1e-5)


@pytest.mark.parametrize("density", [0.0, 0.05, 0.5, 1.0])
@pytest.mark.parametrize("truncate", [False, True])
@pytest.mark.parametrize("pad", [False, True])
@pytest.mark.parametrize("zero", [0.0, float("inf")])
def test_sparsify_windowed_direct(rng, density, truncate, pad, zero):
    """Direct unit coverage of the production extraction kernel
    (ADVICE r4: it replaced `sparsify` on the MXU SpGEMM / dense-MCL
    paths with only indirect test coverage): density x truncation x
    padded dims x non-zero semiring zero, checked against np.nonzero."""
    from combblas_tpu.ops.spgemm import sparsify_windowed

    R, C = 32, 128  # ncell 4096 = 32 chunks
    nrows, ncols = (27, 99) if pad else (R, C)
    x = np.full((R, C), zero, np.float32)
    m = rng.random((R, C)) < density
    m[nrows:, :] = False
    m[:, ncols:] = False
    x[m] = rng.integers(1, 50, (R, C)).astype(np.float32)[m]
    n_ref = int(m.sum())
    cap = max(n_ref // 2, 8) if truncate else n_ref + 32
    t, total = sparsify_windowed(jnp.asarray(x), zero, nrows, ncols, cap)
    assert int(total) == n_ref  # exact pre-truncation count
    r = np.asarray(t.rows)
    c = np.asarray(t.cols)
    v = np.asarray(t.vals)
    live = (r < nrows) & (np.arange(len(r)) < int(t.nnz))
    assert int(t.nnz) == min(n_ref, cap)
    # every surfaced entry is a real nonzero with the right value
    assert np.all(x[r[live], c[live]] != zero)
    np.testing.assert_array_equal(v[live], x[r[live], c[live]])
    # row-major sorted prefix of the true nonzero set
    flat_got = r[live].astype(np.int64) * C + c[live]
    rr, cc = np.nonzero(m)
    flat_ref = np.sort(rr.astype(np.int64) * C + cc)
    np.testing.assert_array_equal(flat_got, flat_ref[: len(flat_got)])
