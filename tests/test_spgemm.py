"""Local ESC SpGEMM and distributed SUMMA vs dense numpy products.

Mirrors the reference's MultTest golden-product pattern
(ReleaseTests/MultTest.cpp:122-234) with generated inputs.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from combblas_tpu import MIN_PLUS, OR_AND, PLUS_TIMES, SpTuples
from combblas_tpu.ops.compressed import CSR
from combblas_tpu.ops.spgemm import expand, flops, local_spgemm
from combblas_tpu.parallel.grid import Grid
from combblas_tpu.parallel.spgemm import spgemm, summa_capacities, summa_spgemm
from combblas_tpu.parallel.spmat import SpParMat
from conftest import random_dense


def test_local_flops(rng):
    da = random_dense(rng, 9, 7, 0.4)
    db = random_dense(rng, 7, 11, 0.4)
    a = SpTuples.from_dense(da, capacity=64)
    b = CSR.from_tuples(SpTuples.from_dense(db, capacity=64))
    expect = sum(
        int((db[k] != 0).sum()) for i, k in zip(*np.nonzero(da))
    )
    assert int(flops(a, b)) == expect


def test_local_spgemm_plus_times(rng):
    da = random_dense(rng, 13, 9, 0.35)
    db = random_dense(rng, 9, 10, 0.35)
    a = SpTuples.from_dense(da, capacity=128)
    b = CSR.from_tuples(SpTuples.from_dense(db, capacity=128))
    fl = int(flops(a, b))
    c = local_spgemm(PLUS_TIMES, a, b, flop_capacity=max(fl, 1), out_capacity=max(fl, 1))
    np.testing.assert_allclose(np.asarray(c.to_dense()), da @ db, rtol=1e-5, atol=1e-6)


def test_local_spgemm_min_plus(rng):
    da = random_dense(rng, 6, 6, 0.5)
    db = random_dense(rng, 6, 6, 0.5)
    a = SpTuples.from_dense(da, capacity=36)
    b = CSR.from_tuples(SpTuples.from_dense(db, capacity=36))
    c = local_spgemm(MIN_PLUS, a, b, flop_capacity=64, out_capacity=64)
    expect = np.full((6, 6), np.inf, np.float32)
    for i in range(6):
        for j in range(6):
            for k in range(6):
                if da[i, k] and db[k, j]:
                    expect[i, j] = min(expect[i, j], da[i, k] + db[k, j])
    got = np.asarray(c.to_dense(MIN_PLUS))
    mask = ~np.isinf(expect)
    np.testing.assert_allclose(got[mask], expect[mask], rtol=1e-6)


@pytest.mark.parametrize("p", [1, 2])
@pytest.mark.parametrize("ring", [False, True])
def test_summa_vs_dense(p, ring, rng):
    grid = Grid.make(p, p)
    da = random_dense(rng, 21, 17, 0.25)
    db = random_dense(rng, 17, 19, 0.25)
    A = SpParMat.from_dense(grid, da)
    B = SpParMat.from_dense(grid, db)
    flop_cap, out_cap = summa_capacities(A, B)
    C = summa_spgemm(
        PLUS_TIMES, A, B,
        flop_capacity=flop_cap, out_capacity=out_cap, ring=ring,
    )
    np.testing.assert_allclose(C.to_dense(), da @ db, rtol=1e-5, atol=1e-6)


def test_summa_boolean_reachability(rng):
    grid = Grid.make(2, 2)
    da = (random_dense(rng, 16, 16, 0.15) != 0)
    A = SpParMat.from_dense(grid, da.astype(np.float32))
    A2 = spgemm(OR_AND, A.apply(lambda v: v != 0), A.apply(lambda v: v != 0))
    expect = (da.astype(np.int32) @ da.astype(np.int32)) > 0
    np.testing.assert_array_equal(A2.to_dense().astype(bool), expect)


def test_summa_square_rmat(rng):
    from combblas_tpu.utils.rmat import rmat_symmetric_coo

    rows, cols = rmat_symmetric_coo(jax.random.key(11), scale=6, edgefactor=6)
    n = 64
    grid = Grid.make(2, 2)
    A = SpParMat.from_global_coo(
        grid, rows, cols, np.ones(len(rows), np.float32), n, n,
        dedup_sr=PLUS_TIMES,
    )
    d = A.to_dense()
    C = spgemm(PLUS_TIMES, A, A)
    np.testing.assert_allclose(C.to_dense(), d @ d, rtol=1e-4, atol=1e-5)
    # jitted with static capacities
    flop_cap, out_cap = summa_capacities(A, A)
    f = jax.jit(
        lambda A, B: summa_spgemm(
            PLUS_TIMES, A, B, flop_capacity=flop_cap, out_capacity=out_cap
        )
    )
    np.testing.assert_allclose(f(A, A).to_dense(), d @ d, rtol=1e-4, atol=1e-5)


def test_summa_rect_matrices_nonuniform(rng):
    # shapes that don't divide the grid evenly
    grid = Grid.make(2, 2)
    da = random_dense(rng, 23, 15, 0.3)
    db = random_dense(rng, 15, 27, 0.3)
    A = SpParMat.from_dense(grid, da)
    B = SpParMat.from_dense(grid, db)
    C = spgemm(PLUS_TIMES, A, B)
    np.testing.assert_allclose(C.to_dense(), da @ db, rtol=1e-5, atol=1e-6)


def test_summa_stage_flops_host_matches_device(rng):
    """The host symbolic twin must track the device pass exactly — axon
    benchmarks size capacities from it with no device cross-check."""
    from combblas_tpu.parallel.spgemm import (
        summa_capacities,
        summa_capacities_host,
        summa_stage_flops,
        summa_stage_flops_host,
    )

    grid = Grid.make(2, 2)
    n = 37  # non-divisible dims exercise the padded owner math
    d = (rng.random((n, n)) < 0.2).astype(np.float32)
    r, c = np.nonzero(d)
    A = SpParMat.from_global_coo(grid, r, c, d[r, c], n, n)
    dev = np.asarray(summa_stage_flops(A, A), np.float64)
    host = summa_stage_flops_host(grid, r, c, r, c, n, n, n)
    np.testing.assert_array_equal(dev, host)
    assert summa_capacities_host(grid, r, c, r, c, n, n, n) == summa_capacities(A, A)
