"""Durability & self-healing (round 16, ISSUE 14): the write-ahead
log, crash recovery, replica supervision and write-home failover.

The load-bearing property here is CRASH-RECOVERY BIT-EXACTNESS: for a
crash at every append/merge/checkpoint boundary (torn final WAL line
included), ``recover_version`` = latest valid snapshot + WAL-suffix
replay must be ``to_host_coo()``-equal with a never-crashed engine
that merged the same acknowledged ops — and no acknowledged write may
be lost.  Tier-1 runs the boundary sweep on a 1x1 grid plus one 2x4
representative; the threaded kill-storm soak is ``slow`` (the
BENCH_SERVE_RECOVERY scenario is its measured twin).
"""

import json
import os
import time

import numpy as np
import pytest

from combblas_tpu.dynamic import (
    DeltaBatch,
    RecoveryError,
    WriteAheadLog,
    apply_delta,
    open_wal,
    recover_version,
)
from combblas_tpu.parallel.grid import Grid
from combblas_tpu.serve import (
    FleetRouter,
    GraphEngine,
    ServeConfig,
    Server,
)
from combblas_tpu.serve.fleet import ReplicaDeadError
from combblas_tpu.tuner import store as tstore
from combblas_tpu.utils import checkpoint

N = 64


def _coo(seed, n=N, m=300):
    r = np.random.default_rng(seed)
    rows = r.integers(0, n, m)
    cols = r.integers(0, n, m)
    return (
        np.concatenate([rows, cols]), np.concatenate([cols, rows])
    )


def _absent_pairs(rows, cols, k, n=N):
    present = set(zip(rows.tolist(), cols.tolist()))
    out = []
    for i in range(n):
        for j in range(i + 1, n):
            if (i, j) not in present and (j, i) not in present:
                out.append((i, j))
                if len(out) >= k:
                    return out
    return out


def _edges(version):
    return version.E.to_host_coo()


def _assert_bit_exact(va, vb):
    for x, y in zip(_edges(va), _edges(vb)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


@pytest.fixture(scope="module")
def grid():
    return Grid.make(1, 1)


@pytest.fixture(autouse=True)
def _fresh_store_singleton():
    tstore._reset_for_tests()
    yield
    tstore._reset_for_tests()


# --- WAL unit behavior -------------------------------------------------------


def test_wal_roundtrip_position_and_resume(tmp_path):
    """Append -> replay round-trips ops and seq ranges; a reopened log
    resumes the frontier (the promotion / recovery lineage)."""
    wal = WriteAheadLog(str(tmp_path / "wal.jsonl"))
    assert wal.position() == -1
    wal.append(0, [3, 9], [9, 3], [1.0, 2.5], [0, 2])
    wal.append(2, [5], [6], [1.0], [1])
    assert wal.position() == 2
    batches = wal.replay()
    assert [(b.first_seq, b.last_seq) for b in batches] == [(0, 1), (2, 2)]
    np.testing.assert_array_equal(batches[0].rows, [3, 9])
    np.testing.assert_array_equal(batches[0].vals,
                                  np.asarray([1.0, 2.5], np.float32))
    np.testing.assert_array_equal(batches[0].ops, [0, 2])
    # suffix replay masks past a snapshot frontier mid-record (the
    # record's seq range is metadata; the ops are sliced)
    suffix = wal.replay(after_seq=0)
    assert [(b.first_seq, b.last_seq) for b in suffix] == [(0, 1), (2, 2)]
    np.testing.assert_array_equal(suffix[0].rows, [9])
    assert len(suffix[0]) == 1
    wal.close()
    # reopen: the frontier survives the process
    wal2 = WriteAheadLog(str(tmp_path / "wal.jsonl"))
    assert wal2.position() == 2
    wal2.close()


def test_wal_torn_final_line_tolerated(tmp_path):
    """The expected crash artifact: a torn (partial) FINAL line is
    skipped — earlier records replay intact."""
    path = str(tmp_path / "wal.jsonl")
    wal = WriteAheadLog(path)
    wal.append(0, [1], [2], [1.0], [0])
    wal.close()
    with open(path, "a") as f:  # a write() died mid-line
        f.write('{"v": "combblas_tpu.wal/v1", "first_seq": 1, "la')
    wal2 = WriteAheadLog(path)
    batches = wal2.replay()
    assert len(batches) == 1 and batches[0].last_seq == 0
    assert wal2.invalid_lines == 1
    wal2.close()


def test_wal_interior_damage_skipped_not_poisoning(tmp_path):
    path = str(tmp_path / "wal.jsonl")
    with open(path, "w") as f:
        f.write('{"v": "combblas_tpu.wal/v1", "first_seq": 0, '
                '"last_seq": 0, "rows": [1], "cols": [2], '
                '"vals": [1.0], "ops": [0]}\n')
        f.write("garbage not json\n")
        f.write('{"v": "some.other/v9", "first_seq": 1, "last_seq": 1, '
                '"rows": [9], "cols": [9], "vals": [1.0], "ops": [0]}\n')
        f.write('{"v": "combblas_tpu.wal/v1", "first_seq": 1, '
                '"last_seq": 1, "rows": [4], "cols": [5], '
                '"vals": [1.0], "ops": [0]}\n')
    wal = WriteAheadLog(path)
    batches = wal.replay()
    assert [(b.first_seq, b.last_seq) for b in batches] == [(0, 0), (1, 1)]
    assert wal.invalid_lines == 2  # garbage + wrong schema
    wal.close()


def test_wal_truncate_keeps_suffix_and_frontier(tmp_path):
    """Checkpoint truncation drops the replayed prefix atomically and
    a FULLY truncated log still remembers its seqno frontier (the
    mark record) — sequence numbers must never restart."""
    path = str(tmp_path / "wal.jsonl")
    wal = WriteAheadLog(path)
    wal.append(0, [1], [2], [1.0], [0])
    wal.append(1, [3], [4], [1.0], [0])
    assert wal.truncate(0) == 1
    assert [b.last_seq for b in wal.replay()] == [1]
    assert wal.position() == 1
    assert wal.truncate(1) == 1  # now empty of data records
    assert wal.replay() == []
    assert wal.position() == 1
    wal.close()
    wal2 = WriteAheadLog(path)  # reopen: frontier still 1
    assert wal2.position() == 1
    wal2.close()
    assert not os.path.exists(path + ".tmp")


def test_wal_later_lines_win_on_reused_seqs(tmp_path):
    """Review finding (round 16): an append whose fsync raised AFTER
    the line reached disk was ROLLED BACK and rejected — the caller's
    retry legitimately reuses its sequence numbers.  Replay must apply
    the LATER (acknowledged) record, never the rejected one."""
    wal = WriteAheadLog(str(tmp_path / "wal.jsonl"))
    wal.append(0, [1], [2], [1.0], [0])   # rejected-but-on-disk
    wal.append(0, [7], [8], [1.0], [0])   # the acknowledged retry
    batches = wal.replay()
    assert len(batches) == 1
    np.testing.assert_array_equal(batches[0].rows, [7])
    wal.close()


def test_wal_positional_drop_kills_rejected_record_only(tmp_path):
    """Review finding (round 16): a record that reached disk before
    its fsync raised is tombstoned by the rollback path — the
    tombstone must kill the WHOLE rejected record (even seqs no retry
    re-claims) while leaving the later retry untouched (positional
    semantics)."""
    wal = WriteAheadLog(str(tmp_path / "wal.jsonl"))
    # rejected append: 3 ops at seqs 0-2, landed then rolled back
    wal.append(0, [1, 2, 3], [4, 5, 6], [1.0] * 3, [0, 0, 0])
    wal.append_drop(0, 2)
    # the retry re-claims only seq 0 (a smaller batch)
    wal.append(0, [9], [9], [1.0], [0])
    batches = wal.replay()
    assert len(batches) == 1
    np.testing.assert_array_equal(batches[0].rows, [9])  # seqs 1-2
    # of the rejected record stay dead: nothing resurrects
    wal.close()


def test_wal_drop_tombstone_suppresses_replay(tmp_path):
    """A merge-failed range (futures failed honestly on the live
    engine) must not resurrect at recovery."""
    wal = WriteAheadLog(str(tmp_path / "wal.jsonl"))
    wal.append(0, [1, 2], [2, 1], [1.0, 1.0], [0, 0])
    wal.append(2, [3], [4], [1.0], [0])
    wal.append_drop(0, 1)
    batches = wal.replay()
    assert [(b.first_seq, b.last_seq) for b in batches] == [(2, 2)]
    wal.close()


# --- snapshot atomicity / corruption fallback --------------------------------


def test_snapshot_atomic_and_corrupt_refused(grid, tmp_path):
    """ISSUE 14 satellite: ``save_version`` writes tmp + os.replace
    (no partial file under the real name), and a corrupt/truncated
    snapshot is REFUSED with a diagnostic naming the file —
    ``load_latest_version`` falls back to the previous retained one."""
    rows, cols = _coo(1)
    eng = GraphEngine.from_coo(grid, rows, cols, N, kinds=("bfs",),
                               keep_coo=True)
    p1 = str(tmp_path / checkpoint.snapshot_name(0))
    checkpoint.save_version(p1, eng.version)
    assert not os.path.exists(p1 + ".tmp")
    # newer snapshot, then corrupt it (truncate to half)
    p2 = str(tmp_path / checkpoint.snapshot_name(5))
    checkpoint.save_version(p2, eng.version)
    blob = open(p2, "rb").read()
    with open(p2, "wb") as f:
        f.write(blob[: len(blob) // 2])
    with pytest.raises(ValueError, match="ckpt-000000000006"):
        checkpoint.load_version(p2, grid)
    with pytest.warns(UserWarning, match="falling back"):
        v, path = checkpoint.load_latest_version(str(tmp_path), grid)
    assert path == p1  # the previous retained snapshot
    _assert_bit_exact(v, eng.version)
    # nothing loadable at all -> RecoveryError naming the dir
    empty = tmp_path / "empty"
    empty.mkdir()
    with pytest.raises(RecoveryError, match="no loadable"):
        checkpoint.load_latest_version(str(empty), grid)


def test_checkpoint_retention_prunes(grid, tmp_path):
    """checkpoint_retain bounds the snapshot set; pruning keeps the
    newest (the recovery source) plus the fallback depth."""
    rows, cols = _coo(2)
    eng = GraphEngine.from_coo(grid, rows, cols, N, kinds=("bfs",),
                               keep_coo=True)
    cfg = ServeConfig(lane_widths=(1,), update_autostart=False,
                      wal_dir=str(tmp_path), checkpoint_retain=2,
                      update_flush=1)
    srv = Server(eng, cfg)
    pairs = _absent_pairs(rows, cols, 4)
    for a, b in pairs:
        srv.submit_update([("insert", a, b), ("insert", b, a)])
        srv.pump_updates(force=True)
        srv.checkpoint_now()
    snaps = checkpoint.list_snapshots(str(tmp_path))
    assert len(snaps) == 2  # bootstrap + 4 manual, pruned to retain=2
    # and the newest one recovers the full state
    wal = open_wal(str(tmp_path))
    v = recover_version(str(tmp_path), wal, grid, kinds=("bfs",))
    wal.close()
    _assert_bit_exact(v, srv.engine.version)
    srv.close()


# --- the crash-recovery property ---------------------------------------------


def _crash_recover_scenario(grid, tmp_path, tag, n_appends, n_merges,
                            ckpt_after, torn):
    """Build a durable server, acknowledge ``n_appends`` write
    batches, merge the first ``n_merges``, checkpoint after
    ``ckpt_after`` merges (None = bootstrap snapshot only), optionally
    tear the final WAL line mid-write — then "crash" (walk away
    without close()) and recover from the files alone.

    The recovered version must be bit-exact with a NEVER-CRASHED
    reference that merged every acknowledged batch, minus a torn tail
    (a torn line was never acknowledged: its append raised before the
    future existed — losing it loses nothing promised)."""
    d = tmp_path / f"crash-{tag}"
    rows, cols = _coo(7)
    eng = GraphEngine.from_coo(grid, rows, cols, N, kinds=("bfs",),
                               keep_coo=True)
    cfg = ServeConfig(lane_widths=(1,), update_autostart=False,
                      wal_dir=str(d), update_flush=1)
    srv = Server(eng, cfg)
    pairs = _absent_pairs(rows, cols, n_appends)
    batches = [
        [("insert", a, b), ("insert", b, a)] for a, b in pairs
    ]
    for k, ops in enumerate(batches):
        srv.submit_update(ops)
        if k < n_merges:
            srv.pump_updates(force=True)
        if ckpt_after is not None and k + 1 == ckpt_after:
            assert srv.checkpoint_now() is not None
    if torn:
        # one more acknowledged batch... whose append is torn mid-line
        # (the dying-process artifact): simulate by appending a
        # partial record BEHIND the server's back
        with open(str(d / "wal.jsonl"), "a") as f:
            f.write('{"v": "combblas_tpu.wal/v1", "first_se')
    # CRASH: no close(), no drain — the files are all that survives
    wal = open_wal(str(d))
    recovered = recover_version(str(d), wal, grid, kinds=("bfs",))
    wal.close()
    # the never-crashed reference: every acknowledged batch applied
    ref = GraphEngine.from_coo(grid, rows, cols, N, kinds=("bfs",),
                               keep_coo=True).version
    for k, ops in enumerate(batches):
        ref = apply_delta(
            ref, DeltaBatch.from_ops(ops, start_seq=2 * k),
            kinds=("bfs",),
        )
    _assert_bit_exact(recovered, ref)
    # cleanliness: quarantine-free teardown for the abandoned server
    srv.scheduler.close()


def test_crash_recovery_bit_exact_at_every_boundary(grid, tmp_path):
    """THE acceptance property: crashes at every append/merge/
    checkpoint boundary recover bit-exact, zero acknowledged writes
    lost.  Sweeps (appends, merges, checkpoint position) over the
    small-graph 1x1 grid; the torn-final-line artifact rides the
    deepest scenario."""
    cases = []
    for k in (1, 2, 4):
        for m in sorted({0, k // 2, k}):
            for c in sorted({None, m if m else None},
                            key=lambda x: -1 if x is None else x):
                cases.append((k, m, c, False))
    cases.append((4, 2, 2, True))  # torn tail on a mid-merge crash
    cases.append((3, 3, None, True))  # torn tail, bootstrap-only ckpt
    for i, (k, m, c, torn) in enumerate(cases):
        _crash_recover_scenario(
            grid, tmp_path, f"{i}", k, m, c, torn
        )


def test_crash_recovery_distributed_representative(tmp_path):
    """One 2x4-grid representative of the boundary sweep (the tier-1
    mesh): snapshot of an INCREMENTALLY merged version + suffix
    replay, crash after the checkpoint."""
    _crash_recover_scenario(
        Grid.make(2, 4), tmp_path, "dist", 3, 2, 2, False
    )


def test_recovered_server_resumes_lineage(grid, tmp_path):
    """Server.from_recovery boots bit-exact AND keeps writing on the
    same seqno lineage: post-recovery writes merge incrementally and a
    second recovery sees them too (no seq collision, no replay dup)."""
    d = str(tmp_path / "resume")
    rows, cols = _coo(9)
    # headroom reserves re-bucket slots, so the post-recovery insert
    # provably exercises the INCREMENTAL path on the restored sticky
    # layout (without it, bucket_full may legitimately spill — on a
    # live engine exactly as on a recovered one)
    eng = GraphEngine.from_coo(grid, rows, cols, N, kinds=("bfs",),
                               keep_coo=True, headroom=0.5)
    cfg = ServeConfig(lane_widths=(1,), update_autostart=False,
                      wal_dir=d, update_flush=1)
    srv = Server(eng, cfg)
    pairs = _absent_pairs(rows, cols, 3)
    (a0, b0), (a1, b1), (a2, b2) = pairs
    srv.submit_update([("insert", a0, b0), ("insert", b0, a0)])
    srv.pump_updates(force=True)
    srv.submit_update([("insert", a1, b1), ("insert", b1, a1)])
    # crash with one un-merged acknowledged write
    srv2 = Server.from_recovery(grid, cfg, kinds=("bfs",))
    lev = None
    for (x, y) in ((a0, b0), (a1, b1)):
        lev = srv2.submit("bfs", x)
        srv2.pump(force=True)
        assert lev.result(timeout=60)["levels"][y] == 1
    f = srv2.submit_update([("insert", a2, b2), ("insert", b2, a2)])
    srv2.pump_updates(force=True)
    res = f.result(timeout=60)
    assert res["mode"] == "incremental"  # restored sticky layout holds
    # a third life sees ALL three writes
    srv3 = Server.from_recovery(grid, cfg, kinds=("bfs",))
    _assert_bit_exact(srv3.engine.version, srv2.engine.version)
    for s in (srv, srv2, srv3):
        s.scheduler.close()


def test_boot_from_coo_refuses_unreplayed_wal(grid, tmp_path):
    """Review finding (round 16): booting a FRESH engine from COO over
    a durability dir whose WAL still holds acknowledged writes no
    snapshot covers must REFUSE — the bootstrap snapshot would
    otherwise truncate (destroy) them silently.  Recovery consumes
    the suffix; after it (or a clean close) the same boot succeeds."""
    d = str(tmp_path / "refuse")
    rows, cols = _coo(13)
    cfg = ServeConfig(lane_widths=(1,), update_autostart=False,
                      wal_dir=d, update_flush=64,
                      update_max_delay_s=30.0)
    eng = GraphEngine.from_coo(grid, rows, cols, N, kinds=("bfs",),
                               keep_coo=True)
    srv = Server(eng, cfg)
    (a, b), = _absent_pairs(rows, cols, 1)
    srv.submit_update([("insert", a, b)])  # acknowledged, un-merged
    # "crash"; a naive re-boot from COO must not destroy the write
    with pytest.raises(RuntimeError, match="would silently destroy"):
        Server(
            GraphEngine.from_coo(grid, rows, cols, N, kinds=("bfs",),
                                 keep_coo=True),
            cfg,
        )
    # recovery consumes the suffix -> the write survives, and a later
    # boot-from-COO (fresh lineage over the exhausted log) is allowed
    srv2 = Server.from_recovery(grid, cfg, kinds=("bfs",))
    r, c, _v = srv2.engine.version.E.to_host_coo()
    assert (a, b) in set(zip(r.tolist(), c.tolist()))
    srv2.scheduler.close()
    srv3 = Server(
        GraphEngine.from_coo(grid, rows, cols, N, kinds=("bfs",),
                             keep_coo=True),
        cfg,
    )
    srv3.scheduler.close()
    srv.scheduler.close()


def test_nondurable_home_death_rebuilds_fresh_lineage(tmp_path):
    """Review finding (round 16): without a WAL a dead home cannot be
    promoted — but the supervisor must still REBUILD the slot (the
    engine object outlives its worker; its retained COO is the fresh
    lineage) instead of leaving writes down forever."""
    fr, rows, cols = _mk_fleet(tmp_path, 31, wal=False)
    try:
        fr.warmup(widths=(1, 2))
        _kill_worker(fr, 0)  # the (non-durable) home dies
        out = fr.supervise_once()
        assert out["promoted"] is None and 0 in out["replaced"]
        assert fr.home == 0  # same slot, fresh lineage
        # reads AND writes serve again
        (a, b), = _absent_pairs(rows, cols, 1)
        res = fr.submit_update(
            [("insert", a, b), ("insert", b, a)]
        ).result(timeout=60)
        assert res["fanned_out"] == 1
        for srv in fr.replicas:
            assert srv.submit("bfs", a).result(
                timeout=60
            )["levels"][b] == 1
    finally:
        fr.close(drain=False)


def test_wal_append_failure_rejects_write(grid, tmp_path):
    """A write whose WAL append failed is REJECTED, not acknowledged
    undurable: the buffer rolls back, nothing merges, and the next
    write proceeds on clean sequence numbers."""
    rows, cols = _coo(11)
    eng = GraphEngine.from_coo(grid, rows, cols, N, kinds=("bfs",),
                               keep_coo=True)
    cfg = ServeConfig(lane_widths=(1,), update_autostart=False,
                      wal_dir=str(tmp_path / "wf"), update_flush=1)
    srv = Server(eng, cfg)
    (a, b), (a2, b2) = _absent_pairs(rows, cols, 2)
    srv.faults.script("wal.append", at=(0,))
    with pytest.raises(RuntimeError, match="NOT acknowledged"):
        srv.submit_update([("insert", a, b)])
    assert srv._upd_buffer.depth() == 0  # rolled back
    assert srv.pump_updates(force=True) == 0  # nothing to merge
    f = srv.submit_update([("insert", a2, b2), ("insert", b2, a2)])
    srv.pump_updates(force=True)
    assert f.result(timeout=60)["ops"] == 2
    # recovery agrees: only the acknowledged write exists
    wal = open_wal(str(tmp_path / "wf"))
    v = recover_version(str(tmp_path / "wf"), wal, grid,
                        kinds=("bfs",))
    wal.close()
    _assert_bit_exact(v, srv.engine.version)
    srv.scheduler.close()


# --- fleet: routing, supervision, promotion, drain ---------------------------


def _mk_fleet(tmp_path, seed, replicas=2, wal=True, grid_shape=(1, 1),
              **cfg_kw):
    """Most fleet-healing mechanics are grid-independent (threads,
    queues, files): they run on the cheap 1x1 grid; the promotion and
    routing tests keep a 2x4 tier-1-mesh representative."""
    rows, cols = _coo(seed)
    kw = dict(lane_widths=(1, 2), update_flush=1,
              update_max_delay_s=0.005)
    kw.update(cfg_kw)
    cfg = ServeConfig(**kw)
    fr = FleetRouter.build(
        Grid.make(*grid_shape), rows, cols, N, replicas=replicas,
        config=cfg, kinds=("bfs",),
        wal_dir=str(tmp_path / "fleet-wal") if wal else None,
    )
    return fr, rows, cols


def _kill_worker(fr, i, timeout=5.0):
    """Deterministically kill replica i's worker thread through the
    replica.death fault point (woken by a direct submit)."""
    fr.replicas[i].faults.script("replica.death", at=(0,))
    probe = fr.replicas[i].submit("bfs", 1)  # wakes THAT worker
    t0 = time.monotonic()
    while not fr._dead(i):
        assert time.monotonic() - t0 < timeout, "worker did not die"
        time.sleep(0.005)
    return probe


def test_route_order_skips_dead_replica(tmp_path):
    """ISSUE 14 satellite: a dead replica's EMPTY queue must not
    attract traffic — routing skips down/closed replicas."""
    fr, rows, cols = _mk_fleet(tmp_path, 21, wal=False,
                               grid_shape=(2, 4))
    try:
        fr.warmup(widths=(1, 2))
        _kill_worker(fr, 1)  # the non-home replica dies
        # the dead replica has queue depth <= 1 (the probe), yet every
        # routed submit lands on the live one
        assert fr._route_order() == [0]
        for _ in range(4):
            assert fr.submit("bfs", 2).result(timeout=60) is not None
        assert fr.submitted[1] == 0
        # replacement (no WAL: rebuilt from the home's retained COO)
        # rejoins the rotation
        assert fr.supervise_once()["replaced"] == [1]
        assert set(fr._route_order()) == {0, 1}
    finally:
        fr.close(drain=False)


def test_fanout_failure_lags_visibly_and_heals(tmp_path):
    """ISSUE 14 satellite: a replica whose rebuild fails mid-fan-out
    LAGS (stats/health degrade) instead of failing the write, and the
    next fan-out retries and heals it."""
    fr, rows, cols = _mk_fleet(tmp_path, 22, wal=False)
    try:
        fr.warmup(widths=(1, 2))
        pairs = _absent_pairs(rows, cols, 2)
        fr.faults.script("fleet.fanout", at=(0,))  # first fan-out dies
        (a, b), (a2, b2) = pairs
        res = fr.submit_update(
            [("insert", a, b), ("insert", b, a)]
        ).result(timeout=60)
        assert res["fanned_out"] == 0 and res["lagging"] == [1]
        assert fr.health()["status"] == "degraded"
        assert fr.lagging() == [1]
        # replica 1 still serves the OLD version, honestly
        assert fr.replicas[1].submit("bfs", a).result(
            timeout=60
        )["levels"][b] != 1
        # next fan-out (the second write) retries replica 1 -> heals
        res = fr.submit_update(
            [("insert", a2, b2), ("insert", b2, a2)]
        ).result(timeout=60)
        assert res["fanned_out"] == 1 and res["lagging"] == []
        assert fr.health()["status"] == "ok"
        lev = fr.replicas[1].submit("bfs", a).result(timeout=60)
        assert lev["levels"][b] == 1  # the lagged write arrived too
    finally:
        fr.close(drain=False)


@pytest.mark.slow
def test_supervisor_replaces_dead_replica_bit_exact(tmp_path):
    """A dead (non-home) replica is quarantined (pending futures fail
    honestly), rebuilt from checkpoint+WAL and re-admitted serving the
    acknowledged writes — warm from the shared plan store.

    ``slow``: the tier-1 representative of the supervise->quarantine->
    rebuild path is ``test_home_death_promotes_at_wal_frontier``
    (which also replaces the dead ex-home through the same code)."""
    fr, rows, cols = _mk_fleet(tmp_path, 23, wal=True)
    try:
        fr.warmup(widths=(1, 2))
        (a, b), = _absent_pairs(rows, cols, 1)
        fr.submit_update(
            [("insert", a, b), ("insert", b, a)]
        ).result(timeout=60)
        probe = _kill_worker(fr, 1)
        out = fr.supervise_once()
        assert out["detected"] == [1] and out["replaced"] == [1]
        assert isinstance(probe.exception(timeout=10),
                          ReplicaDeadError)  # honest, never stranded
        # the replacement serves the acknowledged write, bit-exact
        # with the home
        _assert_bit_exact(fr.replicas[1].engine.version,
                          fr.replicas[0].engine.version)
        mark = fr.replicas[1].engine.trace_mark()
        lev = fr.replicas[1].submit("bfs", a).result(timeout=60)
        assert lev["levels"][b] == 1
        assert fr.replicas[1].engine.retraces_since(mark) == 0
        assert fr.replacements == 1
        assert fr.health()["status"] == "ok"
    finally:
        fr.close(drain=False)


def test_home_death_promotes_at_wal_frontier(tmp_path):
    """THE failover: the home dies with an acknowledged-but-unmerged
    write buffered.  Promotion recovers the new home at the WAL's
    seqno frontier (the buffered write INCLUDED — acknowledged means
    durable), fails the dead home's buffered futures honestly, and
    the write lane continues on the single preserved lineage."""
    fr, rows, cols = _mk_fleet(
        tmp_path, 24, replicas=3, wal=True, grid_shape=(2, 4),
        # writes BUFFER (no flush): the promotion must not depend on
        # the dead home having merged
        update_flush=64, update_max_delay_s=30.0,
    )
    try:
        fr.warmup(widths=(1, 2))
        (a, b), (a2, b2) = _absent_pairs(rows, cols, 2)
        buffered = fr.submit_update([("insert", a, b),
                                     ("insert", b, a)])
        assert not buffered.done()
        _kill_worker(fr, 0)
        out = fr.supervise_once()
        assert out["promoted"] is not None and fr.home == out["promoted"]
        assert fr.promotions == 1
        # honest failure of the buffered future...
        assert isinstance(buffered.exception(timeout=10),
                          ReplicaDeadError)
        # ...but ZERO acknowledged-write loss: the new home serves it
        lev = fr.replicas[fr.home].submit("bfs", a).result(timeout=60)
        assert lev["levels"][b] == 1
        # the lineage continues: a post-promotion write lands
        # everywhere (old home's slot was replaced too).  The config
        # buffers writes for 30 s by design (the buffered-future
        # scenario above), so force the merge deterministically.
        f2 = fr.submit_update(
            [("insert", a2, b2), ("insert", b2, a2)]
        )
        fr.replicas[fr.home].pump_updates(force=True)
        res = f2.result(timeout=60)
        assert res["fanned_out"] == len(fr.replicas) - 1
        for srv in fr.replicas:
            assert srv.submit("bfs", a2).result(
                timeout=60
            )["levels"][b2] == 1
        assert fr.health()["status"] == "ok"
    finally:
        fr.close(drain=False)


def test_read_retry_on_next_best_replica(tmp_path):
    """Bounded read retry (reads only): with one replica failing every
    execution, router-submitted reads still succeed via the retry on
    the other replica."""
    fr, rows, cols = _mk_fleet(tmp_path, 25, wal=False)
    try:
        fr.warmup(widths=(1, 2))
        fr.replicas[0].faults.rate("engine.execute", 1.0, seed=1)
        for _ in range(6):
            assert fr.submit("bfs", 3).result(timeout=60) is not None
        assert fr.read_retries >= 1
        # malformed roots are NOT retried: one honest ValueError
        bad = fr.submit("bfs", N + 99)
        assert isinstance(bad.exception(timeout=60), ValueError)
    finally:
        fr.close(drain=False)


def test_fleet_close_drain_flushes_vs_aborts(tmp_path):
    """ISSUE 14 satellite, the PR 9 single-server guarantee at fleet
    scope: close(drain=True) flushes the home's buffered writes
    through merge (durable: WAL + final checkpoint) before returning;
    close(drain=False) aborts the buffered futures."""
    # drain=True: the buffered write lands and survives into recovery
    fr, rows, cols = _mk_fleet(
        tmp_path, 26, wal=True,
        update_flush=64, update_max_delay_s=30.0,
    )
    (a, b), = _absent_pairs(rows, cols, 1)
    f = fr.submit_update([("insert", a, b), ("insert", b, a)])
    fr.close(drain=True)
    assert f.result(timeout=10)["ops"] == 2
    wal_dir = fr.wal_dir
    g = Grid.make(1, 1)
    wal = open_wal(wal_dir)
    v = recover_version(wal_dir, wal, g, kinds=("bfs",))
    wal.close()
    _assert_bit_exact(v, fr.replicas[0].engine.version)
    # drain=False: buffered futures abort (and stay aborted)
    fr2, rows2, cols2 = _mk_fleet(
        tmp_path / "nf", 27, wal=False,
        update_flush=64, update_max_delay_s=30.0,
    )
    (a2, b2), = _absent_pairs(rows2, cols2, 1)
    f2 = fr2.submit_update([("insert", a2, b2), ("insert", b2, a2)])
    fr2.close(drain=False)
    assert isinstance(f2.exception(timeout=10), RuntimeError)


def test_drain_restore_rolling_restart(tmp_path):
    """Upgrades are first-class: drain/restore cycles every replica
    with reads surviving throughout, a mid-drain write healing via
    the restore fan-out, and ZERO retraces (the engines are reused
    warm)."""
    fr, rows, cols = _mk_fleet(tmp_path, 28, wal=True)
    try:
        fr.warmup(widths=(1, 2))
        (a, b), = _absent_pairs(rows, cols, 1)
        marks = [s.engine.trace_mark() for s in fr.replicas]
        f = fr.submit_update([("insert", a, b), ("insert", b, a)])
        assert fr.rolling_restart() == 2
        f.result(timeout=60)
        assert fr.lagging() == []
        for srv, mark in zip(fr.replicas, marks):
            assert srv.submit("bfs", a).result(
                timeout=60
            )["levels"][b] == 1
            assert srv.engine.retraces_since(mark) == 0
        st = fr.stats()
        assert st["draining"] == [] and st["promotions"] == 0
    finally:
        fr.close(drain=False)


@pytest.mark.slow
def test_fleet_from_recovery_boots_whole_fleet(tmp_path):
    """FleetRouter.from_recovery: every replica = snapshot + WAL
    replay, home re-attached at the frontier, writes resume.

    ``slow``: the tier-1 representative of the recovery-boot path is
    ``test_recovered_server_resumes_lineage`` (Server.from_recovery —
    the same recover+attach machinery, one replica)."""
    fr, rows, cols = _mk_fleet(tmp_path, 29, wal=True)
    (a, b), (a2, b2) = _absent_pairs(rows, cols, 2)
    fr.submit_update([("insert", a, b),
                      ("insert", b, a)]).result(timeout=60)
    fr.close(drain=True)
    cfg = ServeConfig(lane_widths=(1, 2), update_flush=1,
                      update_max_delay_s=0.005)
    with FleetRouter.from_recovery(
        Grid.make(1, 1), replicas=2, config=cfg, kinds=("bfs",),
        wal_dir=str(tmp_path / "fleet-wal"),
    ) as fr2:
        fr2.warmup(widths=(1, 2))
        for srv in fr2.replicas:
            assert srv.submit("bfs", a).result(
                timeout=60
            )["levels"][b] == 1
        res = fr2.submit_update(
            [("insert", a2, b2), ("insert", b2, a2)]
        ).result(timeout=60)
        assert res["fanned_out"] == 1


# --- threaded kill-storm soak (slow; the bench's deterministic twin) ---------


@pytest.mark.slow
@pytest.mark.chaos
def test_kill_storm_soak(tmp_path):
    """Mixed read/write load with replica kills (home included) while
    the supervisor heals: availability holds, every acknowledged
    write survives into the final recovered state."""
    import threading

    fr, rows, cols = _mk_fleet(tmp_path, 30, replicas=3, wal=True,
                               grid_shape=(2, 4))
    acked = []
    try:
        fr.warmup(widths=(1, 2))
        fr.start_supervisor(interval_s=0.02)
        pairs = _absent_pairs(rows, cols, 12)
        stop = threading.Event()

        def writer():
            for a, b in pairs:
                try:
                    f = fr.submit_update(
                        [("insert", a, b), ("insert", b, a)]
                    )
                    f.result(timeout=60)
                    acked.append((a, b))
                except Exception:
                    pass  # failed writes may or may not be durable
                time.sleep(0.01)

        wt = threading.Thread(target=writer)
        wt.start()
        ok = bad = 0
        for i in range(120):
            if i in (30, 70):  # kill a replica / the home mid-stream
                victim = fr.home if i == 70 else (fr.home + 1) % 3
                try:
                    _kill_worker(fr, victim)
                except AssertionError:
                    pass
            try:
                fr.submit("bfs", int(rows[i % len(rows)])).result(
                    timeout=60
                )
                ok += 1
            except Exception:
                bad += 1
        wt.join(120)
        stop.set()
        assert ok / (ok + bad) >= 0.95
        # let the supervisor settle any last kill before closing (a
        # quarantined slot stays in _needs_rebuild until re-admitted)
        deadline = time.monotonic() + 10
        while (
            fr._needs_rebuild
            or any(fr._dead(i) for i in range(3))
        ) and time.monotonic() < deadline:
            time.sleep(0.02)
    finally:
        fr.close(drain=True)
    # zero acknowledged-write loss: recover from the files and check
    # every acked edge exists
    wal = open_wal(str(tmp_path / "fleet-wal"))
    v = recover_version(str(tmp_path / "fleet-wal"), wal,
                        Grid.make(2, 4), kinds=("bfs",))
    wal.close()
    r, c, _vals = v.E.to_host_coo()
    have = set(zip(r.tolist(), c.tolist()))
    missing = [p for p in acked if p not in have]
    assert not missing, f"acknowledged writes lost: {missing}"
