"""Auto-tiered SpGEMM: router, windowed kernel, support oracle, and the
distributed edge-harvest TC tier (ISSUE 3 tentpole).

Property contract: every tier is EXACT — ``spgemm_auto`` must agree with
the ESC golden across semirings, duplicate-entry COO inputs, empty-output
blocks, and forced-tier overrides (the MultTest golden-product pattern,
ReleaseTests/MultTest.cpp:122-234).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from combblas_tpu import MAX_MIN, MIN_PLUS, PLUS_TIMES, obs
from combblas_tpu.ops.compressed import CSR, CSC
from combblas_tpu.ops.spgemm import (
    combine_hilo,
    dense_support_nnz,
    densify_combine,
    pack_support_bits,
    popcount_pair_counts,
    scatter_combine_for,
    spgemm_support_bits,
    support_window_counts,
)
from combblas_tpu.ops.tuples import SpTuples
from combblas_tpu.parallel.grid import Grid
from combblas_tpu.parallel.spgemm import (
    WINDOWED_MAX_COL_WINDOWS,
    WINDOWED_MAX_PANEL_CELLS,
    _pad128,
    choose_spgemm_tier,
    choose_tier_from_counts,
    default_block_cols,
    default_block_rows,
    dot_panel_feasible,
    panel_cap_from_bnnz,
    spgemm,
    spgemm_auto,
    spgemm_windowed,
    summa_rowblock_flops,
    summa_rowblock_flops_host,
    summa_spgemm_windowed,
    summa_window_bnnz,
    summa_window_bnnz_host,
    summa_window_flops_host,
    summa_window_flops_pair,
    windowed_plan,
    windowed_plan_2d,
)
from combblas_tpu.parallel.spmat import SpParMat
from combblas_tpu.semiring import Semiring


def coo(rng, m, k, nnz, dup_frac=0.0):
    r = rng.integers(0, m, nnz).astype(np.int64)
    c = rng.integers(0, k, nnz).astype(np.int64)
    v = (rng.random(nnz) + 0.5).astype(np.float32)
    ndup = int(nnz * dup_frac)
    if ndup:
        r = np.concatenate([r, r[:ndup]])
        c = np.concatenate([c, c[:ndup]])
        v = np.concatenate([v, (rng.random(ndup) + 0.5).astype(np.float32)])
    return r, c, v


def dense_of(M: SpParMat) -> np.ndarray:
    """Host reconstruction; duplicate slots ADD (plus_times semantics) —
    only call on compacted products or plus_times inputs."""
    r, c, v, _ = jax.device_get((M.rows, M.cols, M.vals, M.nnz))
    out = np.zeros((M.nrows, M.ncols), np.float64)
    lr, lc = M.local_rows, M.local_cols
    for i in range(M.grid.pr):
        for j in range(M.grid.pc):
            m_ = r[i, j] < lr
            np.add.at(
                out,
                (r[i, j][m_] + i * lr, c[i, j][m_] + j * lc),
                v[i, j][m_],
            )
    return out


def host_nnz(M: SpParMat) -> int:
    return int(np.asarray(jax.device_get(M.getnnz())))


@pytest.mark.parametrize("srname", ["plus_times", "min_plus", "max_min"])
@pytest.mark.parametrize("p", [1, 2])
def test_windowed_matches_esc_across_semirings(rng, srname, p):
    """spgemm_auto(tier='windowed') == ESC, duplicate-entry COO input."""
    sr = {"plus_times": PLUS_TIMES, "min_plus": MIN_PLUS,
          "max_min": MAX_MIN}[srname]
    grid = Grid.make(p, p)
    m, k, n = 64, 48, 80
    ra, ca, va = coo(rng, m, k, 500, dup_frac=0.2)
    rb, cb, vb = coo(rng, k, n, 600, dup_frac=0.2)
    A = SpParMat.from_global_coo(grid, ra, ca, va, m, k)
    B = SpParMat.from_global_coo(grid, rb, cb, vb, k, n)
    C_esc = spgemm(sr, A, B)
    C_win = spgemm_auto(sr, A, B, tier="windowed", block_rows=16)
    # both outputs are compacted/unique per cell: dense compare is exact
    np.testing.assert_allclose(
        dense_of(C_win), dense_of(C_esc), rtol=1e-5, atol=1e-6
    )
    assert host_nnz(C_win) == host_nnz(C_esc)


def test_windowed_exact_for_integer_counts(rng):
    """0/1 adjacency A²: counts are integers — bit-exact vs ESC."""
    grid = Grid.make(2, 2)
    m = 96
    ra, ca, _ = coo(rng, m, m, 900, dup_frac=0.1)
    ones = np.ones(len(ra), np.float32)
    A = SpParMat.from_global_coo(grid, ra, ca, ones, m, m)
    # ESC golden needs the DEDUPED input for 0/1 semantics
    key = np.unique(ra * m + ca)
    Au = SpParMat.from_global_coo(
        grid, key // m, key % m, np.ones(len(key), np.float32), m, m
    )
    C_esc = spgemm(PLUS_TIMES, Au, Au)
    C_win = spgemm_windowed(PLUS_TIMES, Au, Au, block_rows=16)
    np.testing.assert_array_equal(dense_of(C_win), dense_of(C_esc))
    assert host_nnz(C_win) == host_nnz(C_esc)


def test_empty_output_blocks_are_skipped(rng):
    """Rows with no A entries produce empty output blocks — the symbolic
    plan must mark them skipped, and the result still matches ESC."""
    grid = Grid.make(1, 1)
    m = 64
    # A entries confined to rows [0, 8): blocks 1..7 of 8 are empty
    ra = rng.integers(0, 8, 120).astype(np.int64)
    ca = rng.integers(0, m, 120).astype(np.int64)
    va = np.ones(120, np.float32)
    A = SpParMat.from_global_coo(grid, ra, ca, va, m, m)
    rb, cb, vb = coo(rng, m, m, 400)
    B = SpParMat.from_global_coo(grid, rb, cb, vb, m, m)
    pb = np.asarray(
        jax.device_get(summa_rowblock_flops(A, B, 8, chunk_w=8))
    )
    pt = np.asarray(jax.device_get(summa_rowblock_flops(A, B, 8)))
    fc, oc, skip = windowed_plan(pb, pt, 8, A.local_rows, B.local_cols)
    assert skip[0] is False and all(skip[1:]), skip
    C_win, overflow = summa_spgemm_windowed(
        PLUS_TIMES, A, B, block_rows=8, flop_caps=fc, out_caps=oc,
        skip=skip, backend="scatter",
    )
    assert int(overflow) <= 0
    C_esc = spgemm(PLUS_TIMES, A, B)
    np.testing.assert_allclose(
        dense_of(C_win), dense_of(C_esc), rtol=1e-5, atol=1e-6
    )


def test_forced_tier_overrides_agree(rng, monkeypatch):
    grid = Grid.make(2, 2)
    m = 48
    ra, ca, va = coo(rng, m, m, 300)
    # UNIQUE entries: the mxu tier densifies with the unique_indices
    # scatter contract (duplicate tolerance belongs to the esc/scan/
    # windowed tiers, covered above)
    key, idx = np.unique(ra * m + ca, return_index=True)
    ra, ca, va = ra[idx], ca[idx], va[idx]
    A = SpParMat.from_global_coo(grid, ra, ca, va, m, m)
    ref = dense_of(spgemm(PLUS_TIMES, A, A))
    for tier in ("esc", "scan", "windowed", "mxu"):
        C = spgemm_auto(PLUS_TIMES, A, A, tier=tier, interpret=True)
        np.testing.assert_allclose(
            dense_of(C), ref, rtol=1e-4, atol=1e-5
        )
    # env override is honored
    monkeypatch.setenv("COMBBLAS_SPGEMM_TIER", "windowed")
    C = spgemm_auto(PLUS_TIMES, A, A)
    np.testing.assert_allclose(dense_of(C), ref, rtol=1e-4, atol=1e-5)


def test_tier_gate_rules():
    """The routing rule: mxu for small dense-kernel tiles; windowed only
    with a scatter combiner, bounded cells, and dense-enough output;
    scan otherwise."""
    generic = Semiring(
        name="generic_test", add=jnp.add, mul=jnp.multiply,
        zero_fn=lambda dt: 0, add_kind="generic",
    )
    assert scatter_combine_for(generic) is None
    # small tile + dense-kernel semiring → mxu
    assert choose_tier_from_counts(
        PLUS_TIMES, 4096, 4096 * 4096, 1, 1e6, "scatter"
    ) == "mxu"
    # big tile, dense output, scatter combiner → windowed
    assert choose_tier_from_counts(
        PLUS_TIMES, 1 << 16, 1 << 32, 1, 1e9, "scatter"
    ) == "windowed"
    # generic monoid cannot scatter → scan
    assert choose_tier_from_counts(
        generic, 1 << 16, 1 << 32, 1, 1e9, "scatter"
    ) == "scan"
    # output too sparse relative to the dense tile → scan
    assert choose_tier_from_counts(
        PLUS_TIMES, 1 << 20, 1 << 33, 1, 1e3, "scatter"
    ) == "scan"
    # ISSUE 5: the dot backend now has the 2D B-column-windowed
    # formulation — mid-scale tiles above the mxu envelope route to
    # windowed on TPU too (this exact case returned "scan" before)
    assert choose_tier_from_counts(
        PLUS_TIMES, 1 << 16, 1 << 32, 1, 1e9, "dot", k_dim=1 << 16
    ) == "windowed"
    # ...but not when even a minimum 512-wide B panel would exceed the
    # stage-operand envelope
    assert choose_tier_from_counts(
        PLUS_TIMES, 1 << 20, 1 << 33, 1, 1e9, "dot", k_dim=1 << 20
    ) == "scan"
    # tropical semirings ride the same dot rung (Pallas dense kernel)
    assert choose_tier_from_counts(
        MIN_PLUS, 1 << 16, 1 << 32, 1, 1e9, "dot", k_dim=1 << 16
    ) == "windowed"
    # generic monoid cannot densify-combine → scan even on dot
    assert choose_tier_from_counts(
        generic, 1 << 16, 1 << 32, 1, 1e9, "dot", k_dim=1 << 16
    ) == "scan"
    # allow_mxu=False (the duplicate-entry fallback) re-evaluates the
    # rest of the ladder
    assert choose_tier_from_counts(
        PLUS_TIMES, 4096, 4096 * 4096, 1, 1e7, "scatter",
        allow_mxu=False,
    ) == "windowed"


def test_router_records_obs_counters(rng):
    grid = Grid.make(1, 1)
    m = 48
    ra, ca, va = coo(rng, m, m, 300)
    A = SpParMat.from_global_coo(grid, ra, ca, va, m, m)
    obs.enable(install_hooks=False)
    try:
        obs.reset()
        spgemm_auto(PLUS_TIMES, A, A, tier="windowed", block_rows=16)
        assert obs.registry.get_counter(
            "spgemm.auto.tier", tier="windowed", sr="plus_times"
        ) == 1
        assert obs.registry.get_gauge("spgemm.windowed.blocks") == 3
        assert obs.registry.get_counter(
            "spgemm.windowed.windows_skipped"
        ) >= 0
        assert obs.registry.get_gauge("spgemm.auto.mask_density") > 0
    finally:
        obs.disable()
        obs.reset()


def test_rowblock_flops_host_matches_device(rng):
    grid = Grid.make(2, 2)
    m, k, n = 64, 48, 80
    ra, ca, va = coo(rng, m, k, 400)
    rb, cb, vb = coo(rng, k, n, 500)
    A = SpParMat.from_global_coo(grid, ra, ca, va, m, k)
    B = SpParMat.from_global_coo(grid, rb, cb, vb, k, n)
    for w in (0, 8):
        dev = np.asarray(
            jax.device_get(summa_rowblock_flops(A, B, 8, chunk_w=w))
        )
        host = summa_rowblock_flops_host(
            grid, ra, ca, rb, cb, m, k, n, 8, chunk_w=w
        )
        np.testing.assert_array_equal(dev.astype(np.int64),
                                      host.astype(np.int64))


def test_support_oracle_exact(rng):
    da = (rng.random((50, 40)) < 0.2).astype(np.float32)
    db = (rng.random((40, 60)) < 0.2).astype(np.float32)
    a = SpTuples.from_dense(da, capacity=600)
    b = SpTuples.from_dense(db, capacity=600)
    bits, row_nnz = spgemm_support_bits(a, b, row_block=16)
    P = (da @ db) > 0
    got = np.zeros_like(P)
    bb = np.asarray(bits)
    for j in range(60):
        got[:, j] = (bb[:, j >> 5] >> (j & 31)) & 1
    np.testing.assert_array_equal(got, P)
    np.testing.assert_array_equal(np.asarray(row_nnz), P.sum(1))
    # masked numeric pass over the support: popcount counts == A·B values
    ii, jj = np.nonzero(P)
    chunk = 64
    pad = -(-len(ii) // chunk) * chunk
    iiP = np.pad(ii, (0, pad - len(ii))).astype(np.int32)
    jjP = np.pad(jj, (0, pad - len(jj))).astype(np.int32)
    w = np.pad(np.ones(len(ii), np.int32), (0, pad - len(ii)))
    abits = pack_support_bits(a.rows, a.cols, 50, 40)
    btbits = CSC.from_tuples(b).to_bitmask()
    hilo = popcount_pair_counts(
        abits, btbits, jnp.asarray(iiP), jnp.asarray(jjP),
        jnp.asarray(w), chunk=chunk,
    )
    assert combine_hilo(hilo) == int((da @ db)[ii, jj].sum())


def test_pack_support_bits_dedups(rng):
    m, n = 37, 70
    r = rng.integers(0, m, 200).astype(np.int32)
    c = rng.integers(0, n, 200).astype(np.int32)
    r = np.concatenate([r, r[:50]])
    c = np.concatenate([c, c[:50]])  # hard duplicates: would carry bits
    bits = pack_support_bits(jnp.asarray(r), jnp.asarray(c), m, n)
    ref = np.zeros((m, n), bool)
    ref[r, c] = True
    bb = np.asarray(bits)
    got = np.zeros((m, n), bool)
    for j in range(n):
        got[:, j] = (bb[:, j >> 5] >> (j & 31)) & 1
    np.testing.assert_array_equal(got, ref)


def test_csr_csc_bitmask_views(rng):
    d = (rng.random((20, 45)) < 0.25).astype(np.float32)
    t = SpTuples.from_dense(d, capacity=300)
    rb = np.asarray(CSR.from_tuples(t).to_bitmask())
    cb = np.asarray(CSC.from_tuples(t).to_bitmask())
    for i in range(20):
        for j in range(45):
            assert bool((rb[i, j >> 5] >> (j & 31)) & 1) == bool(d[i, j])
            assert bool((cb[j, i >> 5] >> (i & 31)) & 1) == bool(d[i, j])


def test_dense_support_nnz_padding(rng):
    d = (rng.random((32, 48)) < 0.3).astype(np.float32)
    assert int(dense_support_nnz(jnp.asarray(d), 0.0, 30, 40)) == int(
        (d[:30, :40] != 0).sum()
    )


def test_distributed_edge_harvest_tc_matches_masked(rng):
    """ISSUE 3 satellite: distributed bit-packed edge-harvest TC vs the
    masked-SpGEMM count (the sparse path), duplicate entries included."""
    from combblas_tpu.models.tc import triangle_count

    # n chosen so local_cols (n/2 on the 2x2 grid) is a multiple of 32 —
    # the distributed tier's word-aligned tile-concat requirement
    n = 128
    m = rng.random((n, n)) < 0.08
    m = np.triu(m, 1)
    m = m | m.T
    r0, c0 = np.nonzero(m)
    dup = rng.choice(len(r0), 30)
    r = np.concatenate([r0, r0[dup]])
    c = np.concatenate([c0, c0[dup]])
    grid = Grid.make(2, 2)
    A = SpParMat.from_global_coo(
        grid, r, c, np.ones(len(r), np.float32), n, n
    )
    Au = SpParMat.from_global_coo(
        grid, r0, c0, np.ones(len(r0), np.float32), n, n
    )
    want = triangle_count(Au, kernel="sparse")  # masked-SpGEMM count
    assert triangle_count(A, kernel="edgeharvest") == want
    assert triangle_count(A) == want  # auto routes to the tier
    ref = int(np.trace(np.linalg.matrix_power(m.astype(np.int64), 3)) // 6)
    assert want == ref


def test_distributed_edge_harvest_tc_ceil_blocked(rng):
    """n % local_rows != 0 (ceil-blocking over-cover): the n-sentinel
    minus the last block's offset lands INSIDE the local range — the
    kernel must drop padded/dup/loop slots explicitly, not by sentinel
    arithmetic (regression: corrupted bitmask via scatter-add carry)."""
    from combblas_tpu.models.tc import triangle_count

    n = 127  # 2x2 grid → lr = lc = 64 (word-aligned), p*lr = 128 > n
    m = rng.random((n, n)) < 0.1
    m = np.triu(m, 1)
    m = m | m.T
    r0, c0 = np.nonzero(m)
    # duplicates AND a self-loop stored on the last grid row
    r = np.concatenate([r0, r0[:20], [n - 1]])
    c = np.concatenate([c0, c0[:20], [n - 1]])
    grid = Grid.make(2, 2)
    A = SpParMat.from_global_coo(
        grid, r, c, np.ones(len(r), np.float32), n, n
    )
    ref = int(np.trace(np.linalg.matrix_power(m.astype(np.int64), 3)) // 6)
    assert triangle_count(A, kernel="edgeharvest") == ref


def test_default_block_rows_bounds():
    br = default_block_rows(1 << 16, 1 << 16)
    assert 1 <= br <= 1 << 16
    assert -(-(1 << 16) // br) <= 33  # ~WINDOWED_MAX_BLOCKS programs
    assert default_block_rows(5, 7) >= 5  # tiny tiles: one block


# --- 2D B-column-windowed dot backend (ISSUE 5 tentpole) --------------------


@pytest.mark.parametrize(
    "p,srname",
    [
        (1, "plus_times"),
        (1, "min_plus"),
        # (1, max_min) joined the slow set in round 12 (tier-1 budget):
        # same single-device tropical dot2d path as (1, min_plus)
        pytest.param(1, "max_min", marks=pytest.mark.slow),
        (2, "plus_times"),
        # the distributed tropical (Pallas-matmul) cases cost ~20 s each
        # on the 1-core mesh; the tropical dot2d path stays tier-1 at
        # p=1 and the 2x2 fused kernel at plus_times, so these two run
        # under -m slow
        pytest.param(2, "min_plus", marks=pytest.mark.slow),
        pytest.param(2, "max_min", marks=pytest.mark.slow),
    ],
)
def test_windowed_dot_2d_matches_esc_across_semirings(rng, srname, p):
    """Forced dot-backend 2D windowed == ESC golden across semirings,
    DUPLICATE-ENTRY COO inputs included: ``densify_combine`` folds
    repeats with the semiring combiner, so the dot backend no longer
    carries the mxu tier's unique-entries precondition.  p=1 exercises
    the per-block local fast path, p=2 the fused shard_map kernel."""
    sr = {"plus_times": PLUS_TIMES, "min_plus": MIN_PLUS,
          "max_min": MAX_MIN}[srname]
    grid = Grid.make(p, p)
    m, k, n = 64, 48, 80
    ra, ca, va = coo(rng, m, k, 500, dup_frac=0.2)
    rb, cb, vb = coo(rng, k, n, 600, dup_frac=0.2)
    A = SpParMat.from_global_coo(grid, ra, ca, va, m, k)
    B = SpParMat.from_global_coo(grid, rb, cb, vb, k, n)
    C_esc = spgemm(sr, A, B)
    C_win = spgemm_auto(
        sr, A, B, tier="windowed", backend="dot",
        block_rows=16, block_cols=32, interpret=True,
    )
    np.testing.assert_allclose(
        dense_of(C_win), dense_of(C_esc), rtol=1e-5, atol=1e-6
    )
    assert host_nnz(C_win) == host_nnz(C_esc)


def test_windowed_dot_2d_empty_windows_skipped(rng):
    """A confined to rows [0, 8), B confined to cols [0, 16): every 2D
    window except (0, 0) is symbolically empty — the plan must skip
    them (never densified, never matmul'd, never scanned) and the
    result still matches ESC."""
    grid = Grid.make(1, 1)
    m = 64
    ra = rng.integers(0, 8, 120).astype(np.int64)
    ca = rng.integers(0, m, 120).astype(np.int64)
    A = SpParMat.from_global_coo(
        grid, ra, ca, np.ones(120, np.float32), m, m
    )
    rb = rng.integers(0, m, 200).astype(np.int64)
    cb = rng.integers(0, 16, 200).astype(np.int64)
    B = SpParMat.from_global_coo(
        grid, rb, cb, np.ones(200, np.float32), m, m
    )
    pair = np.asarray(
        jax.device_get(summa_window_flops_pair(A, B, 8, 16, chunk_w=8))
    )
    fc, oc, skip = windowed_plan_2d(pair[0], pair[1], 8, 16, m, m)
    assert not skip[0][0]
    assert all(
        skip[g][h]
        for g in range(8) for h in range(4) if (g, h) != (0, 0)
    ), skip
    panel_cap = panel_cap_from_bnnz(
        jax.device_get(summa_window_bnnz(B, 16)), int(B.capacity)
    )
    C_win, overflow = summa_spgemm_windowed(
        PLUS_TIMES, A, B, block_rows=8, flop_caps=fc, out_caps=oc,
        skip=skip, backend="dot", block_cols=16, panel_cap=panel_cap,
    )
    assert int(overflow) <= 0
    C_esc = spgemm(PLUS_TIMES, A, B)
    np.testing.assert_allclose(
        dense_of(C_win), dense_of(C_esc), rtol=1e-5, atol=1e-6
    )


def test_window_flops_host_matches_device_2d(rng):
    """Host==device agreement of the 2D symbolic plan inputs: the
    per-(row block, col window) flop pair and the per-window B nnz."""
    grid = Grid.make(2, 2)
    m, k, n = 64, 48, 80
    ra, ca, va = coo(rng, m, k, 400, dup_frac=0.1)
    rb, cb, vb = coo(rng, k, n, 500, dup_frac=0.1)
    A = SpParMat.from_global_coo(grid, ra, ca, va, m, k)
    B = SpParMat.from_global_coo(grid, rb, cb, vb, k, n)
    dev = np.asarray(
        jax.device_get(summa_window_flops_pair(A, B, 8, 16, chunk_w=8))
    )
    host_pad = summa_window_flops_host(
        grid, ra, ca, rb, cb, m, k, n, 8, 16, chunk_w=8
    )
    host_true = summa_window_flops_host(
        grid, ra, ca, rb, cb, m, k, n, 8, 16, chunk_w=0
    )
    np.testing.assert_array_equal(
        dev[0].astype(np.int64), host_pad.astype(np.int64)
    )
    np.testing.assert_array_equal(
        dev[1].astype(np.int64), host_true.astype(np.int64)
    )
    bnnz_dev = np.asarray(jax.device_get(summa_window_bnnz(B, 16)))
    bnnz_host = summa_window_bnnz_host(grid, rb, cb, k, n, 16)
    np.testing.assert_array_equal(
        bnnz_dev.astype(np.int64), bnnz_host.astype(np.int64)
    )


def test_densify_combine_absorbs_duplicates(rng):
    """densify_combine == dedup-then-densify under each combiner."""
    m, n = 20, 30
    r = rng.integers(0, m, 80).astype(np.int32)
    c = rng.integers(0, n, 80).astype(np.int32)
    v = (rng.random(80) + 0.5).astype(np.float32)
    r = np.concatenate([r, r[:30]])
    c = np.concatenate([c, c[:30]])
    v = np.concatenate([v, (rng.random(30) + 0.5).astype(np.float32)])
    t = SpTuples.from_coo(r, c, v, m, n, capacity=128)
    for sr, fold, init in (
        (PLUS_TIMES, np.add, 0.0),
        (MIN_PLUS, np.minimum, np.inf),
        (MAX_MIN, np.maximum, -np.inf),
    ):
        ref = np.full((32, 32), init, np.float32)
        for ri, ci, vi in zip(r, c, v):
            ref[ri, ci] = fold(ref[ri, ci], vi)
        got = np.asarray(jax.device_get(densify_combine(sr, t, 32, 32)))
        np.testing.assert_allclose(got, ref, rtol=1e-6)


def test_mxu_unique_precondition_guard(rng):
    """ISSUE 5 satellite: the router detects duplicate-entry tiles and
    demotes mxu to a duplicate-absorbing rung instead of silently
    producing wrong results; ``assume_unique`` skips the check."""
    grid = Grid.make(1, 1)
    m = 48
    ra, ca, va = coo(rng, m, m, 300, dup_frac=0.2)  # repeats guaranteed
    A = SpParMat.from_global_coo(grid, ra, ca, va, m, m)
    tier = choose_spgemm_tier(PLUS_TIMES, A, A, backend="scatter")
    assert tier in ("windowed", "scan")
    assert choose_spgemm_tier(
        PLUS_TIMES, A, A, backend="scatter", assume_unique=True
    ) == "mxu"
    # unique input still routes mxu
    key, idx = np.unique(ra * m + ca, return_index=True)
    Au = SpParMat.from_global_coo(
        grid, ra[idx], ca[idx], va[idx], m, m
    )
    assert choose_spgemm_tier(
        PLUS_TIMES, Au, Au, backend="scatter"
    ) == "mxu"
    # the auto-routed product on the duplicate input stays EXACT (the
    # fallback rung absorbs repeats), and the demotion is observable
    obs.enable(install_hooks=False)
    try:
        obs.reset()
        C = spgemm_auto(PLUS_TIMES, A, A, backend="scatter")
        assert obs.registry.get_counter(
            "spgemm.auto.dedup_fallback", sr="plus_times"
        ) == 1
        assert obs.registry.get_counter(
            "spgemm.auto.tier", tier="mxu", sr="plus_times"
        ) == 0
    finally:
        obs.disable()
        obs.reset()
    ref = spgemm(PLUS_TIMES, A, A)
    np.testing.assert_allclose(
        dense_of(C), dense_of(ref), rtol=1e-5, atol=1e-6
    )


def test_router_routes_midscale_to_windowed_dot(rng, monkeypatch):
    """ISSUE 5 acceptance: a product whose B tile exceeds the mxu
    envelope auto-selects windowed with backend='dot' (it fell through
    to scan before), and the 2D run bounds the stage operand by the
    column window (panel_cells gauge ≤ envelope) while agreeing with
    the ESC golden."""
    import combblas_tpu.parallel.spgemm as psp

    # shrink the mxu envelope so a 96-dim tile is "mid-scale" for the
    # test (the real envelope needs scale-14 tiles — benchmark turf)
    monkeypatch.setattr(psp, "MXU_MAX_TILE_DIM", 32)
    grid = Grid.make(1, 1)
    m = 96
    ra, ca, va = coo(rng, m, m, 2000)
    A = SpParMat.from_global_coo(grid, ra, ca, va, m, m)
    assert psp.choose_spgemm_tier(
        PLUS_TIMES, A, A, backend="dot"
    ) == "windowed"
    obs.enable(install_hooks=False)
    try:
        obs.reset()
        C = spgemm_auto(
            PLUS_TIMES, A, A, backend="dot", block_rows=32,
            block_cols=32,
        )
        assert obs.registry.get_counter(
            "spgemm.auto.tier", tier="windowed", sr="plus_times"
        ) == 1
        panel_cells = obs.registry.get_gauge(
            "spgemm.windowed.panel_cells"
        )
        assert panel_cells == _pad128(m) * _pad128(32)
        assert panel_cells <= WINDOWED_MAX_PANEL_CELLS
        assert obs.registry.get_gauge(
            "spgemm.windowed.col_windows"
        ) == 3
        assert obs.registry.get_counter(
            "spgemm.windowed.col_windows_skipped"
        ) >= 0
        assert obs.registry.get_gauge(
            "spgemm.windowed.window_density"
        ) > 0
    finally:
        obs.disable()
        obs.reset()
    ref = spgemm(PLUS_TIMES, A, A)
    np.testing.assert_allclose(
        dense_of(C), dense_of(ref), rtol=1e-5, atol=1e-6
    )


def test_windowed_dot_panel_envelope():
    """The stage-operand memory bound: default_block_cols keeps one
    dense B panel within WINDOWED_MAX_PANEL_CELLS and the unrolled
    window count bounded; at mid scale the panel is a strict fraction
    of B's full dense tile width (the quantity that used to force the
    router to scan on TPU)."""
    for lrb, lcb in [(1 << 16, 1 << 16), (16384, 16384), (8192, 65536)]:
        bc = default_block_cols(lrb, lcb)
        pk, pwin = _pad128(lrb), _pad128(bc)
        assert 1 <= bc <= max(lcb, 1)
        assert -(-lcb // bc) <= WINDOWED_MAX_COL_WINDOWS
        if pk * 512 <= WINDOWED_MAX_PANEL_CELLS:
            assert pk * pwin <= WINDOWED_MAX_PANEL_CELLS, (lrb, lcb)
    # scale-16 square tile: the panel is ≥16x narrower than dense B
    bc = default_block_cols(1 << 16, 1 << 16)
    assert _pad128(bc) * 16 <= _pad128(1 << 16)
    # tiny tiles degenerate to one window
    assert default_block_cols(64, 80) == 80
    # extreme region pad(k)·lcB > 32·PANEL: the window-count floor
    # would exceed the envelope, so the router gates it to scan (only
    # forced calls may trade memory for program size there)
    assert not dot_panel_feasible(1 << 17, 1 << 16)
    assert dot_panel_feasible(1 << 17)  # a 512-wide window alone fits
    assert choose_tier_from_counts(
        PLUS_TIMES, 1 << 17, (1 << 17) * (1 << 16), 1, 1e12, "dot",
        k_dim=1 << 17, n_dim=1 << 16,
    ) == "scan"


# --- round 9: pipelined carousel, packed launches, 3D windowed --------------


@pytest.mark.parametrize("srname", ["plus_times", "min_plus", "max_min"])
def test_pipelined_carousel_matches_unpipelined(rng, srname):
    """ISSUE 7 satellite: the stage-pipelined windowed carousel
    (ring=True, pipeline=True) and the serial-chain control
    (pipeline=False) both agree exactly with the ESC golden on a 2x2
    grid with DUPLICATE-entry COO input — the overlap restructure is a
    schedule change, never a semantics change."""
    from combblas_tpu.parallel.spgemm import spgemm_windowed

    sr = {"plus_times": PLUS_TIMES, "min_plus": MIN_PLUS,
          "max_min": MAX_MIN}[srname]
    grid = Grid.make(2, 2)
    m, k, n = 64, 48, 80
    ra, ca, va = coo(rng, m, k, 500, dup_frac=0.2)
    rb, cb, vb = coo(rng, k, n, 600, dup_frac=0.2)
    A = SpParMat.from_global_coo(grid, ra, ca, va, m, k)
    B = SpParMat.from_global_coo(grid, rb, cb, vb, k, n)
    ref = dense_of(spgemm(sr, A, B))
    for pipe in (True, False):
        C = spgemm_windowed(
            sr, A, B, block_rows=16, backend="scatter",
            ring=True, pipeline=pipe,
        )
        np.testing.assert_allclose(
            dense_of(C), ref, rtol=1e-5, atol=1e-6
        )


def test_pipelined_carousel_dot2d_and_esc_ring(rng):
    """The carousel restructure covers every ring path: the 2D dot
    windowed carousel and the (now pipelined) ESC ring both match the
    gathered-schedule golden."""
    from combblas_tpu.parallel.spgemm import (
        spgemm_windowed,
        summa_capacities,
        summa_spgemm,
    )

    grid = Grid.make(2, 2)
    m = 96
    ra, ca, va = coo(rng, m, m, 800, dup_frac=0.15)
    A = SpParMat.from_global_coo(grid, ra, ca, va, m, m)
    ref = dense_of(spgemm(PLUS_TIMES, A, A))
    for pipe in (True, False):
        C = spgemm_windowed(
            PLUS_TIMES, A, A, block_rows=16, block_cols=32,
            backend="dot", ring=True, pipeline=pipe,
        )
        np.testing.assert_allclose(
            dense_of(C), ref, rtol=1e-5, atol=1e-6
        )
    fcap, ocap = summa_capacities(A, A)
    C = summa_spgemm(
        PLUS_TIMES, A, A, flop_capacity=fcap, out_capacity=ocap,
        ring=True,
    )
    np.testing.assert_allclose(dense_of(C), ref, rtol=1e-5, atol=1e-6)


def test_packed_plan_equals_skiplist(rng):
    """ISSUE 7 satellite: the packed launch list is exactly the
    complement of the skip list, and a packed (skip-listed) run emits
    the SAME output as the full-grid run with no skips — packing elides
    launches, never results."""
    from combblas_tpu.parallel.spgemm import (
        _live_windows_by_block,
        packed_windows,
        packed_windows_2d,
        panel_cap_from_bnnz,
        summa_window_bnnz,
        summa_window_flops_pair,
    )

    grid = Grid.make(1, 1)
    m = 64
    # A confined to rows [0, 24): the lower row blocks are empty
    ra = rng.integers(0, 24, 200).astype(np.int64)
    ca = rng.integers(0, m, 200).astype(np.int64)
    A = SpParMat.from_global_coo(
        grid, ra, ca, np.ones(200, np.float32), m, m
    )
    rb = rng.integers(0, m, 300).astype(np.int64)
    cb = rng.integers(0, 32, 300).astype(np.int64)  # right windows empty
    B = SpParMat.from_global_coo(
        grid, rb, cb, np.ones(300, np.float32), m, m
    )
    pair = np.asarray(
        jax.device_get(summa_window_flops_pair(A, B, 8, 16, chunk_w=8))
    )
    fc, oc, skip = windowed_plan_2d(pair[0], pair[1], 8, 16, m, m)
    pairs = packed_windows_2d(skip)
    # the packed list IS the complement of the skip list, in kernel order
    assert pairs == tuple(
        (g, h) for g in range(len(skip)) for h in range(len(skip[0]))
        if not skip[g][h]
    )
    assert 0 < len(pairs) < len(skip) * len(skip[0])
    assert packed_windows(tuple(all(row) for row in skip)) == tuple(
        g for g, hs in _live_windows_by_block(skip)
    )
    panel_cap = panel_cap_from_bnnz(
        jax.device_get(summa_window_bnnz(B, 16)), int(B.capacity)
    )
    no_skip = tuple((False,) * len(row) for row in skip)
    outs = {}
    for name, sk in (("packed", skip), ("full", no_skip)):
        C, overflow = summa_spgemm_windowed(
            PLUS_TIMES, A, B, block_rows=8, flop_caps=fc, out_caps=oc,
            skip=sk, backend="dot", block_cols=16, panel_cap=panel_cap,
        )
        assert int(overflow) <= 0
        outs[name] = dense_of(C)
    np.testing.assert_array_equal(outs["packed"], outs["full"])
    np.testing.assert_allclose(
        outs["packed"], dense_of(spgemm(PLUS_TIMES, A, B)),
        rtol=1e-5, atol=1e-6,
    )


def test_blocked_dispatch_matches_fused(rng):
    """ISSUE 7: the blocked-dispatch distributed windowed tier (one
    small shard_map program per occupied row block — the live-set
    bound that fits scale-18 tiles in RAM) emits the same result as
    the fused kernel and the ESC golden, duplicate entries included."""
    from combblas_tpu.parallel.spgemm import (
        WINDOWED_CHUNK_W,
        summa_rowblock_flops_host,
        summa_spgemm_windowed_blocked,
    )

    grid = Grid.make(2, 2)
    m = 96
    ra, ca, va = coo(rng, m, m, 800, dup_frac=0.15)
    # rows confined to [0, 32): the trailing row blocks are empty on
    # EVERY grid row, so the packed host loop's skip path is exercised
    ra = ra % 32
    A = SpParMat.from_global_coo(grid, ra, ca, va, m, m)
    pb = summa_rowblock_flops_host(
        grid, ra, ca, ra, ca, m, m, m, 16, chunk_w=WINDOWED_CHUNK_W
    )
    pt = summa_rowblock_flops_host(
        grid, ra, ca, ra, ca, m, m, m, 16, chunk_w=0
    )
    fc, oc, skip = windowed_plan(pb, pt, 16, A.local_rows, A.local_cols)
    assert any(skip)
    C, over = summa_spgemm_windowed_blocked(
        PLUS_TIMES, A, A, block_rows=16, flop_caps=fc, out_caps=oc,
        skip=skip, chunk_w=WINDOWED_CHUNK_W,
    )
    assert int(over) <= 0
    C_f, over_f = summa_spgemm_windowed(
        PLUS_TIMES, A, A, block_rows=16, flop_caps=fc, out_caps=oc,
        skip=skip, backend="scatter", chunk_w=WINDOWED_CHUNK_W,
    )
    assert int(over_f) <= 0
    np.testing.assert_array_equal(dense_of(C), dense_of(C_f))
    np.testing.assert_allclose(
        dense_of(C), dense_of(spgemm(PLUS_TIMES, A, A)),
        rtol=1e-5, atol=1e-6,
    )
    assert host_nnz(C) == host_nnz(C_f)


@pytest.mark.parametrize("backend", [
    "dot",
    # the scatter backend re-runs the whole 2D->3D->2D route for a
    # second accumulate kernel (~6 s of compiles); the dot case keeps
    # the routing/conversion coverage in tier-1 (round 17 budget) and
    # scatter-vs-dot agreement rides the 2D/3D kernel suites
    pytest.param("scatter", marks=pytest.mark.slow),
])
def test_spgemm_auto_3d_matches_2d(rng, backend):
    """ISSUE 7 satellite: the windowed3d route (2D → layered 3D mesh →
    per-layer windowed SUMMA → fiber reduce → back to 2D) agrees
    BIT-EXACTLY with the 2D spgemm_auto product on the 8-device mesh
    (0/1 adjacency counts are integers)."""
    from combblas_tpu.parallel.mesh3d import Grid3D

    grid = Grid.make(2, 2)
    g3 = Grid3D.make(2, 2, 2)
    m = 64
    ra, ca, _ = coo(rng, m, m, 900, dup_frac=0.1)
    A = SpParMat.from_global_coo(
        grid, ra, ca, np.ones(len(ra), np.float32), m, m
    )
    ref = spgemm_auto(PLUS_TIMES, A, A, tier="windowed", block_rows=16)
    C = spgemm_auto(
        PLUS_TIMES, A, A, tier="windowed3d", grid3=g3,
        backend=backend, block_rows=16, block_cols=16,
    )
    np.testing.assert_array_equal(dense_of(C), dense_of(ref))
    assert host_nnz(C) == host_nnz(ref)


def test_router_upgrades_windowed_to_3d(rng, monkeypatch):
    """choose_spgemm_tier upgrades a 2D-windowed-bound product to
    windowed3d when a COMPATIBLE layered mesh is offered — and keeps
    the 2D tier when the layout does not divide over the layers."""
    import combblas_tpu.parallel.spgemm as psp
    from combblas_tpu.parallel.mesh3d import Grid3D, summa3d_compatible

    monkeypatch.setattr(psp, "MXU_MAX_TILE_DIM", 32)
    grid = Grid.make(1, 1)
    m = 96
    ra, ca, va = coo(rng, m, m, 2000)
    A = SpParMat.from_global_coo(grid, ra, ca, va, m, m)
    g3 = Grid3D.make(2, 2, 2)
    assert psp.choose_spgemm_tier(
        PLUS_TIMES, A, A, backend="scatter"
    ) == "windowed"
    assert psp.choose_spgemm_tier(
        PLUS_TIMES, A, A, backend="scatter", grid3=g3
    ) == "windowed3d"
    # an odd dimension cannot col-split over 2 layers: router stays 2D
    assert not summa3d_compatible(g3, 98, 98, 98)
    ra2 = np.minimum(ra, 97)
    ca2 = np.minimum(ca, 97)
    A2 = SpParMat.from_global_coo(grid, ra2, ca2, va, 98, 98)
    assert psp.choose_spgemm_tier(
        PLUS_TIMES, A2, A2, backend="scatter", grid3=g3
    ) == "windowed"


def test_support_oracle_window_counts_and_seeding(rng):
    """``support_window_counts`` returns the exact per-window output
    nnz, and ``spgemm_windowed(oracle=True)`` (dot backend) stays exact
    with the tightened caps."""
    da = (rng.random((64, 48)) < 0.15).astype(np.float32)
    db = (rng.random((48, 64)) < 0.15).astype(np.float32)
    a = SpTuples.from_dense(da, capacity=600)
    b = SpTuples.from_dense(db, capacity=600)
    bits, _ = spgemm_support_bits(a, b, row_block=16)
    cnt = np.asarray(
        jax.device_get(support_window_counts(bits, 16, 32, 64, 64))
    )
    P = (da @ db) > 0
    for g in range(4):
        for h in range(2):
            want = int(
                P[g * 16:(g + 1) * 16, h * 32:(h + 1) * 32].sum()
            )
            assert cnt[g, h] == want, (g, h)
    grid = Grid.make(1, 1)
    m = 64
    ra, ca, va = coo(rng, m, m, 700)
    A = SpParMat.from_global_coo(grid, ra, ca, va, m, m)
    ref = spgemm(PLUS_TIMES, A, A)
    C = spgemm_windowed(
        PLUS_TIMES, A, A, block_rows=32, block_cols=32, backend="dot",
        oracle=True,
    )
    np.testing.assert_allclose(
        dense_of(C), dense_of(ref), rtol=1e-5, atol=1e-6
    )
    assert host_nnz(C) == host_nnz(ref)
