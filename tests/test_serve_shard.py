"""Cross-host sharded serving (round 20, ISSUE 18): row-slab
partitioning, router-driven bulk-synchronous hop loops, the two-phase
per-slice WAL write protocol under a VECTOR checkpoint frontier, and
one-slice quarantine/respawn recovery.

The load-bearing properties:

* BIT-EXACTNESS — a sharded engine answers bfs/sssp identically (same
  parents, same distances, same ``batch_niter``) to the unsharded
  engine it partitions, including after writes and slice deaths;
* CRASH-RECOVERY on the vector frontier — for a crash at every
  append/commit/checkpoint boundary (frontier-skewing partial
  checkpoints and a torn final WAL line included),
  ``ShardedEngine.recover`` reassembles a ``to_host_coo()`` equal to a
  never-crashed engine that applied every fully-appended batch.

Tier-1 runs the local-mode (in-process slices) representatives; the
full boundary sweep and the subprocess SIGKILL/respawn scenario are
``slow`` (the BENCH_SERVE_SHARD gate is their measured twin).
"""

import os

import numpy as np
import pytest

from combblas_tpu.dynamic import DeltaBatch
from combblas_tpu.parallel.grid import Grid
from combblas_tpu.serve import GraphEngine, ShardedEngine
from combblas_tpu.serve.shard import ShardSpec, plan_partition, shard_coo
from combblas_tpu.tuner import store as tstore

N = 40


@pytest.fixture(autouse=True)
def _fresh_store_singleton():
    tstore._reset_for_tests()
    yield
    tstore._reset_for_tests()


def _coo(seed, n=N, m=170):
    r = np.random.default_rng(seed)
    return r.integers(0, n, m), r.integers(0, n, m)


def _absent_pairs(rows, cols, k, n=N):
    present = set(zip(rows.tolist(), cols.tolist()))
    out = []
    for i in range(n):
        for j in range(n):
            if i != j and (i, j) not in present:
                out.append((i, j))
                if len(out) >= k:
                    return out
    return out


def _assert_coo_equal(a, b):
    ra, ca, wa = a
    rb, cb, wb = b
    np.testing.assert_array_equal(np.asarray(ra), np.asarray(rb))
    np.testing.assert_array_equal(np.asarray(ca), np.asarray(cb))
    if wa is not None or wb is not None:
        np.testing.assert_array_equal(np.asarray(wa), np.asarray(wb))


# --- partition planning (pure) ----------------------------------------------


def test_plan_partition_balanced_contiguous():
    """Slabs are contiguous, cover [0, n) exactly, and differ by at
    most one row (the first ``n % p`` slabs take the remainder)."""
    spec = plan_partition(10, 3)
    assert spec.bounds == ((0, 4), (4, 7), (7, 10))
    assert spec.nslices == 3 and spec.ncols == 10
    sizes = [r1 - r0 for r0, r1 in spec.bounds]
    assert max(sizes) - min(sizes) <= 1
    # owner_of maps every row to the slab containing it
    for row in range(10):
        i = spec.owner_of(row)
        r0, r1 = spec.bounds[i]
        assert r0 <= row < r1
    # degenerate edges: one slice works; p > n (an empty slab would
    # serve nothing) and p < 1 are rejected up front
    assert plan_partition(5, 1).bounds == ((0, 5),)
    with pytest.raises(ValueError, match="nslices"):
        plan_partition(3, 8)
    with pytest.raises(ValueError, match="nslices"):
        plan_partition(3, 0)


def test_shard_coo_translates_rows_keeps_cols_global():
    rows = np.array([0, 3, 7, 9, 4])
    cols = np.array([9, 1, 2, 0, 4])
    w = np.array([1.0, 2.0, 3.0, 4.0, 5.0], np.float32)
    spec = plan_partition(10, 2)  # slabs [0,5) and [5,10)
    r0, c0, w0 = shard_coo(spec, 0, rows, cols, w)
    r1, c1, w1 = shard_coo(spec, 1, rows, cols, w)
    np.testing.assert_array_equal(np.sort(r0), [0, 3, 4])
    np.testing.assert_array_equal(np.sort(r1), [2, 4])  # 7-5, 9-5
    # columns stay global (hop operands are full-width vectors)
    assert set(c0.tolist()) == {9, 1, 4}
    assert set(c1.tolist()) == {2, 0}
    assert len(w0) == 3 and len(w1) == 2
    # unweighted passes weights through as None
    _, _, wn = shard_coo(spec, 0, rows, cols, None)
    assert wn is None
    # every edge lands in exactly one slab
    assert len(r0) + len(r1) == len(rows)


def test_sharded_kinds_validated_up_front(tmp_path):
    rows, cols = _coo(3)
    with pytest.raises(ValueError, match="do not decompose"):
        ShardedEngine.build(rows, cols, nrows=N, nslices=2,
                            kinds=("bfs", "mcl"),
                            home=str(tmp_path / "a"))
    with pytest.raises(ValueError, match="symmetric"):
        ShardedEngine.build(
            rows, cols, nrows=N, nslices=2, kinds=("propagate",),
            features=np.ones((N, 3), np.float32), symmetric=False,
            home=str(tmp_path / "b"),
        )
    with pytest.raises(ValueError, match="features"):
        ShardedEngine.build(rows, cols, nrows=N, nslices=2,
                            kinds=("propagate",), symmetric=True,
                            home=str(tmp_path / "c"))


# --- the local-mode tier-1 representative ------------------------------------


def test_local_bit_exact_write_kill_heal_recover(tmp_path):
    """THE fast representative of the sharded serving arc: a 2-slice
    local-mode engine answers bfs bit-exactly vs the unsharded build,
    a two-phase write lands on both (vector frontier advances in
    lockstep), a killed slice heals mid-execute via whole-batch
    replay, and a full service reboot from the home reassembles the
    identical global COO."""
    home = str(tmp_path / "home")
    rows, cols = _coo(7)
    grid = Grid.make(1, 1)
    eng = GraphEngine.from_coo(grid, rows, cols, N, kinds=("bfs",),
                               keep_coo=True)
    sh = ShardedEngine.build(rows, cols, nrows=N, nslices=2,
                             kinds=("bfs",), home=home, mode="local",
                             warmup=False)
    srcs = np.array([0, 5, 17], np.int32)
    ref = eng.execute("bfs", srcs)
    got = sh.execute("bfs", srcs)
    np.testing.assert_array_equal(np.asarray(ref["parents"]),
                                  got["parents"])
    assert int(ref["batch_niter"]) == int(got["batch_niter"])
    # per-slice residency strictly under the whole graph's
    assert max(sh.version.device_bytes_per_slice) < (
        eng.version.device_bytes()
    )
    # two-phase write: both engines apply the same batch
    (a, b), (a2, b2) = _absent_pairs(rows, cols, 2)
    batch = DeltaBatch.from_ops(
        [("insert", a, b), ("insert", b, a)], start_seq=0
    )
    eng.swap(eng.apply_delta(batch))
    v = sh.apply_delta(batch)
    assert v.frontier == [1, 1]  # every slice stamped, no lag
    assert v.wal_seq == 1
    sh.swap(v)
    got = sh.execute("bfs", srcs)
    ref = eng.execute("bfs", srcs)
    np.testing.assert_array_equal(np.asarray(ref["parents"]),
                                  got["parents"])
    # kill one slice: the next execute heals (respawn from slab
    # snapshot + WAL) and the answer is still bit-exact — the OTHER
    # slice is untouched (recover-one-slice)
    survivor = sh.slices[1]
    sh.slices[0].kill()
    got = sh.execute("bfs", srcs)
    np.testing.assert_array_equal(np.asarray(ref["parents"]),
                                  got["parents"])
    assert sh.replacements == 1
    assert sh.slices[1] is survivor
    # a post-heal write keeps the lineage moving
    batch2 = DeltaBatch.from_ops(
        [("insert", a2, b2), ("insert", b2, a2)], start_seq=2
    )
    sh.swap(sh.apply_delta(batch2))
    coo_before = sh.to_host_coo()
    # whole-service reboot from the files alone
    sh.close()
    sh2 = ShardedEngine.recover(home, mode="local")
    assert sh2.version.frontier == [3, 3]
    _assert_coo_equal(coo_before, sh2.to_host_coo())
    got = sh2.execute("bfs", srcs)
    assert got["parents"].shape == np.asarray(ref["parents"]).shape
    sh2.close()


# --- crash-at-every-boundary recovery on the vector frontier -----------------


def _mk_batches(rows, cols, k):
    pairs = _absent_pairs(rows, cols, k)
    return [
        DeltaBatch.from_ops(
            [("insert", a, b), ("insert", b, a)], start_seq=2 * i
        )
        for i, (a, b) in enumerate(pairs)
    ]


def _wal_begin_payload(batch):
    return {
        "first_seq": int(batch.first_seq),
        "rows": np.asarray(batch.rows, np.int64),
        "cols": np.asarray(batch.cols, np.int64),
        "vals": np.asarray(batch.vals, np.float32),
        "ops": np.asarray(batch.ops, np.int8),
    }


def _crash_scenario(tmp_path, tag, n_commit, n_append_only,
                    commit_partial, ckpt, torn):
    """Build a 2-slice local service, fully apply ``n_commit``
    batches, durably APPEND (phase 1 only — crash before phase 2)
    ``n_append_only`` more, optionally commit the first appended batch
    on slice 0 only (``commit_partial`` — the mid-_commit_all crash),
    checkpoint one slice mid-stream (``ckpt = (slice, after_batch)`` —
    the vector-frontier skew), optionally tear a partial final line
    onto slice 0's log — then crash (kill, no close) and recover.

    Every fully-appended batch is durable on every slice, so the
    recovered engine must be ``to_host_coo``-equal to a NEVER-CRASHED
    twin that applied them all; a torn line was never acknowledged and
    must vanish."""
    home = str(tmp_path / f"crash-{tag}")
    rows, cols = _coo(11)
    batches = _mk_batches(rows, cols, n_commit + n_append_only)
    sh = ShardedEngine.build(rows, cols, nrows=N, nslices=2,
                             kinds=("bfs",), home=home, mode="local",
                             warmup=False)
    for k, batch in enumerate(batches):
        if k < n_commit:
            sh.swap(sh.apply_delta(batch))
        else:
            for sl in sh.slices:  # phase 1 everywhere, then crash
                sl.call("wal_begin", _wal_begin_payload(batch))
            if commit_partial and k == n_commit:
                payload = _wal_begin_payload(batch)
                payload["last_seq"] = int(batch.last_seq)
                sh.slices[0].call("wal_commit", payload)
        if ckpt is not None and k + 1 == ckpt[1]:
            sh.slices[ckpt[0]].call("checkpoint_now",
                                    {"reason": "test"})
    if torn:
        wal_path = os.path.join(home, "slice0", "wal.jsonl")
        assert os.path.exists(wal_path)
        with open(wal_path, "a") as f:
            f.write('{"v": "combblas_tpu.wal/v1", "first_se')
    for sl in sh.slices:  # CRASH: the files are all that survives
        sl.kill()
    recovered = ShardedEngine.recover(home, mode="local")
    # the never-crashed twin: every fully-appended batch applied
    ref = ShardedEngine.build(rows, cols, nrows=N, nslices=2,
                              kinds=("bfs",),
                              home=str(tmp_path / f"ref-{tag}"),
                              mode="local", warmup=False)
    for batch in batches:
        ref.swap(ref.apply_delta(batch))
    _assert_coo_equal(recovered.to_host_coo(), ref.to_host_coo())
    # the vector frontier re-converged at the last appended seq
    last = int(batches[-1].last_seq) if batches else -1
    assert recovered.version.frontier == [last, last]
    recovered.close()
    ref.close()


def test_crash_recovery_fast_representative(tmp_path):
    """One tier-1 scenario covering every boundary class at once:
    committed prefix, appended-uncommitted tail, a partial commit on
    one slice, a one-slice checkpoint (frontier skew) and the torn
    final line."""
    _crash_scenario(tmp_path, "fast", n_commit=2, n_append_only=1,
                    commit_partial=True, ckpt=(1, 1), torn=True)


@pytest.mark.slow
def test_crash_recovery_bit_exact_at_every_boundary(tmp_path):
    """THE acceptance sweep: crash after every append/commit/
    checkpoint boundary combination — committed-only, appended-only,
    partial commits, checkpoints skewing either slice's frontier at
    every position, torn tails — each recovers ``to_host_coo``-equal
    with its never-crashed twin."""
    cases = []
    for n_commit, n_append in ((1, 0), (0, 1), (2, 1), (1, 2)):
        for partial in ({False, n_append > 0}):
            ck_positions = [None] + [
                (s, p) for s in (0, 1)
                for p in range(1, n_commit + n_append + 1)
            ]
            for ckpt in ck_positions:
                cases.append((n_commit, n_append, partial, ckpt,
                              False))
    cases.append((2, 1, True, (1, 2), True))
    cases.append((0, 2, False, None, True))
    for i, (nc, na, partial, ckpt, torn) in enumerate(cases):
        _crash_scenario(tmp_path, str(i), n_commit=nc,
                        n_append_only=na, commit_partial=partial,
                        ckpt=ckpt, torn=torn)


# --- subprocess fleet: SIGKILL + respawn (slow; the bench's twin) ------------


@pytest.mark.slow
@pytest.mark.chaos
def test_process_mode_sigkill_respawn_bit_exact(tmp_path):
    """Real subprocess slices: bfs AND sssp bit-exact vs unsharded,
    one slice SIGKILLed mid-service respawns from its slab snapshot +
    WAL while the other keeps its devices, answers stay bit-exact and
    the respawn costs ZERO post-warmup retraces."""
    rng = np.random.default_rng(1)
    n, m = 48, 300
    rows = rng.integers(0, n, m)
    cols = rng.integers(0, n, m)
    w = rng.random(m).astype(np.float32) + 0.1
    grid = Grid.make(1, 1)
    eng = GraphEngine.from_coo(grid, rows, cols, nrows=n, weights=w,
                               kinds=("bfs", "sssp"), keep_coo=True)
    sh = ShardedEngine.build(
        rows, cols, nrows=n, nslices=2, weights=w,
        kinds=("bfs", "sssp"), home=str(tmp_path / "proc"),
        mode="process", warmup=True, warmup_widths=(4,),
    )
    try:
        srcs = np.array([0, 5, 17, 40], np.int32)
        for kind, key in (("bfs", "parents"), ("sssp", "dist")):
            ref = eng.execute(kind, srcs)
            got = sh.execute(kind, srcs)
            np.testing.assert_array_equal(np.asarray(ref[key]),
                                          got[key])
        mark = sh.trace_mark()
        sh.slices[0].kill()  # SIGKILL; next execute heals + replays
        got = sh.execute("bfs", srcs)
        ref = eng.execute("bfs", srcs)
        np.testing.assert_array_equal(np.asarray(ref["parents"]),
                                      got["parents"])
        assert sh.replacements == 1
        assert sh.retraces_since(mark) == 0
    finally:
        sh.close()


def test_spec_owner_of_rejects_out_of_range():
    spec = ShardSpec(nrows=10, ncols=10, bounds=((0, 5), (5, 10)))
    with pytest.raises(ValueError):
        spec.owner_of(10)
    with pytest.raises(ValueError):
        spec.owner_of(-1)


# --- the round-21 wire protocol: encodings, resident state, epochs -----------


def test_encoding_equivalence_fast_representative(tmp_path):
    """ISSUE 19: THE fast representative of the wire-protocol sweep —
    one 2-slice local engine answers bfs/sssp bit-exactly vs the
    unsharded build under FORCED sparse, forced dense, and auto
    encodings (the router's per-hop choice mixes regimes mid-batch),
    and the per-execute wire accounting shows sparse strictly cheaper
    than dense on hop payloads."""
    rows, cols = _coo(11)
    w = (np.random.default_rng(11).random(rows.shape[0])
         .astype(np.float32) + 0.1)
    grid = Grid.make(1, 1)
    eng = GraphEngine.from_coo(grid, rows, cols, nrows=N, weights=w,
                               kinds=("bfs", "sssp"))
    sh = ShardedEngine.build(
        rows, cols, nrows=N, nslices=2, weights=w,
        kinds=("bfs", "sssp"), home=str(tmp_path / "enc"),
        mode="local", warmup=False,
    )
    try:
        srcs = np.array([0, 5, 17], np.int32)
        refs = {k: eng.execute(k, srcs) for k in ("bfs", "sssp")}
        hop_payload = {}
        for mode in ("sparse", "dense", "auto"):
            sh.frontier_mode = mode  # the router owns the decision
            for kind, keys in (("bfs", ("parents", "levels")),
                               ("sssp", ("dist",))):
                got = sh.execute(kind, srcs)
                for key in keys:
                    np.testing.assert_array_equal(
                        np.asarray(refs[kind][key]), got[key],
                        err_msg=f"{kind}/{key} under {mode}",
                    )
                assert (int(got["batch_niter"])
                        == int(refs[kind]["batch_niter"])), mode
                st = sh.last_exec_stats
                assert st["collects"] == 1
                assert len(st["frontier_nnz"]) == st["hops"]
                if mode in ("sparse", "dense"):
                    assert set(st["enc_hops"]) == {mode}
                    hop_payload[(kind, mode)] = st["bytes_by_enc"][mode]
        # auto mixed regimes on this graph (frontier starts tiny,
        # saturates mid-batch, then dries up)
        assert set(sh.last_exec_stats["enc_hops"]) == {"sparse",
                                                       "dense"}
        for kind in ("bfs", "sssp"):
            assert (hop_payload[(kind, "sparse")]
                    < hop_payload[(kind, "dense")])
    finally:
        sh.close()


def test_stale_epoch_replay_reseeds_resident_state(tmp_path):
    """ISSUE 19: a slice that loses its resident loop state mid-batch
    (amnesia respawn between hops) reports StaleEpochError — a
    PROTOCOL fact from a healthy slice, not a death — and the router
    replays the whole batch under a fresh epoch, re-seeding every
    slice, WITHOUT quarantining the reporter.  The replayed answer is
    bit-exact."""
    from combblas_tpu.serve.policy import StaleEpochError

    rows, cols = _coo(13)
    grid = Grid.make(1, 1)
    eng = GraphEngine.from_coo(grid, rows, cols, N, kinds=("bfs",))
    sh = ShardedEngine.build(
        rows, cols, nrows=N, nslices=2, kinds=("bfs",),
        home=str(tmp_path / "stale"), mode="local", warmup=False,
        frontier="sparse",
    )
    try:
        srcs = np.array([0, 5, 17], np.int32)
        epoch0 = sh._epoch
        orig_fan = sh._fan_hop
        state = {"fans": 0, "stale": 0}

        def fan(kind, payload, **kw):
            if kw.get("op", "hop") == "hop" and state["fans"] == 2:
                # between hops 2 and 3: slice 0 respawns with no
                # resident state (the mid-batch SIGKILL analog)
                sh.slices[0].rt = sh.slices[0]._factory(recover=True)
            state["fans"] += 1
            try:
                return orig_fan(kind, payload, **kw)
            except StaleEpochError:
                state["stale"] += 1
                raise

        sh._fan_hop = fan
        got = sh.execute("bfs", srcs)
        ref = eng.execute("bfs", srcs)
        assert state["stale"] == 1
        assert not sh._needs_rebuild  # reporter was NOT quarantined
        # the replay ran under a FRESH epoch (failed attempt's state
        # can never leak into it)
        assert sh._epoch >= epoch0 + 2
        np.testing.assert_array_equal(np.asarray(ref["parents"]),
                                      got["parents"])
        np.testing.assert_array_equal(np.asarray(ref["levels"]),
                                      got["levels"])
        assert int(got["batch_niter"]) == int(ref["batch_niter"])
    finally:
        sh._fan_hop = orig_fan
        sh.close()


@pytest.mark.slow
def test_encoding_equivalence_sweep(tmp_path):
    """ISSUE 19 (slow twin): the full encoding-equivalence property
    sweep — kinds x widths {1, 4, 16} x {2, 3} slices, forced sparse
    vs forced dense vs auto, all bit-exact vs unsharded (propagate
    allclose, plus the opt-in bf16 wire within its quantization
    budget and the hops==0 final-fan edge)."""
    rng = np.random.default_rng(21)
    n, m = 48, 300
    r0 = rng.integers(0, n, m // 2)
    c0 = rng.integers(0, n, m // 2)
    rows = np.concatenate([r0, c0])   # symmetric: propagate-legal
    cols = np.concatenate([c0, r0])
    w = rng.random(rows.shape[0]).astype(np.float32) + 0.1
    feats = rng.normal(size=(n, 5)).astype(np.float32)
    grid = Grid.make(1, 1)
    eng = GraphEngine.from_coo(
        grid, rows, cols, nrows=n, weights=w, features=feats,
        symmetric=True, kinds=("bfs", "sssp", "propagate"),
    )
    for nslices in (2, 3):
        sh = ShardedEngine.build(
            rows, cols, nrows=n, nslices=nslices, weights=w,
            features=feats, symmetric=True,
            kinds=("bfs", "sssp", "propagate"),
            home=str(tmp_path / f"s{nslices}"), mode="local",
            warmup=False,
        )
        try:
            for width in (1, 4, 16):
                srcs = rng.integers(0, n, width).astype(np.int32)
                refs = {k: eng.execute(k, srcs)
                        for k in ("bfs", "sssp", "propagate")}
                for mode in ("sparse", "dense", "auto"):
                    sh.frontier_mode = mode
                    for kind, keys in (("bfs", ("parents", "levels")),
                                       ("sssp", ("dist",))):
                        got = sh.execute(kind, srcs)
                        for key in keys:
                            np.testing.assert_array_equal(
                                np.asarray(refs[kind][key]), got[key],
                                err_msg=f"{nslices}sl/{kind}/{key}"
                                        f"/w{width}/{mode}",
                            )
                        assert (int(got["batch_niter"])
                                == int(refs[kind]["batch_niter"]))
                ref_f = np.asarray(refs["propagate"]["features"])
                for wire in ("f32", "bf16"):
                    sh.wire = wire
                    got = sh.execute("propagate", srcs)
                    tol = 1e-5 if wire == "f32" else 3e-2
                    np.testing.assert_allclose(
                        ref_f, got["features"], rtol=tol, atol=tol,
                        err_msg=f"{nslices}sl/propagate/w{width}"
                                f"/{wire}",
                    )
                sh.wire = "f32"
            # hops==0 edge: the seed rides the final fan
            sh.propagate_hops = 0
            got = sh.execute("propagate",
                             np.array([0, 1], np.int32))
            assert got["features"].shape == (feats.shape[1], 2)
            sh.propagate_hops = eng.propagate_hops \
                if hasattr(eng, "propagate_hops") else 2
        finally:
            sh.close()
