"""FullyDistVec op pack (sort/find_inds/invert/uniq/randperm) + DenseParMat."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from combblas_tpu import MAX_MIN, PLUS_TIMES, SELECT2ND_MIN
from combblas_tpu.parallel.dense import DenseParMat
from combblas_tpu.parallel.grid import Grid
from combblas_tpu.parallel.spmat import SpParMat
from combblas_tpu.parallel.vec import DistVec
from conftest import random_dense


def _is_pos(v):
    return v > 0


@pytest.mark.parametrize("align", ["row", "col"])
def test_sort(rng, align):
    grid = Grid.make(2, 4)
    x = rng.integers(-50, 50, size=21).astype(np.int32)
    v = DistVec.from_global(grid, x, align=align, fill=999)
    sv, perm = v.sort()
    np.testing.assert_array_equal(sv.to_global(), np.sort(x))
    np.testing.assert_array_equal(x[perm.to_global()], np.sort(x))


def test_find_inds(rng):
    grid = Grid.make(2, 2)
    x = rng.integers(-5, 5, size=19).astype(np.int32)
    v = DistVec.from_global(grid, x, align="col", fill=0)
    inds, count = v.find_inds(_is_pos)
    expect = np.nonzero(x > 0)[0]
    assert int(count) == len(expect)
    np.testing.assert_array_equal(inds.to_global()[: len(expect)], expect)
    assert np.all(inds.to_global()[len(expect) :] == 19)


def test_invert(rng):
    grid = Grid.make(2, 2)
    x = np.array([3, 1, 4, 1, 5], np.int32)
    act = np.array([True, True, True, True, False])
    v = DistVec.from_global(grid, x, align="col", fill=0)
    a = DistVec.from_global(grid, act, align="col", fill=False)
    out = v.invert(a, out_length=8, sr=SELECT2ND_MIN)
    # value 1 occurs at indices 1 and 3 -> min resolution picks 1;
    # value 5 is inactive -> untouched output stays -1
    expect = np.array([-1, 1, -1, 0, 2, -1, -1, -1], np.int32)
    np.testing.assert_array_equal(out.to_global(), expect)


def test_uniq(rng):
    grid = Grid.make(2, 2)
    x = np.array([7, 2, 7, 2, 9, 7], np.int32)
    act = np.ones(6, bool)
    v = DistVec.from_global(grid, x, align="col", fill=0)
    a = DistVec.from_global(grid, act, align="col", fill=False)
    keep = v.uniq(a).to_global()
    np.testing.assert_array_equal(keep, [True, True, False, False, True, False])


def test_uniq_respects_active(rng):
    grid = Grid.make(2, 2)
    x = np.array([7, 2, 7, 2], np.int32)
    act = np.array([False, True, True, True])
    v = DistVec.from_global(grid, x, align="col", fill=0)
    a = DistVec.from_global(grid, act, align="col", fill=False)
    keep = v.uniq(a).to_global()
    np.testing.assert_array_equal(keep, [False, True, True, False])


def test_randperm():
    grid = Grid.make(2, 2)
    p = DistVec.randperm(grid, 23, jax.random.key(7)).to_global()
    np.testing.assert_array_equal(np.sort(p[:23]), np.arange(23))
    p2 = DistVec.randperm(grid, 23, jax.random.key(8)).to_global()
    assert not np.array_equal(p, p2)


def test_dense_roundtrip(rng):
    grid = Grid.make(2, 2)
    d = rng.random((11, 13)).astype(np.float32)
    D = DenseParMat.from_global(grid, d)
    np.testing.assert_allclose(D.to_global(), d)


def test_dense_add_spmat(rng):
    grid = Grid.make(2, 2)
    d = rng.random((12, 12)).astype(np.float32)
    s = random_dense(rng, 12, 12, 0.3)
    D = DenseParMat.from_global(grid, d)
    S = SpParMat.from_dense(grid, s)
    np.testing.assert_allclose(
        D.add_spmat(S).to_global(), d + s, rtol=1e-6
    )


def test_dense_reduce(rng):
    grid = Grid.make(2, 2)
    d = rng.random((10, 14)).astype(np.float32)
    D = DenseParMat.from_global(grid, d)
    np.testing.assert_allclose(
        D.reduce(PLUS_TIMES, "rows").to_global(), d.sum(axis=0), rtol=1e-5
    )
    np.testing.assert_allclose(
        D.reduce(PLUS_TIMES, "cols").to_global(), d.sum(axis=1), rtol=1e-5
    )
    got = D.reduce(MAX_MIN, "cols").to_global()
    np.testing.assert_allclose(got, d.max(axis=1), rtol=1e-6)
