"""I/O: Matrix Market (native C++ parser + python fallback), binary, vectors."""

import numpy as np
import pytest

import combblas_tpu.io.mm as mmio
from combblas_tpu.io import (
    read_binary,
    read_mm,
    read_mm_spmat,
    read_vec,
    write_binary,
    write_mm,
    write_vec,
)
from combblas_tpu.parallel.grid import Grid
from combblas_tpu.parallel.spmat import SpParMat
from combblas_tpu.parallel.vec import DistVec
from conftest import random_dense

MM_GENERAL = """%%MatrixMarket matrix coordinate real general
% a comment line
3 4 5
1 1 1.5
2 1 -2.0
3 3 4.25
1 4 7
3 2 0.5
"""

MM_SYMMETRIC = """%%MatrixMarket matrix coordinate real symmetric
4 4 4
1 1 2.0
2 1 3.0
3 2 5.0
4 4 1.0
"""

MM_PATTERN = """%%MatrixMarket matrix coordinate pattern general
3 3 3
1 2
2 3
3 1
"""


def _expect_general():
    d = np.zeros((3, 4))
    d[0, 0], d[1, 0], d[2, 2], d[0, 3], d[2, 1] = 1.5, -2.0, 4.25, 7, 0.5
    return d


def _dense_of(rows, cols, vals, m, n):
    d = np.zeros((m, n))
    np.add.at(d, (rows, cols), vals)
    return d


def test_native_parser_builds():
    assert mmio._load_native() is not None, "g++ toolchain expected in image"


@pytest.mark.parametrize("use_native", [True, False])
def test_read_mm_general(tmp_path, use_native, monkeypatch):
    p = tmp_path / "a.mtx"
    p.write_text(MM_GENERAL)
    if not use_native:
        monkeypatch.setattr(mmio, "_LIB", None)
        monkeypatch.setattr(mmio, "_LIB_FAILED", True)
    rows, cols, vals, m, n = read_mm(str(p))
    assert (m, n) == (3, 4) and len(rows) == 5
    np.testing.assert_allclose(_dense_of(rows, cols, vals, m, n), _expect_general())


@pytest.mark.parametrize("use_native", [True, False])
def test_read_mm_symmetric_expands(tmp_path, use_native, monkeypatch):
    p = tmp_path / "s.mtx"
    p.write_text(MM_SYMMETRIC)
    if not use_native:
        monkeypatch.setattr(mmio, "_LIB", None)
        monkeypatch.setattr(mmio, "_LIB_FAILED", True)
    rows, cols, vals, m, n = read_mm(str(p))
    d = _dense_of(rows, cols, vals, m, n)
    np.testing.assert_allclose(d, d.T)
    assert d[0, 0] == 2.0 and d[1, 0] == 3.0 and d[0, 1] == 3.0


def test_read_mm_pattern(tmp_path):
    p = tmp_path / "p.mtx"
    p.write_text(MM_PATTERN)
    rows, cols, vals, m, n = read_mm(str(p))
    assert (vals == 1).all() and len(rows) == 3


def test_mm_roundtrip_spmat(tmp_path, rng):
    grid = Grid.make(2, 2)
    d = random_dense(rng, 13, 9, 0.3).astype(np.float64)
    A = SpParMat.from_dense(grid, d.astype(np.float32))
    path = str(tmp_path / "rt.mtx")
    write_mm(path, A, comment="roundtrip test")
    B = read_mm_spmat(grid, path)
    np.testing.assert_allclose(B.to_dense(), d.astype(np.float32), rtol=1e-6)


def test_native_matches_python(tmp_path, rng):
    """Cross-implementation equivalence (the reference's own test pattern)."""
    m, n = 40, 30
    d = random_dense(rng, m, n, 0.2).astype(np.float64)
    r, c = np.nonzero(d)
    path = str(tmp_path / "x.mtx")
    write_mm(path, (r, c, d[r, c], m, n))
    got = read_mm(str(path))
    if mmio._load_native() is None:
        pytest.skip("no toolchain")
    expect = mmio._read_mm_python(path)
    np.testing.assert_allclose(
        _dense_of(got[0], got[1], got[2], m, n),
        _dense_of(expect[0], expect[1], expect[2], m, n),
    )


def test_binary_roundtrip(tmp_path, rng):
    m, n = 17, 21
    d = random_dense(rng, m, n, 0.25).astype(np.float64)
    r, c = np.nonzero(d)
    path = str(tmp_path / "b.bin")
    write_binary(path, (r, c, d[r, c], m, n))
    rows, cols, vals, m2, n2 = read_binary(path)
    assert (m2, n2) == (m, n)
    np.testing.assert_allclose(_dense_of(rows, cols, vals, m, n), d)


def test_vec_roundtrip(tmp_path, rng):
    grid = Grid.make(2, 2)
    x = rng.random(15).astype(np.float32)
    act = rng.random(15) < 0.6
    v = DistVec.from_global(grid, x, align="row")
    a = DistVec.from_global(grid, act, align="row", fill=False)
    path = str(tmp_path / "v.txt")
    write_vec(path, v, active=a)
    v2, a2 = read_vec(grid, path, align="row")
    np.testing.assert_array_equal(a2.to_global(), act)
    np.testing.assert_allclose(v2.to_global()[act], x[act], rtol=1e-6)


def test_vec_roundtrip_bool(tmp_path, rng):
    """Bool vectors must survive write_vec/read_vec (ADVICE r1:
    np.bool_('False') is True, so token parsing must be numeric-first)."""
    grid = Grid.make(2, 2)
    x = rng.random(11) < 0.5
    x[0] = False  # ensure at least one explicit False among actives
    act = np.ones(11, bool)
    v = DistVec.from_global(grid, x, align="row", fill=False)
    a = DistVec.from_global(grid, act, align="row", fill=False)
    path = str(tmp_path / "bv.txt")
    write_vec(path, v, active=a)
    v2, _ = read_vec(grid, path, dtype=np.bool_, align="row", fill=False)
    np.testing.assert_array_equal(np.asarray(v2.to_global(), bool), x)


def test_read_mm_distributed_single_process(tmp_path, rng):
    """Byte-range distributed read (ParallelReadMM analog): single-process
    degenerate case must equal the plain read + distribution."""
    from combblas_tpu.io.mm import read_mm_distributed

    n = 24
    d = (rng.random((n, n)) < 0.2) * rng.random((n, n))
    d = np.round(d.astype(np.float64), 3)
    r, c = np.nonzero(d)
    p = tmp_path / "g.mtx"
    lines = [f"%%MatrixMarket matrix coordinate real general\n{n} {n} {len(r)}"]
    lines += [f"{i+1} {j+1} {d[i, j]}" for i, j in zip(r, c)]
    p.write_text("\n".join(lines) + "\n")

    grid = Grid.make(2, 4)
    A = read_mm_distributed(grid, str(p))
    np.testing.assert_allclose(
        A.to_dense(), d.astype(np.float32), rtol=1e-6
    )


def test_read_mm_distributed_symmetric(tmp_path):
    from combblas_tpu.io.mm import read_mm_distributed

    p = tmp_path / "s.mtx"
    p.write_text(MM_SYMMETRIC)
    grid = Grid.make(2, 2)
    A = read_mm_distributed(grid, str(p))
    d = np.zeros((4, 4))
    d[0, 0], d[1, 0], d[0, 1] = 2.0, 3.0, 3.0
    d[2, 1], d[1, 2], d[3, 3] = 5.0, 5.0, 1.0
    np.testing.assert_allclose(A.to_dense(), d.astype(np.float32))


def test_read_mm_array_general(tmp_path):
    """Dense 'array' format (mmio.c:60-70 parity): column-major body,
    nonzeros returned as COO."""
    from combblas_tpu.io.mm import read_mm

    p = tmp_path / "dense.mtx"
    # column-major listing of [[1, 0], [2.5, 3]]
    p.write_text(
        "%%MatrixMarket matrix array real general\n"
        "2 2\n1.0\n2.5\n0.0\n3.0\n"
    )
    rows, cols, vals, nr, nc = read_mm(str(p))
    assert (nr, nc) == (2, 2)
    got = sorted(zip(rows.tolist(), cols.tolist(), vals.tolist()))
    assert got == [(0, 0, 1.0), (1, 0, 2.5), (1, 1, 3.0)]


def test_read_mm_array_symmetric(tmp_path):
    """Symmetric array: packed lower triangle, mirrored on expand."""
    from combblas_tpu.io.mm import read_mm

    p = tmp_path / "sym.mtx"
    # lower triangle (incl diag) of [[1, 2], [2, 0]] column-major:
    # column 0 rows 0..1 -> 1, 2; column 1 rows 1..1 -> 0
    p.write_text(
        "%%MatrixMarket matrix array real symmetric\n"
        "2 2\n1.0\n2.0\n0.0\n"
    )
    rows, cols, vals, nr, nc = read_mm(str(p))
    got = sorted(zip(rows.tolist(), cols.tolist(), vals.tolist()))
    assert got == [(0, 0, 1.0), (0, 1, 2.0), (1, 0, 2.0)]
