"""Round-15 production observability: per-request tracing, the flight
recorder, SLO error budgets, freshness gauges, label-space pruning and
the Prometheus export surface (ISSUE 13; docs/observability.md
"Serving observability")."""

import json
import os
import urllib.request

import numpy as np
import pytest

from combblas_tpu import obs
from combblas_tpu.obs import export as obs_export
from combblas_tpu.obs import trace as obs_trace
from combblas_tpu.obs.recorder import FlightRecorder
from combblas_tpu.parallel.grid import Grid
from combblas_tpu.serve import (
    ErrorBudget,
    GraphEngine,
    ServeConfig,
)


@pytest.fixture(autouse=True)
def _clean_obs():
    obs.disable()
    obs.reset()
    obs_trace.set_sample_rate(None)
    yield
    obs.disable()
    obs.reset()
    obs_trace.set_sample_rate(None)


N = 48


@pytest.fixture(scope="module")
def engine(tmp_path_factory):
    """One tiny BFS engine shared by the module (plan compiles paid
    once); tests build their own worker-less Servers over it."""
    rng = np.random.default_rng(3)
    r = rng.integers(0, N, 220)
    c = rng.integers(0, N, 220)
    return GraphEngine.from_coo(
        Grid.make(1, 1), np.concatenate([r, c]), np.concatenate([c, r]),
        N, kinds=("bfs",), keep_coo=True,
    )


def _cfg(tmp_path, **kw):
    kw.setdefault("lane_widths", (1, 2))
    kw.setdefault("update_autostart", False)
    kw.setdefault("flight_recorder_dir", str(tmp_path))
    return ServeConfig(**kw)


# --- deterministic sampling -------------------------------------------------


def test_sampling_deterministic_and_proportional():
    ids = list(range(1000))
    a = {i for i in ids if obs_trace.sampled(i, 0.3)}
    b = {i for i in ids if obs_trace.sampled(i, 0.3)}
    assert a == b  # same ids + same rate = same sampled set
    assert 0.2 < len(a) / len(ids) < 0.4  # roughly the asked rate
    # rate monotonicity: raising the rate only ADDS ids
    c = {i for i in ids if obs_trace.sampled(i, 0.6)}
    assert a <= c
    assert {i for i in ids if obs_trace.sampled(i, 0.0)} == set()
    assert {i for i in ids if obs_trace.sampled(i, 1.0)} == set(ids)


def test_sample_rate_env_resolution(monkeypatch):
    from combblas_tpu.tuner import config as tuner_config

    monkeypatch.setenv(tuner_config.ENV_OBS_TRACE_SAMPLE, "0.25")
    obs_trace.set_sample_rate(None)  # re-resolve
    assert obs_trace.sample_rate() == 0.25
    assert tuner_config.obs_trace_sample(2.0) == 1.0  # clamped


# --- the pump stage-sum contract --------------------------------------------


def test_pump_trace_stages_sum_to_e2e(engine, tmp_path):
    obs.enable(install_hooks=False)
    obs_trace.set_sample_rate(1.0)
    srv = engine.serve(_cfg(tmp_path))
    srv.warmup(widths=(1, 2))
    futs = [srv.submit("bfs", i) for i in (1, 2, 3)]
    while srv.pump(force=True):
        pass
    for f in futs:
        assert f.exception(timeout=0) is None
    srv.close()
    recs = [
        r for r in obs.trace_records() if r["name"] == "serve.request"
    ]
    assert len(recs) == 3
    for rec in recs:
        obs.validate_record({"v": 1, "kind": "trace", **rec})
        stages = [st["stage"] for st in rec["stages"]]
        assert stages[:3] == ["queue_wait", "assemble", "execute"]
        assert stages[-1] == "scatter"
        # THE acceptance property: stage durations telescope to the
        # end-to-end latency (each mark charges since the last one)
        total = sum(st["s"] for st in rec["stages"])
        assert abs(total - rec["wall_s"]) < 1e-6, rec
        assert rec["labels"]["status"] == "ok"
        assert rec["labels"]["kind"] == "bfs"
        assert rec["labels"]["plan"] in ("warm", "cold")
        assert rec["labels"]["width"] in (1, 2)
        assert rec["labels"]["version"] == engine.version_id


def test_write_lane_trace_stages(engine, tmp_path):
    obs.enable(install_hooks=False)
    obs_trace.set_sample_rate(1.0)
    srv = engine.serve(_cfg(tmp_path))
    fut = srv.submit_update([("insert", 0, 9), ("insert", 9, 0)])
    srv.pump_updates(force=True)
    assert fut.result(timeout=10)["ops"] == 2
    srv.close()
    recs = [
        r for r in obs.trace_records() if r["name"] == "serve.update"
    ]
    assert len(recs) == 1
    rec = recs[0]
    obs.validate_record({"v": 1, "kind": "trace", **rec})
    assert [st["stage"] for st in rec["stages"]] == [
        "buffer_wait", "merge", "swap", "settle",
    ]
    assert abs(
        sum(st["s"] for st in rec["stages"]) - rec["wall_s"]
    ) < 1e-6
    assert rec["labels"]["mode"] in ("incremental", "rebuild")


def test_trace_jsonl_roundtrip(engine, tmp_path):
    path = str(tmp_path / "trace.jsonl")
    obs.enable(jsonl_path=path, install_hooks=False)
    obs_trace.set_sample_rate(1.0)
    srv = engine.serve(_cfg(tmp_path))
    srv.submit("bfs", 1)
    while srv.pump(force=True):
        pass
    srv.close()
    obs.dump_jsonl()
    recs = obs.parse_jsonl(path)  # validates every line
    traces = [r for r in recs if r["kind"] == "trace"]
    assert traces and traces[0]["name"] == "serve.request"
    agg = obs.aggregate(recs)
    assert len(agg["traces"]) == len(traces)
    # expired requests close their trace with a timeout status
    assert obs.registry.get_counter(
        "serve.trace.sampled", lane="request"
    ) >= 1


# --- zero-cost-when-disabled gates ------------------------------------------


def test_round15_zero_cost_when_disabled(engine, tmp_path):
    """The round-15 analog of the existing gate tests: with obs off
    (and the recorder opted out) the serve path books NOTHING — no
    registry entries, no trace records, no recorder object."""
    assert not obs.ENABLED
    srv = engine.serve(_cfg(tmp_path, flight_recorder=False))
    assert srv._recorder is None  # one attribute read on the batch path
    assert srv.slo is None  # no SLO configured = no budget object
    f = srv.submit("bfs", 1)
    while srv.pump(force=True):
        pass
    assert f.exception(timeout=0) is None
    srv.close()
    assert obs.registry.empty()
    assert obs.trace_records() == []
    # obs ON but sampling at 0 (the default): still no traces
    obs.enable(install_hooks=False)
    obs_trace.set_sample_rate(0.0)
    srv = engine.serve(_cfg(tmp_path, flight_recorder=False))
    f = srv.submit("bfs", 2)
    while srv.pump(force=True):
        pass
    assert f.exception(timeout=0) is None
    srv.close()
    assert obs.trace_records() == []


# --- flight recorder --------------------------------------------------------


def test_flight_recorder_ring_and_dump(tmp_path):
    rec = FlightRecorder(capacity=3, out_dir=str(tmp_path),
                         min_interval_s=0.0)
    for i in range(5):
        rec.record("ev", i=i, query="bfs")  # reserved-name remap
    snap = rec.snapshot()
    assert [e["i"] for e in snap] == [2, 3, 4]  # bounded, oldest first
    path = rec.dump("manual", query="bfs")
    recs = obs.parse_jsonl(path)  # both schemas validate
    assert recs[0]["schema"] == obs.FLIGHTREC_SCHEMA
    assert recs[0]["reason"] == "manual"
    assert [r["i"] for r in recs[1:]] == [2, 3, 4]
    # rate limit: an immediate second dump is suppressed
    rec.min_interval_s = 60.0
    assert rec.dump("manual") is None
    assert rec.dumps == 1


def test_injected_fault_dumps_poisoned_batch(engine, tmp_path):
    """Acceptance: an injected fault produces a flight-recorder dump
    containing the poisoned batch's stage events."""
    obs.enable(install_hooks=False)
    srv = engine.serve(_cfg(tmp_path))
    srv.warmup(widths=(1, 2))
    srv.faults.rate("engine.execute", 1.0, seed=5)
    f = srv.submit("bfs", 1)
    while srv.pump(force=True):
        pass
    assert f.exception(timeout=0) is not None
    dump = srv._recorder.last_dump
    assert dump is not None and os.path.dirname(dump) == str(tmp_path)
    recs = obs.parse_jsonl(dump)
    assert recs[0]["reason"] == "poisoned"
    assert recs[0]["query"] == "bfs"
    evs = [
        r for r in recs
        if r["kind"] == "event" and r["name"] == "serve.batch"
    ]
    assert evs, recs
    assert any(e.get("outcome") == "error" for e in evs)
    assert obs.registry.get_counter(
        "serve.flightrec.dumps", reason="poisoned"
    ) == 1
    assert srv.stats()["flightrec"]["dumps"] == 1
    assert srv.health()["flightrec_last_dump"] == dump
    srv.faults.clear()
    srv.close()


# --- SLO error budgets ------------------------------------------------------


def test_error_budget_window_and_breach():
    clock = [100.0]
    eb = ErrorBudget(target=0.9, window_s=10.0, tenant="t0",
                     clock=lambda: clock[0])
    for _ in range(9):
        assert eb.record(True) is False
    # 9 good + 1 bad: budget = 0.1 * 10 = 1.0, burn = 1.0 -> breach
    assert eb.record(False) is True  # the TRANSITION returns True
    assert eb.record(False) is False  # already breached: no re-fire
    d = eb.describe()
    assert d["breached"] and d["burn"] >= 1.0
    assert d["window_good"] == 9 and d["window_bad"] == 2
    # the window rolls: 11 s later the old buckets expire — and a
    # breached-then-IDLE budget must recover on read alone (no new
    # record()), or an idle tenant would page degraded forever
    clock[0] += 11.0
    d = eb.describe()
    assert d["window_bad"] == 0 and not d["breached"]
    for _ in range(20):
        eb.record(True)
    d = eb.describe()
    assert d["window_bad"] == 0 and not d["breached"]
    assert d["bad_total"] == 2  # lifetime totals survive the window


def test_server_slo_accounting_and_health(engine, tmp_path):
    obs.enable(install_hooks=False)
    srv = engine.serve(_cfg(
        tmp_path, slo_deadline_s=30.0, slo_target=0.5,
        slo_window_s=60.0,
    ))
    srv.warmup(widths=(1, 2))
    ok = [srv.submit("bfs", i) for i in (1, 2)]
    while srv.pump(force=True):
        pass
    for f in ok:
        assert f.exception(timeout=0) is None
    st = srv.stats()["slo"]
    assert st["window_good"] == 2 and st["window_bad"] == 0
    assert obs.registry.get_counter("serve.slo.good", kind="bfs") == 2
    # a poisoned request is a BAD disposition and burns the budget
    srv.faults.rate("engine.execute", 1.0, seed=5)
    bad = srv.submit("bfs", 3)
    while srv.pump(force=True):
        pass
    assert bad.exception(timeout=0) is not None
    srv.faults.clear()
    st = srv.stats()["slo"]
    assert st["window_bad"] == 1
    assert obs.registry.get_counter("serve.slo.bad", kind="bfs") == 1
    assert obs.registry.get_gauge("serve.slo.budget_burn") is not None
    h = srv.health()
    assert h["slo"]["window_bad"] == 1
    srv.close()


# --- freshness gauges -------------------------------------------------------


def test_freshness_gauges_on_refresh(tmp_path):
    obs.enable(install_hooks=False)
    rng = np.random.default_rng(9)
    r = rng.integers(0, 32, 140)
    c = rng.integers(0, 32, 140)
    eng = GraphEngine.from_coo(
        Grid.make(1, 1), np.concatenate([r, c]),
        np.concatenate([c, r]), 32, kinds=("bfs",), keep_coo=True,
    )
    srv = eng.serve(_cfg(tmp_path))
    root = int(r[0])
    eng.refresh("bfs", root=root)  # cold: seeds the analytics cache
    # one merged write: the cached analytic is now one version behind
    fut = srv.submit_update([("insert", 0, 31), ("insert", 31, 0)])
    srv.pump_updates(force=True)
    assert fut.exception(timeout=10) is None
    out = eng.refresh("bfs", root=root)
    assert out["mode"] == "warm"  # insert-only lineage repairs
    assert obs.registry.get_gauge(
        "dynamic.freshness.versions_behind", kind="bfs"
    ) == 1
    ratio = obs.registry.get_gauge("dynamic.freshness.repair_ratio")
    assert ratio == 0.5  # 1 warm / (1 warm + 1 cold)
    fresh = eng.stats()["freshness"]
    assert fresh["refresh_modes"] == {"cold": 1, "warm": 1}
    assert fresh["repair_ratio"] == 0.5
    assert fresh["versions_behind"] == 0  # cache repaired to current
    srv.close()


# --- label-space pruning on tenant churn ------------------------------------


def test_pool_tenant_churn_prunes_label_space(tmp_path):
    """ISSUE 13 satellite regression: add/remove tenant cycles must
    return the registry's label count to baseline — a removed tenant's
    ``tenant=...`` series must not survive it."""
    from combblas_tpu.serve import EnginePool

    obs.enable(install_hooks=False)
    rng = np.random.default_rng(4)
    r = rng.integers(0, 32, 120)
    c = rng.integers(0, 32, 120)
    rows, cols = np.concatenate([r, c]), np.concatenate([c, r])
    grid = Grid.make(1, 1)
    cfg = ServeConfig(lane_widths=(1,), update_autostart=False,
                      flight_recorder=False)
    pool = EnginePool(grid)
    psrv = pool.serve()
    baseline = len(obs.metrics_snapshot())

    def tenant_series():
        return [
            rec for rec in obs.metrics_snapshot()
            if rec["labels"].get("tenant") == "x"
        ]

    for _ in range(2):  # add/serve/remove cycles
        pool.add_tenant("x", rows, cols, 32, config=cfg, kinds=("bfs",))
        f = psrv.submit("x", "bfs", 1)
        while psrv.pump(force=True):
            pass
        assert f.exception(timeout=0) is None
        assert tenant_series()  # labeled series exist while serving
        pool.remove_tenant("x")
        assert tenant_series() == []  # ...and are pruned on removal
    # unlabeled/global series may have appeared, but nothing grows
    # per departed tenant: the tenant-labeled count is back to zero
    # and the snapshot is not accumulating per-cycle
    assert len(obs.metrics_snapshot()) <= baseline + 24
    # the WFQ-prune path also sweeps the registry: simulate a tenant
    # removed between pumps with stale labeled state
    obs.gauge("serve.wfq.deficit", 1.0, tenant="ghost")
    psrv.wfq.add("ghost", 1.0)
    psrv.pump(force=True)  # no backlog: returns 0, but prunes first
    assert [
        rec for rec in obs.metrics_snapshot()
        if rec["labels"].get("tenant") == "ghost"
    ] == []


# --- Prometheus export ------------------------------------------------------


def test_exposition_parity_with_registry():
    """Acceptance: the scrape endpoint's rendered text agrees with the
    registry snapshot (counter / gauge / quantile parity)."""
    obs.enable(install_hooks=False)
    obs.count("par.requests", 5, kind="bfs")
    obs.count("par.requests", 2, kind="pr")
    obs.gauge("par.depth", 7.5)
    for v in (0.1, 0.2, 0.3, 0.4, 1.0):
        obs.observe("par.lat", v, kind="bfs")
    snap = obs.metrics_snapshot()
    text = obs_export.render(snap)
    parsed = obs_export.parse_exposition(text)
    for rec in snap:
        name = obs_export.metric_name(rec["name"])
        if rec["kind"] in ("counter", "gauge"):
            key = (name, obs_export._labels(rec["labels"]))
            assert parsed[key] == pytest.approx(rec["value"])
        else:
            lab = rec["labels"]
            assert parsed[
                (f"{name}_count", obs_export._labels(lab))
            ] == rec["count"]
            assert parsed[
                (f"{name}_sum", obs_export._labels(lab))
            ] == pytest.approx(rec["sum"])
            for q, fld in (("0.50", "p50"), ("0.95", "p95"),
                           ("0.99", "p99")):
                key = (name, obs_export._labels(lab, {"quantile": q}))
                assert parsed[key] == pytest.approx(rec[fld])
    # quantiles come from ONE shared implementation
    from combblas_tpu.obs.sinks import quantiles

    assert quantiles([0.1, 0.2, 0.3, 0.4, 1.0])[0.5] == pytest.approx(
        0.3
    )


def test_scrape_endpoint_live(engine, tmp_path):
    obs.enable(install_hooks=False)
    srv = engine.serve(_cfg(tmp_path))
    f = srv.submit("bfs", 1)
    while srv.pump(force=True):
        pass
    assert f.exception(timeout=0) is None
    port = srv.serve_metrics()
    assert port == srv.serve_metrics()  # idempotent
    base = f"http://127.0.0.1:{port}"
    text = urllib.request.urlopen(f"{base}/metrics", timeout=10
                                  ).read().decode()
    # the served text agrees with a fresh render of the registry
    assert obs_export.parse_exposition(text) == (
        obs_export.parse_exposition(obs_export.render())
    )
    assert "combblas_serve_requests" in text
    hz = json.loads(urllib.request.urlopen(
        f"{base}/healthz", timeout=10
    ).read())
    assert hz["status"] in ("ok", "degraded")
    sz = json.loads(urllib.request.urlopen(
        f"{base}/statz", timeout=10
    ).read())
    assert sz["completed"] >= 1
    assert obs.registry.get_counter(
        "obs.scrape.requests", path="/metrics"
    ) >= 1
    srv.close()  # stops the scrape thread
    assert srv._scrape is None


def test_procfleet_metrics_federation_parity():
    """ISSUE 16: one ``ProcessFleet`` ``/metrics`` scrape federates
    the router's registry with every replica's heartbeat-piggybacked
    child snapshot, relabeled ``replica=i``.  Parity-tested through
    the rendered exposition over stub replicas: the child snapshot is
    a GENUINE ``metrics_snapshot()`` wire shape (what ``_hb_loop``
    piggybacks), the subprocess itself is not needed to test the
    fold."""
    import types

    from combblas_tpu.serve.procfleet import ProcessFleet

    obs.enable(install_hooks=False)
    # forge the child's snapshot by actually populating a registry
    obs.count("serve.requests", 3, kind="bfs")
    for v in (0.01, 0.02):
        obs.observe("serve.e2e_s", v, kind="bfs")
    child_snap = obs.metrics_snapshot()
    obs.reset()
    obs.count("serve.requests", 2, kind="pr")  # router-side series
    stub = types.SimpleNamespace(replicas=[
        types.SimpleNamespace(last_metrics=child_snap,
                              last_metrics_t=1.0),
        types.SimpleNamespace(last_metrics=None,  # no heartbeat yet
                              last_metrics_t=0.0),
    ])
    # the fleet's REAL fold, bound to the stub — the scrape handler
    # discovers it by name on the owner
    stub.metrics_records = ProcessFleet.metrics_records.__get__(stub)
    recs = stub.metrics_records()
    # every child record is relabeled; the router's stay unlabeled
    assert {r["labels"].get("replica")
            for r in recs} == {None, 0}
    port = obs_export.attach_scrape(stub)
    assert port == obs_export.attach_scrape(stub)  # idempotent
    text = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=10
    ).read().decode()
    parsed = obs_export.parse_exposition(text)
    # parity: the served text agrees with a fresh federated render
    assert parsed == obs_export.parse_exposition(
        obs_export.render(stub.metrics_records())
    )
    child_lab = obs_export._labels({"kind": "bfs", "replica": 0})
    assert parsed[("combblas_serve_requests", child_lab)] == 3
    assert parsed[
        ("combblas_serve_e2e_s_count", child_lab)
    ] == 2  # histograms federate with their quantile summaries
    assert parsed[
        ("combblas_serve_requests", obs_export._labels({"kind": "pr"}))
    ] == 2
    obs_export.detach_scrape(stub)
    assert stub._scrape is None


def test_export_cli_renders_jsonl(tmp_path, capsys):
    path = str(tmp_path / "t.jsonl")
    obs.enable(jsonl_path=path, install_hooks=False)
    obs.count("cli.hits", 3)
    obs.dump_jsonl()
    out = str(tmp_path / "m.prom")
    assert obs_export.main([path, "--out", out]) == 0
    text = open(out).read()
    assert ("combblas_cli_hits", "") in obs_export.parse_exposition(
        text
    )


# --- aggregate quantile summaries -------------------------------------------


def test_aggregate_merges_reservoir_quantiles(tmp_path):
    """Satellite: p50/p95/p99 computed once in ``aggregate()`` from
    the histogram reservoirs, across processes."""
    paths = []
    for proc, vals in enumerate(([0.1, 0.2], [0.3, 0.4])):
        obs.reset()
        obs.enable(install_hooks=False)
        for v in vals:
            obs.observe("agg.lat", v)
        p = str(tmp_path / f"p{proc}.jsonl")
        obs.dump_jsonl(p, process=proc, nprocs=2)
        paths.append(p)
    agg = obs.merge_jsonl_files(paths)
    h = agg["histograms"]["agg.lat"]
    assert h["count"] == 4
    assert h["p50"] == pytest.approx(0.25)
    assert h["p99"] == pytest.approx(0.397)


def test_scrape_attach_close_attach_cycle():
    """Round-20 bugfix: repeated serve_metrics()/close() cycles on one
    owner must attach a FRESH working server each time (the old code
    returned the stopped server's dead port), stop() is idempotent
    (a double shutdown() of ThreadingHTTPServer blocks forever), and
    concurrent attaches collapse to one server."""
    import threading
    import types

    obs.enable(install_hooks=False)
    obs.count("serve.requests", 1, kind="bfs")
    stub = types.SimpleNamespace()
    p1 = obs_export.attach_scrape(stub)
    s1 = stub._scrape
    obs_export.detach_scrape(stub)
    assert stub._scrape is None
    s1.stop()  # second stop: must return, not block
    # re-attach after close: a FRESH live server, not the dead one
    p2 = obs_export.attach_scrape(stub)
    assert stub._scrape is not s1 and not stub._scrape._stopped
    text = urllib.request.urlopen(
        f"http://127.0.0.1:{p2}/metrics", timeout=10
    ).read().decode()
    assert "combblas_serve_requests" in text
    # an owner whose scrape was stopped WITHOUT detach (a close path
    # that bypassed detach_scrape) also re-attaches fresh
    stub._scrape.stop()
    p3 = obs_export.attach_scrape(stub)
    assert not stub._scrape._stopped
    # concurrent attaches: one server, one port
    obs_export.detach_scrape(stub)
    ports = []

    def attach():
        ports.append(obs_export.attach_scrape(stub))

    threads = [threading.Thread(target=attach) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(set(ports)) == 1
    obs_export.detach_scrape(stub)
    obs_export.detach_scrape(stub)  # idempotent no-op
    assert stub._scrape is None
