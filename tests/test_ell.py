"""EllParMat: conversion, SpMV across semirings, BFS equivalence."""

import jax
import numpy as np
import pytest

from combblas_tpu import MIN_PLUS, PLUS_TIMES, SELECT2ND_MAX
from combblas_tpu.models.bfs import bfs, traversed_edges
from combblas_tpu.parallel.ellmat import EllParMat
from combblas_tpu.parallel.grid import Grid
from combblas_tpu.parallel.spmat import SpParMat
from combblas_tpu.parallel.spmv import dist_spmv
from combblas_tpu.parallel.vec import DistVec
from conftest import random_dense


@pytest.mark.parametrize("pr,pc", [(2, 2), (2, 4)])
def test_ell_spmv_plus_times(rng, pr, pc):
    grid = Grid.make(pr, pc)
    d = random_dense(rng, 20, 24, 0.3)
    A = SpParMat.from_dense(grid, d)
    E = EllParMat.from_spmat(A)
    assert int(E.getnnz()) == int(A.getnnz())
    x = rng.random(24).astype(np.float32)
    xv = DistVec.from_global(grid, x, align="col")
    y = dist_spmv(PLUS_TIMES, E, xv)
    np.testing.assert_allclose(y.to_global(), d @ x, rtol=1e-5, atol=1e-6)


def test_ell_hub_rows_split_across_buckets(rng):
    """A hub row with degree >> max_k splits over multiple bucket rows whose
    partial folds recombine exactly."""
    grid = Grid.make(2, 2)
    n = 32
    d = np.zeros((n, n), np.float32)
    d[0, 1:] = 1.0  # hub row, degree 31
    d[5, 7] = 2.0
    A = SpParMat.from_dense(grid, d)
    E = EllParMat.from_spmat(A, max_k=2)
    x = rng.random(n).astype(np.float32)
    y = dist_spmv(PLUS_TIMES, E, DistVec.from_global(grid, x, align="col"))
    np.testing.assert_allclose(y.to_global(), d @ x, rtol=1e-5, atol=1e-6)


def test_ell_min_plus(rng):
    grid = Grid.make(2, 2)
    d = random_dense(rng, 12, 12, 0.4)
    A = SpParMat.from_dense(grid, d)
    E = EllParMat.from_spmat(A)
    x = rng.random(12).astype(np.float32)
    xv = DistVec.from_global(grid, x, align="col", fill=np.float32(np.inf))
    y1 = dist_spmv(MIN_PLUS, A, xv).to_global()
    y2 = dist_spmv(MIN_PLUS, E, xv).to_global()
    np.testing.assert_allclose(y2, y1, rtol=1e-6)


def test_ell_bfs_matches_spmat(rng):
    grid = Grid.make(2, 2)
    d = (rng.random((24, 24)) < 0.12).astype(np.float32)
    d = np.maximum(d, d.T)
    np.fill_diagonal(d, 0)
    A = SpParMat.from_dense(grid, d)
    E = EllParMat.from_spmat(A)
    p1, l1, _ = bfs(A, 0)
    p2, l2, _ = bfs(E, 0)
    np.testing.assert_array_equal(l1.to_global(), l2.to_global())
    np.testing.assert_array_equal(p1.to_global(), p2.to_global())
    assert int(traversed_edges(A, p1)) == int(traversed_edges(E, p2))


def test_ell_row_degrees(rng):
    from combblas_tpu.parallel.spmat import ones_i32

    grid = Grid.make(2, 2)
    d = random_dense(rng, 16, 16, 0.3)
    A = SpParMat.from_dense(grid, d)
    E = EllParMat.from_spmat(A, max_k=2)  # force hub-row splitting
    got = E.reduce(PLUS_TIMES, "cols", map_fn=ones_i32).to_global()
    np.testing.assert_array_equal(got, (d != 0).sum(axis=1))


def test_coarse_ladder_matches_fine(rng):
    """ladder='coarse' (power-of-two widths) computes identical SpMV."""
    from combblas_tpu.parallel.ellmat import dist_spmv_ell

    grid = Grid.make(2, 2)
    n = 64
    d = ((rng.random((n, n)) < 0.15) * rng.random((n, n))).astype(np.float32)
    r, c = np.nonzero(d)
    x = rng.random(n).astype(np.float32)
    xv = DistVec.from_global(grid, x, align="col")
    outs = []
    for lad in ("fine", "coarse"):
        E = EllParMat.from_host_coo(
            grid, r.astype(np.int64), c.astype(np.int64),
            d[r, c], n, n, ladder=lad,
        )
        y = dist_spmv_ell(PLUS_TIMES, E, xv)
        outs.append(np.asarray(y.to_global()))
    np.testing.assert_allclose(outs[0], outs[1], rtol=1e-5)
    np.testing.assert_allclose(outs[0], d @ x, rtol=1e-4, atol=1e-5)
