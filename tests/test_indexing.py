"""SpRef / SpAsgn vs numpy fancy indexing.

Mirrors the reference's IndexingTest / SpAsgnTest golden pattern
(ReleaseTests/CMakeLists.txt:41-52) with generated inputs and numpy as the
trusted slow path.
"""

import numpy as np
import pytest

from combblas_tpu.parallel.grid import Grid
from combblas_tpu.parallel.indexing import spasgn, subsref
from combblas_tpu.parallel.spmat import SpParMat
from conftest import random_dense


@pytest.mark.parametrize("p", [1, 2])
def test_subsref_matches_numpy(rng, p):
    grid = Grid.make(p, p)
    d = random_dense(rng, 20, 16, 0.3)
    A = SpParMat.from_dense(grid, d)
    ri = rng.integers(0, 20, size=7)
    ci = rng.integers(0, 16, size=5)
    B = subsref(A, ri, ci)
    assert (B.nrows, B.ncols) == (7, 5)
    np.testing.assert_allclose(B.to_dense(), d[np.ix_(ri, ci)], rtol=1e-6)


def test_subsref_duplicate_indices(rng):
    grid = Grid.make(2, 2)
    d = random_dense(rng, 12, 12, 0.4)
    A = SpParMat.from_dense(grid, d)
    ri = np.array([3, 3, 0, 11])
    ci = np.array([5, 5, 5, 1])
    B = subsref(A, ri, ci)
    np.testing.assert_allclose(B.to_dense(), d[np.ix_(ri, ci)], rtol=1e-6)


def test_subsref_permutation_roundtrip(rng):
    """A(p, p) with a permutation p — the Graph500 kernel-1 relabeling use
    (TopDownBFS.cpp:307's A(nonisov, nonisov) SpRef)."""
    grid = Grid.make(2, 2)
    d = random_dense(rng, 16, 16, 0.3)
    A = SpParMat.from_dense(grid, d)
    p = rng.permutation(16)
    B = subsref(A, p, p)
    np.testing.assert_allclose(B.to_dense(), d[np.ix_(p, p)], rtol=1e-6)


def test_spasgn_matches_numpy(rng):
    grid = Grid.make(2, 2)
    d = random_dense(rng, 16, 16, 0.3)
    A = SpParMat.from_dense(grid, d)
    ri = rng.choice(16, size=6, replace=False)
    ci = rng.choice(16, size=4, replace=False)
    bd = random_dense(rng, 6, 4, 0.6)
    B = SpParMat.from_dense(grid, bd)
    out = spasgn(A, ri, ci, B)
    expect = d.copy()
    expect[np.ix_(ri, ci)] = bd
    np.testing.assert_allclose(out.to_dense(), expect, rtol=1e-6)


def test_spasgn_preserves_outside(rng):
    grid = Grid.make(2, 2)
    d = random_dense(rng, 12, 12, 0.5)
    A = SpParMat.from_dense(grid, d)
    ri = np.array([0, 5])
    ci = np.array([1, 7])
    bd = np.zeros((2, 2), np.float32)  # assigning an empty block clears it
    bd[0, 0] = 9.0
    B = SpParMat.from_dense(grid, bd, capacity=4)
    out = spasgn(A, ri, ci, B).to_dense()
    expect = d.copy()
    expect[np.ix_(ri, ci)] = bd
    np.testing.assert_allclose(out, expect, rtol=1e-6)
