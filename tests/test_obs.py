"""Telemetry subsystem (combblas_tpu/obs): registry, spans, JSONL
round-trip, multihost merge, zero-cost-when-disabled, and the obs_smoke
bench trace against the documented schema (docs/observability.md)."""

import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from combblas_tpu import obs
from combblas_tpu.models.bfs import (
    _bfs_level_step,
    _global_ids,
    bfs,
    bfs_levels_instrumented,
    clear_bfs_caches,
)
from combblas_tpu.parallel.grid import Grid
from combblas_tpu.parallel.spmat import SpParMat
from combblas_tpu.semiring import SELECT2ND_MAX

from conftest import random_dense


@pytest.fixture(autouse=True)
def _clean_obs():
    obs.disable()
    obs.reset()
    yield
    obs.disable()
    obs.reset()


def _graph(rng, n=48, density=0.12, grid_shape=(2, 2)):
    grid = Grid.make(*grid_shape)
    d = (rng.random((n, n)) < density).astype(np.float32)
    d = np.maximum(d, d.T)
    np.fill_diagonal(d, 0.0)
    return SpParMat.from_dense(grid, d), d


# --- registry ---------------------------------------------------------------


def test_registry_counters_gauges_histograms():
    obs.enable(install_hooks=False)
    obs.count("c", 2)
    obs.count("c", 3)
    obs.count("c", 1, kernel="x")  # distinct labeled series
    obs.gauge("g", 1.5, op="summa")
    obs.observe("h", 0.1)
    obs.observe("h", 0.3)
    r = obs.registry
    assert r.get_counter("c") == 5
    assert r.get_counter("c", kernel="x") == 1
    assert r.get_gauge("g", op="summa") == 1.5
    h = r.get_histogram("h")
    assert h["count"] == 2 and abs(h["sum"] - 0.4) < 1e-9
    assert h["min"] == 0.1 and h["max"] == 0.3
    kinds = {rec["kind"] for rec in r.snapshot()}
    assert kinds == {"counter", "gauge", "histogram"}


def test_span_nesting_events_and_table():
    obs.enable(install_hooks=False)
    with obs.span("outer", scale=4):
        obs.span_event("tick", i=0)
        with obs.span("inner"):
            time.sleep(0.001)
    table = obs.report()
    assert set(table) >= {"outer", "inner"}
    assert table["outer"][0] >= table["inner"][0] > 0
    inner = [s for s in obs._spans.log if s["name"] == "inner"][0]
    assert inner["path"] == "outer/inner"
    outer = [s for s in obs._spans.log if s["name"] == "outer"][0]
    assert outer["attrs"] == {"scale": 4}
    assert outer["events"][0]["name"] == "tick"


def test_timers_shim_still_accumulates_when_obs_disabled():
    from combblas_tpu.utils import timers

    timers.reset_all()
    assert not obs.ENABLED
    with timers.phase("shim_phase"):
        pass
    assert "shim_phase" in timers.report()
    assert timers.get("shim_phase") >= 0
    # but the metrics registry stays untouched
    assert obs.registry.empty()


# --- zero-cost-when-disabled ------------------------------------------------


def _bare_levels(A, source, iters):
    """The instrumented BFS's exact step loop with NO obs calls — the
    no-obs baseline for the overhead comparison."""
    grid = A.grid
    n = A.nrows
    row_gids = _global_ids(grid, grid.pr, grid.local_rows(n), n, "row")
    col_gids = _global_ids(
        grid, grid.pc, grid.local_cols(A.ncols), A.ncols, "col"
    )
    parents = jnp.where(row_gids == source, jnp.int32(source), -1)
    levels = jnp.where(row_gids == source, 0, -1).astype(jnp.int32)
    x = jnp.where(col_gids == source, jnp.int32(source), -1)
    for hop in range(iters):
        parents, levels, x, nnew = _bfs_level_step(
            SELECT2ND_MAX, A, parents, levels, x, row_gids, jnp.int32(hop)
        )
        if int(nnew) == 0:
            break
    return parents


def test_disabled_instrumentation_is_free(rng):
    A, d = _graph(rng, n=64)
    assert not obs.ENABLED
    # warm both paths (compile once, identical program underneath)
    p1, l1, n1 = bfs_levels_instrumented(A, 0)
    _bare_levels(A, 0, 64)
    # 1) no bookkeeping: registry AND span log stay empty
    assert obs.registry.empty()
    assert obs._spans.empty()
    # parity with the one-launch kernel
    p2, l2, n2 = bfs(A, 0)
    np.testing.assert_array_equal(
        np.asarray(p1.to_global()), np.asarray(p2.to_global())
    )
    assert n1 == int(n2)

    # 2) <5% wall-time overhead vs the uninstrumented twin loop. Both
    #    drive the same compiled step program, so the delta IS the guard
    #    cost. Samples are INTERLEAVED (bare, instr, bare, instr, ...)
    #    and min-filtered so a CPU load spike (parallel test runners)
    #    cannot land on only one side of the comparison.
    def sample(fn):
        t0 = time.perf_counter()
        for _ in range(3):
            fn()
        return time.perf_counter() - t0

    bare_t, instr_t = [], []
    for _ in range(9):
        bare_t.append(sample(lambda: _bare_levels(A, 0, 64)))
        instr_t.append(sample(lambda: bfs_levels_instrumented(A, 0)))
    t_bare, t_instr = min(bare_t), min(instr_t)
    assert t_instr <= t_bare * 1.05 + 0.005, (t_instr, t_bare)
    assert obs.registry.empty()  # still nothing recorded


def test_windowed_dot_counters_gated(rng):
    """ISSUE 5 satellite: a forced windowed-dot SpGEMM emits the
    ``spgemm.auto.tier{tier=windowed}`` counter and the 2D skip
    counters under obs — and NOTHING when disabled (the zero-cost gate
    extended to the round-7 counter series)."""
    from combblas_tpu import PLUS_TIMES
    from combblas_tpu.parallel.grid import Grid
    from combblas_tpu.parallel.spgemm import spgemm_auto
    from combblas_tpu.parallel.spmat import SpParMat

    grid = Grid.make(1, 1)
    m = 64
    r = rng.integers(0, m, 300).astype(np.int64)
    c = rng.integers(0, m, 300).astype(np.int64)
    A = SpParMat.from_global_coo(
        grid, r, c, np.ones(300, np.float32), m, m
    )
    assert not obs.ENABLED
    spgemm_auto(
        PLUS_TIMES, A, A, tier="windowed", backend="dot",
        block_rows=32, block_cols=32,
    )
    assert obs.registry.empty()  # disabled: zero bookkeeping
    obs.enable(install_hooks=False)
    try:
        obs.reset()
        spgemm_auto(
            PLUS_TIMES, A, A, tier="windowed", backend="dot",
            block_rows=32, block_cols=32,
        )
        assert obs.registry.get_counter(
            "spgemm.auto.tier", tier="windowed", sr="plus_times"
        ) == 1
        assert obs.registry.get_gauge(
            "spgemm.windowed.col_windows"
        ) == 2
        assert obs.registry.get_counter(
            "spgemm.windowed.col_windows_skipped"
        ) >= 0
        assert obs.registry.get_gauge(
            "spgemm.windowed.panel_cells"
        ) == 512 * 512
    finally:
        obs.disable()
        obs.reset()


@pytest.mark.slow  # round 12 (tier-1 budget): 16 s of r9 kernel
# compiles purely for counter bookkeeping; the zero-cost gate
# MECHANISM stays tier-1 via the round-10/11/12 gate tests
def test_round9_pipeline_pack_3d_counters_gated(rng):
    """ISSUE 7 satellite: the round-9 series — pipelined-carousel
    overlap count, packed-launch counters, and the 3D layers gauge —
    are emitted under obs and cost NOTHING when disabled (the zero-cost
    gate extended to the round-9 series)."""
    from combblas_tpu import PLUS_TIMES
    from combblas_tpu.parallel.grid import Grid
    from combblas_tpu.parallel.mesh3d import Grid3D
    from combblas_tpu.parallel.spgemm import spgemm_auto, spgemm_windowed
    from combblas_tpu.parallel.spmat import SpParMat

    grid = Grid.make(2, 2)
    m = 64
    r = rng.integers(0, m, 400).astype(np.int64)
    c = rng.integers(0, m, 400).astype(np.int64)
    A = SpParMat.from_global_coo(
        grid, r, c, np.ones(400, np.float32), m, m
    )
    assert not obs.ENABLED
    spgemm_windowed(
        PLUS_TIMES, A, A, block_rows=16, backend="scatter", ring=True
    )
    assert obs.registry.empty()  # disabled: zero bookkeeping
    assert obs._spans.empty()
    obs.enable(install_hooks=False)
    try:
        obs.reset()
        # fresh static config (different block_rows) forces a retrace so
        # the trace-time counters fire under the enabled registry
        spgemm_windowed(
            PLUS_TIMES, A, A, block_rows=8, backend="scatter", ring=True
        )
        assert obs.registry.get_counter(
            "spgemm.pipeline.stages_overlapped"
        ) == grid.pr - 1
        assert obs.registry.get_counter(
            "trace.summa_spgemm_windowed", backend="scatter", ring=True
        ) == 1
        packed = obs.registry.get_counter("spgemm.windowed.windows_packed")
        assert packed >= 1
        ratio = obs.registry.get_gauge("spgemm.windowed.pack_ratio")
        assert 0 < ratio <= 1.0
        # the 3D route records its layer count
        obs.reset()
        g3 = Grid3D.make(2, 2, 2)
        spgemm_auto(
            PLUS_TIMES, A, A, tier="windowed3d", grid3=g3,
            backend="scatter", block_rows=16,
        )
        assert obs.registry.get_gauge("spgemm.summa3d.layers") == 2
        assert obs.registry.get_counter(
            "spgemm.auto.tier", tier="windowed3d", sr="plus_times"
        ) == 1
    finally:
        obs.disable()
        obs.reset()


def test_round10_tuner_counters_gated(rng, tmp_path, monkeypatch):
    """ISSUE 8 satellite: the round-10 tuner series — store hit/miss,
    plan-source, entries — are emitted under obs and cost NOTHING when
    disabled (the zero-cost gate extended to the plan store)."""
    from combblas_tpu import PLUS_TIMES
    from combblas_tpu.parallel.grid import Grid
    from combblas_tpu.parallel.spgemm import spgemm_auto
    from combblas_tpu.parallel.spmat import SpParMat
    from combblas_tpu.tuner import PlanRecord, config, spgemm_plan_key
    from combblas_tpu.tuner import store as tstore

    monkeypatch.setenv(config.ENV_PLAN_STORE, str(tmp_path))
    tstore._reset_for_tests()
    try:
        grid = Grid.make(1, 1)
        m = 64
        r = rng.integers(0, m, 300).astype(np.int64)
        c = rng.integers(0, m, 300).astype(np.int64)
        A = SpParMat.from_global_coo(
            grid, r, c, np.ones(300, np.float32), m, m
        )
        assert not obs.ENABLED
        spgemm_auto(PLUS_TIMES, A, A)  # store miss -> heuristic route
        assert obs.registry.empty()  # disabled: zero bookkeeping
        obs.enable(install_hooks=False)
        obs.reset()
        st = tstore.get_store()
        st.put(
            spgemm_plan_key(PLUS_TIMES, A, A, "scatter"),
            PlanRecord(tier="scan", cost_s=0.2),
        )
        spgemm_auto(PLUS_TIMES, A, A)
        assert obs.registry.get_counter(
            "tuner.store.hits", op="spgemm"
        ) == 1
        assert obs.registry.get_counter(
            "spgemm.auto.plan_source", source="store", tier="scan",
            op="spgemm",
        ) == 1
        assert obs.registry.get_gauge(
            "tuner.store.entries", dir=st.path
        ) == 1
    finally:
        obs.disable()
        obs.reset()
        tstore._reset_for_tests()


def test_round13_merge_counters_gated(rng):
    """ISSUE 11 satellite: the round-13 merge-tier series —
    ``spgemm.merge.tier`` and the ``merge``-labeled trace counter —
    are emitted under obs and cost NOTHING when disabled (the
    zero-cost gate extended to the merge tiers).  The heavier 3D
    counters (hash_overflow, piece_overflow, 3D stages_overlapped)
    are asserted by tests/test_spgemm_merge.py on the same
    ``obs.ENABLED``-guarded code paths."""
    from combblas_tpu import PLUS_TIMES
    from combblas_tpu.parallel.grid import Grid
    from combblas_tpu.parallel.spgemm import spgemm
    from combblas_tpu.parallel.spmat import SpParMat

    grid = Grid.make(1, 1)
    m = 64
    r = rng.integers(0, m, 300).astype(np.int64)
    c = rng.integers(0, m, 300).astype(np.int64)
    A = SpParMat.from_global_coo(
        grid, r, c, np.ones(300, np.float32), m, m
    )
    assert not obs.ENABLED
    spgemm(PLUS_TIMES, A, A, merge="runs")
    assert obs.registry.empty()  # disabled: zero bookkeeping
    assert obs._spans.empty()
    obs.enable(install_hooks=False)
    try:
        obs.reset()
        spgemm(PLUS_TIMES, A, A, merge="runs")
        assert obs.registry.get_counter(
            "spgemm.merge.tier", tier="runs", source="arg", op="spgemm"
        ) == 1
    finally:
        obs.disable()
        obs.reset()


# --- JSONL round-trip + multihost merge -------------------------------------


def test_jsonl_roundtrip_and_aggregate(tmp_path):
    path = str(tmp_path / "trace.jsonl")
    obs.enable(jsonl_path=path, install_hooks=False)
    with obs.span("phase.a", stage=1):
        obs.span_event("it", round=1, chaos=0.5)
    with obs.span("phase.a", stage=2):
        pass
    obs.count("drops", 3)
    obs.count("drops", 4)
    obs.gauge("imbalance", 2.0, op="spgemm")
    obs.observe("k1.generate_s", 0.25)
    out = obs.dump_jsonl()
    assert out == path
    recs = obs.parse_jsonl(path)  # validates every line against schema
    assert recs[0]["kind"] == "meta" and recs[0]["schema"] == obs.SCHEMA
    agg = obs.aggregate(recs)
    assert agg["counters"]["drops"] == 7
    assert agg["span_table"]["phase.a"][1] == 2
    assert agg["histograms"]["k1.generate_s"]["count"] == 1
    span = [r for r in recs if r["kind"] == "span"][0]
    assert span["events"][0]["chaos"] == 0.5


def test_jsonl_validation_rejects_garbage(tmp_path):
    bad = tmp_path / "bad.jsonl"
    bad.write_text(json.dumps({"v": 1, "kind": "span", "name": "x"}) + "\n")
    with pytest.raises(ValueError):
        obs.parse_jsonl(str(bad))
    worse = tmp_path / "worse.jsonl"
    worse.write_text(json.dumps({"v": 99, "kind": "meta"}) + "\n")
    with pytest.raises(ValueError):
        obs.parse_jsonl(str(worse))


def test_multihost_merge(tmp_path):
    """Per-process JSONL files merged host-side: counters add, spans
    keep their process id (the multi-controller aggregation path)."""
    paths = []
    for proc in (0, 1):
        obs.reset()
        obs.enable(install_hooks=False)
        obs.count("redistribute.dropped", 10 * (proc + 1))
        obs.gauge("hbm.used", 1.0 + proc)
        obs.observe("hop_s", 0.1 * (proc + 1))
        with obs.span("bfs.hop", hop=proc):
            pass
        p = str(tmp_path / f"events.p{proc}.jsonl")
        obs.dump_jsonl(p, process=proc, nprocs=2)
        paths.append(p)
    merged_path = str(tmp_path / "merged.jsonl")
    agg = obs.merge_jsonl_files(paths, merged_path)
    assert agg["counters"]["redistribute.dropped"] == 30
    assert agg["histograms"]["hop_s"]["count"] == 2
    assert agg["span_table"]["bfs.hop"][1] == 2
    assert sorted(s["process"] for s in agg["spans"]) == [0, 1]
    assert {"hbm.used@p0", "hbm.used@p1"} <= set(agg["gauges"])
    # the merged file itself round-trips through the validator
    again = obs.parse_jsonl(merged_path)
    assert again[0]["kind"] == "meta" and again[0]["nprocs"] == 2


@pytest.mark.parametrize("grid_shape", [(2, 4), (1, 1)])
def test_psum_counters_device_aggregation(grid_shape):
    """The in-program add-monoid counter path: per-device counter blocks
    psum'd over the mesh via parallel/collectives (8-device fixture)."""
    grid = Grid.make(*grid_shape)
    pr, pc = grid_shape
    local = np.arange(pr * pc * 3, dtype=np.int32).reshape(pr, pc, 3)
    tot = np.asarray(obs.psum_counters(grid, jnp.asarray(local)))
    np.testing.assert_array_equal(tot, local.sum(axis=(0, 1)))


# --- instrumented hot paths -------------------------------------------------


def test_instrumented_bfs_records_per_hop_frontier(rng, tmp_path):
    A, d = _graph(rng, n=48)
    path = str(tmp_path / "bfs.jsonl")
    obs.enable(jsonl_path=path, install_hooks=False)
    parents, levels, niter = bfs_levels_instrumented(A, 0)
    obs.dump_jsonl()
    recs = obs.parse_jsonl(path)
    hops = [r for r in recs if r["kind"] == "span" and r["name"] == "bfs.hop"]
    assert len(hops) == niter
    curve = []
    for h in hops:
        ev = [e for e in h["events"] if e["name"] == "frontier"]
        assert len(ev) == 1
        curve.append(ev[0]["nnz"])
    # the frontier curve sums to the discovered set minus the source
    assert sum(curve) == int((np.asarray(parents.to_global()) >= 0).sum()) - 1
    # dispatch counters rode along (trace-or-call counts, > 0 either way)
    assert obs.registry.get_counter("spmv.dispatch",
                                    kernel="dist_spmv_masked") > 0


def test_spgemm_and_redistribute_metrics(rng):
    from combblas_tpu.parallel.spgemm import spgemm
    from combblas_tpu.semiring import PLUS_TIMES

    obs.enable(install_hooks=False, device_sync=True)
    A, d = _graph(rng, n=32)
    C = spgemm(PLUS_TIMES, A, A)
    want = d @ d
    np.testing.assert_allclose(np.asarray(C.to_dense()), want, rtol=1e-5)
    assert obs.registry.get_counter("spgemm.symbolic_fill_slots") > 0
    assert obs.registry.get_counter("spgemm.realized_nnz") == int(
        (want != 0).sum()
    )
    assert obs.registry.get_gauge("spgemm.load_imbalance") >= 1.0
    assert "spgemm" in obs.report()

    # redistribute drop accounting (zero on success, but present)
    from combblas_tpu.parallel.redistribute import from_device_coo

    grid = A.grid
    n = 32
    r, c = np.nonzero(d)
    ndev = grid.pr * grid.pc
    chunk = -(-len(r) // ndev)
    pad = chunk * ndev - len(r)
    r3 = np.concatenate([r.astype(np.int32), np.full(pad, n, np.int32)])
    c3 = np.concatenate([c.astype(np.int32), np.full(pad, n, np.int32)])
    shape = (grid.pr, grid.pc, chunk)
    M = from_device_coo(
        grid,
        jax.device_put(r3.reshape(shape), grid.tile_sharding()),
        jax.device_put(c3.reshape(shape), grid.tile_sharding()),
        jnp.ones(shape, jnp.float32),
        n, n,
    )
    np.testing.assert_array_equal(
        np.asarray(M.to_dense()) != 0, d != 0
    )
    assert obs.registry.get_counter("redistribute.dropped", default=-1) == 0
    assert "redistribute" in obs.report()


def test_bfs_caches_bounded_cleared_and_exported():
    from combblas_tpu.models import bfs as bfs_mod

    clear_bfs_caches()
    assert bfs_mod._gid_blocks.cache_info().currsize == 0
    assert bfs_mod._gid_blocks.cache_info().maxsize == 16
    assert bfs_mod._iota_operand.cache_info().maxsize == 8
    bfs_mod._iota_operand(16)
    bfs_mod._iota_operand(16)
    ci = bfs_mod._iota_operand.cache_info()
    assert ci.currsize == 1 and ci.hits >= 1
    obs.enable(install_hooks=False)
    snap = {
        (r["name"]): r["value"]
        for r in obs.metrics_snapshot()
        if r["kind"] == "gauge"
    }
    assert snap["cache.bfs.iota_operand.size"] == 1
    assert snap["cache.bfs.iota_operand.hits"] >= 1
    assert snap["cache.bfs.gid_blocks.maxsize"] == 16
    clear_bfs_caches()
    assert bfs_mod._iota_operand.cache_info().currsize == 0


# --- the smallest bench entrypoint, parsed against the schema ---------------


def test_obs_smoke_bench_trace_matches_schema(tmp_path):
    sys.path.insert(
        0,
        os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                     "benchmarks"),
    )
    import obs_smoke

    out = str(tmp_path / "smoke.jsonl")
    try:
        path = obs_smoke.run(
            scale=6, edgefactor=8, out_path=out, grid_shape=(2, 2),
            cache_dir=str(tmp_path / "cache"),
        )
    finally:
        # undo the smoke run's global compile-cache redirection —
        # including the idempotence guard's committed dir, or a later
        # same-process enable_compile_cache() would refuse to run
        from combblas_tpu.utils import compile_cache as _cc

        _cc._reset_for_tests()
        jax.config.update("jax_compilation_cache_dir", None)
    recs = obs.parse_jsonl(path)  # schema-validates every line
    agg = obs.aggregate(recs)
    # per-hop BFS spans with frontier-nnz events
    hops = [r for r in recs if r["kind"] == "span" and r["name"] == "bfs.hop"]
    assert hops
    assert all(
        any(e["name"] == "frontier" and "nnz" in e for e in h["events"])
        for h in hops
    )
    # SpGEMM fill-in counters (symbolic + realized under DEVICE_SYNC)
    assert agg["counters"]["spgemm.symbolic_fill_slots"] > 0
    assert agg["counters"]["spgemm.realized_nnz"] > 0
    # redistribute drop accounting
    assert "redistribute.dropped" in agg["counters"]
    # compile-cache hit/miss counters (values platform-dependent; the
    # counters themselves are part of the documented trace)
    assert "compile_cache.hits" in agg["counters"]
    assert "compile_cache.misses" in agg["counters"]
    # BFS lru-cache gauges exported via the provider
    assert any(k.startswith("cache.bfs.") for k in agg["gauges"])
    # round 15: the serve-path request traces ride in the same dump —
    # the smallest end-to-end latency-decomposition trace
    traces = [r for r in recs if r["kind"] == "trace"]
    assert traces and all(
        r["name"] == "serve.request" for r in traces
    )
    for r in traces:
        assert abs(
            sum(st["s"] for st in r["stages"]) - r["wall_s"]
        ) < 1e-6


def test_round11_dynamic_counters_gated(rng):
    """ISSUE 9 satellite: the round-11 dynamic-mutation series — delta
    depth/ops, merge mode/latency, refresh runs, serve update counters
    — are emitted under obs and cost NOTHING when disabled."""
    from combblas_tpu.dynamic import DeltaBatch, DeltaBuffer, apply_delta
    from combblas_tpu.parallel.grid import Grid
    from combblas_tpu.serve import GraphEngine, ServeConfig

    n = 48
    r = rng.integers(0, n, 200)
    c = rng.integers(0, n, 200)
    eng = GraphEngine.from_coo(
        Grid.make(1, 1), np.concatenate([r, c]), np.concatenate([c, r]),
        n, kinds=("bfs",), keep_coo=True,
    )
    present = set(
        zip(eng.version.host_coo[0].tolist(),
            eng.version.host_coo[1].tolist())
    )
    a, b = next(
        (a, b) for a in range(n) for b in range(n)
        if a != b and (a, b) not in present
    )
    ops = [("insert", a, b), ("insert", b, a)]

    def exercise():
        buf = DeltaBuffer(capacity=8, nrows=n, ncols=n)
        buf.add_many(ops)
        batch = buf.drain()
        v = apply_delta(eng.version, batch, kinds=eng.kinds())
        eng.refresh("bfs", root=int(r[0]))
        srv = eng.serve(ServeConfig(
            lane_widths=(1,), update_autostart=False,
        ))
        srv.submit_update([("delete", a, b), ("delete", b, a)])
        srv.pump_updates(force=True)
        srv.close()
        return v

    assert not obs.ENABLED
    exercise()
    assert obs.registry.empty()  # disabled: zero bookkeeping

    obs.enable(install_hooks=False)
    try:
        obs.reset()
        eng._analytics.clear()
        exercise()
        g = obs.registry.get_counter
        assert g("dynamic.delta.ops", op="insert") == 2
        assert g("dynamic.delta.batches") >= 1
        assert g("dynamic.merge.applied", mode="incremental") >= 1
        assert obs.registry.get_histogram(
            "dynamic.merge.latency_s"
        )["count"] >= 1
        assert g("dynamic.refresh.runs", kind="bfs", mode="cold") == 1
        assert g("serve.update.submitted") == 1
        assert g("serve.update.merges", mode="incremental") >= 1
        assert obs.registry.get_histogram(
            "serve.update.coalesced"
        )["count"] >= 1
    finally:
        obs.disable()
        obs.reset()


def test_round14_pool_fleet_counters_gated(rng, tmp_path):
    """ISSUE 12 satellite: the round-14 series — pool residency
    gauges/counters, WFQ rounds/served/deficit, fleet routing, and the
    checkpoint histograms — are emitted under obs and cost NOTHING
    when disabled (the zero-cost gate extended to the pool/fleet)."""
    import os

    from combblas_tpu.parallel.grid import Grid
    from combblas_tpu.serve import EnginePool, FleetRouter, ServeConfig
    from combblas_tpu.utils import checkpoint

    grid = Grid.make(1, 1)
    n = 32
    r = rng.integers(0, n, 120)
    c = rng.integers(0, n, 120)
    rows = np.concatenate([r, c])
    cols = np.concatenate([c, r])
    cfg = ServeConfig(lane_widths=(1,), update_autostart=False)

    def exercise(tag):
        pool = EnginePool(grid)
        pool.add_tenant(
            "a", rows, cols, n, config=cfg, kinds=("bfs",)
        )
        psrv = pool.serve()
        f = psrv.submit("a", "bfs", 1)
        while psrv.pump(force=True):
            pass
        assert f.exception(timeout=0) is None
        assert pool.evict("a")
        pool.admit("a")  # re-admission: the rebuild path
        path = os.path.join(tmp_path, f"v-{tag}.npz")
        checkpoint.save_version(path, pool.engine("a").version)
        checkpoint.load_version(path, grid)
        fr = FleetRouter([pool.server("a")])
        fr.submit("bfs", 2)
        pool.server("a").scheduler.fail_pending(
            RuntimeError("gate teardown")
        )

    assert not obs.ENABLED
    exercise("off")
    assert obs.registry.empty()  # disabled: zero bookkeeping

    obs.enable(install_hooks=False)
    try:
        obs.reset()
        exercise("on")
        g = obs.registry.get_counter
        assert g("serve.pool.admits", tenant="a") == 2  # build+rebuild
        assert g("serve.pool.evictions", tenant="a") == 1
        assert obs.registry.get_gauge("serve.pool.resident_bytes") > 0
        assert obs.registry.get_gauge("serve.pool.resident_tenants") == 1
        assert obs.registry.get_histogram(
            "serve.pool.rebuild_s"
        )["count"] == 2
        assert g("serve.wfq.rounds") >= 1
        assert g("serve.wfq.served", tenant="a") >= 1
        assert obs.registry.get_gauge(
            "serve.wfq.deficit", tenant="a"
        ) is not None
        assert g("serve.fleet.submitted", replica=0) == 1
        assert obs.registry.get_gauge("serve.fleet.replicas") == 1
        assert obs.registry.get_histogram(
            "serve.checkpoint.save_s"
        )["count"] == 1
        assert obs.registry.get_histogram(
            "serve.checkpoint.load_s"
        )["count"] == 1
        # tenant-labeled scheduler series (end-to-end labels)
        assert obs.registry.get_gauge(
            "serve.queue.depth", tenant="a"
        ) is not None
    finally:
        obs.disable()
        obs.reset()


def test_round16_durability_counters_gated(rng, tmp_path):
    """ISSUE 14 satellite: the round-16 durability & self-healing
    series — WAL appends/truncates, checkpoint reasons, recovery
    replay counters, fleet versions_behind — are emitted under obs and
    cost NOTHING when disabled (one attribute read on every hot
    path)."""
    import os

    from combblas_tpu.dynamic import open_wal, recover_version
    from combblas_tpu.parallel.grid import Grid
    from combblas_tpu.serve import FleetRouter, GraphEngine, \
        Server, ServeConfig

    grid = Grid.make(1, 1)
    n = 32
    r = rng.integers(0, n, 120)
    c = rng.integers(0, n, 120)
    rows = np.concatenate([r, c])
    cols = np.concatenate([c, r])
    present = set(zip(rows.tolist(), cols.tolist()))
    pairs = [
        (i, j) for i in range(n) for j in range(i + 1, n)
        if (i, j) not in present and (j, i) not in present
    ][:2]

    def exercise(tag):
        d = os.path.join(tmp_path, f"wal-{tag}")
        cfg = ServeConfig(lane_widths=(1,), update_autostart=False,
                          update_flush=1, wal_dir=d,
                          # retain only the newest snapshot so the
                          # manual checkpoint actually truncates
                          # (default retain=2 keeps the bootstrap
                          # snapshot, whose seq pins the WAL suffix)
                          checkpoint_retain=1)
        eng = GraphEngine.from_coo(
            grid, rows, cols, n, kinds=("bfs",), keep_coo=True
        )
        srv = Server(eng, cfg)  # bootstrap checkpoint
        (a, b), (a2, b2) = pairs
        f = srv.submit_update([("insert", a, b), ("insert", b, a)])
        srv.pump_updates(force=True)
        assert f.exception(timeout=0) is None
        srv.checkpoint_now()  # truncates the replayed WAL prefix
        wal = open_wal(d)
        recover_version(d, wal, grid, kinds=("bfs",))
        wal.close()
        srv.scheduler.close()
        # fleet surface: fan-out generation gauges
        fr = FleetRouter.build(
            grid, rows, cols, n, replicas=2, kinds=("bfs",),
            config=ServeConfig(lane_widths=(1,), update_flush=1,
                               update_max_delay_s=0.005),
            start=False,
        )
        fr.replicas[0].submit_update(
            [("insert", a2, b2), ("insert", b2, a2)]
        )
        fr.replicas[0].pump_updates(force=True)
        fr.fan_out()
        fr.close(drain=False)

    assert not obs.ENABLED
    exercise("off")
    assert obs.registry.empty()  # disabled: zero bookkeeping

    obs.enable(install_hooks=False)
    try:
        obs.reset()
        exercise("on")
        g = obs.registry.get_counter
        assert g("serve.wal.appends") == 1  # the acknowledged write
        assert obs.registry.get_histogram(
            "serve.wal.append_s"
        )["count"] == 1
        assert g("serve.wal.truncated") >= 1
        assert g("serve.checkpoint.auto", reason="bootstrap") == 1
        assert g("serve.checkpoint.auto", reason="manual") == 1
        assert g("serve.recovery.runs") == 1
        assert g("serve.recovery.replayed_ops") == 0  # ckpt covered it
        assert obs.registry.get_histogram(
            "serve.recovery.recover_s"
        )["count"] == 1
        assert obs.registry.get_gauge(
            "serve.fleet.versions_behind", replica=1
        ) == 0
        assert g("serve.fleet.fanout") == 1
    finally:
        obs.disable()
        obs.reset()


def test_round17_procfleet_counters_gated():
    """ISSUE 15 satellite: the round-17 process-fleet IPC series —
    per-RPC latency, per-request deadline timeouts, quarantine — are
    emitted under obs and cost NOTHING when disabled.  Exercised
    through the parent-side replica client over an in-process stub
    responder (a socketpair, not a subprocess: the gate measures the
    ROUTER's bookkeeping, and must stay tier-1 cheap)."""
    import socket
    import threading
    import time as _time

    from combblas_tpu.serve.ipc import Channel, ChannelClosed
    from combblas_tpu.serve.procfleet import (
        IpcTimeoutError,
        ReplicaDeadError,
        ReplicaProc,
    )

    def exercise(tag):
        a, b = socket.socketpair()
        stop = threading.Event()
        ch_child = Channel(b)

        def responder():
            while not stop.is_set():
                try:
                    m = ch_child.recv(timeout=0.05)
                except socket.timeout:
                    continue
                except ChannelClosed:
                    return
                if m.get("op") == "ping":
                    ch_child.send({"id": m["id"], "ok": True,
                                   "result": {"pong": True}})
                # "hang" never answers: the deadline sweep's case

        threading.Thread(target=responder, daemon=True).start()
        rp = ReplicaProc(0, None, Channel(a))
        assert rp.call("ping", timeout_s=10)["pong"] is True
        f = rp.rpc("hang", timeout_s=0.15)
        assert isinstance(f.exception(timeout=10), IpcTimeoutError)
        rp.quarantine(ReplicaDeadError(f"gate teardown {tag}"))
        stop.set()

    assert not obs.ENABLED
    exercise("off")
    assert obs.registry.empty()  # disabled: zero bookkeeping

    obs.enable(install_hooks=False)
    try:
        obs.reset()
        exercise("on")
        g = obs.registry.get_counter
        assert obs.registry.get_histogram(
            "serve.procfleet.rpc_latency_s", op="ping"
        )["count"] == 1
        assert g("serve.procfleet.ipc_timeouts", op="hang") == 1
        assert g("serve.procfleet.quarantined", replica=0) == 1
    finally:
        obs.disable()
        obs.reset()


def test_round18_fleet_obs_gated(tmp_path):
    """ISSUE 16: the round-18 fleet-observability plane — IPC channel
    accounting, per-replica deadline misses, the supervision timeline
    — is emitted under obs and costs NOTHING when disabled: no
    registry series, no fleetlog file, no flight-recorder traffic.
    Same stub-responder topology as the round-17 gate (the gate
    measures the router's bookkeeping, not subprocess boot)."""
    import socket
    import threading
    import types

    from combblas_tpu.obs.fleetlog import FleetLog
    from combblas_tpu.obs.recorder import FlightRecorder
    from combblas_tpu.serve.ipc import Channel, ChannelClosed
    from combblas_tpu.serve.procfleet import (
        IpcTimeoutError,
        ProcessFleet,
        ReplicaDeadError,
        ReplicaProc,
    )

    def exercise(tag):
        a, b = socket.socketpair()
        stop = threading.Event()
        ch_child = Channel(b)

        def responder():
            while not stop.is_set():
                try:
                    m = ch_child.recv(timeout=0.05)
                except socket.timeout:
                    continue
                except ChannelClosed:
                    return
                if m.get("op") == "ping":
                    ch_child.send({"id": m["id"], "ok": True,
                                   "result": {"pong": True}})
                # "hang" never answers: the deadline sweep's case

        threading.Thread(target=responder, daemon=True).start()
        rp = ReplicaProc(0, None, Channel(a, peer="replica0"))
        assert rp.call("ping", timeout_s=10)["pong"] is True
        f = rp.rpc("hang", timeout_s=0.15)
        assert isinstance(f.exception(timeout=10), IpcTimeoutError)
        # the supervisor's event hook over a stub fleet: the gate must
        # keep the fleetlog file AND the recorder ring untouched
        stub = types.SimpleNamespace(
            replicas=[rp],
            fleetlog=FleetLog(str(tmp_path / f"fleet-{tag}.jsonl")),
            recorder=FlightRecorder(
                out_dir=str(tmp_path / f"rec-{tag}")),
        )
        ProcessFleet._fleet_event(
            stub, "quarantine", replica=0, reason="gate"
        )
        rp.quarantine(ReplicaDeadError(f"gate teardown {tag}"))
        stop.set()
        return stub

    assert not obs.ENABLED
    stub = exercise("off")
    assert obs.registry.empty()  # disabled: zero bookkeeping
    assert not os.path.exists(stub.fleetlog.path)  # no timeline file
    assert stub.recorder.recorded == 0  # no recorder traffic

    obs.enable(install_hooks=False)
    try:
        obs.reset()
        stub = exercise("on")
        g = obs.registry.get_counter
        # channel accounting: both directions, framed byte counts
        assert g("serve.ipc.bytes_out", peer="replica0") > 0
        assert g("serve.ipc.bytes_in", peer="replica0") > 0
        assert obs.registry.get_histogram(
            "serve.ipc.encode_s", peer="replica0"
        )["count"] >= 2  # ping + hang
        assert obs.registry.get_histogram(
            "serve.ipc.decode_s", peer="replica0"
        )["count"] >= 1  # pong
        assert g("serve.ipc.deadline_missed", replica=0) == 1
        # supervision timeline: ring + file + counter + dump
        assert g("serve.fleetlog.events", event="quarantine") == 1
        (ev,) = stub.fleetlog.snapshot()
        assert ev["name"] == "fleet.quarantine"
        assert ev["reason"] == "gate"
        assert os.path.exists(stub.fleetlog.path)
        assert stub.recorder.dumps == 1  # quarantine dumps the ring
    finally:
        obs.disable()
        obs.reset()


def test_fleetlog_jsonl_roundtrip(tmp_path):
    """ISSUE 16 satellite: the supervision timeline is an ordinary
    ``combblas_tpu.fleetlog/v1`` JSONL file — every line passes
    ``validate_record`` via ``parse_jsonl``, reserved envelope fields
    are remapped (never clobbered), and both the ring and the file are
    bounded."""
    from combblas_tpu.obs.fleetlog import FleetLog

    path = str(tmp_path / "fl" / "fleetlog.jsonl")
    fl = FleetLog(path, capacity=4, max_file_events=5, tenant="t0")
    assert not os.path.exists(path)  # lazy: idle fleet leaves no file
    for i in range(7):
        fl.event("spawn", replica=i, kind="oops", ts="clash")
    recs = obs.parse_jsonl(path)  # validate=True: schema-checked
    assert recs[0]["kind"] == "meta"
    assert recs[0]["schema"] == obs.FLEETLOG_SCHEMA
    events = [r for r in recs if r["kind"] == "event"]
    assert len(events) == 5  # file capped at max_file_events
    assert events[0]["name"] == "fleet.spawn"
    assert events[0]["tenant"] == "t0"
    # reserved names remapped, discriminators intact
    assert events[0]["f_kind"] == "oops"
    assert events[0]["f_ts"] == "clash"
    # ring keeps rotating past the file cap, oldest first
    assert [e["replica"] for e in fl.snapshot()] == [3, 4, 5, 6]
    d = fl.describe()
    assert d["recorded"] == 7 and d["file_events"] == 5
    assert d["truncated"] and d["write_errors"] == 0


def test_round21_shard_wire_counters_gated():
    """ISSUE 19 satellite: the round-21 sharded wire-protocol series —
    per-fan payload bytes by direction and encoding, per-hop frontier
    nnz, the router's encoding decision — are emitted under obs and
    cost NOTHING when disabled.  A tiny 2-slice LOCAL engine keeps the
    gate tier-1 cheap (warmup=False: trace counters are someone else's
    gate)."""
    import numpy as np

    from combblas_tpu.serve import ShardedEngine

    n = 24
    rng = np.random.default_rng(5)
    rows = rng.integers(0, n, 90)
    cols = rng.integers(0, n, 90)
    srcs = np.array([0, 7], np.int32)

    def exercise(tag):
        eng = ShardedEngine.build(
            rows, cols, nrows=n, nslices=2, kinds=("bfs",),
            warmup=False, frontier="auto",
        )
        eng.execute("bfs", srcs)
        eng.close()
        return eng

    assert not obs.ENABLED
    exercise("off")
    assert obs.registry.empty()  # disabled: zero bookkeeping

    obs.enable(install_hooks=False)
    try:
        obs.reset()
        eng = exercise("on")
        st = eng.last_exec_stats
        assert st["hops"] >= 1 and st["collects"] == 1
        g = obs.registry.get_counter
        # every fan accounts both directions; labels partition by
        # encoding (sparse/dense frontier hops + the collect fan)
        by_enc = {
            e: g("serve.shard.hop_bytes", direction="out", encoding=e)
            + g("serve.shard.hop_bytes", direction="in", encoding=e)
            for e in ("sparse", "dense", "collect")
        }
        assert by_enc["collect"] > 0
        assert sum(by_enc.values()) == st["bytes_out"] + st["bytes_in"]
        assert by_enc == st["bytes_by_enc"] | {
            e: 0 for e in by_enc if e not in st["bytes_by_enc"]
        }
        # the router's per-hop decision + frontier size distribution
        assert sum(
            g("serve.shard.encoding", choice=c)
            for c in ("sparse", "dense")
        ) == st["hops"]
        h = obs.registry.get_histogram(
            "serve.shard.frontier_nnz", kind="bfs"
        )
        assert h["count"] == st["hops"]
        assert h["max"] == max(st["frontier_nnz"])
    finally:
        obs.disable()
        obs.reset()
