"""Query-serving subsystem (combblas_tpu/serve): lane bucketing,
pad-sentinel hygiene, request/result mapping under concurrency,
backpressure, error isolation, warm-plan zero-retrace contract, and the
compile-cache idempotence satellite.

The batcher property tests are the acceptance gate for the serving
PR: arbitrary arrival counts round to the correct power-of-two bucket,
padded lanes never leak into user results, and results map back to the
right request ids even under concurrent ``submit()``.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from combblas_tpu import obs
from combblas_tpu.models import PAD_ROOT
from combblas_tpu.parallel.grid import Grid
from combblas_tpu.serve import (
    BackpressureError,
    GraphEngine,
    ServeConfig,
    bucket_width,
)
from combblas_tpu.serve.batcher import assemble
from combblas_tpu.utils.rmat import rmat_symmetric_coo


@pytest.fixture(autouse=True)
def _clean_obs():
    obs.disable()
    obs.reset()
    yield
    obs.disable()
    obs.reset()


SCALE = 7
N = 1 << SCALE


@pytest.fixture(scope="module")
def graph():
    rows, cols = rmat_symmetric_coo(jax.random.key(3), SCALE, 8)
    return np.asarray(rows), np.asarray(cols)


@pytest.fixture(scope="module")
def engine(graph):
    rows, cols = graph
    # explicit kinds: sssp over the unweighted graph (unit weights) is
    # intentional here — the default would exclude it (no weights=)
    return GraphEngine.from_coo(
        Grid.make(2, 2), rows, cols, N,
        kinds=("bfs", "sssp", "pagerank", "bc"),
    )


def test_default_kinds_exclude_unweighted_sssp(graph):
    rows, cols = graph
    eng = GraphEngine.from_coo(Grid.make(1, 1), rows, cols, N)
    assert "sssp" not in eng.kinds()  # no weights: hop counts are not
    assert "bfs" in eng.kinds()       # distances — opt in explicitly


def test_bc_symmetry_claim_is_verified():
    """symmetric=True (bc reuses E as its own transpose) is CHECKED at
    load: a directed COO must not silently serve wrong BC scores."""
    rows = np.array([0, 1, 2], np.int64)  # 0->1->2->3 chain, one-way
    cols = np.array([1, 2, 3], np.int64)
    with pytest.raises(ValueError, match="not structurally symmetric"):
        GraphEngine.from_coo(Grid.make(1, 1), cols, rows, 4)
    # symmetric=False builds the real transpose instead
    eng = GraphEngine.from_coo(
        Grid.make(1, 1), cols, rows, 4, symmetric=False,
    )
    assert eng.ET is not eng.E


@pytest.fixture(scope="module")
def live_roots(graph):
    rows, _ = graph
    deg = np.bincount(rows, minlength=N)
    return np.flatnonzero(deg > 0).astype(np.int32)


# --- batcher ----------------------------------------------------------------


def test_bucket_width_rounds_to_power_of_two():
    """Property: any arrival count lands on the smallest configured
    bucket that fits it (and clamps to the widest past the end)."""
    widths = (1, 2, 4, 8, 16)
    for count in range(1, 40):
        w = bucket_width(count, widths)
        if count <= 16:
            assert w >= count, (count, w)
            assert w in widths
            # minimality: no smaller configured width fits
            smaller = [x for x in widths if x < w]
            assert all(x < count for x in smaller), (count, w)
            assert w == 1 << (count - 1).bit_length()
        else:
            assert w == 16
    with pytest.raises(ValueError):
        bucket_width(0, widths)


def test_assemble_pads_with_sentinel():
    from combblas_tpu.serve.batcher import Request
    from concurrent.futures import Future

    reqs = [
        Request(rid=i, kind="bfs", root=10 + i, future=Future(),
                submitted_at=0.0)
        for i in range(5)
    ]
    src = assemble(reqs, (1, 2, 4, 8))
    assert src.shape == (8,)
    np.testing.assert_array_equal(src[:5], [10, 11, 12, 13, 14])
    assert (src[5:] == PAD_ROOT).all()


def test_pad_root_exported_and_inert(engine, live_roots):
    """models.PAD_ROOT is the public lane-padding sentinel; a PAD_ROOT
    lane discovers nothing / carries no mass in every batch kernel."""
    assert PAD_ROOT == -1
    srcs = np.array([live_roots[0], PAD_ROOT, live_roots[1]], np.int32)
    r = engine.execute("bfs", srcs)
    assert (r["parents"][:, 1] == -1).all()
    assert (r["levels"][:, 1] == -1).all()
    r = engine.execute("pagerank", srcs)
    assert r["ranks"][:, 1].sum() == 0.0
    np.testing.assert_allclose(r["ranks"][:, 0].sum(), 1.0, rtol=1e-4)
    r = engine.execute("sssp", srcs)
    assert np.isinf(r["dist"][:, 1]).all()
    r = engine.execute("bc", srcs)
    assert (r["scores"][:, 1] == 0).all()


# --- engine correctness -----------------------------------------------------


def test_served_results_match_direct_kernels(engine, graph, live_roots):
    """Each serve kind's lanes equal the direct kernel's answer."""
    from combblas_tpu.models.bc import bc_batch_dense
    from combblas_tpu.models.bfs import bfs
    from combblas_tpu.models.pagerank import pagerank_batch
    from combblas_tpu.models.sssp import sssp

    srcs = live_roots[[0, 3, 11]]
    r = engine.execute("bfs", srcs)
    for k, s in enumerate(srcs):
        _, l1, _ = bfs(engine.E, int(s))
        np.testing.assert_array_equal(r["levels"][:, k], l1.to_global())

    r = engine.execute("sssp", srcs)
    d1, _ = sssp(engine.E_weighted, int(srcs[1]))
    np.testing.assert_allclose(r["dist"][:, 1], d1.to_global(), rtol=1e-5)

    r = engine.execute("pagerank", srcs)
    pr_direct, _ = pagerank_batch(
        engine.P_ell, jnp.asarray(srcs), engine.dangling
    )
    np.testing.assert_allclose(
        r["ranks"], pr_direct.to_global(), rtol=1e-5
    )

    # bc: lanes match the public per-lane wrapper, and their sum
    # reproduces the batch total exactly
    from combblas_tpu.models.bc import bc_batch_dense_lanes

    r = engine.execute("bc", srcs)
    lanes = bc_batch_dense_lanes(engine.E, engine.ET, jnp.asarray(srcs))
    np.testing.assert_allclose(
        r["scores"], lanes.to_global(), rtol=1e-5, atol=1e-6
    )
    total = bc_batch_dense(engine.E, engine.ET, jnp.asarray(srcs))
    np.testing.assert_allclose(
        r["scores"].sum(axis=1), total.to_global(), rtol=1e-4, atol=1e-4
    )


def test_warm_plans_never_retrace(engine, live_roots):
    """The zero-retrace contract: after warmup() over the lane buckets,
    serving any mix inside (kinds x widths) performs no traces — the
    obs ``trace.serve`` counter and the engine's host counter agree."""
    obs.enable(install_hooks=False)
    engine.warmup(kinds=("bfs", "pagerank"), widths=(1, 4))
    mark = engine.trace_mark()
    t0 = obs.registry.get_counter("trace.serve", kind="bfs", width=4)
    for batch in (live_roots[:4], live_roots[4:8], live_roots[2:6]):
        engine.execute("bfs", batch[:4])
        engine.execute("pagerank", batch[:4])
        engine.execute("bfs", np.asarray([batch[0]], np.int32))
    assert engine.retraces_since(mark) == 0
    assert (
        obs.registry.get_counter("trace.serve", kind="bfs", width=4) == t0
    )


def test_plan_cache_hit_miss_counters(graph):
    rows, cols = graph
    obs.enable(install_hooks=False)
    eng = GraphEngine.from_coo(
        Grid.make(1, 1), rows, cols, N, kinds=("bfs",)
    )
    eng.execute("bfs", np.asarray([1], np.int32))  # miss (build)
    eng.execute("bfs", np.asarray([1], np.int32))  # hit
    assert obs.registry.get_counter(
        "serve.plan_cache.misses", kind="bfs", width=1
    ) == 1
    assert obs.registry.get_counter(
        "serve.plan_cache.hits", kind="bfs", width=1
    ) == 1
    assert eng.stats()["plans"]["bfs/1"]["executions"] == 2
    # an engine only serves the kinds it was BUILT with: bc's transpose
    # (etc.) may not exist, so the kind is rejected, never approximated
    assert eng.kinds() == ("bfs",)
    with pytest.raises(ValueError, match="not built for kind"):
        eng.execute("bc", np.asarray([1], np.int32))
    with pytest.raises(ValueError, match="unknown query kind"):
        eng.serve().submit("sssp", 1)


def test_close_drains_without_started_worker(engine, live_roots):
    """close(drain=True) on a server whose worker never started must
    still execute the queue — futures may not hang forever."""
    srv = engine.serve(ServeConfig(lane_widths=(4,), max_wait_s=60.0))
    f = srv.submit("bfs", int(live_roots[0]))
    srv.close()  # no start(): the caller's thread drains
    assert f.result(timeout=0)["levels"][int(live_roots[0])] == 0


def test_submit_many_generator_keeps_future_per_root(engine, live_roots):
    """submit_many over a GENERATOR returns exactly one future per
    yielded root, in order, even when backpressure cuts it short."""
    srv = engine.serve(ServeConfig(
        lane_widths=(16,), max_queue=2, max_wait_s=60.0,
    ))  # worker never started: nothing drains
    roots = [int(r) for r in live_roots[:5]]
    futs = srv.submit_many("bfs", (r for r in roots))
    assert len(futs) == len(roots)
    # first 2 admitted (still pending: no worker), rest rejected
    assert [f.done() for f in futs] == [False, False, True, True, True]
    assert all(
        isinstance(f.exception(timeout=0), BackpressureError)
        for f in futs[2:]
    )
    srv.scheduler.fail_pending(RuntimeError("test teardown"))


def test_csc_companion_opt_in_and_released(graph):
    """CSC tiers build lazily from the retained COO (opt-in), which is
    released after the build; without keep_coo the hook raises."""
    rows, cols = graph
    eng = GraphEngine.from_coo(
        Grid.make(1, 1), rows, cols, N, kinds=("bfs",), keep_coo=True
    )
    csc = eng.csc_companion()
    assert len(csc) == 2 and eng._host_coo is None  # edge list dropped
    assert eng.csc_companion() is csc  # cached
    eng2 = GraphEngine.from_coo(
        Grid.make(1, 1), rows, cols, N, kinds=("bfs",)
    )
    with pytest.raises(ValueError, match="keep_coo"):
        eng2.csc_companion()


def test_scatter_returns_lane_copies(engine, live_roots):
    """Per-request results are COPIES, not views pinning the [n, W]
    batch buffer."""
    srv = engine.serve(ServeConfig(lane_widths=(4,), max_wait_s=0.01))
    f = srv.submit("bfs", int(live_roots[0]))
    srv.pump(force=True)
    res = f.result(timeout=0)
    assert res["levels"].base is None


# --- server: batching, mapping, isolation, backpressure ---------------------


def test_results_map_to_request_ids(engine, live_roots):
    """5 requests flush as one width-8 batch: every future gets ITS
    root's answer (ground truth per root), pad lanes reach nobody."""
    from combblas_tpu.models.bfs import bfs

    srv = engine.serve(ServeConfig(lane_widths=(8,), max_wait_s=0.01))
    srv.warmup(kinds=("bfs",), widths=(8,))
    roots = [int(r) for r in live_roots[[9, 1, 5, 13, 2]]]
    futs = {r: srv.submit("bfs", r) for r in roots}
    # worker not started: drive deterministically
    assert srv.pump(force=True) == 1  # ONE coalesced batch
    for r, f in futs.items():
        res = f.result(timeout=0)
        _, l1, _ = bfs(engine.E, r)
        np.testing.assert_array_equal(res["levels"], l1.to_global())
        assert res["levels"][r] == 0  # its own root, not a neighbor's
    assert srv.stats()["mean_occupancy"] == pytest.approx(5 / 8)


def test_concurrent_submit_maps_results(engine, live_roots):
    """Property: under concurrent submit() from many threads, every
    future still maps to its own request (levels[root] == 0 uniquely
    identifies the lane)."""
    engine.warmup(kinds=("bfs",), widths=(1, 2, 4, 8))
    srv = engine.serve(ServeConfig(
        lane_widths=(1, 2, 4, 8), max_wait_s=0.002,
    )).start()
    try:
        roots = [int(r) for r in live_roots[:24]]
        results: dict[int, object] = {}
        errs: list = []

        def worker(rs):
            try:
                for r in rs:
                    results[r] = srv.submit("bfs", r).result(timeout=60)
            except Exception as e:  # pragma: no cover - fail loudly
                errs.append(e)

        threads = [
            threading.Thread(target=worker, args=(roots[i::4],))
            for i in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(120)
        assert not errs
        assert len(results) == len(roots)
        for r, res in results.items():
            assert res["levels"][r] == 0, r
            assert (res["parents"] != PAD_ROOT).any()
    finally:
        srv.close()


def test_backpressure_rejects_when_full(engine, live_roots):
    """A full queue must REJECT with a retry-after hint, not block."""
    srv = engine.serve(ServeConfig(
        lane_widths=(16,), max_queue=3, max_wait_s=7.5,
    ))  # worker never started: nothing drains
    for r in live_roots[:3]:
        srv.submit("bfs", int(r))
    with pytest.raises(BackpressureError) as ei:
        srv.submit("bfs", int(live_roots[3]))
    assert ei.value.retry_after_s == pytest.approx(7.5)
    assert srv.stats()["rejected"] == 1
    # submit_many: admitted prefix + failed remainder, nothing lost
    futs = srv.submit_many("bfs", [int(r) for r in live_roots[4:7]])
    assert len(futs) == 3
    assert all(
        isinstance(f.exception(timeout=0), BackpressureError)
        for f in futs
    )
    srv.scheduler.fail_pending(RuntimeError("test teardown"))


def test_malformed_root_fails_request_not_batch(engine, live_roots):
    """Error isolation: a bad root's future carries the ValueError; its
    batch-mates complete normally."""
    srv = engine.serve(ServeConfig(lane_widths=(4,), max_wait_s=0.01))
    good = [int(r) for r in live_roots[:3]]
    f_good = [srv.submit("bfs", r) for r in good]
    f_bad = srv.submit("bfs", N + 5)  # out of range
    f_bad2 = srv.submit("bfs", "not-a-root")  # wrong type entirely
    assert isinstance(f_bad.exception(timeout=0), ValueError)
    assert isinstance(f_bad2.exception(timeout=0), ValueError)
    srv.pump(force=True)
    for r, f in zip(good, f_good):
        assert f.result(timeout=0)["levels"][r] == 0
    # unknown KIND is a caller bug -> raises at the call site
    with pytest.raises(ValueError):
        srv.submit("nope", good[0])


def test_request_timeout_expires_in_queue(engine, live_roots):
    srv = engine.serve(ServeConfig(lane_widths=(4,), max_wait_s=60.0))
    f = srv.submit("bfs", int(live_roots[0]), timeout_s=0.001)
    time.sleep(0.01)
    srv.pump()  # deadline sweep happens before batching
    assert isinstance(f.exception(timeout=0), TimeoutError)


def test_timeout_callback_may_resubmit(engine, live_roots):
    """Futures settle OUTSIDE the scheduler lock: a done-callback that
    re-enters submit() (the retry pattern retry_after_s invites) must
    not deadlock the sweep."""
    srv = engine.serve(ServeConfig(lane_widths=(4,), max_wait_s=60.0))
    f = srv.submit("bfs", int(live_roots[0]), timeout_s=0.001)
    retried = []
    f.add_done_callback(
        lambda _f: retried.append(srv.submit("bfs", int(live_roots[0])))
    )
    time.sleep(0.01)
    done = threading.Event()

    def sweep():
        srv.scheduler.pop_ready()
        done.set()

    t = threading.Thread(target=sweep, daemon=True)
    t.start()
    assert done.wait(10), "pop_ready deadlocked on re-entrant submit"
    assert isinstance(f.exception(timeout=0), TimeoutError)
    assert len(retried) == 1  # the retry was admitted
    srv.scheduler.fail_pending(RuntimeError("test teardown"))


def test_short_timeout_tightens_flush_deadline(engine, live_roots):
    """A timeout shorter than the kind's max-wait must pull the flush
    forward (dispatch at half the timeout budget) — not sleep until
    max_wait and expire the request in queue."""
    srv = engine.serve(ServeConfig(lane_widths=(4,), max_wait_s=60.0))
    t0 = time.monotonic()
    f = srv.submit("bfs", int(live_roots[0]), timeout_s=1.0)
    nd = srv.scheduler.next_deadline()
    assert nd is not None and nd - t0 < 1.0  # NOT the 60 s flush wait
    assert nd - t0 == pytest.approx(0.5, abs=0.1)  # half the budget
    # at the dispatch-by time the batch flushes (deterministic clock)
    ready = srv.scheduler.pop_ready(now=t0 + 0.6)
    assert ready
    srv._execute_batches(ready)
    assert f.done() and f.exception(timeout=0) is None


def test_closed_server_rejects_submit(engine, live_roots):
    """submit()/start() after close() must raise, never strand a
    future or spawn a worker that can never receive work."""
    srv = engine.serve(ServeConfig(lane_widths=(4,), max_wait_s=0.01))
    srv.close()
    with pytest.raises(RuntimeError, match="closed"):
        srv.submit("bfs", int(live_roots[0]))
    with pytest.raises(RuntimeError, match="closed"):
        srv.submit("bfs", N + 5)  # malformed root: same close semantics
    with pytest.raises(RuntimeError, match="closed"):
        srv.start()


@pytest.mark.slow
def test_serve_stress_throughput(engine, live_roots):
    """Stress/latency: 200 mixed queries through the threaded worker;
    everything completes, batches coalesce (occupancy > half), and the
    warm plans never retrace. Marked slow: tier-1 budget holds."""
    engine.warmup(kinds=("bfs", "pagerank"), widths=(1, 2, 4, 8, 16))
    mark = engine.trace_mark()
    srv = engine.serve(ServeConfig(
        lane_widths=(1, 2, 4, 8, 16), max_wait_s=0.005, max_queue=512,
    )).start()
    try:
        kinds = ["bfs", "pagerank"]
        futs = [
            srv.submit(kinds[i % 2], int(live_roots[i % len(live_roots)]))
            for i in range(200)
        ]
        done = [f.result(timeout=300) for f in futs]
        assert len(done) == 200
        st = srv.stats()
        assert st["completed"] == 200
        assert st["batches"] < 200  # batching actually happened
        assert engine.retraces_since(mark) == 0
    finally:
        srv.close()


# --- satellites -------------------------------------------------------------


def test_compile_cache_idempotent(tmp_path):
    """Second enable with the same dir is a no-op; a different dir
    raises cleanly (process-global jax config must not silently move)."""
    from combblas_tpu.utils import compile_cache as cc

    prior = cc._configured_dir
    cc._reset_for_tests()
    try:
        cc.enable_compile_cache(str(tmp_path / "a"))
        cc.enable_compile_cache(str(tmp_path / "a"))  # idempotent
        cc.enable_compile_cache()  # "ensure enabled": no-op, no raise
        assert cc._configured_dir == str(tmp_path / "a")
        with pytest.raises(ValueError, match="already enabled"):
            cc.enable_compile_cache(str(tmp_path / "b"))
        # entry-count gauge is published through the obs provider path
        obs.enable(install_hooks=False)
        probe = jax.jit(lambda v: v + 1)
        probe(jnp.arange(4)).block_until_ready()
        obs.metrics_snapshot()  # polls providers
        g = obs.registry.get_gauge(
            "compile_cache.entries", dir=str(tmp_path / "a")
        )
        assert g is not None and g >= 0
    finally:
        cc._reset_for_tests()
        import jax as _jax

        if prior is not None:
            # restore the process's committed dir for later tests
            _jax.config.update("jax_compilation_cache_dir", prior)
            cc._configured_dir = prior
        else:
            # fully de-configure: leaving the persistent cache pointed
            # at the (deleted) tmp dir would leak cache writes into it
            # for the rest of the session
            _jax.config.update("jax_compilation_cache_dir", None)
            _jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 1.0
            )
            _jax.config.update(
                "jax_persistent_cache_min_entry_size_bytes", 0
            )
            cc._configured_dir = None
