"""Worker for the 2-process multi-host test (spawned by
test_multihost.py). Each process owns 4 virtual CPU devices; the global
mesh spans 8 devices across both processes — the CPU stand-in for the
reference's `mpirun -np 2` pattern (ReleaseTests/CMakeLists.txt:41+).

Checks replicate-readable results only (a fully-replicated output is
addressable on every process): SpMV row sums and SpGEMM nnz vs host
references computed from the same COO.
"""

import os
import sys

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=4"
)

import jax

jax.config.update("jax_platforms", "cpu")


def main():
    coord, pid = sys.argv[1], int(sys.argv[2])
    from combblas_tpu.parallel.multihost import (
        init_distributed,
        make_global_grid,
    )

    nd = init_distributed(
        coordinator_address=coord, num_processes=2, process_id=pid
    )
    assert nd == 8, f"expected 8 global devices, got {nd}"
    assert jax.process_count() == 2

    import numpy as np

    from combblas_tpu import PLUS_TIMES
    from combblas_tpu.parallel.spgemm import spgemm
    from combblas_tpu.parallel.spmat import SpParMat
    from combblas_tpu.parallel.spmv import dist_spmv
    from combblas_tpu.parallel.vec import DistVec

    # full grid (2x4) for SpMV
    grid = make_global_grid(2, 4)
    assert grid.size == 8

    rng = np.random.default_rng(0)
    n = 48
    d = (rng.random((n, n)) < 0.15).astype(np.float32) * (
        1 + rng.random((n, n)).astype(np.float32)
    )
    r, c = np.nonzero(d)
    A = SpParMat.from_global_coo(grid, r, c, d[r, c], n, n)
    x = DistVec.from_global(grid, np.arange(n, dtype=np.float32), align="col")
    y = dist_spmv(PLUS_TIMES, A, x)
    got = float(jax.device_get(jax.numpy.sum(y.blocks)))
    expect = float((d @ np.arange(n, dtype=np.float32)).sum())
    assert abs(got - expect) < 1e-2 * max(abs(expect), 1), (got, expect)

    # square subgrid (2x2) for SUMMA SpGEMM
    sq = make_global_grid(2, 2)
    B = SpParMat.from_global_coo(sq, r, c, d[r, c], n, n)
    C = spgemm(PLUS_TIMES, B, B)
    got_nnz = int(jax.device_get(C.getnnz()))
    expect_nnz = int(((d @ d) != 0).sum())
    assert got_nnz == expect_nnz, (got_nnz, expect_nnz)

    # distributed byte-range Matrix Market read (ParallelReadMM analog):
    # both processes parse disjoint ranges of the same file
    import tempfile

    from combblas_tpu.io.mm import read_mm_distributed

    path = os.path.join(tempfile.gettempdir(), "mh_worker_graph.mtx")
    if pid == 0:
        lines = [f"%%MatrixMarket matrix coordinate real general\n{n} {n} {len(r)}"]
        lines += [
            f"{i + 1} {j + 1} {d[i, j]:.6f}" for i, j in zip(r, c)
        ]
        with open(path, "w") as f:
            f.write("\n".join(lines) + "\n")
    # both processes reach here only after initialize(); sync via a cheap
    # collective before reading the file process 0 just wrote
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices("mm_file_written")
    M = read_mm_distributed(grid, path)
    got_sum = float(jax.device_get(jax.numpy.sum(M.vals)))
    expect_sum = float(np.round(d[r, c], 6).sum())
    assert abs(got_sum - expect_sum) < 1e-2 * max(abs(expect_sum), 1), (
        got_sum, expect_sum,
    )
    got_mm_nnz = int(jax.device_get(M.getnnz()))
    assert got_mm_nnz == len(r), (got_mm_nnz, len(r))

    print(
        f"proc {pid} OK: devices={nd} spmv_sum={got:.1f} nnz={got_nnz} "
        f"mm_nnz={got_mm_nnz}"
    )


if __name__ == "__main__":
    main()
