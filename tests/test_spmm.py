"""Round-12 batched SpMM lane: kernel golden agreement across
semirings / grids / backends with duplicate-entry COO, the SUMMA
carousel schedules, fused k-hop propagation, the serve ``"propagate"``
kind (pad-lane leak + zero-retrace), tuner op="spmm" store round-trip,
and the round-12 obs series gate.  docs/spmm.md."""

import numpy as np
import pytest

import jax

from combblas_tpu import obs
from combblas_tpu.parallel.dense import DenseParMat
from combblas_tpu.parallel.ellmat import EllParMat
from combblas_tpu.parallel.grid import Grid
from combblas_tpu.parallel.spmat import SpParMat
from combblas_tpu.parallel.spmm import (
    SPMM_BACKENDS,
    admissible_spmm_backends,
    dist_spmm,
    dist_spmm_ell,
    pad_feature_width,
    pad_features,
    resolve_spmm_backend,
    spmm_backend_heuristic,
    spmm_khop,
    summa_spmm,
)
from combblas_tpu.parallel.vec import DistMultiVec
from combblas_tpu.semiring import MAX_MIN, MIN_PLUS, PLUS_TIMES

SRS = {"plus_times": PLUS_TIMES, "min_plus": MIN_PLUS,
       "max_min": MAX_MIN}


@pytest.fixture
def rng():
    return np.random.default_rng(12)


def _coo(rng, n, m, dup=30):
    r = rng.integers(0, n, m)
    c = rng.integers(0, n, m)
    # duplicate entries on purpose: every backend must combine them
    # exactly (the mxu densify uses the combining scatter)
    r = np.concatenate([r, r[:dup]])
    c = np.concatenate([c, c[:dup]])
    v = rng.integers(1, 5, len(r)).astype(np.float32)
    return r, c, v


def _golden(name, r, c, v, X, n):
    F = X.shape[1]
    if name == "plus_times":
        A = np.zeros((n, n), np.float32)
        np.add.at(A, (r, c), v)
        return A @ X
    big = np.full(
        (n, F), np.inf if name == "min_plus" else -np.inf, np.float32
    )
    for rr, cc, vv in zip(r, c, v):
        if name == "min_plus":
            big[rr] = np.minimum(big[rr], vv + X[cc])
        else:
            big[rr] = np.maximum(big[rr], np.minimum(vv, X[cc]))
    return big


# -- kernel golden agreement -------------------------------------------------


@pytest.mark.parametrize("grid_shape,sr_name", [
    ((1, 1), "plus_times"), ((1, 1), "min_plus"), ((1, 1), "max_min"),
    ((2, 2), "plus_times"), ((2, 2), "min_plus"),
    # max_min on 2x2 rides the slow lane: the fold path is the same
    # scatter kernel min_plus already exercises distributed, and the
    # 1x1 case plus the bench golden keep the semiring covered
    pytest.param((2, 2), "max_min", marks=pytest.mark.slow),
])
def test_ell_spmm_golden(rng, grid_shape, sr_name):
    """dist_spmm_ell == dense semiring golden, dup-entry COO, every
    admissible backend, 1x1 and 2x2 grids (integer-valued f32 keeps
    plus_times f32 accumulation exact across fold orders)."""
    n, F = 72, 8
    r, c, v = _coo(rng, n, 420)
    X = rng.integers(0, 4, (n, F)).astype(np.float32)
    grid = Grid.make(*grid_shape)
    E = EllParMat.from_host_coo(grid, r, c, v, n, n)
    Xd = DistMultiVec.from_global(grid, X, align="col")
    g = _golden(sr_name, r, c, v, X, n)
    sr = SRS[sr_name]
    for backend in admissible_spmm_backends(sr):
        got = dist_spmm_ell(sr, E, Xd, backend=backend).to_global()
        np.testing.assert_array_equal(got, g, err_msg=backend)


@pytest.mark.parametrize("ring,pipeline", [
    (False, True), (True, True),
    # the unpipelined carousel is the measurement CONTROL; its golden
    # agreement is tier-1-redundant with the pipelined ring (same
    # contract path, extra compile) — slow lane
    pytest.param(True, False, marks=pytest.mark.slow),
])
def test_summa_spmm_schedules(rng, ring, pipeline):
    """SUMMA SpMM over a DenseParMat panel: gathered vs carousel vs
    unpipelined-carousel schedules all agree with the golden on the
    2x2 mesh, both backends."""
    n, F = 64, 8
    r, c, v = _coo(rng, n, 380)
    X = rng.integers(0, 3, (n, F)).astype(np.float32)
    grid = Grid.make(2, 2)
    A = SpParMat.from_global_coo(grid, r, c, v, n, n)
    Xp = DenseParMat.from_global(grid, X)
    for sr_name, backend in (
        ("plus_times", "mxu_gather"), ("min_plus", "scatter"),
    ):
        got = summa_spmm(
            SRS[sr_name], A, Xp, backend=backend, ring=ring,
            pipeline=pipeline,
        ).to_global()
        np.testing.assert_array_equal(
            got, _golden(sr_name, r, c, v, X, n),
            err_msg=f"{sr_name}/{backend}/ring={ring}",
        )


def test_summa_spmm_mxu_rejects_non_plus_times(rng):
    grid = Grid.make(2, 2)
    n = 16
    r, c, v = _coo(rng, n, 40, dup=0)
    A = SpParMat.from_global_coo(grid, r, c, v, n, n)
    Xp = DenseParMat.from_global(grid, np.ones((n, 4), np.float32))
    with pytest.raises(ValueError, match="plus_times"):
        summa_spmm(MIN_PLUS, A, Xp, backend="mxu_gather")


def test_spmm_khop_fused_and_normalized(rng):
    """spmm_khop chains hops device-resident; normalize=True equals
    the dense (D^-1 A)^k X; host features pad to pow2 lanes that stay
    zero."""
    n, F, k = 60, 6, 3
    r, c, v = _coo(rng, n, 300, dup=0)
    grid = Grid.make(2, 2)
    E = EllParMat.from_host_coo(grid, r, c, v, n, n)
    X = rng.integers(0, 3, (n, F)).astype(np.float32)
    A = np.zeros((n, n), np.float32)
    np.add.at(A, (r, c), v)

    Y = spmm_khop(PLUS_TIMES, E, X, k).to_global()
    G = X
    for _ in range(k):
        G = A @ G
    np.testing.assert_array_equal(Y[:, :F], G)
    assert Y.shape[1] == pad_feature_width(F)
    assert np.all(Y[:, F:] == 0), "pad feature lanes leaked"

    Yn = spmm_khop(PLUS_TIMES, E, X, k, normalize=True).to_global()
    # normalization is by STRUCTURAL row degree (entry count — the
    # P_ell convention), not the value-weighted row sum
    deg = np.bincount(r, minlength=n).astype(np.float32)
    M = A / np.maximum(deg, 1)[:, None]
    Gn = X
    for _ in range(k):
        Gn = M @ Gn
    np.testing.assert_allclose(Yn[:, :F], Gn, atol=1e-5)

    with pytest.raises(ValueError, match="plus_times"):
        spmm_khop(MIN_PLUS, E, X, 2, normalize=True)


def test_pad_feature_width():
    assert [pad_feature_width(f) for f in (1, 2, 3, 64, 65)] == \
        [1, 2, 4, 64, 128]
    out = pad_features(np.ones((3, 5), np.float32))
    assert out.shape == (3, 8) and np.all(out[:, 5:] == 0)


# -- tuner routing (op="spmm") -----------------------------------------------


def test_spmm_backend_resolution_chain(rng, tmp_path, monkeypatch):
    """arg > store > env > heuristic for the SpMM backend; a store
    record with a tier outside the SpMM set is rejected down the
    chain; non-plus_times semirings short-circuit to scatter."""
    from combblas_tpu.tuner import (
        PlanRecord, spmm_plan_key,
    )
    from combblas_tpu.tuner import store as tstore

    monkeypatch.setenv("COMBBLAS_PLAN_STORE", str(tmp_path))
    tstore._reset_for_tests()
    n, F = 48, 8
    r, c, v = _coo(rng, n, 200, dup=0)
    grid = Grid.make(1, 1)
    E = EllParMat.from_host_coo(grid, r, c, v, n, n)

    # heuristic rung (empty store, no env)
    assert resolve_spmm_backend(PLUS_TIMES, E, F) == "mxu_gather"
    assert resolve_spmm_backend(MIN_PLUS, E, F) == "scatter"
    assert spmm_backend_heuristic(MAX_MIN) == "scatter"

    # store rung: a remembered scatter plan beats the heuristic
    store = tstore.get_store()
    key = spmm_plan_key(PLUS_TIMES, E, F)
    store.put(key, PlanRecord(tier="scatter", cost_s=0.01))
    assert resolve_spmm_backend(PLUS_TIMES, E, F) == "scatter"
    # the record round-trips the JSONL (fresh load, same resolution)
    tstore._reset_for_tests()
    st2 = tstore.get_store()
    rec = st2.peek(key)
    assert rec is not None and rec.tier == "scatter"
    assert resolve_spmm_backend(PLUS_TIMES, E, F) == "scatter"
    # feature-width bucket is part of the key: F=32 misses
    assert spmm_plan_key(PLUS_TIMES, E, 32) != key
    assert resolve_spmm_backend(PLUS_TIMES, E, 32) == "mxu_gather"

    # a vetted-out record (spgemm tier under an spmm key) degrades to
    # the next rung instead of routing
    store2 = tstore.get_store()
    store2.put(key, PlanRecord(tier="windowed"))
    assert resolve_spmm_backend(PLUS_TIMES, E, F) == "mxu_gather"

    # env rung (wins over heuristic when the store was vetted out)
    monkeypatch.setenv("COMBBLAS_SPMM_BACKEND", "scatter")
    assert resolve_spmm_backend(PLUS_TIMES, E, F) == "scatter"
    monkeypatch.delenv("COMBBLAS_SPMM_BACKEND")

    # arg rung beats everything; an inexact arg raises
    assert resolve_spmm_backend(
        PLUS_TIMES, E, F, backend="mxu_gather"
    ) == "mxu_gather"
    with pytest.raises(ValueError, match="not exact"):
        resolve_spmm_backend(MIN_PLUS, E, F, backend="mxu_gather")

    # a bogus env value fails loudly naming the knob, never a bare
    # kernel assert (or a silent fallback under python -O)
    monkeypatch.setenv("COMBBLAS_SPMM_BACKEND", "mxu")
    with pytest.raises(ValueError, match="COMBBLAS_SPMM_BACKEND"):
        resolve_spmm_backend(PLUS_TIMES, E, F)
    monkeypatch.delenv("COMBBLAS_SPMM_BACKEND")


def test_probe_spmm_records_winner(rng, tmp_path, monkeypatch):
    """The SpMM micro-probe measures both backends with an injected
    cost functional and persists the winner under the spmm key; the
    routed entry then serves it from the store."""
    from combblas_tpu.tuner import spmm_plan_key
    from combblas_tpu.tuner import store as tstore
    from combblas_tpu.tuner.probe import probe_spmm

    monkeypatch.setenv("COMBBLAS_PLAN_STORE", str(tmp_path))
    tstore._reset_for_tests()
    n, F = 40, 4
    r, c, v = _coo(rng, n, 150, dup=0)
    grid = Grid.make(1, 1)
    E = EllParMat.from_host_coo(grid, r, c, v, n, n)
    X = DistMultiVec.from_global(
        grid, rng.random((n, F)).astype(np.float32), align="col"
    )
    store = tstore.get_store()
    key = spmm_plan_key(PLUS_TIMES, E, F)
    fake_costs = iter([0.5, 0.1])  # heuristic first -> scatter wins

    rec = probe_spmm(
        PLUS_TIMES, E, X, store=store, key=key,
        measure=lambda fn: next(fake_costs),
    )
    assert rec is not None and rec.tier == "scatter"
    assert store.peek(key).tier == "scatter"
    assert resolve_spmm_backend(PLUS_TIMES, E, F) == "scatter"
    # nothing to probe for a single-backend semiring
    assert probe_spmm(MIN_PLUS, E, X, store=store, key=None) is None
    # the routed wrapper agrees with the forced-backend kernel
    got = dist_spmm(PLUS_TIMES, E, X).to_global()
    want = dist_spmm_ell(PLUS_TIMES, E, X, backend="scatter").to_global()
    np.testing.assert_array_equal(got, want)


# -- serve "propagate" kind --------------------------------------------------


def _sym_graph(rng, n, m):
    r = rng.integers(0, n, m)
    c = rng.integers(0, n, m)
    return np.concatenate([r, c]), np.concatenate([c, r])


def test_serve_propagate_golden_padlanes_zero_retrace(rng):
    """The propagate kind end to end: golden per-root features on the
    2x2 mesh, PAD_ROOT lanes structurally inert (zero features, no
    leak into real lanes), zero retraces after warmup, and a
    same-shape hot-swap keeping the plan cache warm."""
    from combblas_tpu.serve import GraphEngine

    n, F = 96, 10
    rows, cols = _sym_graph(rng, n, 380)
    X = rng.integers(0, 3, (n, F)).astype(np.float32)
    grid = Grid.make(2, 2)
    eng = GraphEngine.from_coo(
        grid, rows, cols, n, features=X,
        propagate_hops=2, propagate_normalize=True,
        kinds=("bfs", "propagate"),
    )
    assert "propagate" in eng.kinds()
    eng.warmup(kinds=("propagate",), widths=(4,))
    mark = eng.trace_mark()
    out = eng.execute(
        "propagate", np.array([3, 9, -1, 57], np.int32)
    )
    feats = out["features"]
    assert feats.shape == (F, 4)  # true F, pad width stripped
    A = np.zeros((n, n), np.float32)
    A[rows, cols] = 1.0  # engine dedups: weight 1 per edge
    M = A / np.maximum(A.sum(axis=1), 1)[:, None]
    G = M @ (M @ X)
    for lane, root in ((0, 3), (1, 9), (3, 57)):
        np.testing.assert_allclose(feats[:, lane], G[root], atol=1e-5)
    assert np.all(feats[:, 2] == 0), "pad lane leaked features"
    assert eng.retraces_since(mark) == 0

    # same-shape hot-swap (features carried): still zero retraces
    v2 = eng.build_version(rows, cols)
    assert v2.X is eng.version.X  # table reused, no re-upload
    eng.swap(v2)
    eng.execute("propagate", np.array([3, 9, -1, 57], np.int32))
    assert eng.retraces_since(mark) == 0


def test_serve_propagate_through_server(rng):
    """submit() -> batcher -> scatter: each request gets ITS lane's
    feature row; an engine without features rejects the kind."""
    from combblas_tpu.serve import GraphEngine
    from combblas_tpu.serve.scheduler import ServeConfig

    n, F = 64, 6
    rows, cols = _sym_graph(rng, n, 260)
    X = rng.integers(0, 3, (n, F)).astype(np.float32)
    grid = Grid.make(2, 2)
    eng = GraphEngine.from_coo(
        grid, rows, cols, n, features=X, propagate_hops=1,
        kinds=("propagate",),
    )
    A = np.zeros((n, n), np.float32)
    A[rows, cols] = 1.0
    G = A @ X
    with eng.serve(ServeConfig(lane_widths=(1, 4),
                               max_wait_s=0.001)) as srv:
        srv.warmup()
        mark = eng.trace_mark()
        roots = [1, 5, 17, 33, 50]
        futs = [srv.submit("propagate", r) for r in roots]
        for root, f in zip(roots, futs):
            feats = f.result(timeout=60)["features"]
            assert feats.shape == (F,)
            np.testing.assert_allclose(feats, G[root], atol=1e-5)
        assert eng.retraces_since(mark) == 0

    eng2 = GraphEngine.from_coo(grid, rows, cols, n)
    assert "propagate" not in eng2.kinds()
    # the front door rejects the kind outright — never a stand-in
    with pytest.raises(ValueError, match="not built for kind"):
        eng2.plan("propagate", 1)


# -- obs round-12 series gate ------------------------------------------------


def test_round12_spmm_counters_gated(rng):
    """trace.spmm_ell / trace.spmm_khop / trace.summa_spmm land under
    obs and cost NOTHING when disabled (the zero-cost gate extended to
    the round-12 series).  Fresh static configs per phase: the trace.*
    convention counts TRACES, so an already-compiled config would
    legitimately count nothing."""
    obs.disable()
    obs.reset()
    n = 40
    r, c, v = _coo(rng, n, 160, dup=0)
    grid = Grid.make(1, 1)
    E = EllParMat.from_host_coo(grid, r, c, v, n, n)

    def panel(f):
        return DistMultiVec.from_global(
            grid, np.ones((n, f), np.float32), align="col"
        )

    assert not obs.ENABLED
    dist_spmm_ell(PLUS_TIMES, E, panel(4), backend="scatter")
    assert obs.registry.empty()  # disabled: zero bookkeeping
    obs.enable(install_hooks=False)
    try:
        dist_spmm_ell(PLUS_TIMES, E, panel(8), backend="scatter")
        assert obs.registry.get_counter(
            "trace.spmm_ell", backend="scatter", sr="plus_times"
        ) >= 1
        spmm_khop(PLUS_TIMES, E, np.ones((n, 2), np.float32), 2,
                  backend="scatter")
        assert obs.registry.get_counter(
            "trace.spmm_khop", hops=2, backend="scatter",
            normalize=False,
        ) >= 1
        A = SpParMat.from_global_coo(grid, r, c, v, n, n)
        Xp = DenseParMat.from_global(grid, np.ones((n, 4), np.float32))
        summa_spmm(PLUS_TIMES, A, Xp, backend="mxu_gather")
        assert obs.registry.get_counter(
            "trace.summa_spmm", ring=False, backend="mxu_gather"
        ) >= 1
    finally:
        obs.disable()
        obs.reset()


def test_propagate_rejects_rectangular(rng):
    """k-hop propagation needs a square operator: default kinds skip
    'propagate' on a rectangular graph; asking for it explicitly
    raises at build instead of dying mid-trace at the second hop."""
    from combblas_tpu.serve import GraphEngine

    n, m = 32, 48
    rows = rng.integers(0, n, 120)
    cols = rng.integers(0, m, 120)
    X = rng.random((m, 4)).astype(np.float32)
    eng = GraphEngine.from_coo(
        Grid.make(1, 1), rows, cols, n, ncols=m, features=X,
        symmetric=False,
    )
    assert "propagate" not in eng.kinds()
    # the unused feature table was neither validated nor uploaded
    assert eng.version.X is None
    with pytest.raises(ValueError, match="square"):
        GraphEngine.from_coo(
            Grid.make(1, 1), rows, cols, n, ncols=m, features=X,
            symmetric=False, kinds=("propagate",),
        )
