"""Network front door (round 19): protocol status taxonomy, the shared
frame codec, single-connection e2e over a real TCP socket, wire-deadline
-> scheduler-timeout propagation, tenant-header routing into the pool,
torn-frame / abrupt-disconnect hygiene (no stranded futures on either
peer), trace telescoping across the wire, and the slow-gated open-loop
harness gate.

Tier-1 here is one module-scoped worker server plus worker-less
pump-driven servers (no subprocesses, scale-6 graph); the process-fleet
open-loop representatives are ``slow``.
"""

import socket
import struct
import time

import numpy as np
import pytest

from combblas_tpu import obs
from combblas_tpu.parallel.grid import Grid
from combblas_tpu.serve import (
    BackpressureError,
    CircuitBreakerOpen,
    EnginePool,
    GraphEngine,
    IpcTimeoutError,
    NetClient,
    NetFrontend,
    ReplicaDeadError,
    ServeConfig,
)
from combblas_tpu.serve import frame, ipc
from combblas_tpu.serve.net import protocol as P
from combblas_tpu.utils.rmat import rmat_symmetric_coo_host


@pytest.fixture(autouse=True)
def _clean_obs():
    obs.disable()
    obs.reset()
    yield
    obs.disable()
    obs.reset()


SCALE = 6
N = 1 << SCALE


def _wait(cond, timeout=10.0, tick=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(tick)
    return cond()


@pytest.fixture(scope="module")
def graph():
    rows, cols = rmat_symmetric_coo_host(11, SCALE, 4)
    return rows, cols


@pytest.fixture(scope="module")
def engine(graph):
    rows, cols = graph
    return GraphEngine.from_coo(
        Grid.make(1, 1), rows, cols, N, kinds=("bfs",)
    )


@pytest.fixture(scope="module")
def live_roots(graph):
    rows, _ = graph
    deg = np.bincount(rows, minlength=N)
    return np.flatnonzero(deg > 0).astype(np.int32)


@pytest.fixture(scope="module")
def served(engine):
    """One worker server behind one frontend, warm, shared by the fast
    e2e tests (module scope keeps the compile cost paid once)."""
    srv = engine.serve(
        ServeConfig(
            lane_widths=(1, 2), max_wait_s=0.002,
            update_autostart=False,
        )
    )
    srv.start()
    srv.warmup(widths=(1, 2))
    fe = NetFrontend(srv)
    yield srv, fe
    fe.close()
    srv.close()


# --- protocol taxonomy (pure, no sockets) -----------------------------------


def test_wire_status_taxonomy_round_trip():
    """Every taxonomy member maps to its typed status and rebuilds as
    the SAME exception type client-side (the docstring table in
    serve/net/protocol.py, bijectively)."""
    cases = [
        (CircuitBreakerOpen("bfs", 0.5, tenant="web"),
         P.ST_BREAKER_OPEN, CircuitBreakerOpen),
        (BackpressureError(7, 0.01, tenant="web"),
         P.ST_BACKPRESSURE, BackpressureError),
        (ReplicaDeadError("all replicas failed"),
         P.ST_REPLICA_DEAD, ReplicaDeadError),
        (TimeoutError("deadline"), P.ST_TIMEOUT, TimeoutError),
        (IpcTimeoutError("ipc deadline"), P.ST_TIMEOUT, TimeoutError),
        (ValueError("bad root"), P.ST_INVALID, ValueError),
        (KeyError("tenant"), P.ST_INVALID, ValueError),
        (RuntimeError("boom"), P.ST_UNAVAILABLE, RuntimeError),
    ]
    for exc, status, rebuilt_t in cases:
        msg = P.wire_error(exc, mid=3)
        assert msg["status"] == status, exc
        assert msg["id"] == 3
        assert status in P.ERROR_STATUSES
        assert isinstance(P.wire_exception(msg), rebuilt_t), exc
    # breaker_open wins over backpressure (it IS a subclass): the more
    # specific code must be checked first
    assert isinstance(
        CircuitBreakerOpen("bfs", 0.1), BackpressureError
    )
    m = P.wire_error(CircuitBreakerOpen("bfs", 0.25, tenant="t"))
    assert m["status"] == P.ST_BREAKER_OPEN
    back = P.wire_exception(m)
    assert back.kind == "bfs"
    assert back.retry_after_s == 0.25
    assert back.tenant == "t"
    # retry hints survive the wire round trip
    bp = P.wire_exception(P.wire_error(BackpressureError(9, 0.125)))
    assert bp.retry_after_s == 0.125
    # a NEWER server's unknown status degrades, never crashes
    assert isinstance(
        P.wire_exception({"status": "shiny_new", "error": "x"}),
        RuntimeError,
    )


# --- the shared frame codec -------------------------------------------------


def test_ipc_reexports_are_the_frame_codec():
    """One codec, two transports: serve/ipc.py is a pure re-export of
    serve/frame.py — the process fleet and the net front door cannot
    drift apart."""
    assert ipc.Channel is frame.Channel
    assert ipc.ChannelClosed is frame.ChannelClosed
    assert ipc.encode is frame.encode
    assert ipc.decode is frame.decode
    assert ipc.denumpy is frame.denumpy
    assert ipc.MAX_FRAME == frame.MAX_FRAME


def test_channel_ndarray_round_trip_and_byte_accounting():
    """Binary ndarray replies survive a real socket round trip
    bit-exact, and both peers account whole-frame byte totals."""
    a, b = socket.socketpair()
    ca = frame.Channel(a, peer="net")
    cb = frame.Channel(b, peer="netclient")
    try:
        arr = np.arange(8, dtype=np.int32)
        n = ca.send({"status": "ok", "result": {"levels": arr}})
        assert n > 0
        assert ca.bytes_out == n
        got = cb.recv(timeout=5)
        assert cb.bytes_in == n  # advances only on whole frames
        out = got["result"]["levels"]
        assert isinstance(out, np.ndarray)
        assert out.dtype == np.int32
        np.testing.assert_array_equal(out, arr)
    finally:
        ca.close()
        cb.close()


# --- single-connection e2e --------------------------------------------------


def test_single_connection_e2e(served, live_roots):
    """hello -> ping -> submit (binary ndarray reply, bit-exact vs the
    in-process path) -> submit_many with per-root error isolation ->
    stats/health, then a clean unwind."""
    srv, fe = served
    r0, r1 = int(live_roots[0]), int(live_roots[1])
    direct = srv.submit("bfs", r0).result(timeout=60)
    with NetClient("127.0.0.1", fe.port) as c:
        assert c.server_pooled is False
        assert c.ping()["pong"] is True
        out = c.submit("bfs", r0)
        assert isinstance(out["levels"], np.ndarray)
        assert out["levels"].dtype == np.int32
        np.testing.assert_array_equal(out["levels"], direct["levels"])
        np.testing.assert_array_equal(
            out["parents"], direct["parents"]
        )
        # per-root failure isolation survives the wire: the bad root
        # is a typed per-entry status, not a torn batch
        many = c.submit_many("bfs", [r0, N + 99])
        assert many[0]["status"] == P.ST_OK
        np.testing.assert_array_equal(
            many[0]["result"]["levels"], direct["levels"]
        )
        assert many[1]["status"] == P.ST_INVALID
        assert isinstance(
            P.wire_exception(many[1]), ValueError
        )
        st = c.stats()
        assert st["net"]["connections"] == 1
        assert st["net"]["port"] == fe.port
        assert "backend" in st
        h = c.health()
        assert h["status"] == "ok"
        assert h["net"]["closing"] is False
    assert _wait(lambda: fe.stats()["net"]["connections"] == 0)


def test_submit_update_shares_the_protocol(graph):
    """The write lane rides the same connection: an edge insert over
    the wire merges (pump-driven) and subsequent reads see it."""
    rows, cols = graph
    eng = GraphEngine.from_coo(
        Grid.make(1, 1), rows, cols, N, kinds=("bfs",),
        keep_coo=True,  # the mutation lane needs the host edge list
    )
    srv = eng.serve(ServeConfig(
        lane_widths=(1,), update_autostart=False, update_flush=100,
    ))
    v0 = eng.version_id
    fe = NetFrontend(srv)
    try:
        present = set(zip(rows.tolist(), cols.tolist()))
        a, b = next(
            (i, j) for i in range(N) for j in range(N)
            if i != j and (i, j) not in present
        )
        with NetClient("127.0.0.1", fe.port) as c:
            fut = c.submit_update_nowait(
                [("insert", a, b), ("insert", b, a)]
            )
            assert _wait(lambda: srv.stats()["updates"]["pending"] > 0)
            assert srv.pump_updates(force=True) == 2
            res = fut.result(timeout=30)
            assert res["version"] == v0 + 1
    finally:
        fe.close()
        srv.close()


# --- wire deadline -> scheduler timeout -------------------------------------


def test_wire_deadline_becomes_scheduler_timeout(engine, live_roots):
    """``deadline_s`` on the wire is the scheduler's per-request
    timeout: the request expires IN QUEUE (the deadline sweep, not a
    client-side timer) and comes back as a typed ``timeout`` reply."""
    srv = engine.serve(ServeConfig(
        lane_widths=(4,), max_wait_s=60.0, update_autostart=False,
    ))
    fe = NetFrontend(srv)
    try:
        with NetClient("127.0.0.1", fe.port) as c:
            fut = c.submit_nowait(
                "bfs", int(live_roots[0]), deadline_s=0.001
            )
            assert _wait(lambda: srv.scheduler.depth() == 1)
            time.sleep(0.01)
            srv.pump()  # deadline sweep fails the overdue request
            with pytest.raises(TimeoutError):
                fut.result(timeout=10)
            # a non-positive deadline is a typed invalid reply
            bad = c.submit_nowait(
                "bfs", int(live_roots[0]), deadline_s=-1.0
            )
            with pytest.raises(ValueError, match="deadline_s"):
                bad.result(timeout=10)
    finally:
        fe.close()
        srv.close()


def test_slo_deadline_still_caps_wire_deadline(engine, live_roots):
    """A generous wire deadline cannot LOOSEN the server's SLO budget:
    ``slo_deadline_s`` caps the admitted timeout."""
    srv = engine.serve(ServeConfig(
        lane_widths=(4,), max_wait_s=60.0, slo_deadline_s=0.001,
        update_autostart=False,
    ))
    fe = NetFrontend(srv)
    try:
        with NetClient("127.0.0.1", fe.port) as c:
            fut = c.submit_nowait(
                "bfs", int(live_roots[0]), deadline_s=60.0
            )
            assert _wait(lambda: srv.scheduler.depth() == 1)
            time.sleep(0.01)
            srv.pump()
            with pytest.raises(TimeoutError):
                fut.result(timeout=10)
    finally:
        fe.close()
        srv.close()


# --- admission rejections as wire replies -----------------------------------


def test_backpressure_is_a_typed_wire_reply(engine, live_roots):
    """A full queue rejects over the wire with ``backpressure`` + the
    retry hint — same type, same fields as the in-process raise — and
    the connection stays open; parked futures settle when the backend
    fails them (never stranded)."""
    srv = engine.serve(ServeConfig(
        lane_widths=(16,), max_queue=2, max_wait_s=60.0,
        update_autostart=False,
    ))
    fe = NetFrontend(srv)
    try:
        with NetClient("127.0.0.1", fe.port) as c:
            r = int(live_roots[0])
            f1 = c.submit_nowait("bfs", r)
            f2 = c.submit_nowait("bfs", r)
            # same connection => frames dispatch in order: by the time
            # the third is admitted the first two hold the queue
            f3 = c.submit_nowait("bfs", r)
            with pytest.raises(BackpressureError) as ei:
                f3.result(timeout=10)
            assert ei.value.retry_after_s > 0
            # the rejection was a REPLY: the connection still serves
            assert c.ping()["pong"] is True
            assert not f1.done() and not f2.done()
            srv.scheduler.fail_pending(RuntimeError("teardown"))
            assert isinstance(
                f1.exception(timeout=10), RuntimeError
            )
            assert isinstance(
                f2.exception(timeout=10), RuntimeError
            )
    finally:
        fe.close()
        srv.close()


def test_connection_limit_is_a_typed_hello_reject(engine):
    """Past ``max_conns`` the hello itself answers ``backpressure``
    (typed reply, then close) — never a silent drop."""
    srv = engine.serve(ServeConfig(
        lane_widths=(1,), update_autostart=False,
    ))
    fe = NetFrontend(srv, max_conns=1)
    try:
        c1 = NetClient("127.0.0.1", fe.port)
        try:
            with pytest.raises(BackpressureError):
                NetClient("127.0.0.1", fe.port)
            assert fe.rejected_conns == 1
            assert c1.ping()["pong"] is True  # the admitted conn lives
        finally:
            c1.close()
    finally:
        fe.close()
        srv.close()


# --- tenant-header routing --------------------------------------------------


def _tenant_coo(seed, n=N, m=240):
    r = np.random.default_rng(seed)
    rows = r.integers(0, n, m)
    cols = r.integers(0, n, m)
    return (
        np.concatenate([rows, cols]), np.concatenate([cols, rows])
    )


def test_tenant_header_routes_to_the_right_graph():
    """The hello's tenant header routes every request on the
    connection to that PoolServer tenant: two clients, two tenants,
    two DIFFERENT graphs answering the same root."""
    pool = EnginePool(Grid.make(1, 1))
    for i, name in enumerate(("a", "b")):
        rows, cols = _tenant_coo(i)
        pool.add_tenant(
            name, rows, cols, N, kinds=("bfs",),
            config=ServeConfig(
                lane_widths=(1,), update_autostart=False
            ),
        )
    psrv = pool.serve()
    psrv.warmup(widths=(1,))
    fe = NetFrontend(psrv)
    ca = cb = None
    try:
        ca = NetClient("127.0.0.1", fe.port, tenant="a")
        cb = NetClient("127.0.0.1", fe.port, tenant="b")
        assert ca.server_pooled is True
        fa = ca.submit_nowait("bfs", 3)
        fb = cb.submit_nowait("bfs", 3)

        def drain():
            while psrv.pump(force=True):
                pass
            return fa.done() and fb.done()

        assert _wait(drain)
        got = {"a": fa.result(timeout=0), "b": fb.result(timeout=0)}
        for t in ("a", "b"):
            direct = pool.engine(t).execute(
                "bfs", np.asarray([3], np.int32)
            )["levels"][:, 0]
            np.testing.assert_array_equal(got[t]["levels"], direct)
        assert not np.array_equal(
            got["a"]["levels"], got["b"]["levels"]
        )
        # unknown tenant / missing tenant: typed hello rejects
        with pytest.raises(ValueError, match="unknown tenant"):
            NetClient("127.0.0.1", fe.port, tenant="nope")
        with pytest.raises(ValueError, match="tenant header required"):
            NetClient("127.0.0.1", fe.port)
    finally:
        for c in (ca, cb):
            if c is not None:
                c.close()
        fe.close()
        psrv.close()


# --- torn frames / abrupt disconnects ---------------------------------------


def test_torn_frame_tears_down_only_that_connection(engine):
    """A length prefix promising bytes that never arrive (and an
    oversized prefix) unwind THAT connection; the listener keeps
    serving."""
    srv = engine.serve(ServeConfig(
        lane_widths=(1,), update_autostart=False,
    ))
    fe = NetFrontend(srv)
    try:
        def raw_hello():
            raw = socket.create_connection(
                ("127.0.0.1", fe.port), timeout=5
            )
            ch = frame.Channel(raw, peer="netclient")
            ch.send({
                "v": P.PROTOCOL_VERSION, "op": "hello", "id": 0,
                "tenant": None,
            })
            assert ch.recv(timeout=5)["status"] == P.ST_OK
            return raw, ch

        raw, _ch = raw_hello()
        assert _wait(
            lambda: fe.stats()["net"]["connections"] == 1
        )
        raw.sendall(struct.pack(">I", 1000) + b"\x00\x01")  # torn
        raw.close()
        assert _wait(
            lambda: fe.stats()["net"]["connections"] == 0
        )
        raw2, _ch2 = raw_hello()
        raw2.sendall(struct.pack(">I", frame.MAX_FRAME + 1))
        assert _wait(
            lambda: fe.stats()["net"]["connections"] == 0
        )
        raw2.close()
        # the front door survived both: a fresh client still serves
        with NetClient("127.0.0.1", fe.port) as c:
            assert c.ping()["pong"] is True
    finally:
        fe.close()
        srv.close()


def test_abrupt_disconnect_strands_no_futures(engine, live_roots):
    """A client vanishing with requests parked in the queue: its
    client-side futures fail with ConnectionError immediately, the
    backend futures still settle server-side, and their replies are
    counted as drops — nothing hangs, nothing leaks."""
    srv = engine.serve(ServeConfig(
        lane_widths=(16,), max_wait_s=60.0, update_autostart=False,
    ))
    fe = NetFrontend(srv)
    try:
        c = NetClient("127.0.0.1", fe.port)
        f1 = c.submit_nowait("bfs", int(live_roots[0]))
        f2 = c.submit_nowait("bfs", int(live_roots[1]))
        assert _wait(lambda: srv.scheduler.depth() == 2)
        c.close()  # abrupt: requests still queued server-side
        assert isinstance(f1.exception(timeout=10), ConnectionError)
        assert isinstance(f2.exception(timeout=10), ConnectionError)
        assert c.pending == 0  # client map torn down, not stranded
        assert _wait(
            lambda: fe.stats()["net"]["connections"] == 0
        )
        drops0 = fe.reply_drops
        srv.scheduler.fail_pending(RuntimeError("drain"))
        # server-side futures settled; replies hit the closed channel
        # and are accounted as drops (stranded futures: zero)
        assert _wait(lambda: fe.reply_drops == drops0 + 2)
        assert srv.scheduler.depth() == 0
    finally:
        fe.close()
        srv.close()


# --- trace telescoping across the wire --------------------------------------


def test_net_trace_telescopes_to_wall(served, live_roots):
    """One sampled request produces ONE schema-trace record whose
    stages run net_accept -> net_read -> [serve stages] -> net_write
    and sum EXACTLY to the end-to-end wall (the hold/release
    contract)."""
    from combblas_tpu.obs import trace as obs_trace

    srv, fe = served
    obs.enable(install_hooks=False)
    prev = obs_trace.sample_rate()
    obs_trace.set_sample_rate(1.0)
    try:
        with NetClient("127.0.0.1", fe.port) as c:
            c.submit("bfs", int(live_roots[0]))
        recs = [
            r for r in obs_trace.records()
            if r["labels"].get("transport") == "net"
        ]
        assert len(recs) == 1
        rec = recs[0]
        stages = [s["stage"] for s in rec["stages"]]
        assert stages[0] == "net_accept"
        assert stages[1] == "net_read"
        assert stages[-1] == "net_write"
        assert {"queue_wait", "assemble", "execute"} <= set(stages)
        assert rec["labels"]["status"] == "ok"
        assert sum(
            s["s"] for s in rec["stages"]
        ) == pytest.approx(rec["wall_s"], rel=1e-6, abs=1e-9)
    finally:
        obs_trace.set_sample_rate(prev)


# --- open-loop harness (slow: subprocess fleet) -----------------------------


@pytest.mark.slow
def test_open_loop_gate_small_fleet():
    """Representative of the BENCH_SERVE_NET=1 acceptance gate, scaled
    down: seeded Poisson arrivals over concurrent connections against
    a 2-replica process fleet — >=99% availability, zero stranded
    futures, zero post-warmup retraces, every failure typed."""
    from combblas_tpu.serve.net import loadgen

    out = loadgen.run(
        rate=50, conns=8, seconds=2, scale=6, edgefactor=4,
        replicas=2,
    )
    assert out["ok"], out
    assert out["availability"] >= 0.99
    assert out["stranded_futures"] == 0
    assert out["retraces_after_warmup"] == 0
    assert out["untyped_failures"] == 0
    assert out["offered_qps"] > 0 and out["achieved_qps"] > 0
    assert out["decomposition"], out  # stitched net/router/ipc tiers


@pytest.mark.slow
@pytest.mark.chaos
def test_open_loop_under_sigkill_chaos():
    """Open loop with a scripted SIGKILL mid-run: failures stay TYPED
    (wire statuses, never hangs or untyped blowups) and no futures
    strand on either peer while the fleet self-heals."""
    from combblas_tpu.serve.net import loadgen

    out = loadgen.run(
        rate=40, conns=8, seconds=3, scale=6, edgefactor=4,
        replicas=2, chaos=True,
    )
    assert out["chaos"] is True
    assert out["untyped_failures"] == 0, out
    assert out["stranded_futures"] == 0
    assert out["availability"] >= 0.9, out


# --- blocking-client retry policy (round 20) --------------------------------


def test_client_retry_policy_unit():
    """The ``_call_retrying`` contract, driven with stub send
    functions (no sockets): backpressure sleeps the server's hint and
    resends until the budget runs out; a send failure (the request
    never left this process) reconnects and resends EVEN for writes;
    an in-flight death resends reads but surfaces to write callers
    (``retry_inflight=False`` — idempotency is theirs)."""
    from concurrent.futures import Future

    cli = NetClient.__new__(NetClient)
    cli.max_retries = 3
    cli.backoff_s = 0.001
    cli.max_backoff_s = 0.004
    cli._closed = False
    reconnects = []
    cli._ensure_connected = lambda: reconnects.append(1)

    def failing(exc, fails, then=None):
        state = {"n": 0}

        def send():
            state["n"] += 1
            fut = Future()
            if state["n"] <= fails:
                fut.set_exception(exc)
            else:
                fut.set_result(then)
            return fut

        return send

    # backpressure: two rejects, then success — inside the budget
    bp = BackpressureError(7, 0.001)
    assert cli._call_retrying(failing(bp, 2, {"ok": 1}), 5.0) == {
        "ok": 1
    }
    # budget exhaustion surfaces the typed error
    with pytest.raises(BackpressureError):
        cli._call_retrying(failing(bp, 99), 5.0)
    # the breaker subclass rides the same lane (its retry_after_s is
    # the cooldown hint)
    brk = CircuitBreakerOpen("bfs", 0.001)
    assert cli._call_retrying(failing(brk, 1, {"ok": 2}), 5.0) == {
        "ok": 2
    }
    # send failure: never left the process — writes resend too
    state = {"n": 0}

    def send_fail_then_ok():
        state["n"] += 1
        if state["n"] == 1:
            raise ConnectionError("send failed")
        fut = Future()
        fut.set_result({"ok": 3})
        return fut

    assert cli._call_retrying(
        send_fail_then_ok, 5.0, retry_inflight=False
    ) == {"ok": 3}
    assert reconnects  # the drop triggered a reconnect
    # in-flight death: reads resend...
    gone = ConnectionError("server gone")
    assert cli._call_retrying(failing(gone, 1, {"ok": 4}), 5.0) == {
        "ok": 4
    }
    # ...writes do not (may have been applied server-side)
    with pytest.raises(ConnectionError):
        cli._call_retrying(failing(gone, 1, {"ok": 5}), 5.0,
                           retry_inflight=False)
    # a closed client never retries
    cli._closed = True
    with pytest.raises(ConnectionError):
        cli._call_retrying(failing(gone, 1, {"ok": 6}), 5.0)
    # max_retries=0 restores fail-fast
    cli._closed = False
    cli.max_retries = 0
    with pytest.raises(BackpressureError):
        cli._call_retrying(failing(bp, 1, {"ok": 7}), 5.0)


def test_client_reconnects_after_connection_drop(served, live_roots):
    """E2E over a real socket: the connection dies under the client
    (channel torn down mid-session); the next blocking submit
    reconnects — new socket, new hello, new reader generation — and
    answers bit-exactly.  The nowait primitives stay fail-fast."""
    srv, fe = served
    root = int(live_roots[0])
    direct = srv.submit("bfs", root).result(timeout=60)
    cli = NetClient("127.0.0.1", fe.port)
    try:
        np.testing.assert_array_equal(
            cli.submit("bfs", root)["levels"], direct["levels"]
        )
        cli.ch.close()  # the drop: every send on this channel fails
        out = cli.submit("bfs", root)
        np.testing.assert_array_equal(out["levels"], direct["levels"])
        assert cli.reconnects >= 1
        assert cli.pending == 0  # no stranded futures across the drop
        # nowait on a freshly-dropped channel surfaces the error
        cli.ch.close()
        with pytest.raises(ConnectionError):
            cli.submit_nowait("bfs", root)
        cli.submit("bfs", root)  # the blocking lane still self-heals
    finally:
        cli.close()
