"""Round-13 merge tiers: sorted-run union, hash accumulate, 3D
carousel — property tests.

Every merge tier must be BIT-EXACT with the classic concat+sort
combine (values included: duplicate groups fold in identical operand
order for ``runs``; test values are small integers so the hash tier's
unordered float adds are exact too), the hash tier's counted overflow
must fall back to a sorted tier rather than truncate, and the merge
knob must resolve arg > store > env > heuristic.  Heavy grid/semiring
variants ride ``-m slow`` with one fast tier-1 representative each
(the PR 7/10 budget precedent).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from combblas_tpu import MAX_MIN, MIN_PLUS, PLUS_TIMES, obs
from combblas_tpu.ops.spgemm import (
    hash_merge,
    hash_table_capacity,
    merge_sorted_runs,
)
from combblas_tpu.ops.tuples import SpTuples
from combblas_tpu.parallel.grid import Grid
from combblas_tpu.parallel.mesh3d import Grid3D, SpParMat3D, spgemm3d
from combblas_tpu.parallel.spmat import SpParMat
from combblas_tpu.parallel.spgemm import spgemm

SEMIRINGS = {
    "plus_times": PLUS_TIMES,
    "min_plus": MIN_PLUS,
    "max_min": MAX_MIN,
}


@pytest.fixture
def rng():
    return np.random.default_rng(1313)


def _sorted_run(rng, nrows, ncols, n, cap):
    r = rng.integers(0, nrows, n)
    c = rng.integers(0, ncols, n)
    v = rng.integers(1, 5, n).astype(np.float32)
    order = np.lexsort((c, r))
    return SpTuples.from_coo(
        r[order], c[order], v[order], nrows, ncols, capacity=cap
    )


# FIXED run capacity for the unit tests: every (L, semiring) case
# shares compiled kernels (capacities are trace-time statics — random
# ones minted one XLA compile per case and dominated the tier-1 bill)
_UNIT_CAP = 48


def _coo_canon(C):
    gr, gc, gv = C.to_global_coo()
    o = np.lexsort((np.asarray(gc), np.asarray(gr)))
    return (
        np.asarray(gr)[o], np.asarray(gc)[o], np.asarray(gv)[o]
    )


def _assert_same(a, b, ctx=None):
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y, err_msg=str(ctx))


# --- unit: the merge kernels -------------------------------------------------


@pytest.mark.parametrize(
    "srname",
    [
        "plus_times",
        pytest.param("min_plus", marks=pytest.mark.slow),
        pytest.param("max_min", marks=pytest.mark.slow),
    ],
)
def test_merge_sorted_runs_matches_concat_sort(rng, srname):
    """Rank-space union == stable concat+sort: same entry order
    (duplicates adjacent, ties in run order), padding a strict suffix,
    and compact(assume_sorted) agreeing."""
    nrows, ncols = 37, 29
    sr = SEMIRINGS[srname]
    for L in (1, 2, 3, 5):
        runs = [
            _sorted_run(rng, nrows, ncols, int(rng.integers(0, 40)),
                        _UNIT_CAP)
            for _ in range(L)
        ]
        merged = merge_sorted_runs(runs)
        concat = SpTuples.concat(runs).sort_rowmajor()
        assert int(merged.nnz) == int(concat.nnz)
        m = np.asarray(merged.rows) < nrows
        cm = np.asarray(concat.rows) < nrows
        np.testing.assert_array_equal(
            np.asarray(merged.rows)[m], np.asarray(concat.rows)[cm]
        )
        np.testing.assert_array_equal(
            np.asarray(merged.cols)[m], np.asarray(concat.cols)[cm]
        )
        # duplicate groups must fold in IDENTICAL operand order (the
        # bit-exactness contract): compare the uncombined value streams
        np.testing.assert_array_equal(
            np.asarray(merged.vals)[m], np.asarray(concat.vals)[cm]
        )
        # padding is a strict suffix (valid_mask semantics survive)
        if (~m).any():
            assert not m[np.argmax(~m):].any()
        a, da = merged.compact_counted(
            sr, capacity=merged.capacity, assume_sorted=True
        )
        b, db = concat.compact_counted(
            sr, capacity=concat.capacity, assume_sorted=True
        )
        assert int(da) == int(db)
        ka = np.asarray(a.valid_mask())
        kb = np.asarray(b.valid_mask())
        np.testing.assert_array_equal(
            np.asarray(a.rows)[ka], np.asarray(b.rows)[kb]
        )
        np.testing.assert_array_equal(
            np.asarray(a.vals)[ka], np.asarray(b.vals)[kb]
        )


@pytest.mark.parametrize(
    "srname",
    [
        "plus_times",
        pytest.param("min_plus", marks=pytest.mark.slow),
        pytest.param("max_min", marks=pytest.mark.slow),
    ],
)
def test_hash_merge_matches_compact(rng, srname):
    """The bounded open-addressing combine produces exactly compact()'s
    (key, value) set — any order — with zero overflow at the sized
    table, exact distinct count, and a COUNTED (not silent) overflow
    when the table is deliberately too small."""
    nrows, ncols = 41, 23
    sr = SEMIRINGS[srname]
    cap, table = 207, hash_table_capacity(200)
    for n in (0, 1, 17, 200):
        t = _sorted_run(rng, nrows, ncols, n, cap)
        ref = t.compact(sr, capacity=cap)
        out, over, distinct = hash_merge(
            sr, t, out_capacity=cap, table_capacity=table,
        )
        assert int(over) == 0, (srname, n)
        assert int(distinct) == int(ref.nnz)
        kr = np.asarray(ref.valid_mask())
        ko = np.asarray(out.valid_mask())
        ra = np.lexsort(
            (np.asarray(ref.cols)[kr], np.asarray(ref.rows)[kr])
        )
        oa = np.lexsort(
            (np.asarray(out.cols)[ko], np.asarray(out.rows)[ko])
        )
        for refa, outa in (
            (ref.rows, out.rows), (ref.cols, out.cols),
            (ref.vals, out.vals),
        ):
            np.testing.assert_array_equal(
                np.asarray(refa)[kr][ra], np.asarray(outa)[ko][oa],
                err_msg=f"{srname} n={n}",
            )
    # deliberately undersized table: overflow is COUNTED
    t = _sorted_run(rng, nrows, ncols, 200, 210)
    _, over, _ = hash_merge(
        PLUS_TIMES, t, out_capacity=256, table_capacity=16, n_probes=4
    )
    assert int(over) > 0


# --- 2D ESC stage-chunk merge ------------------------------------------------


def _rand_square(rng, grid, n=64, m=500):
    r = rng.integers(0, n, m)
    c = rng.integers(0, n, m)
    v = rng.integers(1, 4, m).astype(np.float32)  # duplicate COO keys
    return SpParMat.from_global_coo(grid, r, c, v, n, n)


@pytest.mark.parametrize(
    "gshape,srname",
    [
        pytest.param((2, 2), "plus_times"),
        pytest.param((2, 2), "min_plus", marks=pytest.mark.slow),
        pytest.param((2, 2), "max_min", marks=pytest.mark.slow),
        pytest.param((1, 1), "plus_times", marks=pytest.mark.slow),
        pytest.param((1, 1), "min_plus", marks=pytest.mark.slow),
        pytest.param((1, 1), "max_min", marks=pytest.mark.slow),
    ],
)
def test_esc2d_merge_runs_bitexact(rng, gshape, srname):
    """summa_spgemm(merge='runs') — per-stage sorts + rank-space union
    — is bit-exact with the classic concat+sort on duplicate COO."""
    grid = Grid.make(*gshape)
    A = _rand_square(rng, grid)
    sr = SEMIRINGS[srname]
    _assert_same(
        _coo_canon(spgemm(sr, A, A, merge="sort")),
        _coo_canon(spgemm(sr, A, A, merge="runs")),
        (gshape, srname),
    )


# --- 3D fiber-reduce merge tiers + carousel ---------------------------------


def _mats3d(rng, n=64, m=500, layers=2):
    g3 = Grid3D.make(layers, 2, 2)
    r = rng.integers(0, n, m)
    c = rng.integers(0, n, m)
    v = rng.integers(1, 4, m).astype(np.float32)
    A3 = SpParMat3D.from_global_coo(g3, r, c, v, n, n, split="col")
    B3 = SpParMat3D.from_global_coo(g3, r, c, v, n, n, split="row")
    return A3, B3


@pytest.mark.parametrize(
    "tier,merge,kw,srname",
    [
        # fast representatives: one per (tier, merge) pair; the
        # SERIAL windowed+runs case joined the slow set in round 17
        # (tier-1 budget) — the ring=True case below keeps the
        # windowed+runs fiber merge bit-exactness in tier-1, and
        # esc+runs covers the serial schedule
        pytest.param("windowed", "runs", {}, "plus_times",
                     marks=pytest.mark.slow),
        pytest.param("windowed", "hash", {}, "plus_times"),
        pytest.param("esc", "runs", {}, "plus_times"),
        pytest.param("esc", "hash", {}, "min_plus",
                     marks=pytest.mark.slow),
        pytest.param("windowed", "runs", {}, "min_plus",
                     marks=pytest.mark.slow),
        pytest.param("windowed", "runs", {}, "max_min",
                     marks=pytest.mark.slow),
        pytest.param("esc", "runs", {}, "max_min",
                     marks=pytest.mark.slow),
        # carousel vs gathered (the round-13 3D ring): fast windowed
        # pipelined representative; serial control + ESC ring slow
        pytest.param("windowed", "runs", {"ring": True}, "plus_times"),
        pytest.param(
            "windowed", "runs", {"ring": True, "pipeline": False},
            "plus_times", marks=pytest.mark.slow,
        ),
        pytest.param("esc", "sort", {"ring": True}, "plus_times",
                     marks=pytest.mark.slow),
    ],
)
def test_spgemm3d_merge_tiers_bitexact(rng, tier, merge, kw, srname):
    """Every merge tier (and the per-layer carousel schedule) agrees
    bit-exactly with the gathered concat+sort path on the L2x2x2 mesh
    with duplicate COO."""
    sr = SEMIRINGS[srname]
    A3, B3 = _mats3d(rng)
    golden = _coo_canon(spgemm3d(sr, A3, B3, tier=tier, merge="sort"))
    got = _coo_canon(spgemm3d(sr, A3, B3, tier=tier, merge=merge, **kw))
    _assert_same(golden, got, (tier, merge, kw, srname))


def test_hash_overflow_falls_back_to_runs(rng, monkeypatch):
    """A hash table that cannot place its entries must COUNT the
    overflow and transparently rerun through the sorted-runs tier —
    never truncate.  n_probes=0 guarantees nothing places; a DISTINCT
    matrix size keeps the crippled trace out of the jit cache other
    tests share."""
    from combblas_tpu.parallel import mesh3d

    monkeypatch.setattr(mesh3d, "HASH_MERGE_PROBES", 0)
    A3, B3 = _mats3d(rng, n=32, m=300)
    golden = _coo_canon(
        spgemm3d(PLUS_TIMES, A3, B3, tier="windowed", merge="sort")
    )
    obs.enable(install_hooks=False)
    try:
        obs.reset()
        got = _coo_canon(
            spgemm3d(PLUS_TIMES, A3, B3, tier="windowed", merge="hash")
        )
        assert obs.registry.get_counter("spgemm.merge.hash_overflow") > 0
        # the fallback rerun resolved (and counted) the runs tier
        assert obs.registry.get_counter(
            "spgemm.merge.tier", tier="runs", source="hash_fallback",
            op="spgemm3d",
        ) == 1
    finally:
        obs.disable()
        obs.reset()
    _assert_same(golden, got, "hash fallback")


def test_piece_overflow_detected_and_diagnosed(rng):
    """Round-13 satellite: the fiber exchange's piece overflow is
    surfaced — the kernel reports the drop count and the sized entries
    raise naming the slack knob (plus the obs counter) instead of
    silently truncating downstream."""
    from combblas_tpu.parallel.mesh3d import (
        _check_fiber_overflow,
        summa3d_spgemm,
    )

    A3, B3 = _mats3d(rng)
    # deliberately starved piece capacity: the kernel must REPORT it
    _, overflow = summa3d_spgemm(
        PLUS_TIMES, A3, B3, flop_capacity=1 << 14,
        out_capacity=1 << 12, piece_capacity=1,
    )
    assert int(overflow[0]) > 0
    obs.enable(install_hooks=False)
    try:
        obs.reset()
        with pytest.raises(ValueError, match="slack"):
            _check_fiber_overflow(
                int(overflow[0]), 1, "spgemm3d_windowed", 1.02
            )
        assert obs.registry.get_counter(
            "spgemm.summa3d.piece_overflow"
        ) == int(overflow[0])
    finally:
        obs.disable()
        obs.reset()


def test_merge_resolution_chain(rng, tmp_path, monkeypatch):
    """merge= resolves arg > store > env > heuristic (the tuner
    precedence, extended to the round-13 knob)."""
    from combblas_tpu.tuner import store as tuner_store

    monkeypatch.setenv("COMBBLAS_PLAN_STORE", str(tmp_path))
    tuner_store._reset_for_tests()
    A3, B3 = _mats3d(rng)
    store = tuner_store.get_store()
    key = tuner_store.spgemm3d_plan_key(PLUS_TIMES, A3, B3, "")
    store.put(key, tuner_store.PlanRecord(
        tier="windowed", merge="hash", source="bench", cost_s=1.0,
    ))
    obs.enable(install_hooks=False)
    try:
        # arg beats the store record AND the env
        monkeypatch.setenv("COMBBLAS_SPGEMM_MERGE", "sort")
        obs.reset()
        spgemm3d(PLUS_TIMES, A3, B3, merge="runs")
        assert obs.registry.get_counter(
            "spgemm.merge.tier", tier="runs", source="arg",
            op="spgemm3d",
        ) == 1
        # store beats the env
        obs.reset()
        spgemm3d(PLUS_TIMES, A3, B3)
        assert obs.registry.get_counter(
            "spgemm.merge.tier", tier="hash", source="store",
            op="spgemm3d",
        ) == 1
        # env beats the heuristic (tier forced so the record is
        # bypassed — arg > store holds for the tier, so merge falls
        # through to the env rung)
        obs.reset()
        spgemm3d(PLUS_TIMES, A3, B3, tier="esc")
        assert obs.registry.get_counter(
            "spgemm.merge.tier", tier="sort", source="env",
            op="spgemm3d",
        ) == 1
        # heuristic when nothing else decided: windowed scatter pieces
        # arrive presorted -> "runs"
        monkeypatch.delenv("COMBBLAS_SPGEMM_MERGE")
        obs.reset()
        spgemm3d(PLUS_TIMES, A3, B3, tier="windowed")
        assert obs.registry.get_counter(
            "spgemm.merge.tier", tier="runs", source="heuristic",
            op="spgemm3d",
        ) == 1
    finally:
        obs.disable()
        obs.reset()
        tuner_store._reset_for_tests()


def test_forced_hash_on_generic_monoid_degrades(rng, monkeypatch):
    """Review finding (r13): a fleet-wide ``COMBBLAS_SPGEMM_MERGE=hash``
    (or a hash plan record) on a semiring WITHOUT a native scatter
    combiner must degrade to ``runs`` at the knob — counted with a
    ``_degraded`` source — never assert mid-trace inside the shard_map
    body (the round-12 env-vetting precedent)."""
    from combblas_tpu.semiring import Semiring

    sr = Semiring(
        name="plus_times_generic", add=lambda x, y: x + y,
        mul=lambda a, x: a * x, zero_fn=lambda dt: 0,
        one_fn=lambda dt: 1, add_kind="generic",
    )
    monkeypatch.setenv("COMBBLAS_SPGEMM_MERGE", "hash")
    A3, B3 = _mats3d(rng)
    obs.enable(install_hooks=False)
    try:
        obs.reset()
        spgemm3d(sr, A3, B3, tier="esc")
        assert obs.registry.get_counter(
            "spgemm.merge.tier", tier="runs", source="env_degraded",
            op="spgemm3d",
        ) == 1
    finally:
        obs.disable()
        obs.reset()


def test_plan_record_merge_roundtrip(tmp_path, monkeypatch):
    """PlanRecord.merge persists through the JSONL store (additive
    field: pre-r13 lines load as None) and a mangled value is an
    invalid LINE, not a crash."""
    import json

    from combblas_tpu.tuner import store as tuner_store

    monkeypatch.setenv("COMBBLAS_PLAN_STORE", str(tmp_path))
    tuner_store._reset_for_tests()
    store = tuner_store.get_store()
    key = tuner_store.plan_key_from_counts(
        "plus_times", 64, 64, 64, 500, 500, "", "2x2",
        grid3="2x2x2", op="spgemm3d",
    )
    store.put(key, tuner_store.PlanRecord(tier="esc", merge="runs"))
    tuner_store._reset_for_tests()
    got = tuner_store.get_store().peek(key)
    assert got.merge == "runs"
    # hand-mangled merge value: the line is skipped as invalid
    with open(tuner_store.get_store().file, "a") as f:
        line = {
            "v": tuner_store.SCHEMA, "key": key.to_json(),
            "plan": {"tier": "esc", "merge": "bogus"},
        }
        f.write(json.dumps(line) + "\n")
    tuner_store._reset_for_tests()
    st = tuner_store.get_store()
    assert st.stats()["invalid_lines"] == 1
    assert st.peek(key).merge == "runs"  # the valid line still routes
    tuner_store._reset_for_tests()
