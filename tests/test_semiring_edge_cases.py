"""Regression tests for semiring edge cases found in review."""

import jax.numpy as jnp
import numpy as np

from combblas_tpu import MIN_PLUS, SELECT2ND_MAX, SpTuples
from combblas_tpu.ops.compressed import CSC
from combblas_tpu.ops.spmv import spmspv, spmv


def test_min_plus_integer_no_wraparound():
    # Unreached vertex (INT_MAX) must stay unreached, not wrap negative.
    d = np.array([[2, 3], [0, 1]], np.int32)
    t = SpTuples.from_dense(d)
    imax = np.iinfo(np.int32).max
    x = np.array([imax, 5], np.int32)
    y = np.asarray(spmv(MIN_PLUS, t, x))
    assert y[0] == 8  # min(2+inf, 3+5)
    assert y[1] == 6  # 1+5 (d[1,0]==0 is not stored)


def test_min_plus_both_identities():
    assert int(MIN_PLUS.mul(jnp.int32(np.iinfo(np.int32).max), jnp.int32(7))) == np.iinfo(np.int32).max
    assert int(MIN_PLUS.mul(jnp.int32(3), jnp.int32(4))) == 7


def test_select2nd_max_unsigned_zero():
    z = SELECT2ND_MAX.zero(jnp.uint32)
    assert int(z) == 0  # minval of uint32, no OverflowError


def test_spmspv_sentinel_not_prefix():
    # Valid entry NOT in the prefix — sentinel convention must govern.
    d = np.zeros((3, 2), np.float32)
    d[0, 1] = 2.0
    t = SpTuples.from_dense(d)
    csc = CSC.from_tuples(t)
    x_ind = np.array([2, 1], np.int32)  # slot 0 is padding (>= ncols)
    x_val = np.array([0.0, 5.0], np.float32)
    from combblas_tpu import PLUS_TIMES

    y_ind, y_val, y_nnz = spmspv(
        PLUS_TIMES, csc, jnp.asarray(x_ind), jnp.asarray(x_val),
        jnp.int32(1), out_capacity=3,
    )
    assert int(y_nnz) == 1
    assert int(np.asarray(y_ind)[0]) == 0
    assert float(np.asarray(y_val)[0]) == 10.0
