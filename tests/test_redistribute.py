"""On-device tuple redistribution (SparseCommon analog) + labeled I/O +
phase calculator."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from combblas_tpu import PLUS_TIMES
from combblas_tpu.io.labels import read_labeled_spmat, read_labeled_tuples
from combblas_tpu.parallel.grid import COL_AXIS, ROW_AXIS, Grid
from combblas_tpu.parallel.redistribute import (
    from_device_coo,
    redistribute_coo,
)
from combblas_tpu.parallel.spmat import SpParMat
from conftest import random_dense


def _device_chunks(grid, rows, cols, vals, chunk):
    """Scatter global tuples round-robin into [pr, pc, chunk] device chunks
    (simulating per-device generation)."""
    ndev = grid.size
    pr_, pc_ = grid.pr, grid.pc
    R = np.full((ndev, chunk), 1 << 30, np.int32)  # invalid sentinel
    C = np.full((ndev, chunk), 1 << 30, np.int32)
    V = np.zeros((ndev, chunk), np.float32)
    for k in range(len(rows)):
        d, s = k % ndev, k // ndev
        R[d, s], C[d, s], V[d, s] = rows[k], cols[k], vals[k]
    sh = grid.tile_sharding()
    put = lambda x: jax.device_put(
        jnp.asarray(x.reshape(pr_, pc_, chunk)), sh
    )
    return put(R), put(C), put(V)


@pytest.mark.parametrize("pr,pc", [(2, 2), (2, 4)])
def test_redistribute_matches_host_build(rng, pr, pc):
    grid = Grid.make(pr, pc)
    d = random_dense(rng, 16, 16, 0.3)
    rows, cols = np.nonzero(d)
    vals = d[rows, cols]
    chunk = -(-len(rows) // grid.size)
    R, C, V = _device_chunks(grid, rows, cols, vals, chunk)
    A = from_device_coo(grid, R, C, V, 16, 16)
    np.testing.assert_allclose(A.to_dense(), d, rtol=1e-6)


def test_redistribute_reports_drops(rng):
    grid = Grid.make(2, 2)
    d = random_dense(rng, 12, 12, 0.6)
    rows, cols = np.nonzero(d)
    vals = d[rows, cols]
    chunk = -(-len(rows) // grid.size)
    R, C, V = _device_chunks(grid, rows, cols, vals, chunk)
    _, dropped = redistribute_coo(
        grid, R, C, V, 12, 12, stage_capacity=2, tile_capacity=4
    )
    assert int(dropped) > 0  # deliberately starved capacities


def test_redistribute_dedup(rng):
    grid = Grid.make(2, 2)
    rows = np.array([1, 1, 5, 9])
    cols = np.array([2, 2, 3, 9])
    vals = np.array([1.0, 2.0, 5.0, 7.0], np.float32)
    R, C, V = _device_chunks(grid, rows, cols, vals, 1)
    A, dropped = redistribute_coo(
        grid, R, C, V, 12, 12, stage_capacity=8, tile_capacity=8,
        dedup_sr=PLUS_TIMES,
    )
    assert int(dropped) == 0
    dd = A.to_dense()
    assert dd[1, 2] == 3.0 and dd[5, 3] == 5.0 and dd[9, 9] == 7.0
    assert int(A.getnnz()) == 3


def test_read_labeled_tuples(tmp_path):
    p = tmp_path / "net.txt"
    p.write_text(
        "# comment\nprotA protB 0.9\nprotB protC\nprotA protC 0.4\n"
    )
    rows, cols, vals, labels = read_labeled_tuples(str(p))
    assert labels == ["protA", "protB", "protC"]
    np.testing.assert_array_equal(rows, [0, 1, 0])
    np.testing.assert_array_equal(cols, [1, 2, 2])
    np.testing.assert_allclose(vals, [0.9, 1.0, 0.4])
    grid = Grid.make(2, 2)
    A, labels2 = read_labeled_spmat(grid, str(p), symmetrize=True)
    d = A.to_dense()
    assert labels2 == labels
    assert d[0, 1] == d[1, 0] == np.float32(0.9)


def test_calculate_phases(rng):
    from combblas_tpu.parallel.spgemm import calculate_phases

    grid = Grid.make(2, 2)
    d = random_dense(rng, 16, 16, 0.4)
    A = SpParMat.from_dense(grid, d)
    assert calculate_phases(A, A, 10**9) == 1  # huge budget -> unphased
    tight = calculate_phases(A, A, 64)
    assert tight > 1 and (tight & (tight - 1)) == 0  # pow2


def test_square(rng):
    grid = Grid.make(2, 2)
    d = random_dense(rng, 12, 12, 0.3)
    A = SpParMat.from_dense(grid, d)
    np.testing.assert_allclose(
        A.square(PLUS_TIMES).to_dense(), d @ d, rtol=1e-5, atol=1e-6
    )
