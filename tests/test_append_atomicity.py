"""Multi-process file-substrate safety (round 17, ISSUE 15
satellites): the O_APPEND single-``write()`` contract both JSONL logs
(WAL, plan store) rest on, the plan-store compaction flock, and
checkpoint listing/loading under a concurrently-checkpointing sibling.

The writer children are plain interpreters (stdlib only — no jax
import) hammering the SAME files the product code reads back, so the
property is cheap enough for tier-1: two processes' interleaved
appends must produce only whole, parseable lines, with the loaders'
invalid-line counters at ZERO.
"""

import fcntl
import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from combblas_tpu.dynamic import WriteAheadLog
from combblas_tpu.parallel.grid import Grid
from combblas_tpu.serve import GraphEngine
from combblas_tpu.tuner import store as tstore
from combblas_tpu.utils import checkpoint

N = 64

#: Child writer: appends ``count`` fully formed lines produced by
#: ``make_line(worker, k)`` to one shared file — each line down as ONE
#: os.write to an O_APPEND fd, exactly the product appenders' contract.
_WRITER = textwrap.dedent("""
    import json, os, sys
    path, worker, count, kind = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4]
    )
    fd = os.open(path, os.O_APPEND | os.O_CREAT | os.O_WRONLY, 0o644)
    for k in range(count):
        if kind == "wal":
            seq = worker * 100000 + k
            rec = {"v": "combblas_tpu.wal/v1", "first_seq": seq,
                   "last_seq": seq, "rows": [worker], "cols": [k % 64],
                   "vals": [1.0], "ops": [0]}
        else:
            rec = {"v": "combblas_tpu.plans/v1",
                   "key": {"op": "spgemm", "shape": [worker, k, 0],
                           "band": [0, 0], "sr": "plusmul",
                           "backend": "cpu", "grid": "1x1"},
                   "plan": {"tier": "esc", "cost_s": 0.5,
                            "ts": 1000.0 + worker}}
        line = (json.dumps(rec, separators=(",", ":")) + "\\n").encode()
        n = os.write(fd, line)
        assert n == len(line)
    os.close(fd)
""")


def _run_writers(path, kind, nworkers=2, count=400):
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _WRITER, str(path), str(w),
             str(count), kind],
        )
        for w in range(nworkers)
    ]
    for p in procs:
        assert p.wait(timeout=120) == 0


def test_wal_concurrent_appends_only_whole_lines(tmp_path):
    """Two processes appending to ONE WAL: every line parses whole
    (the kernel's O_APPEND atomic seek+write), the loader's invalid
    counter is zero, and replay sees every record."""
    path = tmp_path / "wal.jsonl"
    _run_writers(path, "wal")
    wal = WriteAheadLog(str(path))
    batches = wal.replay()
    assert wal.invalid_lines == 0
    assert sum(len(b) for b in batches) == 800
    # and the product appender interoperates on the same file
    wal.append(500000, [1], [2], [1.0], [0])
    assert wal.position() == 500000
    wal.close()


def test_plan_store_concurrent_appends_only_whole_lines(tmp_path):
    path = tmp_path / "plans"
    path.mkdir()
    _run_writers(path / "plans.jsonl", "plans")
    st = tstore.PlanStore(str(path))
    s = st.stats()
    assert s["invalid_lines"] == 0
    # 2 workers x 400 distinct (worker, k) keys, every one parsed
    # whole — eviction (the max-entries cap) is the only reducer
    assert s["entries"] + s["evicted"] == 800
    # interop: a product append through the locked O_APPEND path
    from combblas_tpu.tuner.store import PlanKey, PlanRecord

    key = PlanKey(op="spgemm", shape=(9, 9, 9), band=(0, 0),
                  sr="plusmul", backend="cpu", grid="1x1")
    st.put(key, PlanRecord(tier="esc", cost_s=0.1))
    st2 = tstore.PlanStore(str(path))
    assert st2.lookup(key) is not None
    assert st2.stats()["invalid_lines"] == 0


# --- compaction flock (satellite: the PR 9 stat->replace window) -------------


def _fill_superseded(store_dir, n=30):
    """A plans.jsonl whose first n lines are shadowed by later ones —
    exactly what load-time compaction rewrites."""
    os.makedirs(store_dir, exist_ok=True)
    f = os.path.join(store_dir, "plans.jsonl")
    with open(f, "w") as fh:
        for i in range(n + 1):  # same key n+1 times: n superseded
            rec = {"v": tstore.SCHEMA,
                   "key": {"op": "spgemm", "shape": [1, 1, 1],
                           "band": [0, 0], "sr": "plusmul",
                           "backend": "cpu", "grid": "1x1"},
                   "plan": {"tier": "esc", "cost_s": float(i),
                            "ts": float(i)}}
            fh.write(json.dumps(rec) + "\n")
    return f


def test_compaction_skipped_under_contention(tmp_path, monkeypatch):
    """A sibling holding the advisory lock (mid-compaction) makes OUR
    compaction a SKIP — never a blocked load, never two rewrites
    racing os.replace."""
    monkeypatch.setenv("COMBBLAS_PLAN_STORE_COMPACT_MIN", "5")
    d = str(tmp_path / "store")
    f = _fill_superseded(d)
    lf = os.open(f + ".lock", os.O_CREAT | os.O_RDWR, 0o644)
    try:
        fcntl.flock(lf, fcntl.LOCK_EX)  # the "sibling compactor"
        st = tstore.PlanStore(d)
        assert st.stats()["compacted_lines"] == 0  # skipped
        assert sum(1 for _ in open(f)) == 31  # file untouched
    finally:
        fcntl.flock(lf, fcntl.LOCK_UN)
        os.close(lf)
    # lock released: the next loader compacts to one surviving line
    st2 = tstore.PlanStore(d)
    assert st2.stats()["compacted_lines"] == 30
    assert sum(1 for _ in open(f)) == 1


def test_compaction_leaves_sibling_append_intact(tmp_path, monkeypatch):
    """The PR 9 window, closed: an append landing after the loader
    read the file (but before its compaction) SURVIVES — the rewrite
    detects the grown file under the exclusive lock and backs off."""
    monkeypatch.setenv("COMBBLAS_PLAN_STORE_COMPACT_MIN", "5")
    d = str(tmp_path / "store")
    f = _fill_superseded(d)

    sibling = {"v": tstore.SCHEMA,
               "key": {"op": "spgemm", "shape": [7, 7, 7],
                       "band": [0, 0], "sr": "plusmul",
                       "backend": "cpu", "grid": "2x2"},
               "plan": {"tier": "esc", "cost_s": 9.0, "ts": 9.0}}
    line = (json.dumps(sibling) + "\n").encode()

    orig_getsize = os.path.getsize
    appended = {}

    def race_append(path):
        # the sibling's append lands exactly inside the old
        # stat->replace window: just before the compactor's size check
        if path == f and not appended:
            fd = os.open(f, os.O_APPEND | os.O_WRONLY)
            os.write(fd, line)
            os.close(fd)
            appended["done"] = True
        return orig_getsize(path)

    monkeypatch.setattr(os.path, "getsize", race_append)
    st = tstore.PlanStore(d)
    monkeypatch.setattr(os.path, "getsize", orig_getsize)
    assert appended  # the race actually ran
    assert st.stats()["compacted_lines"] == 0  # rewrite backed off
    # the sibling's measurement is still on disk and loads
    st2 = tstore.PlanStore(d)
    from combblas_tpu.tuner.store import PlanKey

    key = PlanKey(op="spgemm", shape=(7, 7, 7), band=(0, 0),
                  sr="plusmul", backend="cpu", grid="2x2")
    assert st2.lookup(key) is not None


# --- checkpoint dir under a concurrently-checkpointing sibling ---------------


@pytest.fixture(scope="module")
def grid():
    return Grid.make(1, 1)


def _coo(seed, n=N, m=300):
    r = np.random.default_rng(seed)
    rows = r.integers(0, n, m)
    cols = r.integers(0, n, m)
    return (
        np.concatenate([rows, cols]), np.concatenate([cols, rows])
    )


def test_list_snapshots_ignores_inflight_tmp(tmp_path, grid):
    rows, cols = _coo(1)
    eng = GraphEngine.from_coo(grid, rows, cols, N, kinds=("bfs",))
    p = str(tmp_path / checkpoint.snapshot_name(3))
    checkpoint.save_version(p, eng.version)
    # a sibling's in-flight atomic write: half an npz under .tmp names
    open(p + ".tmp", "wb").write(b"partial")
    open(str(tmp_path / "ckpt-000000000009.npz.tmp"), "wb").write(b"x")
    assert checkpoint.list_snapshots(str(tmp_path)) == [p]
    v, path = checkpoint.load_latest_version(str(tmp_path), grid)
    assert path == p


def test_vanished_snapshot_retries_fresh_listing(tmp_path, grid,
                                                 monkeypatch):
    """ISSUE 15 satellite: a snapshot pruned by a sibling between
    listing and open is NOT a SnapshotError — the loader re-lists
    once and finds the sibling's newer snapshot (no spurious
    rejected-counter, no warning)."""
    import warnings

    rows, cols = _coo(2)
    eng = GraphEngine.from_coo(grid, rows, cols, N, kinds=("bfs",))
    old = str(tmp_path / checkpoint.snapshot_name(3))
    newer = str(tmp_path / checkpoint.snapshot_name(9))
    checkpoint.save_version(old, eng.version)

    real_load = checkpoint.load_version
    state = {"raced": False}

    def racing_load(path, grid_, **kw):
        if path == old and not state["raced"]:
            # the sibling checkpoints seq 9 and prunes seq 3 in the
            # window between our listdir and our open
            state["raced"] = True
            checkpoint.save_version(newer, eng.version)
            os.unlink(old)
        return real_load(path, grid_, **kw)

    monkeypatch.setattr(checkpoint, "load_version", racing_load)
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # any fallback warning fails
        v, path = checkpoint.load_latest_version(str(tmp_path), grid)
    assert state["raced"] and path == newer
    assert checkpoint.snapshot_seq(path) == 9
