"""Multi-tenant engine pool (round 14): tenant routing, byte-accounted
LRU eviction, per-tenant breaker isolation, SLO admission, and the
weighted-fair-queueing pump.

Everything tier-1 here is pump-driven (worker-less) and deterministic;
the threaded mixed-tenant soak is ``slow``.
"""

import threading

import numpy as np
import pytest

from combblas_tpu.parallel.grid import Grid
from combblas_tpu.serve import (
    BackpressureError,
    CircuitBreakerOpen,
    EnginePool,
    ServeConfig,
)

N = 64


def _coo(seed, n=N, m=300):
    r = np.random.default_rng(seed)
    rows = r.integers(0, n, m)
    cols = r.integers(0, n, m)
    return (
        np.concatenate([rows, cols]), np.concatenate([cols, rows])
    )


def _cfg(**kw):
    kw.setdefault("lane_widths", (1, 2, 4))
    kw.setdefault("update_autostart", False)
    return ServeConfig(**kw)


def _pool(grid, names, weights=None, cfg=None, kinds=("bfs",)):
    pool = EnginePool(grid)
    for i, name in enumerate(names):
        rows, cols = _coo(i)
        pool.add_tenant(
            name, rows, cols, N,
            weight=(weights or {}).get(name, 1.0),
            config=cfg or _cfg(), kinds=kinds,
        )
    return pool


@pytest.fixture(scope="module")
def grid():
    return Grid.make(2, 4)


# --- routing + serving ------------------------------------------------------


def test_pool_serves_each_tenant_its_own_graph(grid):
    """Tenant -> engine routing: the same root queried through two
    tenants answers from two DIFFERENT graphs (and matches a direct
    engine execute on each)."""
    pool = _pool(grid, ("a", "b"))
    psrv = pool.serve()
    psrv.warmup(widths=(1,))
    futs = {
        t: psrv.submit(t, "bfs", 3, timeout_s=None) for t in ("a", "b")
    }
    while psrv.pump(force=True):
        pass
    got = {t: f.result(timeout=0)["levels"] for t, f in futs.items()}
    for t in ("a", "b"):
        direct = pool.engine(t).execute(
            "bfs", np.asarray([3], np.int32)
        )["levels"][:, 0]
        np.testing.assert_array_equal(got[t], direct)
    # two independent graphs: the answers differ
    assert not np.array_equal(got["a"], got["b"])


def test_pool_zero_retraces_after_warmup(grid):
    """The per-tenant plan caches hold: a warmed pool serves a mixed
    multi-tenant stream with ZERO retraces."""
    pool = _pool(grid, ("a", "b"))
    psrv = pool.serve()
    psrv.warmup(widths=(1, 2, 4))
    marks = {
        t: pool.engine(t).trace_mark() for t in ("a", "b")
    }
    futs = []
    for i in range(12):
        t = ("a", "b")[i % 2]
        futs.append(psrv.submit(t, "bfs", i % N))
    while psrv.pump(force=True):
        pass
    for f in futs:
        assert f.exception(timeout=0) is None
    for t, m in marks.items():
        assert pool.engine(t).retraces_since(m) == 0, t


def test_unknown_tenant_rejected(grid):
    pool = _pool(grid, ("a",))
    psrv = pool.serve()
    with pytest.raises(ValueError, match="unknown tenant"):
        psrv.submit("nope", "bfs", 0)


# --- byte-accounted LRU eviction --------------------------------------------


def test_lru_eviction_under_byte_budget(grid):
    """The LRU sweep keeps resident bytes under the budget, evicts the
    COLDEST idle tenant first, and a re-admitted tenant rebuilds
    BIT-EXACTLY from the retained host COO (``to_host_coo()``)."""
    pool = _pool(grid, ("a", "b", "c"))
    sizes = {
        t: pool.stats()["tenants"][t]["device_bytes"]
        for t in ("a", "b", "c")
    }
    assert all(v > 0 for v in sizes.values())
    before_a = pool.engine("a").version.E.to_host_coo()

    # budget fits only two graphs; touch order makes "a" the coldest
    pool.engine("a")
    pool.engine("b")
    pool.engine("c")
    pool.byte_budget = sizes["b"] + sizes["c"] + sizes["a"] - 1
    pool.refresh_bytes("c")  # triggers the sweep
    st = pool.stats()
    assert st["resident_bytes"] <= pool.byte_budget
    assert not st["tenants"]["a"]["resident"]  # LRU victim
    assert st["tenants"]["b"]["resident"]
    assert st["tenants"]["c"]["resident"]
    assert st["tenants"]["a"]["evictions"] == 1

    # re-admission: a rebuild from the retained host arrays, bit-exact
    after_a = pool.engine("a").version.E.to_host_coo()
    for x, y in zip(before_a, after_a):
        np.testing.assert_array_equal(x, y)
    st = pool.stats()
    assert st["tenants"]["a"]["admits"] == 2  # build + rebuild
    # the sweep ran again on admit: still under budget
    assert st["resident_bytes"] <= pool.byte_budget


def test_merged_mutations_survive_eviction(grid):
    """Regression (r14 review): an acknowledged write must survive the
    evict/re-admit cycle — the rebuild source is the CURRENT version's
    retained host COO, not the registration-time arrays."""
    cfg = _cfg(update_flush=1, update_max_delay_s=0.001)
    pool = EnginePool(grid)
    rows, cols = _coo(0)
    pool.add_tenant(
        "m", rows, cols, N, config=cfg, kinds=("bfs",), keep_coo=True,
    )
    psrv = pool.serve()
    present = set(zip(rows.tolist(), cols.tolist()))
    a, b = next(
        (i, j) for i in range(N) for j in range(N)
        if i != j and (i, j) not in present and (j, i) not in present
    )
    fut = psrv.submit_update("m", [("insert", a, b), ("insert", b, a)])
    while psrv.pump(force=True):
        pass
    assert fut.result(timeout=0)["ops"] == 2
    merged = pool.engine("m").version.E.to_host_coo()
    assert pool.evict("m")
    readmitted = pool.engine("m").version.E.to_host_coo()
    for x, y in zip(merged, readmitted):
        np.testing.assert_array_equal(x, y)  # the write survived
    lev = pool.engine("m").execute(
        "bfs", np.asarray([a], np.int32)
    )["levels"][:, 0]
    assert lev[b] == 1  # and it still serves


def test_eviction_refuses_busy_and_pending(grid):
    """A tenant with queued work (or a batch on the device) is not
    cold: ``evict`` refuses without ``force``."""
    pool = _pool(grid, ("a",))
    srv = pool.server("a")
    srv.submit("bfs", 1)
    assert not pool.evict("a")  # pending read -> not idle
    assert pool.stats()["tenants"]["a"]["resident"]
    while pool.serve().pump(force=True):
        pass
    assert pool.evict("a")  # drained -> cold, evictable
    # busy flag: never pull device state mid-batch, even forced
    t = pool._get("a")
    pool.admit("a")
    t.busy = True
    assert not pool.evict("a", force=True)
    t.busy = False


# --- SLO admission ----------------------------------------------------------


def test_slo_admission_names_tenant(grid):
    """A tenant's queue-depth budget rejects with a BackpressureError
    that NAMES the tenant, and the SLO deadline caps every admitted
    request's timeout."""
    cfg = _cfg(slo_queue_budget=2, slo_deadline_s=5.0,
               max_wait_s=30.0)
    pool = _pool(grid, ("acme",), cfg=cfg)
    psrv = pool.serve()
    psrv.submit("acme", "bfs", 1)
    psrv.submit("acme", "bfs", 2)
    with pytest.raises(BackpressureError) as ei:
        psrv.submit("acme", "bfs", 3)
    assert ei.value.tenant == "acme"
    assert "acme" in str(ei.value)
    # deadline budget applied although no timeout_s was passed
    q = pool.server("acme").scheduler._pending["bfs"]
    assert all(r.deadline is not None for r in q)
    pool.server("acme").scheduler.fail_pending(RuntimeError("teardown"))


# --- per-tenant breaker + fault isolation -----------------------------------


def test_breaker_isolation_across_tenants(grid):
    """Tenant A's poison trips A's breaker ONLY: B keeps serving, and
    A's fast-fail error names both the kind and the tenant."""
    cfg = _cfg(lane_widths=(1,), breaker_threshold=1)
    pool = _pool(grid, ("a", "b"), cfg=cfg)
    psrv = pool.serve()
    psrv.warmup(widths=(1,))
    # arm ONLY tenant a's injector: every execute fails
    psrv.faults("a").when("engine.execute", lambda ctx: True)

    fa = psrv.submit("a", "bfs", 1)
    fb = psrv.submit("b", "bfs", 1)
    while psrv.pump(force=True):
        pass
    assert fa.exception(timeout=0) is not None  # poisoned, isolated
    assert fb.exception(timeout=0) is None      # b unaffected

    with pytest.raises(CircuitBreakerOpen) as ei:
        psrv.submit("a", "bfs", 2)
    assert ei.value.tenant == "a"
    # b's breaker never saw a's failures
    f2 = psrv.submit("b", "bfs", 2)
    while psrv.pump(force=True):
        pass
    assert f2.exception(timeout=0) is None
    health = psrv.health()
    assert health["status"] == "degraded"
    assert health["breakers"]["a"]["bfs"]["state"] == "open"
    assert health["breakers"]["b"]["bfs"]["state"] == "closed"


# --- weighted fair queueing -------------------------------------------------


def test_wfq_weighted_share_under_saturation(grid):
    """Under saturated queues the served shares converge to the
    configured weights (3:1 here), the deficit-round-robin property."""
    cfg = _cfg(lane_widths=(1,), max_queue=64, max_wait_s=30.0)
    pool = _pool(
        grid, ("heavy", "light"),
        weights={"heavy": 3.0, "light": 1.0}, cfg=cfg,
    )
    psrv = pool.serve(quantum=4)
    psrv.warmup(widths=(1,))
    for i in range(40):
        psrv.submit("heavy", "bfs", i % N)
        psrv.submit("light", "bfs", i % N)
    for _ in range(3):  # three DRR rounds, both queues stay saturated
        psrv.pump(force=True)
    served = psrv.wfq.describe()["served"]
    assert served["heavy"] + served["light"] > 0
    ratio = served["heavy"] / max(served["light"], 1)
    assert 2.4 <= ratio <= 3.6, served
    # drain the rest so no futures are stranded
    while psrv.pump(force=True):
        pass


def test_wfq_write_merges_charge_the_tenant(grid):
    """Write-lane fairness: a tenant's update merges spend its own WFQ
    share (the ops count lands in ``served``), and the merge resolves
    through the pool pump."""
    cfg = _cfg(lane_widths=(1, 2), update_flush=1,
               update_max_delay_s=0.001)
    pool = EnginePool(grid)
    rows, cols = _coo(0)
    pool.add_tenant(
        "w", rows, cols, N, config=cfg, kinds=("bfs",), keep_coo=True,
    )
    psrv = pool.serve()
    vid0 = pool.engine("w").version_id
    fut = psrv.submit_update("w", [("insert", 1, 2), ("insert", 2, 1)])
    while psrv.pump(force=True):
        pass
    res = fut.result(timeout=0)
    assert res["ops"] == 2
    assert pool.engine("w").version_id == vid0 + 1
    assert psrv.wfq.describe()["served"]["w"] >= 2  # write ops charged


# --- introspection ----------------------------------------------------------


def test_pool_stats_and_health_carry_tenant_labels(grid):
    pool = _pool(grid, ("a", "b"))
    psrv = pool.serve()
    st = psrv.stats()
    assert set(st["tenants"]) == {"a", "b"}
    for t in ("a", "b"):
        assert st["servers"][t]["tenant"] == t
        assert "per_kind" in st["servers"][t]
    assert st["resident_bytes"] > 0
    assert st["byte_budget"] == 0  # conftest pins unbounded
    h = psrv.health()
    assert h["status"] == "ok"
    assert set(h["breakers"]) == {"a", "b"}
    # the single-tenant Server surface also names its tenant
    assert pool.server("a").stats()["tenant"] == "a"
    assert pool.server("a").health()["tenant"] == "a"


def test_wfq_prunes_removed_tenants(grid):
    """Tenant churn must not leak WFQ state: after remove_tenant the
    next pump drops the dead name from weights/deficit/served (r14
    review regression), and the worker-path scans tolerate a tenant
    vanishing between snapshot and lookup."""
    pool = _pool(grid, ("a", "b"))
    psrv = pool.serve()
    psrv.warmup(widths=(1,))
    for t in ("a", "b"):
        psrv.submit(t, "bfs", 1)
    while psrv.pump(force=True):
        pass
    assert set(psrv.wfq.describe()["weights"]) == {"a", "b"}
    pool.remove_tenant("b")
    # removal-tolerant scans: none of these may raise
    psrv._has_ready()
    psrv._next_deadline()
    psrv.submit("a", "bfs", 2)
    while psrv.pump(force=True):
        pass
    d = psrv.wfq.describe()
    assert set(d["weights"]) == {"a"}
    assert "b" not in d["deficit"] and "b" not in d["served"]


def test_remove_tenant_fails_pending(grid):
    """Pending READS and buffered WRITES both fail on removal — a
    removed tenant never strands a future (r14 review regression)."""
    pool = EnginePool(grid)
    rows, cols = _coo(0)
    pool.add_tenant(
        "a", rows, cols, N, config=_cfg(), kinds=("bfs",),
        keep_coo=True,
    )
    f = pool.server("a").submit("bfs", 1)
    w = pool.serve().submit_update("a", [("insert", 1, 2)])
    pool.remove_tenant("a")
    assert isinstance(f.exception(timeout=0), RuntimeError)
    assert isinstance(w.exception(timeout=5), RuntimeError)
    with pytest.raises(ValueError, match="unknown tenant"):
        pool.engine("a")


# --- threaded soak ----------------------------------------------------------


@pytest.mark.slow
def test_pool_threaded_mixed_tenants_with_evictions(grid):
    """The worker-threaded pool under a concurrent mixed-tenant stream
    WITH a byte budget forcing evictions mid-flight: every future
    settles, answers stay correct, and the pool ends under budget."""
    pool = _pool(grid, ("a", "b", "c"))
    sizes = [
        pool.stats()["tenants"][t]["device_bytes"]
        for t in ("a", "b", "c")
    ]
    pool.byte_budget = sum(sizes) - 1  # at most two resident
    golden = {
        t: pool.engine(t).execute(
            "bfs", np.asarray([5], np.int32)
        )["levels"][:, 0]
        for t in ("a", "b", "c")
    }
    with pool.serve() as psrv:
        futs = []
        errs = []

        def client(tenant):
            for i in range(10):
                try:
                    futs.append(
                        (tenant, psrv.submit(tenant, "bfs", 5))
                    )
                except BackpressureError as e:
                    errs.append(e)

        threads = [
            threading.Thread(target=client, args=(t,))
            for t in ("a", "b", "c")
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        for tenant, f in futs:
            np.testing.assert_array_equal(
                f.result(timeout=120)["levels"], golden[tenant]
            )
    # mid-flight the sweep may legitimately run over budget (victims
    # with queued work are not cold — counted as over_budget); once
    # drained, every tenant is idle and one sweep restores the bound
    resident = [
        t for t, s in pool.stats()["tenants"].items() if s["resident"]
    ]
    pool.refresh_bytes(resident[0])
    st = pool.stats()
    assert st["resident_bytes"] <= pool.byte_budget
    assert sum(
        t["evictions"] for t in st["tenants"].values()
    ) >= 1  # the budget actually forced churn
