"""Test harness: force an 8-device virtual CPU mesh.

Multi-chip logic (grids, collectives, shardings) is validated the way the
reference validates multi-node logic with `mpirun -np {1,4,16}` on one host
(SURVEY.md §4.4): XLA's host-platform device-count gives us 8 virtual CPU
devices, so 2x4 / 4x2 / 8x1 meshes all run in-process.

NOTE: the baked sitecustomize registers the axon TPU backend at interpreter
startup, so JAX_PLATFORMS env alone is not enough — we also flip the config
before any backend is initialized.
"""

import os
import tempfile

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

# Hermetic plan store (round 10): the measured-plan store defaults to the
# repo's .plan_store dir, and a store populated by an earlier bench run —
# or an ambient COMBBLAS_PLAN_STORE pointing at a fleet store — would
# silently change spgemm_auto's routing under test (tier choices must
# come from the code under test, not leftover measurements), so the env
# var is OVERRIDDEN unconditionally.  Tests that exercise the store
# itself monkeypatch COMBBLAS_PLAN_STORE to their own tmp_path and reset
# the singleton (tuner.store._reset_for_tests).
os.environ["COMBBLAS_PLAN_STORE"] = tempfile.mkdtemp(
    prefix="combblas-plans-"
)

# Hermetic pool/fleet knobs (round 14): an ambient byte budget would
# make tier-1 pool tests evict mid-flight (shapes and retrace counts
# would depend on the operator's fleet settings), an ambient quantum or
# replica count would reroute the WFQ-share and fleet tests — pin the
# defaults ("0" = default per the tuner/config convention); tests that
# exercise the knobs themselves pass explicit arguments instead.
os.environ["COMBBLAS_POOL_BYTE_BUDGET"] = "0"
os.environ["COMBBLAS_POOL_QUANTUM"] = "0"
os.environ["COMBBLAS_FLEET_REPLICAS"] = "0"

# Hermetic durability knobs (round 16): an ambient COMBBLAS_WAL would
# attach a write-ahead log + bootstrap checkpoint to EVERY server any
# tier-1 test builds (extra files, extra fsyncs, rerouted recovery
# semantics) — durability under test must come from explicit
# ServeConfig(wal_dir=...) arguments, so the env knobs are pinned to
# their defaults ("0"/"" = default per the tuner/config convention).
os.environ["COMBBLAS_WAL"] = "0"
os.environ["COMBBLAS_WAL_FSYNC"] = ""
os.environ["COMBBLAS_CHECKPOINT_EVERY"] = "0"
os.environ["COMBBLAS_CHECKPOINT_RETAIN"] = "0"

# Hermetic fleet-observability knobs (round 18): an ambient
# COMBBLAS_FLEETLOG would redirect every test ProcessFleet's
# supervision timeline to an operator path (and cross-test appends
# would interleave), an ambient COMBBLAS_OBS_HB_METRICS_S would change
# the heartbeat-snapshot cadence the federation tests time against —
# pin the defaults ("0" = default per the tuner/config convention);
# tests that exercise the knobs pass explicit arguments instead.
os.environ["COMBBLAS_FLEETLOG"] = "0"
os.environ["COMBBLAS_OBS_HB_METRICS_S"] = "0"

# Hermetic net-frontend knobs (round 19): an ambient COMBBLAS_NET_PORT
# would make every test NetFrontend bind a FIXED operator port (two
# tests in one run would collide on EADDRINUSE), ambient conn/backlog
# caps would change the backpressure tests' admission points, and
# ambient BENCH_NET_* rates would re-scale the slow open-loop harness
# test — pin the defaults ("0" = default per the tuner/config
# convention: port 0 means ephemeral); tests that exercise the knobs
# pass explicit arguments or monkeypatch instead.
os.environ["COMBBLAS_NET_PORT"] = "0"
os.environ["COMBBLAS_NET_MAX_CONNS"] = "0"
os.environ["COMBBLAS_NET_ACCEPT_BACKLOG"] = "0"
os.environ["BENCH_NET_RATE"] = "0"
os.environ["BENCH_NET_CONNS"] = "0"
os.environ["BENCH_NET_SECONDS"] = "0"

# Hermetic sharded wire-protocol knobs (round 21): an ambient
# COMBBLAS_SHARD_FRONTIER would force every sharded test's hop
# encoding (the equivalence sweep pins its own modes via build
# arguments), an ambient density threshold would move auto's
# crossover, and an ambient COMBBLAS_SHARD_WIRE=bf16 would quantize
# the bit-exactness gates — pin the defaults (""/"0" = default per
# the tuner/config convention).
os.environ["COMBBLAS_SHARD_FRONTIER"] = ""
os.environ["COMBBLAS_SHARD_DENSITY"] = "0"
os.environ["COMBBLAS_SHARD_WIRE"] = ""

# Hermetic trace sampling (round 15): an ambient
# COMBBLAS_OBS_TRACE_SAMPLE would make every obs-enabled serve test
# also record per-request traces (and their ``serve.trace.sampled``
# counters would perturb the zero-bookkeeping gates); tests that
# exercise tracing call obs.trace.set_sample_rate explicitly.
os.environ["COMBBLAS_OBS_TRACE_SAMPLE"] = "0"

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")

import numpy as np
import pytest


def pytest_configure(config):
    # "slow" keeps stress/latency tests out of the tier-1 budget
    # (ROADMAP.md runs `-m 'not slow'`); registered here since the repo
    # carries no pytest.ini.  Current slow set: the serve stress test
    # (test_serve.py) and the end-to-end bench.py subprocess run
    # (test_bench_summary.py) — the tier-1 guard for the summary-line
    # contract is the FAST test in that same file.
    config.addinivalue_line(
        "markers", "slow: excluded from the tier-1 `-m 'not slow'` run"
    )
    # chaos tests run SEEDED fault schedules (serve/faults.py), so the
    # fast ones are deterministic and stay in tier-1; long threaded
    # soak variants carry BOTH markers (chaos + slow)
    config.addinivalue_line(
        "markers",
        "chaos: fault-injection scenarios (seeded, deterministic; "
        "tier-1 unless also marked slow)",
    )


@pytest.fixture(scope="session", autouse=True)
def _devices():
    assert len(jax.devices()) == 8, jax.devices()
    yield


@pytest.fixture(scope="module", autouse=True)
def _bounded_jit_cache():
    """Release compiled executables (and the device constants they pin)
    between test modules: a full-suite process otherwise accumulates
    thousands of cached programs and their buffers, and the XLA:CPU
    compiler segfaults once allocation pressure gets high enough
    (reproduced deterministically ~190 tests in)."""
    yield
    jax.clear_caches()


@pytest.fixture
def rng():
    return np.random.default_rng(0)


def random_dense(rng, m, n, density=0.3, dtype=np.float32):
    """Random dense matrix with ~density nonzeros (shared test helper)."""
    d = rng.random((m, n)) * (rng.random((m, n)) < density)
    return d.astype(dtype)
