"""Tests for the Pallas butterfly-pack dense→sparse compaction kernel
(ops/pallas_sparsify.py) — interpret mode on CPU.

Covers the routing-network correctness contract: exact nonzero sets at
every density (including empty / full panels), row-major packing order,
non-suffix sentinel padding, custom semiring zeros, truncation safety
(total exact, no junk exposed), and the gcd panel-size fallback.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from combblas_tpu.ops.pallas_sparsify import (
    dense_to_sptuples,
    dense_to_tuples_arrays,
)


def _extract(t, M, N):
    rows = np.asarray(t.rows)
    cols = np.asarray(t.cols)
    vals = np.asarray(t.vals)
    live = rows < M
    return rows[live], cols[live], vals[live]


@pytest.mark.parametrize("density", [0.0, 0.05, 0.5, 1.0])
@pytest.mark.parametrize("pr", [8, 16])
def test_pack_matches_nonzero(density, pr):
    rng = np.random.default_rng(int(density * 10) + pr)
    M, N = 32, 256
    x = np.where(
        rng.random((M, N)) < density,
        rng.integers(1, 100, (M, N)).astype(np.float32),
        0.0,
    )
    cap = int((x != 0).sum()) + 256
    t, total = dense_to_sptuples(
        jnp.asarray(x), M, N, capacity=cap, panel_rows=pr, interpret=True
    )
    r, c, v = _extract(t, M, N)
    r_ref, c_ref = np.nonzero(x != 0)
    assert int(total) == len(r_ref)
    assert int(t.nnz) == len(r_ref)
    got = sorted(zip(r.tolist(), c.tolist(), v.tolist()))
    want = sorted(zip(r_ref.tolist(), c_ref.tolist(), x[r_ref, c_ref].tolist()))
    assert got == want


def test_pack_is_rowmajor_sorted():
    rng = np.random.default_rng(3)
    M, N = 64, 512
    x = np.where(rng.random((M, N)) < 0.2, 1.0, 0.0).astype(np.float32)
    t, _ = dense_to_sptuples(
        jnp.asarray(x), M, N, capacity=1 << 15, panel_rows=32, interpret=True
    )
    rows = np.asarray(t.rows)
    live = np.nonzero(rows < M)[0]
    flat = rows[live].astype(np.int64) * N + np.asarray(t.cols)[live]
    assert np.all(np.diff(flat) > 0)  # strictly increasing flat order


def test_semiring_zero_inf():
    """min_plus-style zero: +inf cells are padding, 0.0 is a REAL value."""
    rng = np.random.default_rng(4)
    M, N = 16, 128
    x = np.full((M, N), np.inf, np.float32)
    mask = rng.random((M, N)) < 0.3
    x[mask] = rng.integers(0, 5, (M, N)).astype(np.float32)[mask]
    t, total = dense_to_sptuples(
        jnp.asarray(x), M, N, zero=float(np.inf), capacity=4096,
        panel_rows=8, interpret=True,
    )
    assert int(total) == int(mask.sum())
    r, c, v = _extract(t, M, N)
    assert sorted(zip(r.tolist(), c.tolist())) == sorted(
        zip(*[a.tolist() for a in np.nonzero(mask)])
    )


def test_truncation_exact_total_no_junk():
    rng = np.random.default_rng(5)
    M, N = 64, 256
    x = (rng.random((M, N)) < 0.5).astype(np.float32)
    nnz = int(x.sum())
    t, total = dense_to_sptuples(
        jnp.asarray(x), M, N, capacity=64, panel_rows=8, interpret=True
    )
    assert int(total) == nnz  # exact even when truncating
    r, c, v = _extract(t, M, N)
    # every surfaced entry must be a real nonzero (no uninitialized junk)
    assert np.all(x[r, c] == v)


def test_padded_dims_stay_out():
    """Entries only in [:nrows, :ncols]; the padded tail must be absent."""
    M, N = 32, 256
    nrows, ncols = 20, 200
    x = np.zeros((M, N), np.float32)
    x[:nrows, :ncols] = 1.0
    t, total = dense_to_sptuples(
        jnp.asarray(x), nrows, ncols, capacity=8192, panel_rows=8,
        interpret=True,
    )
    r, c, _ = _extract(t, nrows, ncols)
    assert int(total) == nrows * ncols
    assert r.max() == nrows - 1 and c.max() == ncols - 1


def test_gcd_panel_fallback():
    """R not divisible by the default panel size → gcd fallback panels."""
    M, N = 24, 128  # R = 24, panel_rows 16 -> gcd 8
    x = np.eye(24, 128, dtype=np.float32)
    fi, fv, total, end_row = dense_to_tuples_arrays(
        jnp.asarray(x), capacity=256, panel_rows=16, interpret=True
    )
    assert int(total) == 24
    fi = np.asarray(fi)
    live = fi >= 0
    assert int(live[: int(end_row) * 128].sum()) == 24


@pytest.mark.parametrize(
    "total_nnz, pr",
    [
        (5120, 64),   # rows_used8 = 40 -> bucket 64 > cap_rows slack
        (1152, 32),   # rows_used8 = 9*128/128 -> 16-bucket round-up
        (4224, 64),   # 33 rows -> 40 aligned -> bucket 64
    ],
)
def test_exact_capacity_bucket_roundup(total_nnz, pr):
    """capacity == total must write every panel even when the DMA bucket
    rounds above the per-panel slack (ADVICE r4: the old fire test
    compared the bucket-rounded row count against cap_rows and silently
    dropped the panel — capacity=total=5120 returned nnz=0)."""
    rng = np.random.default_rng(total_nnz + pr)
    M, N = pr, 128  # one panel
    x = np.zeros((M, N), np.float32)
    flat = rng.choice(M * N, size=total_nnz, replace=False)
    x.reshape(-1)[flat] = 1.0
    t, total = dense_to_sptuples(
        jnp.asarray(x), M, N, capacity=total_nnz, panel_rows=pr,
        interpret=True,
    )
    assert int(total) == total_nnz
    r, c, v = _extract(t, M, N)
    assert len(r) == total_nnz, "panel dropped at exact capacity"
    r_ref, c_ref = np.nonzero(x != 0)
    assert sorted(zip(r.tolist(), c.tolist())) == sorted(
        zip(r_ref.tolist(), c_ref.tolist())
    )


def test_exact_capacity_multi_panel():
    """Two panels, capacity == total, both with bucket round-up."""
    rng = np.random.default_rng(9)
    M, N = 32, 256  # R = 64 flat rows, pr=32 -> 2 panels
    x = np.where(rng.random((M, N)) < 0.35, 1.0, 0.0).astype(np.float32)
    total_nnz = int((x != 0).sum())
    t, total = dense_to_sptuples(
        jnp.asarray(x), M, N, capacity=total_nnz, panel_rows=32,
        interpret=True,
    )
    assert int(total) == total_nnz
    r, c, _ = _extract(t, M, N)
    assert len(r) == total_nnz
