"""Golden tests for the deterministic Graph500 generator.

The constants below are the output of the graph500-1.2 reference generator
(vendored under the reference's graph500-1.2/generator, driven exactly as
RefGen21::generate_kronecker_range does — RefGen21.h:246-263), captured
with a standalone extractor compiled against the vendored
splittable_mrg.c/mrg_transitions.c/utils.c. Our numpy reimplementation
must reproduce them bit-for-bit.
"""

import numpy as np

from combblas_tpu.utils.refgen21 import graph500_edges, skip_table

# scale 10, M=16, userseed 0xDECAFBAD (init_random's fallback constant)
GOLDEN_S10_SEED_DECAFBAD = np.array([[43, 928], [87, 989], [815, 345], [858, 772], [898, 176], [788, 217], [64, 996], [931, 374], [706, 527], [324, 47], [613, 263], [151, 746], [392, 630], [680, 598], [1004, 262], [54, 64]], np.int64)

# scale 6, M=20, userseed 0 (the reference's -DDETERMINISTIC path)
GOLDEN_S6_SEED0 = np.array([[20, 23], [61, 15], [17, 34], [32, 5], [20, 32], [15, 4], [1, 60], [4, 3], [58, 29], [36, 59], [20, 15], [17, 15], [12, 26], [20, 58], [17, 15], [17, 15], [50, 60], [20, 15], [12, 15], [17, 17]], np.int64)


def test_first_edges_scale10():
    src, dst = graph500_edges(10, nedges=16, userseed=0xDECAFBAD)
    np.testing.assert_array_equal(src, GOLDEN_S10_SEED_DECAFBAD[:, 0])
    np.testing.assert_array_equal(dst, GOLDEN_S10_SEED_DECAFBAD[:, 1])


def test_first_edges_scale6_deterministic():
    src, dst = graph500_edges(6, nedges=20, userseed=0)
    np.testing.assert_array_equal(src, GOLDEN_S6_SEED0[:, 0])
    np.testing.assert_array_equal(dst, GOLDEN_S6_SEED0[:, 1])


def test_subrange_matches_full_stream():
    """Any [start, end) window equals the same slice of the full stream —
    the property multi-host generation relies on (RefGen21::make_graph
    splits the edge range over ranks)."""
    full = graph500_edges(8, nedges=64, userseed=42)
    part = graph500_edges(8, nedges=64, userseed=42, start_edge=17,
                          end_edge=41)
    np.testing.assert_array_equal(part[0], full[0][17:41])
    np.testing.assert_array_equal(part[1], full[1][17:41])


def test_skip_table_shape_and_identity():
    tab = skip_table()
    assert tab.shape == (24, 256, 9)
    # column 0 of every byte level is the identity transition
    ident = tab[0, 0]
    for i in range(24):
        np.testing.assert_array_equal(tab[i, 0], ident)


def test_edges_in_range():
    src, dst = graph500_edges(9, nedges=512, userseed=7)
    n = 1 << 9
    assert src.min() >= 0 and src.max() < n
    assert dst.min() >= 0 and dst.max() < n


def test_native_generator_matches_numpy():
    """The C++ generator is bit-identical to the numpy stream (which is
    itself golden-tested against the reference generator)."""
    from combblas_tpu.utils.refgen21 import (
        _load_native,
        graph500_edges_native,
    )

    if _load_native() is None:
        import pytest

        pytest.skip("no native toolchain")
    for scale, M, seed in [(10, 64, 0xDECAFBAD), (8, 128, 0), (12, 32, 7)]:
        s1, d1 = graph500_edges(scale, nedges=M, userseed=seed)
        s2, d2 = graph500_edges_native(scale, nedges=M, userseed=seed,
                                       nthreads=3)
        np.testing.assert_array_equal(s1, s2)
        np.testing.assert_array_equal(d1, d2)
    # sub-range through the native path
    full = graph500_edges_native(9, nedges=100, userseed=5)
    part = graph500_edges_native(9, nedges=100, userseed=5,
                                 start_edge=33, end_edge=77)
    np.testing.assert_array_equal(part[0], full[0][33:77])
    np.testing.assert_array_equal(part[1], full[1][33:77])
