"""Distributed SpMV and end-to-end BFS on virtual meshes.

The reference's BFS drivers self-check via traversal stats on generated
R-MATs (SURVEY.md §4.3); we go further and validate the whole parent tree
against a host BFS (the Graph500 verify.c checks the reference never wires
in).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from combblas_tpu import MIN_PLUS, PLUS_TIMES, SELECT2ND_MAX
from combblas_tpu.models.bfs import bfs, traversed_edges, validate_bfs_tree
from combblas_tpu.parallel.grid import Grid
from combblas_tpu.parallel.spmat import SpParMat
from combblas_tpu.parallel.spmv import dist_spmv
from combblas_tpu.parallel.vec import DistVec
from combblas_tpu.utils.rmat import rmat_edges, rmat_symmetric_coo
from conftest import random_dense

GRIDS = [(1, 1), (2, 2), (2, 4)]


@pytest.fixture(params=GRIDS, ids=[f"{a}x{b}" for a, b in GRIDS])
def grid(request):
    return Grid.make(*request.param)


def test_dist_spmv_plus_times(grid, rng):
    d = random_dense(rng, 22, 17)
    A = SpParMat.from_dense(grid, d)
    x = rng.random(17).astype(np.float32)
    y = dist_spmv(PLUS_TIMES, A, DistVec.from_global(grid, x))
    assert y.align == "row"
    np.testing.assert_allclose(y.to_global(), d @ x, rtol=1e-5)


def test_dist_spmv_min_plus(grid, rng):
    d = random_dense(rng, 11, 11, 0.4)
    A = SpParMat.from_dense(grid, d)
    x = rng.random(11).astype(np.float32)
    y = dist_spmv(MIN_PLUS, A, DistVec.from_global(grid, x))
    expect = np.where(d != 0, d + x[None, :], np.inf).min(axis=1)
    got = y.to_global()
    mask = ~np.isinf(expect)
    np.testing.assert_allclose(got[mask], expect[mask], rtol=1e-6)
    assert np.all(np.isinf(got[~mask]))


def test_dist_spmv_jitted(grid, rng):
    d = random_dense(rng, 16, 16)
    A = SpParMat.from_dense(grid, d)
    x = DistVec.from_global(grid, rng.random(16).astype(np.float32))
    f = jax.jit(lambda A, x: dist_spmv(PLUS_TIMES, A, x))
    np.testing.assert_allclose(f(A, x).to_global(), d @ x.to_global(), rtol=1e-5)


def test_rmat_generator_deterministic():
    key = jax.random.key(7)
    s1, d1 = rmat_edges(key, 8, 1000)
    s2, d2 = rmat_edges(key, 8, 1000)
    np.testing.assert_array_equal(np.asarray(s1), np.asarray(s2))
    assert np.asarray(s1).max() < 256 and np.asarray(d1).min() >= 0
    # skewed degree distribution: top vertex should have far more than mean
    deg = np.bincount(np.asarray(s1), minlength=256)
    assert deg.max() > 4 * deg.mean()


def test_bfs_small_path_graph(grid):
    # path 0-1-2-3-4 plus isolated 5,6
    n = 7
    d = np.zeros((n, n), np.float32)
    for u, v in [(0, 1), (1, 2), (2, 3), (3, 4)]:
        d[u, v] = d[v, u] = 1
    A = SpParMat.from_dense(grid, d)
    parents, levels, niter = bfs(A, 0)
    np.testing.assert_array_equal(levels.to_global(), [0, 1, 2, 3, 4, -1, -1])
    assert validate_bfs_tree(d, 0, parents.to_global(), levels.to_global()) == []
    assert int(niter) == 5  # 4 expanding levels + 1 empty-frontier detection


def test_bfs_rmat(grid):
    rows, cols = rmat_symmetric_coo(jax.random.key(3), scale=7, edgefactor=8)
    n = 1 << 7
    A = SpParMat.from_global_coo(
        grid, rows, cols, np.ones(len(rows), np.float32), n, n,
        dedup_sr=PLUS_TIMES,
    )
    d = A.to_dense()
    src = int(np.argmax((d != 0).sum(axis=0)))  # highest-degree vertex
    parents, levels, _ = bfs(A, src)
    errs = validate_bfs_tree(d, src, parents.to_global(), levels.to_global())
    assert errs == [], errs[:5]
    te = int(traversed_edges(A, parents))
    assert te > 0


def test_bfs_matches_across_grids():
    rows, cols = rmat_symmetric_coo(jax.random.key(5), scale=6, edgefactor=8)
    n = 64
    levels_by_grid = []
    for g in GRIDS:
        grid = Grid.make(*g)
        A = SpParMat.from_global_coo(
            grid, rows, cols, np.ones(len(rows), np.float32), n, n,
            dedup_sr=PLUS_TIMES,
        )
        _, levels, _ = bfs(A, 0)
        levels_by_grid.append(levels.to_global())
    for lv in levels_by_grid[1:]:
        np.testing.assert_array_equal(lv, levels_by_grid[0])


@pytest.mark.parametrize("shape", [
    (1, 1),
    # (2,2) is slow-lane (round 17, tier-1 budget): the batched
    # lanes are grid-independent mechanics and (2,4) keeps the
    # tier-1-mesh representative
    pytest.param((2, 2), marks=pytest.mark.slow),
    (2, 4),
])
def test_bfs_batch_matches_single(shape):
    """Multi-source batched BFS (one [n, W] frontier matrix) must produce,
    per lane, exactly the trees/levels of the single-root driver."""
    from combblas_tpu.models.bfs import bfs_batch
    from combblas_tpu.parallel.ellmat import EllParMat

    rows, cols = rmat_symmetric_coo(jax.random.key(11), 8, 6)
    n = 1 << 8
    grid = Grid.make(*shape)
    E = EllParMat.from_host_coo(
        grid, np.asarray(rows), np.asarray(cols),
        np.ones(len(rows), np.float32), n, n,
    )
    deg = np.bincount(np.asarray(rows), minlength=n)
    srcs = np.flatnonzero(deg > 0)[[0, 3, 17, 29]].astype(np.int32)
    pb, lb, it = bfs_batch(E, jnp.asarray(srcs))
    P = pb.to_global()  # [n, W]
    L = lb.to_global()
    assert P.shape == (n, len(srcs))
    for k, s in enumerate(srcs):
        p1, l1, _ = bfs(E, int(s))
        np.testing.assert_array_equal(L[:, k], l1.to_global())
        # parents may differ in ties only if semiring add differed; the same
        # SELECT2ND_MAX tie-break applies in both drivers
        np.testing.assert_array_equal(P[:, k], p1.to_global())


def test_batch_traversed_edges_matches_host():
    from combblas_tpu.models.bfs import batch_traversed_edges, bfs_batch
    from combblas_tpu.parallel.ellmat import EllParMat

    rows, cols = rmat_symmetric_coo(jax.random.key(5), 7, 8)
    n = 1 << 7
    grid = Grid.make(2, 2)
    E = EllParMat.from_host_coo(
        grid, np.asarray(rows), np.asarray(cols),
        np.ones(len(rows), np.float32), n, n,
    )
    deg = np.bincount(np.asarray(rows), minlength=n)
    srcs = np.flatnonzero(deg > 0)[[1, 5]].astype(np.int32)
    pb, _, _ = bfs_batch(E, jnp.asarray(srcs))
    lr = grid.local_rows(n)
    degb = jnp.asarray(
        np.pad(deg, (0, lr * grid.pr - n)).reshape(grid.pr, lr), jnp.int32
    )
    te = np.asarray(batch_traversed_edges(degb, pb))
    P = pb.to_global()
    for k in range(len(srcs)):
        expect = int(deg[P[:, k] >= 0].sum()) // 2
        assert te[k] == expect


@pytest.mark.parametrize("shape", [
    (1, 1),
    # (2,2) is slow-lane (round 17, tier-1 budget): (1,1) covers
    # the compact-lane mechanics, (2,4) the tier-1 mesh
    pytest.param((2, 2), marks=pytest.mark.slow),
    (2, 4),
])
def test_bfs_batch_compact_matches(shape):
    """Level-compressed batched BFS: identical levels to bfs_batch, and a
    valid BFS tree per lane (parents reconstructed post-hoc are any valid
    tree, so trees are validated, not compared)."""
    from combblas_tpu.models.bfs import bfs_batch, bfs_batch_compact
    from combblas_tpu.parallel.ellmat import EllParMat

    rows, cols = rmat_symmetric_coo(jax.random.key(13), 8, 6)
    n = 1 << 8
    grid = Grid.make(*shape)
    E = EllParMat.from_host_coo(
        grid, np.asarray(rows), np.asarray(cols),
        np.ones(len(rows), np.float32), n, n,
    )
    deg = np.bincount(np.asarray(rows), minlength=n)
    srcs = np.flatnonzero(deg > 0)[[0, 5, 23]].astype(np.int32)
    p1, l1, _ = bfs_batch(E, jnp.asarray(srcs))
    p2, l2, it = bfs_batch_compact(E, jnp.asarray(srcs))
    L1 = l1.to_global()
    L2 = l2.to_global().astype(np.int32)
    np.testing.assert_array_equal(L1, L2)
    # dense adjacency for tree validation
    d = np.zeros((n, n), bool)
    d[np.asarray(rows), np.asarray(cols)] = True
    P2 = p2.to_global()
    from combblas_tpu.models.bfs import validate_bfs_tree

    for k, s in enumerate(srcs):
        assert not validate_bfs_tree(d, int(s), P2[:, k], L2[:, k]), k


def test_bfs_batch_compact_ring_schedule():
    """The carousel (ppermute ring) fold produces identical levels to the
    fused all-reduce on a multi-device grid — the BitMapCarousel schedule
    as a real, testable program (BFSFriends.h:457-560)."""
    from combblas_tpu.models.bfs import bfs_batch_compact
    from combblas_tpu.parallel.ellmat import EllParMat

    rows, cols = rmat_symmetric_coo(jax.random.key(2), 8, 6)
    n = 1 << 8
    grid = Grid.make(2, 4)
    E = EllParMat.from_host_coo(
        grid, np.asarray(rows), np.asarray(cols),
        np.ones(len(rows), np.float32), n, n,
    )
    deg = np.bincount(np.asarray(rows), minlength=n)
    srcs = np.flatnonzero(deg > 0)[[0, 11]].astype(np.int32)
    _, l1, _ = bfs_batch_compact(E, jnp.asarray(srcs))
    _, l2, _ = bfs_batch_compact(E, jnp.asarray(srcs), ring=True)
    np.testing.assert_array_equal(l1.to_global(), l2.to_global())


@pytest.mark.parametrize("shape", [
    (1, 1),
    # the multi-device variant is slow-lane (round 12, tier-1 budget);
    # the diropt union-step's distributed path keeps coverage via
    # test_bfs_diropt and the 1x1 representative here
    pytest.param((2, 2), marks=pytest.mark.slow),
])
def test_bfs_batch_compact_diropt_matches(shape):
    """The union-frontier budgeted sparse regime (on-device lax.cond)
    produces identical levels + valid trees vs the always-dense path."""
    from combblas_tpu.models.bfs import bfs_batch_compact, validate_bfs_tree
    from combblas_tpu.parallel.ellmat import EllParMat, build_csc_companion

    rows, cols = rmat_symmetric_coo(jax.random.key(21), 8, 6)
    n = 1 << 8
    grid = Grid.make(*shape)
    rr, cc = np.asarray(rows), np.asarray(cols)
    E = EllParMat.from_host_coo(
        grid, rr, cc, np.ones(len(rr), np.float32), n, n
    )
    csc = build_csc_companion(grid, rr, cc, n, n)
    deg = np.bincount(rr, minlength=n)
    srcs = np.flatnonzero(deg > 0)[[0, 3]].astype(np.int32)
    _, l0, _ = bfs_batch_compact(E, jnp.asarray(srcs))
    # small budgets: some levels sparse, some dense
    p1, l1, _ = bfs_batch_compact(
        E, jnp.asarray(srcs), csc=csc,
        frontier_capacity=16, edge_capacity=256,
    )
    np.testing.assert_array_equal(l0.to_global(), l1.to_global())
    # generous budgets: everything through the sparse kernel
    p2, l2, _ = bfs_batch_compact(
        E, jnp.asarray(srcs), csc=csc,
        frontier_capacity=n, edge_capacity=4 * len(rr),
    )
    np.testing.assert_array_equal(l0.to_global(), l2.to_global())
    d = np.zeros((n, n), bool)
    d[rr, cc] = True
    for k, s_ in enumerate(srcs):
        assert not validate_bfs_tree(
            d, int(s_), p1.to_global()[:, k],
            l1.to_global().astype(np.int32)[:, k],
        ), k


@pytest.mark.parametrize("shape", [(1, 1), (2, 2)])
def test_validate_bfs_device(shape, rng):
    """Device-side Graph500 tree validation: clean trees pass, corrupted
    trees are flagged with the right violation class."""
    import dataclasses

    from combblas_tpu.models.bfs import bfs_batch, validate_bfs_device
    from combblas_tpu.parallel.ellmat import EllParMat

    grid = Grid.make(*shape)
    n = 64
    d = rng.random((n, n)) < 0.08
    d = d | d.T
    np.fill_diagonal(d, 0)
    rr, cc = np.nonzero(d)
    E = EllParMat.from_host_coo(
        grid, rr.astype(np.int64), cc.astype(np.int64),
        np.ones(len(rr), np.float32), n, n,
    )
    deg = np.bincount(rr, minlength=n)
    srcs = np.flatnonzero(deg > 0)[[0, 2]].astype(np.int32)
    p, l, _ = bfs_batch(E, jnp.asarray(srcs))
    v = np.asarray(validate_bfs_device(E, p, l))
    assert v.shape == (4, 2)
    assert (v == 0).all(), v

    # corrupt lane 0: point one discovered vertex's parent at a non-neighbor
    pg = p.to_global().copy()
    lg = l.to_global().copy()
    disc = np.flatnonzero((pg[:, 0] >= 0) & (pg[:, 0] != np.arange(n)))
    victim = int(disc[-1])
    non_neighbors = np.flatnonzero(~d[victim])
    bad_parent = int(non_neighbors[0])
    pg[victim, 0] = bad_parent
    from combblas_tpu.parallel.vec import DistMultiVec

    p_bad = DistMultiVec.from_global(grid, pg.astype(np.int32), align="row")
    v2 = np.asarray(validate_bfs_device(E, p_bad, l))
    assert v2[2, 0] > 0  # tree-edge violation in lane 0
    assert (v2[:, 1] == 0).all()  # lane 1 untouched

    # corrupt levels: shift a discovered vertex's level by 2
    lg2 = lg.copy()
    lg2[victim, 0] = lg2[victim, 0] + 2
    l_bad = DistMultiVec.from_global(grid, lg2.astype(np.int32), align="row")
    v3 = np.asarray(validate_bfs_device(E, p, l_bad))
    assert v3[1, 0] > 0 or v3[3, 0] > 0


def _bfs_single_sweep(shape, root_idx, tier_sets):
    """Shared body of the bfs_single agreement tests: run each root
    through each tier config and compare levels + tree validity
    against the reference ``bfs()``."""
    from combblas_tpu.models.bfs import bfs, bfs_single, validate_bfs_tree
    from combblas_tpu.parallel.ellmat import EllParMat, build_csc_companion
    from combblas_tpu.parallel.spmat import SpParMat

    rows, cols = rmat_symmetric_coo(jax.random.key(31), 8, 6)
    n = 1 << 8
    grid = Grid.make(*shape)
    rr, cc = np.asarray(rows), np.asarray(cols)
    E = EllParMat.from_host_coo(
        grid, rr, cc, np.ones(len(rr), np.float32), n, n
    )
    A = SpParMat.from_global_coo(
        grid, rr, cc, np.ones(len(rr), np.float32), n, n
    )
    csc = build_csc_companion(grid, rr, cc, n, n)
    from combblas_tpu.parallel.ellmat import build_csr_companion

    csr = build_csr_companion(grid, rr, cc, n, n)
    deg = np.bincount(rr, minlength=n)
    d = np.zeros((n, n), bool)
    d[rr, cc] = True
    for s in np.flatnonzero(deg > 0)[list(root_idx)]:
        p0, l0, _ = bfs(A, int(s))
        L0 = l0.to_global()
        for tiers in tier_sets:
            p1, l1, _ = bfs_single(E, int(s), csc, csr=csr, tiers=tiers)
            np.testing.assert_array_equal(L0, l1.to_global(), err_msg=str(tiers))
            assert not validate_bfs_tree(
                d, int(s), p1.to_global(), l1.to_global()
            ), tiers


_BFS_SINGLE_N = 1 << 8
_BFS_SINGLE_BIG = (_BFS_SINGLE_N,) * 6
#: The four tier regimes the sweep covers; each DISTINCT tuple traces
#: its own one-launch program, so compiles dominate the test's cost.
_BFS_SINGLE_TIERS = (
    (("td", (1, 0, 0, 0, 0, 0)),),          # forces dense nearly always
    (("td", _BFS_SINGLE_BIG),),             # everything top-down
    (("bu", _BFS_SINGLE_BIG),),             # everything bottom-up
    (("td", (4, 2, 1, 0, 0, 0)), ("bu", (16, 8, 2, 0, 0, 0)),
     ("td", _BFS_SINGLE_BIG)),              # mixed ladder
)


def test_bfs_single_matches():
    """Single-root tiered BFS (the spec's sequential kernel 2), the
    tier-1 representative (round 17, budget): ONE root through the
    two information-densest regimes — the forced-dense config and the
    mixed td/bu/td ladder (which exercises every tier transition plus
    the dense peak in one program).  The full sweep (both roots, all
    four regimes, multi-device grids) runs under ``-m slow``."""
    _bfs_single_sweep(
        (1, 1), [0], (_BFS_SINGLE_TIERS[0], _BFS_SINGLE_TIERS[3])
    )


@pytest.mark.slow
@pytest.mark.parametrize("shape", [(1, 1), (2, 2), (2, 4)])
def test_bfs_single_matches_full_sweep(shape):
    """The exhaustive regime x root x grid sweep (each pure-td and
    pure-bu ladder compiles its own ~10 s program on the 1-core CPU
    mesh; the fast representative above keeps the mixed ladder +
    forced-dense coverage in tier-1)."""
    _bfs_single_sweep(shape, [0, 7], _BFS_SINGLE_TIERS)


def test_single_traversed_edges_matches():
    from combblas_tpu.models.bfs import (
        bfs_single, single_traversed_edges,
    )
    from combblas_tpu.parallel.ellmat import EllParMat, build_csc_companion

    rows, cols = rmat_symmetric_coo(jax.random.key(5), 8, 6)
    n = 1 << 8
    grid = Grid.make(2, 2)
    rr, cc = np.asarray(rows), np.asarray(cols)
    E = EllParMat.from_host_coo(
        grid, rr, cc, np.ones(len(rr), np.float32), n, n
    )
    csc = build_csc_companion(grid, rr, cc, n, n)
    deg = np.bincount(rr, minlength=n)
    s = int(np.flatnonzero(deg > 0)[0])
    p, _, _ = bfs_single(E, s, csc, tiers=(("td", (64, 64, 64, 0, 0, 0)),))
    lr = grid.local_rows(n)
    degb = jnp.asarray(
        np.pad(deg, (0, lr * grid.pr - n)).reshape(grid.pr, lr), jnp.int32
    )
    te = int(np.asarray(single_traversed_edges(degb, p)))
    P = p.to_global()
    assert te == int(deg[P >= 0].sum()) // 2
