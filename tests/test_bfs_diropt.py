"""Direction-optimizing BFS vs the level-synchronous reference path.

Cross-implementation equivalence, the reference's own test pattern
(SURVEY §4.2: dobfs vs tdbfs on generated R-MAT inputs).
"""

import jax
import numpy as np
import pytest

from combblas_tpu.models.bfs import (bfs, bfs_diropt, bfs_diropt_auto,
                                     validate_bfs_tree)
from combblas_tpu.parallel.grid import Grid
from combblas_tpu.parallel.spmat import SpParMat
from combblas_tpu.utils.rmat import rmat_symmetric_coo


def _sym_random(rng, n, density):
    d = (rng.random((n, n)) < density).astype(np.float32)
    d = np.maximum(d, d.T)
    np.fill_diagonal(d, 0)
    return d


@pytest.mark.parametrize("pr,pc", [(2, 2), (2, 4)])
def test_diropt_matches_levelsync(rng, pr, pc):
    grid = Grid.make(pr, pc)
    d = _sym_random(rng, 24, 0.12)
    A = SpParMat.from_dense(grid, d)
    p1, l1, _ = bfs(A, 0)
    p2, l2, _ = bfs_diropt_auto(A, 0)
    # Parents may differ (any valid tree); levels must match exactly.
    np.testing.assert_array_equal(l1.to_global(), l2.to_global())
    assert not validate_bfs_tree(d, 0, p2.to_global(), l2.to_global())


def test_diropt_path_graph_many_levels(rng):
    """A path forces one level per vertex and a tiny frontier throughout —
    the pure top-down regime."""
    grid = Grid.make(2, 2)
    n = 16
    d = np.zeros((n, n), np.float32)
    for i in range(n - 1):
        d[i, i + 1] = d[i + 1, i] = 1
    A = SpParMat.from_dense(grid, d)
    p, l, niter = bfs_diropt(A, 0, frontier_capacity=4, exp_capacity=16)
    np.testing.assert_array_equal(l.to_global(), np.arange(n))
    assert niter == n  # n-1 productive levels + 1 empty terminator


def test_diropt_forces_bottomup(rng):
    """Tiny budgets force the dense bottom-up path from level 1 on; results
    must still be correct."""
    grid = Grid.make(2, 2)
    d = _sym_random(rng, 20, 0.3)
    A = SpParMat.from_dense(grid, d)
    p, l, _ = bfs_diropt(A, 3, frontier_capacity=1, exp_capacity=1)
    p0, l0, _ = bfs(A, 3)
    np.testing.assert_array_equal(l.to_global(), l0.to_global())
    assert not validate_bfs_tree(d, 3, p.to_global(), l.to_global())


def test_diropt_rmat(rng):
    grid = Grid.make(2, 2)
    rows, cols = rmat_symmetric_coo(jax.random.key(3), scale=7, edgefactor=6)
    n = 1 << 7
    A = SpParMat.from_global_coo(
        grid, rows, cols, np.ones(len(rows), np.float32), n, n
    )
    dense = A.to_dense()
    p, l, _ = bfs_diropt_auto(A, 1)
    assert not validate_bfs_tree(dense != 0, 1, p.to_global(), l.to_global())
