"""Unit tests for the padded COO tile (SpTuples) vs dense numpy references.

The reference has no unit tests (SURVEY.md §4) — this is the deterministic
seeded layer it lacks.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from combblas_tpu import MIN_PLUS, PLUS_TIMES, SELECT2ND_MAX, SpTuples
from combblas_tpu.ops.compressed import CSC, CSR
from conftest import random_dense


def test_roundtrip_dense(rng):
    d = random_dense(rng, 13, 7)
    t = SpTuples.from_dense(d, capacity=d.size)
    np.testing.assert_array_equal(np.asarray(t.to_dense()), d)
    assert int(t.nnz) == np.count_nonzero(d)


def test_sort_and_padding_at_tail(rng):
    d = random_dense(rng, 9, 11)
    t = SpTuples.from_dense(d, capacity=120)
    # scramble order
    perm = rng.permutation(120)
    t2 = SpTuples(
        rows=t.rows[perm], cols=t.cols[perm], vals=t.vals[perm],
        nnz=t.nnz, nrows=t.nrows, ncols=t.ncols,
    )
    s = t2.sort_rowmajor()
    n = int(s.nnz)
    rows = np.asarray(s.rows)
    assert np.all(rows[:n] < 9)
    assert np.all(rows[n:] == 9)
    np.testing.assert_array_equal(np.asarray(s.to_dense()), d)


def test_transpose(rng):
    d = random_dense(rng, 5, 8)
    t = SpTuples.from_dense(d, capacity=50)
    np.testing.assert_array_equal(np.asarray(t.transpose().to_dense()), d.T)


def test_compact_merges_duplicates():
    rows = [0, 2, 0, 1, 0]
    cols = [1, 3, 1, 1, 1]
    vals = [1.0, 5.0, 2.0, 3.0, 4.0]
    t = SpTuples.from_coo(rows, cols, vals, 4, 4, capacity=12)
    c = t.compact(PLUS_TIMES)
    dense = np.zeros((4, 4), np.float32)
    dense[0, 1] = 7.0
    dense[2, 3] = 5.0
    dense[1, 1] = 3.0
    np.testing.assert_array_equal(np.asarray(c.to_dense()), dense)
    assert int(c.nnz) == 3
    # compacted: valid prefix
    assert np.all(np.asarray(c.rows)[3:] == 4)


def test_compact_min_semiring():
    t = SpTuples.from_coo([0, 0], [1, 1], [5.0, 2.0], 2, 2, capacity=4)
    c = t.compact(MIN_PLUS)
    assert np.asarray(c.to_dense(MIN_PLUS))[0, 1] == 2.0


def test_prune_and_apply(rng):
    d = random_dense(rng, 10, 10)
    t = SpTuples.from_dense(d, capacity=128)
    p = t.prune(lambda v: v > 0.5)
    expect = np.where(d > 0.5, 0, d)
    np.testing.assert_array_equal(np.asarray(p.to_dense()), expect)
    a = t.apply(lambda v: v * 2)
    np.testing.assert_allclose(np.asarray(a.to_dense()), d * 2, rtol=1e-6)


def test_concat_compact(rng):
    d1 = random_dense(rng, 6, 6)
    d2 = random_dense(rng, 6, 6)
    t = SpTuples.concat(
        [SpTuples.from_dense(d1, capacity=40), SpTuples.from_dense(d2, capacity=40)]
    )
    c = t.compact(PLUS_TIMES)
    np.testing.assert_allclose(np.asarray(c.to_dense()), d1 + d2, rtol=1e-6)


def test_csr_csc_roundtrip(rng):
    d = random_dense(rng, 12, 9)
    t = SpTuples.from_dense(d, capacity=128)
    csr = CSR.from_tuples(t)
    np.testing.assert_array_equal(np.asarray(csr.to_tuples().to_dense()), d)
    lens = np.asarray(csr.row_lens())
    np.testing.assert_array_equal(lens, (d != 0).sum(axis=1))
    csc = CSC.from_tuples(t)
    np.testing.assert_array_equal(np.asarray(csc.to_tuples().to_dense()), d)
    np.testing.assert_array_equal(np.asarray(csc.col_lens()), (d != 0).sum(axis=0))


def test_empty_tile():
    t = SpTuples.empty(4, 4, 8, jnp.float32)
    assert int(t.nnz) == 0
    np.testing.assert_array_equal(np.asarray(t.to_dense()), np.zeros((4, 4)))
    c = t.compact(PLUS_TIMES)
    assert int(c.nnz) == 0
