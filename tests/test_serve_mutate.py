"""Serve write lane (round 11): submit_update admission, coalesced
merge+swap under live reads with zero retraces, backpressure, fault
isolation, and shutdown drain.  docs/dynamic.md "Serving writes"."""

import threading
import time

import numpy as np
import pytest

from combblas_tpu.parallel.grid import Grid
from combblas_tpu.serve import (
    BackpressureError,
    GraphEngine,
    InjectedFault,
    ServeConfig,
)


def _engine(rng, n=96, m=500, grid_shape=(2, 2), kinds=("bfs",)):
    r = rng.integers(0, n, m)
    c = rng.integers(0, n, m)
    rows = np.concatenate([r, c])
    cols = np.concatenate([c, r])
    return GraphEngine.from_coo(
        Grid.make(*grid_shape), rows, cols, n, kinds=kinds,
        keep_coo=True,
    )


def _absent_pair(engine, avoid=()):
    r0, c0, _ = engine.version.host_coo
    present = set(zip(r0.tolist(), c0.tolist()))
    n = engine.nrows
    return next(
        (a, b) for a in range(n) for b in range(n)
        if a != b and (a, b) not in present and (a, b) not in avoid
    )


def test_submit_update_end_to_end(rng):
    eng = _engine(rng)
    cfg = ServeConfig(
        lane_widths=(1, 4), max_wait_s=0.005,
        update_flush=2, update_max_delay_s=0.01,
    )
    a, b = _absent_pair(eng)
    with eng.serve(cfg) as srv:
        srv.warmup()
        mark = eng.trace_mark()
        v0 = eng.version_id
        fut = srv.submit_update([("insert", a, b), ("insert", b, a)])
        res = fut.result(timeout=60)
        assert res["mode"] == "incremental"
        assert res["version"] == v0 + 1
        # reads submitted after the merge see the mutated graph
        out = srv.submit("bfs", a).result(timeout=60)
        assert out["levels"][b] == 1
        assert eng.retraces_since(mark) == 0  # same-shape swap: no trace
        st = srv.stats()["updates"]
        assert st["merges"] == 1 and st["by_mode"] == {"incremental": 1}
        assert st["pending"] == 0


def test_pump_updates_deterministic_and_ordered(rng):
    """Worker-less embedding: update_autostart=False, pump_updates
    drives merges synchronously; two queued updates coalesce into ONE
    merge and both futures resolve to the same version."""
    eng = _engine(rng)
    srv = eng.serve(ServeConfig(
        lane_widths=(1,), update_autostart=False, update_flush=100,
    ))
    (a, b) = _absent_pair(eng)
    (a2, b2) = _absent_pair(eng, avoid={(a, b), (b, a)})
    f1 = srv.submit_update([("insert", a, b), ("insert", b, a)])
    f2 = srv.submit_update([("insert", a2, b2), ("insert", b2, a2)])
    assert not f1.done() and not f2.done()
    assert srv.pump_updates() == 0  # not due: flush=100, age tiny
    assert srv.pump_updates(force=True) == 4
    r1, r2 = f1.result(timeout=5), f2.result(timeout=5)
    assert r1["version"] == r2["version"]  # one coalesced merge
    assert r1["ops"] == 4
    r, c, _ = eng.version.host_coo
    present = set(zip(r.tolist(), c.tolist()))
    assert (a, b) in present and (a2, b2) in present
    srv.close()


def test_update_backpressure_rejects(rng):
    eng = _engine(rng, n=32, m=100)
    srv = eng.serve(ServeConfig(
        lane_widths=(1,), update_autostart=False, update_buffer=3,
    ))
    srv.submit_update([("insert", 0, 1), ("insert", 1, 0)])
    with pytest.raises(BackpressureError) as ei:
        srv.submit_update([("insert", 2, 3), ("insert", 3, 2)])
    assert ei.value.retry_after_s >= 0
    # the admitted update still merges fine
    assert srv.pump_updates(force=True) == 2
    srv.close()


def test_update_invalid_isolated(rng):
    eng = _engine(rng, n=32, m=100)
    srv = eng.serve(ServeConfig(lane_widths=(1,),
                                update_autostart=False))
    bad = srv.submit_update([("insert", 0, 1), ("insert", 99, 0)])
    assert isinstance(bad.exception(timeout=1), ValueError)
    assert srv.stats()["updates"]["invalid"] == 1
    # nothing was admitted (atomic): no pending ops
    assert srv.stats()["updates"]["pending"] == 0
    srv.close()


def test_update_requires_host_coo(rng):
    r = rng.integers(0, 32, 100)
    eng = GraphEngine.from_coo(
        Grid.make(1, 1), np.concatenate([r, r]),
        np.concatenate([r, r]), 32, kinds=("bfs",),  # no keep_coo
    )
    srv = eng.serve(ServeConfig(lane_widths=(1,)))
    with pytest.raises(ValueError, match="keep_coo"):
        srv.submit_update([("insert", 0, 1)])
    srv.close()


@pytest.mark.chaos
def test_update_merge_fault_isolated(rng):
    """An injected merge failure fails exactly the updates it carried;
    the old version keeps serving and the NEXT update merges fine."""
    eng = _engine(rng)
    srv = eng.serve(ServeConfig(
        lane_widths=(1,), update_autostart=False,
    ))
    srv.faults.script("update.merge", [0])  # first merge faults
    a, b = _absent_pair(eng)
    v0 = eng.version_id
    f1 = srv.submit_update([("insert", a, b), ("insert", b, a)])
    srv.pump_updates(force=True)
    assert isinstance(f1.exception(timeout=1), InjectedFault)
    assert eng.version_id == v0  # old version still serving
    assert srv.stats()["updates"]["failed"] == 1
    f2 = srv.submit_update([("insert", a, b), ("insert", b, a)])
    srv.pump_updates(force=True)
    assert f2.result(timeout=5)["version"] == v0 + 1
    srv.close()


def test_close_drains_pending_updates(rng):
    eng = _engine(rng)
    srv = eng.serve(ServeConfig(
        lane_widths=(1,), update_autostart=False,
    ))
    a, b = _absent_pair(eng)
    fut = srv.submit_update([("insert", a, b), ("insert", b, a)])
    srv.close(drain=True)
    assert fut.result(timeout=5)["mode"] == "incremental"
    r, c, _ = eng.version.host_coo
    assert (a, b) in set(zip(r.tolist(), c.tolist()))


def test_close_without_drain_fails_updates(rng):
    eng = _engine(rng)
    srv = eng.serve(ServeConfig(
        lane_widths=(1,), update_autostart=False,
    ))
    fut = srv.submit_update([("insert", 0, 1), ("insert", 1, 0)])
    srv.close(drain=False)
    assert isinstance(fut.exception(timeout=1), RuntimeError)
    with pytest.raises(RuntimeError, match="closed"):
        srv.submit_update([("insert", 2, 3)])


def test_close_without_drain_aborts_live_mutator(rng):
    """drain=False with a RUNNING mutation thread: buffered writes are
    abandoned (failed futures, graph untouched), not merged-and-swapped
    behind the caller's back on the stop path."""
    eng = _engine(rng)
    srv = eng.serve(ServeConfig(
        lane_widths=(1,),
        update_flush=10_000, update_max_delay_s=60.0,  # mutator idles
    )).start()
    a, b = _absent_pair(eng)
    v0 = eng.version_id
    fut = srv.submit_update([("insert", a, b), ("insert", b, a)])
    assert srv.health()["mutator_alive"]
    srv.close(drain=False)
    assert isinstance(fut.exception(timeout=5), RuntimeError)
    assert eng.version_id == v0  # the abandoned write was NOT applied
    r, c, _ = eng.version.host_coo
    assert (a, b) not in set(zip(r.tolist(), c.tolist()))


def test_mixed_read_write_under_load(rng):
    """Concurrent readers + writers through the threaded server: every
    read completes, every write merges, zero retraces (incremental
    merges preserve operand shapes), and the version advances."""
    eng = _engine(rng, n=128, m=700, kinds=("bfs", "pagerank"))
    widths = (1, 2, 4)
    cfg = ServeConfig(
        lane_widths=widths, max_wait_s=0.002,
        update_flush=8, update_max_delay_s=0.005,
    )
    n = eng.nrows
    r0, c0, _ = eng.version.host_coo
    deg = np.asarray(eng.version.deg)
    roots = rng.choice(np.flatnonzero(deg > 0), size=64)
    # endpoints whose degree sits BELOW its fine-ladder class width
    # (5 -> kb 6, 7 -> kb 8, 9..11 -> kb 12, 13..15 -> kb 16): a +1
    # insert stays in class, so every churn merge is provably the
    # in-place incremental fast path — no rebuild, no shape change
    slack = np.isin(deg, (5, 7, 9, 10, 11, 13, 14, 15))
    present = set(zip(r0.tolist(), c0.tolist()))
    pool = np.flatnonzero(slack).tolist()
    # DISJOINT pairs: each vertex in at most one, so its degree moves
    # by exactly +-1 per phase and never drifts out of its slack class
    pairs = [
        (a, b) for a, b in zip(pool[0::2], pool[1::2])
        if (a, b) not in present
    ][:12]
    assert len(pairs) >= 4, "graph too regular for the churn pool"
    with eng.serve(cfg) as srv:
        srv.warmup()
        mark = eng.trace_mark()
        v0 = eng.version_id
        write_futs = []
        stop = threading.Event()

        def writer():
            # insert each slack pair, then delete it again one batch
            # later: real structural change per merge, degree classes
            # provably stable
            for k, (a, b) in enumerate(pairs + pairs):
                if stop.is_set():
                    break
                op = "insert" if k < len(pairs) else "delete"
                try:
                    write_futs.append(srv.submit_update(
                        [(op, a, b), (op, b, a)]
                    ))
                except BackpressureError:
                    pass
                time.sleep(0.002)

        t = threading.Thread(target=writer)
        t.start()
        read_futs = [
            srv.submit(("bfs", "pagerank")[i % 2], int(root))
            for i, root in enumerate(roots)
        ]
        for f in read_futs:
            f.result(timeout=120)
        t.join(10)
        stop.set()
        for f in write_futs:
            f.result(timeout=60)
        st = srv.stats()
        assert st["updates"]["merges"] >= 1
        assert st["updates"]["by_mode"].get("rebuild", 0) == 0
        assert eng.version_id > v0
        assert eng.retraces_since(mark) == 0
        assert st["completed"] == len(roots)
