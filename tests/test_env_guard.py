"""Tier-1 guard: ``COMBBLAS_*`` env knobs are parsed in ONE place.

Round 10 centralized every ``COMBBLAS_SPGEMM_*`` / tuner knob into
``tuner/config.py`` (precedence documented once, identical "0 means
default" semantics everywhere); round 11 added the dynamic-lane and
store-aging knobs THROUGH that module.  This test locks the invariant
in: any new ``os.environ`` read of a ``COMBBLAS_`` name outside the
allowlist below fails tier-1, so scattered knob parsing cannot creep
back.

Allowed:

* ``tuner/config.py`` — the one parser;
* ``obs/__init__.py`` — ``COMBBLAS_OBS`` / ``COMBBLAS_OBS_SYNC`` only:
  the telemetry gate must resolve at import time without pulling the
  tuner package into every obs consumer.
"""

import os
import re

import combblas_tpu

PKG_ROOT = os.path.dirname(os.path.abspath(combblas_tpu.__file__))

#: file (relative, /-separated) -> allowed COMBBLAS_* names, or "*".
ALLOWED = {
    "tuner/config.py": "*",
    "obs/__init__.py": {"COMBBLAS_OBS", "COMBBLAS_OBS_SYNC"},
}

_NAME = re.compile(r"COMBBLAS_[A-Z0-9_]+")


def _env_read_names(lines, idx, window=2):
    """COMBBLAS_* names within ``window`` lines of an os.environ read
    (catches the name sitting on the call line or a continuation)."""
    lo = max(0, idx - window)
    hi = min(len(lines), idx + window + 1)
    names = set()
    for ln in lines[lo:hi]:
        names.update(_NAME.findall(ln))
    return names


def test_no_stray_combblas_env_reads():
    violations = []
    for dirpath, _dirnames, filenames in os.walk(PKG_ROOT):
        if "__pycache__" in dirpath:
            continue
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, PKG_ROOT).replace(os.sep, "/")
            allowed = ALLOWED.get(rel, set())
            if allowed == "*":
                continue
            with open(path, encoding="utf-8") as f:
                lines = f.readlines()
            for i, line in enumerate(lines):
                if "os.environ" not in line and "environ[" not in line:
                    continue
                stray = _env_read_names(lines, i) - set(allowed)
                if stray:
                    violations.append(
                        f"{rel}:{i + 1}: {sorted(stray)}"
                    )
    assert not violations, (
        "COMBBLAS_* env reads outside tuner/config.py (add an accessor "
        "there instead — precedence and '0 means default' semantics "
        "live in one place):\n" + "\n".join(violations)
    )


def test_dynamic_knobs_centralized():
    """The round-11 knobs exist and parse through tuner/config."""
    from combblas_tpu.tuner import config

    assert config.ENV_DYNAMIC_SPILL.startswith("COMBBLAS_")
    assert 0 < config.dynamic_spill_frac() <= 1.0
    assert config.store_max_entries() >= 1
    assert config.store_compact_min() >= 1


def test_durability_knobs_centralized(monkeypatch, tmp_path):
    """The round-16 durability knobs parse through tuner/config with
    the shared conventions: unset/"0"/"off" disable the WAL dir,
    explicit argument beats the env, a bogus fsync policy raises
    NAMING the knob, and the integer knobs clamp sane."""
    import pytest

    from combblas_tpu.tuner import config

    for name in (
        config.ENV_WAL, config.ENV_WAL_FSYNC,
        config.ENV_CHECKPOINT_EVERY, config.ENV_CHECKPOINT_RETAIN,
    ):
        assert name.startswith("COMBBLAS_")
    # conftest pins these to defaults: durability off, fsync always
    assert config.wal_dir() is None
    assert config.wal_fsync() == config.DEFAULT_WAL_FSYNC == "always"
    assert config.checkpoint_every() == config.DEFAULT_CHECKPOINT_EVERY
    assert (
        config.checkpoint_retain() == config.DEFAULT_CHECKPOINT_RETAIN
    )
    monkeypatch.setenv(config.ENV_WAL, str(tmp_path))
    monkeypatch.setenv(config.ENV_WAL_FSYNC, "off")
    monkeypatch.setenv(config.ENV_CHECKPOINT_EVERY, "3")
    monkeypatch.setenv(config.ENV_CHECKPOINT_RETAIN, "5")
    assert config.wal_dir() == str(tmp_path)
    assert config.wal_fsync() == "off"
    assert config.checkpoint_every() == 3
    assert config.checkpoint_retain() == 5
    # argument > env; "off"/"0" disable explicitly; vetting raises
    assert config.wal_dir("off") is None
    assert config.wal_dir("0") is None
    assert config.wal_fsync("always") == "always"
    assert config.checkpoint_every(1) == 1
    assert config.checkpoint_retain(0) == 1  # clamped: retain >= 1
    with pytest.raises(ValueError, match=config.ENV_WAL_FSYNC):
        config.wal_fsync("sometimes")


def test_fleet_obs_knobs_centralized(monkeypatch, tmp_path):
    """The round-18 fleet-observability knobs parse through
    tuner/config with the shared conventions: unset/"0"/"off" disable
    the fleetlog path, explicit argument beats the env, and the
    heartbeat-snapshot cadence clamps sane."""
    from combblas_tpu.tuner import config

    for name in (config.ENV_FLEETLOG, config.ENV_OBS_HB_METRICS_S):
        assert name.startswith("COMBBLAS_")
    # conftest pins these to "0" => defaults: no operator fleetlog
    # redirect, default heartbeat-snapshot cadence
    assert config.fleetlog_path() is None
    assert (
        config.obs_hb_metrics_interval() == config.DEFAULT_OBS_HB_METRICS_S
    )
    log = tmp_path / "fleet.jsonl"
    monkeypatch.setenv(config.ENV_FLEETLOG, str(log))
    monkeypatch.setenv(config.ENV_OBS_HB_METRICS_S, "2.5")
    assert config.fleetlog_path() == str(log)
    assert config.obs_hb_metrics_interval() == 2.5
    # argument > env; "off"/"0" disable explicitly; cadence clamps
    assert config.fleetlog_path("off") is None
    assert config.fleetlog_path("0") is None
    assert config.obs_hb_metrics_interval(0.001) == 0.05
    assert (
        config.obs_hb_metrics_interval(0)
        == config.DEFAULT_OBS_HB_METRICS_S
    )


def test_net_knobs_centralized(monkeypatch):
    """The round-19 net-frontend + open-loop-bench knobs parse through
    tuner/config with the shared conventions: unset/"0" = default
    (port 0 = ephemeral bind), explicit argument beats the env, the
    count knobs clamp sane, and a bogus value raises NAMING the
    knob."""
    import pytest

    from combblas_tpu.tuner import config

    for name in (
        config.ENV_NET_PORT, config.ENV_NET_MAX_CONNS,
        config.ENV_NET_ACCEPT_BACKLOG,
    ):
        assert name.startswith("COMBBLAS_")
    for name in (
        config.ENV_BENCH_NET_RATE, config.ENV_BENCH_NET_CONNS,
        config.ENV_BENCH_NET_SECONDS,
    ):
        assert name.startswith("BENCH_NET_")
    # conftest pins these to "0" => defaults: ephemeral port, default
    # conn/backlog caps, default open-loop shape
    assert config.net_port() == config.DEFAULT_NET_PORT == 0
    assert config.net_max_conns() == config.DEFAULT_NET_MAX_CONNS
    assert config.net_accept_backlog() == config.DEFAULT_NET_ACCEPT_BACKLOG
    assert config.bench_net_rate() == config.DEFAULT_BENCH_NET_RATE
    assert config.bench_net_conns() == config.DEFAULT_BENCH_NET_CONNS
    assert config.bench_net_seconds() == config.DEFAULT_BENCH_NET_SECONDS
    monkeypatch.setenv(config.ENV_NET_PORT, "19219")
    monkeypatch.setenv(config.ENV_NET_MAX_CONNS, "64")
    monkeypatch.setenv(config.ENV_NET_ACCEPT_BACKLOG, "16")
    monkeypatch.setenv(config.ENV_BENCH_NET_RATE, "50.5")
    monkeypatch.setenv(config.ENV_BENCH_NET_CONNS, "32")
    monkeypatch.setenv(config.ENV_BENCH_NET_SECONDS, "2.5")
    assert config.net_port() == 19219
    assert config.net_max_conns() == 64
    assert config.net_accept_backlog() == 16
    assert config.bench_net_rate() == 50.5
    assert config.bench_net_conns() == 32
    assert config.bench_net_seconds() == 2.5
    # argument > env, clamped sane
    assert config.net_port(0) == 0
    assert config.net_max_conns(1) == 1
    assert config.net_max_conns(-3) == 1  # clamp >= 1
    assert config.net_accept_backlog(-1) == 1
    assert config.bench_net_conns(0) == config.DEFAULT_BENCH_NET_CONNS
    assert config.bench_net_rate(0.01) == 0.1  # clamp >= 0.1
    # vetting raises NAMING the knob
    with pytest.raises(ValueError, match=config.ENV_NET_PORT):
        config.net_port(70000)
    with pytest.raises(ValueError, match=config.ENV_NET_PORT):
        config.net_port("not-a-port")
    with pytest.raises(ValueError, match=config.ENV_NET_MAX_CONNS):
        config.net_max_conns("many")
    with pytest.raises(ValueError, match=config.ENV_BENCH_NET_RATE):
        config.bench_net_rate("fast")


def test_shard_wire_knobs_centralized(monkeypatch):
    """The round-21 sharded wire-protocol knobs parse through
    tuner/config with the shared conventions: unset/""/"0" = default,
    explicit argument beats the env, the density fraction is vetted
    to (0, 1], and a bogus value raises NAMING the knob."""
    import pytest

    from combblas_tpu.tuner import config

    for name in (config.ENV_SHARD_FRONTIER, config.ENV_SHARD_DENSITY,
                 config.ENV_SHARD_WIRE):
        assert name.startswith("COMBBLAS_")
    # conftest pins ""/"0" => defaults
    assert config.shard_frontier() == config.DEFAULT_SHARD_FRONTIER
    assert config.shard_frontier() == "auto"
    assert config.shard_density() == config.DEFAULT_SHARD_DENSITY
    assert config.shard_wire() == config.DEFAULT_SHARD_WIRE == "f32"
    monkeypatch.setenv(config.ENV_SHARD_FRONTIER, "sparse")
    monkeypatch.setenv(config.ENV_SHARD_DENSITY, "0.5")
    monkeypatch.setenv(config.ENV_SHARD_WIRE, "bf16")
    assert config.shard_frontier() == "sparse"
    assert config.shard_density() == 0.5
    assert config.shard_wire() == "bf16"
    # explicit argument beats the env
    assert config.shard_frontier("dense") == "dense"
    assert config.shard_density(0.1) == 0.1
    assert config.shard_wire("f32") == "f32"
    # "0" falls through to the default (the bench-knob convention)
    assert config.shard_density(0) == config.DEFAULT_SHARD_DENSITY
    # vetting raises NAMING the knob
    with pytest.raises(ValueError, match=config.ENV_SHARD_FRONTIER):
        config.shard_frontier("csr")
    with pytest.raises(ValueError, match=config.ENV_SHARD_DENSITY):
        config.shard_density(1.5)
    with pytest.raises(ValueError, match=config.ENV_SHARD_DENSITY):
        config.shard_density("most")
    with pytest.raises(ValueError, match=config.ENV_SHARD_WIRE):
        config.shard_wire("fp8")


def test_pool_fleet_knobs_centralized(monkeypatch):
    """The round-14 pool/fleet knobs parse through tuner/config with
    the shared conventions (unset/empty/"0" = default; explicit
    argument beats the env)."""
    from combblas_tpu.tuner import config

    for name in (
        config.ENV_POOL_BYTE_BUDGET, config.ENV_POOL_QUANTUM,
        config.ENV_FLEET_REPLICAS,
    ):
        assert name.startswith("COMBBLAS_")
    # conftest pins these to "0" => defaults
    assert config.pool_byte_budget() == config.DEFAULT_POOL_BYTE_BUDGET
    assert config.pool_quantum() == config.DEFAULT_POOL_QUANTUM
    assert config.fleet_replicas() == config.DEFAULT_FLEET_REPLICAS
    monkeypatch.setenv(config.ENV_POOL_BYTE_BUDGET, str(1 << 20))
    monkeypatch.setenv(config.ENV_POOL_QUANTUM, "8")
    monkeypatch.setenv(config.ENV_FLEET_REPLICAS, "3")
    assert config.pool_byte_budget() == 1 << 20
    assert config.pool_quantum() == 8
    assert config.fleet_replicas() == 3
    # argument > env, clamped sane
    assert config.pool_byte_budget(4096) == 4096
    assert config.pool_quantum(1) == 1
    assert config.fleet_replicas(5) == 5
