"""HipMCL stack: kselect, prune_column, col split/concat, ewise_add,
add_loops, phased SpGEMM, and the MCL clustering driver.

Golden pattern mirrors the reference's ReleaseTests (numpy as the trusted
slow path) plus the self-checking generated-input style of
Applications/CMakeLists.txt ADD_TESTs.
"""

import os

import numpy as np
import jax.numpy as jnp
import pytest

from combblas_tpu import PLUS_TIMES
from combblas_tpu.models.mcl import (
    chaos,
    inflate,
    make_col_stochastic,
    mcl,
    mcl_prune_recovery_select,
)
from combblas_tpu.parallel.grid import Grid
from combblas_tpu.parallel.spgemm import mem_efficient_spgemm, spgemm
from combblas_tpu.parallel.spmat import SpParMat
from combblas_tpu.parallel.vec import DistVec
from conftest import random_dense


def kth_largest_per_col(d, k):
    """Trusted slow path: per-column k-th largest nonzero (or -inf)."""
    out = np.full(d.shape[1], -np.inf, dtype=np.float64)
    for j in range(d.shape[1]):
        nz = np.sort(d[:, j][d[:, j] != 0])[::-1]
        if len(nz) >= k:
            out[j] = nz[k - 1]
    return out


@pytest.mark.parametrize("pr,pc", [(2, 2), (2, 4)])
@pytest.mark.parametrize("k", [1, 2, 5])
def test_kselect_vs_numpy(rng, pr, pc, k):
    grid = Grid.make(pr, pc)
    d = random_dense(rng, 16, 24, 0.4)
    A = SpParMat.from_dense(grid, d)
    got = A.kselect(k).to_global()
    expect = kth_largest_per_col(d, k)
    finite = ~np.isinf(expect)
    np.testing.assert_allclose(got[finite], expect[finite], rtol=1e-6)
    assert np.all(got[~finite] == -np.inf)


def test_kselect_int32(rng):
    grid = Grid.make(2, 2)
    d = (random_dense(rng, 12, 12, 0.5) * 100 - 20).astype(np.int32)
    A = SpParMat.from_dense(grid, d)
    got = A.kselect(2).to_global()
    for j in range(12):
        nz = np.sort(d[:, j][d[:, j] != 0])[::-1]
        if len(nz) >= 2:
            assert got[j] == nz[1], j
        else:
            assert got[j] == np.iinfo(np.int32).min, j


def test_kselect_per_column_k(rng):
    grid = Grid.make(2, 2)
    d = random_dense(rng, 16, 8, 0.6)
    A = SpParMat.from_dense(grid, d)
    ks = np.array([1, 2, 3, 4, 1, 2, 3, 4], dtype=np.int32)
    kvec = DistVec.from_global(grid, ks, align="col", fill=1)
    got = A.kselect(kvec).to_global()
    for j in range(8):
        expect = kth_largest_per_col(d[:, j : j + 1], int(ks[j]))[0]
        if np.isinf(expect):
            assert got[j] == -np.inf
        else:
            np.testing.assert_allclose(got[j], expect, rtol=1e-6)


def test_prune_column_topk(rng):
    grid = Grid.make(2, 2)
    d = random_dense(rng, 16, 16, 0.5)
    A = SpParMat.from_dense(grid, d)
    k = 3
    th = A.kselect(k)
    kept = A.prune_column(th, keep=lambda v, t: v >= t).to_dense()
    for j in range(16):
        expect = d[:, j] * (d[:, j] >= kth_largest_per_col(d, k)[j])
        if np.isinf(kth_largest_per_col(d, k)[j]):  # fewer than k entries
            expect = d[:, j]
        np.testing.assert_allclose(kept[:, j], expect, rtol=1e-6)


def test_nnz_per_column(rng):
    grid = Grid.make(2, 2)
    d = random_dense(rng, 12, 20, 0.3)
    A = SpParMat.from_dense(grid, d)
    np.testing.assert_array_equal(
        A.nnz_per_column().to_global(), (d != 0).sum(axis=0)
    )


def test_ewise_add(rng):
    grid = Grid.make(2, 2)
    da = random_dense(rng, 12, 12, 0.3)
    db = random_dense(rng, 12, 12, 0.3)
    A = SpParMat.from_dense(grid, da)
    B = SpParMat.from_dense(grid, db)
    np.testing.assert_allclose(
        A.ewise_add(B, PLUS_TIMES).to_dense(), da + db, rtol=1e-6
    )


def test_add_loops(rng):
    grid = Grid.make(2, 2)
    d = random_dense(rng, 12, 12, 0.3)
    A = SpParMat.from_dense(grid, d)
    got = A.add_loops(jnp.float32(7.0)).to_dense()
    expect = d.copy()
    np.fill_diagonal(expect, 7.0)
    np.testing.assert_allclose(got, expect, rtol=1e-6)


def test_col_split_concat_roundtrip(rng):
    grid = Grid.make(2, 2)
    d = random_dense(rng, 8, 16, 0.4)
    A = SpParMat.from_dense(grid, d)
    parts = A.col_split(4)
    assert all(p.ncols == 4 for p in parts)
    back = SpParMat.col_concatenate(parts)
    np.testing.assert_allclose(back.to_dense(), d, rtol=1e-6)


@pytest.mark.parametrize("phases", [2, 4])
def test_mem_efficient_spgemm_matches_plain(rng, phases):
    grid = Grid.make(2, 2)
    da = random_dense(rng, 16, 16, 0.3)
    db = random_dense(rng, 16, 16, 0.3)
    A = SpParMat.from_dense(grid, da)
    B = SpParMat.from_dense(grid, db)
    plain = spgemm(PLUS_TIMES, A, B).to_dense()
    phased = mem_efficient_spgemm(PLUS_TIMES, A, B, phases).to_dense()
    np.testing.assert_allclose(phased, plain, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(plain, da @ db, rtol=1e-5, atol=1e-6)


def test_mem_efficient_spgemm_nondivisor_phase_adjust(rng):
    """A non-divisor phase count is adjusted to the nearest divisor >= it
    (still honoring the memory budget), never silently unphased."""
    grid = Grid.make(2, 2)
    da = random_dense(rng, 16, 16, 0.3)
    A = SpParMat.from_dense(grid, da)  # local_cols = 8
    with pytest.warns(UserWarning, match="nearest divisor"):
        # 3 does not divide 8 -> adjusted to 4
        phased = mem_efficient_spgemm(PLUS_TIMES, A, A, 3).to_dense()
    np.testing.assert_allclose(phased, da @ da, rtol=1e-5, atol=1e-6)


def test_mem_efficient_spgemm_irregular_distribution_errors(rng):
    grid = Grid.make(2, 2)
    da = random_dense(rng, 10, 9, 0.4)  # 9 % pc != 0 -> padded dist
    A = SpParMat.from_dense(grid, da)
    if A.ncols == A.local_cols * grid.pc:
        pytest.skip("distribution is regular on this grid")
    with pytest.raises(ValueError, match="phases=1"):
        mem_efficient_spgemm(PLUS_TIMES, A, A, 2)


def test_make_col_stochastic_and_chaos(rng):
    grid = Grid.make(2, 2)
    d = np.abs(random_dense(rng, 12, 12, 0.5)) + 0.0
    A = make_col_stochastic(SpParMat.from_dense(grid, d))
    sums = A.to_dense().sum(axis=0)
    nonempty = (d != 0).any(axis=0)
    np.testing.assert_allclose(sums[nonempty], 1.0, rtol=1e-5)
    # chaos of an idempotent (one 1 per column) matrix is 0
    ident = SpParMat.from_dense(grid, np.eye(12, dtype=np.float32))
    assert float(chaos(ident)) == pytest.approx(0.0, abs=1e-6)
    assert float(chaos(A)) > 0


def test_prune_recovery_select_caps_columns(rng):
    grid = Grid.make(2, 2)
    d = np.abs(random_dense(rng, 16, 16, 0.9))
    A = make_col_stochastic(SpParMat.from_dense(grid, d))
    out = mcl_prune_recovery_select(
        A, hard_threshold=0.0, select_num=3, recover_num=5, recover_pct=0.0
    )
    kept = (out.to_dense() != 0).sum(axis=0)
    assert np.all(kept <= (d != 0).sum(axis=0))
    # with recover_pct=0 no column relaxes: at most `select_num` survivors
    # unless ties duplicate the threshold value (none with random floats)
    assert np.all(kept <= 3)


def test_mcl_two_cliques(rng):
    """Two 6-cliques joined by a single weak edge must split into two
    clusters (the canonical MCL sanity input)."""
    grid = Grid.make(2, 2)
    n = 12
    d = np.zeros((n, n), np.float32)
    d[:6, :6] = 1.0
    d[6:, 6:] = 1.0
    np.fill_diagonal(d, 0.0)
    d[5, 6] = d[6, 5] = 0.1  # weak bridge
    labels, niter, ch = mcl(SpParMat.from_dense(grid, d), inflation=2.0)
    lab = labels.to_global()
    assert len(set(lab[:6])) == 1
    assert len(set(lab[6:])) == 1
    assert lab[0] != lab[6]
    assert ch < 1e-3


def test_mcl_phased_matches_unphased(rng):
    grid = Grid.make(2, 2)
    n = 16
    d = np.zeros((n, n), np.float32)
    d[:8, :8] = 1.0
    d[8:, 8:] = 1.0
    np.fill_diagonal(d, 0.0)
    d[7, 8] = d[8, 7] = 0.05
    A = SpParMat.from_dense(grid, d)
    lab1, _, _ = mcl(A, inflation=2.0, phases=1)
    lab2, _, _ = mcl(A, inflation=2.0, phases=2)
    # same clustering up to label names
    g1, g2 = lab1.to_global(), lab2.to_global()
    assert (g1[:, None] == g1[None, :]).tolist() == (
        (g2[:, None] == g2[None, :]).tolist()
    )


def test_mcl_scan_expansion_matches(rng):
    """MCL with the output-bounded scanned expansion produces the same
    clustering as the default path."""
    from combblas_tpu.models.mcl import mcl

    n = 16
    d = np.zeros((n, n), np.float32)
    d[:8, :8] = 1.0
    d[8:, 8:] = 1.0
    d[7, 8] = d[8, 7] = 0.1
    np.fill_diagonal(d, 0)
    grid = Grid.make(2, 2)
    A = SpParMat.from_dense(grid, d)
    l1, _, _ = mcl(A, inflation=2.0)
    l2, _, _ = mcl(A, inflation=2.0, scan=True)
    np.testing.assert_array_equal(l1.to_global(), l2.to_global())


def test_mcl_chaos_every_matches(rng):
    """K-iterations-per-sync block loop (zero D2H inside a block) produces
    the same clustering as the per-iteration-sync loop."""
    n = 16
    d = np.zeros((n, n), np.float32)
    d[:8, :8] = 1.0
    d[8:, 8:] = 1.0
    d[7, 8] = d[8, 7] = 0.1
    np.fill_diagonal(d, 0)
    grid = Grid.make(2, 2)
    A = SpParMat.from_dense(grid, d)
    l1, it1, ch1 = mcl(A, inflation=2.0)
    l2, it2, ch2 = mcl(A, inflation=2.0, chaos_every=3)
    np.testing.assert_array_equal(l1.to_global(), l2.to_global())
    assert ch2 < 1e-3
    # the block loop may overshoot convergence by up to K-1 iterations
    assert it1 <= it2 <= it1 + 2


@pytest.mark.slow  # ~26 s of reroll recompiles on the 1-core CPU mesh;
# the chaos-every path itself stays tier-1 via test_mcl_chaos_every_matches
def test_mcl_chaos_every_overflow_reroll(rng):
    """A deliberately tiny initial capacity must trigger the on-device
    overflow flag and the save-and-reroll path, still converging exactly."""
    import jax

    jax.clear_caches()  # many reroll compiles; see test_mcl_3d_chaos_every
    from combblas_tpu.models import mcl as mcl_mod

    n = 12
    d = np.zeros((n, n), np.float32)
    d[:6, :6] = 1.0
    d[6:, 6:] = 1.0
    np.fill_diagonal(d, 0)
    d[5, 6] = d[6, 5] = 0.1
    grid = Grid.make(2, 2)
    A = SpParMat.from_dense(grid, d)
    real_caps = mcl_mod._mcl_block_caps
    calls = {"n": 0}

    def tiny_caps(mat):
        calls["n"] += 1
        f, o = real_caps(mat)
        return (max(f // 16, 4), max(o // 16, 4)) if calls["n"] == 1 else (f, o)

    try:
        mcl_mod._mcl_block_caps = tiny_caps
        labels, _, ch = mcl_mod.mcl(A, inflation=2.0, chaos_every=2)
    finally:
        mcl_mod._mcl_block_caps = real_caps
    lab = labels.to_global()
    assert len(set(lab[:6])) == 1 and len(set(lab[6:])) == 1
    assert lab[0] != lab[6] and ch < 1e-3


def test_mcl_float64_reference_eps(tmp_path):
    """With x64 enabled (fresh interpreter: the flag is global), MCL runs
    in float64 and converges at the reference's eps=1e-4 (MCL.cpp:55) —
    the fidelity knob VERDICT r1 asked for. The library is dtype-generic;
    this guards that no op silently downcasts."""
    import subprocess
    import sys

    code = """
import os
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
import numpy as np
from combblas_tpu.models.mcl import mcl
from combblas_tpu.parallel.grid import Grid
from combblas_tpu.parallel.spmat import SpParMat

n = 16
d = np.zeros((n, n), np.float64)
d[:8, :8] = 1.0
d[8:, 8:] = 1.0
d[7, 8] = d[8, 7] = 0.1
np.fill_diagonal(d, 0)
A = SpParMat.from_dense(Grid.make(2, 2), d)
assert A.dtype == np.float64, A.dtype
labels, it, ch = mcl(A, inflation=2.0, eps=1e-4)
lab = labels.to_global()
assert len(np.unique(lab)) == 2, lab
assert ch < 1e-4
print("OK", it, ch)
"""
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(__file__))
    r = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        env=env, timeout=600,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "OK" in r.stdout


def test_mcl_dense_matches_sparse(rng):
    """The round-4 dense one-launch loop must produce the same clustering
    as the sparse path on a 1x1 grid (two cliques + bridge)."""
    grid = Grid.make(1, 1)
    n = 16
    d = np.zeros((n, n), np.float32)
    d[:8, :8] = 1.0
    d[8:, 8:] = 1.0
    np.fill_diagonal(d, 0.0)
    d[7, 8] = d[8, 7] = 0.05
    A = SpParMat.from_dense(grid, d)
    lab_s, _, _ = mcl(A, inflation=2.0)
    lab_d, it_d, ch_d = mcl(A, inflation=2.0, expansion="dense")
    g1, g2 = lab_s.to_global(), lab_d.to_global()
    assert (g1[:, None] == g1[None, :]).tolist() == (
        (g2[:, None] == g2[None, :]).tolist()
    )
    assert ch_d < 1e-3 and it_d >= 1


@pytest.mark.slow  # round 12 (tier-1 budget): randomized partition
# variant; dense-path correctness stays tier-1 via
# test_mcl_dense_matches_sparse / test_mcl_phased_matches_unphased
def test_mcl_dense_random_partition(rng):
    """Dense vs sparse on a random block-structured graph (three groups)."""
    grid = Grid.make(1, 1)
    n = 24
    d = np.zeros((n, n), np.float32)
    for lo, hi in [(0, 8), (8, 16), (16, 24)]:
        blk = (rng.random((hi - lo, hi - lo)) < 0.8).astype(np.float32)
        d[lo:hi, lo:hi] = np.maximum(blk, blk.T)
    np.fill_diagonal(d, 0.0)
    d[7, 8] = d[8, 7] = 0.05
    d[15, 16] = d[16, 15] = 0.05
    A = SpParMat.from_dense(grid, d)
    lab_s, _, _ = mcl(A, inflation=2.0)
    lab_d, _, _ = mcl(A, inflation=2.0, expansion="dense")
    g1, g2 = lab_s.to_global(), lab_d.to_global()
    assert (g1[:, None] == g1[None, :]).tolist() == (
        (g2[:, None] == g2[None, :]).tolist()
    )


def test_phase_adjusted_warning_structured():
    """PhaseAdjustedWarning carries (requested, actual, local_cols) for
    memory-budget callers (VERDICT r3 weak #8)."""
    import warnings

    from combblas_tpu.parallel.spgemm import PhaseAdjustedWarning

    grid = Grid.make(2, 2)
    n = 20  # local_cols = 10; 3 phases -> nearest divisor 5
    d = (np.random.default_rng(0).random((n, n)) < 0.3).astype(np.float32)
    A = SpParMat.from_dense(grid, d)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        mem_efficient_spgemm(PLUS_TIMES, A, A, phases=3)
    ws = [x for x in w if isinstance(x.message, PhaseAdjustedWarning)]
    assert len(ws) == 1
    assert ws[0].message.requested == 3
    assert ws[0].message.actual == 5
    assert ws[0].message.local_cols == 10
