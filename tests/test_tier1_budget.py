"""The tier-1 runtime budget guard (round 20 satellite): parsing the
pytest summary + ``--durations`` table, the slow-id subtraction, and
the CLI's exit-code contract over synthetic logs."""

import importlib.util
import os
import sys

import pytest

_SCRIPT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "scripts", "check_tier1_budget.py",
)


@pytest.fixture(scope="module")
def guard():
    spec = importlib.util.spec_from_file_location(
        "check_tier1_budget", _SCRIPT
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


_LOG = """\
............                                                       [100%]
============================= slowest 5 durations ==========================
40.00s call     tests/test_big.py::test_heavy
12.50s call     tests/test_mid.py::test_medium
5.00s setup    tests/test_big.py::test_heavy
0.40s call     tests/test_small.py::test_tiny

(2 durations < 0.005s hidden.  Use -vv to show these durations.)
830 passed, 22 deselected in 843.21s (0:14:03)
"""


def test_parse_wall_and_durations(guard):
    wall, rows = guard.parse_log(_LOG)
    assert wall == 843.21
    assert (40.0, "call", "tests/test_big.py::test_heavy") in rows
    assert (5.0, "setup", "tests/test_big.py::test_heavy") in rows
    assert len(rows) == 4
    # a failing run's summary parses too, last summary line wins
    wall, _ = guard.parse_log(
        "x\n2 failed, 10 passed in 91.02s (0:01:31)\n"
        "1 failed in 12.00s\n"
    )
    assert wall == 12.0
    assert guard.parse_log("no summary here")[0] is None


def test_projection_subtracts_slow_ids_all_phases(guard):
    wall, rows = guard.parse_log(_LOG)
    projected, shaved = guard.project(
        wall, rows, ["tests/test_big.py::test_heavy"]
    )
    assert shaved == 45.0  # call AND setup phases
    assert projected == pytest.approx(843.21 - 45.0)
    # no slow ids: projection is the measured wall
    assert guard.project(wall, rows)[0] == wall


def test_offenders_rank_in_budget_call_time_only(guard):
    _, rows = guard.parse_log(_LOG)
    worst = guard.offenders(rows,
                            ["tests/test_big.py::test_heavy"], top=5)
    assert worst[0] == ("tests/test_mid.py::test_medium", 12.5)
    assert all(tid != "tests/test_big.py::test_heavy"
               for tid, _ in worst)


def _run(guard, tmp_path, log_text, *argv):
    log = tmp_path / "t1.log"
    log.write_text(log_text)
    return guard.main([str(log), *argv])


def test_cli_within_budget_exits_zero(guard, tmp_path, capsys):
    assert _run(guard, tmp_path, _LOG, "--budget", "860") == 0
    assert "OK" in capsys.readouterr().out


def test_cli_over_budget_names_offenders(guard, tmp_path, capsys):
    assert _run(guard, tmp_path, _LOG, "--budget", "800") == 1
    cap = capsys.readouterr()
    assert "OVER BUDGET" in cap.out
    assert "tests/test_big.py::test_heavy" in cap.err
    assert "mark.slow" in cap.err


def test_cli_slow_ids_file_rescues_budget(guard, tmp_path, capsys):
    ids = tmp_path / "slow.txt"
    ids.write_text("# gated in this PR\n"
                   "tests/test_big.py::test_heavy\n\n")
    assert _run(guard, tmp_path, _LOG, "--budget", "800",
                "--slow-ids", str(ids)) == 0
    assert "45.0s slow-gated" in capsys.readouterr().out


def test_cli_unparseable_log_exits_two(guard, tmp_path, capsys):
    assert _run(guard, tmp_path, "garbage\nnothing useful\n") == 2
    assert "no pytest summary" in capsys.readouterr().err


def test_cli_entrypoint_runs(tmp_path):
    """The script works as a subprocess CLI (the CI invocation)."""
    import subprocess

    log = tmp_path / "t1.log"
    log.write_text(_LOG)
    p = subprocess.run(
        [sys.executable, _SCRIPT, str(log), "--budget", "860"],
        capture_output=True, text=True,
    )
    assert p.returncode == 0, p.stderr
    assert "OK" in p.stdout
