"""Bench summary-line contract (ISSUE 3 satellites 1-2 + CI guard).

The driver's end-of-round capture takes the LAST stdout line; the r05
artifact ended up ``parsed: null`` because tail truncation of the giant
per-run record ate the headline.  The contract under test: ``bench.py``'s
final line is a COMPACT parseable JSON summary carrying ``value``,
``median``, ``warning``, ``rc``, and the same object is mirrored to
``BENCH_SUMMARY.json``.
"""

import importlib.util
import io
import json
import os
import subprocess
import sys
from contextlib import redirect_stdout

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: The summary line's required keys — the satellite-1 contract that the
#: CI guard (this file) pins down.
REQUIRED_KEYS = {"summary", "metric", "value", "median", "warning", "rc"}


@pytest.fixture(scope="module")
def benchmod():
    spec = importlib.util.spec_from_file_location(
        "benchmod_under_test", os.path.join(REPO, "bench.py")
    )
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    return m


def test_emit_summary_is_parseable_with_required_keys(
    benchmod, tmp_path, monkeypatch
):
    monkeypatch.setenv(
        "BENCH_SUMMARY_PATH", str(tmp_path / "BENCH_SUMMARY.json")
    )
    official = {
        "metric": "graph500_bfs_rmat_scale20_1chip_MTEPS",
        "value": 14.5,
        "batch_median_mteps": 246.4,
        "warning": None,
        "runs": [{"huge": "x" * 10000}],  # the giant record is NOT copied
    }
    buf = io.StringIO()
    with redirect_stdout(buf):
        benchmod.emit_summary(official)
    lines = buf.getvalue().strip().splitlines()
    s = json.loads(lines[-1])  # the FINAL line parses alone
    assert REQUIRED_KEYS <= set(s)
    assert s["value"] == 14.5
    assert s["median"] == 246.4
    assert s["rc"] == 0
    assert len(lines[-1]) < 400, "summary must be truncation-proof small"
    sidecar = json.loads((tmp_path / "BENCH_SUMMARY.json").read_text())
    assert sidecar == s


def test_emit_summary_survives_unwritable_sidecar(benchmod, monkeypatch):
    monkeypatch.setenv(
        "BENCH_SUMMARY_PATH", "/nonexistent-dir/BENCH_SUMMARY.json"
    )
    buf = io.StringIO()
    with redirect_stdout(buf):
        benchmod.emit_summary({"value": 1.0}, rc=1)
    s = json.loads(buf.getvalue().strip().splitlines()[-1])
    assert s["rc"] == 1 and "summary_write_error" in s


def test_variance_block_names_the_suspect(benchmod):
    runs = [{"mteps": 40.0, "warmup_s": 5.0}] * 3
    v = benchmod.diagnose_variance(runs, {"mteps": 280.0})
    assert v["suspect"] == "warmup_contamination"
    v = benchmod.diagnose_variance(
        [{"mteps": 40.0, "warmup_s": 120.0}], {"mteps": 50.0}
    )
    assert v["suspect"] == "cache_cold"
    v = benchmod.diagnose_variance(runs, {"mteps": 50.0})
    assert v["suspect"] == "degraded_regime"
    assert {"median_mteps", "operating_point_mteps", "rerun_mteps",
            "detail"} <= set(v)


def test_emit_reports_median_and_spread(benchmod, capsys):
    runs = [
        {"mteps": 90.0}, {"mteps": 100.0}, {"mteps": 130.0},
    ]
    out = benchmod.emit(runs, [], 1.0, {}, 0.0)
    capsys.readouterr()
    assert out["batch_median_mteps"] == 100.0
    sp = out["repeats_spread"]
    assert sp["min"] == 90.0 and sp["max"] == 130.0
    assert sp["rel_spread"] == pytest.approx(0.4)
    # a variance block rides the official record when provided
    out = benchmod.emit(
        runs, [], 1.0, {}, 0.0, {"suspect": "degraded_regime"}
    )
    capsys.readouterr()
    assert out["variance"]["suspect"] == "degraded_regime"


def test_spgemm_bench_summary_fields():
    """The SpGEMM bench line also satisfies the driver's minimal
    contract (parseable, has "value") — pinned here since the perf
    acceptance reads it."""
    # static check on the emitted dict keys (no run): the bench builds
    # its JSON inline, so just assert the file mentions the fields the
    # driver parses
    src = open(os.path.join(REPO, "benchmarks", "spgemm_bench.py")).read()
    for field in ('"value"', '"out_nnz"', '"overflow"', '"tier"'):
        assert field in src, field


@pytest.mark.slow
def test_bench_end_to_end_summary_line(tmp_path):
    """Full bench.py subprocess at a toy scale: stdout ends with the
    parseable summary line and BENCH_SUMMARY.json is written."""
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        BENCH_SCALE="8", BENCH_NROOTS="8", BENCH_REPEATS="1",
        BENCH_SEQ_ROOTS="0", BENCH_VALIDATE="0", BENCH_DRAIN_S="0",
        BENCH_BUDGET_S="600",
        BENCH_SUMMARY_PATH=str(tmp_path / "BENCH_SUMMARY.json"),
    )
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=900,
    )
    lines = [l for l in r.stdout.strip().splitlines() if l.strip()]
    assert lines, r.stderr[-2000:]
    s = json.loads(lines[-1])
    assert REQUIRED_KEYS <= set(s), s
    assert s["rc"] == 0, (s, r.stderr[-2000:])
    assert s["value"] > 0
    # the full record is on an EARLIER line
    full = json.loads(lines[-2])
    assert "runs" in full and full["value"] == s["value"]
    sidecar = json.loads((tmp_path / "BENCH_SUMMARY.json").read_text())
    assert sidecar == s


def test_pool_summary_honors_contract(tmp_path, monkeypatch):
    """Round 14: the standalone BENCH_SERVE_POOL scenario emits the
    SAME final-line contract (plus the per-tenant breakdown) without
    going through bench.py's wrapper."""
    spec = importlib.util.spec_from_file_location(
        "serve_bench_under_test",
        os.path.join(REPO, "benchmarks", "serve_bench.py"),
    )
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    monkeypatch.setenv(
        "BENCH_SUMMARY_PATH", str(tmp_path / "BENCH_SUMMARY.json")
    )
    out = {
        "metric": "serve_pool_throughput",
        "value": 1234.5,
        "p50_ms": 12.0,
        "ok": True,
        "per_tenant": {"t0": {"queries": 10, "rejected": 0}},
        "obs_jsonl": "x" * 10000,  # giant fields are NOT copied
    }
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = m._emit_pool_summary(out)
    assert rc == 0
    line = buf.getvalue().strip().splitlines()[-1]
    s = json.loads(line)
    assert REQUIRED_KEYS <= set(s)
    assert s["value"] == 1234.5
    assert s["median"] == 12.0
    assert s["per_tenant"]["t0"]["queries"] == 10
    mirror = json.load(open(tmp_path / "BENCH_SUMMARY.json"))
    assert mirror == s
    # a failed gate maps to rc=1 (the driver's capture semantics)
    out["ok"] = False
    with redirect_stdout(io.StringIO()):
        assert m._emit_pool_summary(out) == 1
