"""Static catalog-drift sweep (ISSUE 13 satellite): every literal
``obs.count / obs.gauge / obs.observe`` series name in the package must
be cataloged in ``obs/metrics.py``'s docstring.

The catalog stayed honest by convention since PR 1; this test makes it
structural — a new series landing without a catalog row fails tier-1.
Dynamically-built names (``obs.observe("k1." + stage)``) surface as a
prefix ending in ``.`` and are matched as substrings of their
cataloged ``prefix.*`` row.
"""

import os
import re

import combblas_tpu
from combblas_tpu.obs import metrics as obs_metrics

PKG_ROOT = os.path.dirname(os.path.abspath(combblas_tpu.__file__))

#: Literal first-argument series names at obs writer call sites; the
#: name may sit on the call line or a continuation (re.DOTALL-free:
#: \s* crosses newlines on its own).
_CALL = re.compile(
    r"""obs\.(?:count|gauge|observe)\(\s*["']([A-Za-z0-9_.]+)["']"""
)


def _package_series_names() -> dict[str, list[str]]:
    names: dict[str, list[str]] = {}
    for dirpath, _dirs, files in os.walk(PKG_ROOT):
        if "__pycache__" in dirpath:
            continue
        for fn in files:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            with open(path, encoding="utf-8") as f:
                src = f.read()
            rel = os.path.relpath(path, PKG_ROOT)
            for m in _CALL.finditer(src):
                names.setdefault(m.group(1), []).append(rel)
    return names


def test_every_emitted_series_is_cataloged():
    catalog = open(obs_metrics.__file__, encoding="utf-8").read()
    names = _package_series_names()
    assert len(names) > 100  # the sweep actually swept the package
    missing = sorted(
        f"{name}  (emitted by {sorted(set(files))})"
        for name, files in names.items()
        if name not in catalog
    )
    assert not missing, (
        "series emitted but not cataloged in obs/metrics.py — add a "
        "catalog row (name + kind + meaning):\n" + "\n".join(missing)
    )


def test_known_series_are_swept():
    """The sweep regex sees through the repo's call styles: same-line
    literals, continuation-line literals, and **label splats."""
    names = _package_series_names()
    for expected in (
        "serve.requests",            # **self._lab(...) splat style
        "serve.update.failed",       # continuation-line literal
        "dynamic.freshness.versions_behind",  # round 15
        "serve.flightrec.dumps",     # round 15
        "serve.slo.budget_burn",     # round 15
        "serve.pool.admits",
        # round 18: emitted by the CHILD process (_procworker.py) —
        # the sweep must cover subprocess-side series too
        "serve.procfleet.hb_snapshots",
        "serve.ipc.bytes_out",
    ):
        assert expected in names, expected
