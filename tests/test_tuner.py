"""Round-10 autotuner: plan-store persistence + robustness, probe
determinism, store-routed vs heuristic-routed agreement, serve lane
replay, and the shared cache health surface (docs/autotuning.md).

The store contract under test: remembered plans make routing
reproducible across processes, a damaged plans file NEVER takes the
library down (fall back to the next precedence rung, counter bumped),
and store-routed products are bit-exact with heuristic-routed ones —
the store only chooses among exact kernels.
"""

import json
import os

import jax
import numpy as np
import pytest

from combblas_tpu import MAX_MIN, MIN_PLUS, PLUS_TIMES, obs
from combblas_tpu.parallel.grid import Grid
from combblas_tpu.parallel.spgemm import (
    bucket_plan_caps,
    spgemm,
    spgemm_auto,
    spgemm_windowed,
)
from combblas_tpu.parallel.spmat import SpParMat
from combblas_tpu.tuner import (
    PlanKey,
    PlanRecord,
    PlanStore,
    SCHEMA,
    config,
    density_band,
    plan_key_from_counts,
    shape_bucket,
    spgemm_plan_key,
)
from combblas_tpu.tuner import store as tstore
from combblas_tpu.tuner.probe import downsample_coo, probe_spgemm

SRS = {"plus_times": PLUS_TIMES, "min_plus": MIN_PLUS,
       "max_min": MAX_MIN}


def coo(rng, m, k, nnz, dup_frac=0.2):
    r = rng.integers(0, m, nnz).astype(np.int64)
    c = rng.integers(0, k, nnz).astype(np.int64)
    v = (rng.random(nnz) + 0.5).astype(np.float32)
    ndup = int(nnz * dup_frac)
    if ndup:
        r = np.concatenate([r, r[:ndup]])
        c = np.concatenate([c, c[:ndup]])
        v = np.concatenate(
            [v, (rng.random(ndup) + 0.5).astype(np.float32)]
        )
    return r, c, v


def dense_of(M: SpParMat) -> np.ndarray:
    r, c, v, _ = jax.device_get((M.rows, M.cols, M.vals, M.nnz))
    out = np.zeros((M.nrows, M.ncols), np.float64)
    lr, lc = M.local_rows, M.local_cols
    for i in range(M.grid.pr):
        for j in range(M.grid.pc):
            m_ = r[i, j] < lr
            np.add.at(
                out,
                (r[i, j][m_] + i * lr, c[i, j][m_] + j * lc),
                v[i, j][m_],
            )
    return out


def _use_store(monkeypatch, path) -> PlanStore:
    """Point the process store at ``path`` and return the instance."""
    monkeypatch.setenv(config.ENV_PLAN_STORE, str(path))
    tstore._reset_for_tests()
    st = tstore.get_store()
    assert st is not None and st.path == os.path.abspath(str(path))
    return st


@pytest.fixture(autouse=True)
def _fresh_singleton():
    """Each test resolves its own store; drop the cached instance on
    both sides so cross-test state cannot leak through the singleton."""
    tstore._reset_for_tests()
    yield
    tstore._reset_for_tests()


def _key(op="spgemm", sr="plus_times", backend="scatter",
         grid="1x1") -> PlanKey:
    return plan_key_from_counts(
        sr, 1 << 14, 1 << 14, 1 << 14, 131072, 131072, backend, grid,
        op=op, platform="cpu",
    )


# --- store persistence + robustness ----------------------------------------


def test_store_roundtrip(tmp_path):
    st = PlanStore(str(tmp_path))
    key = _key()
    rec = PlanRecord(
        tier="windowed", block_rows=256, block_cols=512, ring=True,
        pipeline=False, dispatch="blocked", cost_s=1.25,
        source="probe", probe_dim=2048,
    )
    st.put(key, rec)
    # a SECOND process (fresh instance, same dir) sees the plan
    st2 = PlanStore(str(tmp_path))
    got = st2.lookup(key)
    assert got == rec
    assert st2.entries() == 1
    assert st2.stats()["hits"] == 1 and st2.stats()["invalid_lines"] == 0


def test_store_append_only_later_line_wins(tmp_path):
    st = PlanStore(str(tmp_path))
    key = _key()
    st.put(key, PlanRecord(tier="scan", cost_s=9.0))
    st.put(key, PlanRecord(tier="windowed", cost_s=1.0))
    st2 = PlanStore(str(tmp_path))
    assert st2.lookup(key).tier == "windowed"
    assert st2.entries() == 1  # one key, latest record
    with open(st2.file) as f:
        assert len(f.readlines()) == 2  # append-only log


def test_store_schema_mismatch_ignored(tmp_path):
    st = PlanStore(str(tmp_path))
    key = _key()
    st.put(key, PlanRecord(tier="windowed", cost_s=1.0))
    with open(st.file, "a") as f:
        f.write(json.dumps({
            "v": "combblas_tpu.plans/v999",
            "key": key.to_json(),
            "plan": {"tier": "scan"},
        }) + "\n")
    st2 = PlanStore(str(tmp_path))
    # the future-schema line is skipped, never guessed at
    assert st2.lookup(key).tier == "windowed"
    assert st2.stats()["invalid_lines"] == 1


def test_store_corrupted_and_truncated_lines_ignored(tmp_path):
    st = PlanStore(str(tmp_path))
    key = _key()
    st.put(key, PlanRecord(tier="scan", cost_s=2.0))
    good_line = json.dumps({
        "v": SCHEMA, "key": _key(sr="min_plus").to_json(),
        "plan": PlanRecord(tier="windowed", cost_s=1.0).to_json(),
    })
    with open(st.file, "a") as f:
        f.write("not json at all\n")
        f.write(good_line + "\n")
        f.write(json.dumps({"v": SCHEMA, "key": {"op": "spgemm"}}) + "\n")
        f.write(json.dumps({
            "v": SCHEMA, "key": key.to_json(),
            "plan": {"tier": "warp_drive"},  # unknown tier
        }) + "\n")
        f.write(good_line[: len(good_line) // 2])  # torn final write
    st2 = PlanStore(str(tmp_path))
    assert st2.entries() == 2  # the two valid records survive
    assert st2.lookup(key).tier == "scan"
    assert st2.lookup(_key(sr="min_plus")).tier == "windowed"
    assert st2.stats()["invalid_lines"] == 4


def test_store_damaged_file_still_routes(tmp_path, monkeypatch, rng):
    """A plans file of pure garbage must leave spgemm_auto on the
    heuristic path — the robustness contract end to end."""
    (tmp_path / "plans.jsonl").write_text("garbage\n{\n\x00\n")
    st = _use_store(monkeypatch, tmp_path)
    assert st.entries() == 0 and st.stats()["invalid_lines"] >= 2
    grid = Grid.make(1, 1)
    r, c, v = coo(rng, 64, 64, 300)
    A = SpParMat.from_global_coo(grid, r, c, v, 64, 64)
    C = spgemm_auto(PLUS_TIMES, A, A)  # heuristic fallback, no raise
    np.testing.assert_allclose(
        dense_of(C), dense_of(spgemm(PLUS_TIMES, A, A)),
        rtol=1e-5, atol=1e-6,
    )
    # an all-garbage store loads EMPTY, so the router skips the keyed
    # lookup entirely (no D2H spent on a store that can't hit)
    assert st.stats()["misses"] == 0 and st.stats()["hits"] == 0


def test_store_disabled_by_env(monkeypatch):
    monkeypatch.setenv(config.ENV_PLAN_STORE, "0")
    tstore._reset_for_tests()
    assert config.store_dir() is None
    assert tstore.get_store() is None


def test_store_default_is_compile_cache_sibling(monkeypatch):
    monkeypatch.delenv(config.ENV_PLAN_STORE, raising=False)
    from combblas_tpu.utils import compile_cache

    d = config.store_dir()
    assert os.path.basename(d) == ".plan_store"
    assert os.path.dirname(d) == os.path.dirname(
        os.path.abspath(compile_cache.CACHE_DIR)
    )


def test_key_buckets_and_bands():
    assert shape_bucket(1 << 14) == 14
    assert shape_bucket((1 << 14) + 1) == 15  # ceil, not floor
    assert density_band(16 * 1024, 1024) == 4  # avg degree 16
    assert density_band(0, 1024) == -8  # clamped floor
    # the host-count key and the matrix key agree (the bench contract)
    grid = Grid.make(1, 1)
    n, nnz = 256, 2048
    rng = np.random.default_rng(7)
    r = rng.integers(0, n, nnz).astype(np.int64)
    c = rng.integers(0, n, nnz).astype(np.int64)
    key = np.unique(r * n + c)
    A = SpParMat.from_global_coo(
        grid, key // n, key % n, np.ones(len(key), np.float32), n, n
    )
    k_mat = spgemm_plan_key(PLUS_TIMES, A, A, "scatter")
    k_cnt = plan_key_from_counts(
        "plus_times", n, n, n, len(key), len(key), "scatter", "1x1"
    )
    assert k_mat == k_cnt


# --- probe -----------------------------------------------------------------


def test_downsample_deterministic_and_band_preserving():
    rng = np.random.default_rng(3)
    n, nnz, p = 5000, 40000, 1024
    r = rng.integers(0, n, nnz)
    c = rng.integers(0, n, nnz)
    a1 = downsample_coo(r, c, (n, n), (p, p), seed=11)
    a2 = downsample_coo(r, c, (n, n), (p, p), seed=11)
    for x, y in zip(a1, a2):
        np.testing.assert_array_equal(x, y)
    assert len(a1[0]) > 0
    assert a1[0].max() < p and a1[1].max() < p
    # restrict-one/fold-one keeps the AVERAGE DEGREE of the original
    # (restricting both axes would shrink it by p/n and measure the
    # rungs in the wrong density band)
    deg_orig = nnz / n
    deg_proxy = len(a1[0]) / p
    assert abs(deg_proxy - deg_orig) / deg_orig < 0.15, (
        deg_proxy, deg_orig
    )
    assert density_band(len(a1[0]), p) == density_band(nnz, n)
    # the B-side split preserves degree the same way
    b = downsample_coo(r, c, (n, n), (p, p), seed=11,
                       modes=("fold", "restrict"))
    assert abs(len(b[0]) / p - deg_orig) / deg_orig < 0.15


def test_probe_deterministic_winner_and_persistence(tmp_path, rng):
    grid = Grid.make(1, 1)
    r, c, v = coo(rng, 128, 128, 800)
    A = SpParMat.from_global_coo(grid, r, c, v, 128, 128)
    key = spgemm_plan_key(PLUS_TIMES, A, A, "scatter")

    def run_once(subdir):
        st = PlanStore(str(tmp_path / subdir))
        seq = iter([0.3, 0.01, 0.2, 0.5])  # injected deterministic costs

        rec = probe_spgemm(
            PLUS_TIMES, A, A, backend="scatter", store=st, key=key,
            measure=lambda fn: next(seq),
            geometry=False,  # tier determinism under test, not the sweep
        )
        return st, rec

    st1, rec1 = run_once("a")
    st2, rec2 = run_once("b")
    # same inputs + same injected costs => identical plan, both runs
    assert rec1 == rec2
    assert rec1.source == "probe" and rec1.cost_s == 0.01
    assert rec1.probe_dim == 128
    # persisted: a fresh load routes from the measured record
    assert PlanStore(st1.path).lookup(key) == rec1
    assert st1.stats()["probe_runs"] >= 2


def test_probe_budget_caps_candidates(tmp_path, rng):
    grid = Grid.make(1, 1)
    r, c, v = coo(rng, 64, 64, 300)
    A = SpParMat.from_global_coo(grid, r, c, v, 64, 64)
    st = PlanStore(str(tmp_path))
    rec = probe_spgemm(
        PLUS_TIMES, A, A, backend="scatter", store=st,
        key=spgemm_plan_key(PLUS_TIMES, A, A, "scatter"),
        budget_s=0.0,  # exhausted after the FIRST (heuristic) rung
        measure=lambda fn: 5.0,
    )
    assert rec is not None  # the first rung is always measured
    assert st.stats()["probe_runs"] == 1


def test_probe_real_measure_smoke(tmp_path, rng):
    """One real (wall-clock) probe on a tiny product: returns a sane
    record and persists it."""
    grid = Grid.make(1, 1)
    r, c, v = coo(rng, 96, 96, 500)
    A = SpParMat.from_global_coo(grid, r, c, v, 96, 96)
    st = PlanStore(str(tmp_path))
    key = spgemm_plan_key(PLUS_TIMES, A, A, "scatter")
    rec = probe_spgemm(
        PLUS_TIMES, A, A, backend="scatter", store=st, key=key,
        geometry=False,  # wall-clock tier smoke; the sweep has its own tests
    )
    assert rec is not None and rec.tier in ("mxu", "windowed", "scan")
    assert rec.cost_s > 0
    assert st.lookup(key) == rec
    assert st.stats()["probe_seconds"] > 0


def test_store_invalid_dispatch_line_ignored(tmp_path):
    st = PlanStore(str(tmp_path))
    key = _key()
    with open(os.path.join(str(tmp_path), "plans.jsonl"), "a") as f:
        f.write(json.dumps({
            "v": SCHEMA, "key": key.to_json(),
            "plan": {"tier": "windowed", "dispatch": "block"},
        }) + "\n")
    st2 = PlanStore(str(tmp_path))
    # a schema-valid but unknown-dispatch line is invalid, not asserted
    # on later at routing time
    assert st2.lookup(key) is None
    assert st2.stats()["invalid_lines"] == 1


def test_store_wrong_op_tier_record_falls_back(
    tmp_path, monkeypatch, rng
):
    """A serve-lane tier under a spgemm key (hand-mangled store) is
    rejected at routing — heuristic fallback, no assert."""
    grid = Grid.make(1, 1)
    r, c, v = coo(rng, 64, 64, 300)
    A = SpParMat.from_global_coo(grid, r, c, v, 64, 64)
    st = _use_store(monkeypatch, tmp_path)
    key = spgemm_plan_key(PLUS_TIMES, A, A, "scatter")
    st._plans[key] = PlanRecord(tier="serve")  # bypass put()'s surface
    C = spgemm_auto(PLUS_TIMES, A, A)
    np.testing.assert_allclose(
        dense_of(C), dense_of(spgemm(PLUS_TIMES, A, A)),
        rtol=1e-5, atol=1e-6,
    )


def test_proxy_dim_never_exceeds_cap():
    from combblas_tpu.tuner.probe import _proxy_dim

    assert _proxy_dim(1 << 14, 2048) == 2048
    assert _proxy_dim(1 << 14, 3000) == 2048  # non-pow2 cap: round DOWN
    assert _proxy_dim(128, 2048) == 128
    assert _proxy_dim(100, 2048) == 128  # small dims still pow2-ceil


def test_ring_wins_over_explicit_blocked(rng):
    """ring is a fused-only schedule: an explicit dispatch='blocked'
    yields to it (obs-counted), instead of silently dropping the
    carousel request."""
    grid = Grid.make(2, 2)
    m = 64
    r, c, v = coo(rng, m, m, 400)
    A = SpParMat.from_global_coo(grid, r, c, v, m, m)
    obs.enable(install_hooks=False)
    try:
        obs.reset()
        spgemm_windowed(
            PLUS_TIMES, A, A, block_rows=8, backend="scatter",
            ring=True, dispatch="blocked",
        )
        assert obs.registry.get_counter(
            "spgemm.windowed.dispatch_conflict"
        ) == 1
        assert obs.registry.get_counter(
            "spgemm.windowed.dispatch", mode="fused"
        ) == 1
    finally:
        obs.disable()
        obs.reset()


# --- store-routed vs heuristic-routed agreement ----------------------------


@pytest.mark.parametrize("srname", [
    "plus_times",
    # store ROUTING is semiring-independent code; the tropical
    # semirings re-pay the Pallas-kernel compiles purely to re-prove
    # it (round 17 budget) — their bit-exactness lives in the spgemm
    # suites, plus_times keeps both grid sizes as the representative
    pytest.param("min_plus", marks=pytest.mark.slow),
    pytest.param("max_min", marks=pytest.mark.slow),
])
@pytest.mark.parametrize("p", [1, 2])
def test_store_routed_bit_exact_vs_heuristic(
    tmp_path, monkeypatch, rng, srname, p
):
    """spgemm_auto routed by a remembered plan must agree with the
    heuristic-routed product on 1x1 AND 2x2 grids across semirings
    with duplicate-entry COO (the store only picks among exact
    kernels)."""
    sr = SRS[srname]
    grid = Grid.make(p, p)
    m = 64
    r, c, v = coo(rng, m, m, 500, dup_frac=0.2)
    A = SpParMat.from_global_coo(grid, r, c, v, m, m)
    # heuristic route (store disabled)
    monkeypatch.setenv(config.ENV_PLAN_STORE, "0")
    tstore._reset_for_tests()
    C_heur = spgemm_auto(sr, A, A)
    # store route: a remembered windowed plan for this key
    st = _use_store(monkeypatch, tmp_path)
    key = spgemm_plan_key(sr, A, A, "scatter")
    st.put(key, PlanRecord(
        tier="windowed", block_rows=16, cost_s=0.5, source="probe",
    ))
    C_store = spgemm_auto(sr, A, A)
    assert st.stats()["hits"] == 1
    np.testing.assert_allclose(
        dense_of(C_store), dense_of(C_heur), rtol=1e-5, atol=1e-6
    )


def test_precedence_arg_over_store_over_env(tmp_path, monkeypatch, rng):
    """The documented chain (tuner/config.py): arg > store > env >
    heuristic."""
    grid = Grid.make(1, 1)
    r, c, v = coo(rng, 64, 64, 300, dup_frac=0.0)
    A = SpParMat.from_global_coo(grid, r, c, v, 64, 64)
    st = _use_store(monkeypatch, tmp_path)
    key = spgemm_plan_key(PLUS_TIMES, A, A, "scatter")
    st.put(key, PlanRecord(tier="scan", cost_s=0.5))
    monkeypatch.setenv(config.ENV_TIER, "windowed")
    obs.enable(install_hooks=False)
    try:
        obs.reset()
        # store beats env
        spgemm_auto(PLUS_TIMES, A, A)
        assert obs.registry.get_counter(
            "spgemm.auto.plan_source", source="store", tier="scan",
            op="spgemm",
        ) == 1
        # arg beats store
        obs.reset()
        spgemm_auto(PLUS_TIMES, A, A, tier="esc")
        assert obs.registry.get_counter(
            "spgemm.auto.plan_source", source="arg", tier="esc",
            op="spgemm",
        ) == 1
        # env beats heuristic (store miss: different semiring key)
        obs.reset()
        spgemm_auto(MIN_PLUS, A, A)
        assert obs.registry.get_counter(
            "spgemm.auto.plan_source", source="env", tier="windowed",
            op="spgemm",
        ) == 1
        # heuristic when nothing else decides
        monkeypatch.delenv(config.ENV_TIER)
        obs.reset()
        spgemm_auto(MAX_MIN, A, A)
        snap = {
            (m_["name"], m_["labels"].get("source"))
            for m_ in obs.registry.snapshot()
            if m_["name"] == "spgemm.auto.plan_source"
        }
        assert snap == {("spgemm.auto.plan_source", "heuristic")}
    finally:
        obs.disable()
        obs.reset()


def test_explicit_schedule_args_beat_store_record(
    tmp_path, monkeypatch, rng
):
    """arg > store holds for the schedule flags too: an explicit
    ring=False must override a remembered ring=True plan (tri-state
    defaults in spgemm_auto)."""
    grid = Grid.make(2, 2)
    m = 64
    r, c, v = coo(rng, m, m, 400, dup_frac=0.0)
    A = SpParMat.from_global_coo(grid, r, c, v, m, m)
    st = _use_store(monkeypatch, tmp_path)
    st.put(
        spgemm_plan_key(PLUS_TIMES, A, A, "scatter"),
        PlanRecord(tier="windowed", block_rows=16, ring=True),
    )
    obs.enable(install_hooks=False)
    try:
        obs.reset()
        spgemm_auto(PLUS_TIMES, A, A, ring=False)  # explicit override
        assert obs.registry.get_counter(
            "spgemm.windowed.dispatch", mode="blocked"
        ) == 1  # ring=False => the blocked building-block default
        obs.reset()
        spgemm_auto(PLUS_TIMES, A, A)  # default: record's ring wins
        assert obs.registry.get_counter(
            "spgemm.windowed.dispatch", mode="fused"
        ) == 1  # ring carousel is fused-only
    finally:
        obs.disable()
        obs.reset()


def test_store_mxu_plan_respects_dedup_guard(tmp_path, monkeypatch, rng):
    """A remembered mxu plan must NOT bypass the unique-entries
    precondition: duplicate-entry inputs fall back (and stay exact)."""
    grid = Grid.make(1, 1)
    m = 64
    r, c, v = coo(rng, m, m, 400, dup_frac=0.25)
    A = SpParMat.from_global_coo(grid, r, c, v, m, m)
    st = _use_store(monkeypatch, tmp_path)
    st.put(
        spgemm_plan_key(PLUS_TIMES, A, A, "scatter"),
        PlanRecord(tier="mxu", cost_s=0.1),
    )
    C = spgemm_auto(PLUS_TIMES, A, A)
    np.testing.assert_allclose(
        dense_of(C), dense_of(spgemm(PLUS_TIMES, A, A)),
        rtol=1e-5, atol=1e-6,
    )


# --- building-block dispatch / bucketed caps -------------------------------


def test_bucket_plan_caps_shapes():
    fc, oc = bucket_plan_caps((3, 17, 1), (1000, 5, 64))
    assert fc == (4, 32, 1) and oc == (1024, 8, 64)
    fc2, oc2 = bucket_plan_caps(
        ((3, 5), (9, 1)), ((33, 2), (7, 128))
    )
    assert fc2 == ((4, 8), (16, 1)) and oc2 == ((64, 2), (8, 128))


@pytest.mark.parametrize("dispatch", [
    "auto", "blocked",
    # "fused" is slow-lane (round 12, tier-1 budget): the fused
    # one-graph kernel keeps tier-1 coverage via the ring tests and
    # test_blocked_dispatch_matches_fused
    pytest.param("fused", marks=pytest.mark.slow),
])
def test_windowed_dispatch_agreement(rng, dispatch):
    """The blocked building-block dispatch (the round-10 multi-device
    default) emits the same product as the fused graph."""
    grid = Grid.make(2, 2)
    m = 96
    r, c, v = coo(rng, m, m, 800, dup_frac=0.1)
    A = SpParMat.from_global_coo(grid, r, c, v, m, m)
    C = spgemm_windowed(
        PLUS_TIMES, A, A, block_rows=8, backend="scatter",
        dispatch=dispatch,
    )
    C_ref = spgemm(PLUS_TIMES, A, A)
    np.testing.assert_allclose(
        dense_of(C), dense_of(C_ref), rtol=1e-5, atol=1e-6
    )


def test_windowed_auto_dispatch_is_blocked_multidev(rng):
    grid = Grid.make(2, 2)
    m = 96
    r, c, v = coo(rng, m, m, 800)
    A = SpParMat.from_global_coo(grid, r, c, v, m, m)
    obs.enable(install_hooks=False)
    try:
        obs.reset()
        spgemm_windowed(PLUS_TIMES, A, A, block_rows=8,
                        backend="scatter")
        assert obs.registry.get_counter(
            "spgemm.windowed.dispatch", mode="blocked"
        ) == 1
        # ring keeps the fused carousel (the pipelined schedule)
        obs.reset()
        spgemm_windowed(PLUS_TIMES, A, A, block_rows=8,
                        backend="scatter", ring=True)
        assert obs.registry.get_counter(
            "spgemm.windowed.dispatch", mode="fused"
        ) == 1
    finally:
        obs.disable()
        obs.reset()


# --- serve lane replay -----------------------------------------------------


def test_serve_lanes_recorded_and_replayed(tmp_path, monkeypatch):
    from combblas_tpu.serve.engine import GraphEngine

    _use_store(monkeypatch, tmp_path)
    rng = np.random.default_rng(5)
    N = 64
    rows = rng.integers(0, N, 300).astype(np.int64)
    cols = rng.integers(0, N, 300).astype(np.int64)
    rows_s = np.concatenate([rows, cols])
    cols_s = np.concatenate([cols, rows])

    def build():
        return GraphEngine.from_coo(
            Grid.make(1, 1), rows_s, cols_s, N, kinds=("bfs",)
        )

    eng1 = build()
    eng1.plan("bfs", 32)  # a non-default lane the traffic mix used
    # fresh "process": new engine + a reloaded store instance
    tstore._reset_for_tests()
    eng2 = build()
    warmed = eng2.warmup()
    assert ("bfs", 32) in warmed  # the remembered lane was pre-traced
    for w in eng2.DEFAULT_WARMUP_WIDTHS:
        assert ("bfs", w) in warmed
    mark = eng2.trace_mark()
    eng2.execute("bfs", np.full(32, -1, np.int32))
    assert eng2.retraces_since(mark) == 0  # zero-retrace steady state


def test_warmup_explicit_widths_unchanged(tmp_path, monkeypatch):
    from combblas_tpu.serve.engine import GraphEngine

    _use_store(monkeypatch, tmp_path)
    rng = np.random.default_rng(6)
    N = 32
    rows = rng.integers(0, N, 100).astype(np.int64)
    cols = rng.integers(0, N, 100).astype(np.int64)
    eng = GraphEngine.from_coo(
        Grid.make(1, 1), np.concatenate([rows, cols]),
        np.concatenate([cols, rows]), N, kinds=("bfs",),
    )
    warmed = eng.warmup(widths=(2, 4))
    assert set(warmed) == {("bfs", 2), ("bfs", 4)}


# --- shared health surface -------------------------------------------------


def test_compile_cache_provider_covers_plan_store(tmp_path, monkeypatch):
    from combblas_tpu.utils import compile_cache

    st = _use_store(monkeypatch, tmp_path)
    st.put(_key(), PlanRecord(tier="windowed", cost_s=1.0))
    obs.enable(install_hooks=False)
    try:
        obs.reset()
        compile_cache._record_cache_entries()
        assert obs.registry.get_gauge(
            "tuner.store.entries", dir=st.path
        ) == 1
        assert obs.registry.get_gauge(
            "compile_cache.entries", cache="plans", dir=st.path
        ) == 1
    finally:
        obs.disable()
        obs.reset()


# --- round 11: store aging (compaction + oldest-cost eviction) --------------


def _key_i(i: int) -> PlanKey:
    """Distinct keys (different shape buckets) for aging tests."""
    return plan_key_from_counts(
        "plus_times", 1 << (8 + i), 1 << (8 + i), 1 << (8 + i),
        1 << (10 + i), 1 << (10 + i), "scatter", "1x1",
        platform="cpu",
    )


def test_store_ts_stamped_and_roundtrips(tmp_path):
    st = PlanStore(str(tmp_path))
    rec = PlanRecord(tier="scan", cost_s=1.0)
    assert rec.ts is None
    st.put(_key(), rec)
    assert rec.ts is not None  # put stamps the measurement time
    got = PlanStore(str(tmp_path)).lookup(_key())
    assert got.ts == rec.ts


def test_store_compaction_rewrites_superseded_lines(
    tmp_path, monkeypatch
):
    """Load-time compaction: a log full of last-wins-shadowed lines is
    rewritten to one line per surviving key (atomic replace), counted
    in stats and the ``tuner.store.compacted`` counter."""
    monkeypatch.setenv(config.ENV_STORE_COMPACT, "5")
    st = PlanStore(str(tmp_path))
    for i in range(8):  # 7 superseded lines for one key
        st.put(_key(), PlanRecord(tier="scan", cost_s=float(i + 1)))
    st.put(_key_i(1), PlanRecord(tier="windowed", cost_s=0.5))
    with open(st.file) as f:
        assert len(f.readlines()) == 9
    obs.enable(install_hooks=False)
    try:
        obs.reset()
        st2 = PlanStore(str(tmp_path))
        assert st2.entries() == 2
        assert st2.stats()["compacted_lines"] == 7
        assert obs.registry.get_counter("tuner.store.compacted") == 7
        with open(st2.file) as f:
            lines = f.readlines()
        assert len(lines) == 2  # the rewritten file is compact
        # survivors keep their latest records
        assert st2.lookup(_key()).cost_s == 8.0
        assert st2.lookup(_key_i(1)).tier == "windowed"
        # a third load has nothing to compact
        st3 = PlanStore(str(tmp_path))
        assert st3.stats()["compacted_lines"] == 0
    finally:
        obs.disable()
        obs.reset()


def test_store_compaction_below_threshold_keeps_log(tmp_path,
                                                    monkeypatch):
    monkeypatch.setenv(config.ENV_STORE_COMPACT, "50")
    st = PlanStore(str(tmp_path))
    for i in range(4):
        st.put(_key(), PlanRecord(tier="scan", cost_s=float(i + 1)))
    st2 = PlanStore(str(tmp_path))
    assert st2.stats()["compacted_lines"] == 0
    with open(st2.file) as f:
        assert len(f.readlines()) == 4  # append-only log untouched


def test_store_max_entries_oldest_cost_eviction(tmp_path, monkeypatch):
    """The cap evicts by measurement age: oldest ``ts`` first (records
    without one age out before any stamped record), newest survive —
    at load AND at put."""
    monkeypatch.setenv(config.ENV_STORE_MAX, "3")
    monkeypatch.setenv(config.ENV_STORE_COMPACT, "1")
    st = PlanStore(str(tmp_path))
    for i in range(5):
        st.put(
            _key_i(i),
            PlanRecord(tier="scan", cost_s=1.0, ts=float(100 + i)),
        )
        assert st.entries() <= 3  # put-time cap holds throughout
    assert st.stats()["evicted"] == 2
    assert st.lookup(_key_i(0)) is None  # oldest ts evicted
    assert st.lookup(_key_i(4)) is not None
    # load-time: the file still carries all 5 lines until a reload
    # compacts; the fresh instance loads, evicts to cap, and rewrites
    st2 = PlanStore(str(tmp_path))
    assert st2.entries() == 3
    assert st2.lookup(_key_i(4)) is not None
    with open(st2.file) as f:
        assert len(f.readlines()) == 3


def test_store_unstamped_records_age_out_first(tmp_path, monkeypatch):
    monkeypatch.setenv(config.ENV_STORE_MAX, "2")
    st = PlanStore(str(tmp_path))
    st.put(_key_i(0), PlanRecord(tier="scan", ts=50.0))
    unstamped = PlanRecord(tier="scan")
    unstamped.ts = None  # simulate a pre-round-11 line
    with st._lock:
        st._plans[_key_i(1)] = unstamped
    st.put(_key_i(2), PlanRecord(tier="scan", ts=60.0))
    assert st.lookup(_key_i(1)) is None  # no ts = oldest
    assert st.lookup(_key_i(0)) is not None


# --- round 11: the shared resolve_tier helper -------------------------------


def test_resolve_tier_precedence_and_vetting(tmp_path, monkeypatch):
    """arg > store > env > heuristic, with the library's record
    vetting: a key-matched record outside ``allowed`` is discarded
    (``tuner.store.rejected{reason=tier}``) and resolution degrades."""
    from combblas_tpu.tuner.resolve import resolve_tier

    st = _use_store(monkeypatch, tmp_path)
    key = _key()
    obs.enable(install_hooks=False)
    try:
        obs.reset()
        # heuristic rung (empty store, no env)
        tier, src, rec = resolve_tier(
            key, op="spgemm", allowed=("scan", "esc"),
            heuristic=lambda: "esc", store=st,
        )
        assert (tier, src, rec) == ("esc", "heuristic", None)
        # store rung
        st.put(key, PlanRecord(tier="scan", cost_s=0.5))
        tier, src, rec = resolve_tier(
            key, op="spgemm", allowed=("scan", "esc"),
            heuristic="esc", store=st,
        )
        assert (tier, src) == ("scan", "store") and rec.tier == "scan"
        # vetting: same record under an op that doesn't allow the tier
        tier, src, rec = resolve_tier(
            key, op="spgemm3d", allowed=("esc", "windowed"),
            heuristic="esc", store=st,
        )
        assert (tier, src, rec) == ("esc", "heuristic", None)
        assert obs.registry.get_counter(
            "tuner.store.rejected", reason="tier"
        ) == 1
        # env rung beats the heuristic when the record was rejected
        monkeypatch.setenv(config.ENV_TIER3D, "windowed")
        tier, src, _rec = resolve_tier(
            key, op="spgemm3d", allowed=("esc", "windowed"),
            heuristic="esc", store=st,
        )
        assert (tier, src) == ("windowed", "env")
        # arg wins over everything
        tier, src, _rec = resolve_tier(
            key, op="spgemm", allowed=("scan", "esc"),
            heuristic="esc", tier="mxu", store=st,
        )
        assert (tier, src) == ("mxu", "arg")
        assert obs.registry.get_counter(
            "spgemm.auto.plan_source", source="arg", tier="mxu",
            op="spgemm",
        ) == 1
    finally:
        obs.disable()
        obs.reset()


def test_resolve_tier_account_false_peeks_silently(tmp_path,
                                                   monkeypatch):
    """account=False (the spgemm3d_bench mirror): peek — no hit/miss
    accounting, no plan_source counter."""
    from combblas_tpu.tuner.resolve import resolve_tier

    st = _use_store(monkeypatch, tmp_path)
    key = _key(op="spgemm3d")
    st.put(key, PlanRecord(tier="windowed", cost_s=0.5))
    hits_before = st.stats()["hits"]
    obs.enable(install_hooks=False)
    try:
        obs.reset()
        tier, src, _rec = resolve_tier(
            key, op="spgemm3d", allowed=("esc", "windowed"),
            heuristic="esc", store=st, account=False,
        )
        assert (tier, src) == ("windowed", "store")
        assert st.stats()["hits"] == hits_before  # peek, not lookup
        assert obs.registry.get_counter(
            "spgemm.auto.plan_source", source="store",
            tier="windowed", op="spgemm3d",
        ) == 0
    finally:
        obs.disable()
        obs.reset()


# --- round 12: window-geometry probing --------------------------------------


def test_probe_geometry_sweep_records_block_shape(tmp_path, rng):
    """When the tier sweep's winner is ``windowed`` and budget remains,
    the probe sweeps a bounded block-geometry grid and persists the
    winning block_rows/block_cols WITH the plan (before round 12,
    geometry reached the store only via BENCH_PLAN_RECORD=1)."""
    from combblas_tpu.tuner.probe import _geometry_candidates

    grid = Grid.make(1, 1)
    r, c, v = coo(rng, 128, 128, 700, dup_frac=0.0)
    A = SpParMat.from_global_coo(grid, r, c, v, 128, 128)
    st = PlanStore(str(tmp_path))
    key = spgemm_plan_key(PLUS_TIMES, A, A, "scatter")
    geo = _geometry_candidates(128, 128)
    assert 1 <= len(geo) <= 5 and (None, None) not in geo
    # injected costs: make "windowed" win the tier sweep (0.4 beats
    # scan's 0.5), then make the SECOND geometry candidate the overall
    # winner (0.05)
    seq = iter([0.4, 0.5] + [0.9, 0.05] + [0.7] * 8)

    rec = probe_spgemm(
        PLUS_TIMES, A, A, backend="scatter", store=st, key=key,
        tier_order=("windowed", "scan"),
        measure=lambda fn: next(seq),
    )
    assert rec is not None and rec.tier == "windowed"
    assert (rec.block_rows, rec.block_cols) == geo[1]
    assert rec.cost_s == 0.05
    # persisted: a fresh load replays the measured geometry
    assert PlanStore(str(tmp_path)).lookup(key) == rec


def test_probe_geometry_skipped_when_windowed_loses(tmp_path, rng):
    grid = Grid.make(1, 1)
    r, c, v = coo(rng, 64, 64, 300, dup_frac=0.0)
    A = SpParMat.from_global_coo(grid, r, c, v, 64, 64)
    st = PlanStore(str(tmp_path))
    seq = iter([0.1, 0.5, 0.5, 0.5])

    rec = probe_spgemm(
        PLUS_TIMES, A, A, backend="scatter", store=st,
        key=spgemm_plan_key(PLUS_TIMES, A, A, "scatter"),
        tier_order=("scan", "windowed"),
        measure=lambda fn: next(seq),
    )
    assert rec is not None and rec.tier == "scan"
    assert rec.block_rows is None and rec.block_cols is None
