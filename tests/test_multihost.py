"""Multi-PROCESS execution, exercised for real (VERDICT r2 missing #4).

Spawns two controller processes that jointly own an 8-device global CPU
mesh via ``jax.distributed.initialize`` (local coordinator), build the
global grid with ``make_global_grid``, and check one SpMV and one SpGEMM
against single-process host references — the CPU analog of the
reference's ``mpirun -np 2`` release tests.

Runs in its own subprocesses (NOT the in-process 8-device fixture): the
distributed runtime cannot share the already-initialized backend.
"""

import os
import socket
import subprocess
import sys

import pytest


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# (no pytest-timeout dependency here; the inner communicate(timeout=240)
# bounds the workers — ADVICE r3 flagged the unregistered mark)
def test_two_process_spmv_spgemm():
    worker = os.path.join(os.path.dirname(__file__), "_multihost_worker.py")
    coord = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # workers set their own device count
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, worker, coord, str(pid)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env,
        )
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-3000:]}"
        assert f"proc {pid} OK" in out
