"""Local semiring SpMV / SpMSpV kernels vs numpy references."""

import numpy as np
import pytest

from combblas_tpu import MIN_PLUS, PLUS_TIMES, SELECT2ND_MAX, SpTuples
from combblas_tpu.ops.compressed import CSC
from combblas_tpu.ops.spmv import spmspv, spmv, spmv_masked
from conftest import random_dense


def test_spmv_plus_times(rng):
    d = random_dense(rng, 17, 13)
    x = rng.random(13).astype(np.float32)
    t = SpTuples.from_dense(d, capacity=256)
    y = spmv(PLUS_TIMES, t, x)
    np.testing.assert_allclose(np.asarray(y), d @ x, rtol=1e-5)


def test_spmv_min_plus(rng):
    m, n = 9, 9
    d = random_dense(rng, m, n, 0.4)
    t = SpTuples.from_dense(d, capacity=100)
    x = rng.random(n).astype(np.float32)
    y = np.asarray(spmv(MIN_PLUS, t, x))
    expect = np.full(m, np.inf, np.float32)
    for i in range(m):
        for j in range(n):
            if d[i, j] != 0:
                expect[i] = min(expect[i], d[i, j] + x[j])
    np.testing.assert_allclose(y, expect, rtol=1e-6)


def test_spmv_select2nd_max_bfs_style(rng):
    # x carries candidate parent ids (or -1 = inactive); y[i] = max parent
    # over in-neighbors, the Graph500 semiring (Semirings.h:166).
    m, n = 8, 8
    d = (random_dense(rng, m, n, 0.4) != 0).astype(np.int32)
    t = SpTuples.from_dense(d, capacity=64)
    x = np.where(rng.random(n) < 0.5, np.arange(n), -1).astype(np.int32)
    y = np.asarray(spmv(SELECT2ND_MAX, t, x))
    expect = np.full(m, -1, np.int32)
    for i in range(m):
        for j in range(n):
            if d[i, j] and x[j] >= 0:
                expect[i] = max(expect[i], x[j])
    np.testing.assert_array_equal(y, expect)


def test_spmv_masked(rng):
    d = random_dense(rng, 10, 10)
    t = SpTuples.from_dense(d, capacity=128)
    x = rng.random(10).astype(np.float32)
    active = rng.random(10) < 0.5
    y = np.asarray(spmv_masked(PLUS_TIMES, t, x, active))
    np.testing.assert_allclose(y, np.where(active, d @ x, 0), rtol=1e-5)


def test_spmspv_plus_times(rng):
    m, n = 15, 12
    d = random_dense(rng, m, n, 0.3)
    t = SpTuples.from_dense(d, capacity=256)
    csc = CSC.from_tuples(t)
    # sparse x with 4 active entries
    active = rng.choice(n, size=4, replace=False)
    xcap = 8
    x_ind = np.full(xcap, n, np.int32)
    x_val = np.zeros(xcap, np.float32)
    x_ind[:4] = np.sort(active)
    x_val[:4] = rng.random(4)
    y_ind, y_val, y_nnz = spmspv(
        PLUS_TIMES, csc,
        np.asarray(x_ind), np.asarray(x_val), np.int32(4),
        out_capacity=m,
    )
    x_dense = np.zeros(n, np.float32)
    x_dense[x_ind[:4]] = x_val[:4]
    expect = d @ x_dense
    got = np.zeros(m, np.float32)
    k = int(y_nnz)
    got[np.asarray(y_ind)[:k]] = np.asarray(y_val)[:k]
    np.testing.assert_allclose(got, np.where(np.abs(expect) > 0, expect, 0), rtol=1e-5)
    # output rows = rows structurally touched
    touched = np.unique(np.nonzero(d[:, x_ind[:4]])[0])
    np.testing.assert_array_equal(np.sort(np.asarray(y_ind)[:k]), touched)


def test_spmspv_select2nd_max(rng):
    # BFS step shape: bool matrix, x holds parent ids.
    m = n = 10
    d = (random_dense(rng, m, n, 0.3) != 0).astype(np.int32)
    t = SpTuples.from_dense(d, capacity=128)
    csc = CSC.from_tuples(t)
    frontier = rng.choice(n, size=3, replace=False)
    xcap = 6
    x_ind = np.full(xcap, n, np.int32)
    x_val = np.full(xcap, -1, np.int32)
    x_ind[:3] = np.sort(frontier)
    x_val[:3] = x_ind[:3]  # parent = self id
    y_ind, y_val, y_nnz = spmspv(
        SELECT2ND_MAX, csc,
        np.asarray(x_ind), np.asarray(x_val), np.int32(3),
        out_capacity=m,
    )
    expect = np.full(m, -1, np.int32)
    for j in frontier:
        for i in range(m):
            if d[i, j]:
                expect[i] = max(expect[i], j)
    k = int(y_nnz)
    got = np.full(m, -1, np.int32)
    got[np.asarray(y_ind)[:k]] = np.asarray(y_val)[:k]
    np.testing.assert_array_equal(got, expect)
