"""Betweenness centrality vs a trusted numpy Brandes implementation."""

import numpy as np
import pytest

from combblas_tpu.models.bc import bc_batch, betweenness_centrality
from combblas_tpu.parallel.grid import Grid
from combblas_tpu.parallel.spmat import SpParMat


def brandes_numpy(adj, sources=None):
    """Textbook Brandes (Algorithm 1 of the 2001 paper)."""
    from collections import deque

    n = adj.shape[0]
    bc = np.zeros(n)
    for s in sources if sources is not None else range(n):
        pred = [[] for _ in range(n)]
        sigma = np.zeros(n)
        sigma[s] = 1
        dist = np.full(n, -1)
        dist[s] = 0
        order = []
        q = deque([s])
        while q:
            v = q.popleft()
            order.append(v)
            for w in np.nonzero(adj[:, v])[0]:
                if dist[w] < 0:
                    dist[w] = dist[v] + 1
                    q.append(w)
                if dist[w] == dist[v] + 1:
                    sigma[w] += sigma[v]
                    pred[w].append(v)
        delta = np.zeros(n)
        for w in reversed(order):
            for v in pred[w]:
                delta[v] += sigma[v] / sigma[w] * (1 + delta[w])
            if w != s:
                bc[w] += delta[w]
    return bc


def _sym_random(rng, n, density):
    d = (rng.random((n, n)) < density).astype(np.float32)
    d = np.maximum(d, d.T)
    np.fill_diagonal(d, 0)
    return d


def test_bc_path_graph():
    """Path 0-1-2-3-4: interior vertices are the only intermediaries."""
    grid = Grid.make(2, 2)
    n = 5
    d = np.zeros((n, n), np.float32)
    for i in range(n - 1):
        d[i, i + 1] = d[i + 1, i] = 1
    A = SpParMat.from_dense(grid, d)
    got = betweenness_centrality(A).to_global()
    np.testing.assert_allclose(got, brandes_numpy(d), rtol=1e-5, atol=1e-5)


def test_bc_star_graph():
    grid = Grid.make(2, 2)
    n = 7
    d = np.zeros((n, n), np.float32)
    d[0, 1:] = d[1:, 0] = 1
    A = SpParMat.from_dense(grid, d)
    got = betweenness_centrality(A).to_global()
    np.testing.assert_allclose(got, brandes_numpy(d), rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("pr,pc", [(2, 2)])
def test_bc_random_graph(rng, pr, pc):
    grid = Grid.make(pr, pc)
    d = _sym_random(rng, 16, 0.25)
    A = SpParMat.from_dense(grid, d)
    got = betweenness_centrality(A).to_global()
    np.testing.assert_allclose(got, brandes_numpy(d), rtol=1e-4, atol=1e-4)


def test_bc_batched_equals_unbatched(rng):
    grid = Grid.make(2, 2)
    d = _sym_random(rng, 12, 0.3)
    A = SpParMat.from_dense(grid, d)
    full = betweenness_centrality(A).to_global()
    batched = betweenness_centrality(A, batch_size=4).to_global()
    np.testing.assert_allclose(batched, full, rtol=1e-4, atol=1e-4)


def test_bc_sampled_sources(rng):
    grid = Grid.make(2, 2)
    d = _sym_random(rng, 12, 0.3)
    A = SpParMat.from_dense(grid, d)
    srcs = np.array([0, 3, 7])
    got = betweenness_centrality(A, sources=srcs).to_global()
    np.testing.assert_allclose(
        got, brandes_numpy(d, srcs), rtol=1e-4, atol=1e-4
    )


def test_bc_batch_dense_matches_host_loop(rng):
    """The one-launch dense Brandes == the host-loop bc_batch."""
    import jax.numpy as jnp

    from combblas_tpu.models.bc import bc_batch, bc_batch_dense
    from combblas_tpu.parallel.ellmat import EllParMat
    from combblas_tpu.parallel.grid import Grid
    from combblas_tpu.parallel.spmat import SpParMat

    grid = Grid.make(2, 2)
    n = 32
    d = (rng.random((n, n)) < 0.12)
    d = (d | d.T).astype(np.float32)
    np.fill_diagonal(d, 0)
    r, c = np.nonzero(d)
    A = SpParMat.from_global_coo(grid, r, c, d[r, c], n, n)
    E = EllParMat.from_host_coo(
        grid, r.astype(np.int64), c.astype(np.int64), d[r, c], n, n
    )
    srcs = np.array([0, 5, 11, 20], np.int64)
    ref = bc_batch(A, srcs).to_global()
    got = bc_batch_dense(
        E, E, jnp.asarray(srcs, jnp.int32)
    ).to_global()
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)


def test_bc_batch_dense_directed_and_depth_bound(rng):
    """Directed graph with distinct E/ET, plus a max_depth exactly at the
    diameter (the truncation edge the backward sweep must still cover)."""
    import jax.numpy as jnp

    from combblas_tpu.models.bc import bc_batch, bc_batch_dense
    from combblas_tpu.parallel.ellmat import EllParMat
    from combblas_tpu.parallel.grid import Grid
    from combblas_tpu.parallel.spmat import SpParMat

    grid = Grid.make(2, 2)
    n = 16
    # directed path 0->1->...->7 plus random extra arcs
    d = np.zeros((n, n), np.float32)
    for v in range(7):
        d[v + 1, v] = 1.0  # edge v -> v+1 in (i,j)=j->i convention
    extra = rng.random((n, n)) < 0.05
    d = np.maximum(d, extra.astype(np.float32))
    np.fill_diagonal(d, 0)
    r, c = np.nonzero(d)
    A = SpParMat.from_global_coo(grid, r, c, d[r, c], n, n)
    E = EllParMat.from_host_coo(
        grid, r.astype(np.int64), c.astype(np.int64), d[r, c], n, n
    )
    rt, ct = c, r  # transpose
    ET = EllParMat.from_host_coo(
        grid, rt.astype(np.int64), ct.astype(np.int64), d[r, c], n, n
    )
    srcs = np.array([0, 3], np.int64)
    ref = bc_batch(A, srcs).to_global()
    got = bc_batch_dense(E, ET, jnp.asarray(srcs, jnp.int32)).to_global()
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)
    # max_depth exactly at the deepest discovered level from source 0
    got_tight = bc_batch_dense(
        E, ET, jnp.asarray(srcs, jnp.int32), max_depth=7
    ).to_global()
    np.testing.assert_allclose(got_tight, ref, rtol=1e-4, atol=1e-4)
