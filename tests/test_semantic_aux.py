"""Semantic graphs / filtered BFS+MIS, phase timers, checkpointing."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from combblas_tpu.models.bfs import bfs, validate_bfs_tree
from combblas_tpu.models.mis import mis
from combblas_tpu.parallel.grid import Grid
from combblas_tpu.parallel.spmat import SpParMat
from combblas_tpu.parallel.vec import DistVec
from combblas_tpu.semantic import SemanticGraph, filtered_bfs, filtered_mis
from combblas_tpu.utils import checkpoint as ckpt
from combblas_tpu.utils import timers
from conftest import random_dense


def _twitterish_graph(rng, n, density=0.25):
    """Symmetric structure with per-edge (latest, follower) attributes."""
    d = (rng.random((n, n)) < density).astype(np.float32)
    d = np.maximum(d, d.T)
    np.fill_diagonal(d, 0)
    r, c = np.nonzero(d)
    # symmetric attribute so the filtered graph stays symmetric
    latest = ((r * 131 + c * 17) % 100 + ((c * 131 + r * 17) % 100)).astype(
        np.float32
    )
    followers = ((r + c) % 7).astype(np.int32)
    return d, r, c, {"latest": latest, "followers": followers}


def _keep_early(attrs):
    return attrs["latest"] < 100


def test_materialize_vs_mask_structure(rng):
    grid = Grid.make(2, 2)
    d, r, c, attrs = _twitterish_graph(rng, 16)
    g = SemanticGraph.from_edges(grid, r, c, attrs, 16, 16)
    mat = g.materialize(_keep_early).to_dense()
    msk = g.mask(_keep_early).to_dense()
    keep = attrs["latest"] < 100
    expect = np.zeros((16, 16), np.float32)
    expect[r[keep], c[keep]] = 1.0
    np.testing.assert_allclose(mat, expect)
    np.testing.assert_allclose(msk, expect)  # mask writes 0/1 values


def test_filtered_bfs_modes_agree(rng):
    grid = Grid.make(2, 2)
    d, r, c, attrs = _twitterish_graph(rng, 20)
    g = SemanticGraph.from_edges(grid, r, c, attrs, 20, 20)
    p1, l1, _ = filtered_bfs(g, _keep_early, 0, materialize=True)
    p2, l2, _ = filtered_bfs(g, _keep_early, 0, materialize=False)
    np.testing.assert_array_equal(l1.to_global(), l2.to_global())
    filt = g.materialize(_keep_early).to_dense()
    assert not validate_bfs_tree(filt, 0, p1.to_global(), l1.to_global())
    assert not validate_bfs_tree(filt, 0, p2.to_global(), l2.to_global())


def test_filtered_bfs_differs_from_unfiltered(rng):
    grid = Grid.make(2, 2)
    d, r, c, attrs = _twitterish_graph(rng, 20, density=0.4)
    g = SemanticGraph.from_edges(grid, r, c, attrs, 20, 20)
    _, l_all, _ = bfs(g.structure, 0)
    _, l_f, _ = filtered_bfs(g, lambda a: a["latest"] < 40, 0)
    assert not np.array_equal(l_all.to_global(), l_f.to_global())


def test_filtered_mis_independent(rng):
    grid = Grid.make(2, 2)
    d, r, c, attrs = _twitterish_graph(rng, 16, density=0.3)
    g = SemanticGraph.from_edges(grid, r, c, attrs, 16, 16)
    inset, _ = filtered_mis(g, _keep_early, jax.random.key(0))
    filt = g.materialize(_keep_early).to_dense()
    s = (np.asarray(inset.to_global()) == 1)[:16]  # status: 1=in, -1=out
    # independence in the filtered graph
    sub = filt[np.ix_(s.nonzero()[0], s.nonzero()[0])]
    assert sub.sum() == 0


def test_timers_accumulate():
    timers.reset_all()
    with timers.phase("unit_test_phase"):
        x = jnp.arange(8).sum()
    rep = timers.report()
    assert "unit_test_phase" in rep
    sec, n = rep["unit_test_phase"]
    assert n == 1 and sec >= 0


def test_checkpoint_npz_roundtrip(tmp_path, rng):
    grid = Grid.make(2, 2)
    d = random_dense(rng, 12, 12, 0.3)
    A = SpParMat.from_dense(grid, d)
    p = str(tmp_path / "mat.npz")
    ckpt.save(p, A)
    B = ckpt.load(p, grid)
    np.testing.assert_allclose(B.to_dense(), d)
    # cross-shape restore (re-shard via global tuples)
    g2 = Grid.make(2, 4)
    C = ckpt.load(p, g2)
    np.testing.assert_allclose(C.to_dense(), d)
    v = DistVec.from_global(grid, np.arange(10, dtype=np.float32))
    pv = str(tmp_path / "vec.npz")
    ckpt.save(pv, v)
    np.testing.assert_allclose(
        ckpt.load(pv, grid).to_global(), np.arange(10)
    )


def test_checkpoint_orbax_roundtrip(tmp_path, rng):
    pytest.importorskip("orbax.checkpoint")
    grid = Grid.make(2, 2)
    d = random_dense(rng, 12, 12, 0.3)
    A = SpParMat.from_dense(grid, d)
    p = str(tmp_path / "omat")
    ckpt.save_orbax(p, A)
    B = ckpt.load_orbax(p, grid)
    np.testing.assert_allclose(B.to_dense(), d)


def test_checkpoint_vec_preserves_fill(tmp_path):
    """Restored vectors must keep their padding fill (ADVICE r1): a MAX
    reduce over an all-negative vector restored with 0-padding would
    silently return 0."""
    from combblas_tpu.semiring import SELECT2ND_MAX

    grid = Grid.make(2, 2)
    x = -np.arange(2, 9, dtype=np.int32)  # 7 values, all negative
    v = DistVec.from_global(grid, x, align="row", fill=np.int32(-(2**31)))
    p = str(tmp_path / "negvec.npz")
    ckpt.save(p, v)
    # same-shape restore: padded blocks verbatim
    v2 = ckpt.load(p, grid)
    assert int(v2.reduce(SELECT2ND_MAX)) == -2
    np.testing.assert_array_equal(v2.to_global(), x)
    # cross-shape restore: fill persisted through meta
    g2 = Grid.make(4, 2)
    v3 = ckpt.load(p, g2)
    assert int(v3.reduce(SELECT2ND_MAX)) == -2
    np.testing.assert_array_equal(v3.to_global(), x)
