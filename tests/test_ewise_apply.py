"""Generalized EWiseApply null-handling semantics + Galerkin golden.

Mirrors the reference's EWiseApply variants (ParFriends.h:2157-2807) and
the GalerkinNew release test (R^T A R via two SpGEMMs).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from combblas_tpu import PLUS_TIMES
from combblas_tpu.parallel.grid import Grid
from combblas_tpu.parallel.spgemm import spgemm
from combblas_tpu.parallel.spmat import SpParMat
from conftest import random_dense


def _sub(a, b):
    return a - b


def _pair(rng, n=12, density=0.3):
    da = random_dense(rng, n, n, density)
    db = random_dense(rng, n, n, density)
    grid = Grid.make(2, 2)
    return grid, da, db, SpParMat.from_dense(grid, da), SpParMat.from_dense(grid, db)


def test_ewise_apply_intersection(rng):
    grid, da, db, A, B = _pair(rng)
    got = A.ewise_apply(B, _sub).to_dense()
    mask = (da != 0) & (db != 0)
    np.testing.assert_allclose(got, np.where(mask, da - db, 0), rtol=1e-6)


def test_ewise_apply_union(rng):
    grid, da, db, A, B = _pair(rng)
    got = A.ewise_apply(
        B, _sub, allow_a_nulls=True, allow_b_nulls=True
    ).to_dense()
    mask = (da != 0) | (db != 0)
    np.testing.assert_allclose(got, np.where(mask, da - db, 0), rtol=1e-6)


def test_ewise_apply_difference(rng):
    """a-only extension: entries of A not in B survive (B reads b_null)."""
    grid, da, db, A, B = _pair(rng)
    got = A.ewise_apply(B, _sub, allow_b_nulls=True).to_dense()
    mask = da != 0
    np.testing.assert_allclose(got, np.where(mask, da - db * (db != 0) * 1.0, 0) * mask, rtol=1e-6)


def test_ewise_apply_b_null_value(rng):
    grid, da, db, A, B = _pair(rng)
    got = A.ewise_apply(B, _sub, allow_b_nulls=True, b_null=7.0).to_dense()
    expect = np.where(
        da != 0, da - np.where(db != 0, db, 7.0), 0
    )
    np.testing.assert_allclose(got, expect.astype(np.float32), rtol=1e-6)


def test_galerkin_rtar(rng):
    """R^T A R — the GalerkinNew release test pattern (RestrictionOp)."""
    grid = Grid.make(2, 2)
    da = random_dense(rng, 16, 16, 0.3)
    dr = random_dense(rng, 16, 8, 0.4)
    A = SpParMat.from_dense(grid, da)
    R = SpParMat.from_dense(grid, dr)
    RT = R.transpose()
    got = spgemm(PLUS_TIMES, spgemm(PLUS_TIMES, RT, A), R).to_dense()
    np.testing.assert_allclose(got, dr.T @ da @ dr, rtol=1e-4, atol=1e-4)
