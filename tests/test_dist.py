"""Distributed matrix/vector layer on the 8-device virtual CPU mesh.

Grid-shape coverage mirrors the reference's mpirun -np {1,4,16} pattern
(SURVEY.md §4.4): 1x1, 2x2 (square) and 2x4/4x2 (rectangular) grids.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from combblas_tpu import MIN_PLUS, PLUS_TIMES, SELECT2ND_MAX
from combblas_tpu.parallel.grid import Grid
from combblas_tpu.parallel.spmat import SpParMat
from combblas_tpu.parallel.vec import DistVec
from conftest import random_dense

GRIDS = [(1, 1), (2, 2), (2, 4), (4, 2)]


@pytest.fixture(params=GRIDS, ids=[f"{a}x{b}" for a, b in GRIDS])
def grid(request):
    return Grid.make(*request.param)


def test_roundtrip(grid, rng):
    d = random_dense(rng, 19, 23)
    A = SpParMat.from_dense(grid, d)
    np.testing.assert_array_equal(A.to_dense(), d)
    assert int(A.getnnz()) == np.count_nonzero(d)


def test_apply_prune(grid, rng):
    d = random_dense(rng, 16, 16)
    A = SpParMat.from_dense(grid, d)
    np.testing.assert_allclose(A.apply(lambda v: v * 3).to_dense(), d * 3, rtol=1e-6)
    p = A.prune(lambda v: v > 0.5)
    np.testing.assert_array_equal(p.to_dense(), np.where(d > 0.5, 0, d))


def test_reduce_rows_cols(grid, rng):
    d = random_dense(rng, 12, 18)
    A = SpParMat.from_dense(grid, d)
    colsum = A.reduce(PLUS_TIMES, axis="rows")
    assert colsum.align == "col"
    np.testing.assert_allclose(colsum.to_global(), d.sum(axis=0), rtol=1e-5)
    rowsum = A.reduce(PLUS_TIMES, axis="cols")
    assert rowsum.align == "row"
    np.testing.assert_allclose(rowsum.to_global(), d.sum(axis=1), rtol=1e-5)
    # min-reduce with mapped values (degrees): count entries per row
    deg = A.reduce(PLUS_TIMES, axis="cols", map_fn=lambda v: jnp.ones_like(v))
    np.testing.assert_array_equal(deg.to_global(), (d != 0).sum(axis=1))


def test_ewise_mult(grid, rng):
    d1 = random_dense(rng, 14, 14, 0.4)
    d2 = random_dense(rng, 14, 14, 0.4)
    A = SpParMat.from_dense(grid, d1)
    B = SpParMat.from_dense(grid, d2)
    keep = A.ewise_mult(B)
    np.testing.assert_array_equal(keep.to_dense(), np.where(d2 != 0, d1, 0))
    excl = A.ewise_mult(B, negate=True)
    np.testing.assert_array_equal(excl.to_dense(), np.where(d2 != 0, 0, d1))
    prod = A.ewise_mult(B, combine=lambda x, y: x * y)
    np.testing.assert_allclose(prod.to_dense(), d1 * d2, rtol=1e-6)


def test_dim_apply(grid, rng):
    d = random_dense(rng, 10, 12)
    A = SpParMat.from_dense(grid, d)
    colscale = rng.random(12).astype(np.float32)
    v = DistVec.from_global(grid, colscale, align="col")
    scaled = A.dim_apply(v, lambda a, s: a * s, axis="cols")
    np.testing.assert_allclose(scaled.to_dense(), d * colscale[None, :], rtol=1e-6)
    rowscale = rng.random(10).astype(np.float32)
    vr = DistVec.from_global(grid, rowscale, align="row")
    scaled_r = A.dim_apply(vr, lambda a, s: a * s, axis="rows")
    np.testing.assert_allclose(scaled_r.to_dense(), d * rowscale[:, None], rtol=1e-6)


def test_transpose_square_grids(rng):
    for shape in [(1, 1), (2, 2)]:
        grid = Grid.make(*shape)
        d = random_dense(rng, 15, 9)
        A = SpParMat.from_dense(grid, d)
        np.testing.assert_array_equal(A.transpose().to_dense(), d.T)


def test_vec_realign(grid, rng):
    x = rng.random(21).astype(np.float32)
    v = DistVec.from_global(grid, x, align="col")
    r = v.realign("row")
    assert r.align == "row"
    np.testing.assert_array_equal(r.to_global(), x)
    back = r.realign("col")
    np.testing.assert_array_equal(back.to_global(), x)


def test_vec_ops(grid, rng):
    x = rng.random(17).astype(np.float32)
    y = rng.random(17).astype(np.float32)
    vx = DistVec.from_global(grid, x)
    vy = DistVec.from_global(grid, y)
    np.testing.assert_allclose(
        vx.ewise(vy, jnp.add).to_global(), x + y, rtol=1e-6
    )
    np.testing.assert_allclose(float(vx.reduce(PLUS_TIMES)), x.sum(), rtol=1e-5)
    np.testing.assert_allclose(
        float(vx.mask_padding(-np.inf).reduce(SELECT2ND_MAX)), x.max(), rtol=1e-6
    )
    it = DistVec.iota(grid, 17)
    np.testing.assert_array_equal(it.to_global(), np.arange(17))


def test_load_imbalance(grid, rng):
    d = random_dense(rng, 16, 16, 0.5)
    A = SpParMat.from_dense(grid, d)
    li = float(A.load_imbalance())
    assert li >= 1.0
