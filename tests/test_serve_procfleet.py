"""Process-isolated serving fleet (round 17, ISSUE 15): subprocess
replicas with real crash domains behind the shared routing/supervision
policy — IPC framing, per-request deadlines, heartbeat liveness,
SIGKILL/SIGSTOP chaos over the WAL/checkpoint substrate.

Tier-1 keeps ONE spawning representative (single replica, 1x1 grid,
pre-staged checkpoint, deterministic ``supervise_once``) plus
spawn-free unit tests of the IPC channel, the parent-side replica
client (stub responder over a socketpair — no subprocess, no jax
child), and the deterministic process fault plan.  The real-signal
chaos scenarios (SIGKILL respawn, SIGSTOP heartbeat-timeout
promotion) are ``slow``; ``BENCH_FLEET=process`` is their measured
twin.
"""

import json
import os
import signal
import socket
import threading
import time

import numpy as np
import pytest

from combblas_tpu.dynamic import open_wal, recover_version
from combblas_tpu.parallel.grid import Grid
from combblas_tpu.serve import (
    BackpressureError,
    GraphEngine,
    ProcessFaultPlan,
    ProcessFleet,
    ServeConfig,
)
from combblas_tpu.serve.ipc import Channel, ChannelClosed
from combblas_tpu.serve.procfleet import (
    IpcTimeoutError,
    ReplicaDeadError,
    ReplicaProc,
)
from combblas_tpu.utils import checkpoint

N = 64


def _coo(seed, n=N, m=300):
    r = np.random.default_rng(seed)
    rows = r.integers(0, n, m)
    cols = r.integers(0, n, m)
    return (
        np.concatenate([rows, cols]), np.concatenate([cols, rows])
    )


def _absent_pairs(rows, cols, k, n=N):
    present = set(zip(rows.tolist(), cols.tolist()))
    out = []
    for i in range(n):
        for j in range(i + 1, n):
            if (i, j) not in present and (j, i) not in present:
                out.append((i, j))
                if len(out) >= k:
                    return out
    return out


# --- IPC framing (no processes) ----------------------------------------------


def test_ipc_channel_roundtrip_with_ndarrays():
    a, b = socket.socketpair()
    ca, cb = Channel(a), Channel(b)
    msg = {
        "id": 1, "ok": True,
        "result": {"levels": np.arange(6, dtype=np.int32).reshape(2, 3),
                   "n": np.int64(7), "f": np.float32(0.5)},
    }
    ca.send(msg)
    got = cb.recv(timeout=5)  # arrays rebuilt by decode()
    np.testing.assert_array_equal(
        got["result"]["levels"], np.arange(6).reshape(2, 3)
    )
    assert got["result"]["levels"].dtype == np.int32
    assert got["result"]["n"] == 7
    # a closed peer is a clean ChannelClosed, never a desync
    ca.close()
    with pytest.raises(ChannelClosed):
        cb.recv(timeout=5)
    cb.close()


def test_ipc_sparse_frontier_and_bf16_roundtrip():
    """ISSUE 19: the ``__spf__`` typed envelope round-trips a
    SparseFrontier (dtypes pinned: rows int32, lanes uint8, optional
    vals f32) through the length-prefixed frame codec, the width
    bound is enforced at construction, and the bf16 pack/unpack pair
    is round-to-nearest-even with |err| <= 2^-8 relative."""
    from combblas_tpu.serve.frame import (
        SparseFrontier, pack_bf16, unpack_bf16,
    )

    a, b = socket.socketpair()
    ca, cb = Channel(a), Channel(b)
    sf = SparseFrontier(40, 3, np.array([1, 7, 39]),
                        np.array([0, 2, 1]))
    sfv = SparseFrontier(40, 3, np.array([5]), np.array([1]),
                         np.array([0.25]))
    ca.send({"id": 1, "ok": True, "result": {"xs": sf, "ds": sfv}})
    got = cb.recv(timeout=5)["result"]
    for orig, back in ((sf, got["xs"]), (sfv, got["ds"])):
        assert isinstance(back, SparseFrontier)
        assert (back.n, back.width, back.nnz) == (orig.n, orig.width,
                                                  orig.nnz)
        np.testing.assert_array_equal(back.rows, orig.rows)
        assert back.rows.dtype == np.int32
        np.testing.assert_array_equal(back.lanes, orig.lanes)
        assert back.lanes.dtype == np.uint8
    assert got["xs"].vals is None
    np.testing.assert_array_equal(got["ds"].vals, [0.25])
    assert got["ds"].vals.dtype == np.float32
    # to_dense scatters (row, lane) -> value (row id when vals=None)
    dense = got["xs"].to_dense(np.int32(-1))
    assert dense.shape == (40, 3)
    assert dense[7, 2] == 7 and dense[0, 0] == -1
    assert got["xs"].nbytes() == 3 * (4 + 1)
    ca.close()
    cb.close()
    with pytest.raises(ValueError, match="width"):
        SparseFrontier(10, 257, np.zeros(0), np.zeros(0))
    # bf16: round-to-nearest-even, exact on bf16-representable values
    q = np.array([0.0, 1.0, -2.5, 3.140625, 1e-3, 7e4], np.float32)
    back = unpack_bf16(pack_bf16(q))
    np.testing.assert_array_equal(back[:4], q[:4])  # representable
    assert np.all(np.abs(back - q) <= np.abs(q) * 2.0 ** -8)


def test_ipc_send_survives_reader_poll_timeout():
    """ISSUE 19 (send-stall fix): ``settimeout`` is socket-GLOBAL, so
    a reader thread polling ``recv`` with a short tick must not
    impose that tick on a concurrent send of a frame bigger than the
    kernel socket buffer headed to a peer that is slow to drain (the
    scale-12 boot payload scenario).  The chunked sender keeps
    partial progress across ticks instead of dying with a spurious
    'peer gone: timed out'."""
    a, b = socket.socketpair()
    ca, cb = Channel(a), Channel(b)
    stop = threading.Event()

    def _reader_ticks():
        # the procfleet reader-loop shape: recv with a tiny poll tick,
        # constantly resetting the socket timeout under the sender
        while not stop.is_set():
            try:
                ca.recv(timeout=0.02)
            except socket.timeout:
                continue
            except ChannelClosed:
                return

    t = threading.Thread(target=_reader_ticks, daemon=True)
    t.start()
    big = {"id": 1, "blob": np.arange(1 << 20, dtype=np.int64)}  # 8 MB
    got: dict = {}

    def _slow_drain():
        time.sleep(1.0)  # peer busy "importing its runtime"
        got.update(cb.recv(timeout=30))

    d = threading.Thread(target=_slow_drain, daemon=True)
    d.start()
    ca.send(big)  # old sendall: ChannelClosed within one poll tick
    d.join(timeout=30)
    stop.set()
    assert not d.is_alive()
    np.testing.assert_array_equal(got["blob"], big["blob"])
    ca.close()
    cb.close()
    t.join(timeout=5)


def test_ipc_oversized_frame_refused():
    from combblas_tpu.serve import ipc

    a, b = socket.socketpair()
    ca = Channel(a)
    big = "x" * (ipc.MAX_FRAME + 1)
    with pytest.raises(ValueError, match="too large"):
        ca.send({"blob": big})
    ca.close()
    b.close()


# --- parent-side replica client over a stub responder ------------------------


def _stub_replica(script=None, idx=0, **kw):
    """A ReplicaProc whose 'child' is an in-process responder thread —
    the parent-side bookkeeping (deadline sweep, heartbeat tracking,
    error mapping, quarantine) without spawning an interpreter."""
    a, b = socket.socketpair()
    stop = threading.Event()
    ch_child = Channel(b)

    def responder():
        while not stop.is_set():
            try:
                m = ch_child.recv(timeout=0.05)
            except socket.timeout:
                continue
            except ChannelClosed:
                return
            op = m.get("op")
            if op == "ping":
                ch_child.send({"id": m["id"], "ok": True,
                               "result": {"pong": True}})
            elif op == "hang":
                pass  # never answers: the deadline sweep's case
            elif op == "badroot":
                ch_child.send({"id": m["id"], "ok": False,
                               "etype": "ValueError",
                               "error": "root out of range"})
            elif op == "busy":
                ch_child.send({"id": m["id"], "ok": False,
                               "etype": "BackpressureError",
                               "error": "queue full",
                               "retry_after_s": 0.02})
            elif op == "hb":
                ch_child.send({"hb": {"depth": 3, "serving": True,
                                      "t": time.time()}})

    t = threading.Thread(target=responder, daemon=True)
    t.start()
    rp = ReplicaProc(idx, None, Channel(a), **kw)
    return rp, stop, ch_child


def test_replica_client_rpc_deadline_and_error_mapping():
    rp, stop, _ch = _stub_replica(ipc_timeout_s=30.0)
    try:
        assert rp.call("ping")["pong"] is True
        # per-request deadline: a hung op fails ITS future with the
        # replica-level (read-retried) error — the router never wedges
        f = rp.rpc("hang", timeout_s=0.2)
        with pytest.raises(IpcTimeoutError):
            f.result(timeout=10)
        assert rp.ipc_timeouts == 1
        # child-side taxonomy survives the wire
        with pytest.raises(ValueError):
            rp.rpc("badroot", timeout_s=5).result(timeout=10)
        exc = rp.rpc("busy", timeout_s=5).exception(timeout=10)
        assert isinstance(exc, BackpressureError)
        # heartbeats update the hang detector's clock
        rp.rpc("hb", timeout_s=5)
        t0 = time.monotonic()
        while rp.last_hb.get("depth") != 3:
            assert time.monotonic() - t0 < 5
            time.sleep(0.005)
        assert rp.heartbeat_age() < 5
        assert rp.depth() >= 3  # hb depth counts toward routing load
    finally:
        stop.set()
        rp.quarantine(ReplicaDeadError("teardown"))


def test_replica_client_quarantine_fails_pending_honestly():
    rp, stop, _ch = _stub_replica()
    try:
        f = rp.rpc("hang", timeout_s=60)
        n = rp.quarantine(ReplicaDeadError("replica 0 died"))
        assert n == 1
        assert isinstance(f.exception(timeout=5), ReplicaDeadError)
        assert not rp.is_serving()
        with pytest.raises(ReplicaDeadError):
            rp.rpc("ping")
    finally:
        stop.set()


def test_replica_client_local_backpressure_bound():
    rp, stop, _ch = _stub_replica(max_inflight=2)
    try:
        rp.rpc("hang", timeout_s=60)
        rp.rpc("hang", timeout_s=60)
        with pytest.raises(BackpressureError):
            rp.submit("bfs", 1)
    finally:
        stop.set()
        rp.quarantine(ReplicaDeadError("teardown"))


def test_broken_channel_fails_pending_and_marks_dead():
    rp, stop, ch_child = _stub_replica()
    try:
        f = rp.rpc("hang", timeout_s=60)
        ch_child.close()  # the process died: EOF on the socket
        assert isinstance(f.exception(timeout=10), ReplicaDeadError)
        t0 = time.monotonic()
        while not rp.broken:
            assert time.monotonic() - t0 < 5
            time.sleep(0.005)
        assert not rp.is_serving()
    finally:
        stop.set()


# --- deterministic process fault plan ----------------------------------------


def test_process_fault_plan_is_deterministic():
    plan = ProcessFaultPlan()
    plan.sigkill(2, replica="home").sigstop(4, replica=1)
    fired = []
    for _ in range(6):
        fired.extend(plan.step())
    assert fired == [("SIGKILL", "home"), ("SIGSTOP", 1)]
    assert plan.stats()["calls"] == 6
    assert [f[0] for f in plan.stats()["fired"]] == [2, 4]
    # unarmed plans cost one attribute read and fire nothing
    assert ProcessFaultPlan().step() == []


# --- the tier-1 spawning representative --------------------------------------


def test_single_process_replica_end_to_end(tmp_path):
    """THE fast representative (ISSUE 15 budget satellite): one
    subprocess replica on a 1x1 grid booted from a pre-staged
    checkpoint — reads over IPC, zero post-warmup retraces asserted
    over IPC, a WAL-durable write, heartbeat surfaced in health(),
    deterministic supervise_once, clean close, and crash recovery
    from the files agreeing with the served state."""
    rows, cols = _coo(41)
    grid = Grid.make(1, 1)
    eng = GraphEngine.from_coo(grid, rows, cols, N, kinds=("bfs",),
                               keep_coo=True, headroom=0.5)
    ckpt = str(tmp_path / "boot.npz")
    checkpoint.save_version(ckpt, eng.version)
    wal_dir = str(tmp_path / "wal")
    fr = ProcessFleet.from_checkpoint(
        ckpt, (1, 1), replicas=1, kinds=("bfs",),
        config=ServeConfig(lane_widths=(1, 2), update_flush=1,
                           update_max_delay_s=0.005),
        wal_dir=wal_dir, workdir=str(tmp_path / "proc"),
        hb_interval_s=0.05, hb_timeout_s=5.0,
    )
    try:
        marks = fr.trace_marks()
        # reads route over IPC and answer exactly like the donor
        lev = fr.submit("bfs", 3).result(timeout=60)["levels"]
        ref = eng.execute("bfs", np.asarray([3], np.int32))["levels"]
        np.testing.assert_array_equal(
            np.asarray(lev), np.asarray(ref)[:, 0]  # lane 0 = root 3
        )
        # zero post-warmup retraces IN THE CHILD, asserted over IPC
        # (the shared plan store + boot warmup claim)
        assert fr.retraces_since(marks) == 0
        # a write is WAL-durable before its future resolves; headroom
        # keeps the merge incremental so plans survive
        (a, b), (a2, b2) = _absent_pairs(rows, cols, 2)
        res = fr.submit_update(
            [("insert", a, b), ("insert", b, a)]
        ).result(timeout=60)
        assert res["ops"] == 2 and res["lagging"] == []
        lev = fr.submit("bfs", a).result(timeout=60)["levels"]
        assert np.asarray(lev)[b] == 1
        # heartbeat liveness is a first-class health fact
        h = fr.health()
        assert h["status"] == "ok" and h["durable"]
        assert h["replicas"][0]["heartbeat_age_s"] < 5.0
        assert h["replicas"][0]["pid"] == fr.replicas[0].proc.pid
        # nothing to heal: the deterministic supervision pass is a
        # no-op on a healthy fleet
        assert fr.supervise_once() == {
            "detected": [], "promoted": None, "replaced": [],
        }
        # close-race regression (round-17 review): a write racing
        # close(drain=True) must SETTLE — merged+durable on the home,
        # fanned or honestly un-fanned — never strand against the
        # shut-down fan executor
        late = fr.submit_update([("insert", a2, b2),
                                 ("insert", b2, a2)])
    finally:
        fr.close(drain=True)
    assert late.result(timeout=60)["ops"] == 2
    # the subprocess exited cleanly and the durable files recover the
    # exact served state (acknowledged write included)
    assert fr.replicas[0].proc.poll() is not None
    wal = open_wal(wal_dir)
    v = recover_version(wal_dir, wal, grid, kinds=("bfs",))
    wal.close()
    rr, rc, _ = v.E.to_host_coo()
    assert (a, b) in set(zip(rr.tolist(), rc.tolist()))


# --- fleet observability plane (round 18, ISSUE 16) --------------------------


def test_fleet_observability_plane_end_to_end(tmp_path):
    """ISSUE 16 acceptance: over a REAL 2-replica subprocess fleet,
    one sampled request yields ONE stitched trace whose router + IPC +
    child stage marks telescope exactly to the trace wall (two
    processes, one clock-skew-safe timeline); heartbeat-piggybacked
    child snapshots federate into one ``/metrics`` scrape with
    ``replica=`` labels; and the supervision timeline records the
    spawns as validated ``fleetlog/v1`` JSONL.  The only spawning
    round-18 test — everything else in the plane is stub-covered
    (test_obs.py / test_obs_serve.py)."""
    import urllib.request

    from combblas_tpu import obs
    from combblas_tpu.obs import export as obs_export
    from combblas_tpu.obs import trace as obs_trace

    rows, cols = _coo(41)
    grid = Grid.make(1, 1)
    eng = GraphEngine.from_coo(grid, rows, cols, N, kinds=("bfs",),
                               keep_coo=True)
    ckpt = str(tmp_path / "boot.npz")
    checkpoint.save_version(ckpt, eng.version)
    obs.enable(install_hooks=False)
    obs_trace.set_sample_rate(1.0)
    fr = None
    try:
        fr = ProcessFleet.from_checkpoint(
            ckpt, (1, 1), replicas=2, kinds=("bfs",),
            config=ServeConfig(lane_widths=(1, 2)),
            wal_dir=str(tmp_path / "wal"),
            workdir=str(tmp_path / "proc"),
            hb_interval_s=0.05, hb_timeout_s=5.0,
            metrics_interval_s=0.05,
        )
        t0 = time.perf_counter()
        lev = fr.submit("bfs", 3).result(timeout=60)["levels"]
        e2e = time.perf_counter() - t0
        ref = eng.execute("bfs", np.asarray([3], np.int32))["levels"]
        np.testing.assert_array_equal(
            np.asarray(lev), np.asarray(ref)[:, 0]
        )
        # ONE stitched trace: router marks + child marks, one record
        stitched = [r for r in obs_trace.records()
                    if r["labels"].get("fleet") == "process"]
        assert len(stitched) == 1
        (rec,) = stitched
        stages = [s["stage"] for s in rec["stages"]]
        assert stages[:2] == ["route", "ipc_send"]  # router-side
        assert stages[-1] == "ipc_recv"
        for child_stage in ("queue_wait", "assemble", "execute",
                            "scatter"):
            assert child_stage in stages  # shipped back over IPC
        assert "ipc_wait" in stages  # the residual the child can't see
        # the telescoping invariant ACROSS the process boundary: the
        # child contributes durations only, scaled into the router's
        # observed window, so the stages sum to the wall exactly
        assert sum(s["s"] for s in rec["stages"]) == pytest.approx(
            rec["wall_s"], abs=1e-6
        )
        assert rec["wall_s"] <= e2e + 0.05
        assert rec["labels"]["replica"] in (0, 1)
        assert rec["labels"]["kind"] == "bfs"
        # metrics federation: both children piggyback registry
        # snapshots on their heartbeats...
        deadline = time.time() + 10
        while time.time() < deadline and not all(
            rp.last_metrics for rp in fr.replicas
        ):
            time.sleep(0.02)
        assert all(rp.last_metrics for rp in fr.replicas)
        fr.supervise_once()  # tick emits the heartbeat-age gauges
        # ...and ONE scrape serves the whole fleet, replica-labeled
        port = fr.serve_metrics()
        base = f"http://127.0.0.1:{port}"
        text = urllib.request.urlopen(
            f"{base}/metrics", timeout=10
        ).read().decode()
        parsed = obs_export.parse_exposition(text)
        child_reqs = [
            k for k in parsed
            if k[0] == "combblas_serve_requests" and 'replica="' in k[1]
        ]
        assert child_reqs  # child-process counters, federated
        assert any(
            k[0] == "combblas_serve_procfleet_heartbeat_age_s"
            for k in parsed
        )
        hz = json.loads(urllib.request.urlopen(
            f"{base}/healthz", timeout=10
        ).read())
        assert hz["status"] == "ok"
        sz = json.loads(urllib.request.urlopen(
            f"{base}/statz", timeout=10
        ).read())
        assert sz["fleetlog"]["recorded"] >= 2
        # supervision timeline: both spawns recorded, schema-valid
        logged = obs.parse_jsonl(fr.fleetlog.path)
        assert logged[0]["schema"] == obs.FLEETLOG_SCHEMA
        spawns = [r for r in logged if r.get("name") == "fleet.spawn"]
        assert sorted(r["replica"] for r in spawns) == [0, 1]
        assert all(r["pid"] > 0 for r in spawns)
    finally:
        if fr is not None:
            fr.close(drain=True)
        obs_trace.set_sample_rate(None)
        obs_trace.clear()
        obs.disable()
        obs.reset()
    assert fr._scrape is None  # close() stops the scrape thread


# --- real-signal chaos (slow; BENCH_FLEET=process is the measured twin) ------


@pytest.mark.slow
@pytest.mark.chaos
def test_sigkill_and_sigstop_chaos_heals(tmp_path):
    """Real crash domains: SIGKILL a non-home replica (respawn from
    checkpoint+WAL serves every acknowledged write), then SIGSTOP the
    home — a HANG, not a death: heartbeat timeout detects it, its
    in-flight futures fail honestly instead of wedging the router,
    and promotion at the WAL frontier moves the write lane to a
    survivor.  The tier-1 representative of the spawn/IPC/supervise
    path is ``test_single_process_replica_end_to_end``."""
    rows, cols = _coo(42)
    fr = ProcessFleet.build(
        (1, 1), rows, cols, N, replicas=3, kinds=("bfs",),
        config=ServeConfig(lane_widths=(1, 2), update_flush=1,
                           update_max_delay_s=0.005),
        wal_dir=str(tmp_path / "wal"),
        workdir=str(tmp_path / "proc"),
        hb_interval_s=0.1, hb_timeout_s=1.5,
        from_coo_kw={"headroom": 0.5},
    )
    try:
        pairs = _absent_pairs(rows, cols, 2)
        (a0, b0), (a1, b1) = pairs
        fr.submit_update(
            [("insert", a0, b0), ("insert", b0, a0)]
        ).result(timeout=60)

        # -- SIGKILL a non-home replica: crash detection + respawn
        victim = (fr.home + 1) % 3
        os.kill(fr.replicas[victim].proc.pid, signal.SIGKILL)
        t0 = time.monotonic()
        while not fr._dead(victim):
            assert time.monotonic() - t0 < 10
            time.sleep(0.02)
        out = fr.supervise_once()
        assert victim in out["replaced"]
        lev = fr.replicas[victim].submit(
            "bfs", a0
        ).result(timeout=60)["levels"]
        assert np.asarray(lev)[b0] == 1  # acked write survived SIGKILL

        # -- SIGSTOP the home: hang detection via heartbeat timeout
        home0 = fr.home
        os.kill(fr.replicas[home0].proc.pid, signal.SIGSTOP)
        stuck = fr.replicas[home0].submit("bfs", a0)  # in-flight
        t0 = time.monotonic()
        while not fr._dead(home0):
            assert time.monotonic() - t0 < 15
            time.sleep(0.02)
        out = fr.supervise_once()
        assert out["promoted"] is not None and fr.home != home0
        # honest failure, not a wedge: the stopped replica's future
        assert isinstance(stuck.exception(timeout=30),
                          (ReplicaDeadError, IpcTimeoutError))
        # routed reads keep serving throughout
        for _ in range(4):
            assert fr.submit("bfs", a0).result(timeout=60) is not None
        # the write lane continues on the promoted lineage, fleet-wide
        res = fr.submit_update(
            [("insert", a1, b1), ("insert", b1, a1)]
        ).result(timeout=60)
        assert res["fanned_out"] == 2 and res["lagging"] == []
        for rp in fr.replicas:
            lev = rp.submit("bfs", a1).result(timeout=60)["levels"]
            assert np.asarray(lev)[b1] == 1
        st = fr.stats()
        assert st["promotions"] == 1 and st["replacements"] == 2
        assert fr.health()["status"] == "ok"
    finally:
        fr.close(drain=False)


@pytest.mark.slow
@pytest.mark.chaos
def test_scripted_fault_plan_kills_through_router(tmp_path):
    """``ProcessFaultPlan`` fires real signals at scripted routed-
    submit indices (deterministic chaos, the FaultInjector philosophy
    at the process level) while the supervisor heals in the
    background — availability holds and every routed read settles."""
    rows, cols = _coo(43)
    fr = ProcessFleet.build(
        (1, 1), rows, cols, N, replicas=2, kinds=("bfs",),
        config=ServeConfig(lane_widths=(1, 2)),
        wal_dir=str(tmp_path / "wal"),
        workdir=str(tmp_path / "proc"),
        hb_interval_s=0.1, hb_timeout_s=1.5,
    )
    try:
        fr.start_supervisor(interval_s=0.05)
        fr.proc_faults.sigkill(5, replica=(fr.home + 1) % 2)
        ok = bad = 0
        for i in range(30):
            try:
                fr.submit("bfs", int(rows[i % len(rows)])).result(
                    timeout=60
                )
                ok += 1
            except Exception:
                bad += 1
        assert fr.sigkills == 1
        assert ok / (ok + bad) >= 0.9
        # wait for the supervisor to heal the kill before closing
        deadline = time.monotonic() + 30
        while (
            fr._needs_rebuild or any(fr._dead(i) for i in range(2))
        ) and time.monotonic() < deadline:
            time.sleep(0.05)
        assert fr.replacements >= 1
    finally:
        fr.close(drain=False)
