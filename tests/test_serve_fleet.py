"""Replicated serving fleet + GraphVersion checkpoints (round 14):
least-loaded routing with spillover, home-replica writes fanned out
through the atomic swap, one shared warm plan store, and the
``save_version``/``load_version`` zero-retrace warm start.

Tier-1 tests are small and pump/worker-deterministic; the threaded
mixed read/write fleet soak is ``slow``.
"""

import os

import numpy as np
import pytest

from combblas_tpu.parallel.grid import Grid
from combblas_tpu.serve import (
    BackpressureError,
    FleetRouter,
    GraphEngine,
    ServeConfig,
)
from combblas_tpu.tuner import config as tuner_config
from combblas_tpu.tuner import store as tstore
from combblas_tpu.utils import checkpoint

N = 64


def _coo(seed, n=N, m=300):
    r = np.random.default_rng(seed)
    rows = r.integers(0, n, m)
    cols = r.integers(0, n, m)
    return (
        np.concatenate([rows, cols]), np.concatenate([cols, rows])
    )


@pytest.fixture(scope="module")
def grid():
    return Grid.make(2, 4)


@pytest.fixture(autouse=True)
def _fresh_store_singleton():
    tstore._reset_for_tests()
    yield
    tstore._reset_for_tests()


# --- checkpoint round-trip ---------------------------------------------------


def test_checkpoint_roundtrip_bit_identical_and_zero_retrace(
    grid, tmp_path
):
    """The ISSUE-12 regression: ``load_version`` -> ``swap`` -> warmed
    kinds produce ZERO retraces, with every bucket array (including
    the headroom-resolved padding rows) bit-identical to the saved
    version."""
    rows, cols = _coo(3)
    eng = GraphEngine.from_coo(
        grid, rows, cols, N, kinds=("bfs", "pagerank"),
        keep_coo=True, headroom=0.5,
    )
    eng.warmup(widths=(1, 4))
    path = os.path.join(tmp_path, "v.npz")
    checkpoint.save_version(path, eng.version)
    v2 = checkpoint.load_version(path, grid)

    # shapes/dtypes/values bit-identical, headroom included
    assert v2.headroom == eng.version.headroom == 0.5
    for nm in ("E", "P_ell"):
        M1, M2 = getattr(eng.version, nm), getattr(v2, nm)
        assert len(M1.buckets) == len(M2.buckets)
        for b1, b2 in zip(M1.buckets, M2.buckets):
            for a1, a2 in zip(b1, b2):
                assert a1.shape == a2.shape
                assert a1.dtype == a2.dtype
                np.testing.assert_array_equal(
                    np.asarray(a1), np.asarray(a2)
                )
    np.testing.assert_array_equal(
        np.asarray(eng.version.dangling.blocks),
        np.asarray(v2.dangling.blocks),
    )
    # the host COO rode along (the write lane stays available)
    assert v2.host_coo is not None

    mark = eng.trace_mark()
    eng.swap(v2)
    r1 = eng.execute("bfs", np.asarray([3], np.int32))
    eng.execute("pagerank", np.asarray([3, 4, 5, 6], np.int32))
    assert eng.retraces_since(mark) == 0  # the warm-start guarantee
    # and a FRESH engine built on the snapshot answers identically
    eng3 = GraphEngine(grid, version=checkpoint.load_version(path, grid),
                       kinds=("bfs", "pagerank"))
    r3 = eng3.execute("bfs", np.asarray([3], np.int32))
    np.testing.assert_array_equal(r1["levels"], r3["levels"])


def test_checkpoint_guards(grid, tmp_path):
    rows, cols = _coo(4)
    eng = GraphEngine.from_coo(grid, rows, cols, N, kinds=("bfs",))
    path = os.path.join(tmp_path, "v.npz")
    checkpoint.save_version(path, eng.version)
    # cross-grid restore is refused (re-bucketing would forfeit the
    # bit-identical shapes the zero-retrace guarantee needs)
    with pytest.raises(ValueError, match="SAME grid shape"):
        checkpoint.load_version(path, Grid.make(1, 1))
    # a non-version npz is refused by schema, never guessed at
    other = os.path.join(tmp_path, "other.npz")
    checkpoint.save(other, _spmat(grid))
    with pytest.raises(ValueError, match="GraphVersion"):
        checkpoint.load_version(other, grid)


def _spmat(grid):
    from combblas_tpu.parallel.spmat import SpParMat

    r = np.arange(8) % 4
    return SpParMat.from_global_coo(
        grid, r, r, np.ones(8, np.float32), 8, 8
    )


# --- routing + spillover -----------------------------------------------------


def test_fleet_routes_least_loaded_and_spills(grid):
    """Queries spread over replicas; when one replica's queue is full
    the router SPILLS to the next, and only a fleet-wide full raises
    (the last replica's tenant-named error)."""
    rows, cols = _coo(5)
    cfg = ServeConfig(lane_widths=(1, 2), max_queue=2,
                      max_wait_s=30.0)
    fr = FleetRouter.build(
        grid, rows, cols, N, replicas=2, config=cfg, kinds=("bfs",),
        start=False,  # worker-less: queues fill deterministically
    )
    futs = [fr.submit("bfs", 1) for _ in range(4)]  # 2 per replica
    assert all(
        s.scheduler.depth() == 2 for s in fr.replicas
    )
    with pytest.raises(BackpressureError):
        fr.submit("bfs", 1)
    assert fr.spillovers >= 1
    assert sum(fr.submitted) == 4
    # submit_many: rejected roots fail their OWN futures, no strand
    many = fr.submit_many("bfs", [1, 2])
    assert all(
        isinstance(f.exception(timeout=0), BackpressureError)
        for f in many
    )
    for s in fr.replicas:
        s.scheduler.fail_pending(RuntimeError("teardown"))
    del futs


def test_fleet_write_home_and_fanout(grid):
    """A write routes to the HOME replica; after its merge the new
    version fans out through the atomic swap, so a query about the
    new edge answers correctly on EVERY replica."""
    rows, cols = _coo(6)
    cfg = ServeConfig(lane_widths=(1, 2), update_flush=1,
                      update_max_delay_s=0.005)
    with FleetRouter.build(
        grid, rows, cols, N, replicas=2, config=cfg, kinds=("bfs",),
    ) as fr:
        fr.warmup(widths=(1, 2))
        # pick an edge absent everywhere
        present = set(zip(*map(np.ndarray.tolist, (rows, cols))))
        a, b = next(
            (i, j) for i in range(N) for j in range(N)
            if i != j and (i, j) not in present
            and (j, i) not in present
        )
        vids = [s.engine.version_id for s in fr.replicas]
        res = fr.submit_update(
            [("insert", a, b), ("insert", b, a)]
        ).result(timeout=120)
        assert res["fanned_out"] == 1
        for s, v0 in zip(fr.replicas, vids):
            assert s.engine.version_id == v0 + 1
        # the new edge is visible on BOTH replicas: b is exactly one
        # hop from a (query each replica directly, bypassing routing)
        for s in fr.replicas:
            lev = s.submit("bfs", a).result(timeout=120)["levels"]
            assert lev[b] == 1
    assert fr.fanouts == 1


# --- shared warm plan store --------------------------------------------------


def test_fleet_cold_vs_warm_replica_ab(grid, tmp_path, monkeypatch):
    """The fleet A/B: replica 1's traffic records its lanes in the
    SHARED plan store; a cold replica serving the same lane retraces,
    while a warm-started replica (fresh store load + ``warmup()``)
    reaches zero-retrace steady state before its first request."""
    monkeypatch.setenv(tuner_config.ENV_PLAN_STORE, str(tmp_path))
    tstore._reset_for_tests()
    rows, cols = _coo(7)

    def build():
        return GraphEngine.from_coo(grid, rows, cols, N, kinds=("bfs",))

    donor = build()
    donor.plan("bfs", 4)  # the traffic mix's lane, recorded

    # COLD replica: no warmup — first width-4 batch must trace
    cold = build()
    mark = cold.trace_mark()
    cold.execute("bfs", np.full(4, -1, np.int32))
    assert cold.retraces_since(mark) > 0

    # WARM replica: a fresh process (new store instance) replays the
    # remembered lane during warmup -> zero retraces at steady state
    tstore._reset_for_tests()
    warm = build()
    warmed = warm.warmup()
    assert ("bfs", 4) in warmed
    mark = warm.trace_mark()
    warm.execute("bfs", np.full(4, -1, np.int32))
    assert warm.retraces_since(mark) == 0


# --- threaded soak -----------------------------------------------------------


@pytest.mark.slow
def test_fleet_threaded_reads_under_writes(grid):
    """Mixed fleet load: reads spread over both replicas while writes
    stream through the home replica and fan out — every read settles,
    every write lands fleet-wide, no stranded futures."""
    import threading

    rows, cols = _coo(8)
    cfg = ServeConfig(lane_widths=(1, 2, 4), max_queue=256,
                      max_wait_s=0.005, update_flush=2,
                      update_max_delay_s=0.01)
    with FleetRouter.build(
        grid, rows, cols, N, replicas=2, config=cfg, kinds=("bfs",),
    ) as fr:
        fr.warmup(widths=(1, 2, 4))
        write_futs = []

        def writer():
            for k in range(6):
                a, b = 1 + k, 40 + k
                write_futs.append(fr.submit_update(
                    [("insert", a, b), ("insert", b, a)]
                ))

        wt = threading.Thread(target=writer)
        wt.start()
        read_futs = []
        for i in range(60):
            try:
                read_futs.append(fr.submit("bfs", i % N))
            except BackpressureError:
                pass
        wt.join(60)
        assert read_futs
        for f in read_futs:
            assert f.result(timeout=120) is not None
        for f in write_futs:
            assert f.result(timeout=120)["fanned_out"] == 1
    st = fr.stats()
    assert st["fanouts"] == len(write_futs)
    assert sum(st["routed"]) == len(read_futs)
