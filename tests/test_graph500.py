"""Kernel-1 (distributed graph construction) — device pipeline vs host.

Reference pipeline: SpParMat Graph500 ctor (SpParMat.cpp:3140-3441) +
DistEdgeList PermEdges/RenameVertices (DistEdgeList.cpp).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from combblas_tpu.models.graph500 import (
    isolated_compression_perm,
    kernel1_device,
    permute_vertices,
)
from combblas_tpu.parallel.grid import Grid
from combblas_tpu.parallel.spmat import SpParMat
from combblas_tpu.parallel.vec import DistVec

def test_permute_vertices_matches_dense(rng):
    grid = Grid.make(2, 2)
    n = 24
    d = (rng.random((n, n)) < 0.2).astype(np.float32)
    A = SpParMat.from_dense(grid, d)
    p = DistVec.randperm(grid, n, jax.random.key(3))
    Ap = permute_vertices(A, p)
    pg = np.asarray(p.to_global())
    expect = np.zeros_like(d)
    expect[np.ix_(pg, pg)] = d  # expect[p[i], p[j]] = d[i, j]
    np.testing.assert_allclose(Ap.to_dense(), expect)


def test_isolated_compression(rng):
    grid = Grid.make(2, 2)
    n = 16
    d = np.zeros((n, n), np.float32)
    # vertices 2, 5, 9 form a triangle; the rest are isolated
    live = [2, 5, 9]
    for a in live:
        for b in live:
            if a != b:
                d[a, b] = 1.0
    A = SpParMat.from_dense(grid, d)
    p, nkeep = isolated_compression_perm(A)
    assert int(nkeep) == 3
    pg = np.asarray(p.to_global())
    # live vertices occupy the prefix, order preserved
    assert sorted(pg[live]) == [0, 1, 2]
    assert sorted(pg.tolist()) == list(range(n))
    Ac = permute_vertices(A, p)
    dc = np.asarray(Ac.to_dense())
    assert (dc[3:, :] == 0).all() and (dc[:, 3:] == 0).all()
    assert (dc[:3, :3].sum()) == d.sum()


@pytest.mark.parametrize("grid_shape", [
    # 1x1 is slow-lane (round 12, tier-1 budget): kernel1_device is the
    # DISTRIBUTED pipeline — the 2x2 case is the one that matters
    pytest.param((1, 1), marks=pytest.mark.slow),
    (2, 2),
])
def test_kernel1_device_matches_host(grid_shape):
    """Device kernel-1 builds the same graph the host path builds
    (same edge multiset after dedup, modulo the isolated-compression
    relabel, which preserves the degree multiset)."""
    from combblas_tpu.utils.rmat import rmat_edges

    grid = Grid.make(*grid_shape)
    scale, ef = 7, 8
    n = 1 << scale
    key = jax.random.key(11)
    A, degrees, nkeep, timings = kernel1_device(grid, scale, ef, key)

    # host reference from the same generator stream
    src, dst = (np.asarray(x) for x in rmat_edges(key, scale, ef * n))
    keep = src != dst
    r = np.concatenate([src[keep], dst[keep]])
    c = np.concatenate([dst[keep], src[keep]])
    uniq = np.unique(r.astype(np.int64) * n + c)
    hr, hc = uniq // n, uniq % n
    hdeg = np.bincount(hr, minlength=n)

    # kernel1_device defers its routing-capacity drop check (axon D2H
    # rule); a caller that skips it would silently lose edges (ADVICE r4)
    assert int(np.asarray(timings["dropped_dev"])) == 0
    assert int(np.asarray(A.getnnz())) == len(uniq)
    assert int(nkeep) == int((hdeg > 0).sum())
    # degree multiset is relabel-invariant
    ddeg = np.asarray(degrees.to_global()).astype(np.int64)
    np.testing.assert_array_equal(np.sort(ddeg), np.sort(hdeg))
    # non-isolated prefix: all edges land inside [0, nkeep)
    rr, cc, _ = A.to_global_coo()
    assert np.asarray(rr).max() < int(nkeep)
    assert np.asarray(cc).max() < int(nkeep)
    assert set(timings) >= {"generate_s", "route_dedup_s", "degree_s"}


def test_kernel1_extra_relabel_isomorphic():
    grid = Grid.make(2, 2)
    scale, ef = 6, 8
    key = jax.random.key(5)
    A1, deg1, nk1, t1 = kernel1_device(grid, scale, ef, key)
    A2, deg2, nk2, t2 = kernel1_device(grid, scale, ef, key, extra_relabel=True)
    assert int(np.asarray(t1["dropped_dev"])) == 0
    assert int(np.asarray(t2["dropped_dev"])) == 0
    assert int(nk1) == int(nk2)
    assert int(np.asarray(A1.getnnz())) == int(np.asarray(A2.getnnz()))
    np.testing.assert_array_equal(
        np.sort(np.asarray(deg1.to_global())),
        np.sort(np.asarray(deg2.to_global())),
    )
