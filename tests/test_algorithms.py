"""Algorithm pack 1: CC (FastSV), SSSP, PageRank, TC, MIS vs trusted refs.

Mirrors the reference's self-checking app-test pattern (SURVEY.md §4.3):
random/er inputs, results validated against an independent implementation
(scipy.sparse.csgraph / dense numpy) instead of golden files.
"""

import numpy as np
import pytest
import scipy.sparse as sp
import scipy.sparse.csgraph as csgraph

from combblas_tpu.parallel.grid import Grid
from combblas_tpu.parallel.spmat import SpParMat
from combblas_tpu.parallel.vec import DistVec
from combblas_tpu.semiring import MIN_PLUS, PLUS_TIMES, SELECT2ND_MIN


def sym_graph(rng, n, density=0.05, weighted=False):
    """Random symmetric loop-free graph as (dense, rows, cols, vals)."""
    d = (rng.random((n, n)) < density).astype(np.float32)
    if weighted:
        d *= np.round(rng.random((n, n)) * 9 + 1).astype(np.float32)
    d = np.triu(d, 1)
    d = d + d.T
    r, c = np.nonzero(d)
    return d, r, c, d[r, c]


@pytest.mark.parametrize("pr,pc", [(2, 2), (2, 4)])
def test_connected_components_vs_scipy(rng, pr, pc):
    from combblas_tpu.models.cc import connected_components, num_components

    grid = Grid.make(pr, pc)
    n = 60
    # sparse enough to have several components
    d, r, c, v = sym_graph(rng, n, density=0.02)
    A = SpParMat.from_global_coo(grid, r, c, v, n, n, dedup_sr=PLUS_TIMES)
    labels, niter = connected_components(A)
    lab = labels.to_global()

    ncomp_ref, lab_ref = csgraph.connected_components(
        sp.csr_matrix(d), directed=False
    )
    assert num_components(labels) == ncomp_ref
    # same partition: our labels constant on each reference component
    for comp in range(ncomp_ref):
        assert len(np.unique(lab[lab_ref == comp])) == 1
    # label = min vertex id of the component
    for comp in range(ncomp_ref):
        members = np.flatnonzero(lab_ref == comp)
        assert lab[members[0]] == members.min()


def test_cc_all_isolated(rng):
    from combblas_tpu.models.cc import connected_components

    grid = Grid.make(2, 2)
    n = 16
    # single undirected edge {0,1} (stored symmetrically), rest isolated
    A = SpParMat.from_global_coo(grid, [0, 1], [1, 0], [1.0, 1.0], n, n)
    labels, _ = connected_components(A)
    lab = labels.to_global()
    assert lab[0] == lab[1] == 0
    assert all(lab[i] == i for i in range(2, n))


@pytest.mark.parametrize("pr,pc", [(2, 2)])
def test_sssp_vs_scipy(rng, pr, pc):
    from combblas_tpu.models.sssp import sssp

    grid = Grid.make(pr, pc)
    n = 50
    d, r, c, v = sym_graph(rng, n, density=0.08, weighted=True)
    A = SpParMat.from_global_coo(grid, r, c, v, n, n, dedup_sr=MIN_PLUS)
    dist, niter = sssp(A, 0)
    got = dist.to_global()

    ref = csgraph.dijkstra(sp.csr_matrix(d), directed=False, indices=0)
    np.testing.assert_allclose(got, ref.astype(np.float32), rtol=1e-6)


def test_sssp_directed_line():
    from combblas_tpu.models.sssp import sssp

    grid = Grid.make(2, 2)
    n = 8
    # path 0 -> 1 -> 2 -> 3 with weights 1,2,3; A[i,j] = w(j->i)
    r = np.array([1, 2, 3])
    c = np.array([0, 1, 2])
    v = np.array([1.0, 2.0, 3.0], np.float32)
    A = SpParMat.from_global_coo(grid, r, c, v, n, n)
    dist, _ = sssp(A, 0)
    got = dist.to_global()
    assert got[0] == 0 and got[1] == 1 and got[2] == 3 and got[3] == 6
    assert np.isinf(got[4:]).all() or (got[4:] >= np.finfo(np.float32).max).all()


@pytest.mark.parametrize("pr,pc", [(2, 2), (4, 2)])
def test_pagerank_vs_dense(rng, pr, pc):
    from combblas_tpu.models.pagerank import pagerank

    grid = Grid.make(pr, pc)
    n = 40
    # directed graph with some dangling nodes
    d = (rng.random((n, n)) < 0.06).astype(np.float32)
    np.fill_diagonal(d, 0)
    d[:, -3:] = 0  # dangling columns
    r, c = np.nonzero(d)
    A = SpParMat.from_global_coo(grid, r, c, d[r, c], n, n)
    ranks, niter = pagerank(A, alpha=0.85, tol=1e-10, max_iters=200)
    got = ranks.to_global()

    # dense reference power iteration
    outdeg = d.sum(axis=0)
    P = np.divide(d, outdeg, where=outdeg > 0, out=np.zeros_like(d))
    x = np.full(n, 1.0 / n)
    for _ in range(200):
        dmass = x[outdeg == 0].sum()
        x_new = 0.85 * (P @ x) + (0.15 + 0.85 * dmass) / n
        if np.abs(x_new - x).sum() < 1e-12:
            x = x_new
            break
        x = x_new
    np.testing.assert_allclose(got, x, atol=1e-5)


def test_pagerank_batch_personalized_vs_dense(rng):
    """W personalized-PageRank chains in one program vs a dense reference
    per source."""
    import jax.numpy as jnp

    from combblas_tpu.models.pagerank import pagerank_batch
    from combblas_tpu.parallel.ellmat import EllParMat
    from combblas_tpu.parallel.vec import DistVec

    grid = Grid.make(2, 2)
    n = 40
    d = (rng.random((n, n)) < 0.08).astype(np.float32)
    np.fill_diagonal(d, 0)
    d[:, -3:] = 0  # dangling columns
    r, c = np.nonzero(d)
    outdeg = d.sum(axis=0)
    vals = 1.0 / outdeg[c]  # column-normalized host-side
    P_ell = EllParMat.from_host_coo(
        grid, r.astype(np.int64), c.astype(np.int64),
        vals.astype(np.float32), n, n,
    )
    dang = DistVec.from_global(
        grid, (outdeg == 0).astype(np.float32), align="col"
    )
    sources = jnp.asarray([0, 7, 19, 33], jnp.int32)
    ranks, niter = pagerank_batch(
        P_ell, sources, dang, alpha=0.85, tol=1e-10, max_iters=300
    )
    got = ranks.to_global()  # [n, W]
    assert int(niter) > 1

    P = np.divide(d, outdeg, where=outdeg > 0, out=np.zeros_like(d))
    for w, s in enumerate([0, 7, 19, 33]):
        e = np.zeros(n)
        e[s] = 1.0
        x = e.copy()
        for _ in range(300):
            dmass = x[outdeg == 0].sum()
            x_new = 0.85 * (P @ x + dmass * e) + 0.15 * e
            if np.abs(x_new - x).sum() < 1e-12:
                break
            x = x_new
        np.testing.assert_allclose(got[:, w], x, atol=1e-5)
        assert abs(got[:, w].sum() - 1.0) < 1e-4


@pytest.mark.parametrize("pr,pc", [(2, 2)])
def test_triangle_count_vs_dense(rng, pr, pc):
    from combblas_tpu.models.tc import triangle_count

    grid = Grid.make(pr, pc)
    n = 40
    d, r, c, v = sym_graph(rng, n, density=0.15)
    A = SpParMat.from_global_coo(grid, r, c, v, n, n, dedup_sr=PLUS_TIMES)
    got = triangle_count(A)
    b = (d != 0).astype(np.int64)
    ref = int(np.trace(b @ b @ b) // 6)
    assert got == ref
    assert ref > 0  # density chosen so the test is non-vacuous


def test_triangle_count_known():
    from combblas_tpu.models.tc import triangle_count

    grid = Grid.make(2, 2)
    # K4 has 4 triangles
    n = 6
    d = np.zeros((n, n), np.float32)
    d[:4, :4] = 1 - np.eye(4)
    r, c = np.nonzero(d)
    A = SpParMat.from_global_coo(grid, r, c, d[r, c], n, n)
    assert triangle_count(A) == 4


@pytest.mark.parametrize("pr,pc", [(2, 2), (2, 4)])
def test_mis_independent_and_maximal(rng, pr, pc):
    import jax

    from combblas_tpu.models.mis import mis

    grid = Grid.make(pr, pc)
    n = 60
    d, r, c, v = sym_graph(rng, n, density=0.08)
    A = SpParMat.from_global_coo(grid, r, c, v, n, n, dedup_sr=PLUS_TIMES)
    status, niter = mis(A, jax.random.key(3))
    s = status.to_global()
    in_set = np.flatnonzero(s == 1)
    assert in_set.size > 0
    # independence: no edge inside the set
    assert d[np.ix_(in_set, in_set)].sum() == 0
    # maximality: every excluded vertex has a neighbor in the set
    excluded = np.flatnonzero(s == -1)
    for v_ in excluded:
        assert d[v_, in_set].sum() > 0, f"vertex {v_} has no MIS neighbor"


def test_gather_scatter_roundtrip(rng):
    grid = Grid.make(2, 2)
    n = 23
    x = DistVec.from_global(grid, np.arange(100, 100 + n, dtype=np.int32))
    idx = DistVec.from_global(
        grid, rng.integers(0, n, size=n).astype(np.int32)
    )
    g = x.gather(idx)
    np.testing.assert_array_equal(
        g.to_global(), (np.arange(100, 100 + n))[idx.to_global()]
    )

    # scatter-min: out[p] = min(base[p], min of src where idx==p)
    base = DistVec.from_global(grid, np.full(n, 1000, np.int32))
    src = DistVec.from_global(grid, np.arange(n, dtype=np.int32))
    out = base.scatter_combine(SELECT2ND_MIN, idx=idx, src=src)
    ref = np.full(n, 1000, np.int64)
    np.minimum.at(ref, idx.to_global(), np.arange(n))
    np.testing.assert_array_equal(out.to_global(), ref.astype(np.int32))


def test_tril_triu_remove_loops(rng):
    grid = Grid.make(2, 2)
    n = 17
    d = (rng.random((n, n)) < 0.3).astype(np.float32)
    r, c = np.nonzero(d)
    A = SpParMat.from_global_coo(grid, r, c, d[r, c], n, n)
    np.testing.assert_array_equal(A.tril().to_dense(), np.tril(d, -1))
    np.testing.assert_array_equal(A.triu().to_dense(), np.triu(d, 1))
    np.testing.assert_array_equal(
        A.tril(strict=False).to_dense(), np.tril(d)
    )
    nl = A.remove_loops().to_dense()
    ref = d.copy()
    np.fill_diagonal(ref, 0)
    np.testing.assert_array_equal(nl, ref)


@pytest.mark.parametrize("shape", [(2, 2), (2, 4)])
def test_lacc_matches_fastsv(rng, shape):
    """LACC (real implementation) labels the same partition as FastSV on
    random graphs including isolated vertices (the reference's ctest
    equivalence role for CC algorithms)."""
    from combblas_tpu.models.cc import connected_components, lacc

    grid = Grid.make(*shape)
    n = 40
    d = (rng.random((n, n)) < 0.06)
    d = (d | d.T).astype(np.float32)
    np.fill_diagonal(d, 0)
    d[:, 7] = 0; d[7, :] = 0  # force an isolated vertex
    A = SpParMat.from_dense(grid, d)
    l1, _ = connected_components(A)
    l2, _ = lacc(A)
    a = l1.to_global()
    b = l2.to_global()
    # same partition: labels equal up to renaming — both use min-id roots,
    # but compare as partitions to be robust
    import itertools
    part_a = {}
    for v, lab in enumerate(a):
        part_a.setdefault(lab, set()).add(v)
    part_b = {}
    for v, lab in enumerate(b):
        part_b.setdefault(lab, set()).add(v)
    assert sorted(map(sorted, part_a.values())) == sorted(
        map(sorted, part_b.values())
    )


def test_lacc_path_and_cliques(rng):
    from combblas_tpu.models.cc import lacc, num_components

    grid = Grid.make(2, 2)
    n = 24
    d = np.zeros((n, n), np.float32)
    for i in range(9):  # path 0..9
        d[i, i + 1] = d[i + 1, i] = 1
    d[10:16, 10:16] = 1  # clique
    np.fill_diagonal(d, 0)
    A = SpParMat.from_dense(grid, d)
    labels, it = lacc(A)
    lab = labels.to_global()
    assert len(set(lab[:10])) == 1
    assert len(set(lab[10:16])) == 1
    assert num_components(labels) == 2 + (n - 16)


def test_sssp_batch_matches_single(rng):
    """Multi-source Bellman-Ford lanes == per-source runs."""
    import jax.numpy as jnp

    from combblas_tpu.models.sssp import sssp, sssp_batch
    from combblas_tpu.parallel.ellmat import EllParMat
    from combblas_tpu.parallel.spmat import SpParMat

    grid = Grid.make(2, 2)
    n = 48
    d = (rng.random((n, n)) < 0.1).astype(np.float32) * (
        0.1 + rng.random((n, n)).astype(np.float32)
    )
    np.fill_diagonal(d, 0)
    r, c = np.nonzero(d)
    A = SpParMat.from_global_coo(grid, r, c, d[r, c], n, n)
    E = EllParMat.from_host_coo(
        grid, r.astype(np.int64), c.astype(np.int64),
        d[r, c].astype(np.float32), n, n,
    )
    srcs = [0, 5, 17]
    db, _ = sssp_batch(E, jnp.asarray(srcs, jnp.int32))
    got = db.to_global()
    for w, s in enumerate(srcs):
        dist, _ = sssp(A, s)
        np.testing.assert_allclose(got[:, w], dist.to_global(), rtol=1e-5)


def test_triangle_count_dense_kernel(rng):
    """Round-4 one-launch MXU TC must match the sparse path."""
    from combblas_tpu.models.tc import triangle_count

    grid = Grid.make(1, 1)
    n = 40
    d = (rng.random((n, n)) < 0.25).astype(np.float32)
    d = np.maximum(d, d.T)
    np.fill_diagonal(d, 0.0)
    A = SpParMat.from_dense(grid, d)
    want = triangle_count(A, kernel="sparse")
    got = triangle_count(A, kernel="dense")
    assert got == want


def test_triangle_count_edge_harvest_kernel(rng):
    """Round-5 edge-harvest TC (dense-row gathers per edge, the
    32K < n <= 64K regime) must match the sparse and dense paths,
    including when the edge count doesn't divide the scan chunk."""
    from combblas_tpu.models.tc import triangle_count

    grid = Grid.make(1, 1)
    n = 48
    d = (rng.random((n, n)) < 0.3).astype(np.float32)
    d = np.maximum(d, d.T)
    np.fill_diagonal(d, 0.0)
    A = SpParMat.from_dense(grid, d)
    want = triangle_count(A, kernel="sparse")
    assert triangle_count(A, kernel="edgeharvest") == want
    assert triangle_count(A, kernel="edgeharvest_bf16") == want
    assert triangle_count(A, kernel="dense") == want


@pytest.mark.parametrize("kernel", ["edgeharvest", "edgeharvest_bf16"])
def test_triangle_count_edge_harvest_duplicates(rng, kernel):
    """Both edge-harvest variants must survive duplicate COO entries: in
    the bits variant a double-added bit would carry into the next bit
    and corrupt the adjacency; in the bf16 variant a duplicated edge
    would walk its common neighbors twice and double-count 3T (ADVICE
    r5) — dedup happens on device in both."""
    from combblas_tpu.models.tc import triangle_count

    grid = Grid.make(1, 1)
    n = 40
    d = (rng.random((n, n)) < 0.3).astype(np.float32)
    d = np.maximum(d, d.T)
    np.fill_diagonal(d, 0.0)
    r, c = np.nonzero(d)
    # duplicate a third of the entries (and one entry three times)
    dup = np.arange(0, len(r), 3)
    r2 = np.concatenate([r, r[dup], r[:1], r[:1]])
    c2 = np.concatenate([c, c[dup], c[:1], c[:1]])
    A = SpParMat.from_global_coo(
        grid, r2, c2, np.ones(len(r2), np.float32), n, n
    )
    want = triangle_count(
        SpParMat.from_global_coo(
            grid, r, c, np.ones(len(r), np.float32), n, n
        ),
        kernel="sparse",
    )
    assert triangle_count(A, kernel=kernel) == want
