"""Resilient serving (ISSUE 6): fault injection, poisoned-batch
bisection, execution-time deadline enforcement, per-kind circuit
breakers, worker backoff, and atomic graph-version hot-swap.

The recovery matrix: every failure path here is driven by the
DETERMINISTIC fault-injection framework (serve/faults.py) — scripted
call indices and seeded schedules, so the chaos tests replay
bit-for-bit and stay in the tier-1 budget. Long threaded soaks are
marked ``slow``; seeded chaos scenarios are marked ``chaos`` (both
markers registered in conftest.py).
"""

import threading
import time
from concurrent.futures import Future

import jax
import numpy as np
import pytest

from combblas_tpu import obs
from combblas_tpu.parallel.grid import Grid
from combblas_tpu.serve import (
    CircuitBreaker,
    CircuitBreakerOpen,
    FaultInjector,
    GraphEngine,
    InjectedFault,
    ServeConfig,
)
from combblas_tpu.serve.batcher import Request
from combblas_tpu.utils.rmat import rmat_symmetric_coo


@pytest.fixture(autouse=True)
def _clean_obs():
    obs.disable()
    obs.reset()
    yield
    obs.disable()
    obs.reset()


SCALE = 7
N = 1 << SCALE


@pytest.fixture(scope="module")
def graph():
    rows, cols = rmat_symmetric_coo(jax.random.key(5), SCALE, 8)
    return np.asarray(rows), np.asarray(cols)


@pytest.fixture(scope="module")
def engine(graph):
    rows, cols = graph
    return GraphEngine.from_coo(
        Grid.make(2, 2), rows, cols, N, kinds=("bfs", "pagerank"),
    )


@pytest.fixture(scope="module")
def live_roots(graph):
    rows, _ = graph
    deg = np.bincount(rows, minlength=N)
    return np.flatnonzero(deg > 0).astype(np.int32)


# --- fault injector ----------------------------------------------------------


def test_injector_script_fires_at_exact_indices():
    inj = FaultInjector()
    inj.script("engine.execute", at=(1, 3))
    fired = []
    for i in range(5):
        try:
            inj.check("engine.execute")
        except InjectedFault as e:
            fired.append((i, e.call))
    assert fired == [(1, 1), (3, 3)]
    st = inj.stats()
    assert st["calls"]["engine.execute"] == 5
    assert st["fired"]["engine.execute"] == 2


def test_injector_rate_is_seed_deterministic():
    def schedule(seed):
        inj = FaultInjector()
        inj.rate("engine.execute", 0.3, seed=seed)
        out = []
        for _ in range(50):
            try:
                inj.check("engine.execute")
                out.append(0)
            except InjectedFault:
                out.append(1)
        return out

    a, b = schedule(42), schedule(42)
    assert a == b  # same seed + same call order = same schedule
    assert 0 < sum(a) < 50  # actually fires, not always
    assert schedule(7) != a  # and the seed matters


def test_injector_unknown_point_and_unarmed_noop():
    inj = FaultInjector()
    with pytest.raises(ValueError, match="unknown fault point"):
        inj.script("not.a.point", at=(0,))
    inj.check("engine.execute")  # unarmed: no-op, no counters
    assert inj.stats() == {"armed": [], "calls": {}, "fired": {}}
    inj.when("batch.scatter", lambda ctx: ctx.get("kind") == "bfs")
    with pytest.raises(InjectedFault):
        inj.check("batch.scatter", kind="bfs")
    inj.check("batch.scatter", kind="pagerank")  # predicate false
    inj.clear()
    inj.check("batch.scatter", kind="bfs")  # disarmed again


# --- poisoned-batch isolation ------------------------------------------------


def test_poisoned_batch_bisection_isolates_one_request(engine, live_roots):
    """One poison request in a width-16 batch fails ALONE with the
    injected error; its 15 lane-mates all succeed via bisection."""
    srv = engine.serve(ServeConfig(lane_widths=(16,), max_wait_s=60.0))
    roots = [int(r) for r in live_roots[:16]]
    poison = roots[5]
    srv.faults.when(
        "engine.execute", lambda ctx: poison in ctx["roots"]
    )
    futs = {r: srv.submit("bfs", r) for r in roots}
    srv.pump(force=True)
    for r, f in futs.items():
        assert f.done(), r  # NO stranded futures
        if r == poison:
            assert isinstance(f.exception(timeout=0), InjectedFault)
        else:
            assert f.result(timeout=0)["levels"][r] == 0, r
    st = srv.stats()
    assert st["per_kind"]["bfs"]["poisoned"] == 1
    assert st["per_kind"]["bfs"]["retried"] > 0
    # one poison must NOT open the breaker (top-level granularity)
    assert st["per_kind"]["bfs"]["breaker"]["state"] == "closed"


def test_transient_fault_retries_and_succeeds(engine, live_roots):
    """A fault that fires once (scripted at call 0) costs a retry, not
    a request: every future completes ok."""
    srv = engine.serve(ServeConfig(lane_widths=(8,), max_wait_s=60.0))
    srv.faults.script("engine.execute", at=(0,))
    roots = [int(r) for r in live_roots[:8]]
    futs = [srv.submit("bfs", r) for r in roots]
    srv.pump(force=True)
    for r, f in zip(roots, futs):
        assert f.result(timeout=0)["levels"][r] == 0
    assert srv.stats()["per_kind"]["bfs"]["poisoned"] == 0


def test_persistent_fault_exhausts_budget_no_stranded(engine, live_roots):
    """Under a 100% execute-fault rate every request fails after its
    bounded retry budget — settled futures, bounded work, nothing
    hangs."""
    srv = engine.serve(ServeConfig(
        lane_widths=(4,), max_wait_s=60.0, retry_budget=3,
    ))
    srv.faults.rate("engine.execute", 1.0, seed=0)
    futs = [srv.submit("bfs", int(r)) for r in live_roots[:4]]
    srv.pump(force=True)
    assert all(f.done() for f in futs)
    assert all(
        isinstance(f.exception(timeout=0), InjectedFault) for f in futs
    )
    # budget 3: each request rides exactly 3 failing executions
    # (width 4, width 2, then alone): 1 top-level batch + 2+4 retry
    # sub-batches — bounded work, and coalescing stats stay clean
    assert srv.batches == 1
    assert srv.retry_batches == 6
    assert srv.scheduler.depth() == 0
    assert srv.stats()["per_kind"]["bfs"]["poisoned"] == 4


def test_scatter_fault_is_recovered_like_execute(engine, live_roots):
    """The batch.scatter failure point rides the same bisection ladder
    — a fault after execution still settles every future."""
    srv = engine.serve(ServeConfig(lane_widths=(4,), max_wait_s=60.0))
    srv.faults.script("batch.scatter", at=(0,))
    roots = [int(r) for r in live_roots[:4]]
    futs = [srv.submit("bfs", r) for r in roots]
    srv.pump(force=True)
    for r, f in zip(roots, futs):
        assert f.result(timeout=0)["levels"][r] == 0


# --- execution-time deadline enforcement -------------------------------------


def test_expired_request_dropped_before_execution(engine, live_roots):
    """A request already past its deadline at execution time is
    settled with TimeoutError WITHOUT occupying a device lane."""
    srv = engine.serve(ServeConfig(lane_widths=(4,), max_wait_s=60.0))
    now = time.monotonic()
    dead = Request(
        rid=0, kind="bfs", root=int(live_roots[0]), future=Future(),
        submitted_at=now - 1.0, deadline=now - 0.5,
    )
    live = Request(
        rid=1, kind="bfs", root=int(live_roots[1]), future=Future(),
        submitted_at=now, deadline=None,
    )
    before = srv.batches
    srv._run_batch([dead, live])
    assert isinstance(dead.future.exception(timeout=0), TimeoutError)
    assert live.future.result(timeout=0)["levels"][int(live_roots[1])] == 0
    assert srv.batches == before + 1  # ONE batch, dead lane never rode
    assert srv.stats()["per_kind"]["bfs"]["timeout"] == 1


# --- circuit breakers --------------------------------------------------------


def test_retry_budget_defaults_to_full_bisection():
    """The default budget tracks the widest lane bucket (1 + log2):
    one poison always fails alone, at ANY configured width."""
    assert ServeConfig().retry_budget == 5  # widths (1..16)
    assert ServeConfig(lane_widths=(1, 2, 4, 8, 16, 32)).retry_budget == 6
    assert ServeConfig(lane_widths=(1,)).retry_budget == 1
    assert ServeConfig(lane_widths=(4,), retry_budget=2).retry_budget == 2
    with pytest.raises(ValueError, match="retry_budget"):
        ServeConfig(retry_budget=0)


def test_half_open_probe_released_on_queue_full(engine, live_roots):
    """A submit that claims the half-open probe slot but is then
    rejected by the full queue must RELEASE the slot — otherwise the
    kind fast-fails for a whole cooldown with no probe in flight."""
    srv = engine.serve(ServeConfig(
        lane_widths=(1,), max_wait_s=60.0, retry_budget=1, max_queue=1,
        breaker_threshold=1, breaker_cooldown_s=0.01,
    ))
    srv.faults.rate("engine.execute", 1.0, seed=0)
    srv.submit("bfs", int(live_roots[0]))
    srv.pump(force=True)  # one failure opens the breaker (threshold 1)
    srv.faults.clear()
    assert srv.health()["breakers"]["bfs"]["state"] == "open"
    time.sleep(0.02)  # cooldown elapses
    # fill the queue with the OTHER kind so the probe submit hits
    # queue-full AFTER claiming the probe slot
    srv.scheduler.submit("pagerank", int(live_roots[0]))
    from combblas_tpu.serve import BackpressureError
    with pytest.raises(BackpressureError):
        srv.submit("bfs", int(live_roots[1]))
    srv.pump(force=True)  # drains pagerank: capacity is back
    # the probe slot was released: the next submit IS the probe and
    # closes the breaker, instead of fast-failing for a cooldown
    probe = srv.submit("bfs", int(live_roots[1]))
    srv.pump(force=True)
    assert probe.result(timeout=0)["levels"][int(live_roots[1])] == 0
    assert srv.health()["breakers"]["bfs"]["state"] == "closed"


def test_breaker_state_machine_deterministic():
    """Unit cycle with an injected clock: closed -> open at the
    threshold -> fast-fail during cooldown -> half-open probe ->
    close on success; a failed probe doubles the cooldown (capped)."""
    br = CircuitBreaker(threshold=3, cooldown_s=1.0, cooldown_max_s=3.0)
    t = 100.0
    for _ in range(2):
        br.record_failure(t)
    assert br.state == "closed"
    br.record_failure(t)
    assert br.state == "open" and br.opened_total == 1
    assert not br.admit(t + 0.5)  # cooling: fast-fail
    assert br.retry_after(t + 0.5) == pytest.approx(0.5)
    assert br.admit(t + 1.0)  # cooldown elapsed: half-open probe
    assert br.state == "half_open"
    assert not br.admit(t + 1.05)  # ONE probe only: others fast-fail
    assert br.retry_after(t + 1.05) > 0
    assert br.admit(t + 1.0 + 1.0)  # stale probe (no outcome): re-probe
    br.record_failure(t + 1.1)  # probe failed: reopen, cooldown x2
    assert br.state == "open" and br.describe(t)["cooldown_s"] == 2.0
    assert not br.admit(t + 2.0)
    assert br.admit(t + 1.1 + 2.0)
    br.record_success(t + 3.2)  # probe succeeded: closed, cooldown reset
    assert br.state == "closed"
    assert br.describe(t)["cooldown_s"] == 1.0
    assert br.fast_fails == 3  # 2 while open + 1 during the probe


def test_breaker_opens_fast_fails_and_recovers(engine, live_roots):
    """End-to-end: consecutive injected batch failures open the bfs
    breaker; submits fast-fail with CircuitBreakerOpen (retry-after
    hint); after the cooldown a half-open probe closes it; OTHER kinds
    keep serving throughout."""
    srv = engine.serve(ServeConfig(
        lane_widths=(1,), max_wait_s=60.0, retry_budget=1,
        breaker_threshold=3, breaker_cooldown_s=0.05,
    ))
    srv.faults.rate("engine.execute", 1.0, seed=0)
    for _ in range(3):  # three top-level failures
        srv.submit("bfs", int(live_roots[0]))
        srv.pump(force=True)
    assert srv.health()["breakers"]["bfs"]["state"] == "open"
    with pytest.raises(CircuitBreakerOpen) as ei:
        srv.submit("bfs", int(live_roots[0]))
    assert ei.value.retry_after_s <= 0.05
    assert srv.health()["status"] == "degraded"
    # pagerank is unaffected: per-KIND isolation
    f = srv.submit("pagerank", int(live_roots[1]))
    srv.faults.clear()  # engine healthy again
    srv.pump(force=True)
    assert f.result(timeout=0)["ranks"].sum() > 0
    time.sleep(0.06)  # cooldown elapses
    probe = srv.submit("bfs", int(live_roots[2]))  # half-open probe
    assert srv.health()["breakers"]["bfs"]["state"] == "half_open"
    srv.pump(force=True)
    assert probe.result(timeout=0)["levels"][int(live_roots[2])] == 0
    assert srv.health()["breakers"]["bfs"]["state"] == "closed"
    assert srv.health()["status"] == "ok" or not srv._worker  # no worker
    st = srv.stats()["per_kind"]["bfs"]
    assert st["breaker_rejected"] == 1
    assert st["breaker"]["opened_total"] == 1


# --- submit_many prefix semantics under injected faults ----------------------


def test_submit_many_prefix_under_injected_admit_fault(engine, live_roots):
    """An admission fault mid-loop: the admitted prefix stays live, the
    remainder's futures all carry the injected error — one future per
    root, in order, nothing lost."""
    srv = engine.serve(ServeConfig(lane_widths=(4,), max_wait_s=60.0))
    srv.faults.script("scheduler.admit", at=(2,))
    roots = [int(r) for r in live_roots[:5]]
    futs = srv.submit_many("bfs", roots)
    assert len(futs) == 5
    assert [f.done() for f in futs] == [False, False, True, True, True]
    assert all(
        isinstance(f.exception(timeout=0), InjectedFault)
        for f in futs[2:]
    )
    srv.pump(force=True)  # the admitted prefix still completes
    for r, f in zip(roots[:2], futs[:2]):
        assert f.result(timeout=0)["levels"][r] == 0


# --- worker backoff ----------------------------------------------------------


def test_worker_error_backoff_grows_and_resets(engine, live_roots):
    """A scheduler-level error makes the worker back off exponentially
    (capped) instead of spinning at 50 ms; a successful pump resets it
    and the retained error surfaces in stats() with a timestamp."""
    srv = engine.serve(ServeConfig(
        lane_widths=(1,), max_wait_s=0.001,
        worker_backoff_s=0.002, worker_backoff_max_s=0.016,
    ))
    real_pop = srv.scheduler.pop_ready
    boom = RuntimeError("scheduler bug (injected)")

    def bad_pop(*a, **k):
        raise boom

    srv.scheduler.pop_ready = bad_pop
    srv.start()
    try:
        srv.submit("bfs", int(live_roots[0]))
        deadline = time.monotonic() + 5
        while srv.worker_errors < 4 and time.monotonic() < deadline:
            time.sleep(0.005)
        assert srv.worker_errors >= 4
        assert srv._backoff_s > 0.002  # grew
        st = srv.stats()
        assert st["last_worker_error"]["repr"] == repr(boom)
        assert st["last_worker_error"]["at"] is not None
        srv.scheduler.pop_ready = real_pop  # heal
        f = srv.submit("bfs", int(live_roots[1]))
        assert f.result(timeout=30)["levels"][int(live_roots[1])] == 0
        deadline = time.monotonic() + 5
        while srv._backoff_s != 0.002 and time.monotonic() < deadline:
            time.sleep(0.005)
        assert srv._backoff_s == 0.002  # reset on success
    finally:
        srv.scheduler.pop_ready = real_pop
        srv.close()


# --- graph-version hot-swap --------------------------------------------------


def test_swap_same_shape_keeps_plans_zero_retraces(engine, graph,
                                                   live_roots):
    """Swapping to a same-shape version (here: rebuilt from the same
    COO) keeps every compiled plan warm — zero retraces — and bumps
    the version id atomically."""
    rows, cols = graph
    engine.warmup(kinds=("bfs",), widths=(1, 4))
    v0 = engine.version_id
    mark = engine.trace_mark()
    r0 = engine.execute("bfs", live_roots[:4])
    v1 = engine.build_version(rows, cols)
    swap_s = engine.swap(v1)
    assert engine.version_id == v0 + 1 and swap_s >= 0
    r1 = engine.execute("bfs", live_roots[:4])
    np.testing.assert_array_equal(r0["levels"], r1["levels"])
    assert engine.retraces_since(mark) == 0  # plan cache SURVIVED
    assert engine.stats()["swaps"] >= 1


def test_swap_changes_served_results(graph):
    """A swap to a genuinely different graph changes answers: the path
    graph's far end moves closer when we add a chord."""
    rows = np.array([0, 1, 1, 2, 2, 3], np.int64)  # 0-1-2-3 path
    cols = np.array([1, 0, 2, 1, 3, 2], np.int64)
    eng = GraphEngine.from_coo(Grid.make(1, 1), rows, cols, 4,
                               kinds=("bfs",))
    before = eng.execute("bfs", np.asarray([0], np.int32))
    assert before["levels"][3, 0] == 3
    rows2 = np.concatenate([rows, [0, 3]])
    cols2 = np.concatenate([cols, [3, 0]])
    eng.swap(eng.build_version(rows2, cols2))
    after = eng.execute("bfs", np.asarray([0], np.int32))
    assert after["levels"][3, 0] == 1  # the chord is live


def test_swap_validation_rejects_bad_versions(engine, graph):
    rows, cols = graph
    with pytest.raises(TypeError, match="GraphVersion"):
        engine.swap("not-a-version")
    small = GraphEngine.from_coo(
        Grid.make(1, 1), np.array([0, 1]), np.array([1, 0]), 2,
        kinds=("bfs",),
    )
    wrong_n = small.build_version(np.array([0, 1]), np.array([1, 0]))
    with pytest.raises(ValueError, match="nrows"):
        engine.swap(wrong_n)
    # rectangular engines: build_version defaults ncols to the CURRENT
    # version's ncols (not nrows — the dedup key is ncols-based), and
    # swap rejects a changed column space
    rect = GraphEngine.from_coo(
        Grid.make(1, 1), np.array([0, 3]), np.array([5, 2]), 4,
        ncols=8, kinds=("bfs",), symmetric=False,
    )
    v_rect = rect.build_version(np.array([1, 2]), np.array([7, 0]))
    assert v_rect.ncols == 8
    rect.swap(v_rect)  # same-shape rectangular swap is fine
    v_sq = rect.build_version(
        np.array([1, 2]), np.array([3, 0]), ncols=4,
    )
    with pytest.raises(ValueError, match="ncols"):
        rect.swap(v_sq)
    # a WEIGHTED sssp engine must not silently downgrade to hop counts
    weighted = GraphEngine.from_coo(
        Grid.make(1, 1), rows, cols, N,
        weights=np.ones(len(rows), np.float32), kinds=("bfs", "sssp"),
    )
    with pytest.raises(ValueError, match="weights"):
        weighted.swap(weighted.build_version(rows, cols))  # no weights=
    weighted.swap(weighted.build_version(
        rows, cols, weights=np.ones(len(rows), np.float32)
    ))  # weighted replacement is fine
    assert weighted.version_id == 2
    # an injected swap fault leaves the OLD version serving
    srv = engine.serve(ServeConfig(lane_widths=(1,), max_wait_s=60.0))
    srv.faults.script("engine.swap", at=(0,))
    vid = engine.version_id
    with pytest.raises(InjectedFault):
        srv.swap_graph(engine.build_version(rows, cols))
    assert engine.version_id == vid  # rollback-by-never-applying


def test_hot_swap_under_concurrent_load_zero_stranded(engine, graph,
                                                      live_roots):
    """The acceptance gate: an atomic swap under sustained threaded
    load completes with ZERO failed in-flight queries, zero stranded
    futures, and zero post-swap retraces (same-shape version)."""
    rows, cols = graph
    engine.warmup(kinds=("bfs", "pagerank"), widths=(1, 2, 4, 8))
    v_next = engine.build_version(rows, cols)  # built OFF the hot path
    v_before = engine.version_id
    mark = engine.trace_mark()
    srv = engine.serve(ServeConfig(
        lane_widths=(1, 2, 4, 8), max_wait_s=0.002, max_queue=512,
    )).start()
    try:
        kinds = ("bfs", "pagerank")
        futs = []
        swap_info = {}
        for i in range(60):
            futs.append(srv.submit(
                kinds[i % 2], int(live_roots[i % len(live_roots)])
            ))
            if i == 30:  # mid-stream, in-flight batches everywhere
                swap_info = srv.swap_graph(v_next)
        results = [f.result(timeout=120) for f in futs]
        assert len(results) == 60  # all settled, none stranded/failed
        assert swap_info["version"] == v_before + 1
        assert engine.retraces_since(mark) == 0  # plans survived
        st = srv.stats()
        assert st["completed"] == 60
        assert st["per_kind"]["bfs"]["poisoned"] == 0
    finally:
        srv.close()


# --- seeded chaos scenarios --------------------------------------------------


@pytest.mark.chaos
def test_chaos_availability_under_seeded_execute_faults(engine,
                                                        live_roots):
    """The ISSUE 6 acceptance bar, deterministically: with a 5%
    seeded execute-fault rate, >= 95% of well-formed requests still
    complete (bisection absorbs the damage), no future is stranded,
    and the recovery work is visible in stats."""
    srv = engine.serve(ServeConfig(
        lane_widths=(1, 2, 4, 8, 16), max_wait_s=60.0, max_queue=512,
    ))
    # seed 11 fires on the 4th execute call at p=0.05 — the schedule
    # is deterministic, so the recovery path provably runs
    srv.faults.rate("engine.execute", 0.05, seed=11)
    nq = 200
    futs = [
        srv.submit("bfs", int(live_roots[i % len(live_roots)]))
        for i in range(nq)
    ]
    while srv.scheduler.depth():
        srv.pump(force=True)
    assert all(f.done() for f in futs)  # zero stranded
    ok = sum(1 for f in futs if f.exception(timeout=0) is None)
    assert ok / nq >= 0.95, f"availability {ok}/{nq}"
    st = srv.stats()
    assert st["faults"]["fired"].get("engine.execute", 0) > 0
    assert st["per_kind"]["bfs"]["retried"] > 0  # recovery really ran


@pytest.mark.chaos
@pytest.mark.slow
def test_chaos_soak_threaded_faults_and_swap_storm(engine, graph,
                                                   live_roots):
    """Threaded soak: seeded faults + repeated hot-swaps under load.
    Everything settles; availability holds; swaps never strand."""
    rows, cols = graph
    engine.warmup(kinds=("bfs", "pagerank"), widths=(1, 2, 4, 8, 16))
    versions = [engine.build_version(rows, cols) for _ in range(3)]
    swaps_before = engine.swaps
    srv = engine.serve(ServeConfig(
        lane_widths=(1, 2, 4, 8, 16), max_wait_s=0.005, max_queue=1024,
    )).start()
    srv.faults.rate("engine.execute", 0.05, seed=99)
    try:
        futs = []
        for i in range(300):
            futs.append(srv.submit(
                ("bfs", "pagerank")[i % 2],
                int(live_roots[i % len(live_roots)]),
            ))
            if i in (75, 150, 225):
                srv.swap_graph(versions[(i // 75) - 1])
        done = [f for f in futs if not f.cancelled()]
        ok = sum(
            1 for f in done if f.exception(timeout=120) is None
        )
        assert all(f.done() for f in futs)
        assert ok / len(futs) >= 0.95
        assert engine.swaps == swaps_before + 3
    finally:
        srv.close()
