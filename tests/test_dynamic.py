"""Streaming mutation lane (round 11): DeltaBuffer semantics, the
incremental-merge == full-rebuild bit-exactness contract, spill paths,
and warm-restart recompute correctness.  docs/dynamic.md."""

import numpy as np
import pytest

import jax

from combblas_tpu.dynamic import (
    DeltaBatch,
    DeltaBuffer,
    DeltaOverflowError,
    apply_delta,
    fold_ops,
)
from combblas_tpu.parallel.grid import Grid
from combblas_tpu.serve import GraphEngine


def _sym_coo(rng, n, m):
    r = rng.integers(0, n, m)
    c = rng.integers(0, n, m)
    return np.concatenate([r, c]), np.concatenate([c, r])


def _weighted_engine(rng, grid, n=96, m=500, kinds=None):
    rows, cols = _sym_coo(rng, n, m)
    w = rng.random(len(rows)).astype(np.float32) + 0.1
    return (
        GraphEngine.from_coo(
            grid, rows, cols, n, weights=w, keep_coo=True, kinds=kinds
        ),
        rows, cols, w,
    )


def _assert_versions_bitexact(v_inc, v_gold):
    """The acceptance contract: every artifact of the incremental
    version equals the full from_coo rebuild BIT-EXACTLY (canonical COO
    comparison — layout-independent)."""
    for name in ("E", "E_weighted", "P_ell", "ET"):
        a, b = getattr(v_inc, name), getattr(v_gold, name)
        assert (a is None) == (b is None), name
        if a is None:
            continue
        ra, ca, va = a.to_host_coo()
        rb, cb, vb = b.to_host_coo()
        assert np.array_equal(ra, rb), f"{name} rows differ"
        assert np.array_equal(ca, cb), f"{name} cols differ"
        assert np.array_equal(va, vb), f"{name} vals differ"
    assert np.array_equal(v_inc.deg, v_gold.deg)
    assert np.array_equal(v_inc.outdeg, v_gold.outdeg)
    assert (v_inc.dangling is None) == (v_gold.dangling is None)
    if v_inc.dangling is not None:
        assert np.array_equal(
            np.asarray(jax.device_get(v_inc.dangling.blocks)),
            np.asarray(jax.device_get(v_gold.dangling.blocks)),
        )
    assert v_inc.nnz == v_gold.nnz


def _golden_rebuild(engine, version):
    """Full from_coo-pipeline rebuild of the merged edge list."""
    r, c, _n = version.host_coo
    return engine.build_version(
        r, c, weights=version.host_weights, keep_coo=True
    )


# -- DeltaBuffer -------------------------------------------------------------


def test_delta_buffer_bounded_and_tickets():
    buf = DeltaBuffer(capacity=4, nrows=10, ncols=10)
    s0 = buf.add("insert", 1, 2, 0.5)
    s1 = buf.add_many([("delete", 2, 3), ("upsert", 3, 4, 2.0)])
    assert (s0, s1) == (0, 2)
    assert buf.depth() == 3
    with pytest.raises(DeltaOverflowError):
        buf.add_many([("insert", 0, 0), ("insert", 0, 1)])  # 3+2 > 4
    assert buf.depth() == 3  # atomic: nothing was admitted
    batch = buf.drain()
    assert len(batch) == 3 and batch.last_seq == 2
    assert buf.drain() is None
    # sequence numbers keep rising across drains
    assert buf.add("insert", 5, 5) == 3


def test_delta_buffer_validates():
    buf = DeltaBuffer(capacity=8, nrows=4, ncols=4)
    with pytest.raises(ValueError):
        buf.add("insert", 4, 0)  # row out of range
    with pytest.raises(ValueError):
        buf.add("frobnicate", 0, 0)  # unknown op
    with pytest.raises(ValueError):
        buf.add_many([("insert", 0, 0), ("insert", 0, 9)])  # atomic
    assert buf.depth() == 0
    with pytest.raises(ValueError):
        DeltaBuffer(combine="median")


def _replay_naive(ops, base, combine):
    """Sequential per-op replay — the semantics fold_ops must match."""
    state = dict(base)  # key -> weight
    for op, k, w in ops:
        if op == "insert":
            state[k] = w
        elif op == "delete":
            state.pop(k, None)
        else:  # upsert
            if k not in state:
                state[k] = w
            elif combine == "min":
                state[k] = min(state[k], w)
            elif combine == "max":
                state[k] = max(state[k], w)
            elif combine == "sum":
                state[k] = state[k] + w
            else:  # last
                state[k] = w
    return state


@pytest.mark.parametrize("combine", ["min", "max", "sum", "last"])
def test_fold_ops_matches_sequential_replay(rng, combine):
    ncols = 16
    base_keys = np.sort(
        rng.choice(ncols * ncols, size=40, replace=False)
    ).astype(np.int64)
    # weights are multiples of 1/64 so float32 sums are EXACT in any
    # association order (the fold reduces upserts before combining with
    # the base; sequential replay combines left-to-right)
    base_w = (rng.integers(1, 512, 40) / 64.0).astype(np.float32)
    # random op stream with heavy duplicate-key pressure
    m = 120
    keys = rng.choice(base_keys.tolist() + [7, 33, 99, 254], size=m)
    opnames = rng.choice(["insert", "delete", "upsert"], size=m)
    vals = (rng.integers(1, 512, m) / 64.0).astype(np.float32)
    batch = DeltaBatch.from_ops([
        (opnames[i], int(keys[i] // ncols), int(keys[i] % ncols),
         float(vals[i]))
        for i in range(m)
    ])
    uniq, present, fw = fold_ops(
        batch, base_keys, base_w, ncols, combine
    )
    ref = _replay_naive(
        [(opnames[i], int(keys[i]), float(vals[i])) for i in range(m)],
        dict(zip(base_keys.tolist(), base_w.tolist())),
        combine,
    )
    for k, p, w in zip(uniq.tolist(), present.tolist(), fw.tolist()):
        assert p == (k in ref), (k, combine)
        if p:
            assert np.float32(w) == np.float32(ref[k]), (k, combine)


# -- incremental merge == full rebuild ---------------------------------------


@pytest.mark.parametrize("gridshape", [
    # 1x1 is slow-lane (round 12, tier-1 budget): the 2x2 case keeps
    # the bit-exactness contract on the grid with per-tile slack, and
    # the 1x1 spill paths have their own dedicated tests
    pytest.param((1, 1), marks=pytest.mark.slow),
    (2, 2),
])
def test_apply_delta_bitexact(rng, gridshape):
    """The acceptance gate: insert/delete/upsert batches — with
    duplicate keys inside one batch — merge bit-exactly equal to the
    full from_coo rebuild, on 1x1 AND 2x2 grids, and the incremental
    path preserves every operand shape (zero retraces after swap)."""
    grid = Grid.make(*gridshape)
    eng, rows, cols, _w = _weighted_engine(rng, grid)
    n = eng.nrows
    key = rows.astype(np.int64) * n + cols
    er, ec = np.divmod(np.unique(key), n)
    ops = []
    for t in range(4):  # symmetric deletes of existing edges
        ops.append(("delete", int(er[t * 11]), int(ec[t * 11])))
        ops.append(("delete", int(ec[t * 11]), int(er[t * 11])))
    # duplicate-key sequences: insert then delete then re-insert, and
    # stacked upserts (the fold must replay them in admission order)
    ops += [
        ("insert", 1, 2, 9.0), ("delete", 1, 2), ("insert", 1, 2, 3.5),
        ("insert", 2, 1, 3.5),
        ("upsert", int(er[50]), int(ec[50]), 0.05),
        ("upsert", int(er[50]), int(ec[50]), 0.01),
        ("upsert", int(ec[50]), int(er[50]), 0.01),
        ("insert", 7, 9, 1.25), ("insert", 9, 7, 1.25),
    ]
    eng.warmup(widths=(1, 2))
    mark = eng.trace_mark()
    v1 = apply_delta(
        eng.version, DeltaBatch.from_ops(ops), kinds=eng.kinds()
    )
    st = v1.dyn.last_stats
    assert st.mode == "incremental", (st.mode, st.reason)
    assert st.rows_patched > 0
    assert st.buckets_reused > 0  # untouched classes share device arrays
    _assert_versions_bitexact(v1, _golden_rebuild(eng, v1))
    eng.swap(v1)
    eng.execute("bfs", np.asarray([1], np.int32))
    eng.execute("sssp", np.asarray([1, 2], np.int32))
    assert eng.retraces_since(mark) == 0


def test_apply_delta_directed_bc_transpose(rng):
    """The transpose twin (ET, bc on directed graphs) is patched
    through the second orientation and stays bit-exact."""
    grid = Grid.make(2, 2)
    n, m = 64, 300
    rows = rng.integers(0, n, m)
    cols = rng.integers(0, n, m)
    eng = GraphEngine.from_coo(
        grid, rows, cols, n, kinds=("bfs", "bc"), symmetric=False,
        keep_coo=True,
    )
    assert eng.version.ET is not None
    ops = [
        ("insert", 0, 5), ("insert", 5, 0), ("delete", int(rows[0]),
                                             int(cols[0])),
        ("insert", 10, 11),
    ]
    v1 = apply_delta(
        eng.version, DeltaBatch.from_ops(ops), kinds=eng.kinds()
    )
    assert v1.dyn.last_stats.mode == "incremental"
    r1, c1, _ = v1.host_coo
    v_gold = eng.build_version(r1, c1, symmetric=False, keep_coo=True)
    _assert_versions_bitexact(v1, v_gold)


def test_apply_delta_spill_threshold(rng):
    """A delta past the structural-change fraction spills to a full
    rebuild — and the rebuild is bit-exact too (the spill path IS the
    from_coo pipeline plus retained state)."""
    grid = Grid.make(1, 1)
    eng, _rows, _cols, _w = _weighted_engine(rng, grid, n=64, m=250)
    n = eng.nrows
    ops = []
    for i in range(n):  # dense new clique rows: far past 10%
        for j in (1, 3, 5):
            ops.append(("insert", i, (i + j) % n, 1.0))
            ops.append(("insert", (i + j) % n, i, 1.0))
    v1 = apply_delta(
        eng.version, DeltaBatch.from_ops(ops), kinds=eng.kinds()
    )
    st = v1.dyn.last_stats
    assert st.mode == "rebuild" and st.reason == "threshold"
    _assert_versions_bitexact(v1, _golden_rebuild(eng, v1))


def test_apply_delta_bucket_full_spill():
    """No free slot anywhere -> honest rebuild (growing a bucket would
    change operand shapes and retrace regardless)."""
    grid = Grid.make(1, 1)
    n = 8
    rows = np.arange(n)
    cols = (rows + 1) % n  # every row degree 1: the class is FULL
    rows_s = np.concatenate([rows, cols])
    cols_s = np.concatenate([cols, rows])
    eng = GraphEngine.from_coo(
        grid, rows_s, cols_s, n, kinds=("bfs",), keep_coo=True
    )
    v1 = apply_delta(
        eng.version,
        DeltaBatch.from_ops([("insert", 0, 4), ("insert", 4, 0)]),
        kinds=eng.kinds(), spill_frac=1.0,  # isolate the capacity spill
    )
    st = v1.dyn.last_stats
    assert st.mode == "rebuild" and st.reason == "bucket_full"
    _assert_versions_bitexact(v1, _golden_rebuild(eng, v1))


def test_apply_delta_chain(rng):
    """Merge state evolves correctly across a chain of deltas: the end
    state equals one rebuild of the final edge list."""
    grid = Grid.make(2, 2)
    eng, rows, cols, _w = _weighted_engine(rng, grid, n=64, m=300)
    n = eng.nrows
    v = eng.version
    for step in range(4):
        a, b = int(rng.integers(0, n)), int(rng.integers(0, n))
        ops = [
            ("insert", a, b, 0.5 + step), ("insert", b, a, 0.5 + step),
            ("upsert", int(rows[step]), int(cols[step]), 0.01),
            ("upsert", int(cols[step]), int(rows[step]), 0.01),
        ]
        v = apply_delta(v, DeltaBatch.from_ops(ops), kinds=eng.kinds())
        eng.swap(v)
    _assert_versions_bitexact(v, _golden_rebuild(eng, v))


def test_apply_delta_requires_host_coo(rng):
    grid = Grid.make(1, 1)
    rows, cols = _sym_coo(rng, 32, 100)
    eng = GraphEngine.from_coo(grid, rows, cols, 32)  # no keep_coo
    with pytest.raises(ValueError, match="keep_coo"):
        apply_delta(
            eng.version, DeltaBatch.from_ops([("insert", 0, 1)]),
            kinds=eng.kinds(),
        )


def test_symmetry_guard_for_bc(rng):
    """A bc-serving symmetric engine (E is its own transpose) must
    reject a delta that breaks structural symmetry — the same check
    from_coo performs at build."""
    grid = Grid.make(1, 1)
    rows, cols = _sym_coo(rng, 32, 120)
    eng = GraphEngine.from_coo(
        grid, rows, cols, 32, kinds=("bfs", "bc"), keep_coo=True
    )
    r0, c0, _ = eng.version.host_coo
    present = set(zip(r0.tolist(), c0.tolist()))
    a, b = next(
        (a, b) for a in range(32) for b in range(32)
        if a != b and (a, b) not in present
    )
    with pytest.raises(ValueError, match="symmetr"):
        apply_delta(
            eng.version,
            DeltaBatch.from_ops([("insert", a, b)]),  # no (b, a) twin
            kinds=eng.kinds(),
        )


# -- warm-restart recompute --------------------------------------------------


def _mutable_engine(rng, n=96, m=500):
    grid = Grid.make(2, 2)
    rows, cols = _sym_coo(rng, n, m)
    return GraphEngine.from_coo(
        grid, rows, cols, n, kinds=("bfs", "pagerank"), keep_coo=True
    ), rows


def test_refresh_cold_then_cached(rng):
    eng, rows = _mutable_engine(rng)
    root = int(rows[0])
    first = eng.refresh("bfs", root=root)
    assert first["mode"] == "cold" and first["result"].shape == (96,)
    again = eng.refresh("bfs", root=root)
    assert again["mode"] == "cached"
    assert np.array_equal(first["result"], again["result"])


def test_refresh_warm_matches_cold_after_inserts(rng):
    """Insert-only deltas: BFS/CC repair from the previous result is
    EXACT (monotone relaxation), and PageRank restarts from the
    previous vector in fewer iterations."""
    eng, rows = _mutable_engine(rng)
    root = int(rows[0])
    eng.refresh("bfs", root=root)
    eng.refresh("cc")
    pr_cold = eng.refresh("pagerank")
    far = int(np.argmax(eng.refresh("bfs", root=root)["result"]))
    ops = [("insert", root, far), ("insert", far, root),
           ("insert", 2, 3), ("insert", 3, 2)]
    eng.swap(eng.apply_delta(DeltaBatch.from_ops(ops)))
    warm_bfs = eng.refresh("bfs", root=root)
    assert warm_bfs["mode"] == "warm"
    cold_bfs = eng.refresh("bfs", root=root, force_cold=True)
    assert np.array_equal(warm_bfs["result"], cold_bfs["result"])
    warm_cc = eng.refresh("cc")
    assert warm_cc["mode"] == "warm"
    cold_cc = eng.refresh("cc", force_cold=True)
    assert np.array_equal(warm_cc["result"], cold_cc["result"])
    warm_pr = eng.refresh("pagerank")
    assert warm_pr["mode"] == "warm"
    assert warm_pr["niter"] <= pr_cold["niter"]
    cold_pr = eng.refresh("pagerank", force_cold=True)
    np.testing.assert_allclose(
        warm_pr["result"], cold_pr["result"], atol=5e-5
    )


def test_refresh_deletes_fall_back_cold(rng):
    """Deletions can RAISE bfs levels / split components — no monotone
    repair expresses that, so the refresh honestly recomputes."""
    eng, rows = _mutable_engine(rng)
    root = int(rows[0])
    eng.refresh("bfs", root=root)
    r, c, _ = eng.version.host_coo
    # delete one symmetric pair not incident to the root
    pick = next(
        i for i in range(len(r)) if r[i] != root and c[i] != root
        and r[i] != c[i]
    )
    ops = [("delete", int(r[pick]), int(c[pick])),
           ("delete", int(c[pick]), int(r[pick]))]
    eng.swap(eng.apply_delta(DeltaBatch.from_ops(ops)))
    out = eng.refresh("bfs", root=root)
    assert out["mode"] == "cold" and out["cold_reason"] == "deletes"
    # and the cold result is trusted fresh state: a further cached read
    assert eng.refresh("bfs", root=root)["mode"] == "cached"


def test_refresh_validates(rng):
    eng, _rows = _mutable_engine(rng, n=32, m=100)
    with pytest.raises(ValueError, match="root"):
        eng.refresh("bfs")
    with pytest.raises(ValueError, match="unknown refresh kind"):
        eng.refresh("toposort")


# -- round 12: headroom-aware bucket sizing + the no-op CSC reset fix --------


def test_headroom_avoids_bucket_full_spill():
    """The SAME degree-1 ring that spills ``bucket_full`` when built
    tight merges INCREMENTALLY when the build reserved headroom slots
    — the growing row re-buckets into the free reserve
    (``headroom_used``) and the result stays bit-exact with the full
    rebuild."""
    grid = Grid.make(1, 1)
    n = 8
    rows = np.arange(n)
    cols = (rows + 1) % n
    rows_s = np.concatenate([rows, cols])
    cols_s = np.concatenate([cols, rows])
    eng = GraphEngine.from_coo(
        grid, rows_s, cols_s, n, kinds=("bfs",), keep_coo=True,
        headroom=0.5,
    )
    assert eng.version.headroom == 0.5
    batch = DeltaBatch.from_ops([("insert", 0, 4), ("insert", 4, 0)])
    v1 = apply_delta(
        eng.version, batch, kinds=eng.kinds(), spill_frac=1.0,
    )
    st = v1.dyn.last_stats
    assert st.mode == "incremental", st.reason
    assert st.headroom_used > 0
    assert st.rows_rebucketed > 0
    _assert_versions_bitexact(v1, _golden_rebuild(eng, v1))
    # identical operand shapes: the zero-retrace contract's premise
    for b_new, b_old in zip(v1.E.buckets, eng.version.E.buckets):
        assert b_new[0].shape == b_old[0].shape


def test_headroom_env_default(monkeypatch):
    """COMBBLAS_DYNAMIC_HEADROOM drives builds that don't pass
    headroom= explicitly (and bucket shapes grow by the slack)."""
    from combblas_tpu.parallel.ellmat import EllParMat

    grid = Grid.make(1, 1)
    n = 8
    rows = np.arange(n)
    cols = (rows + 1) % n
    tight = EllParMat.host_build(
        grid, rows, cols, np.ones(n, np.float32), n, n
    )
    monkeypatch.setenv("COMBBLAS_DYNAMIC_HEADROOM", "1.0")
    slack = EllParMat.host_build(
        grid, rows, cols, np.ones(n, np.float32), n, n
    )
    assert slack[0][0].shape[2] == 2 * tight[0][0].shape[2]


def test_csc_companion_survives_noop_merge(rng):
    """REGRESSION (round 12): a fold that touched no edges (upsert of
    an already-present edge) must CARRY the lazy CSC companion and the
    cached coldeg instead of resetting them to a rebuild-from-COO; any
    structural change still resets."""
    eng, rows, cols, _w = _weighted_engine(rng, Grid.make(2, 2))
    sentinel_csc = object()
    sentinel_coldeg = object()
    eng.csc = sentinel_csc
    eng.coldeg = sentinel_coldeg
    r0, c0 = int(rows[0]), int(cols[0])
    # structurally NO-OP: the edge exists and min-combining a larger
    # weight keeps the stored one -> ins/rem/wchg all empty
    noop = DeltaBatch.from_ops([("upsert", r0, c0, 123.0)])
    v1 = apply_delta(eng.version, noop, kinds=eng.kinds())
    assert v1.dyn.last_stats.mode == "incremental"
    assert v1.dyn.last_stats.inserted == 0
    assert v1.dyn.last_stats.removed == 0
    assert v1.csc is sentinel_csc
    assert v1.coldeg is sentinel_coldeg
    # a real structural change still resets both (lazily rebuilt)
    free = next(
        (a, b) for a in range(3) for b in range(3)
        if not np.any((rows == a) & (cols == b)) and a != b
    )
    real = DeltaBatch.from_ops([
        ("insert", free[0], free[1], 1.0),
        ("insert", free[1], free[0], 1.0),
    ])
    v2 = apply_delta(eng.version, real, kinds=eng.kinds())
    assert v2.csc is None and v2.coldeg is None


def test_symmetry_guard_covers_propagate(rng):
    """A propagate-serving symmetric engine (ET is None: E is its own
    transpose) must reject asymmetric deltas exactly like bc — a
    silent merge would flip the edge direction every served
    propagation walks."""
    n = 64
    rows, cols = _sym_coo(rng, n, 300)
    X = rng.random((n, 4)).astype(np.float32)
    eng = GraphEngine.from_coo(
        Grid.make(2, 2), rows, cols, n, keep_coo=True,
        features=X, kinds=("bfs", "propagate"),
    )
    free = next(
        (a, b) for a in range(4) for b in range(4)
        if a != b and not np.any((rows == a) & (cols == b))
    )
    with pytest.raises(ValueError, match="symmetry"):
        apply_delta(
            eng.version,
            DeltaBatch.from_ops([("insert", free[0], free[1])]),
            kinds=eng.kinds(),
        )
