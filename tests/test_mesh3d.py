"""3D grid: SpParMat3D conversions + SUMMA3D vs the 2D product.

Mirrors the reference's SpGEMM3DTest (3D result vs 2D result on the same
input, ReleaseTests/CMakeLists.txt + SURVEY §4.1-4.2).
"""

import numpy as np
import pytest

from combblas_tpu import PLUS_TIMES
from combblas_tpu.parallel.mesh3d import (
    Grid3D,
    SpParMat3D,
    mem_efficient_spgemm3d,
    spgemm3d,
)
from conftest import random_dense


def test_3d_col_split_concat_roundtrip(rng):
    grid = Grid3D.make(2, 2, 2)
    d = random_dense(rng, 16, 16, 0.35)
    r, c = np.nonzero(d)
    B = SpParMat3D.from_global_coo(grid, r, c, d[r, c], 16, 16, "row")
    parts = B.col_split(2)
    assert all(p.ncols == 8 for p in parts)
    back = SpParMat3D.col_concatenate(parts)
    np.testing.assert_allclose(back.to_dense(), d, rtol=1e-6)


@pytest.mark.parametrize("phases", [
    2,
    # phases=4 is slow-lane (round 12, tier-1 budget): same phased
    # machinery, one more split
    pytest.param(4, marks=pytest.mark.slow),
])
def test_mem_efficient_spgemm3d(rng, phases):
    grid = Grid3D.make(2, 2, 2)
    da = random_dense(rng, 16, 16, 0.3)
    db = random_dense(rng, 16, 16, 0.3)
    ra, ca = np.nonzero(da)
    rb, cb = np.nonzero(db)
    A = SpParMat3D.from_global_coo(grid, ra, ca, da[ra, ca], 16, 16, "col")
    B = SpParMat3D.from_global_coo(grid, rb, cb, db[rb, cb], 16, 16, "row")
    C = mem_efficient_spgemm3d(PLUS_TIMES, A, B, phases)
    np.testing.assert_allclose(C.to_dense(), da @ db, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("split", ["col", "row"])
def test_3d_roundtrip(rng, split):
    grid = Grid3D.make(2, 2, 2)
    d = random_dense(rng, 16, 16, 0.3)
    r, c = np.nonzero(d)
    A = SpParMat3D.from_global_coo(grid, r, c, d[r, c], 16, 16, split=split)
    np.testing.assert_allclose(A.to_dense(), d, rtol=1e-6)
    assert int(A.getnnz()) == len(r)


def test_summa3d_matches_dense(rng):
    grid = Grid3D.make(2, 2, 2)
    da = random_dense(rng, 16, 16, 0.3)
    db = random_dense(rng, 16, 16, 0.3)
    ra, ca = np.nonzero(da)
    rb, cb = np.nonzero(db)
    A = SpParMat3D.from_global_coo(grid, ra, ca, da[ra, ca], 16, 16, "col")
    B = SpParMat3D.from_global_coo(grid, rb, cb, db[rb, cb], 16, 16, "row")
    C = spgemm3d(PLUS_TIMES, A, B)
    assert C.split == "col"
    np.testing.assert_allclose(C.to_dense(), da @ db, rtol=1e-5, atol=1e-6)


def test_summa3d_single_layer_degenerates(rng):
    """L=1 must reproduce plain 2D SUMMA semantics."""
    grid = Grid3D.make(1, 2, 2)
    da = random_dense(rng, 12, 12, 0.4)
    db = random_dense(rng, 12, 12, 0.4)
    ra, ca = np.nonzero(da)
    rb, cb = np.nonzero(db)
    A = SpParMat3D.from_global_coo(grid, ra, ca, da[ra, ca], 12, 12, "col")
    B = SpParMat3D.from_global_coo(grid, rb, cb, db[rb, cb], 12, 12, "row")
    C = spgemm3d(PLUS_TIMES, A, B)
    np.testing.assert_allclose(C.to_dense(), da @ db, rtol=1e-5, atol=1e-6)


def test_summa3d_rectangular(rng):
    """A 32x16 · B 16x32 — exercises B's own row blocking in the sizing
    pass (a bug once used A's)."""
    grid = Grid3D.make(2, 2, 2)
    da = random_dense(rng, 32, 16, 0.3)
    db = random_dense(rng, 16, 32, 0.3)
    ra, ca = np.nonzero(da)
    rb, cb = np.nonzero(db)
    A = SpParMat3D.from_global_coo(grid, ra, ca, da[ra, ca], 32, 16, "col")
    B = SpParMat3D.from_global_coo(grid, rb, cb, db[rb, cb], 16, 32, "row")
    C = spgemm3d(PLUS_TIMES, A, B)
    np.testing.assert_allclose(C.to_dense(), da @ db, rtol=1e-5, atol=1e-6)


def test_summa3d_square(rng):
    """A·A (the MCL expansion shape) through the 3D path."""
    grid = Grid3D.make(2, 2, 2)
    d = random_dense(rng, 16, 16, 0.25)
    r, c = np.nonzero(d)
    A = SpParMat3D.from_global_coo(grid, r, c, d[r, c], 16, 16, "col")
    B = SpParMat3D.from_global_coo(grid, r, c, d[r, c], 16, 16, "row")
    C = spgemm3d(PLUS_TIMES, A, B)
    np.testing.assert_allclose(C.to_dense(), d @ d, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("split", ["col", "row"])
@pytest.mark.parametrize("shape2", [(2, 4), (4, 2)])
def test_2d_3d_conversion_roundtrip(rng, split, shape2):
    """On-device 2D→3D→2D conversion preserves the matrix exactly
    (≈ SpParMat3D(SpParMat&) + readback, SpParMat3D.cpp:74-145,197-320)."""
    from combblas_tpu.parallel.grid import Grid
    from combblas_tpu.parallel.mesh3d import Grid3D, SpParMat3D
    from combblas_tpu.parallel.spmat import SpParMat

    g2 = Grid.make(*shape2)
    g3 = Grid3D.make(2, 2, 2)
    n = 48
    d = random_dense(rng, n, n, 0.15)
    A = SpParMat.from_dense(g2, d)
    A3 = SpParMat3D.from_spmat(A, g3, split=split)
    assert A3.split == split
    np.testing.assert_allclose(A3.to_dense(), d)
    back = A3.to_spmat(g2)
    np.testing.assert_allclose(back.to_dense(), d)
    assert int(np.asarray(back.getnnz())) == int((d != 0).sum())


def test_3d_conversion_then_spgemm(rng):
    """Converted matrices are first-class: SUMMA3D on a converted pair
    matches the dense product (the SpGEMM3DTest pattern,
    ReleaseTests/CMakeLists.txt:43)."""
    from combblas_tpu.parallel.grid import Grid
    from combblas_tpu.parallel.mesh3d import Grid3D, SpParMat3D, spgemm3d
    from combblas_tpu.parallel.spmat import SpParMat

    g2 = Grid.make(2, 4)
    g3 = Grid3D.make(2, 2, 2)
    n = 32
    d = random_dense(rng, n, n, 0.2)
    A = SpParMat.from_dense(g2, d)
    A3 = SpParMat3D.from_spmat(A, g3, split="col")
    B3 = SpParMat3D.from_spmat(A, g3, split="row")
    C3 = spgemm3d(PLUS_TIMES, A3, B3)
    np.testing.assert_allclose(C3.to_dense(), d @ d, rtol=1e-5, atol=1e-6)


def _colvec3d_to_global(v3, grid3, ncols):
    """[L, pc, tc] layer-window column vector → [ncols] global order."""
    L, pc, tc = v3.shape
    lc = L * tc
    out = np.zeros(pc * lc, v3.dtype)
    for l in range(L):
        for j in range(pc):
            out[j * lc + l * tc : j * lc + (l + 1) * tc] = v3[l, j]
    return out[:ncols]


def test_3d_column_ops_match_2d(rng):
    """reduce3d_cols / nnz_per_column3d / kselect3d / prune_column3d /
    dim_apply3d_cols match their 2D SpParMat counterparts."""
    import jax.numpy as jnp

    from combblas_tpu.parallel.grid import Grid
    from combblas_tpu.parallel.mesh3d import (
        Grid3D,
        SpParMat3D,
        dim_apply3d_cols,
        kselect3d,
        nnz_per_column3d,
        prune_column3d,
        reduce3d_cols,
    )
    from combblas_tpu.parallel.spmat import SpParMat

    g2 = Grid.make(2, 4)
    g3 = Grid3D.make(2, 2, 2)
    n = 48
    d = random_dense(rng, n, n, 0.25)
    A2 = SpParMat.from_dense(g2, d)
    A3 = SpParMat3D.from_spmat(A2, g3, split="col")

    sums3 = _colvec3d_to_global(
        np.asarray(reduce3d_cols(PLUS_TIMES, A3)), g3, n
    )
    np.testing.assert_allclose(sums3, d.sum(axis=0), rtol=1e-5)

    nnz3 = _colvec3d_to_global(np.asarray(nnz_per_column3d(A3)), g3, n)
    np.testing.assert_array_equal(nnz3, (d != 0).sum(axis=0))

    k = 3
    ks3 = _colvec3d_to_global(np.asarray(kselect3d(A3, k)), g3, n)
    for j in range(n):
        colv = d[:, j][d[:, j] != 0]
        if len(colv) >= k:
            assert np.isclose(ks3[j], np.sort(colv)[-k], rtol=1e-6), j
        else:
            assert ks3[j] <= colv.min() if len(colv) else True

    th = kselect3d(A3, k)
    pruned = prune_column3d(A3, th, keep=lambda v, t: v >= t)
    dp = pruned.to_dense()
    for j in range(n):
        keep = d[:, j] >= ks3[j]
        np.testing.assert_allclose(dp[:, j], np.where(keep, d[:, j], 0))

    scaled = dim_apply3d_cols(
        A3, reduce3d_cols(PLUS_TIMES, A3),
        lambda v, s: v / jnp.where(s != 0, s, 1),
    )
    cs = scaled.to_dense().sum(axis=0)
    np.testing.assert_allclose(cs[(d != 0).any(axis=0)], 1.0, rtol=1e-5)


def test_spgemm3d_windowed_matches_esc3d(rng):
    """ISSUE 7 tentpole (c): the windowed 3D tier (both backends,
    duplicate-entry COO input) agrees with the ESC 3D kernel and the
    dense golden; spgemm3d(tier=...) routes to it."""
    import jax

    from combblas_tpu.parallel.mesh3d import spgemm3d_windowed

    grid = Grid3D.make(2, 2, 2)
    n = 32
    d = random_dense(rng, n, n, 0.25)
    r, c = np.nonzero(d)
    v = d[r, c]
    # duplicate entries: the windowed tier absorbs them via the
    # combining densify/scatter; the golden adds them
    rd = np.concatenate([r, r[:20]])
    cd = np.concatenate([c, c[:20]])
    vd = np.concatenate([v, v[:20]])
    dd = np.zeros((n, n), np.float64)
    np.add.at(dd, (rd, cd), vd)
    A3 = SpParMat3D.from_global_coo(grid, rd, cd, vd, n, n, "col")
    B3 = SpParMat3D.from_global_coo(grid, rd, cd, vd, n, n, "row")
    want = dd @ dd
    esc = spgemm3d(PLUS_TIMES, A3, B3)
    np.testing.assert_allclose(esc.to_dense(), want, rtol=1e-5, atol=1e-5)
    for backend, bc in (("scatter", None), ("dot", 16)):
        C = spgemm3d_windowed(
            PLUS_TIMES, A3, B3, block_rows=8, block_cols=bc,
            backend=backend,
        )
        assert C.split == "col"
        np.testing.assert_allclose(
            C.to_dense(), want, rtol=1e-5, atol=1e-5
        )
        assert int(jax.device_get(C.getnnz())) == int(
            jax.device_get(esc.getnnz())
        )
    C = spgemm3d(
        PLUS_TIMES, A3, B3, tier="windowed", backend="scatter",
        block_rows=8,
    )
    np.testing.assert_allclose(C.to_dense(), want, rtol=1e-5, atol=1e-5)


def test_summa3d_window_symbolic_host_matches_device(rng):
    """The 3D symbolic-sizing twins agree: device
    ``summa3d_window_flops_pair`` / ``summa3d_window_bnnz`` == the
    host-numpy twins, padded and true variants."""
    import jax

    from combblas_tpu.parallel.mesh3d import (
        summa3d_window_bnnz,
        summa3d_window_bnnz_host,
        summa3d_window_flops_host,
        summa3d_window_flops_pair,
    )

    grid = Grid3D.make(2, 2, 2)
    n = 64
    d = random_dense(rng, n, n, 0.15)
    r, c = np.nonzero(d)
    rd = np.concatenate([r, r[:25]])  # duplicates count per-entry in
    cd = np.concatenate([c, c[:25]])  # the symbolic pass, both twins
    v = np.ones(len(rd), np.float32)
    A3 = SpParMat3D.from_global_coo(grid, rd, cd, v, n, n, "col")
    B3 = SpParMat3D.from_global_coo(grid, rd, cd, v, n, n, "row")
    dev = np.asarray(
        jax.device_get(summa3d_window_flops_pair(A3, B3, 8, 16, chunk_w=8))
    )
    hpad = summa3d_window_flops_host(
        grid, rd, cd, rd, cd, n, n, n, 8, 16, chunk_w=8
    )
    htrue = summa3d_window_flops_host(
        grid, rd, cd, rd, cd, n, n, n, 8, 16, chunk_w=0
    )
    np.testing.assert_array_equal(
        dev[0].astype(np.int64), hpad.astype(np.int64)
    )
    np.testing.assert_array_equal(
        dev[1].astype(np.int64), htrue.astype(np.int64)
    )
    bn_dev = np.asarray(jax.device_get(summa3d_window_bnnz(B3, 16)))
    bn_host = summa3d_window_bnnz_host(grid, rd, cd, n, n, 16)
    np.testing.assert_array_equal(
        bn_dev.astype(np.int64), bn_host.astype(np.int64)
    )


def test_resplit3d_roundtrip(rng):
    from combblas_tpu.parallel.grid import Grid
    from combblas_tpu.parallel.mesh3d import Grid3D, SpParMat3D, resplit3d
    from combblas_tpu.parallel.spmat import SpParMat

    g2 = Grid.make(2, 4)
    g3 = Grid3D.make(2, 2, 2)
    n = 32
    d = random_dense(rng, n, n, 0.2)
    A3 = SpParMat3D.from_spmat(SpParMat.from_dense(g2, d), g3, split="col")
    R = resplit3d(A3, "row")
    assert R.split == "row"
    np.testing.assert_allclose(R.to_dense(), d)
    back = resplit3d(R, "col")
    np.testing.assert_allclose(back.to_dense(), d)


@pytest.mark.slow
def test_mcl_3d_matches_2d(rng):
    # slow-lane (round 17, tier-1 budget): the end-to-end layered
    # MCL re-pays ~12 s of 3D compiles whose building blocks (3D
    # column ops, 2D<->3D conversions, spgemm3d agreement) each
    # keep their own fast tests in this file
    """mcl(layers=2) must produce the same clustering as the 2D path
    (the SpGEMM3DTest equivalence pattern applied to the full pipeline)."""
    from combblas_tpu.models.mcl import mcl
    from combblas_tpu.parallel.grid import Grid
    from combblas_tpu.parallel.spmat import SpParMat

    # two clear 8-cliques + a sparse bridge, sized to divide 2x2x2 splits
    n = 16
    d = np.zeros((n, n), np.float32)
    from combblas_tpu.parallel.mesh3d import Grid3D

    d[:8, :8] = 1.0
    d[8:, 8:] = 1.0
    d[7, 8] = d[8, 7] = 0.1  # the sparse bridge the prune must cut
    np.fill_diagonal(d, 0)
    g2 = Grid.make(2, 2)  # square grid: 2D SUMMA + interpretation
    A2 = SpParMat.from_dense(g2, d)
    labels2, it2, ch2 = mcl(A2, inflation=2.0)
    labels3, it3, ch3 = mcl(
        A2, inflation=2.0, layers=2, grid3=Grid3D.make(2, 2, 2)
    )
    l2 = labels2.to_global()
    l3 = labels3.to_global()
    # same partition (labels are canonical smallest-member ids)
    np.testing.assert_array_equal(l2, l3)
    assert len(np.unique(l2)) == 2


@pytest.mark.slow  # 20-40 s of 3D reroll compiles; the 3D MCL path stays
# tier-1 via test_mcl_3d_matches_2d
def test_mcl_3d_chaos_every_matches(rng):
    """3D K-iterations-per-sync block loop (frozen capacities, on-device
    chaos/overflow carry) must match the per-iteration-sync 3D path."""
    import jax

    # this test compiles many large 3D programs (plus reroll variants);
    # start from an empty executable cache — under a full-suite process
    # the accumulated compile state has produced flaky XLA:CPU aborts
    jax.clear_caches()
    from combblas_tpu.models.mcl import mcl
    from combblas_tpu.parallel.grid import Grid
    from combblas_tpu.parallel.mesh3d import Grid3D
    from combblas_tpu.parallel.spmat import SpParMat

    n = 16
    d = np.zeros((n, n), np.float32)
    d[:8, :8] = 1.0
    d[8:, 8:] = 1.0
    d[7, 8] = d[8, 7] = 0.1
    np.fill_diagonal(d, 0)
    g2 = Grid.make(2, 2)
    A2 = SpParMat.from_dense(g2, d)
    g3 = Grid3D.make(2, 2, 2)
    l1, it1, _ = mcl(A2, inflation=2.0, layers=2, grid3=g3)
    l2, it2, ch2 = mcl(
        A2, inflation=2.0, layers=2, grid3=g3, chaos_every=3
    )
    np.testing.assert_array_equal(l1.to_global(), l2.to_global())
    assert ch2 < 1e-3
    assert it1 <= it2 <= it1 + 2
