"""3D grid: SpParMat3D conversions + SUMMA3D vs the 2D product.

Mirrors the reference's SpGEMM3DTest (3D result vs 2D result on the same
input, ReleaseTests/CMakeLists.txt + SURVEY §4.1-4.2).
"""

import numpy as np
import pytest

from combblas_tpu import PLUS_TIMES
from combblas_tpu.parallel.mesh3d import (
    Grid3D,
    SpParMat3D,
    mem_efficient_spgemm3d,
    spgemm3d,
)
from conftest import random_dense


def test_3d_col_split_concat_roundtrip(rng):
    grid = Grid3D.make(2, 2, 2)
    d = random_dense(rng, 16, 16, 0.35)
    r, c = np.nonzero(d)
    B = SpParMat3D.from_global_coo(grid, r, c, d[r, c], 16, 16, "row")
    parts = B.col_split(2)
    assert all(p.ncols == 8 for p in parts)
    back = SpParMat3D.col_concatenate(parts)
    np.testing.assert_allclose(back.to_dense(), d, rtol=1e-6)


@pytest.mark.parametrize("phases", [2, 4])
def test_mem_efficient_spgemm3d(rng, phases):
    grid = Grid3D.make(2, 2, 2)
    da = random_dense(rng, 16, 16, 0.3)
    db = random_dense(rng, 16, 16, 0.3)
    ra, ca = np.nonzero(da)
    rb, cb = np.nonzero(db)
    A = SpParMat3D.from_global_coo(grid, ra, ca, da[ra, ca], 16, 16, "col")
    B = SpParMat3D.from_global_coo(grid, rb, cb, db[rb, cb], 16, 16, "row")
    C = mem_efficient_spgemm3d(PLUS_TIMES, A, B, phases)
    np.testing.assert_allclose(C.to_dense(), da @ db, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("split", ["col", "row"])
def test_3d_roundtrip(rng, split):
    grid = Grid3D.make(2, 2, 2)
    d = random_dense(rng, 16, 16, 0.3)
    r, c = np.nonzero(d)
    A = SpParMat3D.from_global_coo(grid, r, c, d[r, c], 16, 16, split=split)
    np.testing.assert_allclose(A.to_dense(), d, rtol=1e-6)
    assert int(A.getnnz()) == len(r)


def test_summa3d_matches_dense(rng):
    grid = Grid3D.make(2, 2, 2)
    da = random_dense(rng, 16, 16, 0.3)
    db = random_dense(rng, 16, 16, 0.3)
    ra, ca = np.nonzero(da)
    rb, cb = np.nonzero(db)
    A = SpParMat3D.from_global_coo(grid, ra, ca, da[ra, ca], 16, 16, "col")
    B = SpParMat3D.from_global_coo(grid, rb, cb, db[rb, cb], 16, 16, "row")
    C = spgemm3d(PLUS_TIMES, A, B)
    assert C.split == "col"
    np.testing.assert_allclose(C.to_dense(), da @ db, rtol=1e-5, atol=1e-6)


def test_summa3d_single_layer_degenerates(rng):
    """L=1 must reproduce plain 2D SUMMA semantics."""
    grid = Grid3D.make(1, 2, 2)
    da = random_dense(rng, 12, 12, 0.4)
    db = random_dense(rng, 12, 12, 0.4)
    ra, ca = np.nonzero(da)
    rb, cb = np.nonzero(db)
    A = SpParMat3D.from_global_coo(grid, ra, ca, da[ra, ca], 12, 12, "col")
    B = SpParMat3D.from_global_coo(grid, rb, cb, db[rb, cb], 12, 12, "row")
    C = spgemm3d(PLUS_TIMES, A, B)
    np.testing.assert_allclose(C.to_dense(), da @ db, rtol=1e-5, atol=1e-6)


def test_summa3d_rectangular(rng):
    """A 32x16 · B 16x32 — exercises B's own row blocking in the sizing
    pass (a bug once used A's)."""
    grid = Grid3D.make(2, 2, 2)
    da = random_dense(rng, 32, 16, 0.3)
    db = random_dense(rng, 16, 32, 0.3)
    ra, ca = np.nonzero(da)
    rb, cb = np.nonzero(db)
    A = SpParMat3D.from_global_coo(grid, ra, ca, da[ra, ca], 32, 16, "col")
    B = SpParMat3D.from_global_coo(grid, rb, cb, db[rb, cb], 16, 32, "row")
    C = spgemm3d(PLUS_TIMES, A, B)
    np.testing.assert_allclose(C.to_dense(), da @ db, rtol=1e-5, atol=1e-6)


def test_summa3d_square(rng):
    """A·A (the MCL expansion shape) through the 3D path."""
    grid = Grid3D.make(2, 2, 2)
    d = random_dense(rng, 16, 16, 0.25)
    r, c = np.nonzero(d)
    A = SpParMat3D.from_global_coo(grid, r, c, d[r, c], 16, 16, "col")
    B = SpParMat3D.from_global_coo(grid, r, c, d[r, c], 16, 16, "row")
    C = spgemm3d(PLUS_TIMES, A, B)
    np.testing.assert_allclose(C.to_dense(), d @ d, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("split", ["col", "row"])
@pytest.mark.parametrize("shape2", [(2, 4), (4, 2)])
def test_2d_3d_conversion_roundtrip(rng, split, shape2):
    """On-device 2D→3D→2D conversion preserves the matrix exactly
    (≈ SpParMat3D(SpParMat&) + readback, SpParMat3D.cpp:74-145,197-320)."""
    from combblas_tpu.parallel.grid import Grid
    from combblas_tpu.parallel.mesh3d import Grid3D, SpParMat3D
    from combblas_tpu.parallel.spmat import SpParMat

    g2 = Grid.make(*shape2)
    g3 = Grid3D.make(2, 2, 2)
    n = 48
    d = random_dense(rng, n, n, 0.15)
    A = SpParMat.from_dense(g2, d)
    A3 = SpParMat3D.from_spmat(A, g3, split=split)
    assert A3.split == split
    np.testing.assert_allclose(A3.to_dense(), d)
    back = A3.to_spmat(g2)
    np.testing.assert_allclose(back.to_dense(), d)
    assert int(np.asarray(back.getnnz())) == int((d != 0).sum())


def test_3d_conversion_then_spgemm(rng):
    """Converted matrices are first-class: SUMMA3D on a converted pair
    matches the dense product (the SpGEMM3DTest pattern,
    ReleaseTests/CMakeLists.txt:43)."""
    from combblas_tpu.parallel.grid import Grid
    from combblas_tpu.parallel.mesh3d import Grid3D, SpParMat3D, spgemm3d
    from combblas_tpu.parallel.spmat import SpParMat

    g2 = Grid.make(2, 4)
    g3 = Grid3D.make(2, 2, 2)
    n = 32
    d = random_dense(rng, n, n, 0.2)
    A = SpParMat.from_dense(g2, d)
    A3 = SpParMat3D.from_spmat(A, g3, split="col")
    B3 = SpParMat3D.from_spmat(A, g3, split="row")
    C3 = spgemm3d(PLUS_TIMES, A3, B3)
    np.testing.assert_allclose(C3.to_dense(), d @ d, rtol=1e-5, atol=1e-6)
