"""3D (communication-avoiding) SpGEMM benchmark driver.

The ``mpipspgemm`` role (≈ 3DSpGEMM/test_mpipspgemm.cpp): A·A on an R-MAT
matrix across grid configurations L x pr x pc at fixed device count,
reporting per-configuration wall time — the experiment that shows the
layers/replication trade-off.

Single real chip cannot host a multi-device mesh, so by default this runs
on the virtual CPU mesh (XLA host-device-count): the numbers measure the
SCHEDULE (collective structure, stage counts, merge sizes), not TPU
silicon — on a real pod the same driver measures the real thing. Prints
one JSON line per configuration.

Knobs (mirroring spgemm_bench.py):
  BENCH_SCALE / BENCH_NDEV / BENCH_REPS
  BENCH_KERNEL      esc (default) | windowed | auto — the per-layer
                    local kernel (windowed = the round-9 sort-free
                    tier, ``spgemm3d_windowed``; backend via
                    COMBBLAS_SPGEMM_BACKEND)
  BENCH_RING=1      per-layer carousel schedule (round 13: the 3D
                    SUMMA now pipelines like the 2D rings); unset =
                    let the plan record / kernel default decide
  BENCH_PIPELINE=0  pin the carousel's rotate→compute→rotate serial
                    chain (the A/B measurement control)
  BENCH_MERGE       sort | runs | hash — the fiber-reduce combine
                    tier (round 13); unset = the library's
                    arg > store > env > heuristic resolution
  BENCH_EDGEFACTOR  R-MAT edge factor (default 8)
  BENCH_L           comma list of layer counts to sweep (default
                    "1,2,4,8"); capture runs pin one configuration
  BENCH_GOLDEN=1    verify each configuration EXACTLY against the scipy
                    A² golden (nnz + integer count values); defaults ON
                    up to scale 14, OFF above (the host golden is the
                    bottleneck there) — the env var always wins

Final stdout line is the COMPACT ``{summary, metric, value, median,
warning, rc}`` headline (mirrored to BENCH_SUMMARY.json) so the driver's
tail capture can never lose it — the same truncation-proof contract as
bench.py / spgemm_bench.py.  ``value`` is the BEST configuration's
ms/SpGEMM; ``metric`` names that configuration.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

SCALE = int(os.environ.get("BENCH_SCALE", "12"))
NDEV = int(os.environ.get("BENCH_NDEV", "8"))
REPS = int(os.environ.get("BENCH_REPS", "3"))
# esc | windowed | auto — auto resolves through the tuner precedence
# (plan store > COMBBLAS_SPGEMM3D_TIER env > "esc") and reports the
# provenance in the per-config JSON + final summary (round 10)
KERNEL = os.environ.get("BENCH_KERNEL", "esc")
# BENCH_PLAN_STORE / BENCH_PLAN_RECORD: the spgemm_bench.py round-10
# knobs — point the measured-plan store somewhere ("0" disables) and
# optionally write the BEST configuration's tier back (how 3D store
# records get seeded; spgemm3d has no probe pass).
if os.environ.get("BENCH_PLAN_STORE") is not None:
    os.environ["COMBBLAS_PLAN_STORE"] = os.environ["BENCH_PLAN_STORE"]
PLAN_RECORD = os.environ.get("BENCH_PLAN_RECORD", "0") == "1"
EDGEFACTOR = int(os.environ.get("BENCH_EDGEFACTOR", "8"))
# round-13 schedule/merge knobs (spgemm_bench parity): tri-state —
# unset defers to the library's plan-record / kernel defaults
_ring_env = os.environ.get("BENCH_RING", "")
RING = None if _ring_env == "" else _ring_env == "1"
_pipe_env = os.environ.get("BENCH_PIPELINE", "")
PIPELINE = None if _pipe_env == "" else _pipe_env == "1"
MERGE = os.environ.get("BENCH_MERGE", "") or None
if MERGE not in (None, "sort", "runs", "hash"):
    # vetted at the knob (round-12 SPMM_BACKEND precedent): a typo'd
    # BENCH_MERGE must not die in a bare library assert (stripped
    # under -O) nor persist an invalid plan record
    raise ValueError(
        f"BENCH_MERGE must be sort|runs|hash; got {MERGE!r}"
    )
_RINGTAG = (
    "" if RING is None
    else (("_ring" if PIPELINE in (None, True) else "_ringserial")
          if RING else "_noring")
)
_MERGETAG = f"_{MERGE}" if MERGE else ""
# golden scipy A² per configuration: default ON only at sweep scales
# where the host product is cheap — above scale 14 the ~1e9-nnz golden
# dominates (or OOMs) the run, so it becomes opt-in (env always wins)
GOLDEN = os.environ.get("BENCH_GOLDEN", "1" if SCALE <= 14 else "0") == "1"
_EFTAG = f"ef{EDGEFACTOR}" if EDGEFACTOR != 8 else ""


def emit_summary(official, rc: int = 0, path: str | None = None) -> None:
    """bench.py's final-line contract: a ~150-byte parseable summary as
    the LAST stdout line plus a BENCH_SUMMARY.json mirror, emitted even
    on a crash (the r05 tail-truncation postmortem)."""
    official = official or {}
    s = {
        "summary": 1,
        "metric": official.get("metric"),
        "value": official.get("value", 0.0),
        "median": official.get("median", official.get("value", 0.0)),
        "warning": official.get("warning"),
        "rc": rc,
    }
    # round-10 plan provenance rides along when present (still compact)
    for k in ("plan_source", "plan"):
        if official.get(k) is not None:
            s[k] = official[k]
    path = path or os.environ.get(
        "BENCH_SUMMARY_PATH", "BENCH_SUMMARY.json"
    )
    try:
        with open(path, "w") as f:
            json.dump(s, f)
            f.write("\n")
    except OSError as e:
        s["summary_write_error"] = f"{path}: {e}"
    print(json.dumps(s), flush=True)


def run() -> dict:
    if os.environ.get("JAX_PLATFORMS", "") != "tpu":
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={NDEV}"
        )
    import jax

    if os.environ.get("JAX_PLATFORMS", "") != "tpu":
        jax.config.update("jax_platforms", "cpu")
    import math

    import numpy as np

    from combblas_tpu import PLUS_TIMES, obs
    from combblas_tpu.parallel.mesh3d import Grid3D, SpParMat3D, spgemm3d
    from combblas_tpu.utils.rmat import rmat_symmetric_coo_host

    obs.enable_sidecar(f"spgemm3d-{KERNEL}")

    n = 1 << SCALE
    rows, cols = rmat_symmetric_coo_host(5, SCALE, EDGEFACTOR)
    key = rows * np.int64(n) + cols
    uniq = np.unique(key)
    ru, cu = uniq // n, uniq % n
    vals = np.ones(len(ru), np.float32)
    golden = None
    if GOLDEN:
        from scipy import sparse

        S = sparse.csr_matrix((vals, (ru, cu)), shape=(n, n))
        golden = S @ S
        golden.sort_indices()

    from combblas_tpu.tuner import config as tuner_config
    from combblas_tpu.tuner import store as tuner_store
    from combblas_tpu.tuner.resolve import resolve_tier

    store = tuner_store.get_store()

    layer_counts = tuple(
        int(x) for x in os.environ.get("BENCH_L", "1,2,4,8").split(",")
        if x.strip()
    )
    configs = []
    for L in layer_counts:
        if NDEV % L:
            continue
        p2 = NDEV // L
        p = int(math.isqrt(p2))
        if p * p != p2:
            continue
        configs.append((L, p, p))

    results = []
    for L, pr, pc in configs:
        g3 = Grid3D.make(L, pr, pc)
        # the local split must divide over layers
        if g3.local_cols(n) % L or g3.local_rows(n) % L:
            continue
        A3 = SpParMat3D.from_global_coo(g3, ru, cu, vals, n, n, split="col")
        B3 = SpParMat3D.from_global_coo(g3, ru, cu, vals, n, n, split="row")

        # per-config provenance (BENCH_KERNEL=auto follows the tuner
        # precedence; a named kernel is "arg").  For auto the bench
        # passes tier=None and lets the LIBRARY resolve — its lookup is
        # the one that counts hits and emits spgemm.auto.plan_source;
        # the mirror below (peek: no accounting) only fills the JSON.
        forced = None if KERNEL == "auto" else KERNEL
        cfg_key = tuner_store.plan_key_from_counts(
            "plus_times", n, n, n, len(ru), len(ru),
            tuner_config.env_backend() or "", f"{pr}x{pc}",
            grid3=f"{L}x{pr}x{pc}", op="spgemm3d",
        )
        # the shared store > env > heuristic walk (tuner.resolve),
        # account=False: peek only, no counters — the LIBRARY call
        # below does the accounted resolution; this mirror just fills
        # the provenance JSON (and now applies the same record vetting
        # the library does)
        tier, plan_source, _rec = resolve_tier(
            cfg_key, op="spgemm3d", allowed=("esc", "windowed"),
            heuristic="esc", tier=forced, store=store, account=False,
        )

        # merge provenance mirror: an explicit BENCH_MERGE wins; else a
        # store-routed record's remembered merge; else the library's
        # env/heuristic rung decides inside ("auto" here)
        merge_prov = MERGE or (
            _rec.merge if (_rec is not None and plan_source == "store")
            else None
        ) or "auto"

        def mult():
            return spgemm3d(
                PLUS_TIMES, A3, B3, tier=forced, merge=MERGE,
                ring=RING, pipeline=PIPELINE,
            )

        C = mult()  # warmup/compile + sizes caches
        jax.block_until_ready(C.vals)
        t0 = time.perf_counter()
        for _ in range(REPS):
            C = mult()
        jax.block_until_ready(C.vals)
        dt = (time.perf_counter() - t0) / REPS
        rec = {
            "metric": (
                f"spgemm3d_AxA_scale{SCALE}{_EFTAG}_{KERNEL}"
                f"{_RINGTAG}{_MERGETAG}_L{L}x{pr}x{pc}"
            ),
            "value": round(dt * 1e3, 1),
            "unit": "ms",
            "out_nnz": int(jax.device_get(C.getnnz())),
            "ndev": NDEV,
            "kernel": KERNEL,
            "tier": tier,
            "merge": merge_prov,
            "ring": RING,
            "pipeline": PIPELINE,
            "plan_source": plan_source,
            "plan_key_grid3": f"{L}x{pr}x{pc}",
        }
        if golden is not None:
            gr, gc_, gv = C.to_global_coo()
            from scipy import sparse

            got = sparse.csr_matrix((gv, (gr, gc_)), shape=(n, n))
            got.sum_duplicates()
            got.sort_indices()
            rec["golden_nnz"] = int(golden.nnz)
            rec["golden_exact"] = bool(
                got.nnz == golden.nnz
                and np.array_equal(got.indptr, golden.indptr)
                and np.array_equal(got.indices, golden.indices)
                and np.array_equal(got.data, golden.data)
            )
        print(json.dumps(rec), flush=True)
        results.append(rec)

    if not results:
        return {"metric": None, "value": 0.0,
                "warning": "no admissible L x pr x pc configuration"}
    best = min(results, key=lambda r: r["value"])
    vals_ms = sorted(r["value"] for r in results)
    warning = None
    if golden is not None and not all(
        r.get("golden_exact") for r in results
    ):
        warning = "golden mismatch in at least one configuration"
    if PLAN_RECORD and store is not None:
        # seed the 3D plan store with the best configuration's tier
        # (keyed to ITS grid3; a later auto run routes through it) —
        # only when it beats the remembered cost (sweep-order must not
        # decide which plan survives)
        bL, bpr, bpc = best["plan_key_grid3"].split("x")
        best_key = tuner_store.plan_key_from_counts(
            "plus_times", n, n, n, len(ru), len(ru),
            tuner_config.env_backend() or "", f"{bpr}x{bpc}",
            grid3=best["plan_key_grid3"], op="spgemm3d",
        )
        prev = store.peek(best_key)
        if (
            prev is None
            or prev.cost_s is None
            or prev.cost_s > best["value"] / 1e3
        ):
            store.put(best_key, tuner_store.PlanRecord(
                tier=best["tier"], cost_s=best["value"] / 1e3,
                source="bench",
                # schedule/merge provenance rides the record (round
                # 13): only knobs the bench actually forced persist —
                # an "auto" merge stays None so replay re-resolves
                merge=MERGE,
                ring=bool(RING) if RING is not None else False,
                pipeline=bool(PIPELINE) if PIPELINE is not None
                else True,
            ))
    if obs.ENABLED:
        obs.dump_jsonl()
    return {
        "metric": best["metric"],
        "value": best["value"],
        "median": vals_ms[(len(vals_ms) - 1) // 2],
        "warning": warning,
        "plan_source": best["plan_source"],
        "plan": {
            "tier": best["tier"], "grid3": best["plan_key_grid3"],
            "merge": best["merge"], "ring": best["ring"],
            "pipeline": best["pipeline"],
        },
        "tuner": None if store is None else store.stats(),
    }


def main():
    try:
        official = run()
    except BaseException as e:  # the contract holds even on a crash
        emit_summary(
            {"metric": f"spgemm3d_scale{SCALE}_{KERNEL}",
             "warning": f"{type(e).__name__}: {e}"},
            rc=1,
        )
        raise
    emit_summary(
        official, rc=0 if official.get("warning") is None else 1
    )


if __name__ == "__main__":
    main()
