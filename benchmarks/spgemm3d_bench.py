"""3D (communication-avoiding) SpGEMM benchmark driver.

The ``mpipspgemm`` role (≈ 3DSpGEMM/test_mpipspgemm.cpp): A·A on an R-MAT
matrix across grid configurations L x pr x pc at fixed device count,
reporting per-configuration wall time — the experiment that shows the
layers/replication trade-off.

Single real chip cannot host a multi-device mesh, so by default this runs
on the virtual CPU mesh (XLA host-device-count): the numbers measure the
SCHEDULE (collective structure, stage counts, merge sizes), not TPU
silicon — on a real pod the same driver measures the real thing. Prints
one JSON line per configuration.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

SCALE = int(os.environ.get("BENCH_SCALE", "12"))
NDEV = int(os.environ.get("BENCH_NDEV", "8"))
REPS = int(os.environ.get("BENCH_REPS", "3"))


def main():
    if os.environ.get("JAX_PLATFORMS", "") != "tpu":
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={NDEV}"
        )
    import jax

    if os.environ.get("JAX_PLATFORMS", "") != "tpu":
        jax.config.update("jax_platforms", "cpu")
    import math

    import numpy as np

    from combblas_tpu import PLUS_TIMES
    from combblas_tpu.parallel.mesh3d import Grid3D, SpParMat3D, spgemm3d
    from combblas_tpu.utils.rmat import rmat_symmetric_coo_host

    n = 1 << SCALE
    rows, cols = rmat_symmetric_coo_host(5, SCALE, 8)
    key = rows * np.int64(n) + cols
    uniq = np.unique(key)
    ru, cu = uniq // n, uniq % n
    vals = np.ones(len(ru), np.float32)

    configs = []
    for L in (1, 2, 4, 8):
        if NDEV % L:
            continue
        p2 = NDEV // L
        p = int(math.isqrt(p2))
        if p * p != p2:
            continue
        configs.append((L, p, p))

    for L, pr, pc in configs:
        g3 = Grid3D.make(L, pr, pc)
        # pad n so the local split divides over layers
        lc = g3.local_cols(n)
        if lc % L:
            continue
        A3 = SpParMat3D.from_global_coo(g3, ru, cu, vals, n, n, split="col")
        B3 = SpParMat3D.from_global_coo(g3, ru, cu, vals, n, n, split="row")
        C = spgemm3d(PLUS_TIMES, A3, B3)  # warmup/compile + sizes caches
        jax.block_until_ready(C.vals)
        t0 = time.perf_counter()
        for _ in range(REPS):
            C = spgemm3d(PLUS_TIMES, A3, B3)
        jax.block_until_ready(C.vals)
        dt = (time.perf_counter() - t0) / REPS
        print(
            json.dumps(
                {
                    "metric": f"spgemm3d_AxA_scale{SCALE}_L{L}x{pr}x{pc}",
                    "value": round(dt * 1e3, 1),
                    "unit": "ms",
                    "out_nnz": int(jax.device_get(C.getnnz())),
                    "ndev": NDEV,
                }
            )
        )


if __name__ == "__main__":
    main()
