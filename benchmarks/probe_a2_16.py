"""Round-5 probe: dense-panel A² economics at scale 16 (n = 65536).

VERDICT r4 item 3 asks for the MXU dense strategy's viability past
n = 32K, "or a written floor argument with measured panel probes".
This probe measures the two components of a column-panel-phased dense
A² (the ColSplit(phases) idea, ParFriends.h:550-577, applied to dense
panels):

  MODE=panel    — bf16 [n, n] @ [n, W] MXU panel matmul rate
                  (REPS panels in one fori_loop launch, anti-DCE chained)
  MODE=extract  — sparsify_windowed rate on an [n, W] f32 panel at the
                  measured A² per-panel density (~164M/65536 ≈ 2.5K
                  nnz/col at scale 16)

Full-A² floor = n/W panels x (panel_s + extract_s). One MODE per
process (readback poison).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np

from combblas_tpu.utils.compile_cache import enable_compile_cache

enable_compile_cache()

MODE = os.environ.get("MODE", "panel")
SCALE = int(os.environ.get("BENCH_SCALE", "16"))
W = int(os.environ.get("PROBE_W", "512"))
REPS = int(os.environ.get("PROBE_REPS", "8"))
DRAIN = float(os.environ.get("PROBE_DRAIN_S", "10"))


def main():
    n = 1 << SCALE
    from benchmarks.apps_bench import _graph

    r, c, _ = _graph(SCALE, ef=8)
    nnz = len(r)

    if MODE == "panel":
        @jax.jit
        def build(rr, cc):
            d = jnp.zeros((n, n), jnp.bfloat16)
            return d.at[rr, cc].set(jnp.bfloat16(1.0), mode="drop")

        d = build(jnp.asarray(r, jnp.int32), jnp.asarray(c, jnp.int32))

        @jax.jit
        def panels(dd):
            def body(i, carry):
                j0 = (i * W) % (n - W)
                p = jax.lax.dynamic_slice(dd, (0, j0), (n, W))
                out = jnp.dot(
                    dd, p, preferred_element_type=jnp.float32
                )  # [n, W] f32
                # anti-DCE: unprovable predicate on the panel result
                return jnp.where(jnp.min(out) == -5.0, carry + i, carry)

            return jax.lax.fori_loop(0, REPS, body, jnp.float32(0))

        out = panels(d)
        jax.block_until_ready(out)
        time.sleep(DRAIN)
        t0 = time.perf_counter()
        out = panels(d)
        v = float(jax.device_get(out))
        dt = time.perf_counter() - t0
        per_panel = dt / REPS
        flops = 2.0 * n * n * W
        print(json.dumps({
            "mode": MODE, "n": n, "W": W, "reps": REPS,
            "dt_s": round(dt, 3), "s_per_panel": round(per_panel, 4),
            "TFLOPs": round(flops / per_panel / 1e12, 2),
            "full_A2_matmul_s": round(per_panel * n / W, 1),
            "sink": v, "nnz": nnz,
        }), flush=True)
    elif MODE == "extract":
        from combblas_tpu.ops.spgemm import sparsify_windowed

        # synthetic panel at the measured A2 density: 164M nnz over n
        # cols ~ 2500/col at scale 16 (spgemm_r3b out_nnz)
        dens = float(os.environ.get("PROBE_DENS", "0.04"))
        rng = np.random.default_rng(0)
        panel = np.where(
            rng.random((n, W)) < dens, rng.random((n, W)), 0.0
        ).astype(np.float32)
        cap = 1 << int(panel.astype(bool).sum() * 1.1).bit_length()
        pd = jax.device_put(panel)

        @jax.jit
        def ex(p):
            t, total = sparsify_windowed(p, 0.0, n, W, cap)
            return t.rows, t.cols, t.vals, total

        out = ex(pd)
        jax.block_until_ready(out[3])
        time.sleep(DRAIN)
        t0 = time.perf_counter()
        out = ex(pd)
        total = int(jax.device_get(out[3]))
        dt = time.perf_counter() - t0
        print(json.dumps({
            "mode": MODE, "n": n, "W": W, "panel_nnz": total,
            "dt_s": round(dt, 3),
            "Mnnz_per_s": round(total / dt / 1e6, 2),
            "full_A2_extract_s_at_164M": round(164e6 / (total / dt), 1),
            "cap": cap,
        }), flush=True)


if __name__ == "__main__":
    main()
