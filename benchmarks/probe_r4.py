"""Round-4 single-experiment probes (axon-safe, one experiment per process).

Usage:  python benchmarks/probe_r4.py EXPERIMENT [ARGS...]

Same protocol as instrument.py: fresh process, one warmup + sleep drain,
ONE timed section closed by a single scalar D2H, one JSON line on stdout.

The round-4 question is how to break the ~22 M/s per-element random-memory
wall for SpGEMM accumulation (VERDICT r3 item 1). Candidate escape routes,
one probe each:

  mxu DT N R        dense [N,N]x[N,N] matmul rate with dtype DT in
                    {bf16, f32} (accumulate f32). If bf16 runs at tens of
                    TFLOP/s, DENSE blocked A^2 beats any sparse formulation
                    at bench scales (n=16K..64K) outright.
  mxu3 N R          bf16x3 split-float matmul (hi/lo decomposition, 3
                    bf16 matmuls ~ f32 precision): the precision-restoring
                    variant of the dense path.
  pdma MB R         Pallas double-buffered HBM->VMEM->HBM copy bandwidth
                    (is the XLA-measured 11 GB/s "streaming" a chip limit
                    or an XLA artifact?).
  pscat T N R       Pallas scalar scatter-accumulate: fori_loop of
                    acc[idx[i]] += val[i] into a T-KB VMEM table, N random
                    indices streamed from HBM. The rate bound for any
                    VMEM-resident accumulation kernel.
  pscatv T N R      same, but 8-way vectorized attempt: load 8 idx/vals as
                    a vector, 8 scalar updates per loop step (amortizes
                    loop overhead).
  densepath SCALE   end-to-end dense A^2 at SCALE: sparse->dense scatter
                    (bf16), matmul f32-accum, nnz count of result. The
                    realistic dense-SpGEMM number including conversions.
  cumsum2d M N R    row-wise cumsum over [M,N] f32 (the dense->sparse
                    extraction primitive).
  topk M N K R      lax.top_k(k=K) per row over [M,N] (the dense MCL prune
                    primitive).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))


def timed_once(run, sync):
    t0 = time.perf_counter()
    out = run()
    sync(out)
    return time.perf_counter() - t0


def exp_mxu(dt: str, N: int, R: int):
    import jax
    import jax.numpy as jnp
    from jax import lax

    dtype = {"bf16": jnp.bfloat16, "f32": jnp.float32}[dt]
    a = jax.device_put(jnp.ones((N, N), dtype))
    b = jax.device_put(jnp.ones((N, N), dtype))

    @jax.jit
    def run(a, b):
        def body(_, carry):
            c = jnp.dot(a, carry.astype(dtype),
                        preferred_element_type=jnp.float32)
            return c * (1.0 / N)  # keep values bounded across iterations
        return lax.fori_loop(0, R, body, b.astype(jnp.float32))

    out = run(a, b)
    jax.block_until_ready(out)
    time.sleep(3.0)
    dt_s = timed_once(lambda: run(a, b), lambda o: float(jax.device_get(o[0, 0])))
    flops = 2.0 * N * N * N * R
    return {
        "experiment": f"mxu {dt} N={N} R={R}",
        "dt_s": round(dt_s, 4),
        "TFLOPs": round(flops / dt_s / 1e12, 2),
    }


def exp_mxu3(N: int, R: int):
    """Split-float bf16x3: a = hi + lo, c = hi@hi + hi@lo + lo@hi."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    a = jax.device_put(jnp.ones((N, N), jnp.float32))

    def split(x):
        hi = x.astype(jnp.bfloat16)
        lo = (x - hi.astype(jnp.float32)).astype(jnp.bfloat16)
        return hi, lo

    @jax.jit
    def run(a):
        def body(_, carry):
            ah, al = split(a)
            bh, bl = split(carry)
            c = (jnp.dot(ah, bh, preferred_element_type=jnp.float32)
                 + jnp.dot(ah, bl, preferred_element_type=jnp.float32)
                 + jnp.dot(al, bh, preferred_element_type=jnp.float32))
            return c * (1.0 / N)
        return lax.fori_loop(0, R, body, a)

    out = run(a)
    jax.block_until_ready(out)
    time.sleep(3.0)
    dt_s = timed_once(lambda: run(a), lambda o: float(jax.device_get(o[0, 0])))
    flops = 2.0 * N * N * N * R  # logical flops (not the 3x physical)
    return {
        "experiment": f"mxu3 N={N} R={R}",
        "dt_s": round(dt_s, 4),
        "logical_TFLOPs": round(flops / dt_s / 1e12, 2),
    }


def exp_pdma(mb: int, R: int):
    """Pallas grid-pipelined copy: HBM -> VMEM -> HBM, [n, 512] f32 blocks.

    The automatic BlockSpec pipeline double-buffers DMA; measures what
    bandwidth Pallas can actually move (vs the XLA-level 11 GB/s)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = mb * 1024 * 1024 // 4 // 512
    x = jax.device_put(jnp.ones((n, 512), jnp.float32))
    BR = 1024

    def kernel(x_ref, o_ref):
        o_ref[...] = x_ref[...] + 1.0

    def copy(x):
        return pl.pallas_call(
            kernel,
            grid=(n // BR,),
            in_specs=[pl.BlockSpec((BR, 512), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec((BR, 512), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((n, 512), jnp.float32),
        )(x)

    @jax.jit
    def run(x):
        def body(_, carry):
            return copy(carry)
        return lax.fori_loop(0, R, body, x)

    out = run(x)
    jax.block_until_ready(out)
    time.sleep(3.0)
    dt_s = timed_once(lambda: run(x), lambda o: float(jax.device_get(o[0, 0])))
    bytes_moved = 2.0 * n * 512 * 4 * R  # read + write
    return {
        "experiment": f"pdma {mb}MB R={R}",
        "dt_s": round(dt_s, 4),
        "GBps": round(bytes_moved / dt_s / 1e9, 2),
    }


def _pscat_common(tkb: int, n_idx: int, R: int, vec_w: int):
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    tsize = tkb * 1024 // 4
    rng = np.random.default_rng(0)
    idx_h = rng.integers(0, tsize, size=n_idx).astype(np.int32)
    idx = jax.device_put(jnp.asarray(idx_h))
    vals = jax.device_put(jnp.ones((n_idx,), jnp.float32))

    # table as [tsize//128, 128] (2D for TPU); idx decomposed as (row, col)
    trows = tsize // 128

    def kernel(idx_ref, val_ref, o_ref, acc_ref):
        @pl.when(pl.program_id(0) == 0)
        def _():
            acc_ref[...] = jnp.zeros_like(acc_ref)

        nloc = idx_ref.shape[0]

        def body(i, _):
            if vec_w == 1:
                ix = idx_ref[i]
                r, c = ix // 128, ix % 128
                acc_ref[r, c] += val_ref[i]
            else:
                for u in range(vec_w):
                    ix = idx_ref[i * vec_w + u]
                    r, c = ix // 128, ix % 128
                    acc_ref[r, c] += val_ref[i * vec_w + u]
            return 0

        lax.fori_loop(0, nloc // vec_w, body, 0)

        @pl.when(pl.program_id(0) == pl.num_programs(0) - 1)
        def _():
            o_ref[...] = acc_ref[...]

    CH = 131072

    def scat(idx, vals):
        return pl.pallas_call(
            kernel,
            grid=(n_idx // CH,),
            in_specs=[
                pl.BlockSpec((CH,), lambda i: (i,), memory_space=pltpu.VMEM),
                pl.BlockSpec((CH,), lambda i: (i,), memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((trows, 128), lambda i: (0, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((trows, 128), jnp.float32),
            scratch_shapes=[pltpu.VMEM((trows, 128), jnp.float32)],
        )(idx, vals)

    @jax.jit
    def run(idx, vals):
        def body(_, carry):
            o = scat(idx, vals + carry)
            return o[0, 0] * 0.0
        return lax.fori_loop(0, R, body, jnp.float32(0.0))

    out = run(idx, vals)
    jax.block_until_ready(out)
    time.sleep(3.0)
    dt_s = timed_once(lambda: run(idx, vals), lambda o: float(jax.device_get(o)))
    return {
        "experiment": f"pscat{'v' if vec_w > 1 else ''} T={tkb}KB N={n_idx} R={R}",
        "dt_s": round(dt_s, 4),
        "Mscat_per_s": round(n_idx * R / dt_s / 1e6, 1),
    }


def exp_pscat(tkb: int, n_idx: int, R: int):
    return _pscat_common(tkb, n_idx, R, 1)


def exp_pscatv(tkb: int, n_idx: int, R: int):
    return _pscat_common(tkb, n_idx, R, 8)


def exp_densepath(scale: int):
    """End-to-end dense A^2: COO->dense (bf16) -> matmul (f32 accum) ->
    nnz count. R-MAT graph at SCALE; one launch, timed."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    from combblas_tpu.utils.rmat import rmat_symmetric_coo_host

    n = 1 << scale
    rows, cols = rmat_symmetric_coo_host(42, scale, 8)
    key = rows * np.int64(n) + cols
    uniq = np.unique(key)
    rows_u = jnp.asarray((uniq // n).astype(np.int32))
    cols_u = jnp.asarray((uniq % n).astype(np.int32))
    nnz = len(uniq)
    # true flop count: for C = A@A, each entry (i,k) contributes deg_row(k)
    rdeg = np.bincount((uniq // n).astype(np.int64), minlength=n)
    flops = float(np.sum(rdeg[(uniq % n).astype(np.int64)]))

    @jax.jit
    def run(r, c):
        d = jnp.zeros((n, n), jnp.bfloat16)
        d = d.at[r, c].set(jnp.bfloat16(1.0), mode="drop")
        c2 = jnp.dot(d, d, preferred_element_type=jnp.float32)
        return jnp.sum((c2 != 0).astype(jnp.int32)), c2[0, 0]

    out = run(rows_u, cols_u)
    jax.block_until_ready(out)
    time.sleep(3.0)
    dt_s = timed_once(lambda: run(rows_u, cols_u),
                      lambda o: int(jax.device_get(o[0])))
    return {
        "experiment": f"densepath scale={scale}",
        "n": n, "nnz": int(nnz),
        "flops_M": round(flops / 1e6, 2),
        "dt_s": round(dt_s, 4),
        "MFLOPs": round(flops / dt_s / 1e6, 2),
    }


def exp_mxu_i8(N: int, R: int):
    """int8 x int8 -> int32 matmul rate (exact for 0/1 adjacency inputs
    with counts < 2^31 — the Graph500/TC dense-squaring mode)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    a = jax.device_put(jnp.ones((N, N), jnp.int8))

    @jax.jit
    def run(a):
        def body(_, carry):
            c = jnp.dot(a, carry, preferred_element_type=jnp.int32)
            return (c & 1).astype(jnp.int8)  # cheap re-binarization
        return lax.fori_loop(0, R, body, a)

    out = run(a)
    jax.block_until_ready(out)
    time.sleep(3.0)
    dt_s = timed_once(lambda: run(a), lambda o: int(jax.device_get(o[0, 0])))
    flops = 2.0 * N * N * N * R
    return {
        "experiment": f"mxu_i8 N={N} R={R}",
        "dt_s": round(dt_s, 4),
        "TOPs": round(flops / dt_s / 1e12, 2),
    }


def exp_mxu_large(dt: str, N: int, R: int):
    """Matmul rate at large N with NO per-iteration cast traffic: chain
    C = A@C' where C' stays in the compute dtype (values decay but the
    timing is what matters)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    dtype = {"bf16": jnp.bfloat16, "f32": jnp.float32}[dt]
    a = jax.device_put(jnp.full((N, N), 1e-3, dtype))

    @jax.jit
    def run(a):
        def body(_, carry):
            return jnp.dot(a, carry, preferred_element_type=dtype)
        return lax.fori_loop(0, R, body, a)

    out = run(a)
    jax.block_until_ready(out)
    time.sleep(3.0)
    dt_s = timed_once(lambda: run(a), lambda o: float(jax.device_get(o[0, 0])))
    flops = 2.0 * N * N * N * R
    return {
        "experiment": f"mxu_large {dt} N={N} R={R}",
        "dt_s": round(dt_s, 4),
        "TFLOPs": round(flops / dt_s / 1e12, 2),
    }


def exp_psort(t_log2: int, R: int):
    """Pallas bitonic tile sort: T=2^t_log2 uint32 keys + f32 payload,
    sorted entirely in VMEM via XOR-partner roll+select stages. The
    candidate replacement for XLA's 19-38 Mkeys/s sort."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    T = 1 << t_log2
    RW = T // 128

    def partner(x, j):
        if j >= 128:
            m = j // 128
            n0 = x.shape[0]
            down = pltpu.roll(x, n0 - m, 0)
            up = pltpu.roll(x, m, 0)
            rr = jax.lax.broadcasted_iota(jnp.int32, x.shape, 0)
            return jnp.where((rr & m) == 0, down, up)
        down = pltpu.roll(x, 128 - j, 1)
        up = pltpu.roll(x, j, 1)
        cc = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
        return jnp.where((cc & j) == 0, down, up)

    def sort_kernel(k_ref, v_ref, ko_ref, vo_ref):
        keys = k_ref[...]
        vals = v_ref[...]
        rr = jax.lax.broadcasted_iota(jnp.int32, keys.shape, 0)
        cc = jax.lax.broadcasted_iota(jnp.int32, keys.shape, 1)
        idx = rr * 128 + cc
        kk = 2
        while kk <= T:
            j = kk // 2
            while j >= 1:
                pk = partner(keys, j)
                pv = partner(vals, j)
                asc = (idx & kk) == 0
                i_lower = (idx & j) == 0
                take_self = jnp.where(asc == i_lower, keys <= pk, keys >= pk)
                keys = jnp.where(take_self, keys, pk)
                vals = jnp.where(take_self, vals, pv)
                j //= 2
            kk *= 2
        ko_ref[...] = keys
        vo_ref[...] = vals

    rng = np.random.default_rng(0)
    keys = jax.device_put(jnp.asarray(
        rng.integers(0, 1 << 30, size=T).astype(np.uint32).reshape(RW, 128)))
    vals = jax.device_put(jnp.asarray(
        rng.random(T).astype(np.float32).reshape(RW, 128)))

    def psort(k, v):
        return pl.pallas_call(
            sort_kernel,
            out_shape=(jax.ShapeDtypeStruct((RW, 128), jnp.uint32),
                       jax.ShapeDtypeStruct((RW, 128), jnp.float32)),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 2,
            out_specs=(pl.BlockSpec(memory_space=pltpu.VMEM),) * 2,
        )(k, v)

    @jax.jit
    def run(k, v):
        def body(_, carry):
            ks, vs = psort(carry[0], carry[1])
            # re-shuffle cheaply so the next sort isn't on sorted input
            return (ks[::-1, :], vs)
        return lax.fori_loop(0, R, body, (k, v))

    out = run(keys, vals)
    jax.block_until_ready(out)
    time.sleep(3.0)
    dt_s = timed_once(lambda: run(keys, vals),
                      lambda o: int(jax.device_get(o[0][0, 0])))
    return {
        "experiment": f"psort T=2^{t_log2} R={R}",
        "dt_s": round(dt_s, 4),
        "Mkeys_per_s": round(T * R / dt_s / 1e6, 1),
    }


def exp_psparsify(m: int, ncol: int, density_pct: int, ph: int, R: int):
    """Chip rate of the Pallas butterfly-pack sparsify (ops/pallas_sparsify)
    on a synthetic [m, ncol] f32 matrix at the given % density."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax import lax

    from combblas_tpu.ops.pallas_sparsify import dense_to_tuples_arrays

    rng = np.random.default_rng(0)
    x_h = np.where(
        rng.random((m, ncol)) < density_pct / 100.0,
        rng.random((m, ncol)).astype(np.float32) + 0.5, 0.0
    ).astype(np.float32)
    nnz = int((x_h != 0).sum())
    cap = 1 << int(np.ceil(np.log2(max(nnz, 2) * 1.05)))
    x = jax.device_put(jnp.asarray(x_h))

    @jax.jit
    def run(x):
        def body(_, carry):
            fi, fv, total, end_row = dense_to_tuples_arrays(
                carry, capacity=cap, panel_rows=ph)
            return carry + (total.astype(jnp.float32) * 0.0)
        return lax.fori_loop(0, R, body, x)

    out = run(x)
    jax.block_until_ready(out)
    time.sleep(3.0)
    dt_s = timed_once(lambda: run(x), lambda o: float(jax.device_get(o[0, 0])))
    # correctness spot check AFTER timing (poisons, fine)
    fi, fv, total, end_row = jax.jit(
        lambda x: dense_to_tuples_arrays(x, capacity=cap, panel_rows=ph)
    )(x)
    ok = int(jax.device_get(total)) == nnz
    return {
        "experiment": f"psparsify {m}x{ncol} d={density_pct}% ph={ph} R={R}",
        "nnz": nnz,
        "dt_s": round(dt_s, 4),
        "Mcells_per_s": round(m * ncol * R / dt_s / 1e6, 1),
        "Mnnz_per_s": round(nnz * R / dt_s / 1e6, 1),
        "total_ok": ok,
    }


def _pallas_op_chain(opname: str, nops: int, R: int, rows: int = 8192):
    """Sustained rate of a chained vector op inside ONE Pallas kernel on a
    VMEM-resident [rows, 128] f32 array. Classifies which Mosaic ops hit
    the ~2.5-7 G elem-op/s wall seen in the butterfly-pack kernel."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def kernel(x_ref, o_ref):
        x = x_ref[...]
        acc = x
        cc = lax.broadcasted_iota(jnp.int32, x.shape, 1)
        for i in range(nops):
            if opname == "add":
                acc = acc + x
            elif opname == "select":
                acc = jnp.where((cc & (1 << (i % 7))) != 0, acc, x)
            elif opname == "roll0":
                acc = pltpu.roll(acc, (7 * i + 1) % rows, 0)
            elif opname == "roll1":
                acc = pltpu.roll(acc, (7 * i + 1) % 128, 1)
            elif opname == "roll0_8":
                acc = pltpu.roll(acc, 8 * ((7 * i) % (rows // 8)) + 8, 0)
            elif opname == "mxushift":
                # lane shift as matmul with a shifted identity
                sh = (jnp.eye(128, k=1, dtype=jnp.bfloat16)
                      if i % 2 == 0 else jnp.eye(128, k=-1, dtype=jnp.bfloat16))
                acc = jnp.dot(acc.astype(jnp.bfloat16), sh,
                              preferred_element_type=jnp.float32)
            else:
                raise ValueError(opname)
        o_ref[...] = acc

    def run_once(x):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((rows, 128), jnp.float32),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            compiler_params=pltpu.CompilerParams(
                vmem_limit_bytes=100 * 1024 * 1024,
            ),
        )(x)

    x = jax.device_put(jnp.ones((rows, 128), jnp.float32))

    @jax.jit
    def run(x):
        def body(_, carry):
            return run_once(carry) * 0.5
        return lax.fori_loop(0, R, body, x)

    out = run(x)
    jax.block_until_ready(out)
    time.sleep(3.0)
    dt_s = timed_once(lambda: run(x), lambda o: float(jax.device_get(o[0, 0])))
    return {
        "experiment": f"pop {opname} nops={nops} rows={rows} R={R}",
        "dt_s": round(dt_s, 4),
        "Gelem_op_per_s": round(rows * 128 * nops * R / dt_s / 1e9, 2),
    }


def exp_densespgemm(scale: int, sparsifier: str = "windowed"):
    """End-to-end dense A^2 WITH extraction: COO->bf16 dense -> MXU matmul
    (f32 accum) -> sparse tuples via the chosen extractor ("windowed" =
    ops.spgemm.sparsify_windowed; "pallas" = butterfly-pack; "none").
    One launch, timed; correctness checked after timing vs scipy."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from scipy import sparse

    from combblas_tpu.ops.spgemm import sparsify_windowed
    from combblas_tpu.utils.rmat import rmat_symmetric_coo_host

    n = 1 << scale
    rows, cols = rmat_symmetric_coo_host(5, scale, 8)
    key = rows * np.int64(n) + cols
    uniq = np.unique(key)
    ru = jnp.asarray((uniq // n).astype(np.int32))
    cu = jnp.asarray((uniq % n).astype(np.int32))
    S = sparse.csr_matrix(
        (np.ones(len(uniq), np.float32), ((uniq // n), (uniq % n))),
        shape=(n, n))
    C_ref = S @ S
    nnz_out = int(C_ref.nnz)
    rdeg = np.bincount((uniq // n).astype(np.int64), minlength=n)
    flops = float(np.sum(rdeg[(uniq % n).astype(np.int64)]))
    cap = 1 << int(np.ceil(np.log2(nnz_out * 1.05)))

    @jax.jit
    def run(r, c):
        d = jnp.zeros((n, n), jnp.bfloat16)
        d = d.at[r, c].set(jnp.bfloat16(1.0), mode="drop")
        c2 = jnp.dot(d, d, preferred_element_type=jnp.float32)
        if sparsifier == "windowed":
            t, total = sparsify_windowed(c2, 0.0, n, n, cap)
            return t.rows, t.cols, t.vals, total
        elif sparsifier == "pallas":
            from combblas_tpu.ops.pallas_sparsify import dense_to_sptuples
            t, total = dense_to_sptuples(c2, n, n, capacity=cap)
            return t.rows, t.cols, t.vals, total
        else:
            return r, c, jnp.sum(c2), jnp.sum((c2 != 0).astype(jnp.int32))

    out = run(ru, cu)
    jax.block_until_ready(out)
    time.sleep(5.0)
    dt_s = timed_once(lambda: run(ru, cu),
                      lambda o: int(jax.device_get(o[3])))
    res = {
        "experiment": f"densespgemm scale={scale} sparsifier={sparsifier}",
        "flops_M": round(flops / 1e6, 2),
        "out_nnz": nnz_out,
        "got_nnz": int(jax.device_get(out[3])),
        "dt_s": round(dt_s, 4),
        "MFLOPs": round(flops / dt_s / 1e6, 2),
    }
    if sparsifier != "none":
        rr = np.asarray(jax.device_get(out[0]))
        cc = np.asarray(jax.device_get(out[1]))
        vv = np.asarray(jax.device_get(out[2]))
        live = rr < n
        vsum = float(vv[live].sum())
        res["live_nnz_ok"] = bool(int(live.sum()) == nnz_out)
        res["vsum_ok"] = bool(
            abs(vsum - float(C_ref.sum())) < 1e-2 * float(C_ref.sum()))
    return res


def exp_pwindowed(m: int, ncol: int, density_pct: int, R: int):
    """sparsify_windowed alone on an on-device synthetic [m, ncol] f32
    dense matrix (threshold of threefry bits) — memory + rate isolation."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from combblas_tpu.ops.spgemm import sparsify_windowed

    approx = int(m * ncol * density_pct / 100 * 1.1)
    cap = 1 << max(int(approx) - 1, 1).bit_length()

    @jax.jit
    def run(key):
        u = jax.random.uniform(key, (m, ncol), jnp.float32)
        x = jnp.where(u < density_pct / 100.0, u + 0.5, 0.0)

        def body(_, carry):
            # fold-proof dependency: the carry perturbs the input by a
            # data-dependent (but value-preserving) amount; a `* 0.0`
            # dependency here was DCE'd and measured an empty program
            t, total = sparsify_windowed(
                x + (carry % jnp.float32(1e-30)), 0.0, m, ncol, cap)
            return carry + jnp.minimum(total, 7).astype(jnp.float32)
        tot = lax.fori_loop(0, R, body, jnp.float32(0.0))
        _, total = sparsify_windowed(x, 0.0, m, ncol, cap)
        return tot, total

    key = jax.random.PRNGKey(0)
    out = run(key)
    jax.block_until_ready(out)
    time.sleep(3.0)
    dt_s = timed_once(lambda: run(key), lambda o: float(jax.device_get(o[0])))
    return {
        "experiment": f"pwindowed {m}x{ncol} d={density_pct}% R={R}",
        "total": int(jax.device_get(out[1])),
        "cap": cap,
        "dt_s": round(dt_s, 4),
        "Mcells_per_s": round(m * ncol * (R + 1) / dt_s / 1e6, 1),
        "Mnnz_per_s": round(
            int(jax.device_get(out[1])) * (R + 1) / dt_s / 1e6, 1),
    }


def exp_densewin2(scale: int):
    """densespgemm variant: matmul and extraction as TWO jit programs
    (device-resident handoff, no readback between) — isolates whether the
    one-program composition triggers XLA remat of the matmul inside the
    extraction's lax.map."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from scipy import sparse

    from combblas_tpu.ops.spgemm import sparsify_windowed
    from combblas_tpu.utils.rmat import rmat_symmetric_coo_host

    n = 1 << scale
    rows, cols = rmat_symmetric_coo_host(5, scale, 8)
    key = rows * np.int64(n) + cols
    uniq = np.unique(key)
    ru = jnp.asarray((uniq // n).astype(np.int32))
    cu = jnp.asarray((uniq % n).astype(np.int32))
    S = sparse.csr_matrix(
        (np.ones(len(uniq), np.float32), ((uniq // n), (uniq % n))),
        shape=(n, n))
    nnz_out = int((S @ S).nnz)
    rdeg = np.bincount((uniq // n).astype(np.int64), minlength=n)
    flops = float(np.sum(rdeg[(uniq % n).astype(np.int64)]))
    cap = 1 << int(np.ceil(np.log2(nnz_out * 1.05)))

    @jax.jit
    def mm(r, c):
        d = jnp.zeros((n, n), jnp.bfloat16)
        d = d.at[r, c].set(jnp.bfloat16(1.0), mode="drop")
        return jnp.dot(d, d, preferred_element_type=jnp.float32)

    @jax.jit
    def ext(c2):
        t, total = sparsify_windowed(c2, 0.0, n, n, cap)
        return t.rows, t.cols, t.vals, total

    out = ext(mm(ru, cu))
    jax.block_until_ready(out)
    time.sleep(5.0)
    dt_s = timed_once(lambda: ext(mm(ru, cu)),
                      lambda o: int(jax.device_get(o[3])))
    return {
        "experiment": f"densewin2 scale={scale}",
        "flops_M": round(flops / 1e6, 2),
        "out_nnz": nnz_out,
        "got_nnz": int(jax.device_get(out[3])),
        "dt_s": round(dt_s, 4),
        "MFLOPs_x2conv": round(2 * flops / dt_s / 1e6, 2),
    }


def exp_extreal(scale: int, source: str):
    """sparsify_windowed alone on REAL A^2 data (host-computed, uploaded)
    vs a uniform-random matrix of the same density — isolates whether the
    38 s densespgemm anomaly is data-structure-dependent."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from scipy import sparse

    from combblas_tpu.ops.spgemm import sparsify_windowed
    from combblas_tpu.utils.rmat import rmat_symmetric_coo_host

    n = 1 << scale
    rows, cols = rmat_symmetric_coo_host(5, scale, 8)
    key = rows * np.int64(n) + cols
    uniq = np.unique(key)
    S = sparse.csr_matrix(
        (np.ones(len(uniq), np.float32), ((uniq // n), (uniq % n))),
        shape=(n, n))
    C = (S @ S).astype(np.float32)
    nnz = int(C.nnz)
    if source == "real":
        x_h = np.asarray(C.todense(), np.float32)
    else:
        rng = np.random.default_rng(0)
        x_h = np.where(rng.random((n, n)) < nnz / (n * n),
                       1.0, 0.0).astype(np.float32)
        nnz = int((x_h != 0).sum())
    cap = 1 << int(np.ceil(np.log2(nnz * 1.05)))
    x = jax.device_put(jnp.asarray(x_h))

    @jax.jit
    def ext(c2):
        t, total = sparsify_windowed(c2, 0.0, n, n, cap)
        return t.rows, t.cols, t.vals, total

    out = ext(x)
    jax.block_until_ready(out)
    time.sleep(10.0)
    dt_s = timed_once(lambda: ext(x), lambda o: int(jax.device_get(o[3])))
    return {
        "experiment": f"extreal scale={scale} source={source}",
        "nnz": nnz,
        "got": int(jax.device_get(out[3])),
        "dt_s": round(dt_s, 4),
    }


def exp_winform(nslots_m: int, W: int, form: str, R: int):
    """Window-gather formulation shootout: nslots_m million slots each
    fetching a W-lane window from a 33.5M-entry table.
      flat   x[b0[:,None]+arange(W)]      (computed-index advanced indexing)
      row2d  tab2d[owner] with tab [T/W, W]  (ELL bucket row gather)
      take   jnp.take(tab2d, owner, axis=0)
    Sum-reduced to a scalar carried through a fori_loop (fold-proof: the
    carry feeds the next iteration's indices)."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax import lax

    T = 1 << 25
    nslots = nslots_m * 1_000_000
    rng = np.random.default_rng(0)
    tab = jax.device_put(jnp.asarray(rng.random(T).astype(np.float32)))
    base = jax.device_put(jnp.asarray(
        (rng.integers(0, T // W, size=nslots) * W).astype(np.int32)))
    tab2d = tab.reshape(T // W, W)

    @jax.jit
    def run(tab, base):
        def body(_, carry):
            b = base + (carry.astype(jnp.int32) & 1)  # fold-proof dep
            if form == "flat":
                w = tab[b[:, None] + jnp.arange(W, dtype=jnp.int32)[None, :]]
            elif form == "row2d":
                w = tab2d[b // W]
            else:
                w = jnp.take(tab2d, b // W, axis=0)
            return jnp.sum(w) * 1e-9
        return lax.fori_loop(0, R, body, jnp.float32(0.0))

    out = run(tab, base)
    jax.block_until_ready(out)
    time.sleep(5.0)
    dt_s = timed_once(lambda: run(tab, base),
                      lambda o: float(jax.device_get(o)))
    return {
        "experiment": f"winform {form} W={W} slots={nslots_m}M R={R}",
        "dt_s": round(dt_s, 4),
        "Mwindows_per_s": round(nslots * R / dt_s / 1e6, 1),
        "Melem_per_s": round(nslots * W * R / dt_s / 1e6, 1),
    }


def exp_cumsum2d(m: int, ncol: int, R: int):
    import jax
    import jax.numpy as jnp
    from jax import lax

    x = jax.device_put(jnp.ones((m, ncol), jnp.float32))

    @jax.jit
    def run(x):
        def body(_, carry):
            c = jnp.cumsum(carry, axis=1)
            return c * (1.0 / ncol)
        return lax.fori_loop(0, R, body, x)

    out = run(x)
    jax.block_until_ready(out)
    time.sleep(3.0)
    dt_s = timed_once(lambda: run(x), lambda o: float(jax.device_get(o[0, 0])))
    return {
        "experiment": f"cumsum2d {m}x{ncol} R={R}",
        "dt_s": round(dt_s, 4),
        "Melem_per_s": round(m * ncol * R / dt_s / 1e6, 1),
    }


def exp_topk(m: int, ncol: int, k: int, R: int):
    import jax
    import jax.numpy as jnp
    from jax import lax

    x = jax.device_put(jnp.arange(m * ncol, dtype=jnp.float32).reshape(m, ncol) % 997.0)

    @jax.jit
    def run(x):
        def body(_, carry):
            v, _i = lax.top_k(carry, k)
            return carry.at[:, :k].set(v * 1e-6)
        return lax.fori_loop(0, R, body, x)

    out = run(x)
    jax.block_until_ready(out)
    time.sleep(3.0)
    dt_s = timed_once(lambda: run(x), lambda o: float(jax.device_get(o[0, 0])))
    return {
        "experiment": f"topk {m}x{ncol} k={k} R={R}",
        "dt_s": round(dt_s, 4),
        "Melem_per_s": round(m * ncol * R / dt_s / 1e6, 1),
    }


def main():
    exp = sys.argv[1]
    a = sys.argv[2:]
    if exp == "mxu":
        out = exp_mxu(a[0], int(a[1]), int(a[2]))
    elif exp == "mxu3":
        out = exp_mxu3(int(a[0]), int(a[1]))
    elif exp == "pdma":
        out = exp_pdma(int(a[0]), int(a[1]))
    elif exp == "pscat":
        out = exp_pscat(int(a[0]), int(a[1]), int(a[2]))
    elif exp == "pscatv":
        out = exp_pscatv(int(a[0]), int(a[1]), int(a[2]))
    elif exp == "densepath":
        out = exp_densepath(int(a[0]))
    elif exp == "mxu_i8":
        out = exp_mxu_i8(int(a[0]), int(a[1]))
    elif exp == "mxu_large":
        out = exp_mxu_large(a[0], int(a[1]), int(a[2]))
    elif exp == "psort":
        out = exp_psort(int(a[0]), int(a[1]))
    elif exp == "psparsify":
        out = exp_psparsify(int(a[0]), int(a[1]), int(a[2]), int(a[3]), int(a[4]))
    elif exp == "densespgemm":
        out = exp_densespgemm(int(a[0]), a[1] if len(a) > 1 else "windowed")
    elif exp == "pop":
        out = _pallas_op_chain(a[0], int(a[1]), int(a[2]))
    elif exp == "pwindowed":
        out = exp_pwindowed(int(a[0]), int(a[1]), int(a[2]), int(a[3]))
    elif exp == "densewin2":
        out = exp_densewin2(int(a[0]))
    elif exp == "extreal":
        out = exp_extreal(int(a[0]), a[1])
    elif exp == "winform":
        out = exp_winform(int(a[0]), int(a[1]), a[2], int(a[3]))
    elif exp == "cumsum2d":
        out = exp_cumsum2d(int(a[0]), int(a[1]), int(a[2]))
    elif exp == "topk":
        out = exp_topk(int(a[0]), int(a[1]), int(a[2]), int(a[3]))
    else:
        raise SystemExit(f"unknown experiment {exp}")
    print(json.dumps(out))


if __name__ == "__main__":
    main()
