"""Application-level single-chip benchmarks: PageRank and triangle count.

Same axon-safe protocol as bench.py (host build, one upload, one timed
launch closed by a scalar readback). Prints one JSON line per app.

APP=pagerank: K power iterations of the PLUS_TIMES ELL SpMV with teleport
(the PageRank.cpp loop, :126-157) fused into one launch.
APP=tc: L = tril(A); count = sum((L·L) .* L) — TC.cpp:104-116 — via the
masked ESC SpGEMM.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

APP = os.environ.get("BENCH_APP", "pagerank")
SCALE = int(os.environ.get("BENCH_SCALE", "18"))
ITERS = int(os.environ.get("BENCH_ITERS", "16"))


def _graph(scale, ef=16):
    import numpy as np

    from combblas_tpu.utils.refgen21 import graph500_edges_native

    n = 1 << scale
    src, dst = graph500_edges_native(scale, edgefactor=ef, userseed=11)
    keep = src != dst
    r = np.concatenate([src[keep], dst[keep]])
    c = np.concatenate([dst[keep], src[keep]])
    u = np.unique(r * np.int64(n) + c)
    return (u // n).astype(np.int64), (u % n).astype(np.int64), n


def bench_pagerank():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    from combblas_tpu import PLUS_TIMES
    from combblas_tpu.parallel.ellmat import EllParMat, dist_spmv_ell
    from combblas_tpu.parallel.grid import Grid
    from combblas_tpu.parallel.vec import DistVec

    r, c, n = _graph(SCALE)
    grid = Grid.make(1, 1)
    deg = np.bincount(c, minlength=n).astype(np.float32)
    # column-stochastic edge weights (out-degree normalization)
    w = (1.0 / np.maximum(deg, 1.0))[c].astype(np.float32)
    E = EllParMat.from_host_coo(grid, r, c, w, n, n)
    x0 = DistVec.from_global(
        grid, np.full(n, 1.0 / n, np.float32), align="col"
    )

    @jax.jit
    def power(ell, xb):
        def body(_, xb):
            xv = DistVec(blocks=xb, length=n, align="col", grid=grid)
            y = dist_spmv_ell(PLUS_TIMES, ell, xv)
            yb = 0.85 * y.blocks + 0.15 / n
            return DistVec(
                blocks=yb, length=n, align="row", grid=grid
            ).realign("col").blocks

        return lax.fori_loop(0, ITERS, body, xb)

    out = power(E, x0.blocks)
    jax.block_until_ready(out)
    time.sleep(3)
    t0 = time.perf_counter()
    out = power(E, x0.blocks)
    _ = float(jax.device_get(out[0, 0]))
    dt = time.perf_counter() - t0
    nnz = len(r)
    print(
        json.dumps(
            {
                "metric": f"pagerank_rmat_scale{SCALE}_GFLOPs",
                "value": round(nnz * 2 * ITERS / dt / 1e9, 3),
                "unit": "GFLOP/s",
                "ms_per_iter": round(dt / ITERS * 1e3, 2),
                "nnz": nnz,
                "iters": ITERS,
            }
        )
    )


def bench_tc():
    import jax
    import numpy as np

    from combblas_tpu.models.tc import triangle_count
    from combblas_tpu.parallel.grid import Grid
    from combblas_tpu.parallel.spmat import SpParMat

    r, c, n = _graph(SCALE, ef=8)
    grid = Grid.make(1, 1)
    A = SpParMat.from_global_coo(
        grid, r, c, np.ones(len(r), np.float32), n, n
    )
    t = triangle_count(A)  # warmup/compile (host-orchestrated: sizes once)
    n_tri = int(jax.device_get(t))
    time.sleep(3)
    t0 = time.perf_counter()
    t = triangle_count(A)
    n_tri = int(jax.device_get(t))
    dt = time.perf_counter() - t0
    print(
        json.dumps(
            {
                "metric": f"tc_rmat_scale{SCALE}_s",
                "value": round(dt, 2),
                "unit": "s",
                "triangles": n_tri,
                "nnz": len(r),
            }
        )
    )


if __name__ == "__main__":
    if APP == "pagerank":
        bench_pagerank()
    elif APP == "tc":
        bench_tc()
    else:
        raise SystemExit(f"unknown BENCH_APP {APP}")
