"""Application-level single-chip benchmarks (BASELINE.md tracked configs).

Same axon-safe protocol as bench.py (host build + host symbolic sizing,
one upload, one timed launch closed by a scalar readback). Prints one
JSON line per app. One app per process (fresh-process rule).

APP=pagerank: K power iterations of the PLUS_TIMES ELL SpMV with teleport
(the PageRank.cpp loop, :126-157) fused into one launch.
APP=ppr: W personalized-PageRank chains in ONE program
(``pagerank_batch`` — the multi-root amortization; compare s/iter
against APP=pagerank to see the per-index gather cost split W ways).
APP=tc: L = tril(A); count = sum((L·L) .* L) — TC.cpp:104-116 — host
symbolic sizing + one fused launch (no mid-run readbacks).
APP=cc: FastSV connected components (one while_loop launch).
APP=lacc: LACC star hooking/shortcutting (one while_loop launch).
APP=sssp: Bellman-Ford MIN_PLUS fixed point (one while_loop launch).
APP=sssp_batch: W-source Bellman-Ford chains in ONE program
(``sssp_batch`` — the same W-lane gather amortization as APP=ppr).
APP=bc: batched Brandes from BENCH_ROOTS sources (host loop per level —
the reference's while(fringe.getnnz()) shape; per-level sizing readbacks
degrade this chip (D2H poison), recorded as-is).
APP=mcl: BENCH_ITERS expand/prune/inflate iterations in ONE launch with
frozen host-sized capacities (the chaos_every machinery); overflow flags
checked after timing.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

APP = os.environ.get("BENCH_APP", "pagerank")
SCALE = int(os.environ.get("BENCH_SCALE", "18"))
ITERS = int(os.environ.get("BENCH_ITERS", "16"))


def _graph(scale, ef=16):
    import numpy as np

    from combblas_tpu.utils.refgen21 import graph500_edges_native

    n = 1 << scale
    src, dst = graph500_edges_native(scale, edgefactor=ef, userseed=11)
    keep = src != dst
    r = np.concatenate([src[keep], dst[keep]])
    c = np.concatenate([dst[keep], src[keep]])
    u = np.unique(r * np.int64(n) + c)
    return (u // n).astype(np.int64), (u % n).astype(np.int64), n


def bench_pagerank():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    from combblas_tpu import PLUS_TIMES
    from combblas_tpu.parallel.ellmat import EllParMat, dist_spmv_ell
    from combblas_tpu.parallel.grid import Grid
    from combblas_tpu.parallel.vec import DistVec

    r, c, n = _graph(SCALE)
    grid = Grid.make(1, 1)
    deg = np.bincount(c, minlength=n).astype(np.float32)
    # column-stochastic edge weights (out-degree normalization)
    w = (1.0 / np.maximum(deg, 1.0))[c].astype(np.float32)
    E = EllParMat.from_host_coo(grid, r, c, w, n, n)
    x0 = DistVec.from_global(
        grid, np.full(n, 1.0 / n, np.float32), align="col"
    )

    @jax.jit
    def power(ell, xb):
        def body(_, xb):
            xv = DistVec(blocks=xb, length=n, align="col", grid=grid)
            y = dist_spmv_ell(PLUS_TIMES, ell, xv)
            yb = 0.85 * y.blocks + 0.15 / n
            return DistVec(
                blocks=yb, length=n, align="row", grid=grid
            ).realign("col").blocks

        return lax.fori_loop(0, ITERS, body, xb)

    out = power(E, x0.blocks)
    jax.block_until_ready(out)
    time.sleep(3)
    t0 = time.perf_counter()
    out = power(E, x0.blocks)
    _ = float(jax.device_get(out[0, 0]))
    dt = time.perf_counter() - t0
    nnz = len(r)
    print(
        json.dumps(
            {
                "metric": f"pagerank_rmat_scale{SCALE}_GFLOPs",
                "value": round(nnz * 2 * ITERS / dt / 1e9, 3),
                "unit": "GFLOP/s",
                "ms_per_iter": round(dt / ITERS * 1e3, 2),
                "nnz": nnz,
                "iters": ITERS,
            }
        )
    )


def bench_tc():
    import jax
    import numpy as np

    from combblas_tpu.models.tc import triangle_count
    from combblas_tpu.parallel.grid import Grid
    from combblas_tpu.parallel.spmat import SpParMat

    r, c, n = _graph(SCALE, ef=8)
    grid = Grid.make(1, 1)
    A = SpParMat.from_global_coo(
        grid, r, c, np.ones(len(r), np.float32), n, n
    )
    t = triangle_count(A)  # warmup/compile (host-orchestrated: sizes once)
    n_tri = int(jax.device_get(t))
    time.sleep(3)
    t0 = time.perf_counter()
    t = triangle_count(A)
    n_tri = int(jax.device_get(t))
    dt = time.perf_counter() - t0
    print(
        json.dumps(
            {
                "metric": f"tc_rmat_scale{SCALE}_s",
                "value": round(dt, 2),
                "unit": "s",
                "triangles": n_tri,
                "nnz": len(r),
            }
        )
    )


def bench_ppr():
    """W personalized-PageRank chains, one program (pagerank_batch)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from combblas_tpu.models.pagerank import pagerank_batch
    from combblas_tpu.parallel.ellmat import EllParMat
    from combblas_tpu.parallel.grid import Grid
    from combblas_tpu.parallel.vec import DistVec

    W = int(os.environ.get("BENCH_ROOTS", "64"))
    r, c, n = _graph(SCALE)
    grid = Grid.make(1, 1)
    deg = np.bincount(c, minlength=n).astype(np.float32)
    w = (1.0 / np.maximum(deg, 1.0))[c].astype(np.float32)
    E = EllParMat.from_host_coo(grid, r, c, w, n, n)
    dang = DistVec.from_global(
        grid, (deg == 0).astype(np.float32), align="col"
    )
    rng = np.random.default_rng(0)
    srcs = jnp.asarray(
        rng.choice(np.flatnonzero(deg > 0), size=W, replace=False), jnp.int32
    )
    # fixed iteration count (tol=0 -> runs max_iters): clean s/iter
    ranks, it = pagerank_batch(
        E, srcs, dang, tol=0.0, max_iters=ITERS
    )
    jax.block_until_ready(ranks.blocks)
    time.sleep(3)
    t0 = time.perf_counter()
    ranks, it = pagerank_batch(E, srcs, dang, tol=0.0, max_iters=ITERS)
    _ = float(jax.device_get(ranks.blocks[0, 0, 0]))
    dt = time.perf_counter() - t0
    print(
        json.dumps(
            {
                "metric": f"ppr_batch{W}_rmat_scale{SCALE}_GFLOPs",
                "value": round(len(r) * 2 * W * ITERS / dt / 1e9, 3),
                "unit": "GFLOP/s",
                "nnz": len(r),
                "roots": W,
                "iters": ITERS,
                "ms_per_iter": round(dt / ITERS * 1e3, 2),
                "ms_per_iter_per_root": round(dt / ITERS / W * 1e3, 3),
            }
        )
    )


def bench_tc_fused():
    """TC with host symbolic sizing + ONE fused launch (axon-safe)."""
    import jax
    import numpy as np

    from combblas_tpu import PLUS_TIMES
    from combblas_tpu.parallel.grid import Grid
    from combblas_tpu.parallel.spgemm import (
        summa_capacities_host,
        summa_spgemm,
        summa_stage_flops_host,
    )
    from combblas_tpu.parallel.spmat import SpParMat

    r, c, n = _graph(SCALE, ef=8)
    grid = Grid.make(1, 1)
    m = r > c  # strict lower triangle, host-side
    lr_, lc_ = r[m], c[m]
    fcap, ocap = summa_capacities_host(grid, lr_, lc_, lr_, lc_, n, n, n)
    ntri_host = None
    L = SpParMat.from_global_coo(
        grid, lr_, lc_, np.ones(len(lr_), np.float32), n, n
    )

    @jax.jit
    def count(Lm):
        B = summa_spgemm(
            PLUS_TIMES, Lm, Lm, flop_capacity=fcap, out_capacity=ocap
        )
        C = B.ewise_mult(Lm)
        return C.reduce(PLUS_TIMES, axis="rows").reduce(PLUS_TIMES)

    t = count(L)
    jax.block_until_ready(t)
    time.sleep(3)
    t0 = time.perf_counter()
    t = count(L)
    n_tri = int(jax.device_get(t))
    dt = time.perf_counter() - t0
    flops = int(
        summa_stage_flops_host(
            grid, lr_, lc_, lr_, lc_, n, n, n, padded=False
        ).sum()
    )
    print(
        json.dumps(
            {
                "metric": f"tc_rmat_scale{SCALE}_s",
                "value": round(dt, 2),
                "unit": "s",
                "triangles": n_tri,
                "nnz": int(len(r)),
                "MFLOPs": round(flops * 2 / dt / 1e6, 2),
            }
        )
    )


def bench_cc(algo: str):
    import jax
    import numpy as np

    from combblas_tpu.models.cc import connected_components, lacc
    from combblas_tpu.parallel.grid import Grid
    from combblas_tpu.parallel.spmat import SpParMat

    r, c, n = _graph(SCALE)
    grid = Grid.make(1, 1)
    A = SpParMat.from_global_coo(
        grid, r, c, np.ones(len(r), np.float32), n, n
    )
    fn = lacc if algo == "lacc" else connected_components
    labels, it = fn(A)
    jax.block_until_ready(labels.blocks)
    time.sleep(3)
    t0 = time.perf_counter()
    labels, it = fn(A)
    _ = int(jax.device_get(labels.blocks[0, 0]))
    dt = time.perf_counter() - t0
    print(
        json.dumps(
            {
                "metric": f"{algo}_rmat_scale{SCALE}_s",
                "value": round(dt, 3),
                "unit": "s",
                "nnz": len(r),
                "iters": int(jax.device_get(it)),
                "MTEPS": round(len(r) * int(jax.device_get(it)) / dt / 1e6, 1),
            }
        )
    )


def bench_sssp():
    import jax
    import numpy as np

    from combblas_tpu.models.sssp import sssp
    from combblas_tpu.parallel.grid import Grid
    from combblas_tpu.parallel.spmat import SpParMat

    r, c, n = _graph(SCALE)
    grid = Grid.make(1, 1)
    rng = np.random.default_rng(0)
    w = (rng.random(len(r)) + 0.01).astype(np.float32)
    A = SpParMat.from_global_coo(grid, r, c, w, n, n)
    dist, it = sssp(A, 0)
    jax.block_until_ready(dist.blocks)
    time.sleep(3)
    t0 = time.perf_counter()
    dist, it = sssp(A, 0)
    _ = float(jax.device_get(dist.blocks[0, 0]))
    dt = time.perf_counter() - t0
    niter = int(jax.device_get(it))
    print(
        json.dumps(
            {
                "metric": f"sssp_rmat_scale{SCALE}_s",
                "value": round(dt, 3),
                "unit": "s",
                "nnz": len(r),
                "iters": niter,
                "MTEPS": round(len(r) * niter / dt / 1e6, 1),
            }
        )
    )


def bench_sssp_batch():
    """W-source Bellman-Ford in one program (the batched ELL kernel)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from combblas_tpu.models.sssp import sssp_batch
    from combblas_tpu.parallel.ellmat import EllParMat
    from combblas_tpu.parallel.grid import Grid

    W = int(os.environ.get("BENCH_ROOTS", "64"))
    r, c, n = _graph(SCALE)
    grid = Grid.make(1, 1)
    rng = np.random.default_rng(0)
    w = (rng.random(len(r)) + 0.01).astype(np.float32)
    E = EllParMat.from_host_coo(grid, r, c, w, n, n)
    deg = np.bincount(r, minlength=n)
    srcs = jnp.asarray(
        rng.choice(np.flatnonzero(deg > 0), size=W, replace=False), jnp.int32
    )
    dist, it = sssp_batch(E, srcs)
    jax.block_until_ready(dist.blocks)
    time.sleep(3)
    t0 = time.perf_counter()
    dist, it = sssp_batch(E, srcs)
    _ = float(jax.device_get(dist.blocks[0, 0, 0]))
    dt = time.perf_counter() - t0
    niter = int(jax.device_get(it))
    print(
        json.dumps(
            {
                "metric": f"sssp_batch{W}_rmat_scale{SCALE}_s",
                "value": round(dt, 3),
                "unit": "s",
                "nnz": len(r),
                "roots": W,
                "iters": niter,
                "MTEPS_aggregate": round(
                    len(r) * niter * W / dt / 1e6, 1
                ),
            }
        )
    )


def bench_bc():
    import jax
    import numpy as np

    from combblas_tpu.models.bc import bc_batch
    from combblas_tpu.parallel.grid import Grid
    from combblas_tpu.parallel.spmat import SpParMat

    W = int(os.environ.get("BENCH_ROOTS", "16"))
    r, c, n = _graph(SCALE, ef=8)
    grid = Grid.make(1, 1)
    A = SpParMat.from_global_coo(
        grid, r, c, np.ones(len(r), np.float32), n, n
    )
    rng = np.random.default_rng(0)
    deg = np.bincount(r, minlength=n)
    srcs = rng.choice(np.flatnonzero(deg > 0), size=W, replace=False)
    AT = A.transpose()
    scores = bc_batch(A, srcs, AT=AT)  # warmup (compiles per-level shapes)
    jax.block_until_ready(scores.blocks)
    time.sleep(3)
    t0 = time.perf_counter()
    scores = bc_batch(A, srcs, AT=AT)
    _ = float(jax.device_get(scores.blocks[0, 0]))
    dt = time.perf_counter() - t0
    print(
        json.dumps(
            {
                "metric": f"bc_batch{W}_rmat_scale{SCALE}_s",
                "value": round(dt, 2),
                "unit": "s",
                "nnz": len(r),
                "roots": W,
                "note": "host level loop; per-level sizing readbacks "
                        "degrade this chip (D2H poison)",
            }
        )
    )


def bench_bc_dense():
    """One-launch dense batched Brandes (bc_batch_dense) — the TPU-native
    BC: zero readbacks, W sources per program."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from combblas_tpu.models.bc import bc_batch_dense
    from combblas_tpu.parallel.ellmat import EllParMat
    from combblas_tpu.parallel.grid import Grid

    W = int(os.environ.get("BENCH_ROOTS", "16"))
    r, c, n = _graph(SCALE, ef=8)
    grid = Grid.make(1, 1)
    E = EllParMat.from_host_coo(
        grid, r, c, np.ones(len(r), np.float32), n, n
    )
    rng = np.random.default_rng(0)
    deg = np.bincount(r, minlength=n)
    srcs = jnp.asarray(
        rng.choice(np.flatnonzero(deg > 0), size=W, replace=False), jnp.int32
    )
    # static depth bound: R-MAT diameters are tiny; 64 is generous
    scores = bc_batch_dense(E, E, srcs, max_depth=64)
    jax.block_until_ready(scores.blocks)
    time.sleep(3)
    t0 = time.perf_counter()
    scores = bc_batch_dense(E, E, srcs, max_depth=64)
    _ = float(jax.device_get(scores.blocks[0, 0]))
    dt = time.perf_counter() - t0
    print(
        json.dumps(
            {
                "metric": f"bc_dense{W}_rmat_scale{SCALE}_s",
                "value": round(dt, 2),
                "unit": "s",
                "nnz": len(r),
                "roots": W,
                "s_per_root": round(dt / W, 3),
            }
        )
    )


def bench_mcl():
    """BENCH_ITERS MCL iterations in ONE launch, frozen host-sized caps."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from combblas_tpu.models.mcl import (
        _mcl2d_iter_device,
        make_col_stochastic,
    )
    from combblas_tpu.parallel.grid import Grid
    from combblas_tpu.parallel.spgemm import summa_capacities_host
    from combblas_tpu.parallel.spmat import SpParMat

    K = ITERS
    r, c, n = _graph(SCALE, ef=8)
    grid = Grid.make(1, 1)
    # self-loops added HOST-side so the symbolic sizing sees the matrix
    # the loop actually squares
    diag = np.arange(n, dtype=np.int64)
    r = np.concatenate([r, diag])
    c = np.concatenate([c, diag])
    fcap, ocap = summa_capacities_host(
        grid, r, c, r, c, n, n, n, slack=2.0
    )
    # Frozen caps must cover LATER iterations too: each squares the
    # previous PRUNED matrix, whose flops are bounded by select^2 * n
    # (<= select entries per column in both operands). BENCH_SELECT
    # trades cluster granularity for a provable capacity bound.
    SELECT = int(os.environ.get("BENCH_SELECT", "64"))
    # CAPX covers the select-bound breaking under VALUE TIES: kselect
    # thresholds keep every tied entry (early MCL iterations tie heavily
    # at 1/deg), so columns can exceed SELECT entries and the flop bound
    # with them (overflow flag in the output = raise CAPX).
    CAPX = int(os.environ.get("BENCH_CAPX", "4"))
    bound = SELECT * SELECT * n
    rnd = lambda x: 1 << (max(int(x), 1) - 1).bit_length()
    caps = (
        rnd(CAPX * max(fcap, bound)),
        # distinct output keys <= min(flop bound, dense)
        min(rnd(min(CAPX * max(ocap, bound), n * n)), n * n),
    )
    prune_kwargs = dict(
        hard_threshold=1e-4, select_num=SELECT,
        recover_num=SELECT + SELECT // 4, recover_pct=0.9,
    )
    A = SpParMat.from_global_coo(
        grid, r, c, np.ones(len(r), np.float32), n, n,
    )

    from jax import lax

    @jax.jit
    def block(A0):
        A1 = make_col_stochastic(A0)
        # iteration 1 separately (input capacity differs from ocap)...
        A1, ch, worst = _mcl2d_iter_device(A1, caps, 2.0, prune_kwargs)

        # ...then a fori_loop over the shape-stable remainder (a python
        # unroll of K iterations produced an HLO too large for the
        # remote compiler at chip scales)
        def body(_, st):
            Ak, _ch, worst = st
            Ak, ch2, ov = _mcl2d_iter_device(Ak, caps, 2.0, prune_kwargs)
            return Ak, ch2, jnp.maximum(worst, ov)

        A1, ch, worst = lax.fori_loop(0, K - 1, body, (A1, ch, worst))
        return A1, ch, worst

    out, ch, worst = block(A)
    jax.block_until_ready(out.vals)
    time.sleep(3)
    t0 = time.perf_counter()
    out, ch, worst = block(A)
    ch_v = float(jax.device_get(ch))
    dt = time.perf_counter() - t0
    print(
        json.dumps(
            {
                "metric": f"mcl_rmat_scale{SCALE}_s_per_iter",
                "value": round(dt / K, 2),
                "unit": "s/iter",
                "iters": K,
                "nnz": len(r),
                "chaos": round(ch_v, 5),
                "overflow": int(jax.device_get(worst)),
            }
        )
    )


def bench_mcl_dense():
    """Round-4 dense one-launch MCL: the WHOLE clustering loop as one
    lax.while_loop on the MXU (models/mcl.py:dense_mcl_program).

    Protocol: AOT-compile (lower().compile() — no warmup EXECUTION, so no
    pre-timing readback poisons the run), one timed execution closed by
    the iteration-count readback.  No capacities exist in this
    formulation, so overflow is structurally 0; the chaos trajectory is
    carried on device and reported per iteration.
    """
    import jax
    import numpy as np

    from combblas_tpu.models.mcl import dense_mcl_program
    from combblas_tpu.parallel.grid import Grid
    from combblas_tpu.parallel.spmat import SpParMat
    from combblas_tpu.models.mcl import make_col_stochastic

    K = ITERS
    SELECT = int(os.environ.get("BENCH_SELECT", "64"))
    MODE = os.environ.get("BENCH_DENSE_MODE", "bf16x3")
    # EXPLICIT opt-in to plateau detect-and-perturb (the library default
    # is now 0 — kicks can move boundary vertices between clusters, so
    # only the driver turns them on; ADVICE r5). 5e-5 is the round-5
    # operating point; kicks are counted in the artifact.
    PERTURB = float(os.environ.get("BENCH_MCL_PERTURB", "5e-5"))
    r, c, n = _graph(SCALE, ef=8)
    grid = Grid.make(1, 1)
    diag = np.arange(n, dtype=np.int64)
    r = np.concatenate([r, diag])
    c = np.concatenate([c, diag])
    A = SpParMat.from_global_coo(
        grid, r, c, np.ones(len(r), np.float32), n, n
    )
    A = make_col_stochastic(A)
    run = dense_mcl_program(
        n, n, 2.0, 1e-3, K,
        hard=1e-4, select=min(SELECT, n),
        recover=min(SELECT + SELECT // 4, n),
        rpct=0.9, mode=MODE, perturb_delta=PERTURB,
    )
    rows, cols, vals = A.rows[0, 0], A.cols[0, 0], A.vals[0, 0]
    compiled = jax.jit(run).lower(rows, cols, vals).compile()
    time.sleep(2)
    t0 = time.perf_counter()
    m, it, ch, hist, npert = compiled(rows, cols, vals)
    iters = int(jax.device_get(it))  # the closing readback
    dt = time.perf_counter() - t0
    ch_v = float(jax.device_get(ch))
    hist_v = np.asarray(jax.device_get(hist))[:iters]
    kicks = int(jax.device_get(npert))
    from combblas_tpu import obs

    if obs.ENABLED:  # perturbation kicks as span events (ADVICE r5)
        obs.span_event(
            "mcl.perturb", kicks=kicks, delta=PERTURB, iters=iters,
            chaos=round(ch_v, 6),
        )
        obs.count("mcl.perturb_kicks", kicks)
    print(
        json.dumps(
            {
                "metric": f"mcl_dense_rmat_scale{SCALE}_s_per_iter",
                "value": round(dt / max(iters, 1), 3),
                "unit": "s/iter",
                "total_s": round(dt, 3),
                "iters": iters,
                "converged": bool(ch_v < 1e-3),
                "nnz": len(r),
                "chaos": round(ch_v, 6),
                "chaos_trajectory": [round(float(x), 5) for x in hist_v],
                "overflow": 0,
                "perturbations": kicks,
                "perturb_delta": PERTURB,
                "select": SELECT,
                "mode": MODE,
            }
        )
    )


def bench_tc_dense():
    """Round-4 one-launch MXU triangle count (models/tc.py:_tc_dense):
    AOT-compile, one timed execution, readback closes the window."""
    import jax
    import numpy as np

    from combblas_tpu.models.tc import _tc_combine, _tc_dense
    from combblas_tpu.parallel.grid import Grid
    from combblas_tpu.parallel.spmat import SpParMat

    r, c, n = _graph(SCALE, ef=8)
    grid = Grid.make(1, 1)
    A = SpParMat.from_global_coo(
        grid, r, c, np.ones(len(r), np.float32), n, n
    )
    rows, cols = A.rows[0, 0], A.cols[0, 0]
    fn = jax.jit(_tc_dense, static_argnums=2)
    compiled = fn.lower(rows, cols, n).compile()
    time.sleep(2)
    t0 = time.perf_counter()
    n_tri = _tc_combine(jax.device_get(compiled(rows, cols)))
    dt = time.perf_counter() - t0
    print(
        json.dumps(
            {
                "metric": f"tc_dense_rmat_scale{SCALE}_s",
                "value": round(dt, 3),
                "unit": "s",
                "triangles": n_tri,
                "nnz": len(r),
            }
        )
    )


def _enable_cache():
    from combblas_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache()


def bench_tc_edgeharvest():
    """Round-5 scale-16 TC: per-edge common-neighbor harvest against the
    dense bf16 adjacency (models/tc.py:_tc_edge_harvest) — the regime
    past the n=32K dense-product ceiling where the ESC sparse path runs
    9.23 MFLOP/s (87 s; VERDICT r4 Missing #2). AOT-compile, one timed
    launch, readback closes the window."""
    _enable_cache()
    import jax
    import numpy as np

    from combblas_tpu.models.tc import (
        _tc_combine,
        _tc_edge_harvest,
        _tc_edge_harvest_bits,
    )
    from combblas_tpu.parallel.grid import Grid
    from combblas_tpu.parallel.spmat import SpParMat

    r, c, n = _graph(SCALE, ef=8)
    grid = Grid.make(1, 1)
    A = SpParMat.from_global_coo(
        grid, r, c, np.ones(len(r), np.float32), n, n
    )
    t = A.local_tile(A.rows, A.cols, A.vals, A.nnz)
    chunk = int(os.environ.get("BENCH_TC_CHUNK", "8192"))
    kern = (_tc_edge_harvest_bits
            if os.environ.get("BENCH_TC_BITS", "1") == "1"
            else _tc_edge_harvest)
    fn = jax.jit(kern, static_argnums=(2, 3))
    compiled = fn.lower(t.rows, t.cols, n, chunk).compile()
    time.sleep(3)
    t0 = time.perf_counter()
    hilo = compiled(t.rows, t.cols)
    total3 = _tc_combine(jax.device_get(hilo))  # readback = the barrier
    dt = time.perf_counter() - t0
    tri = total3 // 3
    # sparse-flops equivalence for the standings table: the masked
    # SpGEMM counts 2 ops per multiply over sum_{(i,j) in L} |N(i)| —
    # report the same convention via the wedge count
    print(json.dumps({
        "metric": f"tc_edgeharvest_rmat_scale{SCALE}_s",
        "kernel": kern.__name__,
        "value": round(dt, 3),
        "unit": "s",
        "triangles": tri,
        "nnz": len(r),
        "n": n,
        "traffic_GB": round(
            len(r) * (-(-n // 32) * (4 if kern.__name__.endswith("bits")
                                     else 64)) / 1e9, 1),
        "GBps": round(
            len(r) * (-(-n // 32) * (4 if kern.__name__.endswith("bits")
                                     else 64)) / 1e9 / dt, 1),
    }))


def bench_matching_device():
    """Round-5 chip capture for the ON-DEVICE augmenting matching
    (models/matching.py:maximum_matching_device; VERDICT r4 item 6 +
    Weak #7): each phase's wall time is recorded — phase 1 runs clean,
    phases 2+ run after the phase-1 termination readback, so the
    per-phase times ARE the answer to the D2H-poison question."""
    _enable_cache()
    import jax
    import numpy as np

    from combblas_tpu.models.matching import (
        _mcm_phase,
        maximal_matching,
        ones_f32,
    )
    from combblas_tpu.parallel.grid import Grid
    from combblas_tpu.parallel.spmat import SpParMat

    r, c, n = _graph(SCALE)
    grid = Grid.make(1, 1)
    A = SpParMat.from_global_coo(
        grid, r, c, np.ones(len(r), np.float32), n, n
    )
    t_all = time.perf_counter()
    t0 = time.perf_counter()
    mate_row, mate_col = maximal_matching(A)
    jax.block_until_ready(mate_row.blocks)
    init_s = time.perf_counter() - t0
    AT = A.transpose().apply(ones_f32)
    jax.block_until_ready(AT.vals)
    phases = []
    while True:
        t0 = time.perf_counter()
        mate_row, mate_col, n_aug = _mcm_phase(AT, mate_row, mate_col)
        aug = int(n_aug)  # per-phase readback (measured HARMLESS to
        #                     later phases: 0.12-0.15 s each, PERF_NOTES_r5)
        phases.append({"s": round(time.perf_counter() - t0, 3),
                       "augmented": aug})
        if aug == 0:
            break
    total = time.perf_counter() - t_all
    card = int((np.asarray(mate_row.to_global()) >= 0).sum())
    print(json.dumps({
        "metric": f"matching_device_rmat_scale{SCALE}_s",
        "value": round(total, 3),
        "unit": "s",
        "cardinality": card,
        "n": n,
        "nnz": len(r),
        "init_maximal_s": round(init_s, 3),
        "phases": phases,
    }))


def bench_rcm():
    """Round-5 chip capture for RCM ordering (models/ordering.py;
    RCM.cpp:61-160 role). End-to-end wall time including the
    pseudo-peripheral probe (whose per-probe readbacks poison later
    launches on this chip — recorded as-is, like the reference's
    peripheral search is part of its timed driver)."""
    _enable_cache()
    import jax
    import numpy as np

    from combblas_tpu.models.ordering import rcm_ordering
    from combblas_tpu.parallel.grid import Grid
    from combblas_tpu.parallel.spmat import SpParMat

    r, c, n = _graph(SCALE)
    grid = Grid.make(1, 1)
    A = SpParMat.from_global_coo(
        grid, r, c, np.ones(len(r), np.float32), n, n
    )
    # warm the kernels with a fixed-root ordering (no peripheral probe)
    p = rcm_ordering(A, root=0)
    jax.block_until_ready(p.blocks)
    time.sleep(3)
    t0 = time.perf_counter()
    p = rcm_ordering(A)
    perm = np.asarray(p.to_global())
    dt = time.perf_counter() - t0
    ok = len(np.unique(perm[perm >= 0])) == n
    print(json.dumps({
        "metric": f"rcm_rmat_scale{SCALE}_s",
        "value": round(dt, 3),
        "unit": "s",
        "n": n,
        "nnz": len(r),
        "is_permutation": bool(ok),
    }))


def bench_awpm():
    """Round-5 chip capture for approximate-weight perfect matching
    (models/matching.py:awpm; the BipartiteMatchings AWPM driver role)."""
    _enable_cache()
    import jax
    import numpy as np

    from combblas_tpu.models.matching import awpm
    from combblas_tpu.parallel.grid import Grid
    from combblas_tpu.parallel.spmat import SpParMat

    r, c, n = _graph(SCALE)
    rng = np.random.default_rng(5)
    w = rng.random(len(r)).astype(np.float32) + 0.1
    grid = Grid.make(1, 1)
    A = SpParMat.from_global_coo(grid, r, c, w, n, n)
    t0 = time.perf_counter()
    mr, mc = awpm(A)
    card = int((np.asarray(mr.to_global()) >= 0).sum())
    dt = time.perf_counter() - t0
    out = {
        "metric": f"awpm_rmat_scale{SCALE}_s",
        "value": round(dt, 3),
        "unit": "s",
        "cardinality": card,
        "n": n,
        "nnz": len(r),
    }
    # matched weight without densifying: sum w over matched (r -> mate)
    mrg = np.asarray(mr.to_global())
    matched = mrg >= 0
    key = r * np.int64(n) + c
    order = np.argsort(key)
    mkey = np.flatnonzero(matched) * np.int64(n) + mrg[matched]
    pos = np.searchsorted(key[order], mkey)
    out["weight"] = round(float(w[order][pos].sum()), 2)
    print(json.dumps(out))


def _obs_setup():
    """BENCH_OBS=1: structured telemetry sidecar for this app process
    (spans + counters -> JSONL; path printed to stderr so the stdout
    JSON-line protocol stays parseable). See docs/observability.md."""
    from combblas_tpu import obs

    return obs.enable_sidecar(APP)


def _obs_finish():
    from combblas_tpu import obs

    if obs.ENABLED:
        # telemetry must never fail the bench: COMBBLAS_OBS=1 enables
        # obs WITHOUT a sidecar path (that's BENCH_OBS=1's job), in
        # which case there is nothing to dump
        try:
            print(f"[obs] {obs.dump_jsonl()}", file=sys.stderr,
                  flush=True)
        except Exception:  # no path configured, unwritable dir, ...
            pass


if __name__ == "__main__":
    _obs_setup()
    if APP == "pagerank":
        bench_pagerank()
    elif APP == "ppr":
        bench_ppr()
    elif APP == "tc":
        bench_tc_fused()
    elif APP in ("cc", "fastsv"):
        bench_cc("fastsv")
    elif APP == "lacc":
        bench_cc("lacc")
    elif APP == "sssp":
        bench_sssp()
    elif APP == "sssp_batch":
        bench_sssp_batch()
    elif APP == "bc":
        bench_bc()
    elif APP == "bc_dense":
        bench_bc_dense()
    elif APP == "mcl":
        bench_mcl()
    elif APP == "mcl_dense":
        bench_mcl_dense()
    elif APP == "tc_edgeharvest":
        bench_tc_edgeharvest()
    elif APP == "matching_device":
        bench_matching_device()
    elif APP == "rcm":
        bench_rcm()
    elif APP == "awpm":
        bench_awpm()
    elif APP == "tc_dense":
        bench_tc_dense()
    else:
        raise SystemExit(f"unknown BENCH_APP {APP}")
    _obs_finish()
