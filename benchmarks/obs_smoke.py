"""Smallest obs-wired bench entrypoint: exercise every instrumented hot
path on a tiny R-MAT graph and write one schema-versioned JSONL trace.

    JAX_PLATFORMS=cpu python benchmarks/obs_smoke.py [out.jsonl]

(`--stitched` runs ``run_stitched`` instead: the smallest
CROSS-PROCESS stitched-trace entrypoint — round 18.)

The trace contains, end to end (docs/observability.md has the schema):

  * per-hop BFS spans with ``frontier`` nnz events
    (``models/bfs.py:bfs_levels_instrumented``),
  * SpGEMM symbolic + realized fill-in counters and the per-tile
    LoadImbalance gauge (``parallel/spgemm.py``),
  * redistribute drop counts / retry counters
    (``parallel/redistribute.py:from_device_coo``),
  * compile-cache hit/miss counters (the jax.monitoring bridge; a tiny
    probe program is compiled, evicted from the in-process jit cache,
    and recompiled so the persistent cache registers a genuine hit),
  * kernel dispatch/trace counters (``spmv.dispatch``, ``trace.*``) and
    the BFS lru-cache gauges,
  * a SERVE-PATH request trace (round 15): a worker-less ``Server``
    pumps a handful of BFS queries at sample rate 1.0, so the dump
    carries schema-``trace`` records whose stage durations (queue wait
    -> assemble -> execute -> scatter) sum to each request's
    end-to-end latency — the smallest end-to-end latency-decomposition
    entrypoint.

tests/test_obs.py runs this in-process (2x2 grid under the 8-virtual-
device fixture) and validates the file against the documented schema —
the acceptance gate for the telemetry subsystem. ``DEVICE_SYNC`` is on
here (realized-fill-in metrics need readbacks): this entrypoint is a
CPU/diagnostic tool, never part of a timed chip protocol.
"""

from __future__ import annotations

import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

SCALE = int(os.environ.get("BENCH_SCALE", "8"))
EDGEFACTOR = int(os.environ.get("BENCH_EDGEFACTOR", "8"))


def run(scale: int = SCALE, edgefactor: int = EDGEFACTOR,
        out_path: str | None = None, grid_shape=(1, 1),
        cache_dir: str | None = None) -> str:
    """Run the instrumented pipeline; returns the JSONL path."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from combblas_tpu import obs
    from combblas_tpu.models.bfs import bfs_levels_instrumented
    from combblas_tpu.parallel.grid import Grid
    from combblas_tpu.parallel.redistribute import from_device_coo
    from combblas_tpu.parallel.spgemm import spgemm_scan
    from combblas_tpu.semiring import PLUS_TIMES, SELECT2ND_MAX
    from combblas_tpu.utils.compile_cache import enable_compile_cache
    from combblas_tpu.utils.rmat import rmat_symmetric_coo_host

    if out_path is None:
        out_path = os.path.join(tempfile.gettempdir(), "obs_smoke.jsonl")
    obs.enable(jsonl_path=out_path, device_sync=True)

    # persistent compile cache into a scratch dir so cache hit/miss
    # events fire without touching the repo's .jax_cache — reusing the
    # process's already-committed dir when there is one (the cache dir
    # is process-global and idempotence-guarded; a second run() in the
    # same process must not look like a retarget)
    from combblas_tpu.utils.compile_cache import configured_dir

    enable_compile_cache(
        cache_dir or configured_dir()
        or tempfile.mkdtemp(prefix="obs_smoke_cache_")
    )

    with obs.span("obs_smoke", scale=scale, edgefactor=edgefactor):
        # compile-cache probe: compile, drop the in-process executable,
        # recompile — the second compile is a persistent-cache HIT
        probe = jax.jit(lambda v: (v * 2 + 1).sum())
        float(probe(jnp.arange(64.0)))
        jax.clear_caches()
        float(probe(jnp.arange(64.0)))

        # kernel 1 (host generate + device route): redistribute counters
        n = 1 << scale
        rows, cols = rmat_symmetric_coo_host(42, scale, edgefactor)
        key = rows.astype(np.int64) * n + cols
        uniq = np.unique(key)
        rows_u = (uniq // n).astype(np.int32)
        cols_u = (uniq % n).astype(np.int32)
        grid = Grid.make(*grid_shape)
        ndev = grid.pr * grid.pc
        chunk = -(-len(rows_u) // ndev)
        pad = chunk * ndev - len(rows_u)
        r3 = np.concatenate([rows_u, np.full(pad, n, np.int32)])
        c3 = np.concatenate([cols_u, np.full(pad, n, np.int32)])
        shape = (grid.pr, grid.pc, chunk)
        rdev = jax.device_put(r3.reshape(shape), grid.tile_sharding())
        cdev = jax.device_put(c3.reshape(shape), grid.tile_sharding())
        vdev = jnp.ones(shape, jnp.float32)
        A = from_device_coo(grid, rdev, cdev, vdev, n, n, slack=2.0)

        # SpGEMM (A²): symbolic/realized fill-in + load imbalance
        with obs.span("smoke.spgemm"):
            spgemm_scan(PLUS_TIMES, A, A)

        # per-hop instrumented BFS from the first non-isolated vertex
        deg = np.bincount(rows_u, minlength=n)
        source = int(np.flatnonzero(deg > 0)[0])
        with obs.span("smoke.bfs"):
            parents, levels, niter = bfs_levels_instrumented(
                A, source, sr=SELECT2ND_MAX
            )
        ndisc = int(jnp.sum(parents.blocks >= 0))
        obs.span_event(
            "bfs.result", source=source, levels=int(niter),
            discovered=ndisc,
        )
        obs.gauge("smoke.nnz", int(len(rows_u)))

        # serve-path trace (round 15): every request sampled, pumped
        # deterministically (no worker thread), stages -> JSONL
        from combblas_tpu.obs import trace as obs_trace
        from combblas_tpu.serve import GraphEngine, ServeConfig

        prev_rate = obs_trace.sample_rate()
        obs_trace.set_sample_rate(1.0)
        try:
            engine = GraphEngine.from_coo(
                grid, rows_u, cols_u, n, kinds=("bfs",)
            )
            cfg = ServeConfig(
                lane_widths=(1, 2, 4), update_autostart=False
            )
            with obs.span("smoke.serve"):
                srv = engine.serve(cfg)
                srv.warmup(widths=(1, 2, 4))
                roots = np.flatnonzero(deg > 0)[:5]
                futs = [srv.submit("bfs", int(x)) for x in roots]
                while srv.pump(force=True):
                    pass
                for f in futs:
                    f.result(timeout=60)
                srv.close()
        finally:
            obs_trace.set_sample_rate(prev_rate)
    return obs.dump_jsonl()


def run_stitched(scale: int = 6, edgefactor: int = 4,
                 out_path: str | None = None) -> str:
    """Smallest STITCHED-trace entrypoint (round 18): one subprocess
    replica, one sampled BFS request — the dump carries ONE
    schema-``trace`` record spanning two processes (``route`` ->
    ``ipc_send`` -> ``ipc_wait`` -> the child's queue/assemble/
    execute/scatter marks -> ``ipc_recv``) whose stages sum to the
    request wall, plus the fleet's IPC channel accounting and the
    ``fleetlog/v1`` supervision timeline in the fleet workdir.

        JAX_PLATFORMS=cpu python benchmarks/obs_smoke.py --stitched
    """
    import numpy as np

    from combblas_tpu import obs
    from combblas_tpu.obs import trace as obs_trace
    from combblas_tpu.serve import ProcessFleet, ServeConfig
    from combblas_tpu.utils.rmat import rmat_symmetric_coo_host

    if out_path is None:
        out_path = os.path.join(
            tempfile.gettempdir(), "obs_smoke_stitched.jsonl"
        )
    obs.enable(jsonl_path=out_path, install_hooks=False)
    prev_rate = obs_trace.sample_rate()
    obs_trace.set_sample_rate(1.0)
    work = tempfile.mkdtemp(prefix="obs_smoke_fleet_")
    n = 1 << scale
    rows, cols = rmat_symmetric_coo_host(42, scale, edgefactor)
    fr = ProcessFleet.build(
        (1, 1), rows, cols, n, replicas=1, kinds=("bfs",),
        config=ServeConfig(lane_widths=(1, 2)),
        wal_dir=os.path.join(work, "wal"),
        workdir=os.path.join(work, "proc"),
        hb_interval_s=0.2, hb_timeout_s=10.0,
    )
    try:
        deg = np.bincount(rows, minlength=n)
        root = int(np.flatnonzero(deg > 0)[0])
        fr.submit("bfs", root).result(timeout=120)
        for rec in obs_trace.records():
            if rec["labels"].get("fleet") == "process":
                stages = " -> ".join(
                    s["stage"] for s in rec["stages"]
                )
                print(f"stitched [{stages}] wall_s={rec['wall_s']:.4f}")
        print(f"fleetlog {fr.fleetlog.path}")
    finally:
        fr.close(drain=True)
        obs_trace.set_sample_rate(prev_rate)
    return obs.dump_jsonl()


def run_net(scale: int = 6, edgefactor: int = 4,
            out_path: str | None = None) -> str:
    """Smallest SOCKET-PATH trace entrypoint (round 19): one in-process
    ``Server`` behind a ``NetFrontend`` TCP listener, one sampled BFS
    request through a real ``NetClient`` connection — the dump carries
    a schema-``trace`` record whose stages span the wire
    (``net_accept -> net_read -> queue/assemble/execute ->
    net_write``) and still sum to the request wall.

        JAX_PLATFORMS=cpu python benchmarks/obs_smoke.py --net
    """
    import numpy as np

    from combblas_tpu import obs
    from combblas_tpu.obs import trace as obs_trace
    from combblas_tpu.parallel.grid import Grid
    from combblas_tpu.serve import (
        GraphEngine,
        NetClient,
        NetFrontend,
        ServeConfig,
    )
    from combblas_tpu.utils.rmat import rmat_symmetric_coo_host

    if out_path is None:
        out_path = os.path.join(
            tempfile.gettempdir(), "obs_smoke_net.jsonl"
        )
    obs.enable(jsonl_path=out_path, install_hooks=False)
    prev_rate = obs_trace.sample_rate()
    obs_trace.set_sample_rate(1.0)
    n = 1 << scale
    rows, cols = rmat_symmetric_coo_host(42, scale, edgefactor)
    engine = GraphEngine.from_coo(
        Grid.make(1, 1), rows, cols, n, kinds=("bfs",)
    )
    srv = engine.serve(
        ServeConfig(lane_widths=(1, 2), update_autostart=False)
    )
    srv.start()
    srv.warmup(widths=(1, 2))
    fe = NetFrontend(srv)
    try:
        deg = np.bincount(rows, minlength=n)
        root = int(np.flatnonzero(deg > 0)[0])
        with NetClient("127.0.0.1", fe.port) as client:
            client.submit("bfs", root, timeout_s=120.0)
        for rec in obs_trace.records():
            if rec["labels"].get("transport") == "net":
                stages = " -> ".join(
                    s["stage"] for s in rec["stages"]
                )
                print(f"net [{stages}] wall_s={rec['wall_s']:.4f}")
    finally:
        fe.close()
        srv.close()
        obs_trace.set_sample_rate(prev_rate)
    return obs.dump_jsonl()


def main():
    flags = {"--stitched": run_stitched, "--net": run_net}
    argv = [a for a in sys.argv[1:] if a not in flags]
    entry = run
    for flag, fn in flags.items():
        if flag in sys.argv[1:]:
            entry = fn
    out = entry(out_path=argv[0] if argv else None)
    from combblas_tpu import obs

    print(f"wrote {out}")
    obs.print_report()
    for rec in obs.metrics_snapshot():
        if rec["kind"] == "counter":
            print(f"  {rec['name']}{rec['labels'] or ''} = {rec['value']}")


if __name__ == "__main__":
    main()
