"""SpMSpV / SpMV kernel microbenchmark (≈ Applications/SpMSpV-IPDPS2017).

Compares the COO segment-reduce SpMV against the bucketed sliced-ELL path
on one chip, with the same axon-safe protocol as bench.py (host build, one
upload, batched launches, one barrier readback). Prints one JSON line.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

SCALE = int(os.environ.get("BENCH_SCALE", "18"))
REPS = int(os.environ.get("BENCH_REPS", "8"))


def main():
    import jax
    import numpy as np

    from combblas_tpu import PLUS_TIMES, SELECT2ND_MAX
    from combblas_tpu.parallel.ellmat import EllParMat
    from combblas_tpu.parallel.grid import Grid
    from combblas_tpu.parallel.spmv import dist_spmv
    from combblas_tpu.parallel.vec import DistVec
    from combblas_tpu.utils.rmat import rmat_symmetric_coo_host

    grid = Grid.make(1, 1)
    n = 1 << SCALE
    rows, cols = rmat_symmetric_coo_host(3, SCALE, 16)
    key = rows * np.int64(n) + cols
    uniq = np.unique(key)
    ru, cu = uniq // n, uniq % n
    E = EllParMat.from_host_coo(
        grid, ru, cu, np.ones(len(ru), np.float32), n, n
    )
    x = DistVec.from_global(
        grid, np.random.default_rng(0).random(n).astype(np.float32),
        align="col",
    )

    y = dist_spmv(PLUS_TIMES, E, x)  # warmup/compile
    jax.block_until_ready(y.blocks)
    time.sleep(2)
    t0 = time.perf_counter()
    for _ in range(REPS):
        y = dist_spmv(PLUS_TIMES, E, y.realign("col"))
    _ = float(jax.device_get(y.blocks[0, 0]))  # barrier
    dt = time.perf_counter() - t0
    gflops = len(ru) * 2 * REPS / dt / 1e9
    print(
        json.dumps(
            {
                "metric": f"spmv_ell_rmat_scale{SCALE}_chained_GFLOPs",
                "value": round(gflops, 3),
                "unit": "GFLOP/s",
                "nnz": int(len(ru)),
                "reps": REPS,
            }
        )
    )


if __name__ == "__main__":
    main()
