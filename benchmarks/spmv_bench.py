"""SpMSpV / SpMV kernel microbenchmark (≈ Applications/SpMSpV-IPDPS2017).

Compares the COO segment-reduce SpMV against the bucketed sliced-ELL path
on one chip, with the same axon-safe protocol as bench.py (host build, one
upload, batched launches, one barrier readback). Prints one JSON line.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

SCALE = int(os.environ.get("BENCH_SCALE", "18"))
REPS = int(os.environ.get("BENCH_REPS", "8"))
LADDER = os.environ.get("BENCH_LADDER", "fine")  # fine | coarse (1-lane
# payloads favor coarse: fewer bucket classes, see _width_ladder)


def main():
    import jax
    import numpy as np

    from combblas_tpu import PLUS_TIMES, SELECT2ND_MAX
    from combblas_tpu.parallel.ellmat import EllParMat
    from combblas_tpu.parallel.grid import Grid
    from combblas_tpu.parallel.spmv import dist_spmv
    from combblas_tpu.parallel.vec import DistVec
    from combblas_tpu.utils.rmat import rmat_symmetric_coo_host

    grid = Grid.make(1, 1)
    n = 1 << SCALE
    rows, cols = rmat_symmetric_coo_host(3, SCALE, 16)
    key = rows * np.int64(n) + cols
    uniq = np.unique(key)
    ru, cu = uniq // n, uniq % n
    E = EllParMat.from_host_coo(
        grid, ru, cu, np.ones(len(ru), np.float32), n, n, ladder=LADDER
    )
    x = DistVec.from_global(
        grid, np.random.default_rng(0).random(n).astype(np.float32),
        align="col",
    )

    # All REPS chained inside ONE launch: per-launch dispatch through the
    # tunnel costs ~105ms-1.8s (instrument_r2 probes), which would swamp
    # the ~160ms kernel if launched per-rep.
    import jax.numpy as jnp
    from jax import lax

    @jax.jit
    def chain(ell, x0):
        # ell passed as an ARGUMENT: a closure would embed the bucket
        # arrays as HLO constants and blow the remote-compile size limit.
        def body(_, xb):
            xv = DistVec(blocks=xb, length=n, align="col", grid=grid)
            y = dist_spmv(PLUS_TIMES, ell, xv)
            return y.realign("col").blocks

        return lax.fori_loop(0, REPS, body, x0)

    out = chain(E, x.blocks)  # warmup/compile
    jax.block_until_ready(out)
    time.sleep(3)
    t0 = time.perf_counter()
    out = chain(E, x.blocks)
    _ = float(jax.device_get(out[0, 0]))  # barrier
    dt = time.perf_counter() - t0
    gflops = len(ru) * 2 * REPS / dt / 1e9
    ell_bytes = sum(
        bc.size * 4 + bv.size * 4 + br.size * 4 for bc, bv, br in E.buckets
    )
    print(
        json.dumps(
            {
                "metric": f"spmv_ell{LADDER}_rmat_scale{SCALE}_chained_GFLOPs",
                "value": round(gflops, 3),
                "unit": "GFLOP/s",
                "nnz": int(len(ru)),
                "reps": REPS,
                "ms_per_spmv": round(dt / REPS * 1e3, 2),
                "achieved_GBps": round(ell_bytes * REPS / dt / 1e9, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
