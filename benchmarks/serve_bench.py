"""Query-serving benchmark: batched lanes vs one-call-per-query.

    python benchmarks/serve_bench.py            # 8 virtual CPU devices

Measures, on the tier-1 8-virtual-device CPU mesh (2x4 grid), a mixed
BFS/PageRank query stream served two ways over the SAME warm engine:

  * BASELINE — one engine call per query (the warm width-1 plan: no
    compile or trace cost is charged to the baseline; the gap is purely
    the batching, i.e. per-launch overhead and unamortized lanes);
  * BATCHED — the ``serve.Server`` micro-batcher coalescing the stream
    into width-``BENCH_SERVE_WIDTH`` (default 16) lane buckets.

Reports queries/s for both plus per-request p50/p99 latency under the
batched server, and CHECKS the serving acceptance gates:

  * ``speedup`` >= 4x at batch width 16 (the batched-serving payoff);
  * ``retraces_after_warmup`` == 0 — asserted via the engine's
    trace-time counter, mirrored in obs as ``trace.serve``;
  * ``backpressure_ok`` — a full queue REJECTS ``submit()`` with a
    retry-after hint instead of blocking unboundedly.

"ok" in the final JSON line is the AND of the three gates.

BENCH_OBS=1 attaches the structured telemetry sidecar through
``obs.enable_sidecar`` (queue-depth gauge, occupancy/padding-waste and
latency histograms, plan-cache + trace counters land in the JSONL);
``bench.py`` invokes this file under ``BENCH_SERVE=1`` with the sidecar
on by default.

BENCH_SERVE_CHAOS=1 runs the CHAOS scenario instead (ISSUE 6): the same
mixed stream through the threaded server under a seeded
``BENCH_SERVE_CHAOS_RATE`` (default 5%) execute-fault schedule plus a
``BENCH_SERVE_CHAOS_SWAPS``-deep (default 3) graph hot-swap storm, and
gates on: availability >= 95% of well-formed requests, ZERO stranded
futures, zero post-swap retraces (same-shape versions: the plan cache
must survive every swap), and all swaps applied. Reports availability
%, ok-request p50/p99 latency, and per-swap latency.

BENCH_SERVE_MUTATE=1 runs the MIXED READ/WRITE scenario instead
(ISSUE 9): the read stream serves while a writer thread streams
edge-churn batches through ``submit_update`` (the dynamic mutation
lane, docs/dynamic.md), and gates on zero steady-state retraces, all
merges incremental, and the counter-backed rebuild-amortization ratio
(one measured full ``build_version`` / mean incremental merge) > 1.
Reports p99 read latency under writes, merge mode counts, and
rows-patched/rebucketed counters.  ``BENCH_SERVE_MUTATE_WRITES`` sets
the update-batch count (default 24).

BENCH_SERVE_POOL=1 runs the MULTI-TENANT POOL scenario (ISSUE 12):
``BENCH_POOL_TENANTS`` (default 4, the acceptance floor) tenant graphs
behind one ``EnginePool``, three phases —

  * WFQ fairness (deterministic, pump-driven): two saturated tenants
    at weights 3:1 must serve within 25% of their weighted shares;
  * mixed read/write load (threaded pool worker):
    ``BENCH_SERVE_QUERIES`` (default 2000) weighted mixed-kind queries
    across all tenants plus a ``BENCH_POOL_WRITES`` (default 16)
    update stream into tenant t0, reporting throughput, p50/p99
    latency, per-tenant rejects and occupancy/padding waste, gating
    ZERO steady-state retraces across every tenant's plan cache;
  * LRU eviction: the byte budget is tightened to half the resident
    set, tenants are touched round-robin, and the gate asserts
    resident device bytes STAY under the budget at every admit while
    an evicted tenant re-admits BIT-EXACTLY (``to_host_coo``).

Emits the standard ``{summary, metric, value, median, warning, rc}``
final stdout line + BENCH_SUMMARY.json (with a per-tenant breakdown)
itself, so a standalone run honors the bench headline contract;
results are archived under benchmarks/results/r14/.

BENCH_SERVE_RECOVERY=1 runs the DURABILITY/SELF-HEALING scenario
(ISSUE 14): a ``BENCH_FLEET_REPLICAS``-wide (default 3) durable
``FleetRouter`` (write-ahead log + background checkpointer in a temp
dir) serves a mixed read/write stream while replica workers are KILLED
mid-stream — a non-home replica first, then the HOME itself (forcing a
promotion at the WAL's seqno frontier) — with the supervisor healing
continuously.  Gates: availability >= 95% of reads, ZERO acknowledged
writes lost (every acked edge present in the crash-recovered state),
recovered state bit-exact (``recover_version`` vs the surviving home,
``to_host_coo`` equal), and 0 post-recovery retraces across the healed
fleet.  Results under benchmarks/results/r16/.

BENCH_FLEET=process upgrades the recovery scenario to the PROCESS
fleet (round 17, ISSUE 15): replicas are real OS subprocesses
(``serve.ProcessFleet``) and the kills are real ``SIGKILL``s fired
through the scripted ``ProcessFaultPlan`` — a non-home replica first,
then the HOME mid-stream (promotion at the WAL frontier over IPC) —
followed by a ``SIGSTOP`` hang phase: the stopped replica must be
detected by HEARTBEAT TIMEOUT and routed around (reads keep serving)
rather than wedging the router.  Same four gates as the thread
scenario, plus the first honest replica-parallelism measurement:
read-only throughput through N subprocess replicas (own JAX runtimes,
no shared exec lock) vs the SAME stream through the thread fleet's
shared-lock serialization.  Results under benchmarks/results/r17/.

BENCH_SERVE_NET=1 runs the OPEN-LOOP network scenario (round 19) by
delegating to ``combblas_tpu.serve.net.loadgen``: a seeded Poisson
arrival stream over hundreds of TCP connections against a process
fleet, latencies measured from SCHEDULED arrival time.  Every
scenario in THIS file is closed-loop (the next request waits for the
last), so each summary carries ``warning: "closed-loop (coordinated
omission)"`` — do not compare its tail latencies against the
open-loop numbers (results under benchmarks/results/r19/).

BENCH_SERVE_SHARD=1 runs the SHARDED SERVING scenario (round 20): one
graph row-partitioned over ``BENCH_SHARD_SLICES`` (default 2)
subprocess slices (each a rectangular slab on its own JAX runtime),
served as ONE engine through the batcher.  Gates: per-slice device
residency <= 60% of the unsharded build, bfs/sssp bit-exact vs
unsharded (before AND after a slice SIGKILL+respawn), availability
>= 99% through the kill, zero post-warmup retraces across the
respawn, and two-phase writes + whole-service recovery reassembling
the identical global COO.  Results under benchmarks/results/r20/.
"""

from __future__ import annotations

import json
import os
import sys
import time

# tier-1 virtual mesh, set BEFORE jax initializes its backend
os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "--xla_force_host_platform_device_count" not in os.environ.get(
    "XLA_FLAGS", ""
):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    )
sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
)

SCALE = int(os.environ.get("BENCH_SERVE_SCALE", "9"))
EDGEFACTOR = int(os.environ.get("BENCH_SERVE_EDGEFACTOR", "8"))
WIDTH = int(os.environ.get("BENCH_SERVE_WIDTH", "16"))
NQUERIES = int(os.environ.get("BENCH_SERVE_QUERIES", "256"))


def _percentile(xs: list[float], q: float) -> float:
    # ONE percentile implementation repo-wide (round 15): the obs
    # sinks' quantile helper, shared with the registry snapshot, the
    # JSONL aggregate and the Prometheus exporter
    from combblas_tpu.obs.sinks import quantiles

    return quantiles(xs, (q,))[q]


def _restores_trace_rate(fn):
    """Scenario decorator: whatever sampling rate the scenario sets,
    the PROCESS-GLOBAL rate is restored on every exit path (exception
    included) — a later scenario or test in the same process must not
    inherit it (the obs_smoke try/finally pattern)."""
    import functools

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        from combblas_tpu.obs import trace as obs_trace

        prev = obs_trace.sample_rate()
        try:
            return fn(*args, **kwargs)
        finally:
            obs_trace.set_sample_rate(prev)

    return wrapper


def _trace_decomposition(obs_trace, records=None) -> dict | None:
    """Per-stage mean latency (ms) from the sampled request traces —
    the summary-JSON latency decomposition (None when nothing was
    sampled).  ``records`` narrows the fold to a subset (the process
    scenario folds only its STITCHED cross-process traces)."""
    summary = obs_trace.stage_summary(records)
    if not summary:
        return None
    return {
        stage: round(1e3 * d["mean_s"], 3)
        for stage, d in summary.items()
    }


#: Stitched-trace stages owned by the router (its own marks) vs the
#: wire (send + the residual the child's marks don't cover); every
#: other stage was measured INSIDE the child and shipped back.
_ROUTER_STAGES = frozenset(("route", "ipc_recv"))
_IPC_STAGES = frozenset(("ipc_send", "ipc_wait"))


def _stitched_split(decomp: dict | None) -> dict | None:
    """Fold a stitched-trace decomposition into the router / ipc /
    child 3-way split — the process fleet's isolation-tax headline."""
    if not decomp:
        return None
    out = {"router_ms": 0.0, "ipc_ms": 0.0, "child_ms": 0.0}
    for stage, ms in decomp.items():
        if stage.startswith("_"):  # summary pseudo-keys (_wall)
            continue
        if stage in _ROUTER_STAGES:
            out["router_ms"] += ms
        elif stage in _IPC_STAGES:
            out["ipc_ms"] += ms
        else:
            out["child_ms"] += ms
    return {k: round(v, 3) for k, v in out.items()}


def _setup(scale, edgefactor, width, nqueries, grid_shape, kinds,
           widths, keep_coo=False):
    """Shared graph/stream/warmup setup: the chaos scenario must
    measure the SAME engine, stream, and warm plans the baseline
    scenario does.  ``keep_coo=True`` retains the host edge list (the
    mutation lane's merge-state bootstrap — the mutate scenario)."""
    import numpy as np

    from combblas_tpu.parallel.grid import Grid
    from combblas_tpu.serve import GraphEngine
    from combblas_tpu.utils.rmat import rmat_symmetric_coo_host

    n = 1 << scale
    rows, cols = rmat_symmetric_coo_host(42, scale, edgefactor)
    grid = Grid.make(*grid_shape)

    # raw COO straight in: from_coo deduplicates internally (one
    # int64-key unique pass — doing it here too would double the sort)
    t0 = time.perf_counter()
    engine = GraphEngine.from_coo(
        grid, rows, cols, n, kinds=kinds, keep_coo=keep_coo
    )
    load_s = time.perf_counter() - t0

    # mixed query stream: alternating kinds over random reachable roots
    # (raw rows give the same reachable set as the deduped edge list)
    deg = np.bincount(rows, minlength=n)
    rng = np.random.default_rng(7)
    roots = rng.choice(np.flatnonzero(deg > 0), size=nqueries)
    stream = [
        (kinds[i % len(kinds)], int(r)) for i, r in enumerate(roots)
    ]

    t0 = time.perf_counter()
    engine.warmup(kinds=kinds, widths=widths)
    warmup_s = time.perf_counter() - t0
    return engine, rows, cols, roots, stream, load_s, warmup_s


def run(scale: int = SCALE, edgefactor: int = EDGEFACTOR,
        width: int = WIDTH, nqueries: int = NQUERIES,
        grid_shape=(2, 4), kinds=("bfs", "pagerank")) -> dict:
    import numpy as np

    from combblas_tpu import obs
    from combblas_tpu.serve import BackpressureError, ServeConfig

    sidecar = obs.enable_sidecar("serve")

    # plans for every bucket the server may flush under, plus width-1
    # for the baseline — after this, ZERO traces is the contract
    widths = tuple(sorted({1, width}))
    engine, rows, _cols, roots, stream, load_s, warmup_s = _setup(
        scale, edgefactor, width, nqueries, grid_shape, kinds, widths,
    )
    mark = engine.trace_mark()

    # -- baseline: one warm call per query --------------------------------
    t0 = time.perf_counter()
    for kind, root in stream:
        engine.execute(kind, np.asarray([root], np.int32))
    base_s = time.perf_counter() - t0
    qps_base = nqueries / base_s

    # -- batched serving ---------------------------------------------------
    cfg = ServeConfig(
        lane_widths=(width,),  # the acceptance gate's fixed bucket
        max_queue=max(4 * width, nqueries),
        max_wait_s=0.05,
    )
    lat: list[float] = []

    def _stamp(ts):
        # completion-time stamping: measuring at result()-collection
        # time would charge a fast request for an earlier slow batch
        return lambda _f: lat.append(time.monotonic() - ts)

    t0 = time.perf_counter()
    with engine.serve(cfg) as srv:
        submitted = []
        for kind, root in stream:
            f = srv.submit(kind, root)
            f.add_done_callback(_stamp(time.monotonic()))
            submitted.append(f)
        for f in submitted:
            f.result(timeout=600)
    batch_s = time.perf_counter() - t0
    qps_batch = nqueries / batch_s
    stats = srv.stats()

    retraces = engine.retraces_since(mark)

    # -- backpressure gate: a full queue rejects, never blocks -------------
    tiny = engine.serve(ServeConfig(
        lane_widths=(width,), max_queue=4, max_wait_s=30.0,
    ))  # worker NOT started: the queue cannot drain
    backpressure_ok = False
    retry_after = None
    try:
        for i in range(8):
            tiny.scheduler.submit("bfs", int(roots[0]))
    except BackpressureError as e:
        backpressure_ok = True
        retry_after = e.retry_after_s
    tiny.scheduler.fail_pending(RuntimeError("bench probe teardown"))

    speedup = qps_batch / qps_base if qps_base else float("inf")
    out = {
        "metric": "serve_throughput",
        "warning": "closed-loop (coordinated omission)",
        "unit": "queries/s",
        "value": round(qps_batch, 2),
        "qps_batched": round(qps_batch, 2),
        "qps_baseline": round(qps_base, 2),
        "speedup": round(speedup, 2),
        "p50_ms": round(1e3 * _percentile(lat, 0.50), 2),
        "p99_ms": round(1e3 * _percentile(lat, 0.99), 2),
        "width": width,
        "nqueries": nqueries,
        "kinds": list(kinds),
        "scale": scale,
        "grid": list(grid_shape),
        "edges_raw": int(len(rows)),  # pre-dedup (from_coo dedups)
        "load_s": round(load_s, 2),
        "warmup_s": round(warmup_s, 2),
        "mean_occupancy": stats["mean_occupancy"],
        "batches": stats["batches"],
        "retraces_after_warmup": retraces,
        "backpressure_ok": backpressure_ok,
        "backpressure_retry_after_s": retry_after,
        "ok": bool(
            speedup >= 4.0 and retraces == 0 and backpressure_ok
        ),
    }
    obs.gauge("serve.bench.qps_batched", qps_batch)
    obs.gauge("serve.bench.qps_baseline", qps_base)
    obs.gauge("serve.bench.speedup", speedup)
    if sidecar:
        try:
            out["obs_jsonl"] = obs.dump_jsonl()
        except Exception as e:  # telemetry must never fail the bench
            out["obs_error"] = str(e)
    return out


@_restores_trace_rate
def run_chaos(scale: int = SCALE, edgefactor: int = EDGEFACTOR,
              width: int = WIDTH, nqueries: int | None = None,
              grid_shape=(2, 4), kinds=("bfs", "pagerank")) -> dict:
    """Availability under injected faults + a hot-swap storm (the
    resilience acceptance scenario — see module docstring)."""
    from concurrent.futures import Future, wait

    from combblas_tpu import obs
    from combblas_tpu.serve import BackpressureError, ServeConfig

    sidecar = obs.enable_sidecar("serve-chaos")
    from combblas_tpu.obs import trace as obs_trace

    if sidecar:
        # sampled request traces feed the summary's latency
        # decomposition (deterministic: same rids = same sampled set;
        # rate restored by @_restores_trace_rate on every exit path)
        obs_trace.set_sample_rate(
            float(os.environ.get("BENCH_TRACE_SAMPLE", "0.25"))
        )
    rate = float(os.environ.get("BENCH_SERVE_CHAOS_RATE", "0.05"))
    # default seed 11 fires its first 5% fault on the 4th execute call:
    # even a short, well-coalesced stream provably exercises recovery
    seed = int(os.environ.get("BENCH_SERVE_CHAOS_SEED", "11"))
    nswaps = int(os.environ.get("BENCH_SERVE_CHAOS_SWAPS", "3"))
    nqueries = (
        int(os.environ.get("BENCH_SERVE_QUERIES", "400"))
        if nqueries is None else nqueries
    )

    # a generous deadline SLO so the budget-burn surface is live under
    # chaos: injected faults and their poisons burn the error budget
    slo_deadline_s = float(
        os.environ.get("BENCH_SERVE_SLO_DEADLINE_S", "30")
    )
    widths = tuple(sorted({1, 2, 4, 8, width}))
    engine, rows, cols, _roots, stream, _load_s, _warmup_s = _setup(
        scale, edgefactor, width, nqueries, grid_shape, kinds, widths,
    )
    # the swap storm's versions: SAME COO, so operand shapes match and
    # the zero-post-swap-retrace gate is a real plan-cache assertion
    t0 = time.perf_counter()
    versions = [engine.build_version(rows, cols) for _ in range(nswaps)]
    build_s = time.perf_counter() - t0
    mark = engine.trace_mark()

    cfg = ServeConfig(
        lane_widths=widths, max_queue=max(4 * width, nqueries),
        max_wait_s=0.005, slo_deadline_s=slo_deadline_s,
        slo_target=0.95,
    )
    lat_of: dict = {}  # future -> completion latency (ok OR failed)

    def _stamp(fut, ts):
        fut.add_done_callback(
            lambda f: lat_of.__setitem__(f, time.monotonic() - ts)
        )

    swap_s: list[float] = []
    swap_at = {
        (k + 1) * nqueries // (nswaps + 1): k for k in range(nswaps)
    }
    t0 = time.perf_counter()
    futs = []
    with engine.serve(cfg) as srv:
        srv.faults.rate("engine.execute", rate, seed=seed)
        for i, (kind, root) in enumerate(stream):
            try:
                f = srv.submit(kind, root)
                _stamp(f, time.monotonic())
            except BackpressureError as e:
                # breaker fast-fail / queue-full under high chaos
                # rates: unavailability is DATA here, not a crash
                f = Future()
                f.set_exception(e)
            futs.append(f)
            k = swap_at.get(i)
            if k is not None:  # mid-stream, under live load
                swap_s.append(srv.swap_graph(versions[k])["swap_s"])
        wait(futs, timeout=600)  # failures are data; stranded counted
        stats = srv.stats()
        fault_stats = srv.faults.stats()
    wall_s = time.perf_counter() - t0

    stranded = sum(1 for f in futs if not f.done())
    ok = sum(
        1 for f in futs if f.done() and f.exception(timeout=0) is None
    )
    availability = ok / nqueries
    retraces = engine.retraces_since(mark)
    lat = [lat_of[f] for f in futs if f in lat_of]
    ok_lat = [
        lat_of[f] for f in futs
        if f in lat_of and f.done() and f.exception(timeout=0) is None
    ]
    per_kind = stats["per_kind"]

    out = {
        "metric": "serve_chaos_availability",
        "warning": "closed-loop (coordinated omission)",
        "unit": "fraction_ok",
        "value": round(availability, 4),
        "availability_pct": round(100 * availability, 2),
        "ok": bool(
            availability >= 0.95
            and stranded == 0
            and retraces == 0
            and len(swap_s) == nswaps
        ),
        "nqueries": nqueries,
        "completed_ok": ok,
        "stranded": stranded,
        "fault_rate": rate,
        "fault_seed": seed,
        "faults_injected": fault_stats["fired"].get("engine.execute", 0),
        "retried": {
            k: per_kind[k]["retried"] for k in per_kind
        },
        "poisoned": {
            k: per_kind[k]["poisoned"] for k in per_kind
        },
        "breaker_opened": {
            k: per_kind[k].get("breaker", {}).get("opened_total", 0)
            for k in per_kind
        },
        "p50_ms": round(1e3 * _percentile(lat, 0.50), 2) if lat else None,
        "p99_ms": round(1e3 * _percentile(lat, 0.99), 2) if lat else None,
        "p99_ok_ms": (
            round(1e3 * _percentile(ok_lat, 0.99), 2) if ok_lat else None
        ),
        "swaps": len(swap_s),
        "swap_latency_ms": [round(1e3 * s, 3) for s in swap_s],
        "swap_build_s": round(build_s, 2),
        "retraces_after_swaps": retraces,
        "qps_under_chaos": round(nqueries / wall_s, 2),
        "width": width,
        "scale": scale,
        "grid": list(grid_shape),
        "kinds": list(kinds),
        "batches": stats["batches"],
        "graph_version": stats["graph_version"],
        # round 15: sampled-trace latency decomposition + the SLO
        # error budget's view of the chaos (burn counts the injected
        # damage the availability gate tolerates)
        "latency_decomposition_ms": _trace_decomposition(obs_trace),
        "slo": stats.get("slo"),
        "flightrec": stats.get("flightrec"),
    }
    obs.gauge("serve.bench.chaos_availability", availability)
    if sidecar:
        try:
            out["obs_jsonl"] = obs.dump_jsonl()
        except Exception as e:  # telemetry must never fail the bench
            out["obs_error"] = str(e)
    return out


def run_mutate(scale: int = SCALE, edgefactor: int = EDGEFACTOR,
               width: int = WIDTH, nqueries: int | None = None,
               grid_shape=(2, 4), kinds=("bfs", "pagerank")) -> dict:
    """BENCH_SERVE_MUTATE=1 — mixed read/write traffic (ISSUE 9): the
    usual read stream through the threaded server WHILE a writer thread
    streams edge-churn updates into ``submit_update``.  Measures p99
    read latency under the mix and the rebuild-amortization counters,
    and gates on:

      * zero steady-state retraces (incremental merges preserve every
        operand shape, so same-shape swaps keep the warm plans);
      * >= 1 update merged, ALL incrementally (the writer churns edges
        whose endpoints' degree classes have slack, the in-place path);
      * incremental merge measurably cheaper than a full rebuild at
        this delta fraction: ``amortization`` = (one measured full
        ``build_version``) / (mean incremental merge latency) > 1,
        counter-backed from ``stats()['updates']``.
    """
    import threading

    import numpy as np

    from combblas_tpu import obs
    from combblas_tpu.serve import BackpressureError, ServeConfig

    sidecar = obs.enable_sidecar("serve-mutate")
    nqueries = (
        int(os.environ.get("BENCH_SERVE_QUERIES", "256"))
        if nqueries is None else nqueries
    )
    nwrites = int(os.environ.get("BENCH_SERVE_MUTATE_WRITES", "24"))

    widths = tuple(sorted({1, 2, 4, 8, width}))
    engine, rows, cols, _roots, stream, load_s, warmup_s = _setup(
        scale, edgefactor, width, nqueries, grid_shape, kinds, widths,
        keep_coo=True,
    )
    n = engine.nrows
    r0, c0, _ = engine.version.host_coo
    deg = np.asarray(engine.version.deg)

    # rebuild baseline: one full from_coo-pipeline build of the SAME
    # edge list — what every write batch would cost without the
    # incremental merge (measured, not modeled)
    t0 = time.perf_counter()
    engine.build_version(rows, cols)
    rebuild_s = time.perf_counter() - t0

    # churn pairs whose endpoint degrees sit below their fine-ladder
    # class width (+1 stays in class): provably the in-place path.
    # DISJOINT pairs (each vertex in at most one) so no endpoint's
    # degree drifts across batches out of its slack class — and O(pool)
    # instead of materializing the O(pool^2) cross product
    slack = np.isin(deg, (5, 7, 9, 10, 11, 13, 14, 15, 17, 18, 19))
    present = set(zip(r0.tolist(), c0.tolist()))
    pool = np.flatnonzero(slack).tolist()
    pairs = []
    for a, b in zip(pool[0::2], pool[1::2]):
        if (a, b) not in present:
            pairs.append((a, b))
        if len(pairs) >= max(nwrites, 1):
            break

    cfg = ServeConfig(
        lane_widths=widths, max_queue=max(4 * width, nqueries),
        max_wait_s=0.005, update_flush=4, update_max_delay_s=0.01,
    )
    lat_of: dict = {}
    mark = engine.trace_mark()
    write_futs = []
    write_rejects = 0

    t0 = time.perf_counter()
    with engine.serve(cfg) as srv:

        def writer():
            nonlocal write_rejects
            # insert each slack pair, then delete it one batch later:
            # real structural change per merge, degree classes stable
            for k, (a, b) in enumerate(pairs + pairs):
                op = "insert" if k < len(pairs) else "delete"
                try:
                    write_futs.append(srv.submit_update(
                        [(op, a, b), (op, b, a)]
                    ))
                except BackpressureError:
                    write_rejects += 1
                time.sleep(0.001)

        wt = threading.Thread(target=writer)
        wt.start()
        futs = []
        for kind, root in stream:
            ts = time.monotonic()
            try:
                f = srv.submit(kind, root)
            except BackpressureError:
                continue
            f.add_done_callback(
                lambda _f, ts=ts: lat_of.setdefault(
                    _f, time.monotonic() - ts
                )
            )
            futs.append(f)
        for f in futs:
            f.result(timeout=600)
        wt.join(60)
        for f in write_futs:
            f.result(timeout=600)
        stats = srv.stats()
    wall_s = time.perf_counter() - t0

    retraces = engine.retraces_since(mark)
    upd = stats["updates"]
    incr = upd["by_mode"].get("incremental", 0)
    rebuilds = upd["by_mode"].get("rebuild", 0)
    incr_s = upd["merge_s_by_mode"].get("incremental", 0.0)
    mean_incr_s = incr_s / incr if incr else None
    amortization = (
        rebuild_s / mean_incr_s if mean_incr_s else None
    )
    lat = [lat_of[f] for f in futs if f in lat_of]
    ok = bool(
        retraces == 0
        and upd["merges"] >= 1
        and incr >= 1
        and rebuilds == 0
        and amortization is not None
        and amortization > 1.0
    )
    out = {
        "metric": "serve_mutate_amortization",
        "warning": "closed-loop (coordinated omission)",
        "unit": "rebuild_over_incremental",
        "value": round(amortization, 2) if amortization else None,
        "ok": ok,
        "nqueries": len(futs),
        "p50_read_ms": (
            round(1e3 * _percentile(lat, 0.50), 2) if lat else None
        ),
        "p99_read_ms": (
            round(1e3 * _percentile(lat, 0.99), 2) if lat else None
        ),
        "qps_under_writes": round(len(futs) / wall_s, 2),
        "updates_submitted": upd["submitted"],
        "update_merges": upd["merges"],
        "merges_incremental": incr,
        "merges_rebuild": rebuilds,
        "mean_incremental_merge_ms": (
            round(1e3 * mean_incr_s, 3) if mean_incr_s else None
        ),
        "full_rebuild_ms": round(1e3 * rebuild_s, 3),
        "write_rejects": write_rejects,
        "retraces_after_warmup": retraces,
        "graph_version": stats["graph_version"],
        "rows_patched": (
            obs.registry.get_counter("dynamic.merge.rows_patched")
            if obs.ENABLED else None
        ),
        "rows_rebucketed": (
            obs.registry.get_counter("dynamic.merge.rows_rebucketed")
            if obs.ENABLED else None
        ),
        "width": width,
        "scale": scale,
        "grid": list(grid_shape),
        "kinds": list(kinds),
        "load_s": round(load_s, 2),
        "warmup_s": round(warmup_s, 2),
    }
    obs.gauge("serve.bench.mutate_amortization", amortization or 0.0)
    if sidecar:
        try:
            out["obs_jsonl"] = obs.dump_jsonl()
        except Exception as e:  # telemetry must never fail the bench
            out["obs_error"] = str(e)
    return out


@_restores_trace_rate
def run_pool(scale: int = SCALE, edgefactor: int = EDGEFACTOR,
             grid_shape=(2, 4), kinds=("bfs", "pagerank")) -> dict:
    """BENCH_SERVE_POOL=1 — the multi-tenant pool scenario (ISSUE 12);
    see the module docstring for the three phases and their gates."""
    import threading

    import numpy as np

    from combblas_tpu import obs
    from combblas_tpu.parallel.grid import Grid
    from combblas_tpu.serve import (
        BackpressureError, EnginePool, ServeConfig,
    )
    from combblas_tpu.utils.rmat import rmat_symmetric_coo_host

    sidecar = obs.enable_sidecar("serve-pool")
    from combblas_tpu.obs import trace as obs_trace

    if sidecar:  # rate restored by @_restores_trace_rate
        obs_trace.set_sample_rate(
            float(os.environ.get("BENCH_TRACE_SAMPLE", "0.25"))
        )
    ntenants = max(int(os.environ.get("BENCH_POOL_TENANTS", "4")), 2)
    nqueries = int(os.environ.get("BENCH_SERVE_QUERIES", "2000"))
    nwrites = int(os.environ.get("BENCH_POOL_WRITES", "16"))
    widths = (1, 2, 4, 8, 16)
    n = 1 << scale
    grid = Grid.make(*grid_shape)

    # tenants: independent graphs, weighted 3:1 for the first pair
    # (the fairness phase's A/B), everyone else 1.0
    weights = [3.0, 1.0] + [1.0] * (ntenants - 2)
    cfg = ServeConfig(
        lane_widths=widths, max_queue=4096, max_wait_s=0.005,
        update_flush=4, update_max_delay_s=0.01,
        update_autostart=False,  # the POOL worker merges (WFQ-charged)
        # a generous per-tenant deadline SLO: the budget-burn column
        # in the per-tenant breakdown is live without changing what
        # the scenario admits (a standing backlog stays well inside)
        slo_deadline_s=float(
            os.environ.get("BENCH_SERVE_SLO_DEADLINE_S", "120")
        ),
        slo_target=0.95,
    )
    pool = EnginePool(grid)
    t0 = time.perf_counter()
    tenant_rows = {}
    for i in range(ntenants):
        rows, cols = rmat_symmetric_coo_host(42 + i, scale, edgefactor)
        name = f"t{i}"
        tenant_rows[name] = rows
        pool.add_tenant(
            name, rows, cols, n, weight=weights[i], config=cfg,
            kinds=kinds, keep_coo=(i == 0),
        )
    load_s = time.perf_counter() - t0
    names = [f"t{i}" for i in range(ntenants)]

    psrv = pool.serve()
    t0 = time.perf_counter()
    psrv.warmup()  # every tenant, every (kind, width) lane bucket
    warmup_s = time.perf_counter() - t0
    marks = {t: pool.engine(t).trace_mark() for t in names}

    # -- phase 1: WFQ weighted share (deterministic, pump-driven) ----------
    for _ in range(120):
        psrv.submit("t0", "bfs", 1)
        psrv.submit("t1", "bfs", 1)
    served0 = dict(psrv.wfq.describe()["served"])
    for _ in range(3):  # three DRR rounds, both queues stay saturated
        psrv.pump(force=True)
    served1 = psrv.wfq.describe()["served"]
    share = {
        t: served1.get(t, 0) - served0.get(t, 0) for t in ("t0", "t1")
    }
    fair_ratio = share["t0"] / max(share["t1"], 1)
    fairness_ok = 0.75 * 3.0 <= fair_ratio <= 1.25 * 3.0
    while psrv.pump(force=True):  # drain the saturation backlog
        pass

    # -- phase 2: mixed read/write load under the threaded worker ----------
    rng = np.random.default_rng(7)
    p = np.asarray(weights) / sum(weights)
    roots_of = {}
    for t in names:
        deg = np.bincount(tenant_rows[t], minlength=n)
        roots_of[t] = np.flatnonzero(deg > 0)
    stream = [
        (
            names[int(rng.choice(ntenants, p=p))],
            kinds[q % len(kinds)],
        )
        for q in range(nqueries)
    ]
    # churn pairs whose endpoint degrees sit in slack ladder classes
    # (the run_mutate recipe): provably in-place merges, so the
    # zero-retrace gate is a real plan-cache assertion under writes
    deg0 = np.asarray(pool.engine("t0").version.deg)
    slack = np.isin(deg0, (5, 7, 9, 10, 11, 13, 14, 15, 17, 18, 19))
    pool_v = np.flatnonzero(slack).tolist()
    r0, c0, _ = pool.engine("t0").version.host_coo
    present = set(zip(r0.tolist(), c0.tolist()))
    pairs = []
    for a, b in zip(pool_v[0::2], pool_v[1::2]):
        if (a, b) not in present:
            pairs.append((a, b))
        if len(pairs) >= max(nwrites, 1):
            break

    lat_of: dict = {}
    rejects = {t: 0 for t in names}
    write_futs = []
    write_rejects = 0
    t0 = time.perf_counter()
    with psrv:

        def writer():
            nonlocal write_rejects
            for k, (a, b) in enumerate(pairs + pairs):
                op = "insert" if k < len(pairs) else "delete"
                try:
                    write_futs.append(psrv.submit_update(
                        "t0", [(op, a, b), (op, b, a)]
                    ))
                except BackpressureError:
                    write_rejects += 1
                time.sleep(0.002)

        wt = threading.Thread(target=writer)
        wt.start()
        futs = []
        for tenant, kind in stream:
            root = int(rng.choice(roots_of[tenant]))
            ts = time.monotonic()
            try:
                f = psrv.submit(tenant, kind, root)
            except BackpressureError:
                rejects[tenant] += 1
                continue
            f.add_done_callback(
                lambda _f, ts=ts, t=tenant: lat_of.setdefault(
                    _f, (t, time.monotonic() - ts)
                )
            )
            futs.append(f)
        wt.join(120)
        # wait(), not result(): a failed/expired request must be
        # COUNTED, not crash the scenario before the summary line —
        # and the stranded gate is only real when futures may still
        # be pending at the check
        from concurrent.futures import wait as _wait

        _wait(futs + write_futs, timeout=600)
        stats = psrv.stats()
    wall_s = time.perf_counter() - t0
    stranded = sum(
        1 for f in futs + write_futs if not f.done()
    )
    read_errors = sum(
        1 for f in futs
        if f.done() and f.exception(timeout=0) is not None
    )
    write_errors = sum(
        1 for f in write_futs
        if f.done() and f.exception(timeout=0) is not None
    )
    retraces = {
        t: pool.engine(t).retraces_since(marks[t]) for t in names
    }
    lat_by_t = {t: [] for t in names}
    for t, dt in lat_of.values():
        lat_by_t[t].append(dt)
    lat_all = [dt for _t, dt in lat_of.values()]
    merges = stats["servers"]["t0"]["updates"]["merges"]

    # -- phase 3: LRU eviction under a tightened byte budget ---------------
    sizes = {
        t: pool.stats()["tenants"][t]["device_bytes"] for t in names
    }
    before_t1 = pool.engine("t1").version.E.to_host_coo()
    pool.byte_budget = max(sum(sizes.values()) // 2, max(sizes.values()))
    pool.refresh_bytes(names[-1])
    under_budget = [pool.resident_bytes() <= pool.byte_budget]
    for t in names:  # round-robin touches force evict/re-admit churn
        pool.engine(t)
        under_budget.append(
            pool.resident_bytes() <= pool.byte_budget
        )
    after_t1 = pool.engine("t1").version.E.to_host_coo()
    bit_exact = all(
        np.array_equal(x, y) for x, y in zip(before_t1, after_t1)
    )
    pst = pool.stats()
    evictions = {
        t: pst["tenants"][t]["evictions"] for t in names
    }
    under_budget_ok = all(under_budget)

    qps = len(futs) / wall_s if wall_s else 0.0
    per_tenant = {
        t: {
            "weight": weights[i],
            "queries": len(lat_by_t[t]),
            "rejected": rejects[t],
            "p99_ms": (
                round(1e3 * _percentile(lat_by_t[t], 0.99), 2)
                if lat_by_t[t] else None
            ),
            "mean_occupancy": stats["servers"][t].get("mean_occupancy"),
            "retraces": retraces[t],
            "evictions": evictions[t],
            "admits": pst["tenants"][t]["admits"],
            "device_bytes": sizes[t],
            # round 15: the tenant's SLO error-budget burn over the
            # run's window (None when the server stats predate it)
            "slo_burn": (
                (stats["servers"][t].get("slo") or {}).get("burn")
            ),
        }
        for i, t in enumerate(names)
    }
    padding_waste = None
    if obs.ENABLED:
        h = [
            obs.registry.get_histogram(
                "serve.batch.padding_waste", kind=k
            )
            for k in kinds
        ]
        tot = sum(x["count"] for x in h if x)
        if tot:
            padding_waste = round(
                sum(x["sum"] for x in h if x) / tot, 3
            )
    ok = bool(
        sum(retraces.values()) == 0
        and fairness_ok
        and under_budget_ok
        and bit_exact
        and stranded == 0
        and read_errors == 0  # the stream is well-formed, no faults
        and write_errors == 0
        and merges >= 1
        and sum(evictions.values()) >= 1
    )
    out = {
        "metric": "serve_pool_throughput",
        "warning": "closed-loop (coordinated omission)",
        "unit": "queries/s",
        "value": round(qps, 2),
        "ok": ok,
        "tenants": ntenants,
        "nqueries": len(futs),
        "p50_ms": (
            round(1e3 * _percentile(lat_all, 0.50), 2)
            if lat_all else None
        ),
        "p99_ms": (
            round(1e3 * _percentile(lat_all, 0.99), 2)
            if lat_all else None
        ),
        "padding_waste_mean_lanes": padding_waste,
        "retraces_after_warmup": sum(retraces.values()),
        "fair_share_ratio": round(fair_ratio, 2),
        "fairness_ok": fairness_ok,
        "wfq_shares_measured": share,
        "update_merges": merges,
        "write_rejects": write_rejects,
        "stranded": stranded,
        "read_errors": read_errors,
        "write_errors": write_errors,
        "byte_budget": pool.byte_budget,
        "resident_bytes_final": pool.resident_bytes(),
        "under_budget_ok": under_budget_ok,
        "readmit_bit_exact": bit_exact,
        "per_tenant": per_tenant,
        "latency_decomposition_ms": _trace_decomposition(obs_trace),
        "slo_burn_worst": max(
            (v["slo_burn"] for v in per_tenant.values()
             if v["slo_burn"] is not None),
            default=None,
        ),
        "scale": scale,
        "grid": list(grid_shape),
        "kinds": list(kinds),
        "load_s": round(load_s, 2),
        "warmup_s": round(warmup_s, 2),
        "wall_s": round(wall_s, 2),
    }
    obs.gauge("serve.bench.pool_qps", qps)
    if sidecar:
        try:
            out["obs_jsonl"] = obs.dump_jsonl()
        except Exception as e:  # telemetry must never fail the bench
            out["obs_error"] = str(e)
    return out


def run_recovery(scale: int = SCALE, edgefactor: int = EDGEFACTOR,
                 grid_shape=(2, 4), kinds=("bfs", "pagerank")) -> dict:
    """BENCH_SERVE_RECOVERY=1 — replica kills (home included)
    mid-stream under mixed read/write load, healed live by the
    supervisor; see the module docstring for the four gates."""
    import tempfile
    import threading

    import numpy as np

    from combblas_tpu import obs
    from combblas_tpu.dynamic import open_wal, recover_version
    from combblas_tpu.parallel.grid import Grid
    from combblas_tpu.serve import FleetRouter, ServeConfig

    sidecar = obs.enable_sidecar("serve-recovery")
    nreplicas = max(int(os.environ.get("BENCH_FLEET_REPLICAS", "3")), 2)
    nqueries = int(os.environ.get("BENCH_SERVE_QUERIES", "400"))
    nwrites = int(os.environ.get("BENCH_RECOVERY_WRITES", "24"))
    wal_dir = tempfile.mkdtemp(prefix="combblas-recovery-wal-")

    n = 1 << scale
    from combblas_tpu.utils.rmat import rmat_symmetric_coo_host

    rows, cols = rmat_symmetric_coo_host(42, scale, edgefactor)
    grid = Grid.make(*grid_shape)
    deg = np.bincount(rows, minlength=n)
    rng = np.random.default_rng(7)
    roots = rng.choice(np.flatnonzero(deg > 0), size=nqueries)
    stream = [
        (kinds[i % len(kinds)], int(r)) for i, r in enumerate(roots)
    ]
    # churn pairs absent from the graph (insert-only writes keep the
    # acked-edge-survives check exact)
    present = set(zip(rows.tolist(), cols.tolist()))
    pool = rng.permutation(n).tolist()
    pairs = []
    for a, b in zip(pool[0::2], pool[1::2]):
        if a != b and (a, b) not in present and (b, a) not in present:
            pairs.append((int(a), int(b)))
        if len(pairs) >= nwrites:
            break

    cfg = ServeConfig(
        lane_widths=(1, 2, 4, 8, 16),
        max_queue=max(64, nqueries), max_wait_s=0.005,
        update_flush=2, update_max_delay_s=0.01,
    )
    t0 = time.perf_counter()
    fr = FleetRouter.build(
        grid, rows, cols, n, replicas=nreplicas, config=cfg,
        kinds=kinds, wal_dir=wal_dir,
    )
    load_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    fr.warmup()
    warmup_s = time.perf_counter() - t0
    fr.start_supervisor(interval_s=0.02)

    acked: list = []
    write_failures = 0

    def writer():
        nonlocal write_failures
        for a, b in pairs:
            try:
                fr.submit_update(
                    [("insert", a, b), ("insert", b, a)]
                ).result(timeout=120)
                acked.append((a, b))
            except Exception:
                # a write rejected / failed at a kill boundary was
                # never CONFIRMED merged: it may still be durable
                # (WAL-appended) — allowed, but not counted acked
                write_failures += 1
            time.sleep(0.002)

    def kill(i):
        fr.replicas[i].faults.script("replica.death", at=(0,))
        try:
            fr.replicas[i].submit("bfs", int(roots[0]))
        except Exception:
            pass

    kills = {
        nqueries // 3: lambda: kill((fr.home + 1) % nreplicas),
        (2 * nqueries) // 3: lambda: kill(fr.home),  # THE promotion
    }
    ok = failed = 0
    lat: list[float] = []
    t0 = time.perf_counter()
    wt = threading.Thread(target=writer)
    wt.start()
    for i, (kind, root) in enumerate(stream):
        k = kills.get(i)
        if k is not None:
            k()
        ts = time.monotonic()
        try:
            fr.submit(kind, root).result(timeout=120)
            lat.append(time.monotonic() - ts)
            ok += 1
        except Exception:
            failed += 1
    wt.join(300)
    wall_s = time.perf_counter() - t0
    # let the supervisor finish healing the last kill: a quarantined
    # slot is no longer _dead() but stays in _needs_rebuild until its
    # replacement is actually re-admitted
    deadline = time.monotonic() + 30
    while (
        fr._needs_rebuild
        or any(fr._dead(i) for i in range(nreplicas))
    ) and time.monotonic() < deadline:
        time.sleep(0.02)
    availability = ok / nqueries

    # -- gate: 0 post-recovery retraces across the healed fleet ----------
    marks = [s.engine.trace_mark() for s in fr.replicas]
    for kind in kinds:
        for srv in fr.replicas:
            if srv.is_serving():
                srv.submit(kind, int(roots[0])).result(timeout=120)
    post_retraces = sum(
        s.engine.retraces_since(m) for s, m in zip(fr.replicas, marks)
    )
    home_version = fr.replicas[fr.home].engine.version
    stats = fr.stats()
    fr.close(drain=True)

    # -- gates: recovery bit-exact + zero acknowledged-write loss --------
    wal = open_wal(wal_dir)
    recovered = recover_version(wal_dir, wal, grid, kinds=kinds)
    wal.close()
    hr, hc, hv = home_version.E.to_host_coo()
    rr, rc_, rv = recovered.E.to_host_coo()
    bit_exact = (
        np.array_equal(hr, rr) and np.array_equal(hc, rc_)
        and np.array_equal(hv, rv)
    )
    have = set(zip(rr.tolist(), rc_.tolist()))
    lost = [
        p for p in acked
        if p not in have or (p[1], p[0]) not in have
    ]

    out = {
        "metric": "serve_recovery_availability",
        "warning": "closed-loop (coordinated omission)",
        "unit": "fraction_ok",
        "value": round(availability, 4),
        "availability_pct": round(100 * availability, 2),
        "ok": bool(
            availability >= 0.95
            and not lost
            and bit_exact
            and post_retraces == 0
            and stats["promotions"] >= 1
            and stats["replacements"] >= 2  # both kills healed
        ),
        "nqueries": nqueries,
        "reads_ok": ok,
        "reads_failed": failed,
        "read_retries": stats["read_retries"],
        "writes_acked": len(acked),
        "write_failures": write_failures,
        "acked_writes_lost": len(lost),
        "recovered_bit_exact": bit_exact,
        "post_recovery_retraces": post_retraces,
        "promotions": stats["promotions"],
        "replacements": stats["replacements"],
        "final_home": stats["home"],
        "p50_ms": round(1e3 * _percentile(lat, 0.50), 2) if lat else None,
        "p99_ms": round(1e3 * _percentile(lat, 0.99), 2) if lat else None,
        "qps_under_kills": round(nqueries / wall_s, 2),
        "recovered_nnz": int(len(rr)),
        "replicas": nreplicas,
        "scale": scale,
        "grid": list(grid_shape),
        "kinds": list(kinds),
        "load_s": round(load_s, 2),
        "warmup_s": round(warmup_s, 2),
        "wal_dir": wal_dir,
    }
    obs.gauge("serve.bench.recovery_availability", availability)
    if sidecar:
        try:
            out["obs_jsonl"] = obs.dump_jsonl()
        except Exception as e:  # telemetry must never fail the bench
            out["obs_error"] = str(e)
    return out


def _read_burst_qps(router, stream, timeout=120.0) -> float:
    """Read-only throughput through a fleet front door: submit the
    whole stream, wait for every future — wall-clock covers admission
    through settle (the replica-parallelism measurement's probe)."""
    t0 = time.perf_counter()
    futs = [router.submit(kind, root) for kind, root in stream]
    for f in futs:
        f.result(timeout=timeout)
    return len(futs) / (time.perf_counter() - t0)


@_restores_trace_rate
def run_recovery_process(scale: int = SCALE,
                         edgefactor: int = EDGEFACTOR,
                         kinds=("bfs", "pagerank")) -> dict:
    """BENCH_SERVE_RECOVERY=1 BENCH_FLEET=process — the kill-storm
    over REAL crash domains (module docstring): scripted SIGKILLs
    (non-home, then the home mid-stream), a SIGSTOP hang phase, and
    the N-process vs thread-fleet read-throughput comparison."""
    import signal
    import tempfile
    import threading

    import numpy as np

    from combblas_tpu import obs
    from combblas_tpu.dynamic import open_wal, recover_version
    from combblas_tpu.parallel.grid import Grid
    from combblas_tpu.serve import (
        FleetRouter,
        ProcessFleet,
        ServeConfig,
    )
    from combblas_tpu.utils import checkpoint

    sidecar = obs.enable_sidecar("serve-recovery-process")
    from combblas_tpu.obs import trace as obs_trace

    if sidecar:
        # sampled requests stitch router+IPC+child marks into one
        # trace per request; the summary folds them into the
        # router/ipc/child latency split (rate restored by
        # @_restores_trace_rate on every exit path)
        obs_trace.set_sample_rate(
            float(os.environ.get("BENCH_TRACE_SAMPLE", "0.25"))
        )
    nreplicas = max(int(os.environ.get("BENCH_FLEET_REPLICAS", "3")), 2)
    nqueries = int(os.environ.get("BENCH_SERVE_QUERIES", "400"))
    nwrites = int(os.environ.get("BENCH_RECOVERY_WRITES", "24"))
    nburst = int(os.environ.get("BENCH_PROC_BURST", "200"))
    work = tempfile.mkdtemp(prefix="combblas-procfleet-")
    wal_dir = os.path.join(work, "wal")

    n = 1 << scale
    from combblas_tpu.utils.rmat import rmat_symmetric_coo_host

    rows, cols = rmat_symmetric_coo_host(42, scale, edgefactor)
    # per-replica 1x1 mesh: each subprocess owns its whole runtime,
    # and the thread-fleet comparator shares ONE 1x1 grid — the
    # difference under the burst is exactly the shared exec lock
    grid = Grid.make(1, 1)
    deg = np.bincount(rows, minlength=n)
    rng = np.random.default_rng(7)
    roots = rng.choice(np.flatnonzero(deg > 0), size=nqueries)
    stream = [
        (kinds[i % len(kinds)], int(r)) for i, r in enumerate(roots)
    ]
    burst = [("bfs", int(r)) for r in roots[:nburst]]
    present = set(zip(rows.tolist(), cols.tolist()))
    pool = rng.permutation(n).tolist()
    pairs = []
    for a, b in zip(pool[0::2], pool[1::2]):
        if a != b and (a, b) not in present and (b, a) not in present:
            pairs.append((int(a), int(b)))
        if len(pairs) >= nwrites:
            break

    cfg = ServeConfig(
        lane_widths=(1, 2, 4, 8, 16),
        max_queue=max(64, nqueries), max_wait_s=0.005,
        update_flush=2, update_max_delay_s=0.01,
    )

    # -- comparator: the SAME burst through the thread fleet's
    #    shared-lock serialization (no WAL: read-only probe)
    tfr = FleetRouter.build(
        grid, rows, cols, n, replicas=nreplicas, config=cfg,
        kinds=kinds,
    )
    tfr.warmup()
    thread_qps = _read_burst_qps(tfr, burst)
    tfr.close(drain=False)

    t0 = time.perf_counter()
    fr = ProcessFleet.build(
        (1, 1), rows, cols, n, replicas=nreplicas, config=cfg,
        kinds=kinds, wal_dir=wal_dir,
        workdir=os.path.join(work, "proc"),
        hb_interval_s=0.1, hb_timeout_s=2.0,
        from_coo_kw={"headroom": 0.5},
    )
    load_s = time.perf_counter() - t0
    proc_qps = _read_burst_qps(fr, burst)
    fr.start_supervisor(interval_s=0.02)

    acked: list = []
    write_failures = 0

    def writer():
        nonlocal write_failures
        for a, b in pairs:
            try:
                fr.submit_update(
                    [("insert", a, b), ("insert", b, a)]
                ).result(timeout=120)
                acked.append((a, b))
            except Exception:
                # a write rejected / failed at a kill boundary was
                # never CONFIRMED merged: it may still be durable
                # (WAL-appended) — allowed, but not counted acked
                write_failures += 1
            time.sleep(0.002)

    # scripted REAL signals at routed-submit indices: a non-home
    # SIGKILL first, then the home ("home" resolves at fire time —
    # the promotion scenario)
    fr.proc_faults.sigkill(nqueries // 3,
                           replica=(fr.home + 1) % nreplicas)
    fr.proc_faults.sigkill((2 * nqueries) // 3, replica="home")

    ok = failed = 0
    lat: list[float] = []
    t0 = time.perf_counter()
    wt = threading.Thread(target=writer)
    wt.start()
    for kind, root in stream:
        ts = time.monotonic()
        try:
            fr.submit(kind, root).result(timeout=120)
            lat.append(time.monotonic() - ts)
            ok += 1
        except Exception:
            failed += 1
    wt.join(300)
    wall_s = time.perf_counter() - t0
    deadline = time.monotonic() + 60
    while (
        fr._needs_rebuild
        or any(fr._dead(i) for i in range(nreplicas))
    ) and time.monotonic() < deadline:
        time.sleep(0.02)
    availability = ok / nqueries

    # -- SIGSTOP hang phase: alive-but-silent must be DETECTED by
    #    heartbeat timeout and routed around, never wedging the router
    victim = (fr.home + 1) % nreplicas
    os.kill(fr.replicas[victim].proc.pid, signal.SIGSTOP)
    stop_ok = 0
    t_stop = time.monotonic()
    detected_s = None
    while time.monotonic() - t_stop < 30:
        try:
            fr.submit("bfs", int(roots[0])).result(timeout=120)
            stop_ok += 1
        except Exception:
            pass
        if detected_s is None and fr.replicas[victim].quarantined:
            detected_s = time.monotonic() - t_stop
        if detected_s is not None:
            break
        time.sleep(0.05)
    sigstop_detected = detected_s is not None
    deadline = time.monotonic() + 60
    while (
        fr._needs_rebuild
        or any(fr._dead(i) for i in range(nreplicas))
    ) and time.monotonic() < deadline:
        time.sleep(0.02)

    # -- gate: 0 post-recovery retraces across the healed fleet ----------
    marks = fr.trace_marks()
    for kind in kinds:
        for i, rp in enumerate(fr.replicas):
            if rp.is_serving():
                rp.submit(kind, int(roots[0])).result(timeout=120)
    post_retraces = fr.retraces_since(marks)

    # -- gates: recovery bit-exact vs a SURVIVOR + zero acked loss -------
    survivor_spool = os.path.join(work, "survivor.npz")
    fr.replicas[fr.home].call(
        "spool_version", {"path": survivor_spool}, timeout_s=120
    )
    stats = fr.stats()
    fr.close(drain=True)
    survivor = checkpoint.load_version(survivor_spool, grid,
                                       writable=False)
    wal = open_wal(wal_dir)
    recovered = recover_version(wal_dir, wal, grid, kinds=kinds)
    wal.close()
    hr, hc, hv = survivor.E.to_host_coo()
    rr, rc_, rv = recovered.E.to_host_coo()
    bit_exact = (
        np.array_equal(np.asarray(hr), np.asarray(rr))
        and np.array_equal(np.asarray(hc), np.asarray(rc_))
        and np.array_equal(np.asarray(hv), np.asarray(rv))
    )
    have = set(zip(rr.tolist(), rc_.tolist()))
    lost = [
        p for p in acked
        if p not in have or (p[1], p[0]) not in have
    ]

    # latency decomposition from the STITCHED traces only (the
    # thread-fleet comparator's in-process traces would pollute the
    # router/ipc/child attribution)
    decomp = _trace_decomposition(obs_trace, [
        r for r in obs_trace.records()
        if r["labels"].get("fleet") == "process"
    ])

    out = {
        "metric": "serve_recovery_process_availability",
        "warning": "closed-loop (coordinated omission)",
        "unit": "fraction_ok",
        "value": round(availability, 4),
        "availability_pct": round(100 * availability, 2),
        "ok": bool(
            availability >= 0.95
            and not lost
            and bit_exact
            and post_retraces == 0
            and sigstop_detected
            and stats["promotions"] >= 1
            and stats["replacements"] >= 3  # 2 SIGKILLs + SIGSTOP
        ),
        "fleet": "process",
        "nqueries": nqueries,
        "reads_ok": ok,
        "reads_failed": failed,
        "read_retries": stats["read_retries"],
        "writes_acked": len(acked),
        "write_failures": write_failures,
        "acked_writes_lost": len(lost),
        "recovered_bit_exact": bit_exact,
        "post_recovery_retraces": post_retraces,
        "sigkills": stats["sigkills"],
        "sigstop_detected": sigstop_detected,
        "sigstop_detect_s": (
            round(detected_s, 3) if detected_s is not None else None
        ),
        "sigstop_reads_served": stop_ok,
        "promotions": stats["promotions"],
        "replacements": stats["replacements"],
        "respawn_failures": stats["respawn_failures"],
        "ipc_timeouts": sum(
            r["ipc_timeouts"] for r in stats["per_replica"].values()
        ),
        "final_home": stats["home"],
        "p50_ms": round(1e3 * _percentile(lat, 0.50), 2) if lat else None,
        "p99_ms": round(1e3 * _percentile(lat, 0.99), 2) if lat else None,
        "latency_decomposition_ms": decomp,
        "latency_split_ms": _stitched_split(decomp),
        "qps_under_kills": round(nqueries / wall_s, 2),
        # the replica-parallelism headline: N processes (own runtimes)
        # vs N threads behind one shared exec lock, same read burst.
        # READ WITH cpus: on a single-core image the processes cannot
        # physically parallelize, so the ratio measures the ISOLATION
        # TAX (IPC round trip + result copy); the parallel win needs
        # per-replica silicon (the multi-chip follow-up).
        "read_qps_process": round(proc_qps, 2),
        "read_qps_thread": round(thread_qps, 2),
        "parallel_speedup": round(proc_qps / thread_qps, 2),
        "cpus": os.cpu_count(),
        "recovered_nnz": int(len(rr)),
        "replicas": nreplicas,
        "scale": scale,
        "grid": [1, 1],
        "kinds": list(kinds),
        "load_s": round(load_s, 2),
        "wall_s": round(wall_s, 2),
        "wal_dir": wal_dir,
    }
    obs.gauge("serve.bench.recovery_availability", availability)
    if sidecar:
        try:
            out["obs_jsonl"] = obs.dump_jsonl()
        except Exception as e:  # telemetry must never fail the bench
            out["obs_error"] = str(e)
    return out


def run_shard(scale: int = SCALE, edgefactor: int = EDGEFACTOR) -> dict:
    """BENCH_SERVE_SHARD=1 — cross-host sharded serving (module
    docstring): partition scaling, bit-exactness, one-slice
    SIGKILL+respawn availability, zero post-warmup retraces, durable
    writes and whole-service recovery."""
    import tempfile
    import threading

    import numpy as np

    from combblas_tpu import obs
    from combblas_tpu.dynamic import DeltaBatch
    from combblas_tpu.parallel.grid import Grid
    from combblas_tpu.serve import (
        GraphEngine,
        ServeConfig,
        ShardedEngine,
    )

    sidecar = obs.enable_sidecar("serve-shard")
    nslices = int(os.environ.get("BENCH_SHARD_SLICES", "2"))
    nqueries = int(os.environ.get("BENCH_SERVE_QUERIES", "200"))
    nwrites = int(os.environ.get("BENCH_SHARD_WRITES", "8"))
    mode = os.environ.get("BENCH_SHARD_MODE", "process")
    home = tempfile.mkdtemp(prefix="combblas-shard-bench-")

    n = 1 << scale
    from combblas_tpu.utils.rmat import rmat_symmetric_coo_host

    rows, cols = rmat_symmetric_coo_host(42, scale, edgefactor)
    rng = np.random.default_rng(7)
    weights = (rng.random(len(rows)) + 0.1).astype(np.float32)
    kinds = ("bfs", "sssp")
    deg = np.bincount(rows, minlength=n)
    roots = rng.choice(np.flatnonzero(deg > 0), size=nqueries)
    stream = [
        (kinds[i % len(kinds)], int(r)) for i, r in enumerate(roots)
    ]
    probe = np.asarray(roots[:8], np.int32)

    # -- the unsharded comparator (also the bit-exactness oracle) --------
    grid = Grid.make(1, 1)
    eng = GraphEngine.from_coo(
        grid, rows, cols, n, weights=weights, kinds=kinds,
        keep_coo=True,
    )
    unsharded_bytes = int(eng.version.device_bytes())
    ref = {k: eng.execute(k, probe) for k in kinds}

    t0 = time.perf_counter()
    sh = ShardedEngine.build(
        rows, cols, nrows=n, nslices=nslices, weights=weights,
        kinds=kinds, home=home, mode=mode, warmup=True,
        hb_interval_s=0.1, hb_timeout_s=2.0,
    )
    boot_s = time.perf_counter() - t0
    per_slice = [int(b) for b in sh.version.device_bytes_per_slice]
    bytes_ratio = max(per_slice) / unsharded_bytes

    def _bit_exact() -> bool:
        for kind, key in (("bfs", "parents"), ("sssp", "dist")):
            got = sh.execute(kind, probe)
            if not np.array_equal(np.asarray(ref[kind][key]),
                                  np.asarray(got[key])):
                return False
            if kind == "bfs" and int(
                ref[kind]["batch_niter"]
            ) != int(got["batch_niter"]):
                return False
        return True

    exact_before = _bit_exact()

    # -- round-21 wire-protocol A/B: the same engine answers the probe
    #    batch under forced dense, forced sparse, then auto encoding.
    #    Hop payload (bytes_by_enc over the frontier fans only — the
    #    collect/final fetch is identical across modes) is the gated
    #    quantity: sparse must ship <= 0.20x the dense bytes without
    #    giving back more than 5% hop wall. Five INTERLEAVED rounds
    #    (each round runs all three modes back to back, so scheduler /
    #    allocator drift on a single-CPU runner lands on every mode
    #    equally); bytes are deterministic, wall takes the per-mode
    #    min to shrug off one-sided multi-second GC outliers.
    modes = ("dense", "sparse", "auto")
    walls: dict = {m: [] for m in modes}
    stats: dict = {}
    saved_mode = sh.frontier_mode
    for _ in range(5):
        for fmode in modes:
            sh.frontier_mode = fmode
            sh.execute("bfs", probe)
            walls[fmode].append(sh.last_exec_stats["hop_wall_s"])
            stats[fmode] = sh.last_exec_stats
    sh.frontier_mode = saved_mode
    enc_ab: dict = {}
    for fmode in modes:
        st = stats[fmode]
        hop_payload = sum(
            v for k, v in st["bytes_by_enc"].items()
            if k in ("sparse", "dense")
        )
        best = min(walls[fmode])
        enc_ab[fmode] = {
            "hops": st["hops"],
            "hop_payload_bytes": int(hop_payload),
            "bytes_out": int(st["bytes_out"]),
            "bytes_in": int(st["bytes_in"]),
            "enc_hops": dict(st["enc_hops"]),
            "frontier_nnz": [int(z) for z in st["frontier_nnz"]],
            "hop_wall_s": round(best, 5),
            "hop_ms_mean": round(1e3 * best / max(st["hops"], 1), 3),
        }
    wire_ratio = (
        enc_ab["sparse"]["hop_payload_bytes"]
        / max(enc_ab["dense"]["hop_payload_bytes"], 1)
    )
    hop_wall_ratio = (
        enc_ab["sparse"]["hop_wall_s"]
        / max(enc_ab["dense"]["hop_wall_s"], 1e-9)
    )

    # -- closed-loop stream through the batcher, one slice SIGKILLed
    #    mid-stream while the supervisor heals it ------------------------
    mark = sh.trace_mark()
    srv = sh.serve(ServeConfig(
        lane_widths=(1, 2, 4, 8, 16),
        max_queue=max(64, nqueries), max_wait_s=0.005,
        update_flush=1,
    ))
    srv.start()
    sh.start_supervisor(interval_s=0.05)
    kill_at = nqueries // 2
    victim = 0
    ok = failed = 0
    lat: list[float] = []
    t0 = time.perf_counter()
    for i, (kind, root) in enumerate(stream):
        if i == kill_at:
            sh.slices[victim].kill()  # SIGKILL under load
        ts = time.monotonic()
        try:
            srv.submit(kind, root).result(timeout=120)
            lat.append(time.monotonic() - ts)
            ok += 1
        except Exception:
            failed += 1
    wall_s = time.perf_counter() - t0
    deadline = time.monotonic() + 60
    while (
        sh._needs_rebuild
        or not all(sl.is_serving() for sl in sh.slices)
    ) and time.monotonic() < deadline:
        time.sleep(0.02)
    availability = ok / nqueries
    post_retraces = sh.retraces_since(mark)
    exact_after = _bit_exact()

    # -- two-phase writes through the server, then whole-service
    #    recovery reassembles the identical COO --------------------------
    present = set(zip(rows.tolist(), cols.tolist()))
    pool = rng.permutation(n).tolist()
    pairs = []
    for a, b in zip(pool[0::2], pool[1::2]):
        if a != b and (a, b) not in present and (b, a) not in present:
            pairs.append((int(a), int(b)))
        if len(pairs) >= nwrites:
            break
    acked = 0
    seq = 0
    for a, b in pairs:
        f = srv.submit_update([("insert", a, b), ("insert", b, a)])
        srv.pump_updates(force=True)
        f.result(timeout=120)
        acked += 1
        eng.swap(eng.apply_delta(DeltaBatch.from_ops(
            [("insert", a, b, 1.0), ("insert", b, a, 1.0)],
            start_seq=seq,
        )))
        seq += 2
    frontier = list(sh.version.frontier)
    coo_live = sh.to_host_coo()
    sh.stop_supervisor()
    srv.close()
    sh.close()
    t0 = time.perf_counter()
    sh2 = ShardedEngine.recover(home, mode=mode)
    recover_s = time.perf_counter() - t0
    coo_rec = sh2.to_host_coo()
    recovered_equal = all(
        (x is None and y is None)
        or np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(coo_live, coo_rec)
    )
    er, ec, _ev = eng.version.E.to_host_coo()
    order = np.argsort(
        np.asarray(er, np.int64) * n + np.asarray(ec, np.int64),
        kind="stable",
    )
    writes_match_unsharded = np.array_equal(
        np.asarray(er)[order], coo_rec[0]
    ) and np.array_equal(np.asarray(ec)[order], coo_rec[1])
    sh2.close()

    out = {
        "metric": "serve_shard_availability",
        "warning": "closed-loop (coordinated omission)",
        "unit": "fraction_ok",
        "value": round(availability, 4),
        "availability_pct": round(100 * availability, 2),
        "ok": bool(
            availability >= 0.99
            and bytes_ratio <= 0.60
            and exact_before
            and exact_after
            and post_retraces == 0
            and sh.replacements >= 1
            and acked == len(pairs)
            and recovered_equal
            and writes_match_unsharded
            and wire_ratio <= 0.20
            and hop_wall_ratio <= 1.05
        ),
        "wire": {
            "ratio": round(wire_ratio, 4),
            "hop_wall_ratio": round(hop_wall_ratio, 4),
            "frontier_mode": saved_mode,
            "per_mode": enc_ab,
        },
        "mode": mode,
        "slices": nslices,
        "nqueries": nqueries,
        "reads_ok": ok,
        "reads_failed": failed,
        "bit_exact_before_kill": exact_before,
        "bit_exact_after_respawn": exact_after,
        "post_warmup_retraces": post_retraces,
        "slice_deaths": sh.replacements,
        "replacements": sh.replacements,
        "device_bytes_unsharded": unsharded_bytes,
        "device_bytes_per_slice": per_slice,
        "per_slice_bytes_ratio": round(bytes_ratio, 4),
        "writes_acked": acked,
        "write_frontier": frontier,
        "recovered_coo_equal": recovered_equal,
        "writes_match_unsharded": writes_match_unsharded,
        "p50_ms": round(1e3 * _percentile(lat, 0.50), 2) if lat else None,
        "p99_ms": round(1e3 * _percentile(lat, 0.99), 2) if lat else None,
        "qps_under_kill": round(nqueries / wall_s, 2),
        "boot_s": round(boot_s, 2),
        "recover_s": round(recover_s, 2),
        "nnz": int(len(rows)),
        "scale": scale,
        "kinds": list(kinds),
        "cpus": os.cpu_count(),
        "home": home,
    }
    obs.gauge("serve.bench.shard_availability", availability)
    if sidecar:
        try:
            out["obs_jsonl"] = obs.dump_jsonl()
        except Exception as e:  # telemetry must never fail the bench
            out["obs_error"] = str(e)
    return out


def _emit_pool_summary(out: dict) -> int:
    """The bench headline contract (bench.py ``emit_summary``) for the
    standalone pool scenario: a compact truncation-proof final stdout
    line + BENCH_SUMMARY.json carrying the per-tenant breakdown."""
    rc = 0 if out.get("ok") else 1
    s = {
        "summary": 1,
        "metric": out.get("metric"),
        "value": out.get("value", 0.0),
        "median": out.get("p50_ms", out.get("value", 0.0)),
        "warning": out.get("warning"),
        "rc": rc,
        "per_tenant": out.get("per_tenant"),
    }
    if out.get("wire") is not None:
        # shard scenario: per-hop wire-bytes + hop-latency breakdown
        # rides the summary line so truncated logs still carry it
        s["wire"] = out["wire"]
    path = os.environ.get("BENCH_SUMMARY_PATH", "BENCH_SUMMARY.json")
    try:
        with open(path, "w") as f:
            json.dump(s, f)
            f.write("\n")
    except OSError as e:
        s["summary_write_error"] = f"{path}: {e}"
    print(json.dumps(s), flush=True)
    return rc


def main():
    if os.environ.get("BENCH_SERVE_NET") == "1":
        # the open-loop net harness owns its own headline emission
        # (same contract, same BENCH_EMIT_SUMMARY=0 child-runner rule)
        from combblas_tpu.serve.net import loadgen

        sys.exit(loadgen.main())
    if os.environ.get("BENCH_SERVE_POOL") == "1":
        out = run_pool()
        print(json.dumps(out), flush=True)
        if os.environ.get("BENCH_EMIT_SUMMARY", "1") != "0":
            # STANDALONE contract: compact summary as the final line +
            # BENCH_SUMMARY.json, gate failures as the exit code.
            # Under bench.py's child runner (which sets
            # BENCH_EMIT_SUMMARY=0) the DETAIL line must stay last and
            # the exit code 0 — the parent parses the last line and
            # derives rc itself; a nonzero child exit would discard
            # the whole per-tenant payload as a "child crash".
            sys.exit(_emit_pool_summary(out))
        return
    if os.environ.get("BENCH_SERVE_SHARD") == "1":
        out = run_shard()
        print(json.dumps(out), flush=True)
        if os.environ.get("BENCH_EMIT_SUMMARY", "1") != "0":
            # standalone contract (see the pool branch): summary line
            # + BENCH_SUMMARY.json, gate failures as the exit code
            sys.exit(_emit_pool_summary(out))
        return
    if os.environ.get("BENCH_SERVE_CHAOS") == "1":
        out = run_chaos()
    elif os.environ.get("BENCH_SERVE_MUTATE") == "1":
        out = run_mutate()
    elif os.environ.get("BENCH_SERVE_RECOVERY") == "1":
        if os.environ.get("BENCH_FLEET") == "process":
            out = run_recovery_process()
        else:
            out = run_recovery()
    else:
        out = run()
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
