"""Query-serving benchmark: batched lanes vs one-call-per-query.

    python benchmarks/serve_bench.py            # 8 virtual CPU devices

Measures, on the tier-1 8-virtual-device CPU mesh (2x4 grid), a mixed
BFS/PageRank query stream served two ways over the SAME warm engine:

  * BASELINE — one engine call per query (the warm width-1 plan: no
    compile or trace cost is charged to the baseline; the gap is purely
    the batching, i.e. per-launch overhead and unamortized lanes);
  * BATCHED — the ``serve.Server`` micro-batcher coalescing the stream
    into width-``BENCH_SERVE_WIDTH`` (default 16) lane buckets.

Reports queries/s for both plus per-request p50/p99 latency under the
batched server, and CHECKS the serving acceptance gates:

  * ``speedup`` >= 4x at batch width 16 (the batched-serving payoff);
  * ``retraces_after_warmup`` == 0 — asserted via the engine's
    trace-time counter, mirrored in obs as ``trace.serve``;
  * ``backpressure_ok`` — a full queue REJECTS ``submit()`` with a
    retry-after hint instead of blocking unboundedly.

"ok" in the final JSON line is the AND of the three gates.

BENCH_OBS=1 attaches the structured telemetry sidecar through
``obs.enable_sidecar`` (queue-depth gauge, occupancy/padding-waste and
latency histograms, plan-cache + trace counters land in the JSONL);
``bench.py`` invokes this file under ``BENCH_SERVE=1`` with the sidecar
on by default.

BENCH_SERVE_CHAOS=1 runs the CHAOS scenario instead (ISSUE 6): the same
mixed stream through the threaded server under a seeded
``BENCH_SERVE_CHAOS_RATE`` (default 5%) execute-fault schedule plus a
``BENCH_SERVE_CHAOS_SWAPS``-deep (default 3) graph hot-swap storm, and
gates on: availability >= 95% of well-formed requests, ZERO stranded
futures, zero post-swap retraces (same-shape versions: the plan cache
must survive every swap), and all swaps applied. Reports availability
%, ok-request p50/p99 latency, and per-swap latency.
"""

from __future__ import annotations

import json
import os
import sys
import time

# tier-1 virtual mesh, set BEFORE jax initializes its backend
os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "--xla_force_host_platform_device_count" not in os.environ.get(
    "XLA_FLAGS", ""
):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    )
sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
)

SCALE = int(os.environ.get("BENCH_SERVE_SCALE", "9"))
EDGEFACTOR = int(os.environ.get("BENCH_SERVE_EDGEFACTOR", "8"))
WIDTH = int(os.environ.get("BENCH_SERVE_WIDTH", "16"))
NQUERIES = int(os.environ.get("BENCH_SERVE_QUERIES", "256"))


def _percentile(xs: list[float], q: float) -> float:
    xs = sorted(xs)
    i = min(len(xs) - 1, int(round(q * (len(xs) - 1))))
    return xs[i]


def _setup(scale, edgefactor, width, nqueries, grid_shape, kinds,
           widths):
    """Shared graph/stream/warmup setup: the chaos scenario must
    measure the SAME engine, stream, and warm plans the baseline
    scenario does."""
    import numpy as np

    from combblas_tpu.parallel.grid import Grid
    from combblas_tpu.serve import GraphEngine
    from combblas_tpu.utils.rmat import rmat_symmetric_coo_host

    n = 1 << scale
    rows, cols = rmat_symmetric_coo_host(42, scale, edgefactor)
    grid = Grid.make(*grid_shape)

    # raw COO straight in: from_coo deduplicates internally (one
    # int64-key unique pass — doing it here too would double the sort)
    t0 = time.perf_counter()
    engine = GraphEngine.from_coo(grid, rows, cols, n, kinds=kinds)
    load_s = time.perf_counter() - t0

    # mixed query stream: alternating kinds over random reachable roots
    # (raw rows give the same reachable set as the deduped edge list)
    deg = np.bincount(rows, minlength=n)
    rng = np.random.default_rng(7)
    roots = rng.choice(np.flatnonzero(deg > 0), size=nqueries)
    stream = [
        (kinds[i % len(kinds)], int(r)) for i, r in enumerate(roots)
    ]

    t0 = time.perf_counter()
    engine.warmup(kinds=kinds, widths=widths)
    warmup_s = time.perf_counter() - t0
    return engine, rows, cols, roots, stream, load_s, warmup_s


def run(scale: int = SCALE, edgefactor: int = EDGEFACTOR,
        width: int = WIDTH, nqueries: int = NQUERIES,
        grid_shape=(2, 4), kinds=("bfs", "pagerank")) -> dict:
    import numpy as np

    from combblas_tpu import obs
    from combblas_tpu.serve import BackpressureError, ServeConfig

    sidecar = obs.enable_sidecar("serve")

    # plans for every bucket the server may flush under, plus width-1
    # for the baseline — after this, ZERO traces is the contract
    widths = tuple(sorted({1, width}))
    engine, rows, _cols, roots, stream, load_s, warmup_s = _setup(
        scale, edgefactor, width, nqueries, grid_shape, kinds, widths,
    )
    mark = engine.trace_mark()

    # -- baseline: one warm call per query --------------------------------
    t0 = time.perf_counter()
    for kind, root in stream:
        engine.execute(kind, np.asarray([root], np.int32))
    base_s = time.perf_counter() - t0
    qps_base = nqueries / base_s

    # -- batched serving ---------------------------------------------------
    cfg = ServeConfig(
        lane_widths=(width,),  # the acceptance gate's fixed bucket
        max_queue=max(4 * width, nqueries),
        max_wait_s=0.05,
    )
    lat: list[float] = []

    def _stamp(ts):
        # completion-time stamping: measuring at result()-collection
        # time would charge a fast request for an earlier slow batch
        return lambda _f: lat.append(time.monotonic() - ts)

    t0 = time.perf_counter()
    with engine.serve(cfg) as srv:
        submitted = []
        for kind, root in stream:
            f = srv.submit(kind, root)
            f.add_done_callback(_stamp(time.monotonic()))
            submitted.append(f)
        for f in submitted:
            f.result(timeout=600)
    batch_s = time.perf_counter() - t0
    qps_batch = nqueries / batch_s
    stats = srv.stats()

    retraces = engine.retraces_since(mark)

    # -- backpressure gate: a full queue rejects, never blocks -------------
    tiny = engine.serve(ServeConfig(
        lane_widths=(width,), max_queue=4, max_wait_s=30.0,
    ))  # worker NOT started: the queue cannot drain
    backpressure_ok = False
    retry_after = None
    try:
        for i in range(8):
            tiny.scheduler.submit("bfs", int(roots[0]))
    except BackpressureError as e:
        backpressure_ok = True
        retry_after = e.retry_after_s
    tiny.scheduler.fail_pending(RuntimeError("bench probe teardown"))

    speedup = qps_batch / qps_base if qps_base else float("inf")
    out = {
        "metric": "serve_throughput",
        "unit": "queries/s",
        "value": round(qps_batch, 2),
        "qps_batched": round(qps_batch, 2),
        "qps_baseline": round(qps_base, 2),
        "speedup": round(speedup, 2),
        "p50_ms": round(1e3 * _percentile(lat, 0.50), 2),
        "p99_ms": round(1e3 * _percentile(lat, 0.99), 2),
        "width": width,
        "nqueries": nqueries,
        "kinds": list(kinds),
        "scale": scale,
        "grid": list(grid_shape),
        "edges_raw": int(len(rows)),  # pre-dedup (from_coo dedups)
        "load_s": round(load_s, 2),
        "warmup_s": round(warmup_s, 2),
        "mean_occupancy": stats["mean_occupancy"],
        "batches": stats["batches"],
        "retraces_after_warmup": retraces,
        "backpressure_ok": backpressure_ok,
        "backpressure_retry_after_s": retry_after,
        "ok": bool(
            speedup >= 4.0 and retraces == 0 and backpressure_ok
        ),
    }
    obs.gauge("serve.bench.qps_batched", qps_batch)
    obs.gauge("serve.bench.qps_baseline", qps_base)
    obs.gauge("serve.bench.speedup", speedup)
    if sidecar:
        try:
            out["obs_jsonl"] = obs.dump_jsonl()
        except Exception as e:  # telemetry must never fail the bench
            out["obs_error"] = str(e)
    return out


def run_chaos(scale: int = SCALE, edgefactor: int = EDGEFACTOR,
              width: int = WIDTH, nqueries: int | None = None,
              grid_shape=(2, 4), kinds=("bfs", "pagerank")) -> dict:
    """Availability under injected faults + a hot-swap storm (the
    resilience acceptance scenario — see module docstring)."""
    from concurrent.futures import Future, wait

    from combblas_tpu import obs
    from combblas_tpu.serve import BackpressureError, ServeConfig

    sidecar = obs.enable_sidecar("serve-chaos")
    rate = float(os.environ.get("BENCH_SERVE_CHAOS_RATE", "0.05"))
    # default seed 11 fires its first 5% fault on the 4th execute call:
    # even a short, well-coalesced stream provably exercises recovery
    seed = int(os.environ.get("BENCH_SERVE_CHAOS_SEED", "11"))
    nswaps = int(os.environ.get("BENCH_SERVE_CHAOS_SWAPS", "3"))
    nqueries = (
        int(os.environ.get("BENCH_SERVE_QUERIES", "400"))
        if nqueries is None else nqueries
    )

    widths = tuple(sorted({1, 2, 4, 8, width}))
    engine, rows, cols, _roots, stream, _load_s, _warmup_s = _setup(
        scale, edgefactor, width, nqueries, grid_shape, kinds, widths,
    )
    # the swap storm's versions: SAME COO, so operand shapes match and
    # the zero-post-swap-retrace gate is a real plan-cache assertion
    t0 = time.perf_counter()
    versions = [engine.build_version(rows, cols) for _ in range(nswaps)]
    build_s = time.perf_counter() - t0
    mark = engine.trace_mark()

    cfg = ServeConfig(
        lane_widths=widths, max_queue=max(4 * width, nqueries),
        max_wait_s=0.005,
    )
    lat_of: dict = {}  # future -> completion latency (ok OR failed)

    def _stamp(fut, ts):
        fut.add_done_callback(
            lambda f: lat_of.__setitem__(f, time.monotonic() - ts)
        )

    swap_s: list[float] = []
    swap_at = {
        (k + 1) * nqueries // (nswaps + 1): k for k in range(nswaps)
    }
    t0 = time.perf_counter()
    futs = []
    with engine.serve(cfg) as srv:
        srv.faults.rate("engine.execute", rate, seed=seed)
        for i, (kind, root) in enumerate(stream):
            try:
                f = srv.submit(kind, root)
                _stamp(f, time.monotonic())
            except BackpressureError as e:
                # breaker fast-fail / queue-full under high chaos
                # rates: unavailability is DATA here, not a crash
                f = Future()
                f.set_exception(e)
            futs.append(f)
            k = swap_at.get(i)
            if k is not None:  # mid-stream, under live load
                swap_s.append(srv.swap_graph(versions[k])["swap_s"])
        wait(futs, timeout=600)  # failures are data; stranded counted
        stats = srv.stats()
        fault_stats = srv.faults.stats()
    wall_s = time.perf_counter() - t0

    stranded = sum(1 for f in futs if not f.done())
    ok = sum(
        1 for f in futs if f.done() and f.exception(timeout=0) is None
    )
    availability = ok / nqueries
    retraces = engine.retraces_since(mark)
    lat = [lat_of[f] for f in futs if f in lat_of]
    ok_lat = [
        lat_of[f] for f in futs
        if f in lat_of and f.done() and f.exception(timeout=0) is None
    ]
    per_kind = stats["per_kind"]

    out = {
        "metric": "serve_chaos_availability",
        "unit": "fraction_ok",
        "value": round(availability, 4),
        "availability_pct": round(100 * availability, 2),
        "ok": bool(
            availability >= 0.95
            and stranded == 0
            and retraces == 0
            and len(swap_s) == nswaps
        ),
        "nqueries": nqueries,
        "completed_ok": ok,
        "stranded": stranded,
        "fault_rate": rate,
        "fault_seed": seed,
        "faults_injected": fault_stats["fired"].get("engine.execute", 0),
        "retried": {
            k: per_kind[k]["retried"] for k in per_kind
        },
        "poisoned": {
            k: per_kind[k]["poisoned"] for k in per_kind
        },
        "breaker_opened": {
            k: per_kind[k].get("breaker", {}).get("opened_total", 0)
            for k in per_kind
        },
        "p50_ms": round(1e3 * _percentile(lat, 0.50), 2) if lat else None,
        "p99_ms": round(1e3 * _percentile(lat, 0.99), 2) if lat else None,
        "p99_ok_ms": (
            round(1e3 * _percentile(ok_lat, 0.99), 2) if ok_lat else None
        ),
        "swaps": len(swap_s),
        "swap_latency_ms": [round(1e3 * s, 3) for s in swap_s],
        "swap_build_s": round(build_s, 2),
        "retraces_after_swaps": retraces,
        "qps_under_chaos": round(nqueries / wall_s, 2),
        "width": width,
        "scale": scale,
        "grid": list(grid_shape),
        "kinds": list(kinds),
        "batches": stats["batches"],
        "graph_version": stats["graph_version"],
    }
    obs.gauge("serve.bench.chaos_availability", availability)
    if sidecar:
        try:
            out["obs_jsonl"] = obs.dump_jsonl()
        except Exception as e:  # telemetry must never fail the bench
            out["obs_error"] = str(e)
    return out


def main():
    if os.environ.get("BENCH_SERVE_CHAOS") == "1":
        out = run_chaos()
    else:
        out = run()
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
