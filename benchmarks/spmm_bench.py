"""Batched SpMM benchmark: fused k-hop sparse×dense vs loop-over-columns
batch SpMV, plus the serve ``"propagate"`` capture.

    python benchmarks/spmm_bench.py          # 8 virtual CPU devices

Three scenarios, one JSON line each plus the official final line (the
``bench.py BENCH_SPMM=1`` wrapper turns it into the standard
``{summary, metric, value, median, warning, rc}`` headline +
``BENCH_SUMMARY.json``):

* **golden** — SpMM agreement on 1x1 AND 2x2 grids against scipy
  ``A @ X`` (plus_times, integer-valued f32 data so f32 accumulation
  is EXACT regardless of fold order) and dense semiring folds
  (min_plus / max_min), duplicate-entry COO included, both backends
  where admissible;
* **perf** (the acceptance gate) — R-MAT scale ``BENCH_SPMM_SCALE``
  (default 14), feature width ``BENCH_SPMM_WIDTH`` (default 64),
  ``BENCH_SPMM_HOPS`` (default 2) hops, on the ``BENCH_SPMM_GRID``
  (default 2x2 — the tier-1 virtual mesh, like the serve bench; the
  lane is a DISTRIBUTED system and the per-launch collective is part
  of what fusion amortizes) mesh:
  BASELINE = loop-over-columns batch SpMV (one warm ``dist_spmv_ell``
  launch per column per hop — what the pre-round-12 stack would do;
  column uploads hoisted out of the timed region, matching the fused
  side's untimed upload);
  FUSED = one ``spmm_khop`` launch.  Gate: fused >= 3x baseline.
  Gold-checked against scipy before timing.  Reference points on this
  box: 4.9x on the 2x2 mesh, 2.5x on 1x1 (``BENCH_SPMM_GRID=1x1`` —
  no collectives, so only launch overhead and payload vectorization
  amortize; the TPU gather's free payload width is absent on CPU).
* **serve** — a ``"propagate"`` engine (features loaded, warm lanes),
  ``BENCH_SPMM_QUERIES`` (default 128) single-root queries through the
  batched ``Server``; gates on ZERO post-warmup retraces and reports
  queries/s + p50/p99 latency.

``ok`` in the final line is the AND of the gates (golden, >=3x, zero
retraces).
"""

from __future__ import annotations

import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "--xla_force_host_platform_device_count" not in os.environ.get(
    "XLA_FLAGS", ""
):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    )
sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
)

SCALE = int(os.environ.get("BENCH_SPMM_SCALE", "14"))
EDGEFACTOR = int(os.environ.get("BENCH_SPMM_EDGEFACTOR", "8"))
FEATW = int(os.environ.get("BENCH_SPMM_WIDTH", "64"))
HOPS = int(os.environ.get("BENCH_SPMM_HOPS", "2"))
NQUERIES = int(os.environ.get("BENCH_SPMM_QUERIES", "128"))
REPEATS = int(os.environ.get("BENCH_SPMM_REPEATS", "3"))
GRID = os.environ.get("BENCH_SPMM_GRID", "2x2")


def _percentile(xs, q):
    # the shared obs quantile helper (round 15): one percentile
    # implementation for benches, the registry, and the exporter
    from combblas_tpu.obs.sinks import quantiles

    return quantiles(xs, (q,))[q]


def _rmat(scale, edgefactor, seed=7):
    import jax
    import numpy as np

    from combblas_tpu.utils.rmat import rmat_symmetric_coo

    rows, cols = rmat_symmetric_coo(
        jax.random.key(seed), scale=scale, edgefactor=edgefactor
    )
    return np.asarray(rows), np.asarray(cols)


def run_golden():
    """Exact agreement, small scale, 1x1 + 2x2 grids, dup COO."""
    import numpy as np

    from combblas_tpu.parallel.ellmat import EllParMat
    from combblas_tpu.parallel.grid import Grid
    from combblas_tpu.parallel.vec import DistMultiVec
    from combblas_tpu.parallel.spmm import dist_spmm_ell
    from combblas_tpu.semiring import MAX_MIN, MIN_PLUS, PLUS_TIMES

    rng = np.random.default_rng(0)
    n, m, F = 256, 1500, 24
    r = rng.integers(0, n, m)
    c = rng.integers(0, n, m)
    r = np.concatenate([r, r[:100]])  # duplicates on purpose
    c = np.concatenate([c, c[:100]])
    v = rng.integers(1, 5, len(r)).astype(np.float32)
    X = rng.integers(0, 4, (n, F)).astype(np.float32)
    A = np.zeros((n, n), np.float32)
    np.add.at(A, (r, c), v)

    def golden(name):
        if name == "plus_times":
            return A @ X
        big = np.full(
            (n, F), np.inf if name == "min_plus" else -np.inf, np.float32
        )
        for rr, cc, vv in zip(r, c, v):
            if name == "min_plus":
                big[rr] = np.minimum(big[rr], vv + X[cc])
            else:
                big[rr] = np.maximum(big[rr], np.minimum(vv, X[cc]))
        return big

    checks = 0
    for grid in (Grid.make(1, 1), Grid.make(2, 2)):
        E = EllParMat.from_host_coo(grid, r, c, v, n, n)
        Xd = DistMultiVec.from_global(grid, X, align="col")
        for sr in (PLUS_TIMES, MIN_PLUS, MAX_MIN):
            g = golden(sr.name)
            backends = (
                ("mxu_gather", "scatter")
                if sr.name == "plus_times" else ("scatter",)
            )
            for backend in backends:
                got = dist_spmm_ell(sr, E, Xd, backend=backend).to_global()
                if not np.allclose(got, g, equal_nan=True):
                    return {"golden_ok": False, "checks": checks,
                            "failed": f"{grid.pr}x{grid.pc}/"
                                      f"{sr.name}/{backend}"}
                checks += 1
    return {"golden_ok": True, "checks": checks}


def run_perf():
    """The >=3x gate: fused k-hop SpMM vs loop-over-columns SpMV."""
    import jax
    import numpy as np

    from combblas_tpu.parallel.ellmat import (
        EllParMat, dist_spmv_ell,
    )
    from combblas_tpu.parallel.grid import Grid
    from combblas_tpu.parallel.vec import DistMultiVec, DistVec
    from combblas_tpu.parallel.spmm import (
        _spmm_khop_impl, pad_features, spmm_backend_heuristic,
    )
    from combblas_tpu.semiring import PLUS_TIMES

    rows, cols = _rmat(SCALE, EDGEFACTOR)
    n = 1 << SCALE
    rng = np.random.default_rng(3)
    # integer-valued f32: k-hop plus_times sums stay exactly
    # representable, so the scipy golden is EXACT (==)
    X = rng.integers(0, 3, (n, FEATW)).astype(np.float32)
    pr, pc = (int(x) for x in GRID.split("x"))
    grid = Grid.make(pr, pc)
    ones = np.ones(len(rows), np.float32)
    t0 = time.perf_counter()
    E = EllParMat.from_host_coo(grid, rows, cols, ones, n, n)
    build_s = time.perf_counter() - t0
    backend = spmm_backend_heuristic(PLUS_TIMES)

    # golden (scipy CSR) before timing
    try:
        import scipy.sparse as sp

        A = sp.csr_matrix(
            (ones, (rows, cols)), shape=(n, n), dtype=np.float32
        )
        G = X
        for _ in range(HOPS):
            G = A @ G
        golden_available = True
    except ImportError:
        golden_available = False

    Xd = DistMultiVec.from_global(grid, pad_features(X), align="col")
    fused = _spmm_khop_impl(
        PLUS_TIMES, E, Xd, None, HOPS, backend, False
    )
    jax.block_until_ready(fused.blocks)
    got = fused.to_global()[:, :FEATW]
    # None = "scipy unavailable, exactness unchecked" — reported as a
    # skip, NOT folded into the acceptance verdict as a failure (an
    # absent optional dep must not masquerade as a numerical bug)
    golden_exact = (
        bool(np.array_equal(got, G)) if golden_available else None
    )

    # baseline: one column at a time, k chained SpMV launches each.
    # Columns are uploaded ONCE, outside the timed region (the fused
    # path's Xd upload is also untimed) — the gate isolates the
    # launch-count / fusion effect, not host-transfer overhead.
    cols_dev = [
        DistVec.from_global(grid, X[:, f].copy(), align="col")
        for f in range(FEATW)
    ]
    y = dist_spmv_ell(PLUS_TIMES, E, cols_dev[0])  # warm the one shape
    jax.block_until_ready(y.blocks)

    def run_baseline():
        outs = []
        for v in cols_dev:
            for _ in range(HOPS):
                v = dist_spmv_ell(PLUS_TIMES, E, v)
            outs.append(v.blocks)
        jax.block_until_ready(outs)

    def run_fused():
        out = _spmm_khop_impl(
            PLUS_TIMES, E, Xd, None, HOPS, backend, False
        )
        jax.block_until_ready(out.blocks)

    base_ts, fused_ts = [], []
    for _ in range(max(REPEATS, 1)):
        t0 = time.perf_counter()
        run_baseline()
        base_ts.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        run_fused()
        fused_ts.append(time.perf_counter() - t0)
    base_s = sorted(base_ts)[len(base_ts) // 2]
    fused_s = sorted(fused_ts)[len(fused_ts) // 2]
    speedup = base_s / fused_s if fused_s > 0 else 0.0
    return {
        "scale": SCALE, "edgefactor": EDGEFACTOR, "feature_width": FEATW,
        "hops": HOPS, "grid": GRID, "nnz": int(len(rows)), "backend": backend,
        "build_s": round(build_s, 3),
        "baseline_loop_spmv_s": round(base_s, 4),
        "fused_spmm_s": round(fused_s, 4),
        "speedup": round(speedup, 2),
        "speedup_ok": bool(speedup >= 3.0),
        "golden_exact": golden_exact,
        "repeats": {"baseline": [round(t, 4) for t in base_ts],
                    "fused": [round(t, 4) for t in fused_ts]},
    }


def run_serve():
    """The ``"propagate"`` serve capture: warm lanes, zero retraces."""
    import numpy as np

    from combblas_tpu.parallel.grid import Grid
    from combblas_tpu.serve import GraphEngine
    from combblas_tpu.serve.scheduler import ServeConfig

    scale = int(os.environ.get("BENCH_SPMM_SERVE_SCALE", "11"))
    width = int(os.environ.get("BENCH_SPMM_SERVE_WIDTH", "16"))
    n = 1 << scale
    rows, cols = _rmat(scale, EDGEFACTOR, seed=11)
    rng = np.random.default_rng(5)
    X = rng.random((n, FEATW)).astype(np.float32)
    grid = Grid.make(2, 2)
    t0 = time.perf_counter()
    engine = GraphEngine.from_coo(
        grid, rows, cols, n, features=X,
        propagate_hops=HOPS, propagate_normalize=True,
        kinds=("bfs", "propagate"),
    )
    load_s = time.perf_counter() - t0
    cfg = ServeConfig(lane_widths=(1, 4, width), max_wait_s=0.002)
    lat = []
    with engine.serve(cfg) as srv:
        t0 = time.perf_counter()
        srv.warmup()
        warmup_s = time.perf_counter() - t0
        mark = engine.trace_mark()
        roots = rng.integers(0, n, NQUERIES)
        t0 = time.perf_counter()
        futs = []
        for r in roots:
            ts = time.perf_counter()
            futs.append((ts, srv.submit("propagate", int(r))))
        for ts, f in futs:
            feats = f.result(timeout=120)["features"]
            assert feats.shape == (FEATW,), feats.shape
            lat.append(time.perf_counter() - ts)
        total_s = time.perf_counter() - t0
        retraces = engine.retraces_since(mark)
        stats = srv.stats()
    return {
        "serve_scale": scale, "serve_width": width,
        "queries": NQUERIES,
        "queries_per_s": round(NQUERIES / total_s, 1),
        "p50_ms": round(1e3 * _percentile(lat, 0.50), 2),
        "p99_ms": round(1e3 * _percentile(lat, 0.99), 2),
        "retraces_after_warmup": int(retraces),
        "zero_retrace_ok": bool(retraces == 0),
        "load_s": round(load_s, 2), "warmup_s": round(warmup_s, 2),
        "batches": stats["batches"],
    }


def main():
    out = {"metric": "spmm_khop_speedup", "unit": "x"}
    golden = run_golden()
    print(json.dumps({"phase": "golden", **golden}), flush=True)
    perf = run_perf()
    print(json.dumps({"phase": "perf", **perf}), flush=True)
    serve = run_serve()
    print(json.dumps({"phase": "serve", **serve}), flush=True)
    out.update(
        value=perf["speedup"],
        golden=golden, perf=perf, serve=serve,
        ok=bool(
            golden.get("golden_ok")
            and perf.get("speedup_ok")
            # None (scipy absent) skips the exactness gate visibly
            # rather than failing it; False stays a hard failure
            and perf.get("golden_exact") is not False
            and serve.get("zero_retrace_ok")
        ),
    )
    if perf.get("golden_exact") is None:
        out["warning"] = "scipy unavailable — perf exactness gate skipped"
    if not out["ok"]:
        out["warning"] = "a gate failed (golden / >=3x / retraces)"
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
