"""SUMMA SpGEMM microbenchmark (≈ ReleaseTests/MultTiming.cpp).

A·A on an R-MAT matrix with pre-sized capacities so the timed section is
the compiled SUMMA only (axon-safe protocol: barrier readback closes the
timed window). Prints one JSON line.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

SCALE = int(os.environ.get("BENCH_SCALE", "14"))
REPS = int(os.environ.get("BENCH_REPS", "3"))
# Square process grid side: BENCH_PR=2 runs the DISTRIBUTED SUMMA on a
# pr x pr virtual CPU mesh (XLA host-device-count, the conftest.py
# pattern) — the large-scale distributed capture knob (r9's scale-17
# record). 1 (default) keeps the single-device protocol unchanged.
PR = int(os.environ.get("BENCH_PR", "1"))
# Windowed-tier schedule: BENCH_RING=1 runs the carousel
# (neighbor-rotation) schedule, BENCH_PIPELINE=0 pins its serial-chain
# control — the pipelined-vs-unpipelined A/B of ISSUE 7.
RING = os.environ.get("BENCH_RING", "0") == "1"
PIPELINE = os.environ.get("BENCH_PIPELINE", "1") == "1"
# Input pattern: rmat (default) | banded — a |i-j| <= n/64 band whose
# A² support leaves most 2D windows symbolically EMPTY (the packed-
# launch ratio showcase; R-MAT support is too uniform to skip much).
PATTERN = os.environ.get("BENCH_PATTERN", "rmat")
# Windowed multi-device dispatch: fused (default, one shard_map graph)
# | blocked (one small program per row block — the live-set bound that
# fits scale-17+ tiles in RAM; scatter backend only).
DISPATCH = os.environ.get("BENCH_DISPATCH", "fused")
# esc | mxu | scan | scanphased | windowed | auto  (auto = the tier
# router's choice, sized host-side like every other kernel here)
KERNEL = os.environ.get("BENCH_KERNEL", "esc")
PHASES = int(os.environ.get("BENCH_PHASES", "8"))  # scanphased only
OCAP = os.environ.get("BENCH_OCAP")  # override out_capacity (mxu sparsify
# cost scales with it: searchsorted queries per slot; scan: accumulator
# slots — sized from the exact host symbolic out-nnz when unset)
# BENCH_GOLDEN=1 (default): after timing, verify the result EXACTLY
# against the scipy A² golden (nnz and integer count values) — the same
# golden the ESC path is validated against, so agreement here is
# agreement with ESC. =0 skips (saves the host product + readback).
GOLDEN = os.environ.get("BENCH_GOLDEN", "1") == "1"
BLOCK_ROWS = int(os.environ.get("BENCH_BLOCK_ROWS", "0"))  # windowed tier
BLOCK_COLS = int(os.environ.get("BENCH_BLOCK_COLS", "0"))  # 2D dot backend
# R-MAT edge factor: flops (and the sort-based tiers' cost) grow with
# it while dense n^3 work is fixed, so sweeping it traces the
# scan -> windowed-dot crossover at one scale (results/r7).
EDGEFACTOR = int(os.environ.get("BENCH_EDGEFACTOR", "8"))
# windowed-dot stage-product precision (parallel/spgemm._mxu_dot):
# f32 | bf16 | bf16x3.  f32 default — exact everywhere; on the chip
# bf16 is the fast mode (exact for 0/1 counts < 2^24).
DOT_MODE = os.environ.get("BENCH_DOT_MODE", "f32")
# --- round-10 plan-store knobs ---------------------------------------------
# BENCH_PLAN_STORE=dir points the measured-plan store at `dir` ("0"
# disables) — it simply sets COMBBLAS_PLAN_STORE before the library
# loads, so BENCH_KERNEL=auto resolves through the store (tuner
# precedence: store > env > probe > heuristic; probing via
# COMBBLAS_TUNER_PROBE=1 runs IN-PROCESS before the timed section — on
# readback-poisoned chips keep probing in a separate process, which the
# A/B scenario below does by construction).
if os.environ.get("BENCH_PLAN_STORE") is not None:
    os.environ["COMBBLAS_PLAN_STORE"] = os.environ["BENCH_PLAN_STORE"]
# BENCH_PLAN_RECORD=1: write THIS run's measured (kernel, knobs, cost)
# back into the store (source="bench") — how operators seed a fleet
# store from forced-kernel sweeps.
PLAN_RECORD = os.environ.get("BENCH_PLAN_RECORD", "0") == "1"
# BENCH_TUNER_AB=1: the warm-vs-cold-process scenario — three children
# of this same script at the current BENCH_* settings: `heuristic`
# (store disabled), `cold` (fresh store + probing: pays the probe,
# writes the winner), `warm` (same store: hits the plan, ZERO probe
# runs). Prints one combined JSON line.
TUNER_AB = os.environ.get("BENCH_TUNER_AB", "0") == "1"
# BENCH_FIRST_TOUCH=1 (windowed): time the FIRST mult call — compile
# included — instead of the warm loop; with BENCH_PR>1 and
# BENCH_DISPATCH=fused|blocked this is the bounded-compile A/B of the
# building-block decomposition (ISSUE 8 acceptance).
FIRST_TOUCH = os.environ.get("BENCH_FIRST_TOUCH", "0") == "1"
_EFTAG = f"ef{EDGEFACTOR}" if EDGEFACTOR != 8 else ""
_GRIDTAG = f"_p{PR}x{PR}" if PR > 1 else ""
_RINGTAG = ("_ring" if PIPELINE else "_ringserial") if RING else ""


def tuner_ab():
    """BENCH_TUNER_AB=1: heuristic / cold-probe / warm-store children
    (one process each — the warm child is the 'fresh replica with a
    shipped plan store' of the acceptance gate).  Asserts in-JSON that
    the warm child routed from the store with zero probe runs."""
    import subprocess
    import tempfile

    store_dir = os.environ.get("BENCH_PLAN_STORE") or tempfile.mkdtemp(
        prefix="bench-plans-"
    )

    def child(tag, env_over):
        env = dict(os.environ)
        env.pop("BENCH_TUNER_AB", None)
        # the child re-applies BENCH_PLAN_STORE over COMBBLAS_PLAN_STORE
        # at import — strip it so the per-child store assignment below
        # is authoritative (else the heuristic child would route through
        # a pre-warmed store and the baseline would be a second warm run)
        env.pop("BENCH_PLAN_STORE", None)
        env.setdefault("BENCH_GOLDEN", "0")  # A/B times routing, not golden
        env["BENCH_KERNEL"] = "auto"
        env.update(env_over)
        p = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env, capture_output=True, text=True,
        )
        lines = [
            ln for ln in p.stdout.strip().splitlines()
            if ln.startswith("{")
        ]
        rec = json.loads(lines[-1]) if lines else {}
        rec.pop("obs_jsonl", None)
        rec["_tag"] = tag
        rec["_rc"] = p.returncode
        if p.returncode:
            rec["_stderr"] = p.stderr[-2000:]
        return rec

    heur = child("heuristic", {"COMBBLAS_PLAN_STORE": "0"})
    cold = child("cold", {
        "COMBBLAS_PLAN_STORE": store_dir, "COMBBLAS_TUNER_PROBE": "1",
    })
    warm = child("warm", {
        "COMBBLAS_PLAN_STORE": store_dir, "COMBBLAS_TUNER_PROBE": "1",
    })
    warm_ms = warm.get("ms_per_spgemm") or 0
    heur_ms = heur.get("ms_per_spgemm") or 0
    out = {
        "metric": f"spgemm_tuner_ab_{PATTERN}_scale{SCALE}{_EFTAG}"
                  f"{_GRIDTAG}_warm_ms",
        "value": warm_ms,
        "unit": "ms",
        "store_dir": store_dir,
        "heuristic": heur,
        "cold": cold,
        "warm": warm,
        # the acceptance gates, evaluated in-line:
        "warm_store_hit": warm.get("plan_source") == "store",
        "cold_probe_runs": (cold.get("tuner") or {}).get(
            "probe_runs", -1
        ),
        "warm_probe_runs": (warm.get("tuner") or {}).get(
            "probe_runs", -1
        ),
        "warm_vs_heuristic_speedup": (
            round(heur_ms / warm_ms, 3) if warm_ms and heur_ms else None
        ),
    }
    print(json.dumps(out), flush=True)


def main():
    if TUNER_AB:
        return tuner_ab()
    if PR > 1 and os.environ.get("JAX_PLATFORMS", "") != "tpu":
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={PR * PR}"
        )
    import jax

    if PR > 1 and os.environ.get("JAX_PLATFORMS", "") != "tpu":
        jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from combblas_tpu import PLUS_TIMES, obs
    from combblas_tpu.parallel.grid import Grid
    from combblas_tpu.parallel.spgemm import (
        summa_capacities_host,
        summa_spgemm,
        summa_stage_flops_host,
    )
    from combblas_tpu.parallel.spmat import SpParMat
    from combblas_tpu.utils.rmat import rmat_symmetric_coo_host

    # BENCH_OBS=1: per-process JSONL sidecar (the bench.py convention) —
    # carries the tier-router counters (spgemm.auto.tier,
    # spgemm.windowed.windows_skipped, spgemm.auto.mask_density)
    obs.enable_sidecar(f"spgemm-{KERNEL}")

    grid = Grid.make(PR, PR)
    n = 1 << SCALE
    if PATTERN == "banded":
        bw = max(n // 64, 1)
        ri = np.arange(n, dtype=np.int64)
        rows = np.concatenate(
            [ri for _ in range(-3, 4)]
        )
        cols = np.concatenate(
            [np.clip(ri + o * max(bw // 3, 1), 0, n - 1)
             for o in range(-3, 4)]
        )
    else:
        assert PATTERN == "rmat", PATTERN
        rows, cols = rmat_symmetric_coo_host(5, SCALE, EDGEFACTOR)
    key = rows * np.int64(n) + cols
    uniq = np.unique(key)
    ru, cu = uniq // n, uniq % n
    # Symbolic sizing on HOST from the COO (axon-safe: the device symbolic
    # pass would need a D2H readback before the timed launches, which
    # permanently degrades them — see bench.py module docstring).
    per_stage = summa_stage_flops_host(grid, ru, cu, ru, cu, n, n, n)
    # true scalar multiplies for the MFLOP/s numerator (per_stage above is
    # chunk-padded for capacity sizing)
    flops = int(
        summa_stage_flops_host(
            grid, ru, cu, ru, cu, n, n, n, padded=False
        ).sum()
    )
    fcap, ocap = summa_capacities_host(
        grid, ru, cu, ru, cu, n, n, n, per_stage=per_stage
    )
    # BENCH_KERNEL=auto: resolve the router's tier HERE (host counts
    # only — the axon D2H rule) and run that kernel below; the metric
    # name keeps the requested "auto" and the JSON carries the tier.
    # Round 10: resolution follows the tuner precedence — plan store >
    # env > probe (opt-in) > heuristic — via the SAME key builder the
    # library router uses, so a store warmed here routes spgemm_auto
    # and vice versa.
    A = SpParMat.from_global_coo(
        grid, ru, cu, np.ones(len(ru), np.float32), n, n
    )
    kernel = KERNEL
    tier = None
    backend = None
    plan_source = None
    plan_key = None
    store = None
    from combblas_tpu.tuner import store as tuner_store

    store = tuner_store.get_store()
    if KERNEL in ("auto", "windowed"):
        from combblas_tpu.parallel.spgemm import resolve_spgemm_backend

        # COMBBLAS_SPGEMM_BACKEND=dot forces the 2D MXU path (the TPU
        # stand-in run on this CPU image); default follows the platform
        backend = resolve_spgemm_backend()
    if store is not None:
        from combblas_tpu.parallel.spgemm import (
            resolve_spgemm_backend as _resolve_be,
        )

        # key under the RESOLVED backend even for forced kernels, so a
        # recorded plan and the library router agree on the key
        plan_key = tuner_store.plan_key_from_counts(
            "plus_times", n, n, n, len(ru), len(ru),
            backend or _resolve_be(), f"{grid.pr}x{grid.pc}",
        )
    plan_rec = None
    if KERNEL == "auto":
        # ONE walk of the store > env > probe > heuristic chain,
        # shared with spgemm3d_bench and vetted like the library
        # router (round-11 satellite: the inline copies skipped the
        # record vetting)
        from combblas_tpu.tuner.resolve import resolve_tier

        def _probe():
            from combblas_tpu.tuner.probe import probe_spgemm

            return probe_spgemm(
                PLUS_TIMES, A, A, backend=backend, store=store,
                key=plan_key,
                host_coo_a=(ru, cu, np.ones(len(ru), np.float32)),
            )

        def _heuristic():
            from combblas_tpu.parallel.spgemm import (
                choose_tier_from_counts,
            )

            lrA_, lcB_ = grid.local_rows(n), grid.local_cols(n)
            return choose_tier_from_counts(
                PLUS_TIMES, max(lrA_, lcB_), lrA_ * lcB_, grid.pr,
                float(flops), backend, k_dim=grid.local_rows(n),
                n_dim=lcB_,
            )

        tier, plan_source, plan_rec = resolve_tier(
            plan_key, op="spgemm",
            allowed=("mxu", "windowed", "scan", "esc"),
            heuristic=_heuristic, probe=_probe, store=store,
        )
        obs.count("spgemm.auto.tier", tier=tier, sr="plus_times")
        kernel = tier
    else:
        plan_source = "arg"  # BENCH_KERNEL forced this rung

    def provenance(**knobs):
        """plan provenance fields for the output JSON (satellite 2)."""
        p = {
            "plan_source": plan_source,
            "plan": {"tier": tier or kernel, "backend": backend,
                     **knobs},
        }
        if store is not None:
            p["tuner"] = store.stats()
        return p

    def record_plan(ms_per_spgemm, block_rows=None, block_cols=None):
        """BENCH_PLAN_RECORD=1: persist this run's measured plan —
        only if it BEATS the remembered cost (a forced-kernel seeding
        sweep must converge on the cheapest plan regardless of sweep
        order)."""
        if not PLAN_RECORD or store is None or plan_key is None:
            return
        if kernel not in ("mxu", "windowed", "scan", "esc"):
            return  # scanphased is a bench-only protocol, not a tier
        prev = store.peek(plan_key)
        if (
            prev is not None
            and prev.cost_s is not None
            and prev.cost_s <= ms_per_spgemm / 1e3
        ):
            return
        store.put(plan_key, tuner_store.PlanRecord(
            tier=kernel, block_rows=block_rows, block_cols=block_cols,
            ring=RING, pipeline=PIPELINE,
            # record the dispatch the cost was MEASURED under (None
            # would replay fused measurements as auto->blocked)
            dispatch=DISPATCH if kernel == "windowed" else None,
            cost_s=ms_per_spgemm / 1e3, source="bench",
        ))
    if kernel == "scan":
        # exact output structure on host: out_capacity = nnz(A^2) — the
        # scan variant's accumulator scales with the OUTPUT, which is what
        # lets scale 16 fit in HBM (the round-2 all-stages-live ESC
        # faulted the device there).
        if OCAP:
            ocap = int(OCAP)
        else:
            from scipy import sparse

            S = sparse.csr_matrix(
                (np.ones(len(ru), np.float32), (ru, cu)), shape=(n, n)
            )
            nnz_out = int((S @ S).nnz)
            ocap = 1 << int(np.ceil(np.log2(max(nnz_out, 2) * 1.05)))

    # All REPS chained inside ONE launch (per-launch dispatch through the
    # tunnel costs ~105ms-1.8s; see benchmarks/results/instrument_r2*).
    import dataclasses

    import jax.numpy as jnp
    from jax import lax

    if kernel == "windowed":
        # Round 6: the auto-tiered general sparse-output path. Sizing is
        # HOST-ONLY (axon D2H rule): the row-block symbolic pass + plan
        # come from the COO before any upload; "auto" additionally runs
        # the router's gate over the same host counts and records the
        # chosen tier through obs.
        from combblas_tpu.parallel.spgemm import (
            WINDOWED_CHUNK_W,
            _pad128,
            default_block_cols,
            default_block_rows,
            local_spgemm_windowed,
            panel_cap_from_bnnz,
            summa_rowblock_flops_host,
            summa_spgemm_windowed,
            summa_window_bnnz_host,
            summa_window_flops_host,
            windowed_plan,
            windowed_plan_2d,
        )

        lrA = grid.local_rows(n)
        lcB = grid.local_cols(n)
        # KERNEL=auto already resolved (and obs-counted) the tier above;
        # a direct BENCH_KERNEL=windowed request is its own tier.
        # Geometry precedence mirrors the library: bench knob > the
        # store record's measured shape > the kernel default.
        tier = tier or "windowed"
        rec_br = plan_rec.block_rows if plan_rec is not None else None
        rec_bc = plan_rec.block_cols if plan_rec is not None else None
        block_rows = BLOCK_ROWS or rec_br or default_block_rows(
            lrA, lcB
        )
        extra = {}
        if backend == "dot":
            # 2D B-column-windowed MXU form, sized host-only (axon D2H
            # rule): the 2D symbolic pass, the plan, and the panel slice
            # capacity all come from the COO before any upload.
            block_cols = BLOCK_COLS or rec_bc or default_block_cols(
                grid.local_rows(n), lcB
            )
            # one TRUE-counts pass only: the dot backend never consumes
            # flop caps (no chunked expansion), so the chunk_w-padded
            # einsum would be dead sizing work
            pt = summa_window_flops_host(
                grid, ru, cu, ru, cu, n, n, n, block_rows, block_cols,
                chunk_w=0,
            )
            flop_caps, out_caps, skip = windowed_plan_2d(
                None, pt, block_rows, block_cols, lrA, lcB
            )
            panel_cap = panel_cap_from_bnnz(
                summa_window_bnnz_host(grid, ru, cu, n, n, block_cols),
                len(ru),
            )
            nskip = sum(sum(row) for row in skip)
            obs.count("spgemm.windowed.col_windows_skipped", nskip)
            from combblas_tpu.parallel.spgemm import packed_windows_2d

            npk = len(packed_windows_2d(skip))
            ntot = sum(len(row) for row in skip)
            obs.count("spgemm.windowed.windows_packed", npk)
            obs.gauge(
                "spgemm.windowed.pack_ratio", npk / ntot if ntot else 0.0
            )
            obs.gauge(
                "spgemm.windowed.col_windows", len(skip[0]) if skip else 0
            )
            obs.gauge(
                "spgemm.windowed.panel_cells",
                _pad128(grid.local_rows(n)) * _pad128(block_cols),
            )
            obs.gauge("spgemm.windowed.blocks", len(skip))
            extra = {
                "backend": "dot",
                "mode": DOT_MODE,
                "block_cols": block_cols,
                "col_windows": len(skip[0]) if skip else 0,
                "col_windows_skipped": int(nskip),
                "windows_packed": int(npk),
                "windows_total": int(ntot),
                "pack_ratio": round(npk / ntot, 4) if ntot else 0.0,
                "panel_cap": int(panel_cap),
                "panel_cells": int(
                    _pad128(grid.local_rows(n)) * _pad128(block_cols)
                ),
            }

            def mult(a):
                if grid.size == 1:
                    return local_spgemm_windowed(
                        PLUS_TIMES, a, a, block_rows=block_rows,
                        flop_caps=flop_caps, out_caps=out_caps,
                        skip=skip, backend="dot", block_cols=block_cols,
                        panel_cap=panel_cap, mode=DOT_MODE,
                    )
                return summa_spgemm_windowed(
                    PLUS_TIMES, a, a, block_rows=block_rows,
                    flop_caps=flop_caps, out_caps=out_caps, skip=skip,
                    backend="dot", mode=DOT_MODE,
                    chunk_w=WINDOWED_CHUNK_W, block_cols=block_cols,
                    panel_cap=panel_cap, ring=RING, pipeline=PIPELINE,
                )
        else:
            pb = summa_rowblock_flops_host(
                grid, ru, cu, ru, cu, n, n, n, block_rows,
                chunk_w=WINDOWED_CHUNK_W,
            )
            pt = summa_rowblock_flops_host(
                grid, ru, cu, ru, cu, n, n, n, block_rows, chunk_w=0
            )
            flop_caps, out_caps, skip = windowed_plan(
                pb, pt, block_rows, lrA, lcB
            )
            obs.count("spgemm.windowed.windows_skipped", sum(skip))
            from combblas_tpu.parallel.spgemm import packed_windows

            npk = len(packed_windows(skip))
            obs.count("spgemm.windowed.windows_packed", npk)
            obs.gauge(
                "spgemm.windowed.pack_ratio",
                npk / len(skip) if skip else 0.0,
            )
            obs.gauge("spgemm.windowed.blocks", len(skip))
            # same quantity as the library emitter (parallel/spgemm.py:
            # spgemm_windowed): raw symbolic output bound over dense cells
            obs.gauge(
                "spgemm.auto.mask_density",
                float(np.asarray(pt).sum(axis=1).max(axis=(-1, -2)).sum())
                / max(lrA * lcB, 1),
            )
            extra = {
                "windows_packed": int(npk),
                "windows_total": len(skip),
                "pack_ratio": (
                    round(npk / len(skip), 4) if skip else 0.0
                ),
            }

            if DISPATCH == "blocked" and grid.size > 1:
                # per-block programs share compiles when caps match:
                # pow2-round so most blocks hit one executable
                rnd = lambda x: 1 << (max(int(x), 1) - 1).bit_length()
                flop_caps = tuple(rnd(fcp) for fcp in flop_caps)
                out_caps = tuple(rnd(ocp) for ocp in out_caps)
                extra["dispatch"] = "blocked"

            def mult(a):
                # grid 1x1 here: the per-block-program fast path (the
                # fused shard_map graph measures >2x slower on XLA:CPU)
                if grid.size == 1:
                    return local_spgemm_windowed(
                        PLUS_TIMES, a, a, block_rows=block_rows,
                        flop_caps=flop_caps, out_caps=out_caps, skip=skip,
                        chunk_w=WINDOWED_CHUNK_W,
                    )
                if DISPATCH == "blocked":
                    from combblas_tpu.parallel.spgemm import (
                        summa_spgemm_windowed_blocked,
                    )

                    return summa_spgemm_windowed_blocked(
                        PLUS_TIMES, a, a, block_rows=block_rows,
                        flop_caps=flop_caps, out_caps=out_caps,
                        skip=skip, chunk_w=WINDOWED_CHUNK_W,
                    )
                return summa_spgemm_windowed(
                    PLUS_TIMES, a, a, block_rows=block_rows,
                    flop_caps=flop_caps, out_caps=out_caps, skip=skip,
                    backend="scatter", chunk_w=WINDOWED_CHUNK_W,
                    ring=RING, pipeline=PIPELINE,
                )

        if FIRST_TOUCH:
            # FIRST call, compile included: the bounded first-touch
            # gate of the building-block decomposition (run once per
            # process with BENCH_DISPATCH=fused, once with =blocked)
            t0 = time.perf_counter()
            C, ov = mult(A)
            jax.block_until_ready(C.vals)
            t_first = time.perf_counter() - t0
            out = {
                "metric": (
                    f"spgemm_AxA_{PATTERN}_scale{SCALE}{_EFTAG}"
                    f"{_GRIDTAG}_windowed_firsttouch_{DISPATCH}_s"
                ),
                "value": round(t_first, 3),
                "unit": "s",
                "dispatch": DISPATCH,
                "block_rows": block_rows,
                "blocks": len(skip),
                "out_nnz": int(jax.device_get(C.getnnz())),
                "grid": f"{grid.pr}x{grid.pc}",
                **provenance(block_rows=block_rows),
            }
            if obs.ENABLED:
                out["obs_jsonl"] = obs.dump_jsonl()
            print(json.dumps(out))
            return
        C, ov = mult(A)  # warmup/compile
        jax.block_until_ready(C.vals)
        time.sleep(3)
        t0 = time.perf_counter()
        for _ in range(REPS):
            C, ov = mult(A)
        nnz_v = int(jax.device_get(C.getnnz()))  # barrier
        dt = time.perf_counter() - t0
        record_plan(
            dt / REPS * 1e3, block_rows=block_rows,
            block_cols=(
                extra.get("block_cols") if backend == "dot" else None
            ),
        )
        out = {
            "metric": (
                f"spgemm_AxA_{PATTERN}_scale{SCALE}{_EFTAG}{_GRIDTAG}"
                f"_{KERNEL}{'dot' if backend == 'dot' else ''}"
                f"{_RINGTAG}_MFLOPs"
            ),
            "value": round(flops * 2 * REPS / dt / 1e6, 2),
            "unit": "MFLOP/s",
            "flops": int(flops),
            "ms_per_spgemm": round(dt / REPS * 1e3, 2),
            "out_nnz": nnz_v,
            "overflow": int(jax.device_get(ov)),
            "tier": tier,
            "grid": f"{grid.pr}x{grid.pc}",
            "ring": RING,
            "pipeline": PIPELINE,
            "block_rows": block_rows,
            "blocks": len(skip),
            "windows_skipped": (
                int(sum(skip)) if backend != "dot"
                else extra["col_windows_skipped"]
            ),
            **extra,
            **provenance(block_rows=block_rows),
        }
        if GOLDEN:
            # EXACT agreement with the A² golden: 0/1 adjacency counts
            # are integers < 2^24, so the comparison is bit-exact — the
            # same golden the ESC path reproduces (MultTest role).
            from scipy import sparse

            tr, tc_, tv = (
                np.asarray(jax.device_get(x))
                for x in (C.rows, C.cols, C.vals)
            )
            lr_, lc_ = C.local_rows, C.local_cols
            gr_, gc_, gv_ = [], [], []
            for i in range(grid.pr):  # stitch every tile (PR > 1)
                for j in range(grid.pc):
                    live = tr[i, j] < lr_
                    gr_.append(tr[i, j][live].astype(np.int64) + i * lr_)
                    gc_.append(tc_[i, j][live].astype(np.int64) + j * lc_)
                    gv_.append(tv[i, j][live])
            got = sparse.csr_matrix(
                (np.concatenate(gv_),
                 (np.concatenate(gr_), np.concatenate(gc_))),
                shape=(n, n),
            )
            got.sum_duplicates()
            S = sparse.csr_matrix(
                (np.ones(len(ru), np.float32), (ru, cu)), shape=(n, n)
            )
            P = S @ S
            P.sort_indices()
            got.sort_indices()
            out["golden_nnz"] = int(P.nnz)
            out["golden_nnz_match"] = bool(got.nnz == P.nnz)
            out["golden_exact"] = bool(
                got.nnz == P.nnz
                and np.array_equal(got.indptr, P.indptr)
                and np.array_equal(got.indices, P.indices)
                and np.array_equal(got.data, P.data)
            )
        if obs.ENABLED:
            out["obs_jsonl"] = obs.dump_jsonl()
        print(json.dumps(out))
        return
    if kernel == "scanphased":
        # MemEfficientSpGEMM pattern at benchmark level: B's columns split
        # into flop-BALANCED phases (host symbolic), every phase runs the
        # output-bounded scan kernel with ONE shared capacity set (single
        # compile), all sizing on host before any launch (axon D2H rule).
        # This is what fits scale 16 in HBM: the single-stage expansion
        # (~420M slots x3 arrays, doubled by the sort) exhausts the 16G
        # device; per-phase working sets are PHASES-fold smaller.
        from scipy import sparse as _sp

        from combblas_tpu.parallel.spgemm import summa_spgemm_scan

        deg = np.bincount(ru, minlength=n)
        colflops = deg[cu]  # flops contributed by each entry (B-row walk)
        # order entries by column; split columns at equal-flop boundaries
        order = np.argsort(cu, kind="stable")
        cum = np.cumsum(colflops[order])
        co = cu[order]
        bounds = [0]
        for ph in range(1, PHASES):
            t = cum[-1] * ph / PHASES
            b = min(int(np.searchsorted(cum, t)), len(order) - 1)
            # snap DOWN to the column boundary: a split column would be
            # produced by two phases and double-count its outputs
            bounds.append(int(np.searchsorted(co, co[b], side="left")))
        bounds.append(len(order))
        Bs = []
        fcapp = ocapp = 1
        S = _sp.csr_matrix(
            (np.ones(len(ru), np.float32), (ru, cu)), shape=(n, n)
        )
        # ONE host product: every phase output is a column range of it
        # (phases are column-disjoint), so per-phase out-nnz reads off the
        # CSC indptr instead of PHASES more host SpGEMMs
        Pcsc = (S @ S).tocsc()
        col_nnz = np.diff(Pcsc.indptr)
        for ph in range(PHASES):
            sel = order[bounds[ph]:bounds[ph + 1]]
            rp, cp = ru[sel], cu[sel]
            per = summa_stage_flops_host(grid, ru, cu, rp, cp, n, n, n)
            fcapp = max(fcapp, int(per.max() * 1.05) + 1)
            if len(cp):
                lo, hi = int(cp.min()), int(cp.max()) + 1
                ph_nnz = int(col_nnz[lo:hi].sum())
                ocapp = max(ocapp, int(ph_nnz * 1.05) + 1)
            Bs.append(
                SpParMat.from_global_coo(
                    grid, rp, cp, np.ones(len(rp), np.float32), n, n
                )
            )
        rnd = lambda x: 1 << (x - 1).bit_length()
        fcapp, ocapp = rnd(fcapp), rnd(ocapp)
        # equalize slot capacities so ALL phases share one compiled program
        cap_b = rnd(max(int(b.capacity) for b in Bs))
        Bs = [b.with_capacity(cap_b) for b in Bs]
        A = A.shrink_to_fit()

        def phase_mult(a, b):
            return summa_spgemm_scan(
                PLUS_TIMES, a, b, flop_capacity=fcapp, out_capacity=ocapp
            )

        outs = [phase_mult(A, b) for b in Bs]  # warmup/compile (cached)
        jax.block_until_ready(outs[-1][0].vals)
        time.sleep(3)
        t0 = time.perf_counter()
        nnz_total = jnp.int32(0)
        ov_total = jnp.int32(0)
        for _ in range(REPS):
            for b in Bs:
                Cp, ov = phase_mult(A, b)
                nnz_total = nnz_total + Cp.getnnz()
                ov_total = jnp.maximum(ov_total, ov)
        nnz_v = int(jax.device_get(nnz_total)) // REPS  # barrier
        dt = time.perf_counter() - t0
        print(
            json.dumps(
                {
                    "metric": f"spgemm_AxA_{PATTERN}_scale{SCALE}{_EFTAG}_scanphased{PHASES}_MFLOPs",
                    "value": round(flops * 2 * REPS / dt / 1e6, 2),
                    "unit": "MFLOP/s",
                    "flops": int(flops),
                    "ms_per_spgemm": round(dt / REPS * 1e3, 2),
                    "out_nnz": nnz_v,
                    "overflow": int(jax.device_get(ov_total)),
                }
            )
        )
        return
    if kernel == "scan":
        from combblas_tpu.parallel.spgemm import summa_spgemm_scan

        overflow_dev = None

        @jax.jit
        def chain(mat):
            def body(_, carry):
                a = dataclasses.replace(mat, vals=mat.vals + carry * 0)
                C, ov = summa_spgemm_scan(
                    PLUS_TIMES, a, a,
                    flop_capacity=fcap, out_capacity=ocap,
                )
                return C.vals[0, 0, 0] * 0 + ov.astype(jnp.float32) * 0

            return lax.fori_loop(0, REPS, body, jnp.float32(0))

        out = chain(A)  # warmup/compile
        jax.block_until_ready(out)
        time.sleep(3)
        t0 = time.perf_counter()
        out = chain(A)
        _ = float(jax.device_get(out))  # barrier
        dt = time.perf_counter() - t0
        C, overflow_dev = summa_spgemm_scan(
            PLUS_TIMES, A, A, flop_capacity=fcap, out_capacity=ocap
        )
    elif kernel == "mxu":
        from combblas_tpu.parallel.spgemm import summa_spgemm_mxu

        # round 4: bf16 stage products (13.3 TFLOP/s, exact for the 0/1
        # inputs here) + the windowed output-driven extraction; BENCH_MXU_MODE
        # picks f32/bf16/bf16x3 (see parallel/spgemm._mxu_dot)
        mxu_mode = os.environ.get("BENCH_MXU_MODE", "bf16")
        mxu_ocap = int(OCAP) if OCAP else ocap
        mxu_overflow = None

        def mult(a):
            nonlocal mxu_overflow
            C, mxu_overflow = summa_spgemm_mxu(
                PLUS_TIMES, a, a, out_capacity=mxu_ocap, mode=mxu_mode
            )
            return C

        # The dense accumulators are GBs; a fori_loop chain double-buffers
        # them past HBM (device fault). Kernel time (seconds) dwarfs the
        # per-launch dispatch, so separate launches time honestly here.
        C = mult(A)  # warmup/compile
        jax.block_until_ready(C.vals)
        time.sleep(3)
        t0 = time.perf_counter()
        for _ in range(REPS):
            C = mult(A)
        _ = float(jax.device_get(C.vals[0, 0, 0]))  # barrier
        dt = time.perf_counter() - t0
    else:

        def mult(a):
            # BENCH_RING=1: the carousel (neighbor-rotation) schedule —
            # the pre-round-9 serial carousel is BENCH_KERNEL=esc with
            # ring on the old commit; this one is now stage-pipelined
            return summa_spgemm(
                PLUS_TIMES, a, a, flop_capacity=fcap, out_capacity=ocap,
                ring=RING,
            )

        @jax.jit
        def chain(mat):
            def body(_, carry):
                a = dataclasses.replace(mat, vals=mat.vals + carry * 0)
                C = mult(a)
                return C.vals[0, 0, 0] * 0  # serializing dependence

            return lax.fori_loop(0, REPS, body, jnp.float32(0))

        out = chain(A)  # warmup/compile
        jax.block_until_ready(out)
        time.sleep(3)
        t0 = time.perf_counter()
        out = chain(A)
        _ = float(jax.device_get(out))  # barrier
        dt = time.perf_counter() - t0
        C = mult(A)
    record_plan(dt / REPS * 1e3)
    out = {
        "metric": f"spgemm_AxA_{PATTERN}_scale{SCALE}{_EFTAG}{_GRIDTAG}_{KERNEL}{_RINGTAG}_MFLOPs",
        "value": round(flops * 2 * REPS / dt / 1e6, 2),
        "unit": "MFLOP/s",
        "flops": int(flops),
        "ms_per_spgemm": round(dt / REPS * 1e3, 2),
        "out_nnz": int(jax.device_get(C.getnnz())),
        # nonzero = capacity truncated the product; numbers invalid
        "overflow": (
            int(jax.device_get(mxu_overflow))
            if kernel == "mxu"
            else int(jax.device_get(overflow_dev))
            if kernel == "scan"
            else 0
        ),
        **provenance(),
    }
    from combblas_tpu import obs as _obs

    if _obs.ENABLED:
        out["obs_jsonl"] = _obs.dump_jsonl()
    print(json.dumps(out))


if __name__ == "__main__":
    main()
