"""SUMMA SpGEMM microbenchmark (≈ ReleaseTests/MultTiming.cpp).

A·A on an R-MAT matrix with pre-sized capacities so the timed section is
the compiled SUMMA only (axon-safe protocol: barrier readback closes the
timed window). Prints one JSON line.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

SCALE = int(os.environ.get("BENCH_SCALE", "14"))
REPS = int(os.environ.get("BENCH_REPS", "3"))
KERNEL = os.environ.get("BENCH_KERNEL", "esc")  # esc | mxu
OCAP = os.environ.get("BENCH_OCAP")  # override out_capacity (mxu sparsify
# cost scales with it: searchsorted queries per slot)


def main():
    import jax
    import numpy as np

    from combblas_tpu import PLUS_TIMES
    from combblas_tpu.parallel.grid import Grid
    from combblas_tpu.parallel.spgemm import (
        summa_capacities_host,
        summa_spgemm,
        summa_stage_flops_host,
    )
    from combblas_tpu.parallel.spmat import SpParMat
    from combblas_tpu.utils.rmat import rmat_symmetric_coo_host

    grid = Grid.make(1, 1)
    n = 1 << SCALE
    rows, cols = rmat_symmetric_coo_host(5, SCALE, 8)
    key = rows * np.int64(n) + cols
    uniq = np.unique(key)
    ru, cu = uniq // n, uniq % n
    # Symbolic sizing on HOST from the COO (axon-safe: the device symbolic
    # pass would need a D2H readback before the timed launches, which
    # permanently degrades them — see bench.py module docstring).
    per_stage = summa_stage_flops_host(grid, ru, cu, ru, cu, n, n, n)
    flops = int(per_stage.sum())
    fcap, ocap = summa_capacities_host(
        grid, ru, cu, ru, cu, n, n, n, per_stage=per_stage
    )
    A = SpParMat.from_global_coo(
        grid, ru, cu, np.ones(len(ru), np.float32), n, n
    )

    # All REPS chained inside ONE launch (per-launch dispatch through the
    # tunnel costs ~105ms-1.8s; see benchmarks/results/instrument_r2*).
    import dataclasses

    import jax.numpy as jnp
    from jax import lax

    if KERNEL == "mxu":
        from combblas_tpu.parallel.spgemm import summa_spgemm_mxu

        mxu_ocap = int(OCAP) if OCAP else ocap
        mxu_overflow = None

        def mult(a):
            nonlocal mxu_overflow
            C, mxu_overflow = summa_spgemm_mxu(
                PLUS_TIMES, a, a, out_capacity=mxu_ocap
            )
            return C

        # The dense accumulators are GBs; a fori_loop chain double-buffers
        # them past HBM (device fault). Kernel time (seconds) dwarfs the
        # per-launch dispatch, so separate launches time honestly here.
        C = mult(A)  # warmup/compile
        jax.block_until_ready(C.vals)
        time.sleep(3)
        t0 = time.perf_counter()
        for _ in range(REPS):
            C = mult(A)
        _ = float(jax.device_get(C.vals[0, 0, 0]))  # barrier
        dt = time.perf_counter() - t0
    else:

        def mult(a):
            return summa_spgemm(
                PLUS_TIMES, a, a, flop_capacity=fcap, out_capacity=ocap
            )

        @jax.jit
        def chain(mat):
            def body(_, carry):
                a = dataclasses.replace(mat, vals=mat.vals + carry * 0)
                C = mult(a)
                return C.vals[0, 0, 0] * 0  # serializing dependence

            return lax.fori_loop(0, REPS, body, jnp.float32(0))

        out = chain(A)  # warmup/compile
        jax.block_until_ready(out)
        time.sleep(3)
        t0 = time.perf_counter()
        out = chain(A)
        _ = float(jax.device_get(out))  # barrier
        dt = time.perf_counter() - t0
        C = mult(A)
    print(
        json.dumps(
            {
                "metric": f"spgemm_AxA_rmat_scale{SCALE}_{KERNEL}_MFLOPs",
                "value": round(flops * 2 * REPS / dt / 1e6, 2),
                "unit": "MFLOP/s",
                "flops": int(flops),
                "ms_per_spgemm": round(dt / REPS * 1e3, 2),
                "out_nnz": int(jax.device_get(C.getnnz())),
                # nonzero = BENCH_OCAP truncated the product; numbers invalid
                "overflow": (
                    int(jax.device_get(mxu_overflow))
                    if KERNEL == "mxu" else 0
                ),
            }
        )
    )


if __name__ == "__main__":
    main()
