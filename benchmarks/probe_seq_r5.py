"""Round-5 probe: per-kernel cost of the bfs_single level kernels at
scale 20 on the real chip.

One MODE per process (the first timed readback poisons later launches):
  MODE=dense   — the W-free int32 dense gather sweep
  MODE=sparse  — the budgeted sparse column walk at PROBE_FCAP/PROBE_ECAP
  MODE=cumsum  — just the frontier-compaction prefix ops
  MODE=whole   — bfs_single end-to-end (levels readback only)

Each kernel runs PROBE_REPS times inside ONE lax.fori_loop launch with a
data dependency between iterations, so per-iteration cost = dt/REPS
without per-launch dispatch noise.  Usage:
  BENCH_GRAPH_NPZ=/tmp/g20.npz MODE=dense python benchmarks/probe_seq_r5.py
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
if os.environ.get("PROBE_NOCACHE") != "1":
    from combblas_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache()

from combblas_tpu.parallel.grid import Grid
from combblas_tpu.ops.segment import expand_ranges

MODE = os.environ.get("MODE", "dense")
REPS = int(os.environ.get("PROBE_REPS", "10"))
FCAP = int(os.environ.get("PROBE_FCAP", "131072"))
ECAP = int(os.environ.get("PROBE_ECAP", "2097152"))
FRONTIER = int(os.environ.get("PROBE_FRONTIER", "65536"))
DRAIN = float(os.environ.get("PROBE_DRAIN_S", "10"))
SCALE = int(os.environ.get("BENCH_SCALE", "20"))


def main():
    grid = Grid.make(1, 1)
    n = 1 << SCALE
    data = np.load(os.environ["BENCH_GRAPH_NPZ"])
    from bench import _load_structures

    if os.environ.get("PROBE_LADDER"):
        from combblas_tpu.parallel.ellmat import EllParMat

        E = EllParMat.from_host_coo(
            grid, data["rows"], data["cols"],
            np.zeros(len(data["rows"]), np.int8), n, n,
            ladder=os.environ["PROBE_LADDER"],
        )
        from combblas_tpu.parallel.ellmat import upload_csc_companion

        csc = upload_csc_companion(
            grid, data["csc_indptr"], data["csc_rowidx"]
        )
    else:
        E, csc = _load_structures(grid, data, n)
    lr = grid.local_rows(n)
    lc = grid.local_cols(n)
    nb = len(E.buckets)
    rng = np.random.default_rng(3)
    fr = np.zeros(lc, np.int32) - 1
    act_cols = rng.choice(lc, size=FRONTIER, replace=False)
    fr[act_cols] = act_cols
    x0 = jax.device_put(jnp.asarray(fr))  # [lc] frontier candidates
    csc_indptr, csc_rowidx = csc

    buckets = [tuple(a[0, 0] for a in b) for b in E.buckets]
    indptr = csc_indptr[0, 0]
    rowid = csc_rowidx[0, 0]

    def dense_step(x):
        xpad = jnp.concatenate([x, jnp.full((1,), -1, jnp.int32)])
        y = jnp.full((lr,), -1, jnp.int32)
        for bc, _bv, br in buckets:
            g = xpad[jnp.minimum(bc, lc)]
            yb = jnp.max(g, axis=1)
            y = y.at[br].max(yb, mode="drop")
        return y

    def compact(x):
        act = x >= 0
        pos = jnp.cumsum(act.astype(jnp.int32)) - 1
        scatter = jnp.where(act, pos, FCAP)
        fcols = (
            jnp.full((FCAP,), lc, jnp.int32)
            .at[scatter]
            .set(jnp.arange(lc, dtype=jnp.int32), mode="drop")
        )
        return fcols

    def sparse_step(x):
        fcols = compact(x)
        ipt_pad = jnp.concatenate([indptr, indptr[-1:]])
        deg = jnp.where(fcols < lc, ipt_pad[fcols + 1] - ipt_pad[fcols], 0)
        owner, offset, valid, _ = expand_ranges(deg, ECAP)
        src_col = fcols[owner]
        slot = jnp.minimum(
            ipt_pad[jnp.minimum(src_col, lc)] + offset, rowid.shape[0] - 1
        )
        tgt_row = jnp.where(valid, rowid[slot], lr)
        xpad = jnp.concatenate([x, jnp.full((1,), -1, jnp.int32)])
        contrib = jnp.where(valid, xpad[jnp.minimum(src_col, lc)], -1)
        y = jnp.full((lr,), -1, jnp.int32).at[tgt_row].max(
            contrib, mode="drop"
        )
        return y

    def cumsum_only(x):
        return jnp.cumsum((x >= 0).astype(jnp.int32)) - 1

    def scatter_only(x):
        act = x >= 0
        pos = jnp.arange(lc, dtype=jnp.int32)  # fake positions, no cumsum
        scatter = jnp.where(act, pos, FCAP)
        return (
            jnp.full((FCAP,), lc, jnp.int32)
            .at[jnp.minimum(scatter, FCAP)]
            .set(jnp.arange(lc, dtype=jnp.int32), mode="drop")
        )

    def stats_only(x):
        act = x >= 0
        coldeg = indptr[1:] - indptr[:-1]
        return (jnp.sum(act.astype(jnp.int32))
                + jnp.sum(jnp.where(act, coldeg, 0)))[None]

    if MODE in ("dense", "sparse", "cumsum", "cumsumonly", "scatteronly",
                "stats"):
        fn = {"dense": dense_step, "sparse": sparse_step,
              "cumsum": compact, "cumsumonly": cumsum_only,
              "scatteronly": scatter_only, "stats": stats_only}[MODE]

        @jax.jit
        def reps(x):
            # anti-DCE dependency: the next iteration's frontier depends
            # on min(y) via a predicate XLA cannot prove false (y values
            # are >= -1 by construction, but that's runtime knowledge),
            # so every rep's full kernel must execute; at runtime x is
            # unchanged, keeping the access pattern identical per rep.
            def body(i, x):
                y = fn(x)
                return jnp.where(jnp.min(y) == -5, x * 0 + i, x)

            return jax.lax.fori_loop(0, REPS, body, x)

        out = reps(x0)
        jax.block_until_ready(out)
        time.sleep(DRAIN)
        t0 = time.perf_counter()
        out = reps(x0)
        v = int(np.asarray(jax.device_get(out))[0])
        dt = time.perf_counter() - t0
        print(json.dumps({
            "mode": MODE, "reps": REPS, "dt_s": round(dt, 3),
            "s_per_step": round(dt / REPS, 4), "sink": v,
            "fcap": FCAP, "ecap": ECAP, "frontier": FRONTIER,
        }), flush=True)
    elif MODE in ("v1", "v2", "v3"):
        # ablation ladder for the in-loop overhead: v1 = shard_map'd
        # dense level in a 6-iteration loop; v2 = + DistVec realign;
        # v3 = + parents/levels updates and the any(new) cond (i.e.
        # bfs_single minus stats+switch).
        from jax.sharding import PartitionSpec as P
        from combblas_tpu.parallel.grid import COL_AXIS, ROW_AXIS
        from combblas_tpu.parallel.spmat import TILE_SPEC
        from combblas_tpu.parallel.vec import DistVec

        flat_args = [a for b in E.buckets for a in b]
        row_gids = jnp.arange(lr, dtype=jnp.int32)[None]

        def dense_level_sm(x, undisc):
            def body(xblk, ublk, *flat):
                bks = [
                    tuple(a[0, 0] for a in flat[3 * i : 3 * i + 3])
                    for i in range(nb)
                ]
                xv = xblk[0]
                xpad = jnp.concatenate([xv, jnp.full((1,), -1, jnp.int32)])
                y = jnp.full((lr,), -1, jnp.int32)
                for bc, _bv, br in bks:
                    g = xpad[jnp.minimum(bc, lc)]
                    y = y.at[br].max(jnp.max(g, axis=1), mode="drop")
                y = jnp.where(ublk[0], y, -1)
                return jax.lax.pmax(y, COL_AXIS)[None]

            return jax.shard_map(
                body, mesh=grid.mesh,
                in_specs=(P(COL_AXIS), P(ROW_AXIS)) + (TILE_SPEC,) * (3 * nb),
                out_specs=P(ROW_AXIS), check_vma=False,
            )(x, undisc, *flat_args)

        root = np.int32(data["roots"][0])
        x_init = jnp.where(row_gids == root, jnp.int32(root), -1)

        @jax.jit
        def run(x0):
            if MODE == "v1":
                def body(i, x):
                    y = dense_level_sm(x, x == x)  # undisc all-true
                    return jnp.where(y >= 0, row_gids, -1)

                return jax.lax.fori_loop(0, 6, body, x0)
            if MODE == "v2":
                def body(i, x):
                    y = dense_level_sm(x, x == x)
                    fr = DistVec(
                        blocks=jnp.where(y >= 0, row_gids, -1),
                        length=n, align="row", grid=grid,
                    )
                    return fr.realign("col").blocks

                return jax.lax.fori_loop(0, 6, body, x0)
            # v3: full step minus stats+switch
            parents0 = jnp.where(row_gids == root, jnp.int32(root), -1)
            levels0 = jnp.where(row_gids == root, 0, -1).astype(jnp.int32)

            def cond(st):
                return st[3] & (st[2] < 6)

            def body(st):
                parents, levels, level, _, x = st
                undisc = parents < 0
                y = dense_level_sm(x, undisc)
                new = (y >= 0) & undisc
                parents = jnp.where(new, y, parents)
                levels = jnp.where(new, level + 1, levels)
                fr = DistVec(
                    blocks=jnp.where(new, row_gids, -1),
                    length=n, align="row", grid=grid,
                )
                return (parents, levels, level + 1, jnp.any(new),
                        fr.realign("col").blocks)

            st = jax.lax.while_loop(
                cond, body,
                (parents0, levels0, jnp.int32(0), jnp.bool_(True), x0),
            )
            return st[0]

        out = run(x_init)
        jax.block_until_ready(out)
        time.sleep(DRAIN)
        t0 = time.perf_counter()
        out = run(x_init)
        v = int(np.asarray(jax.device_get(out))[0, 0])
        dt = time.perf_counter() - t0
        print(json.dumps({
            "mode": MODE, "dt_s": round(dt, 3),
            "s_per_level": round(dt / 6, 3), "sink": v,
        }), flush=True)
    elif MODE in ("v4", "v5", "v6"):
        # continue the bisection from v3 toward bfs_single:
        # v4 = v3 + traced source + col_gids-style x0 init
        # v5 = v4 + while bound n (instead of 6) + niter carried
        # v6 = v5 + coldeg shard_map before the loop (csc operands live)
        from jax.sharding import PartitionSpec as P
        from combblas_tpu.parallel.grid import COL_AXIS, ROW_AXIS
        from combblas_tpu.parallel.spmat import TILE_SPEC
        from combblas_tpu.parallel.vec import DistVec

        flat_args = [a for b in E.buckets for a in b]
        row_gids = jnp.arange(lr, dtype=jnp.int32)[None]
        col_gids = jnp.arange(lc, dtype=jnp.int32)[None]

        def dense_level_sm(x, undisc):
            def body(xblk, ublk, *flat):
                bks = [
                    tuple(a[0, 0] for a in flat[3 * i : 3 * i + 3])
                    for i in range(nb)
                ]
                xv = xblk[0]
                xpad = jnp.concatenate([xv, jnp.full((1,), -1, jnp.int32)])
                y = jnp.full((lr,), -1, jnp.int32)
                for bc, _bv, br in bks:
                    g = xpad[jnp.minimum(bc, lc)]
                    y = y.at[br].max(jnp.max(g, axis=1), mode="drop")
                y = jnp.where(ublk[0], y, -1)
                return jax.lax.pmax(y, COL_AXIS)[None]

            return jax.shard_map(
                body, mesh=grid.mesh,
                in_specs=(P(COL_AXIS), P(ROW_AXIS)) + (TILE_SPEC,) * (3 * nb),
                out_specs=P(ROW_AXIS), check_vma=False,
            )(x, undisc, *flat_args)

        bound = 6 if MODE == "v4" else n

        @jax.jit
        def run(source):
            parents0 = jnp.where(row_gids == source, source, -1)
            levels0 = jnp.where(row_gids == source, 0, -1).astype(jnp.int32)
            x0 = jnp.where(col_gids == source, source, -1)
            if MODE == "v6":
                def colde_body(ipt):
                    d = ipt[0, 0][1:] - ipt[0, 0][:-1]
                    return jax.lax.psum(d, ROW_AXIS)[None]

                coldeg = jax.shard_map(
                    colde_body, mesh=grid.mesh,
                    in_specs=(P(ROW_AXIS, COL_AXIS),),
                    out_specs=P(COL_AXIS), check_vma=False,
                )(csc_indptr)
                parents0 = parents0 + jnp.min(coldeg) * 0

            def cond(st):
                return st[3] & (st[2] < bound)

            def body(st):
                parents, levels, level, _, x = st
                undisc = parents < 0
                y = dense_level_sm(x, undisc)
                new = (y >= 0) & undisc
                parents = jnp.where(new, y, parents)
                levels = jnp.where(new, level + 1, levels)
                fr = DistVec(
                    blocks=jnp.where(new, row_gids, -1),
                    length=n, align="row", grid=grid,
                )
                return (parents, levels, level + 1, jnp.any(new),
                        fr.realign("col").blocks)

            st = jax.lax.while_loop(
                cond, body,
                (parents0, levels0, jnp.int32(0), jnp.bool_(True), x0),
            )
            return st[0], st[1], st[2]

        src = np.int32(data["roots"][0])
        out = run(src)
        jax.block_until_ready(out[0])
        time.sleep(DRAIN)
        t0 = time.perf_counter()
        out = run(src)
        it = int(np.asarray(jax.device_get(out[2])))
        dt = time.perf_counter() - t0
        print(json.dumps({
            "mode": MODE, "dt_s": round(dt, 3), "levels": it,
            "s_per_level": round(dt / max(it, 1), 3),
        }), flush=True)
    elif MODE == "v7":
        # v7 = v5 (fast closure version) but with every bucket array
        # passed as a JIT ARGUMENT (the way bfs_single receives E) —
        # isolates operand-passing vs closure-constant embedding.
        from jax.sharding import PartitionSpec as P
        from combblas_tpu.parallel.grid import COL_AXIS, ROW_AXIS
        from combblas_tpu.parallel.spmat import TILE_SPEC
        from combblas_tpu.parallel.vec import DistVec

        flat_args = [a for b in E.buckets for a in b]
        row_gids = jnp.arange(lr, dtype=jnp.int32)[None]
        col_gids = jnp.arange(lc, dtype=jnp.int32)[None]

        @jax.jit
        def run(source, *fa):
            def dense_level_sm(x, undisc):
                def body(xblk, ublk, *flat):
                    bks = [
                        tuple(a[0, 0] for a in flat[3 * i : 3 * i + 3])
                        for i in range(nb)
                    ]
                    xv = xblk[0]
                    xpad = jnp.concatenate(
                        [xv, jnp.full((1,), -1, jnp.int32)]
                    )
                    y = jnp.full((lr,), -1, jnp.int32)
                    for bc, _bv, br in bks:
                        g = xpad[jnp.minimum(bc, lc)]
                        y = y.at[br].max(jnp.max(g, axis=1), mode="drop")
                    y = jnp.where(ublk[0], y, -1)
                    return jax.lax.pmax(y, COL_AXIS)[None]

                return jax.shard_map(
                    body, mesh=grid.mesh,
                    in_specs=(P(COL_AXIS), P(ROW_AXIS))
                    + (TILE_SPEC,) * (3 * nb),
                    out_specs=P(ROW_AXIS), check_vma=False,
                )(x, undisc, *fa)

            parents0 = jnp.where(row_gids == source, source, -1)
            levels0 = jnp.where(row_gids == source, 0, -1).astype(jnp.int32)
            x0 = jnp.where(col_gids == source, source, -1)

            def cond(st):
                return st[3] & (st[2] < n)

            def body(st):
                parents, levels, level, _, x = st
                undisc = parents < 0
                y = dense_level_sm(x, undisc)
                new = (y >= 0) & undisc
                parents = jnp.where(new, y, parents)
                levels = jnp.where(new, level + 1, levels)
                fr = DistVec(
                    blocks=jnp.where(new, row_gids, -1),
                    length=n, align="row", grid=grid,
                )
                return (parents, levels, level + 1, jnp.any(new),
                        fr.realign("col").blocks)

            st = jax.lax.while_loop(
                cond, body,
                (parents0, levels0, jnp.int32(0), jnp.bool_(True), x0),
            )
            return st[0], st[2]

        src = np.int32(data["roots"][0])
        out = run(src, *flat_args)
        jax.block_until_ready(out[0])
        time.sleep(DRAIN)
        t0 = time.perf_counter()
        out = run(src, *flat_args)
        it = int(np.asarray(jax.device_get(out[1])))
        dt = time.perf_counter() - t0
        print(json.dumps({
            "mode": MODE, "dt_s": round(dt, 3), "levels": it,
        }), flush=True)
    elif MODE in ("aot", "nocsc"):
        from combblas_tpu.models.bfs import bfs_single
        from combblas_tpu.parallel.vec import DistVec
        import functools

        root = np.int32(data["roots"][0])
        cdg = DistVec.from_global(grid, data["deg"], align="col").blocks
        if MODE == "nocsc":
            dummy = (jnp.zeros((1, 1, 2), jnp.int32),
                     jnp.zeros((1, 1, 2), jnp.int32))
            args = (E, root, dummy)
        else:
            args = (E, root, csc)
        fn = functools.partial(bfs_single, tiers=(), coldeg=cdg)
        if MODE == "aot":
            compiled = jax.jit(fn).lower(*args).compile()
            call = lambda: compiled(*args)
        else:
            call = lambda: fn(*args)
        p, l, niter = call()
        jax.block_until_ready(p.blocks)
        time.sleep(DRAIN)
        t0 = time.perf_counter()
        p, l, niter = call()
        it = int(np.asarray(jax.device_get(niter)))
        dt = time.perf_counter() - t0
        print(json.dumps({"mode": MODE, "dt_s": round(dt, 3),
                          "levels": it}), flush=True)
    elif MODE in ("w1", "w2", "w3"):
        # morph fast-v7 toward bfs_single:
        # w1 = v7 + levels carry/output + DistVec-wrapped outputs
        # w2 = w1 + unused operands (csc, csr, coldeg, rowdeg, iota)
        # w3 = w2 + gids as NamedSharding operands (bfs_single's
        #      _gid_blocks) instead of plain closure arrays
        from jax.sharding import PartitionSpec as P
        from combblas_tpu.parallel.grid import COL_AXIS, ROW_AXIS
        from combblas_tpu.parallel.spmat import TILE_SPEC
        from combblas_tpu.parallel.vec import DistVec
        from combblas_tpu.models.bfs import _gid_blocks, _iota_operand

        flat_args = [a for b in E.buckets for a in b]
        if MODE == "w3":
            row_gids = _gid_blocks(grid, 1, lr, n, "row")
            col_gids = _gid_blocks(grid, 1, lc, n, "col")
        else:
            row_gids = jnp.arange(lr, dtype=jnp.int32)[None]
            col_gids = jnp.arange(lc, dtype=jnp.int32)[None]
        cdg = DistVec.from_global(grid, data["deg"], align="col").blocks
        rdg = DistVec.from_global(grid, data["deg"], align="row").blocks
        iota = _iota_operand(131072)

        def mkfn(with_unused):
            def run(source, row_gids_, col_gids_, cdg_, rdg_, iota_,
                    ipt, ridx, ipt2, ridx2, *fa):
                def dense_level_sm(x, undisc):
                    def body(xblk, ublk, *flat):
                        bks = [tuple(a[0, 0] for a in flat[3*i:3*i+3])
                               for i in range(nb)]
                        xv = xblk[0]
                        xpad = jnp.concatenate(
                            [xv, jnp.full((1,), -1, jnp.int32)])
                        y = jnp.full((lr,), -1, jnp.int32)
                        for bc, _bv, br in bks:
                            g = xpad[jnp.minimum(bc, lc)]
                            y = y.at[br].max(jnp.max(g, axis=1),
                                             mode="drop")
                        y = jnp.where(ublk[0], y, -1)
                        return jax.lax.pmax(y, COL_AXIS)[None]
                    return jax.shard_map(body, mesh=grid.mesh,
                        in_specs=(P(COL_AXIS), P(ROW_AXIS))
                        + (TILE_SPEC,) * (3 * nb),
                        out_specs=P(ROW_AXIS), check_vma=False,
                    )(x, undisc, *fa)
                parents0 = jnp.where(row_gids_ == source, source, -1)
                levels0 = jnp.where(
                    row_gids_ == source, 0, -1).astype(jnp.int32)
                x0 = jnp.where(col_gids_ == source, source, -1)
                def cond(st):
                    return st[4] & (st[3] < n)
                def body(st):
                    parents, levels, x, level, _ = st
                    undisc = parents < 0
                    y = dense_level_sm(x, undisc)
                    new = (y >= 0) & undisc & (row_gids_ >= 0)
                    parents = jnp.where(new, y, parents)
                    levels = jnp.where(new, level + 1, levels)
                    fr = DistVec(
                        blocks=jnp.where(new, row_gids_, -1), length=n,
                        align="row", grid=grid)
                    return (parents, levels, fr.realign("col").blocks,
                            level + 1, jnp.any(new))
                st = jax.lax.while_loop(cond, body,
                    (parents0, levels0, x0, jnp.int32(0),
                     jnp.bool_(True)))
                mk = lambda b: DistVec(blocks=b, length=n, align="row",
                                       grid=grid)
                return mk(st[0]), mk(st[1]), st[3]
            return run

        run = jax.jit(mkfn(MODE != "w1"))
        args = (np.int32(data["roots"][0]), row_gids, col_gids, cdg,
                rdg, iota, csc_indptr, csc_rowidx, csc_indptr,
                csc_rowidx, *flat_args)
        p, l, niter = run(*args)
        jax.block_until_ready(p.blocks)
        time.sleep(DRAIN)
        t0 = time.perf_counter()
        p, l, niter = run(*args)
        it = int(np.asarray(jax.device_get(niter)))
        dt = time.perf_counter() - t0
        print(json.dumps({"mode": MODE, "dt_s": round(dt, 3),
                          "levels": it}), flush=True)
    elif MODE in ("wa", "wb", "wc"):
        # v3-style fast loop + ONE bfs_single feature each:
        # wa = + (parents, levels, niter) multi-output (plain arrays)
        # wb = + "& (row_gids >= 0)" term in `new`
        # wc = + DistVec-wrapped outputs
        from jax.sharding import PartitionSpec as P
        from combblas_tpu.parallel.grid import COL_AXIS, ROW_AXIS
        from combblas_tpu.parallel.spmat import TILE_SPEC
        from combblas_tpu.parallel.vec import DistVec

        flat_args = [a for b in E.buckets for a in b]
        row_gids = jnp.arange(lr, dtype=jnp.int32)[None]

        def dense_level_sm(x, undisc):
            def body(xblk, ublk, *flat):
                bks = [tuple(a[0, 0] for a in flat[3*i:3*i+3])
                       for i in range(nb)]
                xv = xblk[0]
                xpad = jnp.concatenate(
                    [xv, jnp.full((1,), -1, jnp.int32)])
                y = jnp.full((lr,), -1, jnp.int32)
                for bc, _bv, br in bks:
                    g = xpad[jnp.minimum(bc, lc)]
                    y = y.at[br].max(jnp.max(g, axis=1), mode="drop")
                y = jnp.where(ublk[0], y, -1)
                return jax.lax.pmax(y, COL_AXIS)[None]
            return jax.shard_map(body, mesh=grid.mesh,
                in_specs=(P(COL_AXIS), P(ROW_AXIS))
                + (TILE_SPEC,) * (3 * nb),
                out_specs=P(ROW_AXIS), check_vma=False,
            )(x, undisc, *flat_args)

        root = np.int32(data["roots"][0])
        x_init = jnp.where(row_gids == root, jnp.int32(root), -1)

        @jax.jit
        def run(x0):
            parents0 = jnp.where(row_gids == root, jnp.int32(root), -1)
            levels0 = jnp.where(row_gids == root, 0, -1).astype(jnp.int32)
            def cond(st):
                return st[3] & (st[2] < 6)
            def body(st):
                parents, levels, level, _, x = st
                undisc = parents < 0
                y = dense_level_sm(x, undisc)
                if MODE == "wb":
                    new = (y >= 0) & undisc & (row_gids >= 0)
                else:
                    new = (y >= 0) & undisc
                parents = jnp.where(new, y, parents)
                levels = jnp.where(new, level + 1, levels)
                fr = DistVec(
                    blocks=jnp.where(new, row_gids, -1), length=n,
                    align="row", grid=grid)
                return (parents, levels, level + 1, jnp.any(new),
                        fr.realign("col").blocks)
            st = jax.lax.while_loop(cond, body,
                (parents0, levels0, jnp.int32(0), jnp.bool_(True), x_init))
            if MODE == "wa":
                return st[0], st[1], st[2]
            if MODE == "wc":
                mk = lambda b: DistVec(blocks=b, length=n, align="row",
                                       grid=grid)
                return mk(st[0]), mk(st[1]), st[2]
            return st[0], st[2]

        out = run(x_init)
        first = out[0].blocks if MODE == "wc" else out[0]
        jax.block_until_ready(first)
        time.sleep(DRAIN)
        t0 = time.perf_counter()
        out = run(x_init)
        sink = out[-1] if MODE != "wb" else out[0]
        v = np.asarray(jax.device_get(sink))
        dt = time.perf_counter() - t0
        print(json.dumps({"mode": MODE, "dt_s": round(dt, 3)}),
              flush=True)
    elif MODE in ("w4", "w5", "w6", "w7"):
        # w4 = v7(args, plain outputs) + all of bfs_single's extra
        #      operands passed (csc x2, csr x2, cdg, rdg, iota) UNUSED
        # w5 = w4 minus the two huge flat companions (csc/csr idx)
        # w6 = v7 exactly, re-measured now (chip-state control)
        from jax.sharding import PartitionSpec as P
        from combblas_tpu.parallel.grid import COL_AXIS, ROW_AXIS
        from combblas_tpu.parallel.spmat import TILE_SPEC
        from combblas_tpu.parallel.vec import DistVec
        from combblas_tpu.models.bfs import _iota_operand

        flat_args = [a for b in E.buckets for a in b]
        row_gids = jnp.arange(lr, dtype=jnp.int32)[None]
        col_gids = jnp.arange(lc, dtype=jnp.int32)[None]
        cdg = DistVec.from_global(grid, data["deg"], align="col").blocks
        rdg = DistVec.from_global(grid, data["deg"], align="row").blocks
        iota = _iota_operand(131072)

        def run(source, *ops):
            fa = ops[: 3 * nb]

            def dense_level_sm(x, undisc):
                def body(xblk, ublk, *flat):
                    bks = [tuple(a[0, 0] for a in flat[3*i:3*i+3])
                           for i in range(nb)]
                    xv = xblk[0]
                    xpad = jnp.concatenate(
                        [xv, jnp.full((1,), -1, jnp.int32)])
                    y = jnp.full((lr,), -1, jnp.int32)
                    for bc, _bv, br in bks:
                        g = xpad[jnp.minimum(bc, lc)]
                        y = y.at[br].max(jnp.max(g, axis=1), mode="drop")
                    y = jnp.where(ublk[0], y, -1)
                    return jax.lax.pmax(y, COL_AXIS)[None]
                return jax.shard_map(body, mesh=grid.mesh,
                    in_specs=(P(COL_AXIS), P(ROW_AXIS))
                    + (TILE_SPEC,) * (3 * nb),
                    out_specs=P(ROW_AXIS), check_vma=False,
                )(x, undisc, *fa)
            parents0 = jnp.where(row_gids == source, source, -1)
            levels0 = jnp.where(
                row_gids == source, 0, -1).astype(jnp.int32)
            x0 = jnp.where(col_gids == source, source, -1)
            def cond(st):
                return st[3] & (st[2] < n)
            def body(st):
                parents, levels, level, _, x = st
                undisc = parents < 0
                y = dense_level_sm(x, undisc)
                new = (y >= 0) & undisc
                parents = jnp.where(new, y, parents)
                levels = jnp.where(new, level + 1, levels)
                fr = DistVec(
                    blocks=jnp.where(new, row_gids, -1), length=n,
                    align="row", grid=grid)
                return (parents, levels, level + 1, jnp.any(new),
                        fr.realign("col").blocks)
            st = jax.lax.while_loop(cond, body,
                (parents0, levels0, jnp.int32(0), jnp.bool_(True), x0))
            return st[0], st[1], st[2]

        if MODE == "w7":
            # gids as plain jit ARGUMENTS instead of closures
            def run7(source, rg, cg, *ops):
                fa = ops[: 3 * nb]
                def dense_level_sm(x, undisc):
                    def body(xblk, ublk, *flat):
                        bks = [tuple(a[0, 0] for a in flat[3*i:3*i+3])
                               for i in range(nb)]
                        xv = xblk[0]
                        xpad = jnp.concatenate(
                            [xv, jnp.full((1,), -1, jnp.int32)])
                        y = jnp.full((lr,), -1, jnp.int32)
                        for bc, _bv, br in bks:
                            g = xpad[jnp.minimum(bc, lc)]
                            y = y.at[br].max(jnp.max(g, axis=1),
                                             mode="drop")
                        y = jnp.where(ublk[0], y, -1)
                        return jax.lax.pmax(y, COL_AXIS)[None]
                    return jax.shard_map(body, mesh=grid.mesh,
                        in_specs=(P(COL_AXIS), P(ROW_AXIS))
                        + (TILE_SPEC,) * (3 * nb),
                        out_specs=P(ROW_AXIS), check_vma=False,
                    )(x, undisc, *fa)
                parents0 = jnp.where(rg == source, source, -1)
                levels0 = jnp.where(rg == source, 0, -1).astype(jnp.int32)
                x0 = jnp.where(cg == source, source, -1)
                def cond(st):
                    return st[3] & (st[2] < n)
                def body(st):
                    parents, levels, level, _, x = st
                    undisc = parents < 0
                    y = dense_level_sm(x, undisc)
                    new = (y >= 0) & undisc
                    parents = jnp.where(new, y, parents)
                    levels = jnp.where(new, level + 1, levels)
                    fr = DistVec(
                        blocks=jnp.where(new, rg, -1), length=n,
                        align="row", grid=grid)
                    return (parents, levels, level + 1, jnp.any(new),
                            fr.realign("col").blocks)
                st = jax.lax.while_loop(cond, body,
                    (parents0, levels0, jnp.int32(0), jnp.bool_(True),
                     x0))
                return st[0], st[1], st[2]
            jrun = jax.jit(run7)
            args = (np.int32(data["roots"][0]),
                    jax.device_put(row_gids), jax.device_put(col_gids),
                    *flat_args)
            out = jrun(*args)
            jax.block_until_ready(out[0])
            time.sleep(DRAIN)
            t0 = time.perf_counter()
            out = jrun(*args)
            it = int(np.asarray(jax.device_get(out[2])))
            dt = time.perf_counter() - t0
            print(json.dumps({"mode": MODE, "dt_s": round(dt, 3),
                              "levels": it}), flush=True)
            return
        extra = ()
        if MODE == "w4":
            extra = (csc_indptr, csc_rowidx, csc_indptr, csc_rowidx,
                     cdg, rdg, iota)
        elif MODE == "w5":
            extra = (csc_indptr, csc_indptr, cdg, rdg, iota)
        args = (np.int32(data["roots"][0]), *flat_args, *extra)
        jrun = jax.jit(run)
        out = jrun(*args)
        jax.block_until_ready(out[0])
        time.sleep(DRAIN)
        t0 = time.perf_counter()
        out = jrun(*args)
        it = int(np.asarray(jax.device_get(out[2])))
        dt = time.perf_counter() - t0
        print(json.dumps({"mode": MODE, "dt_s": round(dt, 3),
                          "levels": it}), flush=True)
    elif MODE == "whole":
        from combblas_tpu.models.bfs import bfs_single
        from combblas_tpu.parallel.vec import DistVec

        from combblas_tpu.models.bfs import parse_tier_spec

        from combblas_tpu.models.bfs import DEFAULT_SEQ_TIERS

        spec = os.environ.get("BENCH_SEQ_TIERS", DEFAULT_SEQ_TIERS)
        tiers = parse_tier_spec(spec)
        root = np.int32(data["roots"][int(os.environ.get("ROOT", "0"))])
        cdg = DistVec.from_global(grid, data["deg"], align="col").blocks
        rdg = DistVec.from_global(grid, data["deg"], align="row").blocks
        p, l, niter = bfs_single(E, root, csc, csr=csc, tiers=tiers,
                                 coldeg=cdg, rowdeg=rdg)
        jax.block_until_ready(p.blocks)
        time.sleep(DRAIN)
        t0 = time.perf_counter()
        p, l, niter = bfs_single(E, root, csc, csr=csc, tiers=tiers,
                                 coldeg=cdg, rowdeg=rdg)
        it = int(np.asarray(jax.device_get(niter)))
        dt = time.perf_counter() - t0
        print(json.dumps({
            "mode": MODE, "dt_s": round(dt, 3), "levels": it,
            "tiers": list(tiers),
        }), flush=True)


if __name__ == "__main__":
    main()
