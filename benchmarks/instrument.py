"""Single-experiment BFS/SpMV instrumentation probe (axon-safe).

Usage:  python benchmarks/instrument.py EXPERIMENT [ARGS...]

Each invocation runs ONE experiment in a fresh process and prints one JSON
line. Fresh-process isolation matters: on this chip any device->host
readback permanently degrades subsequent launches (~1000x, see bench.py
module docstring), so a probe gets exactly one timed section, closed by a
single scalar D2H (the only trustworthy synchronization point through the
axon tunnel — block_until_ready returns in microseconds regardless of
in-flight work).

Experiments (scale/edgefactor via BENCH_SCALE / BENCH_EDGEFACTOR):

  chain K R        R launches of a K-level fused BFS-step loop (lax.fori_loop,
                   no early exit — dense-regime level cost is frontier-
                   independent). Varying (K, R) at constant K*R separates
                   per-launch dispatch overhead from per-level kernel time.
  kernel VARIANT R one launch, R chained iterations of a local-kernel piece:
                   full     = gather + semiring fold + row scatter (the real
                              ELL local SpMV, level-equivalent minus realign)
                   fold     = gather + fold only (scatter replaced by a sum)
                   scatter  = row scatter only (folded values precomputed)
  membw MB R       one launch, R chained sums over an MB-megabyte f32 array:
                   achieved HBM read bandwidth reference.

These are the "which phase is slow" numbers VERDICT r1 asked for; results
are committed to benchmarks/results/instrument_r2.json by the driver.
"""

from __future__ import annotations

import json
import os
import sys
import time

SCALE = int(os.environ.get("BENCH_SCALE", "19"))
EDGEFACTOR = int(os.environ.get("BENCH_EDGEFACTOR", "16"))


def build_graph():
    import numpy as np

    from combblas_tpu.utils.rmat import rmat_symmetric_coo_host

    n = 1 << SCALE
    rows, cols = rmat_symmetric_coo_host(42, SCALE, EDGEFACTOR)
    key = rows * np.int64(n) + cols
    uniq = np.unique(key)
    rows_u = (uniq // n).astype(np.int64)
    cols_u = (uniq % n).astype(np.int64)
    return rows_u, cols_u, n


def upload_ell():
    import numpy as np

    from combblas_tpu.parallel.ellmat import EllParMat
    from combblas_tpu.parallel.grid import Grid

    rows_u, cols_u, n = build_graph()
    grid = Grid.make(1, 1)
    E = EllParMat.from_host_coo(
        grid, rows_u, cols_u, np.ones(len(rows_u), np.float32), n, n
    )
    return E, n, len(rows_u)


def ell_bytes(E) -> int:
    """HBM bytes read per full ELL SpMV (cols + vals once, ignoring the
    x-gather reuse and y writes — a lower bound on traffic)."""
    total = 0
    for bc, bv, br in E.buckets:
        total += bc.size * 4 + bv.size * 4 + br.size * 4
    return total


def timed(launch_fn, n_launches: int, sync_fn):
    """Run launch_fn() n_launches times, close with sync_fn() (one D2H)."""
    t0 = time.perf_counter()
    out = None
    for _ in range(n_launches):
        out = launch_fn(out)
    sync_fn(out)
    return time.perf_counter() - t0


def exp_chain(K: int, R: int):
    import jax
    import jax.numpy as jnp
    from jax import lax

    from combblas_tpu.parallel.ellmat import dist_spmv_ell_masked
    from combblas_tpu.parallel.vec import DistVec
    from combblas_tpu.semiring import SELECT2ND_MAX

    E, n, nnz = upload_ell()
    grid = E.grid
    lr = grid.local_rows(n)
    row_gids = jnp.arange(lr, dtype=jnp.int32).reshape(1, lr)

    def mk(b, align):
        return DistVec(blocks=b, length=n, align=align, grid=grid)

    @jax.jit
    def chainK(parents, x):
        def body(_, st):
            parents, x = st
            unvisited = mk(parents < 0, "row")
            y = dist_spmv_ell_masked(SELECT2ND_MAX, E, mk(x, "col"), unvisited)
            new = (y.blocks >= 0) & (parents < 0)
            parents = jnp.where(new, y.blocks, parents)
            x = mk(jnp.where(new, row_gids, -1), "row").realign("col").blocks
            return parents, x

        return lax.fori_loop(0, K, body, (parents, x))

    parents0 = jnp.where(row_gids == 0, 0, -1).astype(jnp.int32)
    x0 = jnp.where(row_gids == 0, 0, -1).astype(jnp.int32)
    # warmup compile
    p, x = chainK(parents0, x0)
    jax.block_until_ready((p, x))
    time.sleep(3.0)

    def launch(prev):
        if prev is None:
            prev = (parents0, x0)
        return chainK(*prev)

    dt = timed(launch, R, lambda out: int(jax.device_get(out[0][0, 0])))
    return {
        "experiment": f"chain K={K} R={R}",
        "levels": K * R,
        "launches": R,
        "dt_s": round(dt, 4),
        "ms_per_level": round(dt / (K * R) * 1e3, 3),
        "nnz": nnz,
        "ell_bytes_per_level": ell_bytes(E),
        "achieved_GBps": round(ell_bytes(E) * K * R / dt / 1e9, 2),
    }


def exp_kernel(variant: str, R: int):
    import jax
    import jax.numpy as jnp
    from jax import lax

    from combblas_tpu.parallel.ellmat import (
        _bucket_fold,
        _ell_local_spmv,
        _scatter_rows,
    )
    from combblas_tpu.semiring import SELECT2ND_MAX

    E, n, nnz = upload_ell()
    sr = SELECT2ND_MAX
    lr = E.local_rows
    lc = E.local_cols
    # strip the [pr, pc] tile dims — single-device local arrays
    buckets = [(bc[0, 0], bv[0, 0].astype(jnp.int32), br[0, 0]) for bc, bv, br in E.buckets]
    nb_tot = sum(b[0].shape[0] for b in buckets)

    if variant == "full":

        @jax.jit
        def run(x):
            def body(_, x):
                y = _ell_local_spmv(sr, buckets, x, lr, lc)
                return jnp.where(y >= 0, y, x)  # data dependence

            return lax.fori_loop(0, R, body, x)

    elif variant == "fold":

        @jax.jit
        def run(x):
            def body(_, x):
                zero = sr.zero(x.dtype)
                xpad = jnp.concatenate([x, zero[None]])
                acc = jnp.int32(0)
                for bc, bv, br in buckets:
                    g = xpad[jnp.minimum(bc, lc)]
                    prods = sr.mul(bv, g)
                    yb = _bucket_fold(sr, prods)
                    acc = acc + jnp.sum(yb)
                return x.at[0].set(acc)  # data dependence, no scatter

            return lax.fori_loop(0, R, body, x)

    elif variant == "scatter":
        ybs = [jnp.zeros((b[0].shape[0],), jnp.int32) for b in buckets]

        @jax.jit
        def run(x):
            def body(_, x):
                y = jnp.full((lr,), sr.zero(jnp.int32), jnp.int32)
                for (bc, bv, br), yb in zip(buckets, ybs):
                    y = _scatter_rows(sr, y, br, yb + x[0])
                return jnp.maximum(y, x)

            return lax.fori_loop(0, R, body, x)

    else:
        raise SystemExit(f"unknown kernel variant {variant}")

    x0 = jnp.full((lc,), -1, jnp.int32).at[0].set(0)
    out = run(x0)
    jax.block_until_ready(out)
    time.sleep(3.0)

    dt = timed(lambda prev: run(x0 if prev is None else prev), 1,
               lambda out: int(jax.device_get(out[0])))
    return {
        "experiment": f"kernel {variant} R={R}",
        "iters": R,
        "dt_s": round(dt, 4),
        "ms_per_iter": round(dt / R * 1e3, 3),
        "nnz": nnz,
        "n_buckets": len(buckets),
        "bucket_rows_total": int(nb_tot),
        "ell_bytes": ell_bytes(E),
        "achieved_GBps": round(ell_bytes(E) * R / dt / 1e9, 2),
    }


def exp_membw(mb: int, R: int):
    import jax
    import jax.numpy as jnp
    from jax import lax

    n = mb * 1024 * 1024 // 4
    a = jnp.arange(n, dtype=jnp.float32)

    @jax.jit
    def run(s):
        def body(_, s):
            return s + jnp.sum(a + s)

        return lax.fori_loop(0, R, body, s)

    out = run(jnp.float32(0))
    jax.block_until_ready(out)
    time.sleep(3.0)
    dt = timed(lambda prev: run(jnp.float32(0)), 1,
               lambda out: float(jax.device_get(out)))
    return {
        "experiment": f"membw {mb}MB R={R}",
        "dt_s": round(dt, 4),
        "ms_per_iter": round(dt / R * 1e3, 3),
        "achieved_GBps": round(mb / 1024 * R / dt, 1),
    }


def main():
    exp = sys.argv[1]
    if exp == "chain":
        out = exp_chain(int(sys.argv[2]), int(sys.argv[3]))
    elif exp == "kernel":
        out = exp_kernel(sys.argv[2], int(sys.argv[3]))
    elif exp == "membw":
        out = exp_membw(int(sys.argv[2]), int(sys.argv[3]))
    elif exp == "membw2":
        out = exp_membw2(int(sys.argv[2]), int(sys.argv[3]))
    elif exp == "args":
        out = exp_args(int(sys.argv[2]), int(sys.argv[3]))
    else:
        raise SystemExit(f"unknown experiment {exp}")
    out["scale"] = SCALE
    print(json.dumps(out))




def exp_args(mb: int, R: int):
    """Trivial kernel over an MB-sized resident argument, R launches:
    if per-launch time scales with MB, the tunnel streams arguments per
    launch (the fixed-cost hypothesis for the BFS gap)."""
    import jax
    import jax.numpy as jnp

    n = mb * 1024 * 1024 // 4
    a = jax.device_put(jnp.ones((n,), jnp.float32))

    @jax.jit
    def run(a, s):
        return a[:8].sum() + s

    out = run(a, jnp.float32(0))
    jax.block_until_ready(out)
    time.sleep(3.0)
    dt = timed(lambda prev: run(a, prev if prev is not None else jnp.float32(0)),
               R, lambda out: float(jax.device_get(out)))
    return {
        "experiment": f"args {mb}MB R={R}",
        "dt_s": round(dt, 4),
        "ms_per_launch": round(dt / R * 1e3, 3),
        "implied_stream_MBps": round(mb * R / dt, 1),
    }


def exp_membw2(mb: int, R: int):
    """HBM bandwidth: array passed as ARGUMENT (not closure constant —
    closures get embedded in the compile request, which the remote-compile
    endpoint rejects >~100MB)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    n = mb * 1024 * 1024 // 4
    a = jax.device_put(jnp.ones((n,), jnp.float32))

    @jax.jit
    def run(a, s):
        def body(_, s):
            return s + jnp.sum(a * (1.0 + s * 1e-30))
        return lax.fori_loop(0, R, body, s)

    out = run(a, jnp.float32(0))
    jax.block_until_ready(out)
    time.sleep(3.0)
    dt = timed(lambda prev: run(a, jnp.float32(0)), 1,
               lambda out: float(jax.device_get(out)))
    return {
        "experiment": f"membw2 {mb}MB R={R}",
        "dt_s": round(dt, 4),
        "ms_per_iter": round(dt / R * 1e3, 3),
        "achieved_GBps": round(mb / 1024 * R / dt, 1),
    }

if __name__ == "__main__":
    main()
