"""Single-experiment BFS/SpMV instrumentation probe (axon-safe).

Usage:  python benchmarks/instrument.py EXPERIMENT [ARGS...]

Each invocation runs ONE experiment in a fresh process and prints one JSON
line. Fresh-process isolation matters: on this chip any device->host
readback permanently degrades subsequent launches (~1000x, see bench.py
module docstring), so a probe gets exactly one timed section, closed by a
single scalar D2H (the only trustworthy synchronization point through the
axon tunnel — block_until_ready returns in microseconds regardless of
in-flight work).

Experiments (scale/edgefactor via BENCH_SCALE / BENCH_EDGEFACTOR):

  chain K R        R launches of a K-level fused BFS-step loop (lax.fori_loop,
                   no early exit — dense-regime level cost is frontier-
                   independent). Varying (K, R) at constant K*R separates
                   per-launch dispatch overhead from per-level kernel time.
  kernel VARIANT R one launch, R chained iterations of a local-kernel piece:
                   full     = gather + semiring fold + row scatter (the real
                              ELL local SpMV, level-equivalent minus realign)
                   fold     = gather + fold only (scatter replaced by a sum)
                   scatter  = row scatter only (folded values precomputed)
  membw MB R       BROKEN for useful sizes: the array is a jit-closure
                   constant, embedded in the remote-compile request, which
                   rejects bodies >~100MB (HTTP 413). Kept for the record;
                   use membw2.
  membw2 MB R      HBM read-bandwidth reference; array passed as an
                   argument (resident), R chained sums in one launch.
  args MB R        R launches of a trivial kernel over an MB-sized resident
                   argument: separates fixed dispatch cost from any
                   per-launch argument streaming (measured: ~105 ms fixed,
                   no streaming).
  gatherw W R      one launch, R iterations of the full bucket gather with
                   W payload lanes per index ([lc+1, W] table): the
                   multi-root batching question (measured: W=8 costs the
                   same as W=1; W=64 costs ~2x).
  pallas_gather R [W]  Mosaic 2D-gather feasibility probe (take_along_axis
                   from a VMEM table). NOTE arg order: R first, then W
                   (default 128). Currently fails lowering: Mosaic's
                   dynamic-gather is register-block-local, not a
                   large-table gather.

These are the "which phase is slow" numbers VERDICT r1 asked for; results
are committed to benchmarks/results/instrument_r2.json by the driver.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

SCALE = int(os.environ.get("BENCH_SCALE", "19"))
EDGEFACTOR = int(os.environ.get("BENCH_EDGEFACTOR", "16"))


def build_graph():
    import numpy as np

    from combblas_tpu.utils.rmat import rmat_symmetric_coo_host

    n = 1 << SCALE
    rows, cols = rmat_symmetric_coo_host(42, SCALE, EDGEFACTOR)
    key = rows * np.int64(n) + cols
    uniq = np.unique(key)
    rows_u = (uniq // n).astype(np.int64)
    cols_u = (uniq % n).astype(np.int64)
    return rows_u, cols_u, n


def upload_ell():
    import numpy as np

    from combblas_tpu.parallel.ellmat import EllParMat
    from combblas_tpu.parallel.grid import Grid

    rows_u, cols_u, n = build_graph()
    grid = Grid.make(1, 1)
    E = EllParMat.from_host_coo(
        grid, rows_u, cols_u, np.ones(len(rows_u), np.float32), n, n
    )
    return E, n, len(rows_u)


def ell_bytes(E) -> int:
    """HBM bytes read per full ELL SpMV (cols + vals once, ignoring the
    x-gather reuse and y writes — a lower bound on traffic)."""
    total = 0
    for bc, bv, br in E.buckets:
        total += bc.size * 4 + bv.size * 4 + br.size * 4
    return total


def timed(launch_fn, n_launches: int, sync_fn):
    """Run launch_fn() n_launches times, close with sync_fn() (one D2H)."""
    t0 = time.perf_counter()
    out = None
    for _ in range(n_launches):
        out = launch_fn(out)
    sync_fn(out)
    return time.perf_counter() - t0


def exp_chain(K: int, R: int):
    import jax
    import jax.numpy as jnp
    from jax import lax

    from combblas_tpu.parallel.ellmat import dist_spmv_ell_masked
    from combblas_tpu.parallel.vec import DistVec
    from combblas_tpu.semiring import SELECT2ND_MAX

    E, n, nnz = upload_ell()
    grid = E.grid
    lr = grid.local_rows(n)
    row_gids = jnp.arange(lr, dtype=jnp.int32).reshape(1, lr)

    def mk(b, align):
        return DistVec(blocks=b, length=n, align=align, grid=grid)

    @jax.jit
    def chainK(parents, x):
        def body(_, st):
            parents, x = st
            unvisited = mk(parents < 0, "row")
            y = dist_spmv_ell_masked(SELECT2ND_MAX, E, mk(x, "col"), unvisited)
            new = (y.blocks >= 0) & (parents < 0)
            parents = jnp.where(new, y.blocks, parents)
            x = mk(jnp.where(new, row_gids, -1), "row").realign("col").blocks
            return parents, x

        return lax.fori_loop(0, K, body, (parents, x))

    parents0 = jnp.where(row_gids == 0, 0, -1).astype(jnp.int32)
    x0 = jnp.where(row_gids == 0, 0, -1).astype(jnp.int32)
    # warmup compile
    p, x = chainK(parents0, x0)
    jax.block_until_ready((p, x))
    time.sleep(3.0)

    def launch(prev):
        if prev is None:
            prev = (parents0, x0)
        return chainK(*prev)

    dt = timed(launch, R, lambda out: int(jax.device_get(out[0][0, 0])))
    return {
        "experiment": f"chain K={K} R={R}",
        "levels": K * R,
        "launches": R,
        "dt_s": round(dt, 4),
        "ms_per_level": round(dt / (K * R) * 1e3, 3),
        "nnz": nnz,
        "ell_bytes_per_level": ell_bytes(E),
        "achieved_GBps": round(ell_bytes(E) * K * R / dt / 1e9, 2),
    }


def exp_kernel(variant: str, R: int):
    import jax
    import jax.numpy as jnp
    from jax import lax

    from combblas_tpu.parallel.ellmat import (
        _bucket_fold,
        _ell_local_spmv,
        _scatter_rows,
    )
    from combblas_tpu.semiring import SELECT2ND_MAX

    E, n, nnz = upload_ell()
    sr = SELECT2ND_MAX
    lr = E.local_rows
    lc = E.local_cols
    # strip the [pr, pc] tile dims — single-device local arrays
    buckets = [(bc[0, 0], bv[0, 0].astype(jnp.int32), br[0, 0]) for bc, bv, br in E.buckets]
    nb_tot = sum(b[0].shape[0] for b in buckets)

    if variant == "full":

        @jax.jit
        def run(x):
            def body(_, x):
                y = _ell_local_spmv(sr, buckets, x, lr, lc)
                return jnp.where(y >= 0, y, x)  # data dependence

            return lax.fori_loop(0, R, body, x)

    elif variant == "fold":

        @jax.jit
        def run(x):
            def body(_, x):
                zero = sr.zero(x.dtype)
                xpad = jnp.concatenate([x, zero[None]])
                acc = jnp.int32(0)
                for bc, bv, br in buckets:
                    g = xpad[jnp.minimum(bc, lc)]
                    prods = sr.mul(bv, g)
                    yb = _bucket_fold(sr, prods)
                    acc = acc + jnp.sum(yb)
                return x.at[0].set(acc)  # data dependence, no scatter

            return lax.fori_loop(0, R, body, x)

    elif variant == "scatter":
        ybs = [jnp.zeros((b[0].shape[0],), jnp.int32) for b in buckets]

        @jax.jit
        def run(x):
            def body(_, x):
                y = jnp.full((lr,), sr.zero(jnp.int32), jnp.int32)
                for (bc, bv, br), yb in zip(buckets, ybs):
                    y = _scatter_rows(sr, y, br, yb + x[0])
                return jnp.maximum(y, x)

            return lax.fori_loop(0, R, body, x)

    else:
        raise SystemExit(f"unknown kernel variant {variant}")

    x0 = jnp.full((lc,), -1, jnp.int32).at[0].set(0)
    out = run(x0)
    jax.block_until_ready(out)
    time.sleep(3.0)

    dt = timed(lambda prev: run(x0 if prev is None else prev), 1,
               lambda out: int(jax.device_get(out[0])))
    return {
        "experiment": f"kernel {variant} R={R}",
        "iters": R,
        "dt_s": round(dt, 4),
        "ms_per_iter": round(dt / R * 1e3, 3),
        "nnz": nnz,
        "n_buckets": len(buckets),
        "bucket_rows_total": int(nb_tot),
        "ell_bytes": ell_bytes(E),
        "achieved_GBps": round(ell_bytes(E) * R / dt / 1e9, 2),
    }


def exp_membw(mb: int, R: int):
    import jax
    import jax.numpy as jnp
    from jax import lax

    n = mb * 1024 * 1024 // 4
    a = jnp.arange(n, dtype=jnp.float32)

    @jax.jit
    def run(s):
        def body(_, s):
            return s + jnp.sum(a + s)

        return lax.fori_loop(0, R, body, s)

    out = run(jnp.float32(0))
    jax.block_until_ready(out)
    time.sleep(3.0)
    dt = timed(lambda prev: run(jnp.float32(0)), 1,
               lambda out: float(jax.device_get(out)))
    return {
        "experiment": f"membw {mb}MB R={R}",
        "dt_s": round(dt, 4),
        "ms_per_iter": round(dt / R * 1e3, 3),
        "achieved_GBps": round(mb / 1024 * R / dt, 1),
    }


def exp_scatter(variant: str, n_m: float, t_m: float, R: int):
    """Scatter/gather throughput probe — the SpGEMM-redesign question.

    N million values are scattered into a T-million-cell table R times in
    one launch. Variants:
      add         .at[idx].add, random unsorted indices
      min         .at[idx].min int32, random unsorted
      addsort     .at[idx].add, SORTED indices + indices_are_sorted hint
      segsum      jax.ops.segment_sum, sorted ids, NO hint (today's
                  segment_reduce path)
      segsumhint  segment_sum, sorted ids, indices_are_sorted=True
      gather      x[idx] baseline (known ~133M idx/s)
    Distinguishes the two contradictory round-2 scatter numbers (79 ms for
    22.6M row-scatter vs '0.2us/element') and prices the bucketed-
    accumulation SpGEMM before building it.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    N = int(n_m * 1e6)
    T = int(t_m * 1e6)
    rng = np.random.default_rng(0)
    idx_np = rng.integers(0, T, size=N, dtype=np.int32)
    if variant in ("addsort", "segsum", "segsumhint"):
        idx_np = np.sort(idx_np)
    idx = jax.device_put(jnp.asarray(idx_np))
    vals = jax.device_put(jnp.ones((N,), jnp.float32))

    if variant == "add":

        def op(idx, vals, s):
            t = jnp.zeros((T,), jnp.float32)
            return t.at[idx].add(vals + s * 1e-30, mode="drop")

    elif variant == "min":

        def op(idx, vals, s):
            t = jnp.full((T,), jnp.int32(2**31 - 1))
            return t.at[idx].min(
                jnp.arange(N, dtype=jnp.int32) + (s * 0).astype(jnp.int32),
                mode="drop",
            ).astype(jnp.float32)

    elif variant == "addsort":

        def op(idx, vals, s):
            t = jnp.zeros((T,), jnp.float32)
            return t.at[idx].add(
                vals + s * 1e-30, mode="drop", indices_are_sorted=True
            )

    elif variant == "segsum":

        def op(idx, vals, s):
            return jax.ops.segment_sum(
                vals + s * 1e-30, idx, num_segments=T
            )

    elif variant == "segsumhint":

        def op(idx, vals, s):
            return jax.ops.segment_sum(
                vals + s * 1e-30, idx, num_segments=T,
                indices_are_sorted=True,
            )

    elif variant == "gather":

        def op(idx, vals, s):
            x = vals + s * 1e-30
            pad = jnp.zeros((T,), jnp.float32).at[: min(N, T)].set(x[: min(N, T)])
            return pad[idx][:T]

    else:
        raise SystemExit(f"unknown scatter variant {variant}")

    @jax.jit
    def run(idx, vals):
        def body(_, s):
            out = op(idx, vals, s)
            return out[0] + s * 1e-30

        return lax.fori_loop(0, R, body, jnp.float32(0))

    out = run(idx, vals)
    jax.block_until_ready(out)
    time.sleep(3.0)
    dt = timed(lambda prev: run(idx, vals), 1,
               lambda out: float(jax.device_get(out)))
    return {
        "experiment": f"scatter {variant} N={n_m}M T={t_m}M R={R}",
        "dt_s": round(dt, 4),
        "ms_per_iter": round(dt / R * 1e3, 3),
        "Melem_per_s": round(N * R / dt / 1e6, 1),
        "ns_per_elem": round(dt / (N * R) * 1e9, 2),
    }


def _build_local_esc(scale: int, ef: int = 8):
    """Local A (SpTuples, row-sorted) + A as CSR + exact capacities for A^2."""
    import jax
    import numpy as np

    from combblas_tpu.ops.compressed import CSR
    from combblas_tpu.ops.tuples import SpTuples
    from combblas_tpu.utils.rmat import rmat_symmetric_coo_host

    n = 1 << scale
    rows, cols = rmat_symmetric_coo_host(5, scale, ef)
    key = rows * np.int64(n) + cols
    uniq = np.unique(key)
    ru = (uniq // n).astype(np.int64)
    cu = (uniq % n).astype(np.int64)
    nnz = len(ru)
    # exact flops on host: sum over entries of rowlen[col]
    rowlen = np.bincount(ru, minlength=n)
    flops = int(rowlen[cu].sum())
    a = SpTuples.from_coo(ru, cu, np.ones(nnz, np.float32), n, n)
    csr = CSR.from_tuples(a, assume_sorted=True)
    return a, csr, n, nnz, flops


def exp_escparts(variant: str, scale: int, R: int):
    """Decompose local ESC SpGEMM (A^2, rmat ef8) phase by phase:
      expand / sort / segsum / compact / full — each timed alone in one
      launch chain. Identifies which of the 26.6 s at scale 14 is sort,
      which is the segment scatter, which is compaction scatters.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    from combblas_tpu import PLUS_TIMES
    from combblas_tpu.ops.spgemm import expand
    from combblas_tpu.ops.tuples import SpTuples

    sr = PLUS_TIMES
    a, csr, n, nnz, flops = _build_local_esc(scale)
    fcap = flops  # exact
    ocap = flops  # generous; compact clamps

    exp_t = None
    if variant in ("sort", "segsum", "compact"):
        # materialize the expansion once (untimed) as the phase input
        exp_t = jax.jit(
            lambda a, c: expand(sr, a, c, fcap), static_argnums=()
        )(a, csr)
        jax.block_until_ready(exp_t.vals)

    if variant == "expand":

        @jax.jit
        def run(a, csr):
            def body(_, s):
                import dataclasses

                t = expand(
                    sr,
                    dataclasses.replace(a, vals=a.vals + s * 1e-30),
                    csr,
                    fcap,
                )
                return t.vals[0] + s * 1e-30

            return lax.fori_loop(0, R, body, jnp.float32(0))

        args = (a, csr)
    elif variant == "sort":

        @jax.jit
        def run(t):
            def body(_, s):
                import dataclasses

                st = dataclasses.replace(t, vals=t.vals + s * 1e-30)
                st = st.sort_rowmajor()
                return st.vals[0] + s * 1e-30

            return lax.fori_loop(0, R, body, jnp.float32(0))

        args = (exp_t,)
    elif variant == "segsum":
        # sorted expansion -> the segment fold + scatters of compact_counted
        # WITHOUT the sort (assume_sorted) — isolates the post-sort phases
        exp_t = jax.jit(lambda t: t.sort_rowmajor())(exp_t)
        jax.block_until_ready(exp_t.vals)

        @jax.jit
        def run(t):
            def body(_, s):
                import dataclasses

                st = dataclasses.replace(t, vals=t.vals + s * 1e-30)
                out, _ = st.compact_counted(
                    sr, capacity=ocap, assume_sorted=True
                )
                return out.vals[0] + s * 1e-30

            return lax.fori_loop(0, R, body, jnp.float32(0))

        args = (exp_t,)
    elif variant == "compact":

        @jax.jit
        def run(t):
            def body(_, s):
                import dataclasses

                st = dataclasses.replace(t, vals=t.vals + s * 1e-30)
                out, _ = st.compact_counted(sr, capacity=ocap)
                return out.vals[0] + s * 1e-30

            return lax.fori_loop(0, R, body, jnp.float32(0))

        args = (exp_t,)
    elif variant == "full":

        @jax.jit
        def run(a, csr):
            def body(_, s):
                import dataclasses

                from combblas_tpu.ops.spgemm import local_spgemm

                aa = dataclasses.replace(a, vals=a.vals + s * 1e-30)
                C = local_spgemm(
                    sr, aa, csr, flop_capacity=fcap, out_capacity=ocap
                )
                return C.vals[0] + s * 1e-30

            return lax.fori_loop(0, R, body, jnp.float32(0))

        args = (a, csr)
    else:
        raise SystemExit(f"unknown escparts variant {variant}")

    out = run(*args)
    jax.block_until_ready(out)
    time.sleep(3.0)
    dt = timed(lambda prev: run(*args), 1,
               lambda out: float(jax.device_get(out)))
    return {
        "experiment": f"escparts {variant} scale={scale} R={R}",
        "dt_s": round(dt, 4),
        "s_per_iter": round(dt / R, 3),
        "nnz": nnz,
        "flops": flops,
        "MFLOPs": round(flops * 2 * R / dt / 1e6, 2),
    }


def main():
    exp = sys.argv[1]
    if exp == "chain":
        out = exp_chain(int(sys.argv[2]), int(sys.argv[3]))
    elif exp == "kernel":
        out = exp_kernel(sys.argv[2], int(sys.argv[3]))
    elif exp == "membw":
        out = exp_membw(int(sys.argv[2]), int(sys.argv[3]))
    elif exp == "membw2":
        out = exp_membw2(int(sys.argv[2]), int(sys.argv[3]))
    elif exp == "args":
        out = exp_args(int(sys.argv[2]), int(sys.argv[3]))
    elif exp == "gatherw":
        out = exp_gatherw(int(sys.argv[2]), int(sys.argv[3]))
    elif exp == "pallas_gather":
        out = exp_pallas_gather(int(sys.argv[2]),
                                int(sys.argv[3]) if len(sys.argv) > 3 else 128)
    elif exp == "sort":
        out = exp_sort(int(sys.argv[2]), int(sys.argv[3]))
    elif exp == "argsort":
        out = exp_argsort(int(sys.argv[2]), int(sys.argv[3]))
    elif exp == "scatter":
        out = exp_scatter(
            sys.argv[2], float(sys.argv[3]), float(sys.argv[4]),
            int(sys.argv[5]),
        )
    elif exp == "escparts":
        out = exp_escparts(sys.argv[2], int(sys.argv[3]), int(sys.argv[4]))
    else:
        raise SystemExit(f"unknown experiment {exp}")
    out["scale"] = SCALE
    print(json.dumps(out))




def exp_args(mb: int, R: int):
    """Trivial kernel over an MB-sized resident argument, R launches:
    if per-launch time scales with MB, the tunnel streams arguments per
    launch (the fixed-cost hypothesis for the BFS gap)."""
    import jax
    import jax.numpy as jnp

    n = mb * 1024 * 1024 // 4
    a = jax.device_put(jnp.ones((n,), jnp.float32))

    @jax.jit
    def run(a, s):
        return a[:8].sum() + s

    out = run(a, jnp.float32(0))
    jax.block_until_ready(out)
    time.sleep(3.0)
    dt = timed(lambda prev: run(a, prev if prev is not None else jnp.float32(0)),
               R, lambda out: float(jax.device_get(out)))
    return {
        "experiment": f"args {mb}MB R={R}",
        "dt_s": round(dt, 4),
        "ms_per_launch": round(dt / R * 1e3, 3),
        "implied_stream_MBps": round(mb * R / dt, 1),
    }


def exp_membw2(mb: int, R: int):
    """HBM bandwidth: array passed as ARGUMENT (not closure constant —
    closures get embedded in the compile request, which the remote-compile
    endpoint rejects >~100MB)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    n = mb * 1024 * 1024 // 4
    a = jax.device_put(jnp.ones((n,), jnp.float32))

    @jax.jit
    def run(a, s):
        def body(_, s):
            return s + jnp.sum(a * (1.0 + s * 1e-30))
        return lax.fori_loop(0, R, body, s)

    out = run(a, jnp.float32(0))
    jax.block_until_ready(out)
    time.sleep(3.0)
    dt = timed(lambda prev: run(a, jnp.float32(0)), 1,
               lambda out: float(jax.device_get(out)))
    return {
        "experiment": f"membw2 {mb}MB R={R}",
        "dt_s": round(dt, 4),
        "ms_per_iter": round(dt / R * 1e3, 3),
        "achieved_GBps": round(mb / 1024 * R / dt, 1),
    }


def exp_gatherw(W: int, R: int):
    """Width-batched gather: g = x2[idx] where x2 is [lc+1, W] — the
    multi-source-BFS amortization question. If dt(W=8) ~= dt(W=1), the
    gather cost is per-INDEX, and batching 8 BFS roots into one frontier
    matrix makes each gathered index fetch 8 lanes of payload ~free."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    E, n, nnz = upload_ell()
    lc = E.local_cols
    buckets = [(bc[0, 0], br[0, 0]) for bc, _, br in E.buckets]

    @jax.jit
    def run(x2):
        def body(_, x2):
            acc = jnp.zeros((W,), jnp.int32)
            for bc, _br in buckets:
                g = x2[jnp.minimum(bc, lc)]  # [nb, kb, W]
                acc = acc + jnp.max(jnp.max(g, axis=1), axis=0)
            return x2.at[0].set(acc)

        return lax.fori_loop(0, R, body, x2)

    x0 = jnp.tile(jnp.arange(lc + 1, dtype=jnp.int32)[:, None], (1, W))
    out = run(x0)
    jax.block_until_ready(out)
    time.sleep(3.0)
    dt = timed(lambda prev: run(x0 if prev is None else prev), 1,
               lambda out: int(jax.device_get(out[0, 0])))
    slots = sum(bc.size for bc, _ in buckets)
    return {
        "experiment": f"gatherw W={W} R={R}",
        "iters": R,
        "dt_s": round(dt, 4),
        "ms_per_iter": round(dt / R * 1e3, 3),
        "gather_slots": int(slots),
        "Mindex_per_s": round(slots * R / dt / 1e6, 1),
        "payload_GBps": round(slots * W * 4 * R / dt / 1e9, 2),
    }


def exp_pallas_gather(R: int, W: int = 128):
    """Feasibility + speed of a Pallas TPU kernel doing vectorized dynamic
    gather from a VMEM-resident [lc+1, W] table (the hand-rolled multi-root
    ELL-SpMV core; Mosaic supports 2D gather via jnp.take axis=0)."""
    import functools

    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    E, n, nnz = upload_ell()
    lc = E.local_cols
    # use the biggest mid-size bucket's indices as the workload
    bc = max((b[0][0, 0] for b in E.buckets), key=lambda a: a.size)
    nb, kb = bc.shape
    idx = jnp.minimum(bc, lc).reshape(-1)  # [nb*kb]
    m = idx.shape[0]
    TILE = 65536
    m_pad = -(-m // TILE) * TILE
    idx = jnp.concatenate([idx, jnp.zeros((m_pad - m,), jnp.int32)])

    def kernel(x_ref, idx_ref, o_ref):
        # Mosaic 2D gather: per-lane gather along sublanes —
        # g[e, r] = x[idx[e], r] via take_along_axis with broadcast idx.
        idx2 = jnp.broadcast_to(idx_ref[:][:, None], (TILE, W))
        g = jnp.take_along_axis(x_ref[:], idx2, axis=0)  # [TILE, W]
        o_ref[:] = jnp.max(g.reshape(-1, 8, g.shape[1]), axis=0)

    @jax.jit
    def run(x):
        def body(_, carry):
            x = carry
            out = pl.pallas_call(
                kernel,
                grid=(m_pad // TILE,),
                in_specs=[
                    pl.BlockSpec(memory_space=pltpu.VMEM),
                    pl.BlockSpec((TILE,), lambda i: (i,)),
                ],
                out_specs=pl.BlockSpec((8, W), lambda i: (i, 0)),
                out_shape=jax.ShapeDtypeStruct(
                    (m_pad // TILE * 8, W), jnp.int32
                ),
            )(x, idx)
            return x.at[0, 0].set(jnp.max(out))

        return lax.fori_loop(0, R, body, x)

    x0 = jnp.tile(jnp.arange(lc + 1, dtype=jnp.int32)[:, None], (1, W))
    out = run(x0)
    jax.block_until_ready(out)
    time.sleep(3.0)
    dt = timed(lambda prev: run(x0 if prev is None else prev), 1,
               lambda out: int(jax.device_get(out[0, 0])))
    return {
        "experiment": f"pallas_gather R={R} W={W}",
        "iters": R,
        "dt_s": round(dt, 4),
        "ms_per_iter": round(dt / R * 1e3, 3),
        "gather_slots": int(m),
        "Mindex_per_s": round(m * R / dt / 1e6, 1),
    }



def exp_sort(n_millions: int, R: int):
    """XLA sort throughput on this chip: sort of N uint32 keys (the ESC
    SpGEMM bottleneck candidate — compact() sorts the expanded tuples)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    n = n_millions * 1_000_000
    a = jax.device_put(jnp.arange(n, dtype=jnp.uint32)[::-1])

    @jax.jit
    def run(a):
        def body(_, carry):
            s = jnp.sort(carry)
            return s[::-1]  # keep it unsorted for the next iteration

        return lax.fori_loop(0, R, body, a)

    out = run(a)
    jax.block_until_ready(out)
    time.sleep(3.0)
    dt = timed(lambda prev: run(a), 1, lambda out: int(jax.device_get(out[0])))
    return {
        "experiment": f"sort {n_millions}M R={R}",
        "dt_s": round(dt, 4),
        "ms_per_sort": round(dt / R * 1e3, 2),
        "Mkeys_per_s": round(n * R / dt / 1e6, 1),
    }


def exp_argsort(n_millions: int, R: int):
    """argsort (sort with permutation payload) — what compact() actually
    does (sort_rowmajor carries values)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    n = n_millions * 1_000_000
    a = jax.device_put(jnp.arange(n, dtype=jnp.uint32)[::-1])

    @jax.jit
    def run(a):
        def body(_, carry):
            order = jnp.argsort(carry)
            return carry[order[::-1]]

        return lax.fori_loop(0, R, body, a)

    out = run(a)
    jax.block_until_ready(out)
    time.sleep(3.0)
    dt = timed(lambda prev: run(a), 1, lambda out: int(jax.device_get(out[0])))
    return {
        "experiment": f"argsort {n_millions}M R={R}",
        "dt_s": round(dt, 4),
        "ms_per_argsort": round(dt / R * 1e3, 2),
        "Mkeys_per_s": round(n * R / dt / 1e6, 1),
    }


if __name__ == "__main__":
    main()
