"""Warm-restart recompute: repair analytics instead of re-deriving them.

The algebra allows incremental recompute for the kinds the engine
serves as whole-graph analytics:

* **BFS levels** — after an INSERT-ONLY delta, old levels are valid
  upper bounds, so a min-plus relaxation seeded from them converges to
  the exact new levels in ~(changed-region diameter) sweeps instead of
  a full traversal ("delta-frontier repair": the first sweep relaxes
  exactly the endpoints of changed edges, later sweeps re-expand only
  from rows the previous sweep improved).  Deletions can RAISE levels,
  which no monotone repair can express — those fall back to a cold run.
* **Connected components** — same monotonicity: insertions only merge
  components, so FastSV seeded from the previous labels (each vertex
  already pointing at its old component's minimum) re-converges in a
  few hook/shortcut rounds.  Deletions may split — cold fallback.
* **PageRank** — the power iteration converges from ANY starting
  vector, so every delta warm-restarts from the previous ranks; small
  perturbations sit near the fixed point and save most iterations.

All three run over the engine's loaded ``EllParMat`` artifacts (the
same operands the serve plans use) as single jitted programs, and are
exposed through ``GraphEngine.refresh(kind)`` — which owns the cached
previous results, version lineage checks (``GraphVersion.delta_from``),
and the cold-vs-warm decision.  Obs: ``dynamic.refresh.*``.
"""

from __future__ import annotations

import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs
from ..semiring import MIN_PLUS, PLUS_TIMES, SELECT2ND_MIN

#: Kinds ``GraphEngine.refresh`` understands.
REFRESH_KINDS = ("bfs", "cc", "pagerank")

#: Sentinel for unreached vertices in refresh("bfs") level vectors.
UNREACHED = np.int32(-1)
_INF = jnp.float32(jnp.inf)


# -- BFS level repair --------------------------------------------------------


@jax.jit
def _bfs_relax_impl(E, lev_blocks):
    """Min-plus relaxation to fixpoint: ``lev <- min(lev, min over
    in-neighbors j of lev[j] + 1)``.  From a cold start (inf everywhere
    except the root) this IS BFS; from a warm start (old levels after
    insert-only deltas) it repairs.  Returns (blocks, sweeps)."""
    from ..parallel.ellmat import dist_spmv_ell
    from ..parallel.vec import DistVec

    grid, n = E.grid, E.nrows

    def mk(blocks):
        return DistVec(blocks=blocks, length=n, align="row", grid=grid)

    def cond(state):
        _, changed, it = state
        return changed & (it < n)

    def step(state):
        xb, _, it = state
        y = dist_spmv_ell(MIN_PLUS, E, mk(xb).realign("col"))
        nb = jnp.minimum(xb, y.blocks)
        return nb, jnp.any(nb != xb), it + 1

    blocks, _, niter = jax.lax.while_loop(
        cond, step, (lev_blocks, jnp.bool_(True), jnp.int32(0))
    )
    return blocks, niter


def _bfs_refresh(engine, root: int, prev: np.ndarray | None):
    from ..parallel.vec import DistVec

    n = engine.nrows
    if prev is None:
        lev = np.full(n, np.inf, np.float32)
        lev[int(root)] = 0.0
    else:
        lev = np.where(prev < 0, np.inf, prev).astype(np.float32)
    x0 = DistVec.from_global(
        engine.grid, lev, align="row", fill=np.float32(np.inf)
    )
    blocks, niter = _bfs_relax_impl(engine.E, x0.blocks)
    out = DistVec(
        blocks=blocks, length=n, align="row", grid=engine.grid
    ).to_global()
    levels = np.where(np.isfinite(out), out, -1).astype(np.int32)
    return levels, int(niter)


# -- connected-components repair ---------------------------------------------


@jax.jit
def _cc_ell_impl(E, f0_blocks):
    """FastSV over an ``EllParMat`` with an explicit initial parent
    vector (``models/cc.py:_connected_components_impl`` generalized:
    iota is just the cold start).  Any initial vector whose entries
    name SAME-COMPONENT vertices converges to the per-component minimum
    — previous labels qualify after insert-only deltas."""
    from ..parallel.ellmat import dist_spmv_ell
    from ..parallel.vec import DistVec

    grid, n = E.grid, E.nrows

    def mk(blocks):
        return DistVec(blocks=blocks, length=n, align="row", grid=grid)

    def cond(state):
        _, changed, it = state
        return changed & (it < n)

    def step(state):
        fb, _, it = state
        f = mk(fb)
        gf = f.gather(f)
        u = dist_spmv_ell(SELECT2ND_MIN, E, gf.realign("col"))
        f1 = f.scatter_combine(SELECT2ND_MIN, idx=f, src=u)
        nb = jnp.minimum(jnp.minimum(f1.blocks, u.blocks), gf.blocks)
        return nb, jnp.any(nb != fb), it + 1

    fb, _, niter = jax.lax.while_loop(
        cond, step, (f0_blocks, jnp.bool_(True), jnp.int32(0))
    )

    def jcond(state):
        _, changed = state
        return changed

    def jstep(state):
        fb, _ = state
        gf = mk(fb).gather(mk(fb))
        return gf.blocks, jnp.any(gf.blocks != fb)

    fb, _ = jax.lax.while_loop(jcond, jstep, (fb, jnp.bool_(True)))
    return fb, niter


def _cc_refresh(engine, prev: np.ndarray | None):
    from ..parallel.vec import DistVec

    n = engine.nrows
    f0 = (
        np.arange(n, dtype=np.int32) if prev is None
        else np.asarray(prev, np.int32)
    )
    x0 = DistVec.from_global(engine.grid, f0, align="row")
    # padding slots must carry self-ids out of range, like iota does
    x0 = x0.mask_padding(np.int32(2**31 - 1))
    blocks, niter = _cc_ell_impl(engine.E, x0.blocks)
    labels = DistVec(
        blocks=blocks, length=n, align="row", grid=engine.grid
    ).to_global().astype(np.int32)
    return labels, int(niter)


# -- PageRank restart --------------------------------------------------------


@partial(jax.jit, static_argnames=("alpha", "tol", "max_iters"))
def _pagerank_ell_impl(P_ell, dangling_col, x0_blocks,
                       alpha: float = 0.85, tol: float = 1e-6,
                       max_iters: int = 100):
    """Whole-graph PageRank over the loaded transition matrix with an
    explicit starting vector (``models/pagerank.py:_pagerank_impl``'s
    loop, retargeted at the serving artifacts ``P_ell``/``dangling``).
    A warm ``x0`` near the fixed point saves most iterations."""
    from ..parallel.ellmat import dist_spmv_ell
    from ..parallel.vec import DistVec

    grid, n = P_ell.grid, P_ell.nrows
    col_gids = DistVec.iota(grid, n, jnp.int32, align="col").blocks
    dang_mask = jnp.where(col_gids < n, dangling_col, 0.0)
    row_valid = DistVec.iota(grid, n, jnp.int32, align="row").blocks < n

    def mk(blocks):
        return DistVec(blocks=blocks, length=n, align="row", grid=grid)

    def cond(state):
        _, err, it = state
        return (err > tol) & (it < max_iters)

    def step(state):
        xb, _, it = state
        x_col = mk(xb).realign("col")
        spread = dist_spmv_ell(PLUS_TIMES, P_ell, x_col)
        dmass = jnp.sum(dang_mask * x_col.blocks)
        base = (1.0 - alpha) / n + alpha * dmass / n
        nb = jnp.where(row_valid, alpha * spread.blocks + base, 0.0)
        err = jnp.sum(jnp.abs(nb - xb))
        return nb, err, it + 1

    xb, _, niter = jax.lax.while_loop(
        cond, step, (x0_blocks, jnp.float32(jnp.inf), jnp.int32(0))
    )
    return xb, niter


def _pagerank_refresh(engine, prev: np.ndarray | None):
    from ..parallel.vec import DistVec

    n = engine.nrows
    if engine.P_ell is None:
        raise ValueError(
            "refresh('pagerank') needs the pagerank artifacts "
            "(engine kinds= did not include 'pagerank')"
        )
    x0 = (
        np.full(n, 1.0 / n, np.float32) if prev is None
        else np.asarray(prev, np.float32)
    )
    v0 = DistVec.from_global(engine.grid, x0, align="row")
    alpha, tol, iters = engine.pagerank_opts
    blocks, niter = _pagerank_ell_impl(
        engine.P_ell, engine.dangling.realign("col").blocks, v0.blocks,
        alpha=alpha, tol=tol, max_iters=iters,
    )
    ranks = DistVec(
        blocks=blocks, length=n, align="row", grid=engine.grid
    ).to_global().astype(np.float32)
    return ranks, int(niter)


# -- the engine-facing entry -------------------------------------------------


def refresh_analytic(engine, kind: str, root: int | None = None,
                     force_cold: bool = False) -> dict:
    """Compute (or repair) one whole-graph analytic for the engine's
    CURRENT version.  The engine's ``_analytics`` cache holds the
    previous result + the version it was computed on; the warm path is
    taken when the current version's ``delta_from`` lineage points at
    exactly the cached version AND the delta is repair-compatible
    (insert-only for bfs/cc; anything for pagerank).  Called under the
    engine's execution lock by ``GraphEngine.refresh``."""
    if kind not in REFRESH_KINDS:
        raise ValueError(
            f"unknown refresh kind {kind!r}; expected {REFRESH_KINDS}"
        )
    if kind == "bfs":
        if root is None:
            raise ValueError("refresh('bfs') needs root=")
        root = int(root)
        if not (0 <= root < engine.nrows):
            raise ValueError(f"root {root} outside [0, {engine.nrows})")
    ck = (kind, root if kind == "bfs" else None)
    entry = engine._analytics.get(ck)
    vid = engine.version_id
    if entry is not None and obs.ENABLED:
        # the ROADMAP-named freshness gauge: how many graph versions
        # the cached analytic lags the served version at refresh time
        # (0 = the cache answers for the current graph)
        obs.gauge(
            "dynamic.freshness.versions_behind",
            vid - entry["vid"], kind=kind,
        )
    if entry is not None and entry["vid"] == vid and not force_cold:
        engine._refresh_modes["cached"] = (
            engine._refresh_modes.get("cached", 0) + 1
        )
        obs.count("dynamic.refresh.runs", kind=kind, mode="cached")
        return {**entry, "mode": "cached", "latency_s": 0.0}

    prev = None
    mode = "cold"
    reason = "first" if entry is None else "lineage"
    if entry is not None and not force_cold:
        delta = getattr(engine.version, "delta_from", None)
        if delta is not None and delta[0] == entry["vid"]:
            _parent, ins, rem = delta
            if kind == "pagerank":
                prev, mode, reason = entry["result"], "warm", ""
            elif len(rem) == 0:  # monotone repair needs insert-only
                prev, mode, reason = entry["result"], "warm", ""
            else:
                reason = "deletes"
    elif force_cold:
        reason = "forced"

    t0 = time.perf_counter()
    if kind == "bfs":
        result, niter = _bfs_refresh(engine, root, prev)
    elif kind == "cc":
        result, niter = _cc_refresh(engine, prev)
    else:
        result, niter = _pagerank_refresh(engine, prev)
    dt = time.perf_counter() - t0
    out = {"kind": kind, "vid": vid, "result": result, "niter": niter}
    engine._analytics[ck] = out
    engine._refresh_modes[mode] = engine._refresh_modes.get(mode, 0) + 1
    obs.count("dynamic.refresh.runs", kind=kind, mode=mode)
    obs.observe("dynamic.refresh.iters", niter, kind=kind, mode=mode)
    obs.observe("dynamic.refresh.latency_s", dt, kind=kind, mode=mode)
    if obs.ENABLED:
        # repair-vs-cold ratio over this engine's recompute history —
        # the streaming lane's warm-start payoff as one gauge
        warm = engine._refresh_modes.get("warm", 0)
        cold = engine._refresh_modes.get("cold", 0)
        if warm + cold:
            obs.gauge(
                "dynamic.freshness.repair_ratio", warm / (warm + cold)
            )
    return {
        **out, "mode": mode, "cold_reason": reason, "latency_s": dt,
    }
