"""Write-ahead log: acknowledged writes survive the process (round 16).

The mutation lane's ``DeltaBuffer`` and the merged ``GraphVersion``s
are memory-only — before this module, a crash lost every acknowledged
write since boot.  The WAL closes that hole with the same append-only
JSONL conventions as the plan store (``tuner/store.py``): one fully
formed line per acknowledged ``submit_update`` batch, written with a
single ``write`` call so a torn write from a dying process truncates
to an invalid FINAL line (tolerated at replay), never a poisoned log.

Line format (schema ``combblas_tpu.wal/v1``)::

    {"v": "combblas_tpu.wal/v1", "first_seq": 17, "last_seq": 18,
     "rows": [3, 9], "cols": [9, 3], "vals": [1.0, 1.0], "ops": [0, 0]}

``first_seq``/``last_seq`` are the ``DeltaBuffer`` sequence numbers the
batch was admitted under — replay is ordered and deduplicated by them
(records whose range a snapshot already covers are skipped; a record
re-appended after a failover whose range is not past the frontier is
superseded — later lines win, the plan-store stance).  ``ops`` are the
``delta.OP_INSERT/OP_DELETE/OP_UPSERT`` codes.  Two auxiliary record
shapes share the schema line: ``{"v": ..., "drop": [a, z]}`` tombstones
a range whose merge FAILED on the live engine (replay must not
resurrect writes whose futures were failed), and ``{"v": ...,
"mark": z}`` records the seqno frontier across a truncation (a fully
truncated log must never restart sequence numbers).

Durability contract: ``Server.submit_update`` appends BEFORE the
caller's future exists — under ``COMBBLAS_WAL_FSYNC=always`` (the
default) an acknowledged write is on disk when ``submit_update``
returns.  ``fsync=off`` trades that for OS-buffered throughput.

:func:`recover_version` is the crash-recovery half: latest valid
snapshot (``utils.checkpoint.load_latest_version`` — a corrupt newest
snapshot falls back to the previous retained one) + WAL-suffix replay
through the existing incremental ``dynamic.merge.apply_delta``,
property-tested BIT-EXACT (``to_host_coo()`` equal) against a
never-crashed engine for crashes at every append/merge/checkpoint
boundary, torn final line included (tests/test_serve_recovery.py).

Obs series ``serve.wal.*`` / ``serve.recovery.*`` are cataloged in
``obs/metrics.py`` (round 16).
"""

from __future__ import annotations

import json
import os
import threading
import time

import numpy as np

from .. import obs
from .delta import DeltaBatch, OP_NAMES

#: JSONL schema tag — bump on any incompatible record layout change;
#: records carrying another tag are skipped at replay (never guessed
#: at — the plan-store convention).
SCHEMA = "combblas_tpu.wal/v1"

#: File name inside the durability directory (``COMBBLAS_WAL``); the
#: checkpoints (``ckpt-*.npz``) live beside it.
WAL_FILENAME = "wal.jsonl"


class RecoveryError(RuntimeError):
    """Crash recovery could not produce a version — no valid snapshot
    in the checkpoint directory (every retained candidate was corrupt
    or missing).  The message names the directory and what was
    tried."""


def wal_path(dirpath: str) -> str:
    return os.path.join(dirpath, WAL_FILENAME)


def _rec_last(rec: dict) -> int:
    """Highest sequence number a record accounts for (data record's
    ``last_seq``; a drop tombstone's range end; a frontier mark's
    position)."""
    if "mark" in rec:
        return int(rec["mark"])
    return int(rec["drop"][1] if "drop" in rec else rec["last_seq"])


class WriteAheadLog:
    """Append-only JSONL delta log (see module docstring).

    Thread-safe: ``append`` (the write lane) and ``truncate`` (the
    background checkpointer) serialize on one lock.  ``fsync`` resolves
    through ``tuner.config.wal_fsync`` (argument >
    ``COMBBLAS_WAL_FSYNC`` > ``always``).
    """

    def __init__(self, path: str, fsync: str | None = None):
        from ..tuner import config as tuner_config

        self.path = str(path)
        self.fsync = tuner_config.wal_fsync(fsync)
        self._lock = threading.Lock()
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        # resume at the existing frontier: a reopened log (recovery,
        # home promotion) continues the seqno lineage, never restarts
        self._position = -1
        self.appended = 0
        self.invalid_lines = 0
        self._invalid_reported = 0  # obs high-water (reads repeat)
        self.truncated_records = 0
        for rec in self._read_records():
            self._position = max(self._position, _rec_last(rec))
        self._fd = self._open_append()

    def _open_append(self) -> int:
        """O_APPEND fd: every record goes down as ONE ``os.write`` of
        one whole line (round 17) — the kernel's atomic append seek
        means two PROCESSES sharing a log (or a log file a sibling
        still holds open across a failover) can never interleave
        bytes mid-line; the property test in
        tests/test_append_atomicity.py pins this."""
        return os.open(
            self.path, os.O_APPEND | os.O_CREAT | os.O_WRONLY, 0o644
        )

    # -- write side --------------------------------------------------------

    def append(self, first_seq: int, rows, cols, vals, op_codes) -> int:
        """Durably record one acknowledged batch; returns the byte
        offset written at.  One ``write`` call per record (torn-tail
        tolerance) + fsync per policy."""
        return self._append_rec({
            "v": SCHEMA,
            "first_seq": int(first_seq),
            "last_seq": int(first_seq) + len(rows) - 1,
            "rows": [int(r) for r in rows],
            "cols": [int(c) for c in cols],
            "vals": [float(v) for v in vals],
            "ops": [int(o) for o in op_codes],
        })

    def append_drop(self, first_seq: int, last_seq: int) -> int:
        """Tombstone a sequence range whose ops were REJECTED on the
        live engine — a failed merge (futures failed honestly), or an
        append that reached disk before its fsync raised (the write
        was rolled back and never acknowledged).  POSITIONAL: a drop
        kills only records EARLIER in the file, so a later retry that
        legitimately reuses the rolled-back sequence numbers is
        untouched.  Without the tombstone, a crash would resurrect
        writes the callers were told failed."""
        return self._append_rec({
            "v": SCHEMA,
            "drop": [int(first_seq), int(last_seq)],
        })

    def _append_rec(self, rec: dict) -> int:
        data = (json.dumps(rec, separators=(",", ":")) + "\n").encode(
            "utf-8"
        )
        last = _rec_last(rec)
        t0 = time.perf_counter()
        with self._lock:
            if self._fd is None:
                raise ValueError("WAL is closed")
            off = os.lseek(self._fd, 0, os.SEEK_END)
            # ONE write syscall for the whole line (the O_APPEND
            # atomicity contract); a partial count (ENOSPC et al)
            # leaves a torn tail the loader skips — surface it as an
            # append failure so the write is REJECTED, never
            # acknowledged half-durable
            n = os.write(self._fd, data)
            if n != len(data):
                raise OSError(
                    f"short WAL append ({n}/{len(data)} bytes)"
                )
            if self.fsync == "always":
                os.fsync(self._fd)
            self._position = max(self._position, int(last))
            self.appended += 1
        obs.count("serve.wal.appends")
        obs.observe("serve.wal.append_s", time.perf_counter() - t0)
        return off

    def position(self) -> int:
        """Sequence-number frontier: the highest ``last_seq`` this log
        holds (or ever held before a truncate), ``-1`` when empty —
        where a resumed ``DeltaBuffer`` lineage continues from."""
        with self._lock:
            return self._position

    # -- read side ---------------------------------------------------------

    def _read_records(self) -> list[dict]:
        """Parse the file, skipping damage: a torn FINAL line is the
        expected crash artifact (silently tolerated, counted); an
        invalid or schema-mismatched interior line is skipped with a
        counter — a damaged log degrades, it never poisons replay.

        Re-read from disk on every replay/truncate ON PURPOSE: a
        promotion or recovery opens a SECOND handle on the same file,
        so an in-memory record cache could silently diverge from the
        disk truth.  The cost is bounded — checkpoint truncation keeps
        the file to the suffix since the last snapshot (default: a
        handful of merge batches), not the full write history."""
        if not os.path.exists(self.path):
            return []
        with open(self.path, encoding="utf-8") as f:
            lines = f.read().splitlines()
        out = []
        invalid = 0
        for i, line in enumerate(lines):
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
                if rec.get("v") != SCHEMA:
                    raise ValueError(f"schema {rec.get('v')!r}")
                if "mark" in rec:
                    int(rec["mark"])  # frontier marker (see truncate)
                elif "drop" in rec:
                    a, z = rec["drop"]
                    if not int(a) <= int(z):
                        raise ValueError("inconsistent drop record")
                else:
                    n = len(rec["rows"])
                    if not (
                        len(rec["cols"]) == len(rec["vals"])
                        == len(rec["ops"]) == n
                        and n >= 1
                        and int(rec["last_seq"])
                        == int(rec["first_seq"]) + n - 1
                        and all(
                            0 <= int(o) < len(OP_NAMES)
                            for o in rec["ops"]
                        )
                    ):
                        raise ValueError("inconsistent record")
            except (ValueError, KeyError, TypeError):
                invalid += 1
                continue
            out.append(rec)
        # the file is re-read per replay/truncate: report damage as a
        # LEVEL (lines currently damaged), count obs once per new line
        self.invalid_lines = invalid
        if invalid > self._invalid_reported:
            obs.count(
                "serve.wal.invalid", invalid - self._invalid_reported
            )
            self._invalid_reported = invalid
        return out

    def replay(self, after_seq: int = -1) -> list[DeltaBatch]:
        """The suffix of acknowledged batches past ``after_seq`` (a
        snapshot's ``wal_seq`` stamp), in sequence order, as
        ``DeltaBatch``es ready for ``apply_delta``.  Deduplicates
        overlapping records (later lines win) and slices a record that
        straddles the frontier to exactly the unreplayed ops."""
        with self._lock:
            records = self._read_records()
        # dropped (rejected) ranges: their ops were failed/rejected
        # honestly on the live engine and must not resurrect.
        # POSITIONAL — a tombstone kills only records written BEFORE
        # it (merge failures and rejected appends both tombstone
        # after the data line; a later retry reusing the seqs is a
        # fresh claim the tombstone must not touch).
        drops = [
            (idx, int(r["drop"][0]), int(r["drop"][1]))
            for idx, r in enumerate(records) if "drop" in r
        ]
        data = [
            (idx, r) for idx, r in enumerate(records)
            if "drop" not in r and "mark" not in r
        ]
        # LATER LINES WIN, per op: a record whose range a later record
        # re-claims was superseded — e.g. an append whose fsync raised
        # AFTER the line hit disk was ROLLED BACK and rejected, and
        # the caller's retry legitimately reuses its sequence numbers;
        # replaying the rejected line instead of the acknowledged
        # retry would be exactly the acked-write loss the WAL forbids.
        claimed: set[int] = set()
        masks: list = [None] * len(data)
        for i in range(len(data) - 1, -1, -1):
            pos, rec = data[i]
            a, z = int(rec["first_seq"]), int(rec["last_seq"])
            seqs = np.arange(a, z + 1, dtype=np.int64)
            live = seqs > int(after_seq)
            for dpos, da, dz in drops:
                if dpos > pos:  # positional: later tombstones only
                    live &= (seqs < da) | (seqs > dz)
            live &= np.asarray(
                [s not in claimed for s in seqs.tolist()], bool
            )
            claimed.update(seqs.tolist())
            masks[i] = live
        out = []
        for (_pos, rec), live in zip(data, masks):
            if not live.any():
                continue
            out.append(DeltaBatch(
                rows=np.asarray(rec["rows"], np.int64)[live],
                cols=np.asarray(rec["cols"], np.int64)[live],
                vals=np.asarray(rec["vals"], np.float32)[live],
                ops=np.asarray(rec["ops"], np.int8)[live],
                first_seq=int(rec["first_seq"]),
                last_seq=int(rec["last_seq"]),
                oldest_at=0.0,
            ))
        return out

    # -- maintenance -------------------------------------------------------

    def truncate(self, through_seq: int) -> int:
        """Drop the replayed prefix: atomically rewrite the log keeping
        only records with ``last_seq > through_seq`` (the records a
        snapshot at ``through_seq`` does NOT cover).  tmp + ``os.replace``
        — a crash mid-truncate leaves either the old or the new file,
        both valid.  Returns records dropped."""
        through = int(through_seq)
        with self._lock:
            records = self._read_records()
            keep = [
                r for r in records
                if "mark" not in r and _rec_last(r) > through
            ]
            dropped = sum(1 for r in records if "mark" not in r) \
                - len(keep)
            if dropped <= 0:
                return 0
            tmp = self.path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                # frontier mark FIRST: a fully truncated log must
                # still remember its seqno lineage — a reopened WAL
                # whose position regressed to -1 would restart
                # sequence numbers and corrupt replay dedup
                mark = {
                    "v": SCHEMA,
                    "mark": max(through, self._position),
                }
                f.write(json.dumps(mark, separators=(",", ":")))
                f.write("\n")
                for rec in keep:
                    f.write(json.dumps(rec, separators=(",", ":")))
                    f.write("\n")
                f.flush()
                os.fsync(f.fileno())
            os.close(self._fd)
            # None across the gap: if the reopen below fails
            # (EMFILE, permissions), a later append must fail-stop
            # ("WAL is closed") rather than os.write through a stale
            # descriptor number another file may have reused
            self._fd = None
            os.replace(tmp, self.path)
            self._fd = self._open_append()
            self.truncated_records += dropped
        obs.count("serve.wal.truncated", dropped)
        return dropped

    def close(self) -> None:
        with self._lock:
            if self._fd is not None:
                os.close(self._fd)
                self._fd = None

    def stats(self) -> dict:
        with self._lock:
            size = (
                os.path.getsize(self.path)
                if os.path.exists(self.path) else 0
            )
            return {
                "path": self.path,
                "fsync": self.fsync,
                "position": self._position,
                "appended": self.appended,
                "invalid_lines": self.invalid_lines,
                "truncated_records": self.truncated_records,
                "bytes": size,
            }


def open_wal(dirpath: str, fsync: str | None = None) -> WriteAheadLog:
    """The durability directory's WAL (``wal.jsonl`` beside the
    ``ckpt-*.npz`` snapshots)."""
    return WriteAheadLog(wal_path(dirpath), fsync=fsync)


def recover(dirpath: str, grid, *, kinds: tuple | None = None,
            combine: str | None = None, fsync: str | None = None):
    """One-call crash recovery from a durability DIRECTORY: opens the
    WAL, runs :func:`recover_version`, closes the log — the shape
    every product call site (``Server.from_recovery``, fleet
    promotion/replacement) actually wants.  Use ``recover_version``
    directly only when you already hold an open log."""
    wal = open_wal(dirpath, fsync=fsync)
    try:
        return recover_version(
            dirpath, wal, grid, kinds=kinds, combine=combine
        )
    finally:
        wal.close()


def recover_version(checkpoint_dir: str, wal: WriteAheadLog | None,
                    grid, *, kinds: tuple | None = None,
                    combine: str | None = None, batch_filter=None):
    """Crash recovery: latest valid snapshot + WAL-suffix replay.

    Loads the newest loadable snapshot in ``checkpoint_dir`` (a corrupt
    newest file falls back to the previous retained one — the atomic-
    write + retention policy guarantees a predecessor exists unless
    every snapshot was destroyed), then replays every WAL batch past
    the snapshot's ``wal_seq`` stamp through the incremental
    ``apply_delta`` — each acknowledged ``submit_update`` batch is one
    replay unit, so the recovered version is bit-exact
    (``to_host_coo()`` equal) with a never-crashed engine that merged
    the same acknowledged ops, whatever batch coalescing its flush
    timing produced.

    Returns the recovered ``GraphVersion`` (its ``wal_seq`` at the
    replayed frontier); raises :class:`RecoveryError` when no snapshot
    is loadable.  ``kinds`` gates the same structural checks the
    engine's own merges run; ``combine`` is the upsert monoid (the
    buffer's ``min`` default).

    ``batch_filter`` (sharded recovery, round 20): a callable mapping
    each replayed :class:`DeltaBatch` to the sub-batch THIS store
    actually owns (e.g. a row slab, translated to slab coordinates) or
    ``None`` when nothing in the batch lands here.  The frontier stamp
    still advances for filtered-out batches — a slice's ``wal_seq``
    means "every acknowledged write through here is REFLECTED", which
    for a foreign-row batch is vacuously true; skipping the stamp
    would force an eternal no-op replay of the same records.
    """
    from ..utils import checkpoint as ckpt
    from . import merge as dyn_merge

    t0 = time.perf_counter()
    version, snap_path = ckpt.load_latest_version(checkpoint_dir, grid)
    obs.gauge("serve.recovery.snapshot_seq", int(version.wal_seq))
    batches = replayed_ops = 0
    if wal is not None:
        for batch in wal.replay(after_seq=version.wal_seq):
            last_seq = batch.last_seq
            if batch_filter is not None:
                batch = batch_filter(batch)
            if batch is not None and len(batch):
                version = dyn_merge.apply_delta(
                    version, batch, kinds=kinds, combine=combine,
                )
                batches += 1
                replayed_ops += len(batch)
            version.wal_seq = last_seq
    obs.count("serve.recovery.replayed_ops", replayed_ops)
    obs.observe("serve.recovery.recover_s", time.perf_counter() - t0)
    obs.count("serve.recovery.runs")
    version.recovered_from = (snap_path, batches, replayed_ops)
    return version
