"""``combblas_tpu.dynamic`` — the streaming graph-mutation lane.

PR 6 landed the READ half of dynamic serving: double-buffered
``GraphVersion`` hot-swap with surviving plan caches.  This package is
the WRITE half (the capability bar is the reference's in-place
``SpParMat::Prune`` / assign ops, PAPER.md §2), three layers:

1. **delta** (`delta.py`) — ``DeltaBuffer``: a bounded host-side COO
   delta log (insert / delete / upsert with a per-semiring combine on
   duplicate keys and a deterministic, vectorized fold), batched
   admission with reject-on-full backpressure, obs-visible depth/age.
2. **merge** (`merge.py`) — ``apply_delta(version, batch)``: fold a
   drained batch into the existing ``EllParMat`` tiles and their
   weighted / normalized / transpose twins PER TILE — rows whose
   degree-class slots still fit are patched in place, overflowing rows
   re-bucket into free padding slots, and a spill threshold falls back
   to a full rebuild — re-uploading only the touched bucket classes so
   same-shape swaps keep the zero-retrace guarantee, with counters
   making the incremental-vs-rebuild amortization measurable.
3. **refresh** (`refresh.py`) — warm-restart recompute:
   delta-frontier BFS/CC repair (re-expand only from the endpoints of
   changed edges; insert-only, by monotonicity) and PageRank restart
   from the previous vector, exposed as ``GraphEngine.refresh(kind)``.
4. **wal** (`wal.py`, round 16) — the durability layer: a
   schema-versioned append-only write-ahead log of acknowledged
   ``submit_update`` batches (torn-tail tolerant, fsync-policy knob)
   plus ``recover_version`` = latest valid ``utils.checkpoint``
   snapshot + WAL-suffix replay through ``apply_delta``, bit-exact
   with a never-crashed engine (docs/serving.md "Durability &
   self-healing").

``serve.api.Server`` wires it into traffic: ``submit_update()`` admits
mutations into the buffer, a dedicated mutation thread coalesces and
merges them OFF the execution lock, and ``swap_graph`` flips the
version atomically — reads stay hot while writes stream in
(``BENCH_SERVE_MUTATE=1`` in serve_bench measures the mix).  See
docs/dynamic.md.
"""

from .delta import (  # noqa: F401
    COMBINES,
    DeltaBatch,
    DeltaBuffer,
    DeltaOverflowError,
    OP_NAMES,
    fold_ops,
)
from .merge import (  # noqa: F401
    MergeState,
    MergeStats,
    apply_delta,
    bootstrap_state,
)
from .refresh import REFRESH_KINDS, refresh_analytic  # noqa: F401
from .wal import (  # noqa: F401
    RecoveryError,
    WriteAheadLog,
    open_wal,
    recover,
    recover_version,
)

__all__ = [
    "DeltaBuffer", "DeltaBatch", "DeltaOverflowError", "OP_NAMES",
    "COMBINES", "fold_ops",
    "apply_delta", "bootstrap_state", "MergeState", "MergeStats",
    "refresh_analytic", "REFRESH_KINDS",
    "WriteAheadLog", "open_wal", "recover", "recover_version",
    "RecoveryError",
]
