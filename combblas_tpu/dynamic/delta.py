"""DeltaBuffer — the bounded host-side COO delta log of the mutation lane.

Production graphs change while they serve.  The write path starts here:
edge mutations (``insert`` / ``delete`` / ``upsert``) are ADMITTED into a
bounded in-memory log instead of touching the loaded matrices, so writes
coalesce while reads stay hot, and a full buffer REJECTS instead of
buffering unboundedly (the same load-shedding stance as the serve
queue).  A drained batch is a plain numpy COO record
(:class:`DeltaBatch`) that :func:`combblas_tpu.dynamic.merge.apply_delta`
folds into the current ``GraphVersion``.

Semantics, applied in ADMISSION ORDER (every op carries a monotonically
increasing sequence number, so replay is deterministic even when several
ops hit the same (row, col) key):

* ``insert(r, c, w)`` — the edge exists with weight ``w`` afterwards
  (an existing edge is overwritten — a *reset* op);
* ``delete(r, c)``    — the edge is absent afterwards (also a reset);
* ``upsert(r, c, w)`` — combine ``w`` into the edge's current weight via
  the buffer's ``combine`` monoid (``min`` by default — the
  shortest-path dedup convention of ``GraphEngine.from_coo``), or
  insert it with weight ``w`` when absent.

The fold of many same-key ops reduces to: the LAST reset op decides
presence, and the upserts AFTER it combine associatively — which is what
lets :func:`fold_ops` vectorize the whole dedup (no per-key Python loop)
while staying bit-identical to sequential replay.

Unweighted graphs ignore the weight payload (every surviving edge is
structural weight 1); ``upsert`` then degrades to ``insert``.

Thread-safe; obs series ``dynamic.delta.*`` (cataloged in
``obs/metrics.py``) make depth and batch age visible.
"""

from __future__ import annotations

import dataclasses
import threading
import time

import numpy as np

from .. import obs

#: Op codes carried in ``DeltaBatch.ops`` (int8).
OP_INSERT, OP_DELETE, OP_UPSERT = 0, 1, 2
OP_NAMES = ("insert", "delete", "upsert")
_OP_CODE = {name: i for i, name in enumerate(OP_NAMES)}

#: Supported duplicate-key combine monoids for ``upsert``.
COMBINES = ("min", "max", "sum", "last")


class DeltaOverflowError(RuntimeError):
    """The delta buffer is full: the caller should back off and retry
    (mirror of the serve queue's ``BackpressureError`` — the write lane
    sheds load the same way the read lane does).  ``retry_after_s`` is
    the buffer's flush-delay hint."""

    def __init__(self, depth: int, retry_after_s: float):
        super().__init__(
            f"delta buffer full ({depth} pending ops); retry after "
            f"{retry_after_s:.3f}s"
        )
        self.retry_after_s = retry_after_s


@dataclasses.dataclass(frozen=True)
class DeltaBatch:
    """One drained batch of edge mutations, in admission order.

    ``rows``/``cols`` are int64 global indices, ``vals`` float32 weights
    (1.0 for ops that carried none), ``ops`` the int8 op codes above.
    ``first_seq``/``last_seq`` delimit the buffer sequence numbers the
    batch covers (the write lane settles update futures by comparing
    their ticket against ``last_seq``); ``oldest_at`` is the admission
    ``time.monotonic()`` of the oldest op (batch age at drain).
    """

    rows: np.ndarray
    cols: np.ndarray
    vals: np.ndarray
    ops: np.ndarray
    first_seq: int
    last_seq: int
    oldest_at: float

    def __len__(self) -> int:
        return int(self.rows.shape[0])

    @staticmethod
    def from_ops(ops, start_seq: int = 0,
                 now: float | None = None) -> "DeltaBatch":
        """Build a batch directly from an iterable of
        ``(op, row, col[, weight])`` tuples — the test/tooling path that
        skips the buffer."""
        rows, cols, vals, codes = [], [], [], []
        for item in ops:
            op, r, c = item[0], item[1], item[2]
            w = item[3] if len(item) > 3 else 1.0
            code = _OP_CODE.get(op)
            if code is None:
                raise ValueError(
                    f"unknown delta op {op!r}; expected one of {OP_NAMES}"
                )
            rows.append(int(r))
            cols.append(int(c))
            vals.append(float(w))
            codes.append(code)
        now = time.monotonic() if now is None else now
        return DeltaBatch(
            rows=np.asarray(rows, np.int64),
            cols=np.asarray(cols, np.int64),
            vals=np.asarray(vals, np.float32),
            ops=np.asarray(codes, np.int8),
            first_seq=start_seq,
            last_seq=start_seq + max(len(rows) - 1, 0),
            oldest_at=now,
        )


class DeltaBuffer:
    """Bounded, thread-safe delta log (see module docstring).

    ``capacity`` bounds PENDING ops (admission control); ``nrows`` /
    ``ncols``, when given, validate indices at the front door so a
    malformed op is rejected before it can poison a merge.  ``combine``
    names the upsert duplicate-key monoid.
    """

    def __init__(self, capacity: int = 65536, *,
                 nrows: int | None = None, ncols: int | None = None,
                 combine: str = "min",
                 retry_after_s: float = 0.05,
                 start_seq: int = 0):
        if capacity < 1:
            raise ValueError("delta buffer capacity must be >= 1")
        if combine not in COMBINES:
            raise ValueError(
                f"combine must be one of {COMBINES}, got {combine!r}"
            )
        self.capacity = int(capacity)
        self.nrows = None if nrows is None else int(nrows)
        self.ncols = None if ncols is None else int(ncols)
        self.combine = combine
        self.retry_after_s = float(retry_after_s)
        self._lock = threading.Lock()
        self._rows: list[int] = []
        self._cols: list[int] = []
        self._vals: list[float] = []
        self._ops: list[int] = []
        # start_seq (round 16): a recovered / promoted server resumes
        # the WAL's seqno lineage instead of restarting at 0 — replay
        # dedup and snapshot stamps depend on sequence numbers being a
        # single monotone line across process lives
        self._next_seq = int(start_seq)
        self._oldest_at: float | None = None
        # host-side counters (always live; obs mirrors cost nothing
        # when telemetry is disabled)
        self.admitted = 0
        self.rejected = 0
        self.drained_batches = 0

    # -- admission ---------------------------------------------------------

    def _validate(self, op: str, row: int, col: int) -> int:
        code = _OP_CODE.get(op)
        if code is None:
            raise ValueError(
                f"unknown delta op {op!r}; expected one of {OP_NAMES}"
            )
        row, col = int(row), int(col)
        if row < 0 or (self.nrows is not None and row >= self.nrows):
            raise ValueError(f"row {row} outside [0, {self.nrows})")
        if col < 0 or (self.ncols is not None and col >= self.ncols):
            raise ValueError(f"col {col} outside [0, {self.ncols})")
        return code

    def add(self, op: str, row: int, col: int,
            weight: float = 1.0) -> int:
        """Admit one op; returns its sequence number (the caller's
        ticket — a drain whose ``last_seq`` >= it contains this op).
        Raises ``DeltaOverflowError`` when full and ``ValueError`` for a
        malformed op (neither mutates the buffer)."""
        code = self._validate(op, row, col)
        with self._lock:
            depth = len(self._rows)
            if depth >= self.capacity:
                self.rejected += 1
                obs.count("serve.update.rejected")
                raise DeltaOverflowError(depth, self.retry_after_s)
            seq = self._next_seq
            self._next_seq += 1
            self._rows.append(int(row))
            self._cols.append(int(col))
            self._vals.append(float(weight))
            self._ops.append(code)
            if self._oldest_at is None:
                self._oldest_at = time.monotonic()
            self.admitted += 1
            depth += 1
        obs.count("dynamic.delta.ops", op=op)
        obs.gauge("dynamic.delta.depth", depth)
        return seq

    def add_many(self, ops) -> int:
        """Admit a sequence of ``(op, row, col[, weight])`` tuples
        ATOMICALLY (all admitted or none — a partially-admitted update
        would make the caller's future ambiguous).  Returns the LAST
        sequence number."""
        items = []
        for item in ops:
            op, r, c = item[0], item[1], item[2]
            w = item[3] if len(item) > 3 else 1.0
            self._validate(op, r, c)  # raises before any admission
            items.append((op, int(r), int(c), float(w)))
        if not items:
            raise ValueError("add_many needs at least one op")
        with self._lock:
            depth = len(self._rows)
            if depth + len(items) > self.capacity:
                self.rejected += 1
                obs.count("serve.update.rejected")
                raise DeltaOverflowError(depth, self.retry_after_s)
            for op, r, c, w in items:
                self._rows.append(r)
                self._cols.append(c)
                self._vals.append(w)
                self._ops.append(_OP_CODE[op])
            last = self._next_seq + len(items) - 1
            self._next_seq += len(items)
            if self._oldest_at is None:
                self._oldest_at = time.monotonic()
            self.admitted += len(items)
            depth += len(items)
        for op, _r, _c, _w in items:
            obs.count("dynamic.delta.ops", op=op)
        obs.gauge("dynamic.delta.depth", depth)
        return last

    def rollback(self, from_seq: int) -> int:
        """Un-admit the TAIL of pending ops with sequence number >=
        ``from_seq`` and rewind the sequence counter — the write
        lane's WAL-append failure path (round 16): ops whose durable
        record could not be written were never acknowledged, so they
        must not merge.  Only a tail can be rolled back (earlier ops
        may already be acknowledged); the caller must ensure no drain
        ran in between (``Server.submit_update`` holds its admission
        lock across append + rollback).  Returns ops removed."""
        with self._lock:
            first_pending = self._next_seq - len(self._rows)
            if from_seq < first_pending:
                raise ValueError(
                    f"rollback(from_seq={from_seq}) reaches below the "
                    f"pending tail (first pending seq {first_pending})"
                    " — those ops were already drained/acknowledged"
                )
            n = self._next_seq - int(from_seq)
            if n <= 0:
                return 0
            del self._rows[-n:]
            del self._cols[-n:]
            del self._vals[-n:]
            del self._ops[-n:]
            self._next_seq = int(from_seq)
            self.admitted -= n
            if not self._rows:
                self._oldest_at = None
            depth = len(self._rows)
        obs.gauge("dynamic.delta.depth", depth)
        return n

    # -- introspection -----------------------------------------------------

    def depth(self) -> int:
        with self._lock:
            return len(self._rows)

    def oldest_age(self, now: float | None = None) -> float | None:
        """Age in seconds of the oldest pending op, or None when empty
        (the write lane's flush-deadline input)."""
        with self._lock:
            if self._oldest_at is None:
                return None
            now = time.monotonic() if now is None else now
            return max(0.0, now - self._oldest_at)

    def stats(self) -> dict:
        with self._lock:
            return {
                "depth": len(self._rows),
                "capacity": self.capacity,
                "admitted": self.admitted,
                "rejected": self.rejected,
                "drained_batches": self.drained_batches,
                "combine": self.combine,
            }

    # -- drain -------------------------------------------------------------

    def drain(self, now: float | None = None) -> DeltaBatch | None:
        """Pop everything pending as one :class:`DeltaBatch` (admission
        order), or ``None`` when empty."""
        now = time.monotonic() if now is None else now
        with self._lock:
            if not self._rows:
                return None
            n = len(self._rows)
            batch = DeltaBatch(
                rows=np.asarray(self._rows, np.int64),
                cols=np.asarray(self._cols, np.int64),
                vals=np.asarray(self._vals, np.float32),
                ops=np.asarray(self._ops, np.int8),
                first_seq=self._next_seq - n,
                last_seq=self._next_seq - 1,
                oldest_at=self._oldest_at,
            )
            self._rows, self._cols = [], []
            self._vals, self._ops = [], []
            age = max(0.0, now - self._oldest_at)
            self._oldest_at = None
            self.drained_batches += 1
        obs.count("dynamic.delta.batches")
        obs.observe("dynamic.delta.age_s", age)
        obs.gauge("dynamic.delta.depth", 0)
        return batch


def fold_ops(batch: DeltaBatch, base_keys: np.ndarray,
             base_weights: np.ndarray | None, ncols: int,
             combine: str = "min"):
    """Fold a batch against a SORTED base edge-key set, vectorized.

    ``base_keys`` are the current deduped edge keys (``row * ncols +
    col``, strictly increasing); ``base_weights`` the aligned weights
    (``None`` for unweighted graphs — the weight payload is then
    ignored and every surviving edge has weight 1).

    Returns ``(final_keys, final_present, final_weights)`` for exactly
    the keys the batch TOUCHES (sorted, unique): ``final_present[i]``
    says whether key ``i`` exists after the batch, ``final_weights[i]``
    its post-combine weight.  Bit-identical to replaying the ops one by
    one in sequence order (the per-key fold described in the module
    docstring), which the property tests assert.
    """
    if combine not in COMBINES:
        raise ValueError(f"unknown combine {combine!r}")
    m = len(batch)
    if m == 0:
        e = np.empty(0, np.int64)
        return e, np.empty(0, bool), np.empty(0, np.float32)
    keys = batch.rows.astype(np.int64) * np.int64(ncols) + batch.cols
    pos = np.arange(m, dtype=np.int64)
    order = np.lexsort((pos, keys))  # by key, then admission order
    ks, ops, vs, ps = keys[order], batch.ops[order], batch.vals[order], pos[order]
    uniq, start = np.unique(ks, return_index=True)
    nseg = len(uniq)
    sorted_idx = np.arange(m, dtype=np.int64)
    seg_of = np.searchsorted(start, sorted_idx, side="right") - 1
    # base state per touched key
    bpos = np.searchsorted(base_keys, uniq)
    in_base = (bpos < len(base_keys)) & (
        base_keys[np.minimum(bpos, max(len(base_keys) - 1, 0))] == uniq
    ) if len(base_keys) else np.zeros(nseg, bool)
    base_w = np.ones(nseg, np.float32)
    if base_weights is not None and len(base_keys):
        base_w = np.where(
            in_base,
            base_weights[np.minimum(bpos, len(base_keys) - 1)],
            np.float32(1.0),
        ).astype(np.float32)
    # last RESET (insert/delete) position per segment (-1 = none)
    reset_pos = np.where(ops != OP_UPSERT, sorted_idx, np.int64(-1))
    last_reset = np.maximum.reduceat(reset_pos, start)
    # presence/weight after the last reset (or the base, if none)
    has_reset = last_reset >= 0
    safe_reset = np.maximum(last_reset, 0)
    present0 = np.where(has_reset, ops[safe_reset] == OP_INSERT, in_base)
    w0 = np.where(has_reset, vs[safe_reset], base_w).astype(np.float32)
    # upserts AFTER the reset combine associatively
    up_mask = (ops == OP_UPSERT) & (sorted_idx > last_reset[seg_of])
    if combine == "min":
        ident, ufunc = np.float32(np.inf), np.minimum
    elif combine == "max":
        ident, ufunc = np.float32(-np.inf), np.maximum
    elif combine == "sum":
        ident, ufunc = np.float32(0.0), np.add
    else:  # "last": the max-position upsert's value wins
        ident, ufunc = None, None
    has_up_seg = np.zeros(nseg, bool)
    np.logical_or.at(has_up_seg, seg_of, up_mask)
    if combine == "last":
        lastpos = np.full(nseg, -1, np.int64)
        np.maximum.at(
            lastpos, seg_of, np.where(up_mask, sorted_idx, np.int64(-1))
        )
        up_red = vs[np.maximum(lastpos, 0)].astype(np.float32)
        # "last" treats the combine as overwrite: the reduced value IS
        # the final weight whenever any upsert fired
        w_with_up = up_red
    else:
        acc = np.full(nseg, ident, np.float32)
        ufunc.at(acc, seg_of, np.where(up_mask, vs, ident).astype(np.float32))
        up_red = acc
        w_with_up = np.where(
            present0, ufunc(w0, up_red), up_red
        ).astype(np.float32)
    final_present = present0 | has_up_seg
    final_w = np.where(has_up_seg, w_with_up, w0).astype(np.float32)
    if base_weights is None:
        final_w = np.ones(nseg, np.float32)  # unweighted: structural 1s
    return uniq, final_present, final_w
